"""Telemetry runtime: span nesting (including across threads), trace
export validity, Prometheus format, histogram ring bounds, the retrace
watchdog, the counters shim's kind-aware deltas, and the defaults-inert
contract (env unset => no files, no spans, bit-identical results)."""

import json
import logging
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.runtime import counters, telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset_telemetry()
    yield
    telemetry.reset_telemetry()


@pytest.fixture
def traced(tmp_path, monkeypatch):
    """Enable tracing into a per-test directory."""
    monkeypatch.setenv("TPUML_TRACE", str(tmp_path))
    return tmp_path


def _load_trace(tdir):
    files = [f for f in os.listdir(tdir) if f.startswith("trace-")]
    assert len(files) == 1, files
    with open(os.path.join(tdir, files[0])) as f:
        return json.load(f)


# --- spans -----------------------------------------------------------------


def test_span_nesting_and_attrs(traced):
    with telemetry.span("outer", phase="a"):
        with telemetry.span("inner") as sp:
            sp.set_attr(rows=42)
    telemetry.flush()

    doc = _load_trace(traced)
    xs = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert set(xs) == {"outer", "inner"}
    outer, inner = xs["outer"], xs["inner"]
    assert inner["args"]["parent_id"] == outer["args"]["span_id"]
    assert "parent_id" not in outer["args"]  # root spans have no parent
    assert outer["args"]["phase"] == "a"
    assert inner["args"]["rows"] == 42
    # complete events nest in time: ts/dur are microseconds
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1

    stats = telemetry.span_stats()
    assert stats["outer"]["count"] == 1
    assert stats["outer"]["wall_seconds"] >= stats["inner"]["wall_seconds"]


def test_span_parenting_across_threads(traced):
    """bind_context carries the active span into worker threads — the
    same mechanism the CV fold pool and the streaming decode/stage
    threads use."""
    def work():
        with telemetry.span("child"):
            pass

    with telemetry.span("root"):
        t = threading.Thread(target=telemetry.bind_context(work))
        t.start()
        t.join()
    telemetry.flush()

    doc = _load_trace(traced)
    xs = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert xs["child"]["args"]["parent_id"] == xs["root"]["args"]["span_id"]
    # distinct threads get distinct tids (and thread_name metadata)
    assert xs["child"]["tid"] != xs["root"]["tid"]
    meta_tids = {
        e["tid"] for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert {xs["child"]["tid"], xs["root"]["tid"]} <= meta_tids


def test_trace_file_and_event_log_valid(traced):
    with telemetry.span("a"):
        pass
    with telemetry.span("b"):
        pass
    telemetry.flush()

    doc = _load_trace(traced)
    assert isinstance(doc["traceEvents"], list)
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "M", "i")
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float))
            assert e["dur"] >= 0

    logs = [f for f in os.listdir(traced) if f.startswith("events-")]
    assert len(logs) == 1
    with open(os.path.join(traced, logs[0])) as f:
        lines = [json.loads(line) for line in f]
    assert {rec["name"] for rec in lines} == {"a", "b"}
    assert all("wall_seconds" in rec for rec in lines)


def test_timed_span_measures_even_untraced():
    ts = telemetry.timed_span("anything")
    with ts:
        pass
    assert ts.seconds >= 0.0
    # nothing recorded: tracing is off
    assert telemetry.span_stats() == {}


def test_kmeans_fit_trace_covers_fit(traced):
    """End-to-end: a traced fit produces a loadable trace whose root
    span covers the whole fit and whose children account for the bulk
    of it."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 4)).astype(np.float32)
    df = DataFrame({"features": X})
    KMeans(k=3, maxIter=2, seed=0).setFeaturesCol("features").fit(df)
    telemetry.flush()

    stats = telemetry.span_stats()
    assert "KMeans.fit" in stats
    assert "preprocess" in stats and "fit.dispatch" in stats
    root = stats["KMeans.fit"]["wall_seconds"]
    covered = (
        stats["preprocess"]["wall_seconds"]
        + stats["fit.dispatch"]["wall_seconds"]
    )
    assert covered <= root
    assert covered >= 0.95 * root

    doc = _load_trace(traced)
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"KMeans.fit", "preprocess", "fit.dispatch"} <= names


# --- metrics ---------------------------------------------------------------


def test_histogram_ring_is_bounded(monkeypatch):
    monkeypatch.setenv("TPUML_TELEMETRY_RESERVOIR", "4")
    h = telemetry.histogram("span_seconds")
    for i in range(100):
        h.observe(float(i))
    series = h.value()
    assert series.count == 100
    assert series.sum == sum(range(100))
    assert series.min == 0.0 and series.max == 99.0
    # deterministic last-N ring, not an unbounded (or sampled) buffer
    assert list(series.ring) == [96.0, 97.0, 98.0, 99.0]


def test_metric_kind_mismatch_raises():
    with pytest.raises(ValueError, match="registered as a gauge"):
        # deliberate kind mismatch: the runtime check under test
        # tpuml: ignore[TPU007]
        telemetry.counter("resumed_from")


def test_prometheus_dump_format():
    telemetry.counter("retries").inc(3)
    telemetry.gauge("hbm_budget_bytes").set(1024.0, site="gang_fit")
    telemetry.histogram("span_seconds").observe(0.5, name="x")
    text = telemetry.prometheus_dump()
    lines = text.splitlines()
    assert "# TYPE tpuml_retries counter" in lines
    assert "tpuml_retries 3" in lines
    assert "# TYPE tpuml_hbm_budget_bytes gauge" in lines
    assert 'tpuml_hbm_budget_bytes{site="gang_fit"} 1024' in lines
    assert "# TYPE tpuml_span_seconds summary" in lines
    assert 'tpuml_span_seconds{name="x",quantile="0.5"} 0.5' in lines
    assert 'tpuml_span_seconds_count{name="x"} 1' in lines
    assert 'tpuml_span_seconds_sum{name="x"} 0.5' in lines
    # every sample line belongs to a HELP/TYPE-declared family
    for line in lines:
        if line and not line.startswith("#"):
            assert line.startswith("tpuml_")

    snap = telemetry.metrics_snapshot()
    assert snap["retries"]["kind"] == "counter"
    json.dumps(snap)  # snapshot must be JSON-clean


def test_write_metrics_files(traced):
    telemetry.counter("retries").inc()
    paths = telemetry.write_metrics()
    assert paths is not None
    prom, js = paths
    assert os.path.exists(prom) and os.path.exists(js)
    with open(js) as f:
        snap = json.load(f)
    assert snap["retries"]["series"][0]["value"] == 1


# --- counters shim ---------------------------------------------------------


def test_counters_shim_roundtrip():
    counters.bump("retries")
    counters.bump("retries", 2)
    counters.note("resumed_from", 7)
    snap = counters.snapshot()
    assert snap["retries"] == 3
    assert snap["resumed_from"] == 7
    assert counters.get("retries") == 3


def test_delta_since_gauge_is_kind_driven():
    """Regression: gauge semantics in delta_since must follow the
    declared metric kind, not a hard-coded name match."""
    counters.note("my_shim_gauge", 5)  # tpuml: ignore[TPU007]
    counters.bump("my_shim_counter", 2)  # tpuml: ignore[TPU007]
    base = counters.snapshot()
    counters.note("my_shim_gauge", 9)  # tpuml: ignore[TPU007]
    counters.bump("my_shim_counter", 3)  # tpuml: ignore[TPU007]
    delta = counters.delta_since(base)
    # gauge: last value, NOT 9 - 5; counter: the increment
    assert delta["my_shim_gauge"] == 9
    assert delta["my_shim_counter"] == 3
    # unchanged metrics are omitted
    assert counters.delta_since(counters.snapshot()) == {}
    # shim-created metrics carry the right registry kinds
    assert telemetry.metric_kind("my_shim_gauge") == "gauge"
    assert telemetry.metric_kind("my_shim_counter") == "counter"


# --- retrace watchdog ------------------------------------------------------


def test_retrace_watchdog_detects_storm(traced, monkeypatch):
    monkeypatch.setenv("TPUML_TELEMETRY_RETRACE_LIMIT", "2")
    assert telemetry.install_retrace_watchdog()

    # the package logger doesn't propagate to root (caplog can't see
    # it) — attach a capturing handler directly
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = logging.getLogger("spark_rapids_ml_tpu")
    handler = _Capture(level=logging.WARNING)
    logger.addHandler(handler)
    try:
        with telemetry.span("retrace.victim"):
            # a fresh jit per call: every invocation recompiles — the
            # storm TPU003 exists to catch, forced deliberately
            for n in range(1, 6):
                # deliberate recompile storm: the watchdog under test
                # tpuml: ignore[TPU003]
                fn = jax.jit(lambda x: x * 2.0)
                fn(jnp.ones((n, 3), jnp.float32)).block_until_ready()
    finally:
        logger.removeHandler(handler)

    compiles = telemetry.counter("xla_compiles").value(
        site="retrace.victim"
    )
    assert compiles is not None and compiles > 2
    assert telemetry.counter("retrace_storms").value() == 1
    warnings = [r for r in records if "retrace storm" in r.getMessage()]
    assert len(warnings) == 1  # warn-once per site
    assert "retrace.victim" in warnings[0].getMessage()


# --- defaults-inert --------------------------------------------------------


def test_defaults_inert_no_spans_no_files(tmp_path, monkeypatch):
    monkeypatch.delenv("TPUML_TRACE", raising=False)
    assert not telemetry.enabled()
    # the disabled span is a shared singleton: zero per-call allocation
    assert telemetry.span("a") is telemetry.span("b", k=1)
    with telemetry.span("a") as sp:
        sp.set_attr(x=1)
        sp.fence(None)
    assert telemetry.span_stats() == {}
    assert telemetry.flush() is None
    assert telemetry.write_metrics() is None
    assert os.listdir(tmp_path) == []


def test_traced_fit_bit_identical_to_untraced(tmp_path, monkeypatch):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(512, 4)).astype(np.float32)
    df = DataFrame({"features": X})

    def centers():
        m = KMeans(k=3, maxIter=4, seed=0).setFeaturesCol("features").fit(df)
        return m.cluster_centers_

    monkeypatch.delenv("TPUML_TRACE", raising=False)
    plain = centers()
    monkeypatch.setenv("TPUML_TRACE", str(tmp_path))
    traced = centers()
    assert plain.tobytes() == traced.tobytes()
