"""Serving replica groups over the pod's process grid.

A pod-scale serving fleet is N replicas over a world of W processes:
each replica owns a contiguous block of ``W // N`` ranks — one rank per
replica in the common case, ``mesh_mp`` ranks per replica when the
replica itself shards model state over the PR-16 model axis
(``TPUML_MESH_MP``), mirroring how ``host_file_shard`` keys dp replica
groups for input reading. The serving router (``serving/router.py``)
uses these groups to map replica indices onto process ranks and to
rank-stamp warmup spans and residency reports.

Deliberately numpy/jax-free: the router imports this at construction
time and the grouping math is pure integer arithmetic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

__all__ = ["ReplicaGroup", "replica_groups", "group_of"]


@dataclass(frozen=True)
class ReplicaGroup:
    """One serving replica's slot in the process grid."""

    index: int
    ranks: Tuple[int, ...]

    @property
    def leader(self) -> int:
        """The rank that speaks for the group (loads report residency
        per leader; model-sharded members hold 1/mp of the state)."""
        return self.ranks[0]

    @property
    def size(self) -> int:
        return len(self.ranks)


def replica_groups(world: int, mp: int = 1) -> List[ReplicaGroup]:
    """Partition ``world`` process ranks into contiguous serving
    replicas of ``mp`` ranks each. Ragged worlds raise — a replica
    missing model-axis shards could not answer any request."""
    world, mp = int(world), int(mp)
    if world < 1:
        raise ValueError(f"world size must be >= 1, got {world}")
    if mp < 1:
        raise ValueError(f"mp degree must be >= 1, got {mp}")
    if world % mp:
        raise ValueError(
            f"world size {world} is not divisible by mp={mp}; every "
            "serving replica needs a full set of model-axis shards"
        )
    return [
        ReplicaGroup(index=i, ranks=tuple(range(i * mp, (i + 1) * mp)))
        for i in range(world // mp)
    ]


def group_of(rank: int, world: int, mp: int = 1) -> ReplicaGroup:
    """The replica group containing ``rank``."""
    rank = int(rank)
    if not 0 <= rank < int(world):
        raise ValueError(f"rank {rank} outside world of {world}")
    return replica_groups(world, mp)[rank // int(mp)]
