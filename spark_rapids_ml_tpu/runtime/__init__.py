"""Resilience + observability runtime: checkpoint/resume, bounded
retries, fault injection, and the telemetry layer.

See ``docs/fault_tolerance.md`` and ``docs/observability.md`` for the
operator-facing contracts. All pieces are env-gated and fully inert by
default:

- ``TPUML_CKPT_DIR`` / ``TPUML_CKPT_EVERY`` — :class:`FitCheckpointer`
- ``TPUML_RETRIES`` / ``TPUML_BACKOFF_MS``  — :func:`with_retries`
- ``TPUML_FAULT_SPEC``                      — :func:`fault_site` hooks
- ``TPUML_TRACE`` / ``TPUML_TELEMETRY_*``   — :mod:`telemetry` spans,
  typed metrics, and the retrace/HBM watchdogs
- ``TPUML_SCHED_*``                         — :class:`FitScheduler`
  (explicit construction is the opt-in; see ``docs/scheduler.md``)
"""

from . import counters, metricspec, telemetry
from .admission import (
    AdmissionError,
    CircuitBreaker,
    DeadlineExceeded,
    Overloaded,
    ServiceEwma,
    ShuttingDown,
)
from .checkpoint import CKPT_VERSION, FitCheckpointer, array_digest, params_hash
from .faults import (
    FaultInjector,
    FaultSpecError,
    InjectedFault,
    InjectedResourceExhausted,
    SimulatedPreemption,
    fault_site,
    fault_sites_active,
    parse_fault_spec,
    reset_faults,
)
from .retry import (
    backoff_schedule,
    is_resource_exhausted,
    resolve_backoff_ms,
    resolve_retries,
    with_retries,
)
from .scheduler import FitPreempted, FitScheduler, preempt_point

__all__ = [
    "AdmissionError",
    "CircuitBreaker",
    "DeadlineExceeded",
    "Overloaded",
    "ServiceEwma",
    "ShuttingDown",
    "FitPreempted",
    "FitScheduler",
    "preempt_point",
    "CKPT_VERSION",
    "FitCheckpointer",
    "array_digest",
    "params_hash",
    "FaultInjector",
    "FaultSpecError",
    "InjectedFault",
    "InjectedResourceExhausted",
    "SimulatedPreemption",
    "fault_site",
    "fault_sites_active",
    "parse_fault_spec",
    "reset_faults",
    "backoff_schedule",
    "is_resource_exhausted",
    "resolve_backoff_ms",
    "resolve_retries",
    "with_retries",
    "counters",
    "metricspec",
    "telemetry",
]
