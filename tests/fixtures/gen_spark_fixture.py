"""Generate ``spark_vectorudt_parquet/`` — a parquet directory with the
EXACT physical layout Spark ML writes for a DataFrame of
``(features: VectorUDT, extra: array<float>, label: double)``:

* VectorUDT's on-disk struct ``{type: tinyint, size: int,
  indices: list<int>, values: list<double>}`` with MIXED dense
  (type=1: size/indices null) and sparse (type=0: CSR-style
  indices/values) rows — the shape ``data/dataframe.py`` decodes
  (reference consumes it through Spark itself, ``core.py:160-241``);
* the ``org.apache.spark.sql.parquet.row.metadata`` schema key Spark
  stamps on every file (carrying the UDT class name);
* Spark's directory layout: ``part-*.parquet`` + an empty ``_SUCCESS``.

This image has no pyspark, so the fixture is synthesized with pyarrow to
Spark 3.5's documented physical schema; on machines with pyspark the
live round-trip test in ``test_pyspark_parity.py`` covers the same
contract against genuinely Spark-written files.

Run from the repo root:  python tests/fixtures/gen_spark_fixture.py
"""
import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "spark_vectorudt_parquet")

N, D = 64, 4

SPARK_ROW_METADATA = {
    "type": "struct",
    "fields": [
        {
            "name": "features",
            "type": {
                "type": "udt",
                "class": "org.apache.spark.ml.linalg.VectorUDT",
                "pyClass": "pyspark.ml.linalg.VectorUDT",
                "sqlType": {
                    "type": "struct",
                    "fields": [
                        {"name": "type", "type": "byte", "nullable": False,
                         "metadata": {}},
                        {"name": "size", "type": "integer", "nullable": True,
                         "metadata": {}},
                        {"name": "indices",
                         "type": {"type": "array", "elementType": "integer",
                                  "containsNull": False},
                         "nullable": True, "metadata": {}},
                        {"name": "values",
                         "type": {"type": "array", "elementType": "double",
                                  "containsNull": False},
                         "nullable": True, "metadata": {}},
                    ],
                },
            },
            "nullable": True,
            "metadata": {},
        },
        {
            "name": "extra",
            "type": {"type": "array", "elementType": "float",
                     "containsNull": True},
            "nullable": True,
            "metadata": {},
        },
        {"name": "label", "type": "double", "nullable": True, "metadata": {}},
    ],
}


def main():
    rng = np.random.default_rng(42)
    types = []
    sizes = []
    indices = []
    values = []
    dense_truth = np.zeros((N, D))
    for i in range(N):
        if i % 3 == 0:
            # sparse row (type=0): CSR-style indices/values, size = D
            nz = sorted(rng.choice(D, size=2, replace=False).tolist())
            vv = [round(float(v), 6) for v in rng.normal(size=2)]
            types.append(0)
            sizes.append(D)
            indices.append(nz)
            values.append(vv)
            for j, v in zip(nz, vv):
                dense_truth[i, j] = v
        else:
            # dense row (type=1): Spark serializes (1, None, None, values)
            # — size AND indices are null, not empty
            vv = [float(i), float(i) / 2.0, float(i % 5), -1.0]
            types.append(1)
            sizes.append(None)
            indices.append(None)
            values.append(vv)
            dense_truth[i] = vv

    features = pa.StructArray.from_arrays(
        [
            pa.array(types, pa.int8()),
            pa.array(sizes, pa.int32()),
            pa.array(indices, pa.list_(pa.int32())),
            pa.array(values, pa.list_(pa.float64())),
        ],
        names=["type", "size", "indices", "values"],
    )
    extra = pa.array(
        [[float(i), float(2 * i)] for i in range(N)], pa.list_(pa.float32())
    )
    label = pa.array([float(i % 2) for i in range(N)], pa.float64())
    schema = pa.schema(
        [
            pa.field("features", features.type),
            pa.field("extra", extra.type),
            pa.field("label", label.type),
        ],
        metadata={
            "org.apache.spark.sql.parquet.row.metadata": json.dumps(
                SPARK_ROW_METADATA
            )
        },
    )
    table = pa.Table.from_arrays([features, extra, label], schema=schema)
    os.makedirs(OUT, exist_ok=True)
    pq.write_table(
        table,
        os.path.join(
            OUT, "part-00000-6a1c0e5b-spark-shaped-c000.snappy.parquet"
        ),
        compression="snappy",
    )
    open(os.path.join(OUT, "_SUCCESS"), "w").close()
    np.save(os.path.join(HERE, "spark_vectorudt_expected.npy"), dense_truth)
    print(f"wrote {OUT} ({N} rows, d={D})")


if __name__ == "__main__":
    main()
