"""Distributed context — the ``CumlContext`` replacement.

The reference bootstraps a NCCL/UCX communicator per barrier stage by
allGather-ing a NCCL uid through Spark
(``/root/reference/python/src/spark_rapids_ml/common/cuml_context.py:36-147``).
TPU-natively the communicator is the XLA runtime itself:

  * single-host: the local device mesh IS the cluster — nothing to boot.
  * multi-host: ``jax.distributed.initialize(coordinator, nprocs, pid)``
    plays the role of the uid allGather (out-of-band rendezvous), after
    which ``jax.devices()`` spans all hosts and the same mesh/pjit code
    runs unchanged over ICI/DCN.

``TpuDistContext`` is a context manager mirroring the reference's lifecycle
(enter = communicator formation, exit = teardown; ``cuml_context.py:109-166``).
On exception it calls ``jax.distributed.shutdown`` so surviving processes
don't hang — the analog of ``nccl.abort()`` (``cuml_context.py:155-160``).
"""

from __future__ import annotations


from typing import Optional

import jax

from ..runtime import envspec
from ..runtime.faults import fault_site
from ..runtime.retry import with_retries
from ..utils.logging import get_logger

logger = get_logger("TpuDistContext")


_process_initialized = False


class DistConfigError(ValueError):
    """Malformed multi-process rendezvous configuration (TPUML_* env)."""


def _env_topology_var(name: str) -> int:
    """Registry read re-raised as :class:`DistConfigError` (the launcher
    contract error type) with the variable named in the message."""
    try:
        return int(envspec.get(name))
    except envspec.EnvSpecError as e:
        raise DistConfigError(str(e)) from None


def _validated_env_topology() -> tuple:
    """(num_procs, proc_id) from env, with bounds checked up front.

    A malformed launcher env used to surface as a bare ``ValueError`` from
    ``int()`` deep inside the first mesh touch; the registry read names
    the variable and the constraint (type + lower bound); the cross-var
    bound is checked here.
    """
    num_procs = _env_topology_var("TPUML_NUM_PROCS")
    proc_id = _env_topology_var("TPUML_PROC_ID")
    if proc_id >= num_procs:
        raise DistConfigError(
            f"TPUML_PROC_ID={proc_id} must be < TPUML_NUM_PROCS={num_procs}"
        )
    return num_procs, proc_id


def distributed_env_configured() -> bool:
    """True when the launcher provided multi-process rendezvous info."""
    return (
        envspec.is_set("TPUML_COORDINATOR")
        and _validated_env_topology()[0] > 1
    )


def ensure_distributed() -> None:
    """Idempotent env-driven multi-process bootstrap.

    Called from ``make_mesh`` — every estimator's first mesh touch — so any
    fit in a launcher-provided multi-process environment joins the global
    device world before sharding anything (the reference injects its
    communicator into every fit the same way, ``core.py:749-755``).
    ``jax.distributed`` is process-global, so unlike the reference's
    per-stage NCCL communicator it is formed once and reused by every
    subsequent fit in the process.
    """
    global _process_initialized
    if _process_initialized or not distributed_env_configured():
        return
    TpuDistContext().__enter__()


class TpuDistContext:
    """rank/nranks multi-process bootstrap for multi-host TPU pods.

    Environment-driven (the launcher provides the rendezvous info, exactly
    as Spark's allGather provided the NCCL uid in the reference):

      TPUML_COORDINATOR  address of process 0, e.g. "10.0.0.1:8476"
      TPUML_NUM_PROCS    total process count
      TPUML_PROC_ID      this process's rank

    With no env set, runs single-process (all local devices).  Entering is
    idempotent across instances (first enter in the process initializes).
    On exception, exit shuts the distributed runtime down so surviving
    ranks fail fast instead of hanging in a collective — the analog of the
    reference's ``nccl.abort()`` (``cuml_context.py:155-160``).
    """

    def __init__(
        self,
        coordinator: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
    ):
        self.coordinator = coordinator or envspec.get("TPUML_COORDINATOR")
        env_procs, env_pid = _validated_env_topology()
        self.num_processes = num_processes or env_procs
        self.process_id = process_id if process_id is not None else env_pid
        if not (0 <= self.process_id < self.num_processes):
            raise DistConfigError(
                f"process_id={self.process_id} must be in "
                f"[0, num_processes={self.num_processes})"
            )
        self._initialized_here = False

    @property
    def rank(self) -> int:
        return self.process_id

    @property
    def nranks(self) -> int:
        return self.num_processes

    def __enter__(self) -> "TpuDistContext":
        global _process_initialized
        if (
            self.num_processes > 1
            and self.coordinator
            and not _process_initialized
        ):
            logger.info(
                "jax.distributed.initialize(coordinator=%s, nprocs=%d, pid=%d)",
                self.coordinator, self.num_processes, self.process_id,
            )
            # The common multi-host launch race is rank 0's coordinator not
            # listening yet when rank N boots; retry with backoff so a pod
            # slice survives staggered container starts (TPUML_RETRIES).
            def _connect() -> None:
                fault_site("init:connect")
                jax.distributed.initialize(
                    coordinator_address=self.coordinator,
                    num_processes=self.num_processes,
                    process_id=self.process_id,
                )

            with_retries(_connect, what="jax.distributed.initialize")
            self._initialized_here = True
            _process_initialized = True
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        global _process_initialized
        if exc_type is not None:
            logger.error("distributed stage failed: %s", exc_val)
            if self._initialized_here:
                # abort semantics: tear the runtime down so peers blocked in
                # a collective error out instead of hanging
                try:
                    jax.distributed.shutdown()
                except Exception:  # pragma: no cover - teardown best effort
                    pass
                _process_initialized = False
