"""Exact kNN benchmark (reference ``bench_nearest_neighbors.py``)."""

from __future__ import annotations

import numpy as np

from .base import BenchmarkBase
from .utils import with_benchmark

from spark_rapids_ml_tpu.data import DataFrame


class BenchmarkNearestNeighbors(BenchmarkBase):
    name = "knn"
    default_dataset = "blobs"

    def add_arguments(self, parser) -> None:
        parser.add_argument("--k", type=int, default=200)
        parser.add_argument("--num_queries", type=int, default=1000)

    def run_once(self, train_df, transform_df):
        a = self.args
        X, _ = self.features_and_label(train_df)
        # queries come from transform_df (== train_df unless --transform_path)
        Xq_all, _ = self.features_and_label(transform_df)
        Xq = Xq_all[: a.num_queries]
        if a.mode == "cpu":
            from sklearn.neighbors import NearestNeighbors as SkNN

            model, fit_t = with_benchmark(
                "fit", lambda: SkNN(n_neighbors=a.k, algorithm="brute").fit(X)
            )
            _, search_t = with_benchmark("kneighbors", lambda: model.kneighbors(Xq))
        else:
            from spark_rapids_ml_tpu.knn import NearestNeighbors

            est = NearestNeighbors(k=a.k, num_workers=a.num_chips)
            model, fit_t = with_benchmark("fit", lambda: est.fit(train_df))
            _, search_t = with_benchmark(
                "kneighbors",
                lambda: model.kneighbors(DataFrame({"features": Xq})),
            )
        return {
            "fit_time": fit_t,
            "transform_time": search_t,
            "total_time": fit_t + search_t,
        }
