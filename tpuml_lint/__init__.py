"""tpuml-lint — AST-based invariant checker for spark-tpu-ml.

Run as ``python -m tpuml_lint <paths>``. Stdlib-only; see
``docs/static_analysis.md`` for the rule catalog and suppression
syntax (``# tpuml: ignore[TPU00N]``).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from . import (
    tpu001_raw_env,
    tpu002_env_docs,
    tpu003_jit_in_loop,
    tpu004_nondeterminism,
    tpu005_static_args,
    tpu006_lane_align,
    tpu007_metric_catalog,
    tpu008_label_cardinality,
    tpu009_inline_pspec,
    tpu010_lock_order,
    tpu011_block_under_lock,
    tpu012_thread_lifecycle,
)
from .core import (
    Finding,
    SourceFile,
    apply_baseline,
    iter_py_files,
    load_baseline,
    load_source,
    write_baseline,
)
from .envinfo import repo_root_from

__version__ = "0.1.0"

#: per-file rules expose check_file(sf); project rules expose
#: check_project(files, repo_root).
FILE_RULES = (
    tpu001_raw_env,
    tpu003_jit_in_loop,
    tpu004_nondeterminism,
    tpu005_static_args,
    tpu006_lane_align,
    tpu009_inline_pspec,
    tpu012_thread_lifecycle,
)
PROJECT_RULES = (
    tpu002_env_docs,
    tpu007_metric_catalog,
    tpu008_label_cardinality,
    tpu010_lock_order,
    tpu011_block_under_lock,
)
ALL_RULES = FILE_RULES + PROJECT_RULES


def run(
    paths: Sequence[str],
    repo_root: str,
    rules: Sequence[str] = (),
) -> Tuple[List[Finding], List[SourceFile]]:
    """Lint ``paths``; returns (unsuppressed findings, parsed files).

    ``rules`` restricts to the given codes (empty = all). Project rules
    see every parsed file regardless of which file a finding lands in;
    suppression comments are honoured only for findings in parsed files
    (doc-file findings from TPU002 can't carry python comments).
    """
    selected = {r.upper() for r in rules}

    def want(code: str) -> bool:
        return not selected or code in selected

    findings: List[Finding] = []
    files: List[SourceFile] = []
    by_path = {}
    for ap in iter_py_files(paths, repo_root):
        sf, err = load_source(ap, repo_root)
        if err is not None:
            findings.append(err)
            continue
        files.append(sf)
        by_path[sf.path] = sf

    for sf in files:
        for rule in FILE_RULES:
            if not want(rule.CODE):
                continue
            for f in rule.check_file(sf):
                if not sf.suppressed(f):
                    findings.append(f)

    for rule in PROJECT_RULES:
        if not want(rule.CODE):
            continue
        for f in rule.check_project(files, repo_root):
            sf = by_path.get(f.path)
            if sf is not None and sf.suppressed(f):
                continue
            findings.append(f)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, files
