"""Measured autotuner (runtime/autotune.py): the off-default must be
perfectly inert, the cache must survive corruption/concurrency without
failing a fit, probes must be budget-bounded and warm-cache-free, and
rank discipline must keep non-zero ranks from ever writing the file."""

import json
import os
import threading

import numpy as np
import pytest

import jax.numpy as jnp

import spark_rapids_ml_tpu.ops.streaming as streaming
import spark_rapids_ml_tpu.ops.tree_kernels as tk
from spark_rapids_ml_tpu.ops.ivf_kernels import resolve_ann_params
from spark_rapids_ml_tpu.runtime import autotune, envspec, telemetry
from spark_rapids_ml_tpu.serving.runtime import ServingRuntime


@pytest.fixture(autouse=True)
def _fresh_tuner():
    autotune.reset_autotune()
    telemetry.reset_telemetry()
    yield
    autotune.reset_autotune()
    telemetry.reset_telemetry()


def _probe_span_count():
    return sum(
        st["count"]
        for name, st in telemetry.span_stats().items()
        if name.startswith("autotune.probe.")
    )


def _cfg(**kw):
    base = dict(
        max_depth=4, n_bins=32, n_features=16, n_stats=2, impurity="gini",
        k_features=16, min_samples_leaf=1, min_info_gain=0.0,
        min_samples_split=2, bootstrap=True,
    )
    base.update(kw)
    return tk.ForestConfig(**base)


# --------------------------------------------------------------------------
# defaults inert
# --------------------------------------------------------------------------


def test_defaults_inert(tmp_path, monkeypatch):
    """TPUML_AUTOTUNE unset: no cache file, no probe spans, no autotune
    metric series, and tune()/consult() answer None before any I/O."""
    monkeypatch.delenv("TPUML_AUTOTUNE", raising=False)
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    assert not autotune.active()
    assert autotune.consult("rf_tree_batch", "k") is None
    calls = []
    assert (
        autotune.tune("rf_tree_batch", "k", [1, 2], lambda c: calls.append(c))
        is None
    )
    assert not calls, "off mode must never invoke the measure closure"
    assert autotune.consult("rf_tree_batch", "k") is None
    assert list(tmp_path.iterdir()) == [], "off mode must not create files"
    snap = telemetry.metrics_snapshot()
    assert not any(k.startswith("autotune") for k in snap)
    assert _probe_span_count() == 0


def test_defaults_inert_resolvers(monkeypatch):
    """With the tuner off, every wired resolver answers exactly its
    static heuristic — the bit-identical-outputs contract."""
    monkeypatch.delenv("TPUML_AUTOTUNE", raising=False)
    cfg = _cfg()
    monkeypatch.setenv("TPUML_RF_TREE_BATCH", "auto")
    base_batch = tk.resolve_tree_batch(8, cfg, 600)
    assert resolve_ann_params(4096) == resolve_ann_params(4096)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    assert streaming.select_wire_format(x, requested="auto") in (
        "int8", "f16", "f32",
    )
    with autotune.collect() as decisions:
        assert tk.resolve_tree_batch(8, cfg, 600) == base_batch
    assert decisions == [], "off mode must not file provenance"


# --------------------------------------------------------------------------
# probe engine
# --------------------------------------------------------------------------


def test_probe_default_always_measured_and_budget_bounded(monkeypatch):
    """The heuristic default (candidates[0]) is measured even under a
    zero budget, and the budget stops further measurements."""
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    measured = []

    def measure(c):
        measured.append(c)
        return 0.010 if c == "default" else 0.001

    d = autotune.probe(
        "k", "s", ["default", "b", "c", "d"], measure, budget_ms=0.0,
        store_result=False,
    )
    assert measured == ["default"], measured
    assert d.value == "default"


def test_probe_prefers_measured_winner_with_margin(monkeypatch):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    costs = {1: 0.02, 2: 0.01, 4: 0.004, 8: 0.03}
    d = autotune.probe("k", "s", [1, 2, 4, 8], costs.get, store_result=False)
    assert d.value == 4
    # near-tie (within the 2% hysteresis margin) resolves to the default
    d2 = autotune.probe(
        "k2", "s", [1, 2], {1: 0.1000, 2: 0.0999}.get, store_result=False
    )
    assert d2.value == 1


def test_probe_infeasible_and_raising_candidates_dropped(monkeypatch):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")

    def measure(c):
        if c == "bad":
            return None
        if c == "boom":
            raise RuntimeError("candidate exploded")
        return {"a": 0.02, "b": 0.01}[c]

    d = autotune.probe(
        "k", "s", ["a", "bad", "boom", "b"], measure, store_result=False
    )
    assert d.value == "b"


def test_probe_spans_carry_warmup_and_count(monkeypatch, tmp_path):
    """Probe dispatches run under autotune.probe.<knob> spans with the
    inheritable warmup attr — and a warm cache runs ZERO of them."""
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    # TPUML_TRACE is path-valued: point it at tmp so the atexit dump
    # doesn't litter the working directory.
    monkeypatch.setenv("TPUML_TRACE", str(tmp_path / "trace"))
    telemetry.reset_telemetry()
    seen = []

    def sink(span, _thread):
        if span["name"].startswith("autotune.probe."):
            seen.append(span)

    telemetry.add_span_sink(sink)
    try:
        key = autotune.shape_key(n=100)
        v = autotune.tune("k", key, [1, 2], {1: 0.02, 2: 0.01}.get)
        assert v == 2
        assert seen and all(s["args"].get("warmup") for s in seen)
        cold_probes = telemetry.counter("autotune_probes_total").value(knob="k")
        assert cold_probes == 1
        n_spans = len(seen)
        # warm pass: same knob+key answers from the cache, no new spans
        autotune.reset_autotune()
        assert autotune.tune("k", key, [1, 2], {1: 0.02, 2: 0.01}.get) == 2
        assert len(seen) == n_spans
        assert telemetry.counter("autotune_probes_total").value(knob="k") == 1
        assert telemetry.counter("autotune_cache_hits").value(knob="k") == 1
    finally:
        telemetry.remove_span_sink(sink)


# --------------------------------------------------------------------------
# cache robustness
# --------------------------------------------------------------------------


def _cache_file(tmp_path):
    return os.path.join(str(tmp_path), autotune.CACHE_FILENAME)


def test_corrupt_cache_falls_back_loudly_once(tmp_path, monkeypatch):
    import logging

    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    with open(_cache_file(tmp_path), "w") as f:
        f.write("{ definitely not json")
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    # the package root disables propagation, so attach directly
    logger = logging.getLogger("spark_rapids_ml_tpu.autotune")
    handler = _Capture(level=logging.WARNING)
    logger.addHandler(handler)
    try:
        assert autotune.consult("k", "s") is None
        assert autotune.consult("k", "s2") is None
    finally:
        logger.removeHandler(handler)
    warnings = [r for r in records if "unreadable" in r.getMessage()]
    assert len(warnings) == 1, "corrupt cache must warn exactly once"


def test_truncated_cache_tolerated(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    autotune.store("k", "s", 7, fitness_s=0.01)
    full = open(_cache_file(tmp_path)).read()
    with open(_cache_file(tmp_path), "w") as f:
        f.write(full[: len(full) // 2])  # torn write
    autotune.reset_autotune()
    assert autotune.consult("k", "s") is None  # heuristics, not a crash


def test_wrong_version_and_malformed_entries_tolerated(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    doc = {
        "version": 999,
        "entries": {"k|s": {"value": 3}},
    }
    with open(_cache_file(tmp_path), "w") as f:
        json.dump(doc, f)
    assert autotune.consult("k", "s") is None
    # right version, junk entries: only well-formed ones survive
    autotune.reset_autotune()
    doc = {
        "version": autotune.CACHE_VERSION,
        "entries": {"k|s": {"value": 3}, "k|bad": "nope", "k|bad2": {}},
    }
    with open(_cache_file(tmp_path), "w") as f:
        json.dump(doc, f)
    assert autotune.consult("k", "s") == 3
    assert autotune.consult("k", "bad") is None
    assert autotune.consult("k", "bad2") is None


def test_concurrent_writers_keep_a_valid_file(tmp_path, monkeypatch):
    """N threads storing different knobs concurrently: the file stays
    parseable (atomic replace) and the merge keeps every knob."""
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))

    def write(i):
        autotune.store(f"knob{i}", "s", i, fitness_s=0.01)

    threads = [threading.Thread(target=write, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    doc = json.load(open(_cache_file(tmp_path)))
    assert doc["version"] == autotune.CACHE_VERSION
    autotune.reset_autotune()
    for i in range(8):
        assert autotune.consult(f"knob{i}", "s") == i


def test_rank_nonzero_never_writes(tmp_path, monkeypatch):
    """Simulated 2-rank world: rank 1 probes (its fit still benefits
    in-process) but only rank 0 may write the shared file."""
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    monkeypatch.setenv("TPUML_PROC_ID", "1")
    v = autotune.tune("k", "s", [1, 2], {1: 0.02, 2: 0.01}.get)
    assert v == 2
    assert autotune.consult("k", "s") == 2  # in-process winner survives
    assert not os.path.exists(_cache_file(tmp_path))
    monkeypatch.setenv("TPUML_PROC_ID", "0")
    autotune.store("k", "s", 2, fitness_s=0.01)
    assert os.path.exists(_cache_file(tmp_path))


def test_memory_only_when_cache_dir_unset(monkeypatch):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.delenv("TPUML_AUTOTUNE_CACHE", raising=False)
    v = autotune.tune("k", "s", [1, 2], {1: 0.02, 2: 0.01}.get)
    assert v == 2
    assert autotune.consult("k", "s") == 2


def test_force_reprobes_and_overwrites_stale_entry(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    assert autotune.tune("k", "s", [1, 2], {1: 0.02, 2: 0.01}.get) == 2
    # hardware moved under the cache: candidate 1 is now fastest
    calls = []

    def remeasure(c):
        calls.append(c)
        return {1: 0.001, 2: 0.01}[c]

    # on-mode trusts the (stale) entry — no measurement
    autotune.reset_autotune()
    assert autotune.tune("k", "s", [1, 2], remeasure) == 2
    assert not calls
    monkeypatch.setenv("TPUML_AUTOTUNE", "force")
    autotune.reset_autotune()
    assert autotune.tune("k", "s", [1, 2], remeasure) == 1
    assert calls
    doc = json.load(open(_cache_file(tmp_path)))
    assert doc["entries"]["k|s"]["value"] == 1
    # and the overwrite persists for a later on-mode run
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    autotune.reset_autotune()
    assert autotune.consult("k", "s") == 1


# --------------------------------------------------------------------------
# shape keys
# --------------------------------------------------------------------------


def test_shape_key_buckets_and_pins():
    k1 = autotune.shape_key(n=1000, d=17, dtype="float32")
    k2 = autotune.shape_key(n=900, d=20, dtype="float32")
    k3 = autotune.shape_key(n=3000, d=17, dtype="float32")
    assert k1 == k2, "same pow2 buckets must share an entry"
    assert k1 != k3
    assert autotune.shape_key(n=1000, dtype="float32") != autotune.shape_key(
        n=1000, dtype="float16"
    )
    assert "backend=" in k1 and "mesh=1x1" in k1
    assert autotune.shape_key(n=8, depth=13) != autotune.shape_key(n=8, depth=7)


# --------------------------------------------------------------------------
# resolver integration
# --------------------------------------------------------------------------


def test_tree_batch_consults_cache_and_validates(monkeypatch, tmp_path):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    monkeypatch.setenv("TPUML_RF_TREE_BATCH", "auto")
    cfg = _cfg()
    key = autotune.shape_key(
        n=600, d=cfg.n_features, k=cfg.n_stats, dtype="uint8",
        depth=cfg.max_depth, group=8,
    )
    autotune.store("rf_tree_batch", key, 2, fitness_s=0.01)
    with autotune.collect() as decisions:
        assert tk.resolve_tree_batch(8, cfg, 600) == 2
    assert decisions[-1]["provenance"] == "cache_hit"
    # a stale width that does not divide the group falls back loudly-
    # silently to the heuristic (and files heuristic provenance)
    autotune.store("rf_tree_batch", key, 3, fitness_s=0.01)
    autotune.reset_autotune()
    with autotune.collect() as decisions:
        batch = tk.resolve_tree_batch(8, cfg, 600)
    assert 8 % batch == 0
    assert decisions[-1]["provenance"] == "heuristic"


def test_ann_params_consult_applies_only_matching_nlist(monkeypatch, tmp_path):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    n = 4096
    base_nlist, base_nprobe = resolve_ann_params(n)
    autotune.store(
        "ann_params", autotune.shape_key(n=n), [base_nlist, base_nprobe + 3]
    )
    assert resolve_ann_params(n) == (base_nlist, base_nprobe + 3)
    # explicit pins always win over the cache
    assert resolve_ann_params(n, nlist=32, nprobe=4) == (32, 4)
    # entry whose nlist no longer matches the resolved nlist: nprobe
    # half of the pair must NOT apply
    autotune.reset_autotune()
    autotune.store(
        "ann_params", autotune.shape_key(n=n), [base_nlist + 1, 1]
    )
    nl, npb = resolve_ann_params(n, nlist=base_nlist)
    assert (nl, npb) == (base_nlist, base_nprobe)


def test_serving_window_consults_cache(monkeypatch, tmp_path):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    monkeypatch.delenv("TPUML_SERVE_BATCH_WINDOW_US", raising=False)
    from spark_rapids_ml_tpu.serving.registry import MIN_BUCKET_ROWS

    autotune.store(
        "serve_batch_window_us",
        autotune.shape_key(k=MIN_BUCKET_ROWS),
        777,
    )
    rt = ServingRuntime()
    assert rt._window_s == pytest.approx(777 / 1e6)
    # explicit arg and env pin both bypass the cache
    rt = ServingRuntime(batch_window_us=123)
    assert rt._window_s == pytest.approx(123 / 1e6)
    monkeypatch.setenv("TPUML_SERVE_BATCH_WINDOW_US", "456")
    rt = ServingRuntime()
    assert rt._window_s == pytest.approx(456 / 1e6)


def test_stream_stage_depth_consults_cache(monkeypatch, tmp_path):
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    monkeypatch.delenv("TPUML_STREAM_STAGE_DEPTH", raising=False)
    from spark_rapids_ml_tpu.data.chunks import ArrayChunkSource
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 8)).astype(np.float32)
    mesh = make_mesh()
    np_dtype = np.dtype("float32")
    src = ArrayChunkSource(X)
    first = next(iter(src.iter_chunks(32, np_dtype)))
    depth_key = autotune.shape_key(
        n=first.X.shape[0], d=first.X.shape[1], dtype=np_dtype, mesh=mesh
    )
    autotune.store("stream_stage_depth", depth_key, 0)
    consumed = list(
        streaming.iter_device_chunks(
            ArrayChunkSource(X), mesh, 32, jnp.float32,
            need_y=False, need_w=False,
        )
    )
    assert consumed
    assert streaming.last_ingest_report()["stage_depth"] == 0


def test_wire_format_tuned_only_among_feasible(monkeypatch, tmp_path):
    """The tuner may pick a WIDER (more accurate) format than the error
    probe's choice, never a narrower one; and a poisoned cache entry
    outside the feasible ladder is ignored."""
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    rng = np.random.default_rng(0)
    # smooth data: int8 feasible, so the ladder is int8/f16/f32
    x = rng.uniform(-1, 1, size=(64, 8)).astype(np.float32)
    kind = streaming.select_wire_format(x, requested="auto", mesh=mesh)
    assert kind in ("int8", "f16", "f32")
    # the winner is cached: a second resolve consults, zero probes
    before = telemetry.counter("autotune_probes_total").value(
        knob="wire_dtype"
    )
    assert (
        streaming.select_wire_format(x, requested="auto", mesh=mesh) == kind
    )
    after = telemetry.counter("autotune_probes_total").value(knob="wire_dtype")
    assert after == before
    # explicit requests are never tuned
    assert streaming.select_wire_format(x, requested="f32", mesh=mesh) == "f32"


def test_fit_report_carries_autotune_provenance(monkeypatch, tmp_path):
    """End-to-end: a RandomForest fit with the tuner on reports every
    knob decision in _fit_report['autotuned']; with the tuner off the
    key is absent and the model is identical."""
    from spark_rapids_ml_tpu.classification import RandomForestClassifier
    from spark_rapids_ml_tpu.data import DataFrame

    rng = np.random.default_rng(3)
    X = rng.normal(size=(240, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    df = DataFrame({"features": list(X), "label": y})

    def fit_model():
        est = RandomForestClassifier(
            numTrees=4, maxDepth=3, seed=7, num_workers=1
        )
        return est.fit(df)

    monkeypatch.delenv("TPUML_AUTOTUNE", raising=False)
    monkeypatch.setenv("TPUML_RF_TREE_BATCH", "auto")
    m_off = fit_model()
    assert "autotuned" not in m_off._fit_report
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_CACHE", str(tmp_path))
    m_on = fit_model()
    tuned = m_on._fit_report["autotuned"]
    assert any(d["knob"] == "rf_tree_batch" for d in tuned)
    assert all(
        d["provenance"] in ("cache_hit", "probed", "heuristic") for d in tuned
    )
    # consult-only knob: tuned widths come from the cache, so the fitted
    # forest is identical either way at the same (valid) width
    np.testing.assert_array_equal(
        m_off.transform(df)["prediction"], m_on.transform(df)["prediction"]
    )
