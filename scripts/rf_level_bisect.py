"""Bisect the per-level cost of the RF compact-strategy build at the bench
shape (131k x 256, k=16, nb=128, S=2) on the real chip.

Stages timed at a steady-state deep level (default n_nodes=1024):
  full level  — histogram + gain + routing, as _build_tree runs it
  sort        — the per-level stable lax.sort((seg, iota))
  glue        — searchsorted/table/row-index machinery after the sort
  gathers     — sw[src2] + hist_src[src2] row gathers
  kernel      — subblock_hist + wide segment_sum
  gain        — _best_splits_from_hist over the full histogram
  subset_gather — make_hist_src (contraction gather) cost
  routing     — best-feature bin lookup + child computation

All timings amortize RTT with ITERS in-jit repeats carrying a non-foldable
dependence, with per-rep salted inputs (tunnel memoization).
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.ops import tree_kernels as tk
from spark_rapids_ml_tpu.ops.rf_pallas import BLOCK_ROWS, subblock_hist

N = 131072
D = 256
K = 16
NB = 128
S = 2
N_NODES = int(os.environ.get("RF_BISECT_NODES", 1024))
ITERS = 32


def timed(fn, *args, reps=3):
    jitted = jax.jit(fn)
    float(jitted(jnp.float32(0), *args))
    best = 1e30
    for r in range(reps):
        salt = jnp.float32(1e-22 * (r + 1))
        t0 = time.perf_counter()
        float(jitted(salt, *args))
        best = min(best, time.perf_counter() - t0)
    return best / ITERS


def loop(body):
    def fn(salt, *args):
        def step(i, c):
            out = body(c, i, *args)
            return c + jnp.sum(out).astype(jnp.float32) * 1e-30
        return lax.fori_loop(0, ITERS, step, salt)
    return fn


def dep(ix, c):
    return jnp.where(c >= jnp.float32(-1e30), ix, 0)


def main():
    rng = np.random.default_rng(0)
    bins_np = rng.integers(0, NB, size=(N, D), dtype=np.uint8)
    bins = jnp.asarray(bins_np)
    sw = jnp.asarray(rng.random((N, S)).astype(np.float32))
    # realistic skewed node occupancy at a deep level
    node_p = rng.dirichlet(np.full(N_NODES, 0.5))
    seg_np = rng.choice(N_NODES, size=N, p=node_p).astype(np.int32)
    seg = jnp.asarray(seg_np)
    feats = jnp.asarray(
        np.stack([rng.choice(D, size=K, replace=False) for _ in range(N_NODES)])
        .astype(np.int32)
    )
    packed = tk._pack_bins(bins)
    hist_src = tk._contract_gather(packed, feats[jnp.clip(seg, 0, N_NODES-1)])

    r_sub = tk._compact_r_sub(N, N_NODES, BLOCK_ROWS, S)
    n_pad = -(-(N + (N_NODES + 1) * r_sub) // BLOCK_ROWS) * BLOCK_ROWS
    n_sb = n_pad // r_sub
    print(f"n_nodes={N_NODES} r_sub={r_sub} n_pad={n_pad} n_sb={n_sb}")

    # --- sort
    def f_sort(c, i, seg):
        iota = jnp.arange(N, dtype=jnp.int32)
        _, perm = lax.sort((dep(seg, c), iota), num_keys=1)
        return perm
    print(f"sort            : {timed(loop(f_sort), seg)*1e3:6.2f} ms")

    # --- glue (post-sort index machinery)
    def glue(keys_s, perm):
        starts = jnp.searchsorted(
            keys_s, jnp.arange(N_NODES + 1, dtype=jnp.int32), side="left"
        ).astype(jnp.int32)
        lens = starts[1:] - starts[:-1]
        plen = -(-lens // r_sub) * r_sub
        pstart = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(plen)])
        sb_pos = jnp.arange(n_sb, dtype=jnp.int32) * r_sub
        seg_sb = jnp.searchsorted(pstart[1:], sb_pos, side="right").astype(jnp.int32)
        sbc = jnp.clip(seg_sb, 0, N_NODES - 1)
        tbl = jnp.stack([starts[:-1], pstart[:-1], lens], axis=1)
        tbl_rows = jnp.broadcast_to(tbl[sbc][:, None, :], (n_sb, r_sub, 3)).reshape(n_pad, 3)
        pos = jnp.arange(n_pad, dtype=jnp.int32)
        off = pos - tbl_rows[:, 1]
        src = tbl_rows[:, 0] + off
        pvalid = (off < tbl_rows[:, 2]) & (
            jnp.broadcast_to(seg_sb[:, None], (n_sb, r_sub)).reshape(n_pad) < N_NODES)
        src2 = perm[jnp.clip(src, 0, N - 1)]
        seg_red = jnp.where(seg_sb < N_NODES, seg_sb, N_NODES)
        return src2, pvalid, seg_red

    def f_glue(c, i, seg):
        iota = jnp.arange(N, dtype=jnp.int32)
        keys_s, perm = lax.sort((dep(seg, c), iota), num_keys=1)
        src2, pvalid, seg_red = glue(keys_s, perm)
        return src2 + pvalid + seg_red[:1]
    print(f"sort+glue       : {timed(loop(f_glue), seg)*1e3:6.2f} ms")

    # --- + gathers
    def f_gath(c, i, seg, sw, hist_src):
        iota = jnp.arange(N, dtype=jnp.int32)
        keys_s, perm = lax.sort((dep(seg, c), iota), num_keys=1)
        src2, pvalid, seg_red = glue(keys_s, perm)
        swq = sw[src2] * pvalid[:, None].astype(sw.dtype)
        binq = hist_src[src2].astype(jnp.int32)
        return swq.sum() + binq.sum()
    print(f"sort+glue+gather: {timed(loop(f_gath), seg, sw, hist_src)*1e3:6.2f} ms")

    # --- full _hist_compact
    def f_hist(c, i, seg, sw, hist_src):
        h, p = tk._hist_compact(
            jnp.where(c >= jnp.float32(-1e30), hist_src, 0), seg, sw,
            n_nodes=N_NODES, nb=NB, r_sub=r_sub, n_pad=n_pad,
            f_chunk=K, variance=False)
        return h.sum() + p.sum()
    t_hist = timed(loop(f_hist), seg, sw, hist_src)
    print(f"hist_compact    : {t_hist*1e3:6.2f} ms")

    # --- gain search
    hist_full, parent = tk._hist_compact(
        hist_src, seg, sw, n_nodes=N_NODES, nb=NB, r_sub=r_sub,
        n_pad=n_pad, f_chunk=K, variance=False)
    cfg = tk.ForestConfig(
        max_depth=13, n_bins=NB, n_features=D, n_stats=S, impurity="gini",
        k_features=K, min_samples_leaf=1, min_info_gain=0.0,
        min_samples_split=2, bootstrap=True)
    pcount = tk._count(parent, "gini")
    pimp = tk._impurity(parent, "gini")
    realf = feats.T

    def f_gain(c, i, hist_full, parent, pcount, pimp, realf):
        g, f, b = tk._best_splits_from_hist(
            jnp.where(c >= jnp.float32(-1e30), hist_full, 0.0),
            parent, pcount, pimp, realf, NB, cfg)
        return g.sum() + f.sum() + b.sum()
    print(f"gain search     : {timed(loop(f_gain), hist_full, parent, pcount, pimp, realf)*1e3:6.2f} ms")

    # --- subset gather (contraction)
    def f_subset(c, i, packed, seg):
        rf = feats[jnp.clip(dep(seg, c), 0, N_NODES - 1)]
        return tk._contract_gather(packed, rf)
    print(f"subset extract  : {timed(loop(f_subset), packed, seg)*1e3:6.2f} ms")

    # --- routing
    bf = jnp.asarray(rng.integers(0, D, size=(N_NODES,)).astype(np.int32))
    bb = jnp.asarray(rng.integers(0, NB, size=(N_NODES,)).astype(np.int32))

    def f_route(c, i, packed, seg, bf, bb):
        lc = jnp.clip(dep(seg, c), 0, N_NODES - 1)
        row_feat = bf[lc]
        row_bin = tk._contract_gather(packed, row_feat[:, None])[:, 0]
        go_right = (row_bin > bb[lc]).astype(jnp.int32)
        return 2 * seg + 1 + go_right
    print(f"routing         : {timed(loop(f_route), packed, seg, bf, bb)*1e3:6.2f} ms")

    # --- feats top_k
    def f_feats(c, i, key):
        r = jax.random.uniform(jax.random.fold_in(key, i + c.astype(jnp.int32)), (N_NODES, D))
        return lax.top_k(r, K)[1]
    print(f"feats top_k     : {timed(loop(f_feats), jax.random.PRNGKey(0))*1e3:6.2f} ms")


if __name__ == "__main__":
    main()
