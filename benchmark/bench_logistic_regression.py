"""LogisticRegression benchmark (reference ``bench_logistic_regression.py``;
reference headline config maxIter=200, ``run_benchmark.sh:115-135``)."""

from __future__ import annotations

import numpy as np

from .base import BenchmarkBase
from .utils import with_benchmark


class BenchmarkLogisticRegression(BenchmarkBase):
    name = "logistic_regression"
    default_dataset = "classification"

    def add_arguments(self, parser) -> None:
        parser.add_argument("--maxIter", type=int, default=200)
        parser.add_argument("--regParam", type=float, default=0.0)
        parser.add_argument("--tol", type=float, default=1e-6)

    def run_once(self, train_df, transform_df):
        a = self.args
        X, y = self.features_and_label(train_df)
        Xe, ye = self.features_and_label(transform_df)
        if a.mode == "cpu":
            from sklearn.linear_model import LogisticRegression as SkLR

            c = 1.0 / (a.regParam * len(y)) if a.regParam > 0 else 1e12
            model, fit_t = with_benchmark(
                "fit", lambda: SkLR(max_iter=a.maxIter, C=c, tol=a.tol).fit(X, y)
            )
            pred, tr_t = with_benchmark("transform", lambda: model.predict(Xe))
        else:
            from spark_rapids_ml_tpu.classification import LogisticRegression

            est = LogisticRegression(
                maxIter=a.maxIter, regParam=a.regParam, tol=a.tol,
                num_workers=a.num_chips,
            )
            model, fit_t = with_benchmark("fit", lambda: est.fit(train_df))
            out, tr_t = with_benchmark("transform", lambda: model.transform(transform_df))
            pred = np.asarray(out["prediction"])
        acc = float((pred == ye).mean())
        return {
            "fit_time": fit_t,
            "transform_time": tr_t,
            "total_time": fit_t + tr_t,
            "accuracy": acc,
        }
