"""Fused Pallas Lloyd step: assignment + centroid stats in ONE data pass.

The XLA chunked step (``kmeans_kernels._chunk_stats``) materializes two
(csize, k) intermediates per chunk in HBM — the distance tile consumed by
argmin and the assignment one-hot consumed by the stats contraction and
the counts reduction (~268 MB each at csize=65536, k=1024, f32). Measured
effect on v5e at 12M x 256 / k=1024: the iteration runs at ~103 ms where
the two MXU contractions alone price at ~64 ms (bf16) — and switching the
contractions to bf16 does not move the time, the signature of an
HBM-intermediate-bound loop, not an MXU-bound one.

This kernel streams row tiles HBM->VMEM once and keeps EVERYTHING else
VMEM-resident: distances (computed as ``c_sq - 2 x.c``; ``x_sq`` joins
only for the cost, it cannot change the argmin), the one-hot, and the
(k, d) sums / (k,) counts / cost accumulators. HBM traffic per iteration
drops to one read of X.

Numerics match the XLA step: f32 accumulation everywhere;
``matmul_dtype=bfloat16`` rounds only the two contraction operands (the
one-hot is exact in bf16; x rounds at ~1e-3 relative, washed out by the
per-cluster mean) — the same contract as ``kmeans_kernels.stats_dot``.

Reference role: this replaces the fused distance+update kernels cuML's
KMeans runs per minibatch (``/root/reference/python/src/spark_rapids_ml/
clustering.py`` drives cuml.cluster.KMeans_mg whose CUDA kernels fuse
pairwise distances with the assignment reduction).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ._compat import pallas_tpu_compiler_params

# Test hook (mirrors ops.linalg.FORCE_INTERPRET): run the kernel through
# the Pallas interpreter on CPU so tests cover the real kernel body.
FORCE_INTERPRET = False

# rows per VMEM tile: (tile, k) f32 distance block is the big resident —
# 8 MB at tile=2048, k=1024 — plus the (k, d) f32 sums accumulator (1 MB
# at k=1024, d=256). Both double-buffered operands stay well inside the
# 100 MB budget.
_TILE = 2048


# Hardware-lowering probe results keyed by (d, k_pad, matmul_dtype); the
# policy lives in ops.linalg.probe_pallas_lowering. (n does not affect
# lowering — it only changes the grid length — so one tile suffices.)
_LOWERING_OK: dict = {}


def _probe_lowering(d: int, k: int, matmul_dtype) -> bool:
    from .linalg import probe_pallas_lowering

    key = (d, -(-k // 128) * 128, jnp.dtype(matmul_dtype).name if matmul_dtype else None)

    def compile_fn():
        # avals only — the probe may run while an outer fit is tracing,
        # so no device buffers and nothing the outer trace could capture
        x = jax.ShapeDtypeStruct((_TILE, d), jnp.float32)
        m = jax.ShapeDtypeStruct((_TILE,), jnp.float32)
        c = jax.ShapeDtypeStruct((k, d), jnp.float32)
        lloyd_step_pallas.lower(x, m, c, matmul_dtype=matmul_dtype).compile()

    return probe_pallas_lowering(_LOWERING_OK, key, compile_fn, "fused Lloyd")


def kmeans_pallas_ok(n_local: int, d: int, k: int, dtype, matmul_dtype=None) -> bool:
    """Trace-time gate: TPU, f32 input, lane-aligned d (KMeans ingestion
    pads features to 128, so the reference d=3000 shape qualifies), local
    rows divisible by the tile (the shard_rows csize invariant makes the
    padded count a 65536-multiple in practice), and a (tile, k_pad)
    distance block + (k_pad, d) accumulator that fit the VMEM budget."""
    k_pad = -(-k // 128) * 128
    # residents: double-buffered (tile, k_pad) distance/one-hot temporaries,
    # the centers INPUT and the sums OUTPUT (both (k_pad, d) f32), and
    # double-buffered (tile, d) row blocks
    vmem = (
        _TILE * k_pad * 4 * 2
        + 2 * k_pad * d * 4
        + _TILE * d * 4 * 2
    )
    ok = (
        (jax.default_backend() == "tpu" or FORCE_INTERPRET)
        and dtype == jnp.float32
        and d % 128 == 0
        and n_local % _TILE == 0
        and vmem < 90 * 1024 * 1024
    )
    if ok and not FORCE_INTERPRET:
        ok = _probe_lowering(d, k, matmul_dtype)
    return ok


@functools.partial(jax.jit, static_argnames=("matmul_dtype", "interpret"))
def lloyd_step_pallas(
    Xl: jax.Array,       # (n_local, d) f32 — padded rows carry mask 0
    ml: jax.Array,       # (n_local,) f32 row validity
    centers: jax.Array,  # (k, d) f32
    *,
    matmul_dtype=None,
    interpret: bool | None = None,
):
    """One Lloyd accumulation pass over local rows.

    Returns (sums (k, d) f32, counts (k,) int32, cost () f32) — the same
    triple as ``kmeans_kernels._chunk_stats``, before the cross-device
    psum."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = FORCE_INTERPRET
    n, d = Xl.shape
    k = centers.shape[0]
    k_pad = -(-k // 128) * 128
    if k_pad > k:
        # padded centers must never win the argmin: +inf squared norm
        centers = jnp.pad(centers, ((0, k_pad - k), (0, 0)))
        c_sq = jnp.concatenate(
            [
                (centers[:k] * centers[:k]).sum(axis=1),
                jnp.full((k_pad - k,), jnp.inf, jnp.float32),
            ]
        )
    else:
        c_sq = (centers * centers).sum(axis=1)
    cd = centers.astype(matmul_dtype) if matmul_dtype is not None else centers

    def kern(x_ref, m_ref, c_ref, csq_ref, sums_ref, counts_ref, cost_ref):
        # Everything stays 2-D (keepdims): Mosaic rejects both scalar VMEM
        # stores and 1-D full reductions ("Offset change" on
        # vector<1x2048> -> vector<1>) — both discovered only on hardware.
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            sums_ref[:] = jnp.zeros_like(sums_ref)
            counts_ref[:] = jnp.zeros_like(counts_ref)
            cost_ref[:] = jnp.zeros_like(cost_ref)

        x = x_ref[:]                       # (tile, d) f32
        # mask loads 1-D ((tile,) linear layout: a (n, 1) operand would be
        # tile-padded T(8,128) = 128x HBM expansion + a full copy) and is
        # expanded to (tile, 1) in-register for the 2-D ops below
        m = m_ref[:][:, None]              # (tile, 1) f32
        xd = x.astype(cd.dtype)
        xc = jax.lax.dot_general(
            xd, c_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                  # (tile, k_pad)
        # x_sq is row-constant: it joins for the cost only, never the argmin
        part = csq_ref[:] - 2.0 * xc       # (1, k_pad) - : broadcasts
        a = jnp.argmin(part, axis=1, keepdims=True)   # (tile, 1)
        best = jnp.min(part, axis=1, keepdims=True)   # (tile, 1)
        x_sq = (x * x).sum(axis=1, keepdims=True)     # (tile, 1)
        contrib = jnp.maximum(best + x_sq, 0.0) * m   # (tile, 1)
        cost_ref[:, :] += jnp.sum(contrib, axis=0, keepdims=True)
        onehot = (
            a == jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)
        )                                  # (tile, k_pad) bool
        counts_ref[:] += jnp.sum(
            onehot & (m > 0), axis=0, keepdims=True
        ).astype(jnp.int32)
        oh = onehot.astype(cd.dtype) * m.astype(cd.dtype)
        sums_ref[:] += jax.lax.dot_general(
            oh, xd, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                  # (k_pad, d)

    sums, counts, cost = pl.pallas_call(
        kern,
        grid=(pl.cdiv(n, _TILE),),
        in_specs=[
            pl.BlockSpec((_TILE, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )(Xl, ml, cd, c_sq.reshape(1, k_pad))
    return sums[:k], counts[0, :k], cost[0, 0]
