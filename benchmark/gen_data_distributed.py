"""Distributed-scale synthetic data generation.

The reference generates benchmark datasets *in parallel executors with
per-partition seeds* so any scale can be produced without materializing the
dataset anywhere (``/root/reference/python/benchmark/gen_data_distributed.py``,
1172 LoC, registry at :1164-1169). The analog here: a multiprocessing pool
where each worker writes one parquet file, generating it row-group by
row-group from seeds keyed by ``(seed, file_index, group_index)`` —

  * output is deterministic and INDEPENDENT of the worker count;
  * peak memory per worker is one row group (``--rows_per_group``), so a
    100M x 256 dataset (~98 GB f32) generates with a few hundred MB of RAM;
  * the files use the same schema ``DataFrame.write_parquet`` produces, so
    ``DataFrame.scan_parquet`` + the streaming fit path consume them
    directly.

CLI (mirrors the reference's ``gen_data_distributed.py`` entry):

  python -m benchmark.gen_data_distributed blobs \
      --num_rows 100000000 --num_cols 256 --output_dir /data/blobs \
      --output_num_files 50 --num_procs 8
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .gen_data import GENERATOR_PAIRS as GENERATORS

# ---------------------------------------------------------------------------
# Parallel writer
# ---------------------------------------------------------------------------

_worker_state: Dict[str, Any] = {}


def _init_worker(kind, struct, seed, rows_per_group, out_dir, dtype="float32"):
    _worker_state.update(
        kind=kind, struct=struct, seed=seed,
        rows_per_group=rows_per_group, out_dir=out_dir, dtype=dtype,
    )


def _write_file(task: Tuple[int, int]) -> str:
    """Generate and write one parquet file, one bounded row group at a
    time. RNG streams are keyed by (seed, file_index, group_index), so the
    output is independent of the WORKER COUNT — but it does depend on the
    file/row-group layout: regenerating with a different
    ``--output_num_files`` or ``--rows_per_group`` produces a different
    (same-distribution) dataset."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    file_idx, n_file_rows = task
    st = _worker_state
    chunk_fn = GENERATORS[st["kind"]][1]
    path = os.path.join(st["out_dir"], f"part-{file_idx:05d}.parquet")
    writer = None
    try:
        lo = 0
        g = 0
        while lo < n_file_rows:
            count = min(st["rows_per_group"], n_file_rows - lo)
            rng = np.random.default_rng([st["seed"], file_idx, g])
            X, y = chunk_fn(st["struct"], count, rng)
            import scipy.sparse as sp

            if sp.issparse(X):
                # densified on disk, one bounded group at a time —
                # exactly how DataFrame.write_parquet stores CSR
                X = X.toarray()
            # storage dtype: float16 halves disk AND host->device wire
            # bytes (the streaming path upcasts on device); compute stays
            # f32/f64 regardless
            X = np.asarray(X, dtype=st["dtype"])
            arrays = [
                pa.FixedSizeListArray.from_arrays(pa.array(X.ravel()), X.shape[1])
            ]
            names = ["features"]
            if y is not None:
                arrays.append(pa.array(np.asarray(y, np.float64)))
                names.append("label")
            table = pa.Table.from_arrays(arrays, names=names)
            if writer is None:
                writer = pq.ParquetWriter(path, table.schema)
            writer.write_table(table)
            lo += count
            g += 1
    finally:
        if writer is not None:
            writer.close()
    return path


def generate(
    kind: str,
    n_rows: int,
    n_cols: int,
    output_dir: str,
    *,
    num_files: int = 50,
    num_procs: Optional[int] = None,
    rows_per_group: int = 262_144,
    seed: int = 0,
    dtype: str = "float32",
    **gen_kwargs: Any,
) -> str:
    """Generate ``n_rows x n_cols`` of ``kind`` as ``num_files`` parquet
    files under ``output_dir``, in parallel, with bounded memory."""
    if kind not in GENERATORS:
        raise ValueError(f"unknown kind {kind!r}; choose from {sorted(GENERATORS)}")
    os.makedirs(output_dir, exist_ok=True)
    # a prior run's files would otherwise silently merge into the dataset
    # (readers glob every *.parquet in the directory)
    import glob as _glob

    for stale in _glob.glob(os.path.join(output_dir, "part-*.parquet")):
        os.remove(stale)
    struct = GENERATORS[kind][0](n_rows, n_cols, seed, **gen_kwargs)
    # generators with a fast narrow-dtype path read this; the writer
    # casts to it regardless, so it is a hint, not a contract
    struct["_dtype"] = dtype

    base = n_rows // num_files
    rem = n_rows % num_files
    tasks = [(i, base + (1 if i < rem else 0)) for i in range(num_files)]
    tasks = [t for t in tasks if t[1] > 0]

    init_args = (kind, struct, seed, rows_per_group, output_dir, dtype)
    num_procs = num_procs or min(len(tasks), os.cpu_count() or 1)
    if num_procs <= 1:
        _init_worker(*init_args)
        for t in tasks:
            _write_file(t)
    else:
        # spawn, not fork: the caller may be a multi-threaded JAX process
        # (forked children can inherit held allocator locks and deadlock);
        # workers only need numpy + pyarrow and all initargs pickle
        ctx = mp.get_context("spawn")
        with ctx.Pool(num_procs, initializer=_init_worker, initargs=init_args) as pool:
            for _ in pool.imap_unordered(_write_file, tasks):
                pass
    return output_dir


def main() -> None:
    parser = argparse.ArgumentParser(
        description="Generate synthetic benchmark data at scale (parallel, "
        "bounded memory)"
    )
    parser.add_argument("kind", choices=sorted(GENERATORS.keys()))
    parser.add_argument("--num_rows", type=int, default=5000)
    parser.add_argument("--num_cols", type=int, default=3000)
    parser.add_argument("--output_dir", required=True)
    parser.add_argument("--output_num_files", type=int, default=50)
    parser.add_argument("--num_procs", type=int, default=None)
    parser.add_argument("--rows_per_group", type=int, default=262_144)
    parser.add_argument("--random_seed", type=int, default=0)
    parser.add_argument(
        "--dtype", choices=["float64", "float32", "float16"], default="float32",
        help="storage dtype (float16 halves disk + ingest bytes; compute "
        "dtype is unaffected)",
    )
    args = parser.parse_args()

    generate(
        args.kind, args.num_rows, args.num_cols, args.output_dir,
        num_files=args.output_num_files, num_procs=args.num_procs,
        rows_per_group=args.rows_per_group, seed=args.random_seed,
        dtype=args.dtype,
    )
    print(
        f"wrote {args.num_rows}x{args.num_cols} {args.kind} -> "
        f"{args.output_dir} ({args.output_num_files} files)"
    )


if __name__ == "__main__":
    main()
