"""KMeans — Spark ML drop-in, TPU-native fit/transform.

Reference: ``/root/reference/python/src/spark_rapids_ml/clustering.py``
(491 LoC; cuML ``KMeansMG`` fit at :340-378, per-batch predict transform at
:458-491). Param mapping parity (reference ``clustering.py:59-82``):
``initMode→init``, ``k→n_clusters``, ``maxIter→max_iter``,
``seed→random_state``, ``tol→tol``; ``distanceMeasure`` only supports
"euclidean"; ``weightCol`` unsupported.

TPU-native fit (vs cuML's NCCL-allreduce Lloyd):
  * k-means|| seeding (Spark's default initMode): device passes compute
    min-distances and candidate weights (``ops/kmeans_kernels.py``), the
    small weighted k-means++ reduction of ~l·steps candidates runs on host;
  * Lloyd loop = ONE compiled ``lax.while_loop`` with per-device chunked
    scans and ``psum`` of (sums, counts, cost) over the dp mesh axis.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core import FitFunc, FitInputs, _TpuEstimator, _TpuModel
from ..data.dataframe import DataFrame
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
    HasTol,
    HasWeightCol,
    TypeConverters,
    _mk,
)
from ..ops.kmeans_kernels import (
    count_closest,
    kmeans_lloyd,
    min_sq_dists,
    mp_kmeans_shards,
)
from ..runtime import envspec

_CHUNK = 4096


class KMeansClass:
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "k": "n_clusters",
            "initMode": "init",
            "initSteps": "init_steps",
            "maxIter": "max_iter",
            "seed": "random_state",
            "tol": "tol",
            "distanceMeasure": "distance_measure",
            "weightCol": None,
            "solver": "",
            "maxBlockSizeInMB": "",
        }

    @classmethod
    def _param_value_mapping(cls) -> Dict[str, Callable[[Any], Any]]:
        def _check_init(v: str) -> str:
            if v not in ("k-means||", "random"):
                raise ValueError(f"Unsupported initMode: {v!r}")
            return v

        def _check_dist(v: str) -> str:
            if v != "euclidean":
                raise ValueError(
                    f"Only euclidean distance is supported, got {v!r}"
                )
            return v

        return {"init": _check_init, "distance_measure": _check_dist}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_clusters": 2,
            "init": "k-means||",
            "init_steps": 2,
            "max_iter": 20,
            "tol": 1e-4,
            "random_state": 1,
            "oversampling_factor": 2.0,
            "distance_measure": "euclidean",
            "matmul_dtype": None,
        }


class _KMeansParams(
    HasFeaturesCol, HasFeaturesCols, HasPredictionCol, HasMaxIter, HasTol, HasSeed, HasWeightCol
):
    k = _mk("k", "number of clusters", TypeConverters.toInt)
    initMode = _mk("initMode", "init algorithm: k-means|| or random", TypeConverters.toString)
    initSteps = _mk("initSteps", "k-means|| init steps", TypeConverters.toInt)
    distanceMeasure = _mk("distanceMeasure", "distance measure", TypeConverters.toString)
    # accepted-but-ignored Spark >= 3.4 params (""-mapped)
    solver = _mk("solver", "optimization solver (ignored)", TypeConverters.toString)
    maxBlockSizeInMB = _mk(
        "maxBlockSizeInMB", "block size hint (ignored)", TypeConverters.toFloat
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            k=2, initMode="k-means||", initSteps=2, maxIter=20, tol=1e-4,
            distanceMeasure="euclidean",
        )

    def getK(self) -> int:
        return self.getOrDefault("k")

    def getInitMode(self) -> str:
        return self.getOrDefault("initMode")


class KMeans(KMeansClass, _TpuEstimator, _KMeansParams):
    """``KMeans(k=1000, maxIter=30).fit(df)`` — drop-in for
    ``pyspark.ml.clustering.KMeans``."""

    def __init__(self, **kwargs: Any) -> None:
        _TpuEstimator.__init__(self)
        _KMeansParams.__init__(self)
        self._set_params(**kwargs)

    def setK(self, value: int) -> "KMeans":
        self._set_params(k=value)
        return self

    def setMaxIter(self, value: int) -> "KMeans":
        self._set_params(maxIter=value)
        return self

    def setTol(self, value: float) -> "KMeans":
        self._set_params(tol=value)
        return self

    def setSeed(self, value: int) -> "KMeans":
        self._set_params(seed=value)
        return self

    def setInitMode(self, value: str) -> "KMeans":
        self._set_params(initMode=value)
        return self

    def _chunk_rows(self, n_rows: int, n_dp: int) -> int:
        return self._equal_chunk_rows(n_rows, n_dp, _CHUNK)

    @staticmethod
    def _resolve_matmul_dtype(params):
        """Validated (early, before any seeding work) bf16-matmul option;
        returns a jnp dtype or None. Kwarg beats TPUML_KMEANS_MATMUL_DTYPE."""
        # registry read: empty-string env (a shell-default pattern) means
        # unset, and a malformed env value names the variable in the error
        mm = (
            params.get("matmul_dtype")
            or envspec.get("TPUML_KMEANS_MATMUL_DTYPE")
            or None
        )
        if mm is not None and str(mm) not in ("float32", "bfloat16"):
            raise ValueError(
                f"matmul_dtype must be float32|bfloat16, got {mm!r}"
            )
        return jnp.bfloat16 if str(mm) == "bfloat16" else None

    def _feature_pad_multiple(self) -> int:
        """Lloyd's ``while_loop`` triggers a defensive full copy of X at
        lane-unaligned d (~2x matrix HBM at exactly the reference's d=3000
        shape); zero columns are invariant under Lloyd updates (zero-seeded
        centers stay zero, distances/costs unchanged) and TPU tiles the
        minor dim to 128 physically anyway, so the padding is HBM-free.
        ``TPUML_LANE_PAD`` overrides (CI exercises the path on CPU)."""
        env = envspec.get("TPUML_LANE_PAD")
        if env is not None:
            return int(env)
        import jax

        return 128 if jax.default_backend() == "tpu" else 0

    # ---- seeding ---------------------------------------------------------
    # ONE sampling implementation serves both the resident and streaming
    # fits, parameterized over a slice "owner" — each rank owns the global
    # logical rows [offset, offset+n_local) and keeps only O(n_local) host
    # state. The rng consumption sequence is part of the contract (same
    # seed => identical seeding on every path and every rank), so the
    # logic must not fork: uniform draws happen in rank-lockstep segments
    # (segmented draws of one generator consume the identical stream as a
    # single full-range draw).
    #
    # owner keys:
    #   offset, n_local — this rank's slice of [0, n_rows)
    #   gather_local(sorted_local_idx) -> rows of MY slice (host)
    #   assemble(my_rows) -> all ranks' rows, rank-order (identity when
    #                        the owner spans the full range)
    #   min_d2_vs(cands) -> (n_local,) min sq dist of my slice to cands
    #   reduce_sum(x) -> world sum (identity single-owner)
    #   count_closest(cands) -> world closest-row counts per candidate

    @staticmethod
    def _rng_slice(
        rng: np.random.Generator, n_rows: int, offset: int, n_local: int
    ) -> np.ndarray:
        """Lockstep uniforms for [0, n_rows), keeping only this rank's
        slice."""
        if offset:
            rng.random(offset)
        r = rng.random(n_local)
        rest = n_rows - offset - n_local
        if rest:
            rng.random(rest)
        return r

    @staticmethod
    def _gather_global(owner: Dict[str, Any], idx: np.ndarray) -> np.ndarray:
        """Rows for sorted GLOBAL indices: each rank serves its own hits;
        rank-order assembly reproduces the sorted order."""
        idx = np.sort(np.asarray(idx, np.int64))
        off, nl = owner["offset"], owner["n_local"]
        mine = idx[(idx >= off) & (idx < off + nl)] - off
        return owner["assemble"](owner["gather_local"](mine))

    @staticmethod
    def _seed_random(
        n_rows: int, k: int, rng: np.random.Generator, owner: Dict[str, Any]
    ) -> np.ndarray:
        idx = rng.choice(n_rows, size=k, replace=n_rows < k)
        return KMeans._gather_global(owner, idx)

    @staticmethod
    def _seed_scalable_kmeanspp(
        n_rows: int,
        k: int,
        steps: int,
        oversample: float,
        rng: np.random.Generator,
        owner: Dict[str, Any],
    ) -> np.ndarray:
        """k-means|| (Bahmani et al.): sample ~l=oversample*k candidates per
        round with prob l*d²/Σd², then reduce candidates to k centers with
        weighted k-means++ on host (the candidate set is tiny)."""
        l = max(int(oversample * k), 1)
        off, nl = owner["offset"], owner["n_local"]
        first = int(rng.integers(0, n_rows))
        cands = KMeans._gather_global(owner, np.asarray([first]))
        local_d2 = np.asarray(owner["min_d2_vs"](cands), np.float64)
        for _ in range(steps):
            total = float(owner["reduce_sum"](float(local_d2.sum())))
            if total <= 0:
                break
            r = KMeans._rng_slice(rng, n_rows, off, nl)
            sel = np.nonzero(r < np.minimum(l * local_d2 / total, 1.0))[0]
            new = owner["assemble"](owner["gather_local"](sel))
            if len(new) == 0:
                continue
            cands = np.concatenate([cands, new], axis=0)
            local_d2 = np.minimum(
                local_d2, np.asarray(owner["min_d2_vs"](new), np.float64)
            )
        if len(cands) < k:
            # not enough candidates — top up with random rows
            extra = KMeans._seed_random(n_rows, k - len(cands), rng, owner)
            return np.concatenate([cands, extra], axis=0)
        if len(cands) == k:
            return cands
        weights = np.asarray(owner["count_closest"](cands), np.float64)
        return _weighted_kmeanspp(cands.astype(np.float64), weights, k, rng)

    def _resident_owner(self, inputs: FitInputs) -> Dict[str, Any]:
        """Full-range owner: every rank computes identical samples; the
        device gathers are collective-safe because all ranks issue them
        with identical arguments."""
        from ..parallel.mesh import fetch_global, gather_rows_global

        # seeding addresses "logical valid rows 0..n_rows"; padded-array
        # positions of those rows come from the mask (padding is at the
        # end single-process but interleaved per-process block multi-host)
        valid_pos = np.nonzero(fetch_global(inputs.mask, inputs.mesh) > 0)[0]

        def gather_local(idx: np.ndarray) -> np.ndarray:
            if len(idx) == 0:
                d = inputs.n_features_padded or inputs.n_features
                return np.empty((0, d), np.float32)
            return gather_rows_global(inputs.X, valid_pos[idx], inputs.mesh)

        def min_d2_vs(cands: np.ndarray) -> np.ndarray:
            return np.asarray(
                fetch_global(
                    min_sq_dists(
                        inputs.X, inputs.mask, jnp.asarray(cands, inputs.dtype),
                        mesh=inputs.mesh, csize=inputs.csize,
                    ),
                    inputs.mesh,
                ),
                np.float64,
            )[valid_pos]

        def count_closest_fn(cands: np.ndarray) -> np.ndarray:
            return fetch_global(
                count_closest(
                    inputs.X, inputs.mask, jnp.asarray(cands, inputs.dtype),
                    mesh=inputs.mesh, csize=inputs.csize,
                ),
                inputs.mesh,
            )

        return {
            "offset": 0,
            "n_local": inputs.n_rows,
            "gather_local": gather_local,
            "assemble": lambda rows: rows,
            "min_d2_vs": min_d2_vs,
            "reduce_sum": lambda x: x,
            "count_closest": count_closest_fn,
        }

    def _init_random(self, inputs: FitInputs, k: int, rng: np.random.Generator) -> np.ndarray:
        return self._seed_random(inputs.n_rows, k, rng, self._resident_owner(inputs))

    def _init_scalable_kmeanspp(
        self,
        inputs: FitInputs,
        k: int,
        steps: int,
        oversample: float,
        rng: np.random.Generator,
    ) -> np.ndarray:
        return self._seed_scalable_kmeanspp(
            inputs.n_rows, k, steps, oversample, rng,
            self._resident_owner(inputs),
        )

    # ---- fit -------------------------------------------------------------
    def _get_tpu_fit_func(self, dataset: DataFrame) -> FitFunc:
        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            k = int(params["n_clusters"])
            if k > inputs.n_rows:
                raise ValueError(f"k={k} must be <= number of rows {inputs.n_rows}")
            mm = self._resolve_matmul_dtype(params)
            rng = np.random.default_rng(int(params.get("random_state") or 0))
            if params.get("init") == "random":
                centers0 = self._init_random(inputs, k, rng)
            else:
                centers0 = self._init_scalable_kmeanspp(
                    inputs, k, int(params.get("init_steps", 2)),
                    float(params.get("oversampling_factor", 2.0)), rng,
                )
            centers0 = jnp.asarray(centers0, dtype=inputs.dtype)
            centers, cost, n_iter = kmeans_lloyd(
                inputs.X,
                inputs.mask,
                centers0,
                mesh=inputs.mesh,
                csize=inputs.csize,
                max_iter=int(params["max_iter"]),
                tol=float(params["tol"]),
                # bf16 matmul operands / f32 accumulation on the two MXU
                # contractions (~2x); final cost pass stays f32
                matmul_dtype=mm,
            )
            # strip lane-padding columns (zero by the Lloyd invariant)
            result = {
                "cluster_centers": np.asarray(centers)[:, : inputs.n_features],
                "training_cost": float(cost),
                "n_iter": int(n_iter),
            }
            mp = mp_kmeans_shards(inputs.mesh, k)
            if mp > 1:
                kb = -(-k // mp)
                result["_fit_report"] = {
                    "mp_degree": mp,
                    "centroid_shard_bytes": int(
                        kb
                        * inputs.n_features_padded
                        * jnp.dtype(inputs.dtype).itemsize
                    ),
                }
            return result

        return _fit

    def _get_tpu_streaming_fit_func(self, dataset: DataFrame):
        """Out-of-core fit: seeding and Lloyd each run as chunked passes —
        device memory holds one chunk slab plus k×d centroid state; the only
        O(n) host state is the 8-byte/row min-distance array k-means||
        keeps (the dataset itself never materializes)."""
        from ..core import StreamInputs
        from ..ops.streaming import (
            streamed_count_closest,
            streamed_kmeans_lloyd,
            streamed_min_sq_dists_update,
            streamed_rows_at,
        )

        def _stream_owner(inputs: StreamInputs) -> Dict[str, Any]:
            """Slice owner: each rank owns its partition's rows in the
            process-major global order and keeps only O(local) host state."""
            import jax as _jax

            from ..parallel.mesh import (
                allgather_host,
                allgather_ragged_rows,
                allreduce_sum_host,
            )

            nproc = _jax.process_count()
            offset = 0
            if nproc > 1:
                counts = allgather_host(
                    np.asarray([inputs.source.n_rows])
                ).ravel().astype(np.int64)
                offset = int(counts[: _jax.process_index()].sum())

            def gather_local(idx: np.ndarray) -> np.ndarray:
                return streamed_rows_at(
                    inputs.source, inputs.chunk_rows, idx, inputs.dtype
                )

            def min_d2_vs(cands: np.ndarray) -> np.ndarray:
                return streamed_min_sq_dists_update(
                    inputs.source, inputs.mesh, inputs.chunk_rows, inputs.dtype,
                    cands, None,
                )

            def count_closest_fn(cands: np.ndarray) -> np.ndarray:
                local = streamed_count_closest(
                    inputs.source, inputs.mesh, inputs.chunk_rows, inputs.dtype,
                    cands,
                )
                (total,) = allreduce_sum_host(local)
                return total

            return {
                "offset": offset,
                "n_local": int(inputs.source.n_rows),
                "gather_local": gather_local,
                "assemble": (
                    allgather_ragged_rows if nproc > 1 else (lambda rows: rows)
                ),
                "min_d2_vs": min_d2_vs,
                "reduce_sum": (
                    (lambda x: float(allreduce_sum_host(np.asarray([x]))[0][0]))
                    if nproc > 1
                    else (lambda x: x)
                ),
                "count_closest": count_closest_fn,
            }

        def _fit(inputs: StreamInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            k = int(params["n_clusters"])
            if k > inputs.n_rows:
                raise ValueError(f"k={k} must be <= number of rows {inputs.n_rows}")
            mm = self._resolve_matmul_dtype(params)  # validate before seeding
            rng = np.random.default_rng(int(params.get("random_state") or 0))
            owner = _stream_owner(inputs)
            if params.get("init") == "random":
                centers0 = self._seed_random(inputs.n_rows, k, rng, owner)
            else:
                centers0 = self._seed_scalable_kmeanspp(
                    inputs.n_rows, k, int(params.get("init_steps", 2)),
                    float(params.get("oversampling_factor", 2.0)), rng, owner,
                )
            # checkpoint identity: seeding is deterministic (seeded rng +
            # chunked passes), so refit regenerates the same centers0 and
            # its digest proves the Lloyd walk being resumed is this one
            from ..runtime.checkpoint import FitCheckpointer, array_digest

            ckpt = FitCheckpointer.from_env(
                "kmeans",
                {
                    "k": k,
                    "d": int(inputs.source.n_features),
                    "n_rows": int(inputs.n_rows),
                    "max_iter": int(params["max_iter"]),
                    "tol": float(params["tol"]),
                    "seed": int(params.get("random_state") or 0),
                    "init": str(params.get("init")),
                    "matmul_dtype": str(mm),
                    "centers0": array_digest(centers0),
                },
            )
            centers, cost, n_iter = streamed_kmeans_lloyd(
                inputs.source,
                inputs.mesh,
                inputs.chunk_rows,
                inputs.dtype,
                np.asarray(centers0),
                max_iter=int(params["max_iter"]),
                tol=float(params["tol"]),
                matmul_dtype=mm,
                checkpointer=ckpt if ckpt.enabled else None,
            )
            return {
                "cluster_centers": np.asarray(centers),
                "training_cost": float(cost),
                "n_iter": int(n_iter),
            }

        return _fit

    def _create_model(self, result: Dict[str, Any]) -> "KMeansModel":
        return KMeansModel(**result)


class KMeansModel(KMeansClass, _TpuModel, _KMeansParams):
    def __init__(self, **attrs: Any) -> None:
        _TpuModel.__init__(self, **attrs)
        _KMeansParams.__init__(self)

    @property
    def cluster_centers_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["cluster_centers"])

    def clusterCenters(self) -> List[np.ndarray]:
        return list(self.cluster_centers_)

    @property
    def trainingCost(self) -> float:
        """Sum of squared distances to closest center (Spark
        ``summary.trainingCost`` analog)."""
        return float(self._model_attributes["training_cost"])

    @property
    def numIter(self) -> int:
        return int(self._model_attributes["n_iter"])

    def predict(self, vector: Any) -> int:
        """Single-vector predict (the reference falls back to the CPU model,
        ``clustering.py:445-449``; here the same kernel serves both).
        The jitted assigner is cached — rebuilding it per call would retrace."""
        pred_col = self.getOrDefault("predictionCol")
        if getattr(self, "_predict_fn_col", None) != pred_col:
            self._predict_fn = self._get_tpu_transform_func()
            self._predict_fn_col = pred_col
        out = self._predict_fn(np.asarray(vector, dtype=np.float32).reshape(1, -1))
        return int(out[pred_col][0])

    def _get_tpu_transform_func(
        self, dataset: Optional[DataFrame] = None
    ) -> Callable[[np.ndarray], Dict[str, np.ndarray]]:
        from ..ops.kmeans_kernels import pairwise_sq_dists

        pred_col = self.getOrDefault("predictionCol")
        centers_np = self.cluster_centers_

        @jax.jit
        def _assign(Xb: jax.Array) -> jax.Array:
            centers = jnp.asarray(centers_np, dtype=Xb.dtype)
            d2 = pairwise_sq_dists(Xb, centers)
            return jnp.argmin(d2, axis=1).astype(jnp.int32)

        def _fn(Xb: np.ndarray) -> Dict[str, np.ndarray]:
            return {pred_col: np.asarray(_assign(jnp.asarray(Xb)))}

        return _fn


def _weighted_kmeanspp(
    cands: np.ndarray, weights: np.ndarray, k: int, rng: np.random.Generator
) -> np.ndarray:
    """Weighted k-means++ over the (small) k-means|| candidate set."""
    m = len(cands)
    w = np.maximum(weights, 1e-12)
    centers = np.empty((k, cands.shape[1]), dtype=cands.dtype)
    first = rng.choice(m, p=w / w.sum())
    centers[0] = cands[first]
    min_d2 = ((cands - centers[0]) ** 2).sum(axis=1)
    for i in range(1, k):
        p = w * min_d2
        tot = p.sum()
        if tot <= 0:
            # all remaining candidates coincide with chosen centers
            centers[i:] = cands[rng.choice(m, size=k - i)]
            break
        centers[i] = cands[rng.choice(m, p=p / tot)]
        d2 = ((cands - centers[i]) ** 2).sum(axis=1)
        min_d2 = np.minimum(min_d2, d2)
    return centers
