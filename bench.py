"""Benchmark entry point — prints ONE JSON line with the headline metric.

Covers the three BASELINE.md fit workloads (PCA, KMeans, LogisticRegression;
reference methodology ``/root/reference/python/benchmark/databricks/run_benchmark.sh:44-135``)
at the 256-feature width of the 100M x 256 north-star, measuring per-chip fit
throughput so the number scales linearly to pod size.  Also reports an MFU
estimate per algorithm (FLOP model / chip peak).

``vs_baseline`` compares against an A10G cuML roofline estimate derived from
the reference's benchmark hardware (BASELINE.md: 2x g5.2xlarge, A10G 24 GB):

* PCA — Gram-bound, 2*n*d^2 FLOPs; A10G sustains ~15 TFLOP/s effective fp32
  on SYRK-shaped work -> 15e12 / (2*256^2) ~= 1.1e8 samples/sec/GPU.
* KMeans — distance-bound, 2*n*k*d FLOPs/iter (k=1024) ->
  15e12 / (2*1024*256) ~= 2.9e7 sample-iters/sec/GPU.
* LogReg — bandwidth-bound (matvec-shaped): ~2 passes over X per L-BFGS
  iter at 600 GB/s A10G HBM -> 600e9 / (2*256*4) ~= 2.9e8
  sample-iters/sec/GPU.

Measurement methodology (this environment reaches the chip through a remote
tunnel with a ~65 ms per-dispatch round trip and ~30 MB/s host->device
bandwidth — both properties of the tunnel, not the chip):

* data is generated ON DEVICE with ``jax.random`` (a host-side 4 GB matrix
  would take minutes just to ship through the tunnel);
* every timed rep is exactly ONE jitted call returning ONE small array (a
  scalar checksum over all output leaves + an aux counter), so per-rep
  overhead is one round trip instead of one per output leaf;
* per-rep input perturbations are materialized BEFORE the clock starts —
  identical (executable, buffers) pairs may be memoized by a remote backend,
  which would report physically impossible times (observed round 1);
* the streaming (out-of-core) number necessarily measures host->device
  ingest, i.e. the tunnel, so it is reported but EXCLUDED from the geomean
  and flagged ``tunnel_bound``.

Headline metric stays ``pca_fit_throughput`` (round-1 continuity); the same
JSON line carries ``kmeans``/``logreg``/``pca_stream`` sub-objects and
per-algo MFU.

Robustness (round-1 postmortem): any algo failing with a transient
``UNAVAILABLE`` TPU backend error is retried once after a cooldown; partial
results still produce a JSON line; diagnostics go to stderr.
"""

import contextlib
import json
import math
import os
import sys
import time
import traceback

import numpy as np

# Honor an env/CLI platform pin in-process (sitecustomize TPU hooks ignore
# plain env vars) BEFORE the first backend touch.
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from spark_rapids_ml_tpu.utils.platform import pin_platform  # noqa: E402

_platform = None
for _i, _a in enumerate(sys.argv[1:], start=1):
    if _a == "--platform":
        if _i + 1 >= len(sys.argv):
            sys.exit("--platform requires a value (cpu|tpu)")
        _platform = sys.argv[_i + 1]
    elif _a.startswith("--platform="):
        _platform = _a.split("=", 1)[1]
pin_platform(_platform)

N_ROWS = int(os.environ.get("BENCH_ROWS", 12_000_000))
N_COLS = int(os.environ.get("BENCH_COLS", 256))
KMEANS_K = int(os.environ.get("BENCH_KMEANS_K", 1024))
KMEANS_ITERS = 10
LOGREG_ITERS = 20


def _csize(n_rows: int) -> int:
    # 64k rows/chunk keeps the (chunk, k) distance + one-hot tiles ~0.5 GB
    # so a ~12 GB resident X still fits v5e HBM; tiles this tall keep the
    # MXU contraction saturated
    return min(65_536, max(256, n_rows // 8))


CSIZE = _csize(N_ROWS)

# bf16 peak FLOP/s per chip by device kind (MFU denominator).
_PEAK_BY_KIND = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]
_CPU_PEAK = 1e12  # nominal, keeps MFU finite on the CPU fallback


def _chip_peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, peak in _PEAK_BY_KIND:
        if key in kind:
            return peak
    return _CPU_PEAK


def _checksum(out, aux=None):
    """Reduce an output pytree to ONE tiny array (inside jit).

    Summing every leaf forces the whole computation; returning a single
    2-vector makes the host fetch a single round trip (the tunnel charges
    ~65 ms per fetched leaf otherwise).
    """
    import jax
    import jax.numpy as jnp

    acc = jnp.float32(0.0)
    for leaf in jax.tree_util.tree_leaves(out):
        acc = acc + jnp.sum(jnp.asarray(leaf).astype(jnp.float32))
    return jnp.stack([acc, jnp.float32(0.0 if aux is None else aux)])


def _best_time(make_args, run, reps: int = 3):
    """(min wall time, aux from first rep) of ``run(*make_args(rep))``.

    Per-rep argument sets are materialized and blocked on BEFORE timing so
    the clock sees exactly one dispatch + one 2-scalar fetch per rep.
    """
    import jax

    argsets = [make_args(rep) for rep in range(reps)]
    for a in argsets:
        jax.block_until_ready(a)
    times, aux = [], 0.0
    for i, a in enumerate(argsets):
        t0 = time.perf_counter()
        out = np.asarray(run(*a))
        times.append(time.perf_counter() - t0)
        if i == 0:
            aux = float(out[1])
    return min(times), aux


INNER_FITS = max(1, int(os.environ.get("BENCH_INNER_FITS", 4)))


def _gen_dataset(mesh, n_rows, seed, dtype=None):
    """On-device chunked dataset generation -> (X, mask, y), row-sharded.

    Chunked because random.normal over the full matrix would hold the
    uint32 bit buffer AND the f32 output at once (2x matrix bytes — OOM
    for a ~12 GB X on a 16 GiB chip). Chunks land in a preallocated
    buffer via dynamic_update_slice (aliased in-place by XLA) — NOT a
    lax.scan stacked output, whose exotic layout forces downstream
    shard_map kernels to materialize a default-layout copy of the whole
    matrix (observed OOM at d=3000). ``dtype`` narrows the stored X
    (generation stays f32); labels come from a fixed seed-0 true weight
    vector so every caller labels consistently.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    x_dtype = jnp.float32 if dtype is None else dtype
    n_dp = mesh.shape["dp"]
    pad_unit = CSIZE * n_dp
    n_pad = ((n_rows + pad_unit - 1) // pad_unit) * pad_unit
    row_sharding = NamedSharding(mesh, P("dp"))
    w_true = jnp.asarray(
        np.random.default_rng(0).standard_normal(N_COLS, dtype=np.float32)
    )

    def _gen(key, w):
        def body(i, Xg):
            blk = jax.random.normal(
                jax.random.fold_in(key, i), (pad_unit, N_COLS), jnp.float32
            )
            return lax.dynamic_update_slice_in_dim(
                Xg, blk.astype(x_dtype), i * pad_unit, 0
            )

        Xg = lax.fori_loop(
            0, n_pad // pad_unit, body, jnp.zeros((n_pad, N_COLS), x_dtype)
        )
        m = (jnp.arange(n_pad) < n_rows).astype(jnp.float32)
        yg = (
            lax.dot_general(
                Xg, w.astype(x_dtype)[:, None],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )[:, 0]
            > 0
        ).astype(jnp.float32) * m
        return Xg, m, yg

    gen = jax.jit(
        _gen, out_shardings=(row_sharding, row_sharding, row_sharding)
    )
    X, m, y = gen(jax.random.key(seed), w_true)
    jax.block_until_ready(X)
    return X, m, y


def _time_scanned_fits(fit_body, args_for_rep):
    """Best per-fit time of INNER_FITS fits inside ONE dispatch.

    A single fit is ~20-50 ms on chip while the tunnel charges ~65 ms per
    dispatch — one fit per dispatch under-reports the chip several-fold.
    ``fit_body(eps, *args) -> checksum`` runs per inner fit; the eps scan
    perturbs each fit's inputs so XLA cannot CSE them into one."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def inner(*args):
        def body(acc, eps):
            return acc + fit_body(eps, *args), None

        acc, _ = lax.scan(
            body,
            jnp.zeros((2,), jnp.float32),
            jnp.arange(1, INNER_FITS + 1, dtype=jnp.float32) * 1e-7,
        )
        return acc

    timed = jax.jit(inner)
    np.asarray(timed(*args_for_rep(0)))  # compile (distinct rep-0 inputs
    # would be memoizable on remote backends; _best_time starts at rep 1)
    t, _ = _best_time(lambda rep: args_for_rep(rep + 1), timed)
    return t / INNER_FITS


def bench_pca(X, mask, mesh, n_chips):
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.models.feature import _pca_fit_kernel

    def fit_body(eps, X, m):
        return _checksum(
            _pca_fit_kernel(X, m * (1.0 + eps), 3, mesh=mesh, csize=CSIZE)
        )

    t = _time_scanned_fits(
        fit_body,
        lambda rep: (X, mask * jnp.float32(1.0 + rep * 1e-6)),
    )
    # transform path (reference reports fit AND transform per workload,
    # ``benchmark/base.py:241-270``): one centered projection sweep at
    # k=3 — the exact compute of PCAModel.transform
    W = jnp.asarray(
        np.random.default_rng(5).standard_normal((3, N_COLS)), jnp.float32
    )
    mu = jnp.asarray(
        np.random.default_rng(6).standard_normal(N_COLS), jnp.float32
    )

    def tr_body(eps, X, m):
        return _checksum((X - mu[None, :] * (1.0 + eps)) @ W.T)

    t_tr = _time_scanned_fits(
        tr_body, lambda rep: (X, mask * jnp.float32(1.0 + rep * 1e-6))
    )
    n = N_ROWS
    flops = 2.0 * n * N_COLS * N_COLS  # Gram dominates
    return {
        "samples_per_sec_per_chip": n / t / n_chips,
        "fit_seconds": t,
        "transform_seconds": t_tr,
        "transform_samples_per_sec_per_chip": n / t_tr / n_chips,
        "inner_fits_per_dispatch": INNER_FITS,
        "flops_model": flops,
        "baseline_samples_per_sec": 1.1e8,
        "baseline_inputs": {
            "formula": "a10g_syrk_flat_v1",
            "samples_per_sec": 1.1e8,
            "d": N_COLS,
        },
    }


def bench_kmeans(X, mask, mesh, n_chips):
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.kmeans_kernels import kmeans_lloyd

    key = jax.random.key(1)
    centers0 = jax.random.normal(key, (KMEANS_K, N_COLS), dtype=jnp.float32)
    jax.block_until_ready(centers0)
    csize = CSIZE
    # bf16 matmul operands (f32 accumulation) on the two MXU contractions
    # — the TF32-tensor-core analog; see pairwise_sq_dists
    km_dtype = os.environ.get("BENCH_KMEANS_DTYPE", "bfloat16")
    if km_dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"BENCH_KMEANS_DTYPE must be float32|bfloat16, got {km_dtype!r}"
        )
    mm = jnp.bfloat16 if km_dtype == "bfloat16" else None

    def timed_fn(X, m, c):
        out = kmeans_lloyd(
            X, m, c, mesh=mesh, csize=csize, max_iter=KMEANS_ITERS, tol=0.0,
            matmul_dtype=mm,
        )
        return _checksum(out, aux=out[2])

    timed = jax.jit(timed_fn)
    warm = np.asarray(timed(X, mask, centers0))  # compile + iteration count
    iters = int(warm[1]) + 1  # +1 final cost pass
    # rep-dependent center jitter -> distinct input buffers (see _best_time)
    t, _ = _best_time(
        lambda rep: (X, mask, centers0 + jnp.float32((rep + 1) * 1e-6)),
        timed,
    )
    # transform path: one chunked assignment pass (argmin over pairwise
    # distances) — the exact compute of KMeansModel.transform
    from spark_rapids_ml_tpu.ops.kmeans_kernels import pairwise_sq_dists

    def tr_body(eps, X, m, c):
        nchunks = X.shape[0] // csize

        def chunk(i, acc):
            xc = jax.lax.dynamic_slice(X, (i * csize, 0), (csize, N_COLS))
            d2 = pairwise_sq_dists(xc, c * (1.0 + eps), matmul_dtype=mm)
            return acc + jnp.argmin(d2, axis=1).astype(jnp.float32).sum()

        return jnp.stack(
            [jax.lax.fori_loop(0, nchunks, chunk, jnp.float32(0.0)),
             jnp.float32(0.0)]
        )

    t_tr = _time_scanned_fits(
        tr_body, lambda rep: (X, mask, centers0 + jnp.float32(rep * 1e-6))
    )
    # FLOPs are spent on padded rows; throughput counts real samples only
    flops = 2.0 * X.shape[0] * KMEANS_K * N_COLS * iters
    n = N_ROWS
    return {
        "samples_per_sec_per_chip": n * iters / t / n_chips,
        "fit_seconds": t,
        "transform_seconds": t_tr,
        "transform_samples_per_sec_per_chip": n / t_tr / n_chips,
        "iters": iters,
        "matmul_dtype": km_dtype,
        "flops_model": flops,
        "baseline_samples_per_sec": 2.9e7,
        "baseline_inputs": {
            "formula": "a10g_kmeans_flat_v1",
            "samples_per_sec": 2.9e7,
            "k": KMEANS_K,
            "d": N_COLS,
        },
    }


def bench_logreg(X, mask, y, mesh, n_chips):
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.logreg_kernels import logreg_fit

    # bf16 objective reads (f32 stats/params/accumulation): halves the
    # HBM bytes of the bandwidth-bound eval — the TPU analog of the TF32
    # tensor-core reads cuML gets implicitly on Ampere-class GPUs
    # default float32: the bf16 objective needs a SEPARATE bf16-placed
    # dataset, and any extra resident next to the shared 12M x 256 f32 X
    # costs more in HBM-pressure slowdown than the halved reads buy
    # (measured: bf16 474M samples/s standalone vs 252M beside the f32 X,
    # f32 itself dropping 455->261M when a 3 GB bf16 sibling stays live).
    # The bf16 path earns its keep in the estimator, where X arrives
    # bf16-placed at ingestion (objective_dtype="bfloat16") and is the
    # ONLY resident.
    obj_dtype = os.environ.get("BENCH_LOGREG_DTYPE", "float32")

    n_rows = N_ROWS
    Xb, mb, yb = X, mask, y
    if obj_dtype == "bfloat16":
        # the fit must SEE a bf16 X: converting the shared f32 X inside the
        # program holds both copies live (observed 17.3 GB > 15.75 GB at
        # 12M x 256 on v5e). Generate a separate bf16 dataset instead —
        # at half the rows so it fits NEXT TO the f32 X the other entries
        # still need. The eval is bandwidth-bound, so samples/sec is
        # row-count-insensitive at these sizes; "rows" is recorded.
        n_rows = int(os.environ.get("BENCH_LOGREG_BF16_ROWS", N_ROWS // 2))
        try:
            Xb, mb, yb = _gen_dataset(mesh, n_rows, seed=7, dtype=jnp.bfloat16)
        except Exception as e:  # noqa: BLE001
            # the extra bf16 dataset may not fit next to the resident f32
            # X; deliver the f32 number rather than no logreg entry at all
            print(
                f"[bench] logreg bf16 dataset generation failed "
                f"({type(e).__name__}: {e}); falling back to float32",
                file=sys.stderr,
            )
            obj_dtype = "float32"
            n_rows = N_ROWS

    def make_timed(dt):
        def timed_fn(X, m, y, l2):
            out = logreg_fit(
                X, m, y,
                n_classes=2, multinomial=False, fit_intercept=True,
                standardization=False,
                l1=jnp.float32(0.0), l2=l2,
                use_l1=False, max_iter=LOGREG_ITERS, tol=jnp.float32(0.0),
                mesh=mesh, objective_dtype=dt,
            )
            return _checksum(out, aux=out["n_iter"])

        return jax.jit(timed_fn)

    timed = make_timed(obj_dtype)
    try:
        warm = np.asarray(timed(Xb, mb, yb, jnp.float32(1e-5)))  # compile
    except Exception as e:  # noqa: BLE001
        if obj_dtype == "float32":
            raise
        # narrow-dtype path failed on this backend (e.g. Mosaic lowering):
        # fall back to f32, record the dtype that actually ran, and keep
        # the original error visible for diagnosis
        print(
            f"[bench] logreg {obj_dtype} objective failed "
            f"({type(e).__name__}: {e}); falling back to float32",
            file=sys.stderr,
        )
        obj_dtype = "float32"
        n_rows = N_ROWS
        Xb, mb, yb = X, mask, y
        timed = make_timed(obj_dtype)
        warm = np.asarray(timed(Xb, mb, yb, jnp.float32(1e-5)))
    iters = max(int(warm[1]), 1)
    # rep-dependent l2 -> distinct scalar input buffer (see _best_time)
    t, _ = _best_time(
        lambda rep: (
            Xb, mb, yb, jnp.float32(1e-5 * (1.0 + (rep + 1) * 1e-3))
        ),
        timed,
    )
    # transform path: one decision sweep (X @ w > 0) — the compute of
    # LogisticRegressionModel.transform's prediction column
    w_t = jnp.asarray(
        np.random.default_rng(9).standard_normal(N_COLS), jnp.float32
    )

    def tr_body(eps, X, m, y):
        z = X @ (w_t * (1.0 + eps))
        return _checksum((z > 0).astype(jnp.float32) * m)

    t_tr = _time_scanned_fits(
        tr_body,
        lambda rep: (Xb, mb * jnp.float32(1.0 + rep * 1e-6), yb),
    )
    # ~2 objective evals/iter (step + line search), fwd+grad = 4*n*d each
    flops = 8.0 * n_rows * N_COLS * iters
    return {
        # throughput is PER ITERATION (samples x iters / s): the
        # reference benchmark runs maxIter=200 tol=1e-30
        # (run_benchmark.sh:126-135) while this leg runs 20 iterations —
        # per-iter normalization makes the numbers comparable, and
        # per_iter=true in the JSON says so explicitly
        "samples_per_sec_per_chip": n_rows * iters / t / n_chips,
        # end-to-end (un-normalized) rate alongside, so a consumer that
        # ignores per_iter cannot misread the 20x-inflated headline as
        # comparable with the other entries' end-to-end definition; the
        # vs_baseline ratio is consistent either way because the 2.9e8
        # baseline below is ALSO a per-iteration rate
        "samples_per_sec_per_chip_e2e": n_rows / t / n_chips,
        "fit_seconds": t,
        "transform_seconds": t_tr,
        "transform_samples_per_sec_per_chip": n_rows / t_tr / n_chips,
        "iters": iters,
        "per_iter": True,
        "rows": n_rows,
        "objective_dtype": obj_dtype,
        "gang_lanes": 1,
        "flops_model": flops,
        "baseline_samples_per_sec": 2.9e8,
        "baseline_inputs": {
            "formula": "a10g_logreg_flat_per_iter_v1",
            "samples_per_sec_per_iter": 2.9e8,
            "d": N_COLS,
        },
    }


LOGREG_MULTI_FOLDS = 3
LOGREG_MULTI_MAPS = 8


def bench_logreg_multi(X, mask, y, mesh, n_chips):
    """Gang-scheduled CV-shaped grid: numFolds=3 × 8 maps = 24 fold-masked
    L-BFGS lanes through ONE ``logreg_fit_batched`` dispatch over the
    shared resident X, against the same 24 solves run sequentially (solo
    ``logreg_fit`` with the fold mask folded into the row mask — exactly
    what the unganged CrossValidator dispatches). The gang leg reads X
    once per iteration for all 24 lanes; ``vs_sequential`` is the measured
    amortization."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.logreg_kernels import (
        logreg_fit,
        logreg_fit_batched,
    )
    from spark_rapids_ml_tpu.parallel.mesh import shard_aligned

    n_folds, n_maps = LOGREG_MULTI_FOLDS, LOGREG_MULTI_MAPS
    B = n_folds * n_maps
    fold_host = (
        np.random.default_rng(11).integers(0, n_folds, size=N_ROWS).astype(np.int32)
    )
    fid = shard_aligned(fold_host, mesh, X.shape[0])
    l2s = np.logspace(-6, -2, n_maps).astype(np.float32)
    lane_l2 = jnp.asarray(np.tile(l2s, n_folds))
    lane_fold = jnp.asarray(np.repeat(np.arange(n_folds, dtype=np.int32), n_maps))
    zeros_b = jnp.zeros((B,), jnp.float32)

    def gang_fn(X, m, y, l2v):
        out = logreg_fit_batched(
            X, m, y,
            n_classes=2, multinomial=False, fit_intercept=True,
            standardization=False,
            l1=zeros_b, l2=l2v, use_l1=False,
            max_iter=LOGREG_ITERS, tol=zeros_b,
            mesh=mesh, objective_dtype="float32",
            fold_id=fid, lane_fold=lane_fold, n_folds=n_folds,
        )
        return _checksum(out, aux=out["n_iter"].max())

    gang_timed = jax.jit(gang_fn)
    warm = np.asarray(gang_timed(X, mask, y, lane_l2))  # compile
    iters = max(int(warm[1]), 1)
    t, _ = _best_time(
        lambda rep: (X, mask, y, lane_l2 * jnp.float32(1.0 + (rep + 1) * 1e-3)),
        gang_timed,
    )

    # sequential leg: same 24 (fold, map) solves, one device program each
    def solo_fn(X, m, y, l2, fsel):
        m_f = m * (fid != fsel).astype(m.dtype)
        out = logreg_fit(
            X, m_f, y,
            n_classes=2, multinomial=False, fit_intercept=True,
            standardization=False,
            l1=jnp.float32(0.0), l2=l2,
            use_l1=False, max_iter=LOGREG_ITERS, tol=jnp.float32(0.0),
            mesh=mesh, objective_dtype="float32",
        )
        return _checksum(out, aux=out["n_iter"])

    solo_timed = jax.jit(solo_fn)
    warm_s = np.asarray(
        solo_timed(X, mask, y, jnp.float32(float(l2s[0])), jnp.int32(0))
    )  # compile
    t0 = time.perf_counter()
    out = None
    for f in range(n_folds):
        for j in range(n_maps):
            # perturbed l2 -> distinct scalar input buffer per solve
            out = solo_timed(
                X, mask, y,
                jnp.float32(float(l2s[j]) * 1.000123), jnp.int32(f),
            )
    np.asarray(out)  # block on the last solve: the device ran all 24
    t_seq = time.perf_counter() - t0

    # batched objective: ~2 evals/iter, fwd+grad = 4*n*d each, ×B lanes
    # riding ONE read of X per evaluation
    flops = 8.0 * N_ROWS * N_COLS * iters * B
    return {
        # lane-samples per second: B solves × rows × iters (per-iter
        # normalized, matching the logreg entry's convention) — against
        # the same solo per-iter baseline, so vs_baseline directly shows
        # the gang amortization over a one-lane solve
        "samples_per_sec_per_chip": N_ROWS * B * iters / t / n_chips,
        "fit_seconds": t,
        "seq_fit_seconds": t_seq,
        "solves_per_sec": B / t,
        "vs_sequential": t_seq / t,
        "gang_lanes": B,
        "iters": iters,
        "per_iter": True,
        "rows": N_ROWS,
        "flops_model": flops,
        "baseline_samples_per_sec": 2.9e8,
        "baseline_inputs": {
            "formula": "a10g_logreg_flat_per_iter_v1",
            "samples_per_sec_per_iter": 2.9e8,
            "d": N_COLS,
            "lanes": B,
        },
    }


def bench_linreg(X, mask, y, mesh, n_chips):
    """Normal-equation LinearRegression fit: suffstats (Gram + X'y) then a
    replicated solve — same roofline shape as PCA (A10G ~15 TFLOP/s on
    SYRK-shaped work -> 1.1e8 samples/sec/GPU at d=256)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.linreg_kernels import (
        linreg_suffstats_chunked,
        solve_normal,
    )

    def fit_body(eps, X, m, y):
        stats = linreg_suffstats_chunked(
            X, m * (1.0 + eps), y, mesh=mesh, csize=CSIZE
        )
        return _checksum(
            solve_normal(stats, jnp.float32(1e-5), standardization=True)
        )

    t = _time_scanned_fits(
        fit_body,
        lambda rep: (X, mask * jnp.float32(1.0 + rep * 1e-6), y),
    )
    # transform path: one prediction sweep (X @ w + b)
    w_t = jnp.asarray(
        np.random.default_rng(9).standard_normal(N_COLS), jnp.float32
    )

    def tr_body(eps, X, m, y):
        return _checksum(X @ (w_t * (1.0 + eps)))

    t_tr = _time_scanned_fits(
        tr_body,
        lambda rep: (X, mask * jnp.float32(1.0 + rep * 1e-6), y),
    )
    n = N_ROWS
    flops = 2.0 * n * N_COLS * N_COLS
    return {
        "samples_per_sec_per_chip": n / t / n_chips,
        "fit_seconds": t,
        "transform_seconds": t_tr,
        "transform_samples_per_sec_per_chip": n / t_tr / n_chips,
        "inner_fits_per_dispatch": INNER_FITS,
        "gang_lanes": 1,
        "flops_model": flops,
        "baseline_samples_per_sec": 1.1e8,
        "baseline_inputs": {
            "formula": "a10g_syrk_flat_v1",
            "samples_per_sec": 1.1e8,
            "d": N_COLS,
        },
    }


RF_TREES = int(os.environ.get("BENCH_RF_TREES", 50))
RF_ROWS = int(os.environ.get("BENCH_RF_ROWS", 131_072))
RF_DEPTH = int(os.environ.get("BENCH_RF_DEPTH", 13))
RF_BINS = 128


def bench_rf(X, mask, y, mesh, n_chips):
    """RandomForestClassifier at the reference forest config (50 trees,
    depth 13, 128 bins — ``databricks/run_benchmark.sh:102-112``) on a
    131k-row slice (the shape with a recorded round-2 datapoint: 426 s).

    Throughput unit is tree-samples/sec/chip (= rows x trees / seconds):
    trees are embarrassingly parallel with zero collectives, so the rate is
    invariant in tree count and scales linearly with chips.

    Baseline model: a histogram builder on A10G is bound by shared-memory
    atomics; cuML sustains ~1.8e9 histogram updates/s/GPU (consistent with
    the 2xA10G cluster finishing the 1Mx3000 50-tree benchmark inside its
    3600 s budget, ``databricks/README.md:37-40``). One tree-sample costs
    d x depth x n_stats updates, so at d=256/depth 13/S=2 the A10G model
    is 1.8e9 / 6656 ~= 2.7e5 tree-samples/sec/GPU."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.tree_kernels import (
        ForestConfig,
        binize,
        build_forest,
        next_pow2,
        resolve_contract_gather,
        resolve_hist_strategy,
        resolve_tree_batch,
    )

    n_dp = mesh.shape["dp"]
    n_rf = min(RF_ROWS, X.shape[0])
    n_rf = max(n_dp, (n_rf // n_dp) * n_dp)
    Xs = X[:n_rf]
    ys = y[:n_rf]
    ms = mask[:n_rf]
    d_pad = next_pow2(N_COLS)
    # quantile edges ON DEVICE (a host fetch of the subsample would pay the
    # tunnel's ~30 MB/s for ~67 MB); the estimator path sketches on host
    # because there the data starts on host
    qs = jnp.linspace(0.0, 1.0, RF_BINS + 1)[1:-1]
    # one-shot setup jit: this function runs once per bench invocation
    # tpuml: ignore[TPU003]
    edges = jax.jit(
        lambda Xs: jnp.quantile(Xs[: min(65536, n_rf)], qs, axis=0).T.astype(
            jnp.float32
        )
    )(Xs)
    bins = binize(Xs, edges, d_pad=d_pad)
    stats = jnp.stack([1.0 - ys, ys], axis=1) * ms[:, None]
    trees_per_dev = -(-RF_TREES // n_dp)
    from jax.sharding import NamedSharding, PartitionSpec as P
    # reference semantics: the benchmark config leaves featureSubsetStrategy
    # at Spark's default "auto", which cuML resolves to sqrt(d) per split
    # for classification (``/root/reference/python/src/spark_rapids_ml/
    # tree.py:380-386``). Resolution is shared with the estimator so the
    # bench can never drift from what the library fits. Override with
    # BENCH_RF_K=<n> or BENCH_RF_K=all (all-features variant).
    from spark_rapids_ml_tpu.models.tree import _resolve_k_features

    raw_k = os.environ.get("BENCH_RF_K", "auto")
    k_feat = _resolve_k_features(
        N_COLS if raw_k == "all" else (raw_k if raw_k == "auto" else int(raw_k)),
        N_COLS,
        True,
    )
    cfg = ForestConfig(
        max_depth=RF_DEPTH, n_bins=RF_BINS, n_features=N_COLS, n_stats=2,
        impurity="gini", k_features=k_feat, min_samples_leaf=1,
        min_info_gain=0.0, min_samples_split=2, bootstrap=True,
        hist_strategy=resolve_hist_strategy(),
        contract_gather=resolve_contract_gather(),
    )

    # trees build in groups of <= 8 per dispatch: a multi-minute single
    # device program outlives remote-runtime health checks and a killed
    # client wedges the tunnel (round-2 postmortem; the estimator groups
    # the same way). One compiled program serves every group (same size).
    group = min(8, trees_per_dev)
    trees_per_dev = -(-trees_per_dev // group) * group
    keys = jax.random.key_data(
        jax.random.split(jax.random.key(7), n_dp * trees_per_dev)
    ).reshape(n_dp, trees_per_dev, 2)
    keys = jax.device_put(np.asarray(keys), NamedSharding(mesh, P("dp")))
    # tree-batched growth (TPUML_RF_TREE_BATCH, default auto): the whole
    # dispatch group advances one level per device program instead of
    # lax.map-ing trees sequentially — same resolution the estimator uses,
    # so the bench measures exactly what the library ships
    rows_per_tree = n_rf // n_dp
    tree_batch = resolve_tree_batch(group, cfg, rows_per_tree)

    def timed_fn(bins, ms, stats, kg):
        return _checksum(
            build_forest(
                bins, ms, stats, kg, mesh=mesh, cfg=cfg,
                tree_batch=tree_batch,
            )
        )

    timed = jax.jit(timed_fn)
    # warm-up/compile on a DISTINCT key set: remote backends may memoize
    # (executable, input values) pairs, and the timed groups must be fresh
    warm_keys = jax.device_put(
        np.asarray(
            jax.random.key_data(
                jax.random.split(jax.random.key(99), n_dp * group)
            ).reshape(n_dp, group, 2)
        ),
        NamedSharding(mesh, P("dp")),
    )
    np.asarray(timed(bins, ms, stats, warm_keys))  # compile
    # best of BENCH_RF_REPS full passes: a transient tunnel stall would
    # otherwise land in the single summed time (every rep perturbs stats
    # so a remote backend cannot memoize the group dispatches)
    reps = max(1, int(os.environ.get("BENCH_RF_REPS", 2)))
    # transient-stall filtering matters for sub-second dispatches; once a
    # full pass takes this long, a ~100 ms stall is noise and a second
    # pass would only burn the capture run's wall-clock budget
    rep_cap_s = float(os.environ.get("BENCH_RF_MAX_SECONDS_FOR_REPS", 90))
    # pre-slice and block every group's keys OUTSIDE the timed region
    # (the _best_time discipline). Inside it, groups stay host-synchronous
    # — one dispatch, one fetch — deliberately: the ~65 ms/group fetch is
    # <1% of a multi-second group build, and queueing many unfetched
    # multi-second programs is the long-occupancy shape that tripped
    # remote health checks in round 2.
    kgs = [keys[:, g0 : g0 + group] for g0 in range(0, trees_per_dev, group)]
    jax.block_until_ready(kgs)
    times = []
    for rep in range(reps):
        stats_r = stats * jnp.float32(1.0 + (rep + 1) * 1e-6)
        jax.block_until_ready(stats_r)
        t0 = time.perf_counter()
        for kg in kgs:
            np.asarray(timed(bins, ms, stats_r, kg))
        t_rep = time.perf_counter() - t0
        times.append(t_rep)
        if t_rep > rep_cap_s:
            break
    t = min(times)
    n_trees = trees_per_dev * n_dp
    # per-level cost: each group dispatch walks RF_DEPTH levels, groups
    # run back-to-back, so the derived average is t / (levels * groups).
    # BENCH_RF_LEVEL_TIMING=1 replaces the average with MEASURED marginal
    # level costs — depth-prefix builds of one group, differenced — at
    # the price of one compile per depth (tuning runs only).
    n_groups = len(kgs)
    seconds_per_level = t / (RF_DEPTH * n_groups)
    level_seconds = None
    if os.environ.get("BENCH_RF_LEVEL_TIMING") == "1":
        prefix_t = []
        for dep in range(1, RF_DEPTH + 1):
            cfg_l = cfg._replace(max_depth=dep)
            tb_l = resolve_tree_batch(group, cfg_l, rows_per_tree)
            # per-depth variant, compiled once and reused for the timed
            # call  # tpuml: ignore[TPU003]
            f_l = jax.jit(
                lambda b, m, s, kg, _c=cfg_l, _tb=tb_l: _checksum(
                    build_forest(
                        b, m, s, kg, mesh=mesh, cfg=_c, tree_batch=_tb
                    )
                )
            )
            np.asarray(f_l(bins, ms, stats, warm_keys))  # compile
            # perturb stats so a memoizing remote backend re-executes
            s_l = stats * jnp.float32(1.0 + dep * 1e-6)
            jax.block_until_ready(s_l)
            t0l = time.perf_counter()
            np.asarray(f_l(bins, ms, s_l, warm_keys))
            prefix_t.append(time.perf_counter() - t0l)
        level_seconds = [round(prefix_t[0], 4)] + [
            round(max(0.0, b - a), 4)
            for a, b in zip(prefix_t, prefix_t[1:])
        ]
    # transform path: the two-hop bin-space descent the model uses on TPU
    # (round 5; binize of the query batch is timed INSIDE, as the model
    # pays it per batch), over the FULL forest width (one built group's
    # trees tiled to n_trees — apply cost is content-independent).
    from spark_rapids_ml_tpu.ops.tree_kernels import binize, rf_classify_bins

    # one-shot warm build, outside the timed region  # tpuml: ignore[TPU003]
    grp = jax.jit(
        lambda b, m, s, kg: build_forest(
            b, m, s, kg, mesh=mesh, cfg=cfg, tree_batch=tree_batch
        )
    )(bins, ms, stats, warm_keys)
    feat_g = grp["feature"].reshape(-1, grp["feature"].shape[-1])
    thr_b = grp["threshold_bin"].reshape(feat_g.shape)
    leafs = grp["leaf_stats"].reshape(feat_g.shape + (2,))
    reps_t = -(-n_trees // feat_g.shape[0])

    def prep(feat_g, thr_b, leafs):
        prob = leafs / jnp.maximum(leafs.sum(-1, keepdims=True), 1e-12)
        tile = lambda a: jnp.tile(a, (reps_t,) + (1,) * (a.ndim - 1))[:n_trees]
        return tile(feat_g), tile(thr_b), tile(prob)

    # one-shot tiling, outside the timed region  # tpuml: ignore[TPU003]
    feat_t, thrb_t, prob_t = jax.jit(prep)(feat_g, thr_b, leafs)
    jax.block_until_ready((feat_t, thrb_t, prob_t))
    d_pad4 = -(-Xs.shape[1] // 4) * 4
    # row-chunked + group=4: the descent's per-tree-group transients must
    # coexist with the resident multi-GB design matrix here (a single
    # full-width pass RESOURCE_EXHAUSTed alongside it)
    n_half = n_rf // 2

    # packed-forest lockstep engine (round 6): pack OUTSIDE the timed fn
    # — the model pays it once and caches (models/tree._ensure_packed),
    # so the steady-state serving cost is traversal only. Falls back to
    # the per-tree bins descent when the traversal kernel can't lower
    # (CPU smoke runs, oversized feature words).
    from spark_rapids_ml_tpu.ops.rf_pallas import packed_traverse_ok
    from spark_rapids_ml_tpu.ops.tree_kernels import (
        pack_forest, rf_classify_packed,
    )

    pf = pack_forest(
        np.asarray(feat_t), np.asarray(thrb_t), max_depth=RF_DEPTH
    )
    use_packed = pf.k2 == 0 or packed_traverse_ok(
        pf.feat1.shape[0], pf.k1, pf.k2, d_pad4 // 4
    )
    if use_packed:
        pk = tuple(
            jax.device_put(a) for a in (pf.feat1, pf.thr1, pf.feat2, pf.thr2)
        )
        jax.block_until_ready(pk)

        def tr_fn(Xq, edges, feat_t, thrb_t, prob_t):
            acc = jnp.float32(0.0)
            for lo in (0, n_rf - n_half):
                xbq = binize(Xq[lo : lo + n_half], edges, d_pad=d_pad4)
                acc = acc + _checksum(
                    rf_classify_packed(
                        xbq, *pk, prob_t,
                        k1=pf.k1, k2=pf.k2, max_depth=RF_DEPTH,
                    )[0]
                )
            return acc

    else:

        def tr_fn(Xq, edges, feat_t, thrb_t, prob_t):
            acc = jnp.float32(0.0)
            # second chunk is anchored to the END so odd n_rf still covers
            # every row (the one-row overlap double-counts a checksum term,
            # not timed work of any significance)
            for lo in (0, n_rf - n_half):
                xbq = binize(Xq[lo : lo + n_half], edges, d_pad=d_pad4)
                acc = acc + _checksum(
                    rf_classify_bins(
                        xbq, feat_t, thrb_t, prob_t, max_depth=RF_DEPTH, group=4
                    )[0]
                )
            return acc

    tr_timed = jax.jit(tr_fn)
    np.asarray(tr_timed(Xs, edges, feat_t, thrb_t, prob_t))  # compile
    t_tr, _ = _best_time(
        lambda rep: (
            Xs * jnp.float32(1.0 + (rep + 1) * 1e-6), edges, feat_t,
            thrb_t, prob_t,
        ),
        tr_timed,
    )
    # updates model: one histogram update per (row, sampled feature, stat,
    # level) — both sides of the comparison pay k_features per node, so
    # the A10G atomics baseline divides by the same per-sample cost
    updates = float(n_rf) * k_feat * 2 * RF_DEPTH * n_trees
    return {
        "samples_per_sec_per_chip": n_rf * n_trees / t / n_chips,
        "fit_seconds": t,
        "transform_seconds": t_tr,
        "transform_engine": "packed" if use_packed else "bins",
        "transform_samples_per_sec_per_chip": n_rf / t_tr / n_chips,
        # FIL/treelite serving roofline (reference tree.py:557-591): GPU
        # forest inference is bound by per-(row, tree, level) node fetches
        # hitting L1/SMEM at ~1e10 fetches/s/GPU — tens of millions of
        # rows/s at small forests, matching published FIL numbers
        "transform_baseline_samples_per_sec": 1e10 / (n_trees * RF_DEPTH),
        "trees": n_trees,
        "rows": n_rf,
        "k_features": k_feat,
        "hist_strategy": cfg.hist_strategy,
        "tree_batch": tree_batch,
        "seconds_per_level": round(seconds_per_level, 5),
        **({"level_seconds": level_seconds} if level_seconds else {}),
        "flops_model": updates,  # scatter-equivalent work, not MXU flops
        "baseline_samples_per_sec": 1.8e9 / (k_feat * RF_DEPTH * 2),
        "baseline_inputs": {
            "formula": "rf_hist_atomics_v1",
            "atomics_per_sec": 1.8e9,
            "k_features": k_feat,
            "depth": RF_DEPTH,
            "n_stats": 2,
            "transform_formula": "fil_node_fetch_v1",
            "node_fetches_per_sec": 1e10,
        },
    }


GBT_ROUNDS = int(os.environ.get("BENCH_GBT_ROUNDS", 20))
GBT_ROWS = int(os.environ.get("BENCH_GBT_ROWS", 131_072))
GBT_DEPTH = int(os.environ.get("BENCH_GBT_DEPTH", 8))


def bench_gbt(X, mask, y, mesh, n_chips):
    """Binary logistic gradient boosting (``ops/gbt_kernels.gbt_round``):
    sequential rounds, each a tree-batched level-wise build over the
    current gradient field plus an in-round margin advance. Rows stay
    data-parallel — every tree sees the full dataset through psum'd
    histograms — so unlike rf the round chain has collectives, and the
    fit rate measures the boosting-loop steady state (stats recompute,
    T-batched histograms, leaf Newton steps, margin update).

    Throughput unit matches rf: tree-samples/sec/chip (rows x trees /
    seconds).

    Baseline model (derived roofline like ann): XGBoost-class GPU hist
    boosting on the A10G is bound by the same shared-memory histogram
    atomics as the RF baseline (1.8e9 updates/s) but pays ALL d features
    per node (boosted trees don't subsample features per split) x depth
    levels x 2 stats (grad, hess) per tree-sample; the per-round
    gradient/margin streaming passes are charged at zero (they are HBM
    reads the histogram pass already pays)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.ops.gbt_kernels import GBTConfig, gbt_round
    from spark_rapids_ml_tpu.ops.tree_kernels import (
        ForestConfig,
        binize,
        next_pow2,
        resolve_contract_gather,
        resolve_hist_strategy,
    )

    n_dp = mesh.shape["dp"]
    n_g = min(GBT_ROWS, X.shape[0])
    n_g = max(n_dp, (n_g // n_dp) * n_dp)
    Xs, ys, ms = X[:n_g], y[:n_g], mask[:n_g]
    d_pad = next_pow2(N_COLS)
    qs = jnp.linspace(0.0, 1.0, RF_BINS + 1)[1:-1]
    # one-shot setup jit (same device-side sketch as rf)
    # tpuml: ignore[TPU003]
    edges = jax.jit(
        lambda Xs: jnp.quantile(Xs[: min(65536, n_g)], qs, axis=0).T.astype(
            jnp.float32
        )
    )(Xs)
    bins = binize(Xs, edges, d_pad=d_pad)
    cfg = GBTConfig(
        loss="logistic", n_out=1, learning_rate=0.1,
        tree=ForestConfig(
            max_depth=GBT_DEPTH, n_bins=RF_BINS, n_features=N_COLS,
            n_stats=4, impurity="variance", k_features=N_COLS,
            min_samples_leaf=1, min_info_gain=0.0, min_samples_split=2,
            bootstrap=False,
            hist_strategy=resolve_hist_strategy(),
            contract_gather=resolve_contract_gather(),
        ),
    )
    keys_np = np.asarray(jax.random.split(jax.random.PRNGKey(7), GBT_ROUNDS))
    zeros = jax.device_put(
        np.zeros((n_g, 1), np.float32), NamedSharding(mesh, P("dp"))
    )
    warm_key = jnp.asarray(np.asarray(jax.random.PRNGKey(99)))
    # compile on a distinct key (remote-memoization discipline, as in rf)
    out_w = gbt_round(bins, ms, ys, zeros, warm_key, mesh=mesh, cfg=cfg)
    jax.block_until_ready(out_w["margins"])

    reps = max(1, int(os.environ.get("BENCH_GBT_REPS", 2)))
    times = []
    last = None
    for rep in range(reps):
        # a fresh epsilon init perturbs every round's stats so a
        # memoizing remote backend cannot replay the chain
        margins = zeros + jnp.float32((rep + 1) * 1e-6)
        jax.block_until_ready(margins)
        t0 = time.perf_counter()
        outs = []
        for r in range(GBT_ROUNDS):
            out = gbt_round(
                bins, ms, ys, margins, jnp.asarray(keys_np[r]),
                mesh=mesh, cfg=cfg,
            )
            margins = out.pop("margins")
            outs.append(out)
        jax.block_until_ready(margins)
        times.append(time.perf_counter() - t0)
        last = outs
    t = min(times)

    # transform leg: the model's descent engines over the boosted forest
    # (summed leaf payloads; margin = init + sum), packed when the
    # traversal kernel lowers, else the two-hop bins descent — the same
    # engine split the rf entry reports
    feat_t = jnp.concatenate([o["feature"] for o in last], axis=0)
    thrb_t = jnp.concatenate([o["threshold_bin"] for o in last], axis=0)
    vals_t = jnp.concatenate([o["values"] for o in last], axis=0)[:, :, None]
    jax.block_until_ready((feat_t, thrb_t, vals_t))
    from spark_rapids_ml_tpu.ops.rf_pallas import packed_traverse_ok
    from spark_rapids_ml_tpu.ops.tree_kernels import (
        pack_forest, rf_eval_bins, rf_eval_packed,
    )

    d_pad4 = -(-Xs.shape[1] // 4) * 4
    pf = pack_forest(
        np.asarray(feat_t), np.asarray(thrb_t), max_depth=GBT_DEPTH
    )
    use_packed = pf.k2 == 0 or packed_traverse_ok(
        pf.feat1.shape[0], pf.k1, pf.k2, d_pad4 // 4
    )
    n_half = n_g // 2
    if use_packed:
        pk = tuple(
            jax.device_put(a) for a in (pf.feat1, pf.thr1, pf.feat2, pf.thr2)
        )
        jax.block_until_ready(pk)

        def tr_fn(Xq, edges, feat_t, thrb_t, vals_t):
            acc = jnp.float32(0.0)
            for lo in (0, n_g - n_half):
                xbq = binize(Xq[lo : lo + n_half], edges, d_pad=d_pad4)
                acc = acc + _checksum(
                    rf_eval_packed(
                        xbq, *pk, vals_t,
                        k1=pf.k1, k2=pf.k2, max_depth=GBT_DEPTH,
                    )
                )
            return acc

    else:

        def tr_fn(Xq, edges, feat_t, thrb_t, vals_t):
            acc = jnp.float32(0.0)
            for lo in (0, n_g - n_half):
                xbq = binize(Xq[lo : lo + n_half], edges, d_pad=d_pad4)
                acc = acc + _checksum(
                    rf_eval_bins(
                        xbq, feat_t, thrb_t, vals_t,
                        max_depth=GBT_DEPTH, group=4,
                    )
                )
            return acc

    tr_timed = jax.jit(tr_fn)
    np.asarray(tr_timed(Xs, edges, feat_t, thrb_t, vals_t))  # compile
    t_tr, _ = _best_time(
        lambda rep: (
            Xs * jnp.float32(1.0 + (rep + 1) * 1e-6), edges, feat_t,
            thrb_t, vals_t,
        ),
        tr_timed,
    )
    n_trees = GBT_ROUNDS * cfg.n_out
    updates = float(n_g) * N_COLS * 2 * GBT_DEPTH * n_trees
    return {
        "samples_per_sec_per_chip": n_g * n_trees / t / n_chips,
        "fit_seconds": t,
        "transform_seconds": t_tr,
        "transform_engine": "packed" if use_packed else "bins",
        "transform_samples_per_sec_per_chip": n_g / t_tr / n_chips,
        "transform_baseline_samples_per_sec": 1e10 / (n_trees * GBT_DEPTH),
        "rounds": GBT_ROUNDS,
        "trees": n_trees,
        "rows": n_g,
        "depth": GBT_DEPTH,
        "hist_strategy": cfg.tree.hist_strategy,
        "seconds_per_round": round(t / GBT_ROUNDS, 5),
        "flops_model": updates,  # scatter-equivalent work, not MXU flops
        "baseline_samples_per_sec": 1.8e9 / (N_COLS * GBT_DEPTH * 2),
        "baseline_kind": "derived-roofline",
        "baseline_inputs": {
            "formula": "gbt_hist_atomics_v1",
            "atomics_per_sec": 1.8e9,
            "d": N_COLS,
            "depth": GBT_DEPTH,
            "n_stats": 2,
            "transform_formula": "fil_node_fetch_v1",
            "node_fetches_per_sec": 1e10,
        },
    }


KNN_QUERIES = int(os.environ.get("BENCH_KNN_QUERIES", 131_072))
KNN_ITEMS = int(os.environ.get("BENCH_KNN_ITEMS", 1_000_000))
KNN_K = 16


def bench_knn(X, mask, mesh, n_chips):
    """Exact brute-force kNN (the reference's NearestNeighbors workload):
    one ring pass over the item shards, distance matmul + running top-k.

    Baseline model (round 5, sharpened from the round-4 optimistic floor
    per that verdict): cuML brute kNN per query pays (a) the distance
    matmul, 2*ni*d FLOPs at A10G's ~15 TFLOP/s effective TF32, and (b)
    the k-selection pass over the ni-wide distance row — cuML's
    warp-select reads the materialized tile from L2/HBM, charged at half
    the 600 GB/s HBM rate (generous: tiles partially hit L2). UCX
    inter-GPU exchange is charged at zero (single-GPU roofline). At
    1M x 256 this lands ~9% below the old matmul-only floor."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.knn_kernels import ring_knn

    n_dp = mesh.shape["dp"]
    # clamp to REAL rows (N_ROWS), not the padded count: padding rows are
    # masked out of results but would inflate "rows" and the baseline's
    # workload credit
    ni = min(KNN_ITEMS, N_ROWS, X.shape[0])
    ni = max(n_dp, (ni // n_dp) * n_dp)
    nq = min(KNN_QUERIES, ni)
    nq = max(n_dp, (nq // n_dp) * n_dp)
    Xi, mi = X[:ni], mask[:ni]
    ids = jnp.arange(ni, dtype=jnp.int32)

    def timed_fn(Xq, Xi, mi, ids):
        return _checksum(ring_knn(Xq, Xi, mi, ids, mesh=mesh, k=KNN_K))

    timed = jax.jit(timed_fn)
    np.asarray(timed(X[:nq], Xi, mi, ids))  # compile
    t, _ = _best_time(
        lambda rep: (
            X[:nq] * jnp.float32(1.0 + (rep + 1) * 1e-6), Xi, mi, ids
        ),
        timed,
    )
    flops = 2.0 * nq * ni * N_COLS
    # per-query GPU cost: matmul + k-selection read (see docstring)
    base_q_s = 2.0 * ni * N_COLS / 15e12 + ni * 4.0 / (0.5 * 600e9)
    return {
        "samples_per_sec_per_chip": nq / t / n_chips,
        "fit_seconds": t,
        "rows": ni,
        "queries": nq,
        "flops_model": flops,
        "baseline_samples_per_sec": 1.0 / base_q_s,
        "baseline_kind": "derived-roofline",
        "baseline_inputs": {
            "formula": "knn_matmul_select_v1",
            "matmul_flops_per_sec": 15e12,
            "select_bytes_per_sec": 0.5 * 600e9,
            "items": ni,
            "d": N_COLS,
        },
    }


ANN_ROWS = int(os.environ.get("BENCH_ANN_ROWS", 131_072))
ANN_QUERIES = int(os.environ.get("BENCH_ANN_QUERIES", 65_536))
ANN_K = 16


def bench_ann(mesh, n_chips):
    """IVF-Flat approximate kNN serving (the reference's
    ``ApproximateNearestNeighbors`` ivfflat workload): k-means coarse
    quantizer + probe-list scan (``ops/ivf_kernels.py``). The timed
    quantity is the SEARCH rate; the one-off index build is reported
    separately (serving amortizes it away, exactly as cuML does).

    Data is host blobs (~128 MB at 128k x 256): IVF needs cluster
    structure — a uniform cloud has no identifiable cells and every ANN
    engine degrades toward brute force there (the reference benches ANN
    on ``gen_data.py`` blobs for the same reason).

    Baseline model: RAFT IVF-Flat on the A10G — the knn_matmul_select_v1
    constants applied per query to the PROBED candidate pool instead of
    all items: (a) coarse quantization, 2*nlist*d FLOPs at 15 TFLOP/s
    effective TF32; (b) candidate scan, 2*d FLOPs over the nprobe*cap
    gathered rows; (c) warp-select reading the nprobe*cap-wide distance
    row from L2/HBM at half the 600 GB/s HBM rate. Build is charged at
    zero. vs_baseline is only meaningful at matched approximation
    quality, so recall@k against the exact engine on a query subsample
    rides in the entry (docs/ann_performance.md has the trade-off
    curve)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.models.umap import knn_brute
    from spark_rapids_ml_tpu.ops.ivf_kernels import (
        build_ivf_index,
        ivf_search,
        resolve_ann_params,
    )
    from spark_rapids_ml_tpu.ops.knn_kernels import resolve_knn_topk

    n_dp = mesh.shape["dp"]
    ni = max(n_dp, (ANN_ROWS // n_dp) * n_dp)
    nq = min(ANN_QUERIES, ni)
    nq = max(n_dp, (nq // n_dp) * n_dp)
    d = N_COLS
    rng = np.random.default_rng(11)
    centers = rng.normal(size=(64, d)).astype(np.float32) * 4.0
    lab = rng.integers(0, 64, size=ni)
    Xh = (centers[lab] + rng.normal(size=(ni, d))).astype(np.float32)

    nlist, nprobe = resolve_ann_params(ni)
    t0 = time.perf_counter()
    index = build_ivf_index(Xh, nlist=nlist, seed=0, mesh=mesh)
    jax.block_until_ready(index.grouped_x)
    t_build = time.perf_counter() - t0

    topk = resolve_knn_topk()

    def timed(Xq):
        return np.asarray(
            _checksum(
                ivf_search(
                    Xq, index, k=ANN_K, nprobe=nprobe, topk_impl=topk,
                    mesh=mesh,
                )
            )
        )

    Q = Xh[:nq]
    timed(jnp.asarray(Q))  # compile + commit the index to the mesh
    t, _ = _best_time(
        lambda rep: (jnp.asarray(Q * np.float32(1.0 + (rep + 1) * 1e-6)),),
        timed,
    )

    # recall@k vs the exact sweep on a subsample — the quantity that makes
    # the throughput claim meaningful
    sub = min(1024, nq)
    _, aids = ivf_search(
        jnp.asarray(Xh[:sub]), index, k=ANN_K, nprobe=nprobe, topk_impl=topk
    )
    _, eids = knn_brute(jnp.asarray(Xh), jnp.asarray(Xh[:sub]), k=ANN_K)
    a, e = np.asarray(aids), np.asarray(eids)
    recall = float(
        np.mean([len(set(a[i]) & set(e[i])) / ANN_K for i in range(sub)])
    )

    cap = index.cap
    pool = nlist + nprobe * cap
    base_q_s = 2.0 * pool * d / 15e12 + nprobe * cap * 4.0 / (0.5 * 600e9)
    return {
        "samples_per_sec_per_chip": nq / t / n_chips,
        "fit_seconds": t,
        "build_seconds": round(t_build, 4),
        "rows": ni,
        "queries": nq,
        "nlist": nlist,
        "nprobe": nprobe,
        "recall": round(recall, 4),
        "flops_model": 2.0 * nq * pool * d,
        "baseline_samples_per_sec": 1.0 / base_q_s,
        "baseline_kind": "derived-roofline",
        "baseline_inputs": {
            "formula": "ann_ivf_probe_v1",
            "matmul_flops_per_sec": 15e12,
            "select_bytes_per_sec": 0.5 * 600e9,
            "nlist": nlist,
            "nprobe": nprobe,
            "cap": cap,
            "d": d,
        },
    }


UMAP_ROWS = int(os.environ.get("BENCH_UMAP_ROWS", 65_536))
UMAP_NEIGHBORS = 15


def bench_umap(mesh, n_chips):
    """UMAP end-to-end through the estimator (the reference benchmarks
    UMAP the same way and scores trustworthiness:
    ``python/benchmark/benchmark/bench_umap.py``).

    Pipeline timed: brute-force kNN graph (device) -> fuzzy simplicial
    set (host symmetrization of n*k entries) -> spectral init ->
    negative-sampling SGD (device). Data is host-side blobs (~64 MB at
    64k x 256) — the one entry where ingest rides inside fit, as it does
    in the reference's Spark flow; at these sizes the transfer is a few
    seconds of the multi-ten-second fit.

    Baseline model (round 5, replacing the round-4 1e4 proxy per that
    verdict): a derived cuML-on-A10G fit roofline —
      knn      2*n^2*d FLOPs at 15 TFLOP/s effective TF32;
      SGD      epochs * f_active*m_edges * (1+neg) head updates, c f32
               atomics each, at the 1.8e9 atomics/s constant the RF
               baseline uses (the 512 KB embedding is L2-resident);
      spectral 0.2 s flat credit for the GPU Lanczos init;
      fuzzy-set/transfer/launch overheads charged at ZERO.
    Constants measured at the bench shape: the symmetrized edge factor
    m/(n*k) = 1.74 and the mean Bernoulli activation f = 0.278
    (scripts/umap_profile.py lineage). At 65k x 256 this gives ~1.0 s,
    consistent with published cuML UMAP times (MNIST 70k in ~1-2 s) —
    i.e. a roofline, not a proxy. The transform baseline reuses the knn
    term plus one third of the SGD (the refine epochs).

    flops_model counts the brute kNN graph (2*n^2*d), the dominant
    device compute of this implementation; MFU is indicative only.
    """
    from sklearn.manifold import trustworthiness

    from spark_rapids_ml_tpu.data import DataFrame as TDF
    from spark_rapids_ml_tpu.umap import UMAP

    n, d = UMAP_ROWS, N_COLS
    rng = np.random.default_rng(3)
    centers = rng.normal(size=(32, d)).astype(np.float32) * 4.0
    lab = rng.integers(0, 32, size=n)
    Xh = (centers[lab] + rng.normal(size=(n, d))).astype(np.float32)
    df = TDF({"features": Xh})
    # warm-pass data is PERTURBED vs the timed pass: identical
    # (executable, buffers) pairs may be memoized by a remote backend
    # (module docstring; observed round 1) — the timed fit must see
    # fresh buffers
    df_warm = TDF({"features": Xh * np.float32(1.0 + 1e-6)})

    est = UMAP(n_neighbors=UMAP_NEIGHBORS, random_state=42)
    # graph engine: the bench runs the IVF-Flat approximate graph by
    # default (BENCH_UMAP_GRAPH=exact restores the old sweep) — set
    # explicitly because the estimator's own default gate keeps exact
    # below TPUML_ANN_GATE_ROWS (defaults-inert contract); scoped so the
    # process env is untouched for later entries
    graph_mode = os.environ.get("BENCH_UMAP_GRAPH", "ivf")
    prev_graph = os.environ.pop("TPUML_UMAP_GRAPH", None)
    os.environ["TPUML_UMAP_GRAPH"] = graph_mode
    try:
        # warm pass at FULL size first: the kNN-graph/SGD executables are
        # shape-specialized, so only a same-shape fit excludes compile time
        # from the timed pass (every other leg warms the same way);
        # BENCH_UMAP_WARM=0 skips when wall-clock budget is tight
        if os.environ.get("BENCH_UMAP_WARM", "1") != "0":
            est.fit(df_warm)
        t0 = time.perf_counter()
        model = est.fit(df)
        t_fit = time.perf_counter() - t0
        emb = np.asarray(model.embedding_)

        model.transform(df_warm)  # warm transform executables (fresh buffers)
        t0 = time.perf_counter()
        out = model.transform(df)
        emb_t = np.asarray(out["embedding"])
        t_tr = time.perf_counter() - t0
        assert emb_t.shape[0] == n
    finally:
        if prev_graph is None:
            os.environ.pop("TPUML_UMAP_GRAPH", None)
        else:
            os.environ["TPUML_UMAP_GRAPH"] = prev_graph

    # quality: trustworthiness on a subsample (the reference's score;
    # exact trust is O(sub^2) host work)
    sub = rng.choice(n, size=min(4096, n), replace=False)
    trust = float(
        trustworthiness(Xh[sub], emb[sub], n_neighbors=UMAP_NEIGHBORS)
    )

    # derived A10G roofline (docstring): knn + SGD atomics + spectral
    m_edges = n * UMAP_NEIGHBORS * 1.74   # measured symmetrized factor
    f_active = 0.278                      # measured mean(w)/max(w)
    epochs = 200 if n > 10000 else 500
    knn_s = 2.0 * n * n * d / 15e12
    sgd_s = epochs * f_active * m_edges * 6 * 2 / 1.8e9
    base_fit_s = knn_s + sgd_s + 0.2
    # stage decomposition + engine choice straight from the estimator's
    # fit/transform reports, so a drifting vs_baseline is attributable to
    # a stage (graph vs init vs sgd) without rerunning under a profiler
    rep = dict(getattr(model, "_fit_report", None) or {})
    trep = dict(getattr(model, "_transform_report", None) or {})

    # graph recall when the approximate engine ran: the fit's index is
    # deterministic in (X, nlist, seed), so rebuild it and score the probe
    # search against the exact sweep on a query subsample — graph_seconds
    # is only comparable across engines at matched recall
    graph_recall = None
    if rep.get("graph_engine") == "ivf":
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.models.umap import knn_brute
        from spark_rapids_ml_tpu.ops.ivf_kernels import (
            build_ivf_index,
            ivf_search,
        )

        gidx = build_ivf_index(
            Xh, nlist=rep["ann_nlist"], seed=42  # = random_state above
        )
        qs = jnp.asarray(Xh[: min(1024, n)])
        _, aids = ivf_search(
            qs, gidx, k=UMAP_NEIGHBORS + 1, nprobe=rep["ann_nprobe"]
        )
        _, eids = knn_brute(jnp.asarray(Xh), qs, k=UMAP_NEIGHBORS + 1)
        a, e = np.asarray(aids), np.asarray(eids)
        graph_recall = round(
            float(
                np.mean(
                    [
                        len(set(a[i]) & set(e[i])) / a.shape[1]
                        for i in range(a.shape[0])
                    ]
                )
            ),
            4,
        )
    return {
        "samples_per_sec_per_chip": n / t_fit / n_chips,
        "fit_seconds": t_fit,
        "transform_seconds": t_tr,
        "transform_samples_per_sec_per_chip": n / t_tr / n_chips,
        "transform_baseline_samples_per_sec": n / (knn_s + sgd_s / 3.0),
        "transform_engine": trep.get("sgd_engine"),
        "rows": n,
        "trustworthiness": round(trust, 4),
        "graph_seconds": rep.get("graph_seconds"),
        "graph_engine": rep.get("graph_engine"),
        "graph_recall": graph_recall,
        "ann_nlist": rep.get("ann_nlist"),
        "ann_nprobe": rep.get("ann_nprobe"),
        "init_seconds": rep.get("init_seconds"),
        "sgd_seconds": rep.get("sgd_seconds"),
        "epoch_ms": rep.get("epoch_ms"),
        "sgd_engine": rep.get("sgd_engine"),
        "flops_model": 2.0 * float(n) * n * d,
        "baseline_samples_per_sec": n / base_fit_s,
        "baseline_kind": "derived-roofline",
        "baseline_inputs": {
            "formula": "umap_roofline_v1",
            "knn_flops_per_sec": 15e12,
            "atomics_per_sec": 1.8e9,
            "edge_factor": 1.74,
            "f_active": f_active,
            "epochs": epochs,
            "n_neighbors": UMAP_NEIGHBORS,
            "spectral_flat_seconds": 0.2,
        },
    }


def bench_pca_stream(mesh, n_chips):
    """Out-of-core PCA: chunks stream through a bounded device buffer
    (``ops/streaming.py``), the path that handles beyond-HBM datasets
    (BASELINE.md 100M x 256 north-star). Self-calibrates the row count so a
    slow host->device link cannot blow the wall-clock budget; the reported
    rate is per-pass ingest+accumulate throughput (2 passes per fit).

    Through a remote tunnel this measures the TUNNEL's ~30 MB/s, not the
    chip's PCIe/DMA ingest; callers should treat it as a correctness-at-
    scale check there (it is excluded from the headline geomean)."""
    import jax

    from spark_rapids_ml_tpu.data.chunks import GeneratorChunkSource
    from spark_rapids_ml_tpu.models.feature import _pca_from_cov
    from spark_rapids_ml_tpu.ops.streaming import streamed_suffstats

    d = N_COLS
    n_dp = mesh.shape["dp"]
    chunk_rows = int(os.environ.get("BENCH_STREAM_CHUNK", 1 << 18))
    chunk_rows = max(n_dp, (chunk_rows // n_dp) * n_dp)
    rng = np.random.default_rng(2)
    block = rng.standard_normal((chunk_rows, d), dtype=np.float32)

    def gen(start, count, seed):
        return block[:count], None

    def run(rows):
        src = GeneratorChunkSource(gen, rows, d)
        stats = streamed_suffstats(src, mesh, chunk_rows, np.float32, with_y=False)
        cov = stats["G"] / (stats["n"] - 1.0)
        out = _pca_from_cov(stats["mean_x"], cov, stats["n"], 3)
        # force a device->host fetch of every (small) leaf: block_until_ready
        # alone is not trustworthy through a remote tunnel (lazy futures
        # observed round 1), and the calibration scales the real run's row
        # count off this timer
        for leaf in jax.tree_util.tree_leaves(out):
            np.asarray(leaf)
        return out

    # calibrate: compile + measure a 4-chunk fit, then size the real run
    calib_rows = 4 * chunk_rows
    run(calib_rows)  # compile
    t0 = time.perf_counter()
    run(calib_rows)
    t_calib = time.perf_counter() - t0
    budget_s = float(os.environ.get("BENCH_STREAM_SECONDS", 45))
    max_rows = int(os.environ.get("BENCH_STREAM_ROWS", 16_000_000))
    rows = int(min(max_rows, calib_rows * max(1.0, budget_s / max(t_calib, 1e-9))))
    rows = max(chunk_rows, (rows // chunk_rows) * chunk_rows)

    t0 = time.perf_counter()
    run(rows)
    t = time.perf_counter() - t0
    # the wire encoding the fit ACTUALLY used (env request resolved through
    # select_wire_format — "auto" lands here as the probed choice)
    from spark_rapids_ml_tpu.ops.streaming import last_ingest_report

    wire_kind = last_ingest_report().get("wire_dtype", "f32")

    # Decomposition (round-3 verdict: the artifact alone must distinguish
    # "tunnel-bound" from "streaming kernels are slow"):
    # (a) device math only — fold ONE device-resident chunk repeatedly
    #     through both passes' steps (no H2D inside the timed loop);
    # (b) ingest only — stream + transfer every chunk but fold it with a
    #     trivial (read-proving) step;
    # (c) decode only — run the chunk source with no transfer/fold at all
    #     (quantization cost shows up as ingest minus decode).
    # overlap_efficiency = (a + b - total) / min(a, b), clipped to [0, 1]:
    # 1.0 means the slower leg fully hides the faster one.
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.data.chunks import Chunk
    from spark_rapids_ml_tpu.ops.streaming import (
        StreamGuard, gram2_init, gram2_step, iter_device_chunks,
        moments1_init, moments1_step, put_chunk, wire_dense,
    )

    n_chunks = max(1, rows // chunk_rows)
    dev = put_chunk(
        Chunk(X=block, n_valid=chunk_rows), mesh, np.float32, wire=wire_kind
    )
    jax.block_until_ready(
        [v for k, v in dev.items() if v is not None and k != "_wire"]
    )
    mean0 = jnp.zeros((d,), jnp.float32)

    def math_pass():
        acc1 = moments1_init(d, jnp.float32, False)
        for _ in range(n_chunks):
            acc1 = moments1_step(acc1, dev["X"], dev["mask"])
        np.asarray(jnp.ravel(acc1["sum_x"])[:1])
        acc2 = gram2_init(d, jnp.float32, False)
        for _ in range(n_chunks):
            acc2 = gram2_step(acc2, dev["X"], dev["mask"], mean0)
        np.asarray(jnp.ravel(acc2["G"])[:1])

    math_pass()  # compile
    t0 = time.perf_counter()
    math_pass()
    t_math = time.perf_counter() - t0

    import functools

    import jax as _jax

    @functools.partial(_jax.jit, donate_argnums=(0,))
    def _touch(acc, Xc, m):
        Xc = wire_dense(Xc)
        return acc + (Xc[0, :8].astype(jnp.float32) * m[:8]).sum()

    def ingest_pass():
        # the LIBRARY path: decode/quantize/transfer ride the same
        # prefetch + staging ring as streamed_suffstats, so the measured
        # overlap_efficiency reflects the shipped machinery (round-4
        # verdict: the serial put_chunk loop here never exercised it)
        src = GeneratorChunkSource(gen, rows, d)
        for _pass in range(2):
            acc = jnp.float32(0.0)
            guard = StreamGuard()
            with contextlib.closing(
                iter_device_chunks(
                    src, mesh, chunk_rows, np.float32,
                    need_y=False, need_w=False,
                )
            ) as chunks:
                for _, devc in chunks:
                    acc = _touch(acc, devc["X"], devc["mask"])
                    guard.tick(devc, acc)
            guard.flush(acc)

    # warm: the first _touch call pays jit trace+compile (several tunnel
    # round trips) — keep that out of the measured ingest leg, matching
    # the math leg's warm pass
    src_w = GeneratorChunkSource(gen, chunk_rows, d)
    accw = jnp.float32(0.0)
    for chunk in src_w.iter_chunks(chunk_rows, np.float32):
        devw = put_chunk(chunk, mesh, np.float32, wire=wire_kind)
        accw = _touch(accw, devw["X"], devw["mask"])
    np.asarray(accw)
    t0 = time.perf_counter()
    ingest_pass()
    t_ingest = time.perf_counter() - t0

    def decode_pass():
        src = GeneratorChunkSource(gen, rows, d)
        for _pass in range(2):
            for _chunk in src.iter_chunks(chunk_rows, np.float32):
                pass

    t0 = time.perf_counter()
    decode_pass()
    t_decode = time.perf_counter() - t0
    overlap = max(
        0.0, min(1.0, (t_math + t_ingest - t) / max(min(t_math, t_ingest), 1e-9))
    )

    flops = 2.0 * rows * d * d  # pass-2 Gram dominates
    stream_gb = rows * d * 4 * 2 / 1e9  # 2 passes
    # The stream fit ingests host data every chunk; when the effective
    # ingest rate is far below PCIe-class (threshold 1 GB/s), the number
    # measures the link, not the chip, and is excluded from the geomean.
    ingest_gbps = stream_gb / max(t, 1e-9)
    return {
        "samples_per_sec_per_chip": rows / t / n_chips,
        "fit_seconds": t,
        "rows": rows,
        "stream_gb": round(stream_gb, 2),
        "ingest_gbps": round(ingest_gbps, 3),
        "device_math_seconds": round(t_math, 4),
        "device_math_samples_per_sec": round(rows / max(t_math, 1e-9), 1),
        "ingest_seconds": round(t_ingest, 4),
        "decode_seconds": round(t_decode, 4),
        "overlap_efficiency": round(overlap, 3),
        "wire_dtype": wire_kind,
        "flops_model": flops,
        "baseline_samples_per_sec": 1.1e8,
        "baseline_inputs": {
            "formula": "a10g_syrk_flat_v1",
            "samples_per_sec": 1.1e8,
            "d": d,
        },
        "tunnel_bound": ingest_gbps < 1.0,
    }


SERVE_TRAIN_ROWS = int(os.environ.get("BENCH_SERVE_ROWS", 4096))
SERVE_COLS = int(os.environ.get("BENCH_SERVE_COLS", 32))
SERVE_REQUESTS = int(os.environ.get("BENCH_SERVE_REQUESTS", 60))


def bench_serving(mesh, n_chips):
    """Online-serving latency bench: small rf/pca/umap models resident
    in a ServingRuntime, driven with a mixed-shape request stream.

    Reports (a) served micro-batched throughput vs the direct
    per-request ``model.transform`` loop — the A/B the registry +
    memoized closures exist to win (per-call closure rebuilds are what
    sank rf/umap transform in round 5); (b) client-observed p50/p99
    latency under an open-loop QPS sweep; (c) a batch-window sweep.
    Every phase runs inside telemetry spans so roofline attribution
    lands on the serving sites, and the retrace contract is enforced:
    ``retrace_storms`` must read 0 after the full load, else this entry
    raises (the bench-regression gate then sees the entry missing)."""
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.models.tree import RandomForestClassifier
    from spark_rapids_ml_tpu.models.umap import UMAP
    from spark_rapids_ml_tpu.runtime import telemetry as tele
    from spark_rapids_ml_tpu.serving import ServingRuntime

    rng = np.random.default_rng(41)
    n, d = SERVE_TRAIN_ROWS, SERVE_COLS
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X[:, 0] + 0.25 * rng.standard_normal(n) > 0).astype(np.float32)
    df = DataFrame({"features": X, "label": y})
    umap_rows = min(n, 2048)

    t0 = time.perf_counter()
    models = {
        "rf": RandomForestClassifier(
            numTrees=8, maxDepth=6, seed=3, num_workers=1
        ).fit(df),
        "pca": PCA(k=8).fit(df),
        "umap": UMAP(
            n_neighbors=8, n_epochs=30, random_state=3, num_workers=1
        ).fit(DataFrame({"features": X[:umap_rows]})),
    }
    fit_seconds = time.perf_counter() - t0

    # mixed-shape request stream: sizes that pad, share buckets, and
    # dispatch exact; umap stays small (never coalesced, each distinct
    # shape compiles once)
    sizes = {"rf": (1, 3, 8, 17, 33, 64), "pca": (2, 5, 16, 27), "umap": (3, 8)}
    stream = []
    for fam, szs in sizes.items():
        for i in range(SERVE_REQUESTS // (3 * len(szs)) or 1):
            for s in szs:
                q = rng.standard_normal((s, d)).astype(np.float32)
                stream.append((fam, q))
    rows_total = sum(q.shape[0] for _, q in stream)

    # A: direct per-request loop — one model.transform per request, the
    # path a naive deployment runs (and what BENCH_r05 measured)
    per_family_direct = {}
    t0 = time.perf_counter()
    for fam, model in models.items():
        reqs = [q for f, q in stream if f == fam]
        tf = time.perf_counter()
        for q in reqs:
            model.transform(DataFrame({"features": q}))
        per_family_direct[fam] = time.perf_counter() - tf
    direct_seconds = time.perf_counter() - t0

    # B: served — same requests through the micro-batched runtime, with
    # the live ops plane attached (ephemeral port): the scrape-under-
    # load criterion is measured against THIS mixed-shape stream
    import urllib.request as _urlreq

    from spark_rapids_ml_tpu.runtime import opsplane as ops

    os.environ["TPUML_OPS_PORT"] = "0"
    scrape_ms = {"/metrics": [], "/statusz": []}

    def _scrape(path):
        addr = ops.address()
        if addr is None:
            return
        t_s = time.perf_counter()
        with _urlreq.urlopen(
            f"http://{addr[0]}:{addr[1]}{path}", timeout=30
        ) as resp:
            resp.read()
        scrape_ms[path].append((time.perf_counter() - t_s) * 1e3)

    try:
        t0 = time.perf_counter()
        with ServingRuntime(batch_window_us=2000, max_bucket_rows=64) as rt:
            for fam, model in models.items():
                rt.register(fam, model)
            warm_seconds = time.perf_counter() - t0

            per_family_served = {}
            t0 = time.perf_counter()
            for fam in models:
                reqs = [q for f, q in stream if f == fam]
                tf = time.perf_counter()
                futs = [rt.predict_async(fam, q) for q in reqs]
                # scrape while this family's requests are in flight —
                # the live-ops latency under genuine dispatch load
                _scrape("/metrics")
                _scrape("/statusz")
                for f in futs:
                    f.result(600)
                per_family_served[fam] = time.perf_counter() - tf
            served_seconds = time.perf_counter() - t0

            # open-loop QPS sweep on the rf stream (bounded: 40 requests
            # per rate), client-observed latency
            qps_sweep = {}
            q8 = rng.standard_normal((8, d)).astype(np.float32)
            for qps in (64, 256, 1024):
                # latency recorded AT RESOLUTION (done-callback fires on
                # the dispatcher thread) — collecting after the submit
                # loop would charge early requests the remaining
                # open-loop sleep time
                lat = []
                with tele.span("serve.bench.qps", qps=qps):
                    futs = []
                    for _i in range(40):
                        t_req = time.perf_counter()
                        f = rt.predict_async("rf", q8)
                        f.add_done_callback(
                            lambda _f, t=t_req: lat.append(
                                (time.perf_counter() - t) * 1e3
                            )
                        )
                        futs.append(f)
                        time.sleep(1.0 / qps)
                    for f in futs:
                        f.result(600)
                qps_sweep[str(qps)] = {
                    "p50_ms": round(float(np.percentile(lat, 50)), 3),
                    "p99_ms": round(float(np.percentile(lat, 99)), 3),
                }

        # batch-window sweep: burst of 48 rf requests per window setting
        window_sweep = {}
        for window_us in (0, 500, 2000, 8000):
            with ServingRuntime(
                batch_window_us=window_us, max_bucket_rows=64
            ) as rt:
                rt.register("rf", models["rf"])
                lat = []
                with tele.span("serve.bench.window", window_us=window_us):
                    t_burst = time.perf_counter()
                    futs = []
                    for s in (3, 5, 8, 17) * 12:
                        f = rt.predict_async(
                            "rf",
                            rng.standard_normal((s, d)).astype(np.float32),
                        )
                        f.add_done_callback(
                            lambda _f: lat.append(
                                (time.perf_counter() - t_burst) * 1e3
                            )
                        )
                        futs.append(f)
                    for f in futs:
                        f.result(600)
            window_sweep[str(window_us)] = {
                "p50_ms": round(float(np.percentile(lat, 50)), 3),
                "p99_ms": round(float(np.percentile(lat, 99)), 3),
            }

        # C: overload sweep — offered load past measured capacity into a
        # bounded-queue runtime with a per-request deadline: graceful
        # degradation means goodput PLATEAUS past capacity (admission
        # sheds absorb the excess, typed errors at submit) instead of
        # collapsing under unbounded queue growth
        deadline_ms = 250.0  # the serving_p99_ms SLO objective
        overload_sweep = {}
        q8 = rng.standard_normal((8, d)).astype(np.float32)
        with ServingRuntime(
            batch_window_us=2000, max_bucket_rows=64, queue_limit=32
        ) as rt:
            rt.register("rf", models["rf"])
            # measured capacity: closed-loop burst (stays under the
            # queue bound), no deadline — also primes the EWMA service
            # model the deadline_unmeetable shed decision uses
            t_c = time.perf_counter()
            futs = [rt.predict_async("rf", q8) for _ in range(24)]
            for f in futs:
                f.result(600)
            capacity_qps = 24 / max(time.perf_counter() - t_c, 1e-9)
            for mult in (1, 2, 4):
                offered = capacity_qps * mult
                n_req = 96
                shed = 0
                rec = []  # (latency_ms, resolved_ok) at resolution
                futs = []
                with tele.span("serve.bench.overload", mult=mult):
                    t_s = time.perf_counter()
                    for i in range(n_req):
                        # absolute schedule: sleep granularity must not
                        # silently lower the offered rate
                        lag = t_s + i / offered - time.perf_counter()
                        if lag > 0:
                            time.sleep(lag)
                        t_req = time.perf_counter()
                        try:
                            f = rt.predict_async(
                                "rf", q8, deadline_ms=deadline_ms
                            )
                        except Exception:
                            shed += 1  # typed Overloaded at admission
                            continue
                        f.add_done_callback(
                            lambda f_, t=t_req: rec.append((
                                (time.perf_counter() - t) * 1e3,
                                f_.exception() is None,
                            ))
                        )
                        futs.append(f)
                    for f in futs:
                        try:
                            f.result(600)
                        except Exception:
                            pass  # DeadlineExceeded while queued
                    elapsed = time.perf_counter() - t_s
                ok_lat = [l for l, good in rec if good]
                missed = len(rec) - len(ok_lat)
                overload_sweep[str(mult)] = {
                    "offered_qps": round(offered, 1),
                    "goodput_qps": round(len(ok_lat) / elapsed, 1),
                    "shed_frac": round(shed / n_req, 4),
                    "deadline_missed": missed,
                    "admitted_p99_ms": (
                        round(float(np.percentile(ok_lat, 99)), 3)
                        if ok_lat else None
                    ),
                }

        # degradation gates: past-capacity goodput must hold (plateau,
        # not collapse), and what IS served must honor the deadline
        top = overload_sweep[str(4)]
        base = overload_sweep[str(1)]
        if top["goodput_qps"] <= 0 or (
            base["goodput_qps"] > 0
            and top["goodput_qps"] < 0.35 * base["goodput_qps"]
        ):
            raise RuntimeError(
                f"overload goodput collapsed past capacity: {overload_sweep}"
            )
        for mult, row in overload_sweep.items():
            p99 = row["admitted_p99_ms"]
            if p99 is not None and p99 > 1.5 * deadline_ms:
                raise RuntimeError(
                    f"admitted-request p99 {p99} ms at {mult}x offered load "
                    f"is unbounded by the {deadline_ms} ms deadline"
                )
    finally:
        ops.stop()
        os.environ.pop("TPUML_OPS_PORT", None)

    # live-scrape contract: the plane must be ABLE to answer in <50 ms
    # while the dispatcher is under load (min-of-samples: a loaded CI
    # host may stall any single scrape, but a plane that can never
    # answer fast is a real regression)
    ops_scrape_ms = {
        path.lstrip("/"): {
            "count": len(v),
            "min_ms": round(min(v), 3),
            "max_ms": round(max(v), 3),
        }
        for path, v in scrape_ms.items()
        if v
    }
    for path, st in ops_scrape_ms.items():
        if st["min_ms"] >= 50.0:
            raise RuntimeError(
                f"ops-plane /{path} never answered under 50 ms during the "
                f"mixed-shape stream: {st}"
            )

    # the hard serving gate: the whole mixed load must not have scored a
    # single retrace storm (warmup sites absorb declared compiles)
    snap = tele.metrics_snapshot()
    storms = snap.get("retrace_storms")
    n_storms = sum(s["value"] for s in storms["series"]) if storms else 0
    if n_storms:
        raise RuntimeError(
            f"serving load swept {n_storms} retrace storm(s): "
            f"{storms['series']}"
        )
    p99_series = [
        s for s in snap.get("serve_p99_ms", {}).get("series", [])
    ]
    lat_all = qps_sweep["256"]
    # mean valid-row fraction across every dispatched bucket: the
    # micro-batching efficiency number the regression gate watches
    fill_series = snap.get("serve_batch_fill", {}).get("series", [])
    fill_count = sum(s["count"] for s in fill_series)
    serve_batch_fill = (
        round(sum(s["sum"] for s in fill_series) / fill_count, 4)
        if fill_count else 0.0
    )

    # FLOP model: pca projection + rf traversal compares + umap knn
    # against the resident training table (dominant term)
    n_trees, depth = 8, 6
    per_row = {
        "pca": 2.0 * d * 8,
        "rf": float(n_trees * depth),
        "umap": 2.0 * d * umap_rows,
    }
    flops = sum(
        per_row[fam] * sum(q.shape[0] for f, q in stream if f == fam)
        for fam in models
    )
    served_rps = rows_total / served_seconds
    direct_rps = rows_total / direct_seconds
    return {
        "samples_per_sec_per_chip": served_rps / n_chips,
        "fit_seconds": served_seconds,
        "setup_fit_seconds": round(fit_seconds, 4),
        "warm_seconds": round(warm_seconds, 4),
        "rows": rows_total,
        "requests": len(stream),
        "p50_ms": lat_all["p50_ms"],
        "p99_ms": lat_all["p99_ms"],
        "serve_batch_fill": serve_batch_fill,
        "qps_sweep": qps_sweep,
        "window_sweep": window_sweep,
        "ops_scrape_ms": ops_scrape_ms,
        "retrace_storms": n_storms,
        "serve_vs_direct": {
            fam: round(
                per_family_direct[fam] / max(per_family_served[fam], 1e-9), 3
            )
            for fam in models
        },
        "flops_model": flops,
        "baseline_samples_per_sec": direct_rps / n_chips,
        "baseline_kind": "direct_transform_per_request",
        "baseline_inputs": {
            "formula": "same_process_per_request_transform_loop_v1",
            "requests": len(stream),
            "rows": rows_total,
            "direct_seconds": round(direct_seconds, 4),
            "d": d,
        },
        "p99_series_models": sorted(
            {s["labels"].get("model") for s in p99_series}
        ),
        "capacity_qps": round(capacity_qps, 1),
        "overload_sweep": overload_sweep,
        "overload_deadline_ms": deadline_ms,
        "goodput_qps": overload_sweep[str(4)]["goodput_qps"],
        "shed_frac": overload_sweep[str(4)]["shed_frac"],
    }


def bench_router(mesh, n_chips):
    """Pod-scale router bench: one light resident model replicated over
    loopback replica fleets of 1/2/4, each fleet driven at the SAME
    fixed offered load, chosen above the 4-replica aggregate admission
    capacity so every fleet size is saturated.

    The single-replica fleet runs through the SAME ``Router`` front
    door, so the A/B isolates replica count, not router overhead.

    ``replica_scaling_efficiency`` is delivered-fraction against the
    offered-load-capped ideal: ``(g4/offered) / min(1, 4*g1/offered)``
    — at saturation (a chip host, where one replica's capacity is far
    under the offered load) this is exactly ``g4/(4*g1)``; when a
    single replica already absorbs most of the offered load (this
    1-core CI box: the dispatcher consumes the queue WHILE sleeping in
    its batch window, so one replica's admission capacity tracks the
    offered rate) the ideal is capped at 1 and the metric reads how
    close the fleet gets to delivering everything offered.

    Gates (raise = entry missing = regression): scaling efficiency
    >= 0.75; fleet goodput must never DEGRADE vs one replica
    (>= 0.9x); the 4-replica fleet must shed no more than the single
    replica; admitted p99 <= 1.5x the single-replica p99; zero retrace
    storms across the whole sweep. The ISSUE-17 absolute-scaling gates
    (2-rep >= 1.7x, 4-rep >= 3x single) arm only when the offered load
    exceeds the scaling target — i.e. when fleet-1 is genuinely
    saturated and N-replica goodput is physically expressible; a
    waived arm is logged to stderr, never silent. The reported
    ``fleet_p99_ms`` is read from the MERGED fleet snapshot
    (``Router.fleet_p99_ms`` -> ``telemetry.merge_metric_snapshots``,
    pooled reservoirs), not recomputed client-side."""
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.runtime import telemetry as tele
    from spark_rapids_ml_tpu.serving import Router

    rng = np.random.default_rng(47)
    d = 32
    X = rng.standard_normal((2048, d)).astype(np.float32)
    t0 = time.perf_counter()
    model = PCA(k=4).fit(DataFrame({"features": X}))
    setup_fit_seconds = time.perf_counter() - t0

    # per-replica admission capacity ~= queue_limit per (window +
    # compute) cycle; the fixed offered load sits 1.5x above the
    # 4-replica aggregate so goodput measures admitted capacity at
    # every fleet size and the excess sheds typed at the front door
    window_us = 40_000
    queue_limit = 12
    deadline_ms = 250.0  # the serving_p99_ms SLO objective
    per_replica_qps = queue_limit / (window_us / 1e6)
    offered = 1.5 * 4 * per_replica_qps
    duration_s = 2.0
    n_req = int(offered * duration_s)
    q2 = rng.standard_normal((2, d)).astype(np.float32)  # coalescable
    rt_kwargs = dict(
        batch_window_us=window_us, max_bucket_rows=32,
        queue_limit=queue_limit,
    )

    fleet_sweep = {}
    elapsed4 = 0.0
    for n_rep in (1, 2, 4):
        # distinct registry name per fleet: the merged serve_p99_ms
        # series stay separable by label across the sweep
        mname = f"pca{n_rep}"
        with Router(
            replicas=n_rep, policy="p2c", runtime_kwargs=rt_kwargs
        ) as router:
            router.register(mname, model)
            # prime dispatchers + the routing EWMA below the queue bound
            for _ in range(3):
                warm = [
                    router.predict_async(mname, q2)
                    for _ in range(4 * n_rep)
                ]
                for f in warm:
                    f.result(600)
            shed = 0
            rec = []  # (latency_ms, resolved_ok) at resolution
            futs = []
            with tele.span("serve.bench.router", replicas=n_rep):
                t_s = time.perf_counter()
                for i in range(n_req):
                    # absolute schedule: sleep granularity must not
                    # silently lower the offered rate
                    lag = t_s + i / offered - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    t_req = time.perf_counter()
                    try:
                        f = router.predict_async(
                            mname, q2, deadline_ms=deadline_ms
                        )
                    except Exception:
                        shed += 1  # typed Overloaded at the front door
                        continue
                    f.add_done_callback(
                        lambda f_, t=t_req: rec.append((
                            (time.perf_counter() - t) * 1e3,
                            f_.exception() is None,
                        ))
                    )
                    futs.append(f)
                for f in futs:
                    try:
                        f.result(600)
                    except Exception:
                        pass  # DeadlineExceeded while queued
                elapsed = time.perf_counter() - t_s
            fleet_p99 = router.fleet_p99_ms().get(mname)
            drained = router.drain(30.0)
        if n_rep == 4:
            elapsed4 = elapsed
        ok_lat = [l for l, good in rec if good]
        fleet_sweep[str(n_rep)] = {
            "offered_qps": round(n_req / elapsed, 1),
            "goodput_qps": round(len(ok_lat) / elapsed, 1),
            "shed_frac": round(shed / n_req, 4),
            "deadline_missed": len(rec) - len(ok_lat),
            "admitted_p99_ms": (
                round(float(np.percentile(ok_lat, 99)), 3)
                if ok_lat else None
            ),
            "fleet_p99_ms": (
                None if fleet_p99 is None else round(fleet_p99, 3)
            ),
            "drained": bool(drained["drained"]),
        }

    g1 = fleet_sweep["1"]["goodput_qps"]
    g2 = fleet_sweep["2"]["goodput_qps"]
    g4 = fleet_sweep["4"]["goodput_qps"]
    if g1 <= 0 or g2 < 0.9 * g1 or g4 < 0.9 * g1:
        raise RuntimeError(
            f"fleet goodput DEGRADED vs one replica at fixed "
            f"{offered:.0f} qps offered: 1->{g1} 2->{g2} 4->{g4} "
            f"(router spreading must never cost throughput): "
            f"{fleet_sweep}"
        )
    # absolute-scaling gates arm only where N-replica goodput is
    # physically expressible: the target must sit under the offered
    # load (on a saturated chip host it does; on this box one replica
    # absorbs most of the offered rate and the arm is logged, not
    # silently skipped)
    for n_rep, factor, g_n in (("2", 1.7, g2), ("4", 3.0, g4)):
        target = factor * g1
        if target <= offered:
            if g_n < target:
                raise RuntimeError(
                    f"replica scaling collapsed: {n_rep}-replica "
                    f"goodput {g_n} qps under the armed {factor}x "
                    f"single-replica target {target:.0f} qps: "
                    f"{fleet_sweep}"
                )
        else:
            print(
                f"[bench] router: {factor}x scaling gate waived — "
                f"target {target:.0f} qps exceeds the {offered:.0f} "
                f"qps offered load (single replica absorbs "
                f"{g1 / offered:.0%} of it on this host)",
                file=sys.stderr,
            )
    eff = (g4 / offered) / min(1.0, 4 * g1 / offered)
    if eff < 0.75:
        raise RuntimeError(
            f"replica scaling efficiency {eff:.3f} under 0.75 "
            f"(goodput vs the offered-load-capped 4-replica ideal): "
            f"{fleet_sweep}"
        )
    if fleet_sweep["4"]["shed_frac"] > fleet_sweep["1"]["shed_frac"]:
        raise RuntimeError(
            f"4-replica fleet shed MORE than one replica at the same "
            f"offered load: {fleet_sweep}"
        )
    p99_1 = fleet_sweep["1"]["admitted_p99_ms"]
    for n_rep in ("2", "4"):
        p99_n = fleet_sweep[n_rep]["admitted_p99_ms"]
        if p99_1 and p99_n and p99_n > 1.5 * p99_1:
            raise RuntimeError(
                f"admitted p99 at {n_rep} replicas ({p99_n} ms) blew "
                f"1.5x the single-replica p99 ({p99_1} ms): "
                f"{fleet_sweep}"
            )

    # the serving retrace contract holds fleet-wide: the whole sweep
    # (3 fleets x warmup + saturation) must not have scored one storm
    snap = tele.metrics_snapshot()
    storms = snap.get("retrace_storms")
    n_storms = sum(s["value"] for s in storms["series"]) if storms else 0
    if n_storms:
        raise RuntimeError(
            f"router load swept {n_storms} retrace storm(s): "
            f"{storms['series']}"
        )

    rows_per_req = int(q2.shape[0])
    ok4 = int(round(g4 * elapsed4))
    top = fleet_sweep["4"]
    return {
        "samples_per_sec_per_chip": g4 * rows_per_req / n_chips,
        "fit_seconds": elapsed4,
        "setup_fit_seconds": round(setup_fit_seconds, 4),
        "requests": n_req,
        "rows": ok4 * rows_per_req,
        "replicas": 4,
        "policy": "p2c",
        "offered_qps": round(offered, 1),
        "capacity_qps": g1,  # measured through the same front door
        "aggregate_goodput_qps": g4,
        "goodput_qps": g4,
        "shed_frac": top["shed_frac"],
        "replica_scaling_efficiency": round(eff, 4),
        "p99_ms": top["admitted_p99_ms"],
        "fleet_p99_ms": top["fleet_p99_ms"],
        "fleet_sweep": fleet_sweep,
        "retrace_storms": n_storms,
        # pca projection flops on the rows that actually served (4-rep)
        "flops_model": 2.0 * d * 4 * ok4 * rows_per_req,
        "baseline_samples_per_sec": g1 * rows_per_req / n_chips,
        "baseline_kind": "single_replica_router",
        "baseline_inputs": {
            "formula": "same_router_one_replica_fixed_offered_load_v1",
            "offered_qps": round(offered, 1),
            "queue_limit": queue_limit,
            "batch_window_us": window_us,
            "deadline_ms": deadline_ms,
            "rows_per_request": rows_per_req,
        },
    }


def bench_fit_sched(mesh, n_chips):
    """Multi-tenant fit-scheduler bench: many small same-shape KMeans
    fits driven through a :class:`FitScheduler`.

    Reports (a) scheduled closed-loop capacity (``fits_per_sec``)
    against the direct sequential ``.fit()`` loop — pack-compatible
    jobs gang through one coscheduled preprocess, so the scheduler
    should at worst break even and win once a backlog forms; (b) an
    open-loop arrival sweep at 1x/2x/4x measured capacity into a
    bounded queue with a per-fit deadline — graceful degradation means
    goodput plateaus past capacity (typed ``Overloaded`` sheds at
    submit, ``DeadlineExceeded`` in the backlog) while admitted fits
    keep a bounded client-observed p99. Hard gates: the swept load must
    score zero new retrace storms (same shapes => one compile), the 4x
    goodput must hold >= 35% of the 1x goodput, and every future must
    resolve (drain reports zero aborts)."""
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.runtime import FitScheduler, telemetry as tele

    rng = np.random.default_rng(47)
    n, d, k, iters = 1024, 8, 4, 4
    n_fits = int(os.environ.get("BENCH_SCHED_FITS", 12))
    X = rng.standard_normal((n, d)).astype(np.float32)
    df = DataFrame({"features": X})

    def make():
        return KMeans(k=k, maxIter=iters, seed=3, num_workers=n_chips)

    def _storms():
        s = tele.metrics_snapshot().get("retrace_storms")
        return sum(row["value"] for row in s["series"]) if s else 0

    make().fit(df)  # warm the compile cache outside every timed phase
    storms_base = _storms()

    # baseline: the direct sequential fit loop a naive tenant runs
    t0 = time.perf_counter()
    for _ in range(n_fits):
        make().fit(df)
    direct_seconds = time.perf_counter() - t0
    direct_fps = n_fits / direct_seconds

    # capacity: the same fits submitted at once — the backlog gangs
    # through one coscheduled preprocess; also primes the EWMA the
    # deadline shed decision uses
    with tele.span("sched.bench.capacity", fits=n_fits):
        with FitScheduler() as sched:
            t0 = time.perf_counter()
            futs = [
                sched.submit(make(), df, tenant=f"t{i % 4}")
                for i in range(n_fits)
            ]
            for f in futs:
                f.result(600)
            fit_seconds = time.perf_counter() - t0
            cap_stats = sched.stats()
    capacity_fps = n_fits / fit_seconds

    # open-loop arrival sweep: offered fit rate past capacity into a
    # bounded queue with a deadline; latency recorded AT RESOLUTION
    mean_fit_ms = 1e3 * fit_seconds / n_fits
    deadline_ms = max(8.0 * mean_fit_ms, 50.0)
    arrival_sweep = {}
    for mult in (1, 2, 4):
        offered = capacity_fps * mult
        n_req = max(2 * n_fits, 16)
        shed = 0
        rec = []  # (latency_ms, resolved_ok) at resolution
        with tele.span("sched.bench.arrival", mult=mult):
            with FitScheduler(queue_limit=8) as sched:
                futs = []
                t_s = time.perf_counter()
                for i in range(n_req):
                    # absolute schedule: sleep granularity must not
                    # silently lower the offered rate
                    lag = t_s + i / offered - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    t_req = time.perf_counter()
                    try:
                        f = sched.submit(
                            make(), df, tenant=f"t{i % 4}",
                            deadline_ms=deadline_ms,
                        )
                    except Exception:
                        shed += 1  # typed Overloaded at admission
                        continue
                    f.add_done_callback(
                        lambda f_, t=t_req: rec.append((
                            (time.perf_counter() - t) * 1e3,
                            f_.exception() is None,
                        ))
                    )
                    futs.append(f)
                for f in futs:
                    try:
                        f.result(600)
                    except Exception:
                        pass  # DeadlineExceeded while queued
                elapsed = time.perf_counter() - t_s
                report = sched.drain(timeout=60)
        if report["aborted"]:
            raise RuntimeError(
                f"fit_sched drain left {report['aborted']} future(s) "
                f"unresolved at {mult}x offered load"
            )
        ok_lat = [l for l, good in rec if good]
        arrival_sweep[str(mult)] = {
            "offered_fps": round(offered, 2),
            "goodput_fps": round(len(ok_lat) / elapsed, 2),
            "shed_frac": round(shed / n_req, 4),
            "deadline_missed": len(rec) - len(ok_lat),
            "fit_p50_ms": (
                round(float(np.percentile(ok_lat, 50)), 3) if ok_lat else None
            ),
            "fit_p99_ms": (
                round(float(np.percentile(ok_lat, 99)), 3) if ok_lat else None
            ),
        }

    # degradation gate: goodput past capacity must plateau, not collapse
    top, base = arrival_sweep["4"], arrival_sweep["1"]
    if top["goodput_fps"] <= 0 or (
        base["goodput_fps"] > 0
        and top["goodput_fps"] < 0.35 * base["goodput_fps"]
    ):
        raise RuntimeError(
            f"fit_sched goodput collapsed past capacity: {arrival_sweep}"
        )
    # retrace gate: same-shape fits through the scheduler must not have
    # swept a single NEW storm across the whole load
    new_storms = _storms() - storms_base
    if new_storms:
        raise RuntimeError(
            f"fit_sched load swept {new_storms} retrace storm(s)"
        )

    # FLOP model: lloyd assignment distances dominate each fit
    per_fit = 2.0 * n * d * k * iters
    rows_total = n * n_fits
    return {
        "samples_per_sec_per_chip": rows_total / fit_seconds / n_chips,
        "fit_seconds": fit_seconds,
        "rows": rows_total,
        "fits": n_fits,
        "fits_per_sec": round(capacity_fps, 3),
        "fit_p50_ms": arrival_sweep["1"]["fit_p50_ms"],
        "fit_p99_ms": arrival_sweep["1"]["fit_p99_ms"],
        "shed_frac": arrival_sweep["4"]["shed_frac"],
        "goodput_qps": arrival_sweep["4"]["goodput_fps"],
        "sched_occupancy": cap_stats["occupancy"],
        "arrival_sweep": arrival_sweep,
        "arrival_deadline_ms": round(deadline_ms, 1),
        "retrace_storms": new_storms,
        "flops_model": per_fit * n_fits,
        "baseline_samples_per_sec": rows_total / direct_seconds / n_chips,
        "baseline_kind": "direct_sequential_fit_loop",
        "baseline_inputs": {
            "formula": "same_process_sequential_fit_loop_v1",
            "fits": n_fits,
            "rows": rows_total,
            "direct_seconds": round(direct_seconds, 4),
            "direct_fits_per_sec": round(direct_fps, 3),
            "n": n, "d": d, "k": k, "iters": iters,
        },
    }


def bench_lifecycle(mesh, n_chips):
    """Continuous-training lifecycle bench: sustained closed-loop QPS
    through >= 3 consecutive versioned hot-swaps, plus the canary
    re-flip (rollback) latency.

    Reports the client-observed p99 during the swap windows against the
    steady-state p99 (``swap_p99_delta_ms``) and the time a rollback
    takes to re-flip the live version (``rollback_ms``). Hard gates —
    the zero-downtime contract: zero typed sheds and zero new retrace
    storms across every flip, every version lands (v4 resident at the
    end), and the during-swap p99 must stay within 15% of steady state
    (small absolute floor for sub-ms noise), else this entry raises and
    the bench-regression gate sees it missing."""
    import threading

    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.runtime import telemetry as tele
    from spark_rapids_ml_tpu.serving import ModelLifecycle, ServingRuntime

    rng = np.random.default_rng(53)
    n, d, k = 2048, 16, 8
    n_swaps = int(os.environ.get("BENCH_LIFECYCLE_SWAPS", 3))
    X = rng.standard_normal((n, d)).astype(np.float32)
    df = DataFrame({"features": X})

    t0 = time.perf_counter()
    # v1 + swap candidates fitted on the same data with the same params:
    # served outputs stay identical, so any latency delta is pure swap
    # machinery (stage+warm beside live, atomic flip, evict)
    versions = [PCA(k=k).fit(df) for _ in range(1 + n_swaps)]
    other = rng.standard_normal((n, d)).astype(np.float32)
    divergent = PCA(k=k).fit(DataFrame({"features": other}))
    fit_seconds = time.perf_counter() - t0

    def _metric_total(name):
        s = tele.metrics_snapshot().get(name)
        return sum(row["value"] for row in s["series"]) if s else 0

    storms_base = _metric_total("retrace_storms")
    sheds_base = _metric_total("serve_shed_total")

    sizes = (3, 8, 17, 33)
    queries = [
        rng.standard_normal((s, d)).astype(np.float32) for s in sizes
    ]

    # baseline: the direct per-request transform loop a deployment
    # without the resident registry runs (no hot-swap possible there
    # short of a process restart)
    t0 = time.perf_counter()
    for i in range(64):
        versions[0].transform(DataFrame({"features": queries[i % 4]}))
    direct_seconds = time.perf_counter() - t0
    direct_rows = sum(q.shape[0] for q in queries) * 16

    lat_steady, lat_swap = [], []  # (latency_ms, rows) at resolution
    phase = {"buf": lat_steady}
    stop = threading.Event()
    errors = []

    with ServingRuntime(batch_window_us=2000, max_bucket_rows=64) as rt:
        rt.register("pca", versions[0])
        lc = ModelLifecycle(rt)

        def client(tid):
            i = tid
            while not stop.is_set():
                q = queries[i % len(queries)]
                t_r = time.perf_counter()
                try:
                    rt.predict("pca", q, timeout=600)
                except Exception as e:  # typed shed = gate failure
                    errors.append(e)
                    return
                phase["buf"].append(
                    ((time.perf_counter() - t_r) * 1e3, q.shape[0])
                )
                i += 1

        swap_ms = []
        t_serve = time.perf_counter()
        threads = [
            threading.Thread(target=client, args=(t,)) for t in range(3)
        ]
        for t in threads:
            t.start()
        try:
            time.sleep(1.0)  # steady-state window
            for v, model in enumerate(versions[1:], start=2):
                phase["buf"] = lat_swap
                t_s = time.perf_counter()
                with tele.span("serve.bench.swap", version=v):
                    lc.swap("pca", model=model)
                swap_ms.append((time.perf_counter() - t_s) * 1e3)
                time.sleep(0.2)  # tail of the swap window
                phase["buf"] = lat_steady
                time.sleep(0.5)  # recover between consecutive swaps
        finally:
            stop.set()
            for t in threads:
                t.join(120)
        serve_seconds = time.perf_counter() - t_serve

        if errors:
            raise RuntimeError(
                f"lifecycle load took a typed shed under swap: {errors[0]!r}"
            )
        final = rt.registry.get("pca")
        if final.version != 1 + n_swaps or rt.registry.names() != ["pca"]:
            raise RuntimeError(
                f"swap ladder did not land a single consistent version: "
                f"v{final.version}, resident={rt.registry.names()}"
            )

        # rollback latency: a mirrored canary re-flipped to the live
        # version (shadow route cleared + candidate evicted + breaker)
        lc.start_canary(
            "pca", model=divergent, fraction=1.0, min_requests=10**6
        )
        rt.predict("pca", queries[1], timeout=600)
        t_r = time.perf_counter()
        lc.rollback("pca", reason="manual")
        rollback_ms = (time.perf_counter() - t_r) * 1e3
        lc.drain(timeout=30)

    new_storms = _metric_total("retrace_storms") - storms_base
    if new_storms:
        raise RuntimeError(
            f"lifecycle load swept {new_storms} retrace storm(s)"
        )
    new_sheds = _metric_total("serve_shed_total") - sheds_base
    if new_sheds:
        raise RuntimeError(
            f"lifecycle load shed {new_sheds} request(s) across the flips"
        )

    steady = np.array([ms for ms, _ in lat_steady])
    swapw = np.array([ms for ms, _ in lat_swap])
    if steady.size < 16 or swapw.size < 4:
        raise RuntimeError(
            f"lifecycle load under-sampled: steady={steady.size} "
            f"swap={swapw.size}"
        )
    steady_p99 = float(np.percentile(steady, 99))
    swap_p99 = float(np.percentile(swapw, 99))
    # the 15% zero-downtime latency gate; the absolute floor absorbs
    # host-side warm-compile CPU contention on the CPU backend, where
    # the bucket-ladder compiles and the serving compute share cores
    # (on an accelerator device compute is unaffected and the relative
    # bound is the binding one) — a retrace storm or a blocked flip
    # shows up as a 100ms+ delta and still trips it
    if swap_p99 > max(1.15 * steady_p99, steady_p99 + 10.0):
        raise RuntimeError(
            f"hot-swap disturbed the tail: during-swap p99 "
            f"{swap_p99:.3f}ms vs steady {steady_p99:.3f}ms (>15%)"
        )

    rows_served = int(
        sum(r for _, r in lat_steady) + sum(r for _, r in lat_swap)
    )
    return {
        "samples_per_sec_per_chip": rows_served / serve_seconds / n_chips,
        "fit_seconds": fit_seconds,
        "rows": rows_served,
        "swaps": len(swap_ms),
        "swap_ms": [round(m, 3) for m in swap_ms],
        "p50_ms": round(float(np.percentile(steady, 50)), 3),
        "p99_ms": round(steady_p99, 3),
        "swap_p99_ms": round(swap_p99, 3),
        "swap_p99_delta_ms": round(max(0.0, swap_p99 - steady_p99), 3),
        "rollback_ms": round(rollback_ms, 3),
        "retrace_storms": new_storms,
        "flops_model": 2.0 * rows_served * d * k,
        "baseline_samples_per_sec": direct_rows / direct_seconds / n_chips,
        "baseline_kind": "direct_transform_loop",
        "baseline_inputs": {
            "formula": "per_request_model_transform_loop_v1",
            "requests": 64,
            "rows": direct_rows,
            "direct_seconds": round(direct_seconds, 4),
            "n": n, "d": d, "k": k,
        },
    }


def bench_autotune(mesh, n_chips):
    """Measured-autotuner A/B: tuned-vs-default on three legs (rf tree
    batch, pca_stream stage depth, serving batch window).

    Per leg: (1) resolve the heuristic default and measure it, (2) run
    the probe search over the knob's candidate grid with
    ``autotune.probe`` — each candidate measured by a short dispatch of
    the real work — writing the winner into the tuning cache, (3)
    re-run with ``TPUML_AUTOTUNE=on`` (cache-warm: zero probes,
    asserted) and measure the tuned config. ``tuned_vs_default`` is
    tuned throughput over default throughput; when the search keeps the
    heuristic default the leg reports exactly 1.0 WITHOUT re-measuring
    (same config — a noisy re-measure would just launder timer jitter
    into a fake win/loss) and the provenance shows the tuner returning
    the default. The entry-level ``tuned_vs_default`` is the MINIMUM
    over legs — the regression gate bites on the worst knob, not an
    average that can hide one.

    On CPU the ratios measure the host (``tunnel_bound`` flags them);
    the search mechanics — default measured first, budget bound, warm
    cache answering with zero probes — are asserted here either way."""
    import shutil
    import tempfile

    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.data.chunks import GeneratorChunkSource
    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.models.tree import RandomForestClassifier
    from spark_rapids_ml_tpu.ops.streaming import streamed_suffstats
    from spark_rapids_ml_tpu.runtime import autotune, telemetry
    from spark_rapids_ml_tpu.serving import ServingRuntime

    @contextlib.contextmanager
    def env(**kv):
        old = {k: os.environ.get(k) for k in kv}
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        try:
            yield
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    cache_dir = os.environ.get("BENCH_AUTOTUNE_CACHE")
    tmp_cache = None
    if not cache_dir:
        tmp_cache = tempfile.mkdtemp(prefix="tpuml-autotune-bench-")
        cache_dir = tmp_cache
    reps = int(os.environ.get("BENCH_AUTOTUNE_REPS", 2))
    # the library default budget (2 s) is sized for in-situ micro-probes;
    # these legs dispatch whole fits per candidate, so give the search
    # room — it is still a hard wall-clock stop, just a bench-sized one
    budget_ms = float(os.environ.get("BENCH_AUTOTUNE_BUDGET_MS", 60_000))
    legs = {}
    t_total0 = time.perf_counter()

    def _timed(fn):
        """min-of-reps wall seconds (min: least-noise point estimate)."""
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    def _leg(name, knob, default_value, heuristic_key, candidates,
             run_default, run_tuned, measure, rows):
        """Shared leg harness: measure default, probe, measure tuned.

        ``run_tuned`` does one tuned pass and RETURNS the decision list
        that pass produced (fit reports for estimators, a collect()
        scope for direct calls) — the fit loop runs its own nested
        collector, so an outer collect() around an estimator fit sees
        nothing."""
        t_default = _timed(run_default)
        with env(TPUML_AUTOTUNE="on", TPUML_AUTOTUNE_CACHE=cache_dir):
            autotune.reset_autotune()
            decision = autotune.probe(
                knob, heuristic_key, candidates, measure,
                reps=reps, budget_ms=budget_ms,
            )
        if decision.value == default_value:
            t_tuned = t_default  # identical config: exactly 1.0
            ratio = 1.0
        else:
            with env(TPUML_AUTOTUNE="on", TPUML_AUTOTUNE_CACHE=cache_dir):
                autotune.reset_autotune()
                probes_before = _autotune_probe_count()
                t_tuned = None
                tuned_decisions = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    tuned_decisions = run_tuned()
                    dt = time.perf_counter() - t0
                    t_tuned = dt if t_tuned is None else min(t_tuned, dt)
                # warm-cache contract: the tuned run must answer from
                # the cache the probe just wrote — zero new searches
                if _autotune_probe_count() != probes_before:
                    raise RuntimeError(
                        f"{name}: tuned run probed on a warm cache"
                    )
                if not any(
                    d["knob"] == knob and d["provenance"] == "cache_hit"
                    for d in tuned_decisions
                ):
                    raise RuntimeError(
                        f"{name}: tuned run did not consult the cache "
                        f"(decisions: {tuned_decisions})"
                    )
            ratio = t_default / max(t_tuned, 1e-9)
        legs[name] = {
            "knob": knob,
            "default": default_value,
            "tuned": decision.value,
            "default_seconds": round(t_default, 4),
            "tuned_seconds": round(t_tuned, 4),
            "tuned_vs_default": round(ratio, 4),
            "probe_ms": round(decision.probe_ms or 0.0, 1),
            "candidates": len(candidates),
            "rows": rows,
        }
        return ratio

    def _autotune_probe_count():
        snap = telemetry.metrics_snapshot().get("autotune_probes_total")
        return sum(r["value"] for r in snap["series"]) if snap else 0

    # --- leg 1: rf tree batch (consult-only knob; bench is the prober) ---
    rng = np.random.default_rng(11)
    n_rf = int(os.environ.get("BENCH_AUTOTUNE_RF_ROWS", 4096))
    d_rf = 32
    X = rng.standard_normal((n_rf, d_rf)).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    df = DataFrame({"features": X, "label": y})
    n_trees = 8

    def rf_fit(width):
        with env(
            TPUML_AUTOTUNE=None,
            TPUML_RF_TREE_BATCH=(width if width is not None else "auto"),
        ):
            RandomForestClassifier(
                numTrees=n_trees, maxDepth=6, seed=3, num_workers=1
            ).fit(df)

    def rf_tuned():
        m = RandomForestClassifier(
            numTrees=n_trees, maxDepth=6, seed=3, num_workers=1
        ).fit(df)
        return (m._fit_report or {}).get("autotuned", [])

    rf_fit(None)  # warm the compile caches off the clock
    # the key + heuristic width exactly as the resolver derives them: a
    # cold tuned fit files a heuristic-provenance decision carrying both
    with env(TPUML_AUTOTUNE="on", TPUML_AUTOTUNE_CACHE=cache_dir):
        autotune.reset_autotune()
        cold = rf_tuned()
    rf_dec = next(d for d in cold if d["knob"] == "rf_tree_batch")
    rf_default = rf_dec["value"]
    group = n_trees  # single worker: the whole forest is one group
    widths = [rf_default] + [
        w for w in (1, 2, 4, 8) if group % w == 0 and w != rf_default
    ]

    def rf_measure(width):
        rf_fit(width)  # one compile per width rides the probe budget
        return _timed(lambda: rf_fit(width))

    r_rf = _leg(
        "rf", "rf_tree_batch", rf_default, rf_dec["key"], widths,
        lambda: rf_fit(None),
        rf_tuned,
        rf_measure, n_rf * n_trees,
    )

    # --- leg 2: pca_stream stage depth (consult-only; bench probes) ------
    n_dp = mesh.shape["dp"]
    chunk_rows = max(n_dp, (int(
        os.environ.get("BENCH_AUTOTUNE_STREAM_CHUNK", 8192)
    ) // n_dp) * n_dp)
    n_chunks = int(os.environ.get("BENCH_AUTOTUNE_STREAM_CHUNKS", 8))
    d_s = 64
    block = rng.standard_normal((chunk_rows, d_s), dtype=np.float32)

    def gen(start, count, seed):
        return block[:count], None

    def stream_run(depth):
        with env(
            TPUML_AUTOTUNE=None,
            TPUML_STREAM_STAGE_DEPTH=depth,
        ):
            src = GeneratorChunkSource(gen, n_chunks * chunk_rows, d_s)
            streamed_suffstats(
                src, mesh, chunk_rows, np.float32, with_y=False
            )

    def stream_tuned():
        # no env wrapper: runs under the caller's TPUML_AUTOTUNE=on so
        # the depth consult answers from the cache the probe wrote
        with autotune.collect() as ds:
            src = GeneratorChunkSource(gen, n_chunks * chunk_rows, d_s)
            streamed_suffstats(src, mesh, chunk_rows, np.float32, with_y=False)
        return ds

    stream_run(None)  # warm compile
    with env(TPUML_AUTOTUNE="on", TPUML_AUTOTUNE_CACHE=cache_dir):
        autotune.reset_autotune()
        cold = stream_tuned()
    sd_dec = next(d for d in cold if d["knob"] == "stream_stage_depth")
    sd_default = sd_dec["value"]
    depths = [sd_default] + [
        c for c in (0, 1, 2, 4) if c != sd_default
    ]

    r_stream = _leg(
        "pca_stream", "stream_stage_depth", sd_default, sd_dec["key"],
        depths,
        lambda: stream_run(None),
        stream_tuned,
        lambda c: _timed(lambda: stream_run(c)),
        n_chunks * chunk_rows,
    )

    # --- leg 3: serving batch window (consult-only; bench probes) --------
    n_sv, d_sv = 512, 16
    Xs = rng.standard_normal((n_sv, d_sv)).astype(np.float32)
    pca_model = PCA(k=4).fit(DataFrame({"features": Xs}))
    sizes = (1, 3, 8, 16)
    queries = [
        rng.standard_normal((s, d_sv)).astype(np.float32) for s in sizes
    ] * 8
    serve_rows = sum(q.shape[0] for q in queries)

    def serve_run(window):
        with env(
            TPUML_AUTOTUNE=None,
            TPUML_SERVE_BATCH_WINDOW_US=window,
        ):
            with ServingRuntime(
                batch_window_us=window, warmup=False
            ) as rt:
                rt.register("pca", pca_model)
                for q in queries:
                    rt.predict("pca", q, timeout=180)

    def serve_tuned():
        with autotune.collect() as ds:
            rt = ServingRuntime(warmup=False)
        with rt:
            rt.register("pca", pca_model)
            for q in queries:
                rt.predict("pca", q, timeout=180)
        return ds

    serve_run(None)  # warm compile
    with env(TPUML_AUTOTUNE="on", TPUML_AUTOTUNE_CACHE=cache_dir):
        autotune.reset_autotune()
        with autotune.collect() as cold:
            sv = ServingRuntime(warmup=False)
            sv.close()
    sv_dec = next(d for d in cold if d["knob"] == "serve_batch_window_us")
    sv_default = sv_dec["value"]
    windows = [sv_default] + [
        w for w in (0, 100, 500, 2000) if w != sv_default
    ]

    r_serving = _leg(
        "serving", "serve_batch_window_us", sv_default, sv_dec["key"],
        windows,
        lambda: serve_run(None),
        serve_tuned,
        lambda w: _timed(lambda: serve_run(w)),
        serve_rows,
    )

    if tmp_cache:
        shutil.rmtree(tmp_cache, ignore_errors=True)

    total_seconds = time.perf_counter() - t_total0
    ratios = [r_rf, r_stream, r_serving]
    # headline throughput: the tuned rf leg (rows x trees / tuned time);
    # baseline = the default config, so vs_baseline == the rf leg's ratio
    rf_leg = legs["rf"]
    return {
        "fit_seconds": rf_leg["tuned_seconds"],
        "samples_per_sec_per_chip": (
            rf_leg["rows"] / rf_leg["tuned_seconds"] / n_chips
        ),
        "baseline_samples_per_sec": (
            rf_leg["rows"] / rf_leg["default_seconds"] / n_chips
        ),
        "baseline_kind": "heuristic_default_config",
        "flops_model": float(n_rf) * d_rf * 6 * n_trees * 2,
        "tuned_vs_default": round(min(ratios), 4),
        "legs": legs,
        "total_seconds": round(total_seconds, 2),
        "budget_ms_per_search": budget_ms,
    }


def _probe_backend(
    attempts: int | None = None,
    probe_timeout: int | None = None,
    cooldown: int | None = None,
) -> bool:
    """Fail fast if the backend hangs at init (round-1 failure mode).

    A wedged TPU tunnel blocks *inside* ``make_c_api_client`` — uninterruptible
    from Python — so probe in a subprocess with a hard timeout before touching
    the backend in-process.  Skipped when pinned to CPU.

    A client killed while HOLDING the grant wedges the tunnel until lease
    expiry (observed >1 h); waiting clients queue harmlessly. The defaults
    (~5.5 min of patience) ride out short wedges while leaving budget for
    the CPU-fallback run; BENCH_PROBE_{ATTEMPTS,TIMEOUT,COOLDOWN} override.

    Returns True if the accelerator is reachable; False means the caller
    should fall back to CPU (a flagged CPU number beats no number at all).
    """
    import subprocess

    # env read at call time (import-time defaults would freeze overrides
    # set after import, and a malformed value would break the import itself)
    if attempts is None:
        attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", 3))
    if probe_timeout is None:
        probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", 75))
    if cooldown is None:
        cooldown = int(os.environ.get("BENCH_PROBE_COOLDOWN", 45))
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return True
    last = ""
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.devices())"],
                capture_output=True, text=True, timeout=probe_timeout,
            )
            if proc.returncode == 0:
                return True
            last = proc.stderr[-2000:]
        except subprocess.TimeoutExpired:
            last = f"backend init did not respond within {probe_timeout}s (hang in make_c_api_client)"
        print(f"[bench] backend probe attempt {attempt} failed: {last}", file=sys.stderr)
        if attempt + 1 < attempts:
            time.sleep(cooldown)
    print(
        "[bench] accelerator backend unreachable after "
        f"{attempts} probes; falling back to CPU (flagged in output). "
        f"Last error: {last}",
        file=sys.stderr,
    )
    return False


def main() -> None:
    global N_ROWS, CSIZE
    tpu_ok = _probe_backend()
    if not tpu_ok:
        pin_platform("cpu")
    import jax

    # persistent compile cache: the RF depth-13 program dominates compile
    # time; caching lets an in-round run warm the driver's capture run
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    devices = jax.devices()
    n_chips = len(devices)
    peak = _chip_peak_flops(devices[0])
    if devices[0].platform == "cpu" and "BENCH_ROWS" not in os.environ:
        # CPU fallback at the accelerator row count would blow any time
        # budget (kmeans k=1024 over millions of rows); scale down unless
        # the caller pinned a size explicitly
        N_ROWS = min(N_ROWS, 50_000)
        CSIZE = _csize(N_ROWS)
        global RF_ROWS, RF_TREES, RF_DEPTH, KNN_QUERIES, KNN_ITEMS, UMAP_ROWS
        global ANN_ROWS, ANN_QUERIES, GBT_ROWS, GBT_ROUNDS, GBT_DEPTH
        if "BENCH_UMAP_ROWS" not in os.environ:
            UMAP_ROWS = 2048
        if "BENCH_KNN_QUERIES" not in os.environ:
            KNN_QUERIES = 512
        if "BENCH_KNN_ITEMS" not in os.environ:
            KNN_ITEMS = 8192
        if "BENCH_ANN_ROWS" not in os.environ:
            ANN_ROWS = 8192
        if "BENCH_ANN_QUERIES" not in os.environ:
            ANN_QUERIES = 512
        if "BENCH_RF_ROWS" not in os.environ:
            RF_ROWS = 8192
        if "BENCH_RF_TREES" not in os.environ:
            RF_TREES = 4
        if "BENCH_RF_DEPTH" not in os.environ:
            RF_DEPTH = 8
        if "BENCH_GBT_ROWS" not in os.environ:
            GBT_ROWS = 8192
        if "BENCH_GBT_ROUNDS" not in os.environ:
            GBT_ROUNDS = 4
        if "BENCH_GBT_DEPTH" not in os.environ:
            GBT_DEPTH = 5
        print(
            f"[bench] cpu device: reducing N_ROWS to {N_ROWS}, "
            f"rf to {RF_TREES}x{RF_ROWS}x depth {RF_DEPTH} "
            "(set BENCH_ROWS / BENCH_RF_ROWS to override)",
            file=sys.stderr,
        )

    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(n_chips)

    # X-free entries run FIRST: umap and pca_stream never touch the
    # shared design matrix, and next to the resident ~12.3 GB X they
    # RESOURCE_EXHAUST the chip (observed round 4). Generation happens
    # lazily at the first entry that needs X — INSIDE that entry's
    # watchdog deadline, which the 1200 s default absorbs (~80 s gen).
    # Entries run on watchdog worker threads, so access is locked, the
    # triple is assigned atomically (an abandoned worker must never
    # expose a half-built dict), and a generation failure is cached so
    # later entries fail fast instead of re-running a doomed multi-
    # minute generation each.
    import threading

    _ds: dict = {}
    _ds_lock = threading.Lock()
    _ds_evt = threading.Event()

    def _X():
        # Claim-then-generate OUTSIDE the lock: the multi-minute generation
        # must not hold _ds_lock — if the watchdog abandons the generating
        # worker, later entries would block on the lock and trip their own
        # watchdogs too instead of failing fast; with the Event they wait
        # bounded-by-their-watchdog, and if the abandoned thread's
        # generation eventually completes they proceed normally.
        with _ds_lock:
            lead = not _ds.get("claimed")
            _ds["claimed"] = True
        if lead:
            try:
                # Generate the design matrix ON DEVICE (host gen +
                # device_put would pay the tunnel's ~30 MB/s: minutes for
                # gigabytes). Padded rows get random values and a zero
                # mask — kernels mask them out.
                out = _gen_dataset(mesh, N_ROWS, seed=0)
                with _ds_lock:
                    _ds["all"] = out
            except Exception as e:  # noqa: BLE001
                with _ds_lock:
                    _ds["err"] = repr(e)
            finally:
                _ds_evt.set()
        else:
            _ds_evt.wait()
        with _ds_lock:
            if "err" in _ds:
                raise RuntimeError(
                    f"dataset generation already failed: {_ds['err']}"
                )
            return _ds["all"]

    runs = {
        "umap": lambda: bench_umap(mesh, n_chips),
        "ann": lambda: bench_ann(mesh, n_chips),
        "pca_stream": lambda: bench_pca_stream(mesh, n_chips),
        "serving": lambda: bench_serving(mesh, n_chips),
        "router": lambda: bench_router(mesh, n_chips),
        "fit_sched": lambda: bench_fit_sched(mesh, n_chips),
        "lifecycle": lambda: bench_lifecycle(mesh, n_chips),
        "autotune": lambda: bench_autotune(mesh, n_chips),
        "pca": lambda: bench_pca(*_X()[:2], mesh, n_chips),
        "kmeans": lambda: bench_kmeans(*_X()[:2], mesh, n_chips),
        "logreg": lambda: bench_logreg(*_X(), mesh, n_chips),
        "logreg_multi": lambda: bench_logreg_multi(*_X(), mesh, n_chips),
        "linreg": lambda: bench_linreg(*_X(), mesh, n_chips),
        "rf": lambda: bench_rf(*_X(), mesh, n_chips),
        "gbt": lambda: bench_gbt(*_X(), mesh, n_chips),
        "knn": lambda: bench_knn(*_X()[:2], mesh, n_chips),
    }
    # BENCH_ONLY=rf,kmeans : run a subset (tuning loops); full runs only
    # for the recorded metric
    only = os.environ.get("BENCH_ONLY")
    if only:
        keep = {s.strip() for s in only.split(",") if s.strip()}
        unknown = keep - set(runs)
        if unknown:
            sys.exit(f"BENCH_ONLY names unknown entries: {sorted(unknown)}")
        if not keep:
            sys.exit(f"BENCH_ONLY={only!r} selects no entries")
        runs = {k: v for k, v in runs.items() if k in keep}
    from spark_rapids_ml_tpu.utils.profiling import trace

    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    results = {}
    watchdog_tripped = []
    meta = {
        "device": getattr(devices[0], "device_kind", "cpu"),
        "tpu_unreachable": not tpu_ok,
        # timings taken inside an active trace carry profiler overhead —
        # not comparable with unprofiled runs
        "profiled": bool(profile_dir),
        "n_chips": n_chips,
        "n_rows": N_ROWS,
        "n_cols": N_COLS,
    }
    # live references for the SIGTERM handler: an external timeout kill
    # mid-run still emits the entries that already finished
    _PARTIAL.update(
        results=results, meta=meta, tripped=watchdog_tripped, emitted=False
    )
    from spark_rapids_ml_tpu.runtime import counters as _res_counters
    from spark_rapids_ml_tpu.runtime import telemetry as _telemetry

    for name, fn in runs.items():
        for attempt in (0, 1):
            try:
                res_base = _res_counters.snapshot()
                tele_base = _telemetry.span_stats()
                # per-algo TensorBoard profile capture when requested
                with trace(
                    os.path.join(profile_dir, name) if profile_dir else None
                ):
                    res = _run_with_watchdog(name, fn, watchdog_tripped)
                # resilience-runtime provenance: robustness overhead must be
                # visible in the perf trajectory, and a clean run must prove
                # itself clean (both read 0 with no TPUML_* resilience env)
                res_delta = _res_counters.delta_since(res_base)
                res["retries"] = res_delta.get("retries", 0) + res_delta.get(
                    "chunk_halvings", 0
                )
                res["resumed_from"] = res_delta.get("resumed_from", 0)
                # span provenance when tracing is on: device seconds measured
                # by span fencing, and per-site span counts for this entry
                flops_measured = 0.0
                if _telemetry.enabled():
                    tele_now = _telemetry.span_stats()
                    dev = 0.0
                    spans = {}
                    for site, st in tele_now.items():
                        prev = tele_base.get(site, {})
                        dc = st["count"] - prev.get("count", 0)
                        if dc > 0:
                            spans[site] = dc
                            dev += st["device_seconds"] - prev.get(
                                "device_seconds", 0.0
                            )
                            flops_measured += st.get(
                                "flops_total", 0.0
                            ) - prev.get("flops_total", 0.0)
                    res["device_seconds"] = round(dev, 4)
                    res["spans"] = spans
                res["mfu"] = res["flops_model"] / (
                    res["fit_seconds"] * peak * n_chips
                )
                if flops_measured > 0:
                    # measured roofline position: XLA cost_analysis() FLOPs
                    # attributed to this entry's spans, replacing the
                    # hand-rolled flops_model estimate (kept as mfu_derived
                    # so trajectories across the swap stay comparable)
                    res["mfu_derived"] = round(res["mfu"], 4)
                    res["flops_measured"] = flops_measured
                    res["mfu"] = flops_measured / (
                        res["fit_seconds"] * peak * n_chips
                    )
                res["vs_baseline"] = (
                    res["samples_per_sec_per_chip"] / res["baseline_samples_per_sec"]
                )
                if "transform_baseline_samples_per_sec" in res:
                    res["transform_vs_baseline"] = (
                        res["transform_samples_per_sec_per_chip"]
                        / res["transform_baseline_samples_per_sec"]
                    )
                results[name] = res
                if devices[0].platform == "cpu" and "tunnel_bound" not in res:
                    # CPU-fallback numbers (probe failed, or the backend
                    # quietly initialized host-only) measure the host, not
                    # the chip: flag every entry so bench_regress compares
                    # rounds as skip:tunnel-bound instead of gating on
                    # host noise
                    res["tunnel_bound"] = True
                print(
                    f"[bench] {name}: {res['samples_per_sec_per_chip']:.3e} "
                    f"samples/sec/chip, mfu={res['mfu']:.3f}, "
                    f"vs_baseline={res['vs_baseline']:.2f}",
                    file=sys.stderr,
                )
                break
            except Exception as e:  # noqa: BLE001
                transient = "UNAVAILABLE" in str(e)
                print(
                    f"[bench] {name} attempt {attempt} failed"
                    f"{' (transient, will retry)' if transient and attempt == 0 else ''}:\n"
                    f"{traceback.format_exc()}",
                    file=sys.stderr,
                )
                if not (transient and attempt == 0):
                    break
                time.sleep(15)

    if not results:
        print("[bench] all algorithms failed; no metric to report", file=sys.stderr)
        if watchdog_tripped:
            # a parked worker thread can block interpreter teardown — see
            # the _hard_exit note below
            _hard_exit(1)
        sys.exit(1)

    # BENCH_REQUIRE_TRANSFORM=rf[,umap,...] — CI contract: the named
    # entries must have produced a transform_vs_baseline figure; a silent
    # fit-only result (transform path crashed, or an entry rename dropped
    # the metric) fails the run instead of shipping an artifact that
    # quietly lost the serving measurement.
    required = [
        s for s in os.environ.get("BENCH_REQUIRE_TRANSFORM", "").split(",") if s
    ]
    missing = [
        name
        for name in required
        if "transform_vs_baseline" not in results.get(name, {})
    ]
    if missing:
        print(
            f"[bench] BENCH_REQUIRE_TRANSFORM unmet: no transform_vs_baseline "
            f"for {missing} (have: {sorted(results)})",
            file=sys.stderr,
        )
        if watchdog_tripped:
            _hard_exit(1)
        sys.exit(1)

    # model-axis A/B columns for the mp-capable entries (subprocess probe;
    # skipped for subsets that exclude all four families)
    _merge_mp_ab(results)

    # flag BEFORE emitting: a SIGTERM landing mid-print must not re-enter
    # emission from the handler (interleaved/duplicate JSON lines)
    _PARTIAL["emitted"] = True
    _emit_line(results, meta, watchdog_tripped)
    if _telemetry.enabled():
        # Prometheus + JSON metric dump next to the trace files
        _telemetry.write_metrics()
    if watchdog_tripped:
        # a tripped watchdog means a worker thread is still parked inside
        # a device call that never returned; normal interpreter exit would
        # block on runtime teardown behind it, leaving this process alive
        # and holding the tunnel grant — the exact wedge the watchdog
        # exists to bound. Flush and leave.
        _hard_exit(0)


# model-axis A/B: fit the four mp-capable families (pca/linreg/kmeans/ann)
# at TPUML_MESH_MP unset vs =2 in a clean subprocess on 8 virtual CPU
# devices, and attach {mp1,mp2} fit seconds + the measured per-shard HBM
# bytes from _fit_report/_ann_report to the matching bench entries. A
# subprocess because the main bench holds the real backend (and its own
# mesh) — the probe must not flip TPUML_MESH_MP under live entries.
_MP_AB_CHILD = r"""
import json, os, time
import numpy as np

os.environ.setdefault("TPUML_ANN_GATE_ROWS", "1")

from sklearn.datasets import make_blobs

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors
from spark_rapids_ml_tpu.regression import LinearRegression

rows, d, k = 4096, 64, 8
rng = np.random.default_rng(0)
X, _ = make_blobs(n_samples=rows, n_features=d, centers=k, random_state=0)
X = X.astype(np.float32)
y = (X @ rng.normal(size=d)).astype(np.float32)
df = DataFrame({"features": X})
df_lab = DataFrame({"features": X, "label": y})
qdf = DataFrame({"features": X[:128]})


def one_pass():
    out = {}
    t0 = time.perf_counter()
    m = PCA(k=4).setInputCol("features").fit(df)
    out["pca"] = (time.perf_counter() - t0, dict(m._fit_report))
    t0 = time.perf_counter()
    m = LinearRegression(regParam=1e-3).fit(df_lab)
    out["linreg"] = (time.perf_counter() - t0, dict(m._fit_report))
    t0 = time.perf_counter()
    m = KMeans(k=k, maxIter=10, seed=0).fit(df)
    out["kmeans"] = (time.perf_counter() - t0, dict(m._fit_report))
    t0 = time.perf_counter()
    m = ApproximateNearestNeighbors(k=10, num_workers=1).fit(df)
    m.kneighbors(qdf)
    out["ann"] = (time.perf_counter() - t0, dict(m._ann_report))
    return out


os.environ.pop("TPUML_MESH_MP", None)
base = one_pass()
os.environ["TPUML_MESH_MP"] = "2"
sharded = one_pass()

bkeys = {
    "pca": "gram_shard_bytes",
    "linreg": "gram_shard_bytes",
    "kmeans": "centroid_shard_bytes",
    "ann": "index_shard_bytes",
}
# replicated model-axis bytes for the gram/centroid families are exact
# analytically (f32, d aligned, k % mp == 0); the IVF index has capacity
# padding so only its measured shard bytes are reported
full = {"pca": d * d * 4, "linreg": d * d * 4, "kmeans": k * d * 4}
rep = {}
for name, bkey in bkeys.items():
    t1, _ = base[name]
    t2, r2 = sharded[name]
    entry = {
        "mp_degree": int(r2.get("mp_degree", 1)),
        "mp1_fit_seconds": round(t1, 4),
        "mp2_fit_seconds": round(t2, 4),
        "shard_bytes_mp2": int(r2.get(bkey, 0)),
    }
    if name in full:
        entry["replicated_bytes"] = full[name]
    rep[name] = entry
print("MPAB " + json.dumps(rep))
"""


def _mp_ab_probe() -> dict:
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop("TPUML_MESH_MP", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _MP_AB_CHILD],
            capture_output=True,
            text=True,
            timeout=900,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except Exception as e:  # noqa: BLE001
        print(f"[bench] mp A/B probe failed to launch: {e!r}", file=sys.stderr)
        return {}
    for ln in proc.stdout.splitlines():
        if ln.startswith("MPAB "):
            try:
                return json.loads(ln[5:])
            except json.JSONDecodeError:
                break
    print(
        f"[bench] mp A/B probe produced no result (rc={proc.returncode}):\n"
        f"{proc.stderr[-2000:]}",
        file=sys.stderr,
    )
    return {}


def _merge_mp_ab(results) -> None:
    targets = [n for n in ("pca", "linreg", "kmeans", "ann") if n in results]
    if not targets or os.environ.get("BENCH_MP_AB", "1") == "0":
        return
    ab = _mp_ab_probe()
    for name in targets:
        if name in ab:
            results[name]["mp_degree"] = ab[name]["mp_degree"]
            results[name]["mp_ab"] = ab[name]


def _emit_line(results, meta, watchdog_tripped):
    """Assemble and print the one-line JSON metric. Pure-Python over
    already-fetched scalars — safe to call from the SIGTERM handler."""
    # tunnel-bound entries (host->device ingest via the remote tunnel)
    # measure the link, not the chip — keep them out of the geomean
    vs = [
        r["vs_baseline"]
        for r in results.values()
        if not r.get("tunnel_bound")
    ] or [r["vs_baseline"] for r in results.values()]
    geomean_vs = math.exp(sum(math.log(max(v, 1e-12)) for v in vs) / len(vs))
    if "pca" in results:
        head_name, headline = "pca", results["pca"]
    else:  # BENCH_ONLY subset without pca: label honestly
        head_name, headline = next(iter(results.items()))
    line = {
        "metric": f"{head_name}_fit_throughput",
        "value": round(headline["samples_per_sec_per_chip"], 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(headline["vs_baseline"], 3),
        "vs_baseline_geomean": round(geomean_vs, 3),
        **meta,
    }
    # provenance scalars each entry may carry (configuration that actually
    # ran — dtype fallbacks, tree counts, dispatch amortization)
    _extras = (
        "iters", "per_iter", "trees", "rows", "queries", "objective_dtype",
        "matmul_dtype", "inner_fits_per_dispatch", "ingest_gbps",
        "stream_gb", "overlapped_abandoned", "k_features",
        "device_math_seconds", "device_math_samples_per_sec",
        "ingest_seconds", "overlap_efficiency",
        "transform_seconds", "transform_engine",
        "transform_samples_per_sec_per_chip",
        "transform_vs_baseline", "samples_per_sec_per_chip_e2e",
        "trustworthiness", "baseline_kind", "baseline_inputs",
        "graph_seconds", "graph_engine", "graph_recall", "ann_nlist",
        "ann_nprobe", "build_seconds", "nlist", "nprobe", "recall",
        "init_seconds", "sgd_seconds", "epoch_ms",
        "sgd_engine", "retries", "resumed_from",
        "wire_dtype", "decode_seconds", "device_seconds", "spans",
        "mfu_derived", "flops_measured",
        "hist_strategy", "tree_batch", "seconds_per_level",
        "level_seconds", "rounds", "depth", "seconds_per_round",
        "gang_lanes", "solves_per_sec", "vs_sequential", "seq_fit_seconds",
        "p50_ms", "p99_ms", "qps_sweep", "window_sweep", "retrace_storms",
        "serve_vs_direct", "setup_fit_seconds", "warm_seconds", "requests",
        "p99_series_models", "capacity_qps", "overload_sweep",
        "overload_deadline_ms", "goodput_qps", "shed_frac",
        "fits", "fits_per_sec", "fit_p50_ms", "fit_p99_ms",
        "sched_occupancy", "arrival_sweep", "arrival_deadline_ms",
        "ops_scrape_ms", "serve_batch_fill",
        "mp_degree", "mp_ab",
        "replicas", "policy", "offered_qps", "aggregate_goodput_qps",
        "replica_scaling_efficiency", "fleet_p99_ms", "fleet_sweep",
        "swaps", "swap_ms", "swap_p99_ms", "swap_p99_delta_ms",
        "rollback_ms",
        "tuned_vs_default", "legs", "total_seconds", "budget_ms_per_search",
    )
    for name, r in results.items():
        line[name] = {
            "samples_per_sec_per_chip": round(r["samples_per_sec_per_chip"], 1),
            "fit_seconds": round(r["fit_seconds"], 4),
            "mfu": round(r["mfu"], 4),
            "vs_baseline": round(r["vs_baseline"], 3),
        }
        for k in _extras:
            if k in r:
                line[name][k] = r[k]
        if r.get("tunnel_bound"):
            line[name]["tunnel_bound"] = True
    if watchdog_tripped:
        line["watchdog_tripped"] = watchdog_tripped
    print(json.dumps(line))


class _BenchTimeout(RuntimeError):
    pass


def _hard_exit(code):
    """Flush and leave WITHOUT interpreter unwind: with a worker thread
    parked in a dead device call, normal exit blocks on runtime teardown
    (keeping the process alive holding the tunnel grant), and an unwind
    with a dispatch mid-flight aborts in teardown anyway (observed)."""
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


def _algo_deadline():
    raw = os.environ.get("BENCH_ALGO_TIMEOUT", "1200")
    try:
        return float(raw)
    except ValueError:
        # one clear config error, not N phantom per-algorithm failures
        sys.exit(f"BENCH_ALGO_TIMEOUT must be a number of seconds, got {raw!r}")


_ABANDONED = []  # threads of tripped entries; may wake and run later


def _run_with_watchdog(name, fn, tripped):
    """Run one bench entry on a worker thread with a deadline.

    A tunnel dispatch can hang forever client-side (observed: a compile
    fetch that never returned, eating an entire capture run). The worker
    is a daemon thread: on timeout the entry is abandoned (recorded in
    ``tripped``) and the loop moves on — later entries may still succeed
    if the backend recovers, and the final JSON line always prints.
    BENCH_ALGO_TIMEOUT=0 disables the deadline.

    An abandoned worker that UNBLOCKS later keeps issuing its entry's
    remaining device work until the entry finishes (a parked C call
    cannot be interrupted); its late result is discarded via the cancel
    flag. Entries that overlapped a live abandoned worker at START or
    END are flagged ``overlapped_abandoned`` (their timings shared the
    chip) — a worker that wakes and finishes strictly inside another
    entry's window can still evade the flag; treat entries after a trip
    with suspicion."""
    import threading

    deadline = _algo_deadline()
    if deadline <= 0:
        return fn()
    overlapped_at_start = any(a.is_alive() for a in _ABANDONED)
    box = {}
    cancelled = threading.Event()

    def work():
        try:
            res = fn()
            if not cancelled.is_set():
                box["res"] = res
        except BaseException as e:  # noqa: BLE001
            if not cancelled.is_set():
                box["err"] = e

    t = threading.Thread(target=work, name=f"bench-{name}", daemon=True)
    t.start()
    t.join(deadline)
    if t.is_alive():
        cancelled.set()
        tripped.append(name)
        _ABANDONED.append(t)
        raise _BenchTimeout(
            f"{name} exceeded BENCH_ALGO_TIMEOUT={deadline:.0f}s "
            "(device call never returned; entry abandoned)"
        )
    if "err" in box:
        err = box["err"]
        if not isinstance(err, Exception):
            # KeyboardInterrupt/SystemExit re-raised in the main thread
            # would escape the per-entry handler and unwind the whole run
            # (wedge-prone with parked workers); surface as a failure
            raise RuntimeError(f"{name} worker raised {type(err).__name__}: {err}")
        raise err
    res = box["res"]
    if overlapped_at_start or any(a.is_alive() for a in _ABANDONED):
        res["overlapped_abandoned"] = True
    return res


_PARTIAL = {"results": None, "meta": None, "tripped": None, "emitted": False}


def _install_signal_handlers():
    """External timeouts/cancellations send SIGTERM; the default handler
    kills the process mid-dispatch with nothing recorded. Instead: emit
    the JSON line for every entry that already finished (a partial
    capture beats none), then leave via os._exit — an interpreter unwind
    with a dispatch mid-flight aborts in runtime teardown anyway
    (observed), and a lingering process would keep holding the tunnel's
    exclusive chip grant (the round-2 wedge postmortem)."""
    import signal

    def _graceful(signum, frame):
        print(
            f"[bench] signal {signum}: emitting partial results and exiting",
            file=sys.stderr,
        )
        try:
            if (
                not _PARTIAL["emitted"]
                and _PARTIAL["results"]  # placed by main(), non-empty
            ):
                _PARTIAL["emitted"] = True
                _emit_line(
                    _PARTIAL["results"], _PARTIAL["meta"], _PARTIAL["tripped"]
                )
        except Exception:  # noqa: BLE001 — never mask the exit on a bug here
            traceback.print_exc()
        _hard_exit(128 + signum)

    def _interrupt(signum, frame):
        # Ctrl-C on a healthy run: default KeyboardInterrupt unwind (the
        # clean client teardown). After a watchdog trip the unwind would
        # block behind the parked worker — partial-emit and leave instead.
        if _PARTIAL["tripped"]:
            _graceful(signum, frame)
        raise KeyboardInterrupt

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _interrupt)
    except (ValueError, OSError):
        pass  # non-main thread or unsupported platform


if __name__ == "__main__":
    _install_signal_handlers()
    main()
