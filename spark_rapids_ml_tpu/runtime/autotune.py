"""Measured knob autotuner: shape-keyed search with a persistent cache.

ROADMAP item 5. The framework's ``auto`` resolvers (wire dtype, gang
width, tree batch, ANN nlist/nprobe, serve batch window, stream stage
depth) pick values from hand-derived cost models. This module closes
the loop with the hardware's actual answer — the classic empirical-
autotuning move (ATLAS / AutoTVM): measure a small candidate grid with
short dispatches of the real jitted work, keep the winner, and persist
it keyed by the workload shape so the search runs once per
(knob, shape, backend), not once per fit.

Three layers:

- **shape-keyed tuning cache** — one JSON file
  (``autotune-cache.json`` under ``TPUML_AUTOTUNE_CACHE``), written
  atomically (tmp + ``os.replace``) by rank 0 only, keyed by
  ``knob|signature`` where the signature buckets n/d/k to powers of
  two and pins dtype, backend + device kind, and the mesh's dp×mp.
  Corrupt / truncated / concurrently-rewritten files are tolerated:
  the tuner warns **once** and falls back to heuristics — a broken
  cache can slow a fit down, never break it.
- **probe engine** — :func:`probe` runs a successive-halving search
  over a per-knob candidate list. Every measurement executes under an
  ``autotune.probe.<knob>`` span carrying the inheritable
  ``warmup=True`` attr, so probe compiles count in ``xla_compiles``
  but are never scored as retrace storms (the serving-warmup
  contract). The search is wall-clock bounded by
  ``TPUML_AUTOTUNE_BUDGET_MS``; the heuristic default is always
  measured first, so a truncated search can never do worse than no
  tuner. Fitness is measured seconds (lower wins); when telemetry is
  recording, the probe site's roofline stats (mfu / achieved_gbps)
  ride into the cache entry as diagnostics.
- **resolver hook** — :func:`consult` (cache read) and :func:`tune`
  (consult-else-probe) are checked by every ``auto`` resolver before
  its static heuristic, gated by ``TPUML_AUTOTUNE=off|on|force``.
  ``off`` (the default) short-circuits before any cache or file I/O:
  no reads, no probes, bit-identical outputs. ``force`` re-probes
  even over an existing entry. Decisions (value + provenance
  ``cache_hit|probed|heuristic``) are collected per fit into
  ``_fit_report["autotuned"]`` and counted on the
  ``autotune_cache_hits/misses/probes_total`` + ``autotune_probe_ms``
  metrics.

See ``docs/autotune.md`` for the search strategy, shape-signature
semantics, and the measured tuned-vs-default table.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from . import envspec, lockwitness, telemetry

_LOGGER = logging.getLogger("spark_rapids_ml_tpu.autotune")

CACHE_FILENAME = "autotune-cache.json"
CACHE_VERSION = 1

# A candidate must beat the heuristic default by more than this margin
# to displace it: ties (and measurement noise) resolve toward the
# default, so "the default already wins" shows the tuner RETURNING the
# default instead of churning on noise.
DEFAULT_MARGIN = 0.02

_LOCK = lockwitness.make_lock("autotune.cache")
_FILE_LOCK = lockwitness.make_lock("autotune.file")

# in-memory cache state, all guarded by _LOCK:
#   path    — cache file the entries were loaded from (None = memory-only)
#   entries — {"knob|signature": entry dict}
#   loaded  — whether a load was attempted for `path`
_STATE: Dict[str, Any] = {"path": None, "entries": {}, "loaded": False}
_WARNED: set = set()

# per-fit decision collector (contextvar so concurrent scheduler fits
# on different threads collect independently)
_DECISIONS: contextvars.ContextVar[Optional[List[Dict[str, Any]]]] = (
    contextvars.ContextVar("tpuml_autotune_decisions", default=None)
)


# --------------------------------------------------------------------------
# mode gates
# --------------------------------------------------------------------------


def mode() -> str:
    """Validated ``TPUML_AUTOTUNE`` (off | on | force)."""
    return str(envspec.get("TPUML_AUTOTUNE"))


def active() -> bool:
    """True when the tuner may consult the cache or probe. The ``off``
    default returns False before any file or cache access — the
    defaults-inert gate every resolver checks first."""
    return mode() != "off"


def _budget_s() -> float:
    return float(envspec.get("TPUML_AUTOTUNE_BUDGET_MS")) / 1e3


# --------------------------------------------------------------------------
# shape signatures
# --------------------------------------------------------------------------


def _bucket(x: int) -> int:
    """Round up to the next power of two (0 stays 0): workloads whose
    sizes share a pow2 bucket share a tuning entry."""
    x = int(x)
    if x <= 0:
        return 0
    return 1 << (x - 1).bit_length()


def _backend_signature() -> str:
    """``platform:device_kind`` of the live backend; tuned winners never
    travel across device generations."""
    try:
        import jax

        dev = jax.devices()[0]
        kind = str(getattr(dev, "device_kind", dev.platform))
        return f"{dev.platform}:{kind}".replace(" ", "_")
    except Exception:
        return "unknown:unknown"


def _mesh_signature(mesh: Any) -> str:
    if mesh is None:
        return "1x1"
    try:
        dp = int(mesh.shape.get("dp", 1))
        mp = int(mesh.shape.get("mp", 1))
        return f"{dp}x{mp}"
    except Exception:
        return "1x1"


def shape_key(
    *,
    n: int = 0,
    d: int = 0,
    k: int = 0,
    dtype: Any = None,
    mesh: Any = None,
    **extra: Any,
) -> str:
    """Canonical workload-shape signature for one tuning decision.

    ``n``/``d``/``k`` (rows / features / output arity) are bucketed to
    powers of two; ``dtype``, backend + device kind, and the mesh's
    dp×mp are pinned exactly. ``extra`` key=value pairs (sorted) extend
    the signature for knob-specific shape inputs (e.g. tree depth)."""
    parts = [
        f"n={_bucket(n)}",
        f"d={_bucket(d)}",
        f"k={_bucket(k)}",
        f"dtype={str(dtype) if dtype is not None else 'na'}",
        f"backend={_backend_signature()}",
        f"mesh={_mesh_signature(mesh)}",
    ]
    for key in sorted(extra):
        parts.append(f"{key}={extra[key]}")
    return "|".join(parts)


# --------------------------------------------------------------------------
# persistent cache
# --------------------------------------------------------------------------


def _cache_path() -> Optional[str]:
    root = envspec.get("TPUML_AUTOTUNE_CACHE")
    if not root:
        return None
    return os.path.join(str(root), CACHE_FILENAME)


def _warn_once(tag: str, msg: str, *args: Any) -> None:
    with _LOCK:
        if tag in _WARNED:
            return
        _WARNED.add(tag)
    _LOGGER.warning(msg, *args)


def _read_entries(path: str) -> Dict[str, Any]:
    """Parse one cache file; corrupt/partial content degrades to {} with
    a loud-once warning (heuristics are always a safe answer)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        entries = doc.get("entries")
        if doc.get("version") != CACHE_VERSION or not isinstance(entries, dict):
            raise ValueError(
                f"version={doc.get('version')!r} entries={type(entries).__name__}"
            )
        return {
            key: e
            for key, e in entries.items()
            if isinstance(e, dict) and "value" in e
        }
    except FileNotFoundError:
        return {}
    except Exception as e:  # torn write, concurrent writer, hand edits…
        _warn_once(
            f"corrupt:{path}",
            "autotune cache %s is unreadable (%s); ignoring it and "
            "falling back to heuristics — delete or re-probe "
            "(TPUML_AUTOTUNE=force) to rebuild",
            path,
            e,
        )
        return {}


def _entries() -> Dict[str, Any]:
    """The live entry map, (re)loaded when the configured path changed."""
    path = _cache_path()
    with _LOCK:
        if _STATE["loaded"] and _STATE["path"] == path:
            return _STATE["entries"]
    loaded = _read_entries(path) if path else {}
    with _LOCK:
        # keep winners probed in-process before/without a cache file
        loaded.update(
            {
                key: e
                for key, e in _STATE["entries"].items()
                if key not in loaded
            }
        )
        _STATE.update(path=path, entries=loaded, loaded=True)
        return _STATE["entries"]


def _persist(entries: Dict[str, Any]) -> None:
    """Atomic rank-0 write (tmp + rename), merging the on-disk map so
    concurrent processes tuning different knobs both land."""
    path = _cache_path()
    if path is None:
        return
    if int(envspec.get("TPUML_PROC_ID")) != 0:
        return  # rank-0-written, like the trace/metric shard convention
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # the file lock makes read-merge-replace atomic against sibling
        # THREADS; sibling PROCESSES race benignly — os.replace keeps
        # the file valid and a lost entry re-probes next run
        with _FILE_LOCK:
            merged = _read_entries(path)
            merged.update(entries)
            doc = {"version": CACHE_VERSION, "entries": merged}
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
    except Exception as e:
        _warn_once(
            f"write:{path}",
            "autotune cache %s is unwritable (%s); tuned winners stay "
            "in-process for this run",
            path,
            e,
        )


def cache_key(knob: str, key: str) -> str:
    return f"{knob}|{key}"


def lookup(knob: str, key: str) -> Optional[Dict[str, Any]]:
    """The stored entry for (knob, key), or None. No metrics, no
    provenance — :func:`consult` is the resolver-facing read."""
    return _entries().get(cache_key(knob, key))


# --------------------------------------------------------------------------
# decisions + per-fit collection
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Decision:
    """One resolved knob: what the tuner answered and why."""

    knob: str
    key: str
    value: Any
    provenance: str  # cache_hit | probed | heuristic
    fitness_s: Optional[float] = None
    probe_ms: Optional[float] = None

    def as_report(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "knob": self.knob,
            "key": self.key,
            "value": self.value,
            "provenance": self.provenance,
        }
        if self.fitness_s is not None:
            out["fitness_s"] = round(self.fitness_s, 6)
        if self.probe_ms is not None:
            out["probe_ms"] = round(self.probe_ms, 3)
        return out


@contextlib.contextmanager
def collect() -> Iterator[List[Dict[str, Any]]]:
    """Collect every decision made on this context (fit) into a list —
    the ``_fit_report["autotuned"]`` provenance. Nested collectors see
    only their own scope."""
    sink: List[Dict[str, Any]] = []
    token = _DECISIONS.set(sink)
    try:
        yield sink
    finally:
        _DECISIONS.reset(token)


def _note(decision: Decision) -> None:
    sink = _DECISIONS.get()
    if sink is not None:
        sink.append(decision.as_report())


def record_heuristic(knob: str, key: str, value: Any) -> None:
    """A resolver fell through to its static heuristic while the tuner
    is active: file the provenance so ``autotuned`` reports are
    complete. No-op (and no allocation) when the tuner is off."""
    if not active():
        return
    _note(Decision(knob=knob, key=key, value=value, provenance="heuristic"))


# --------------------------------------------------------------------------
# resolver hooks
# --------------------------------------------------------------------------


def consult(knob: str, key: str) -> Optional[Any]:
    """Cache-read hook every ``auto`` resolver checks before its static
    heuristic. Returns the stored winner or None (miss / tuner off).
    ``force`` mode still answers from the cache here — re-probing is
    the job of the sites that CAN measure (:func:`tune`)."""
    if not active():
        return None
    entry = lookup(knob, key)
    if entry is None:
        telemetry.counter("autotune_cache_misses").inc(1, knob=knob)
        return None
    telemetry.counter("autotune_cache_hits").inc(1, knob=knob)
    _note(
        Decision(
            knob=knob,
            key=key,
            value=entry["value"],
            provenance="cache_hit",
            fitness_s=entry.get("fitness_s"),
        )
    )
    return entry["value"]


def store(
    knob: str,
    key: str,
    value: Any,
    *,
    fitness_s: Optional[float] = None,
    probe_ms: Optional[float] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> None:
    """Record (and persist, rank 0) a winner for (knob, key)."""
    entry: Dict[str, Any] = {
        "value": value,
        "provenance": "probed",
        "ts": time.time(),
    }
    if fitness_s is not None:
        entry["fitness_s"] = round(float(fitness_s), 6)
    if probe_ms is not None:
        entry["probe_ms"] = round(float(probe_ms), 3)
    if extra:
        entry.update(extra)
    entries = _entries()
    with _LOCK:
        entries[cache_key(knob, key)] = entry
    _persist({cache_key(knob, key): entry})


def probe(
    knob: str,
    key: str,
    candidates: Sequence[Any],
    measure: Callable[[Any], Optional[float]],
    *,
    reps: int = 2,
    budget_ms: Optional[float] = None,
    store_result: bool = True,
) -> Decision:
    """Successive-halving search over ``candidates`` scored by
    ``measure`` (seconds per probe dispatch, lower wins; None =
    infeasible, candidate dropped).

    ``candidates[0]`` is the heuristic default and is ALWAYS measured
    (before the budget gate), so the search can never return something
    worse-measured than the default. Each round measures the surviving
    candidates once and keeps the best half; ``reps`` bounds the round
    count, the wall-clock budget (``TPUML_AUTOTUNE_BUDGET_MS`` unless
    ``budget_ms`` overrides) stops new measurements mid-search. Every
    measurement runs under an ``autotune.probe.<knob>`` span with the
    inheritable ``warmup=True`` attr: probe compiles never score as
    retrace storms."""
    if not candidates:
        raise ValueError(f"autotune probe for {knob!r}: empty candidate list")
    budget = (_budget_s() if budget_ms is None else float(budget_ms) / 1e3)
    site = f"autotune.probe.{knob}"
    t_start = time.perf_counter()
    scores: Dict[int, float] = {}  # candidate index -> best seconds

    def _measure(idx: int) -> None:
        with telemetry.span(site, warmup=True, knob=knob, candidate=idx):
            try:
                s = measure(candidates[idx])
            except Exception as e:  # an infeasible candidate, not a crash
                _LOGGER.info(
                    "autotune %s: candidate %r failed the probe (%s); dropped",
                    knob, candidates[idx], e,
                )
                s = None
        if s is not None:
            prev = scores.get(idx)
            scores[idx] = float(s) if prev is None else min(prev, float(s))
        elif idx in scores:
            del scores[idx]

    _measure(0)  # the default: measured unconditionally
    alive = list(range(len(candidates)))
    for rnd in range(max(1, int(reps))):
        for idx in alive:
            if idx == 0 and rnd == 0:
                continue  # already measured above
            if time.perf_counter() - t_start > budget:
                break
            _measure(idx)
        measured = [i for i in alive if i in scores]
        if not measured:
            break
        measured.sort(key=lambda i: scores[i])
        alive = measured[: max(1, len(measured) // 2)]
        if len(alive) == 1 or time.perf_counter() - t_start > budget:
            break

    elapsed_ms = (time.perf_counter() - t_start) * 1e3
    best_idx = min(scores, key=lambda i: scores[i]) if scores else 0
    if (
        best_idx != 0
        and 0 in scores
        and scores[0] <= scores[best_idx] * (1.0 + DEFAULT_MARGIN)
    ):
        best_idx = 0  # within noise of the default: keep the default
    best_s = scores.get(best_idx)

    extra: Dict[str, Any] = {
        "candidates": len(candidates),
        "measured": len(scores),
        "default_s": round(scores[0], 6) if 0 in scores else None,
    }
    if telemetry.enabled():
        stats = telemetry.span_stats().get(site, {})
        for diag in ("mfu", "achieved_gbps", "bound"):
            if diag in stats:
                extra[diag] = stats[diag]

    telemetry.counter("autotune_probes_total").inc(1, knob=knob)
    telemetry.histogram("autotune_probe_ms").observe(elapsed_ms, knob=knob)
    decision = Decision(
        knob=knob,
        key=key,
        value=candidates[best_idx],
        provenance="probed",
        fitness_s=best_s,
        probe_ms=elapsed_ms,
    )
    if store_result:
        store(
            knob,
            key,
            decision.value,
            fitness_s=best_s,
            probe_ms=elapsed_ms,
            extra=extra,
        )
    _note(decision)
    _LOGGER.info(
        "autotune %s [%s]: %r in %.0f ms (%d/%d candidates measured%s)",
        knob, key, decision.value, elapsed_ms, len(scores), len(candidates),
        "" if best_s is None else f", best {best_s * 1e3:.2f} ms",
    )
    return decision


def tune(
    knob: str,
    key: str,
    candidates: Sequence[Any],
    measure: Callable[[Any], Optional[float]],
    *,
    reps: int = 2,
    budget_ms: Optional[float] = None,
) -> Optional[Any]:
    """The full resolver hook for sites that can measure in place:
    cache hit wins (``on``), otherwise probe + store; ``force``
    re-probes over any entry. Returns None when the tuner is off or
    the probe machinery fails — the caller's heuristic is always the
    fallback, a broken tuner never breaks a fit."""
    if not active():
        return None
    if mode() != "force":
        hit = consult(knob, key)
        if hit is not None:
            return hit
    else:
        # force still files the miss/hit count so warm-vs-cold is
        # observable, then re-probes regardless
        consult(knob, key)
    try:
        return probe(
            knob, key, candidates, measure, reps=reps, budget_ms=budget_ms
        ).value
    except Exception as e:
        _warn_once(
            f"probe:{knob}",
            "autotune probe for %s failed (%s); using the static "
            "heuristic for this and future shapes this run",
            knob,
            e,
        )
        return None


def reset_autotune() -> None:
    """Drop in-memory cache state and warn-once markers (test isolation).
    The on-disk cache file is untouched."""
    with _LOCK:
        _STATE.update(path=None, entries={}, loaded=False)
        _WARNED.clear()


def last_entries() -> Dict[str, Any]:
    """Snapshot of the in-memory entry map (diagnostics / tests)."""
    return dict(_entries())
