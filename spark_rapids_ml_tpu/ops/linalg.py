"""Shared dense linear-algebra kernels (jit-friendly global math).

These are the TPU-native equivalents of the reference's native CUDA kernels
(``/root/reference/jvm/native/src/rapidsml_jni.cu``): ``dgemmCov`` (Gram /
covariance, :109-127), ``calSVD`` (eigendecomposition of the covariance,
:215-268) and ``signFlip`` (deterministic eigenvector sign, :35-60).
Written as global math over row-sharded arrays: under ``jit`` XLA's SPMD
partitioner turns the row reductions into ``psum`` over the dp axis — the
role NCCL allreduce played for cuML.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import pallas_tpu_compiler_params, shard_map

from ..parallel.layout import LAYOUT
from ..parallel.mesh import DP_AXIS, MP_AXIS


def masked_mean(X: jax.Array, mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(column means, valid count) under a row-validity mask."""
    n = mask.sum()
    s = (X * mask[:, None]).sum(axis=0)
    return s / n, n


def mean_and_cov(X: jax.Array, mask: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Column mean and sample covariance (n-1 normalized) with masking.

    Computed as a single Gram pass: cov = (XᵀX - n·μμᵀ) / (n-1). The XᵀX
    contraction is the MXU hot loop; rows are dp-sharded so XLA emits one
    psum of the d×d partial Gram per device — identical communication
    volume to the reference's cuML allreduce of cov partials.
    """
    mean, n = masked_mean(X, mask)
    # Center BEFORE the Gram: the one-pass (X'X - n μμ')/(n-1) form
    # catastrophically cancels in f32 when |μ| >> σ. The subtraction fuses
    # into the matmul's operand read, so the extra pass is ~free on TPU.
    Xc = (X - mean[None, :]) * mask[:, None]
    cov = (Xc.T @ Xc) / (n - 1.0)
    return mean, cov, n

# Test hook (mirrors ops.logreg_pallas.FORCE_INTERPRET): when True,
# _pallas_gram_ok ignores the backend check and the kernels run through the
# Pallas interpreter, letting CPU CI exercise the real kernel branches
# inside the fit paths.
FORCE_INTERPRET = False


def row_chunk(i, csize: int, *arrays):
    """Rows ``[i*csize, (i+1)*csize)`` of each array, sliced along axis 0.

    The canonical chunk access for every chunked-scan kernel. Slice with
    ``dynamic_slice`` — do NOT ``lax.scan`` over a reshaped X: scan
    materializes its xs operand in the layout the loop body's matmuls
    prefer, which at lane-unaligned d (e.g. 3000) is a full transposed
    copy of the design matrix — doubling memory and OOMing resident fits
    that otherwise fit (observed at 1M×3000 on v5e). Slicing reads the
    original buffer in place.

    Use :func:`check_row_chunking` at kernel entry so a non-divisible row
    count fails loudly at trace time instead of silently dropping the tail.
    """
    return tuple(
        lax.dynamic_slice_in_dim(a, i * csize, csize, 0) for a in arrays
    )


def check_row_chunking(n_rows: int, csize: int) -> int:
    """Trace-time guard: rows must split into whole ``csize`` chunks
    (``shard_rows`` pads to this). Returns the chunk count."""
    if n_rows % csize != 0:
        raise ValueError(
            f"chunked kernel requires rows ({n_rows}) divisible by the "
            f"chunk size ({csize}); pad with shard_rows first"
        )
    return n_rows // csize


def _pallas_gram_tile(d: int) -> int:
    """Row-tile size for :func:`_shifted_gram_pallas`: ~16 MB of f32 per
    block (double-buffered by the pipeline) regardless of feature width,
    in VPU-sublane multiples. Measured on v5e at 12M×256: 8 MB blocks
    sustain ~670 GB/s, 16 MB ~715 GB/s (against ~735 achievable)."""
    return max(256, (4_194_304 // d) // 8 * 8)


def _shifted_gram_pallas(
    Xl: jax.Array,
    ml: jax.Array,
    mean_hat: jax.Array,
    *,
    tile: int | None = None,
    interpret: bool | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Pallas TPU kernel: one pass over local rows accumulating the shifted
    Gram ``Σ m·(x-μ̂)(x-μ̂)ᵀ`` and row-sum ``Σ m·(x-μ̂)``.

    XLA's fused ``(X-μ̂)ᵀ(X-μ̂)`` on a skinny (d≈256) design matrix sustains
    only ~half the chip's HBM bandwidth (measured 385 GB/s vs 735 GB/s
    achievable on v5e); this kernel streams row tiles HBM→VMEM with the
    d×d accumulator resident in VMEM and reaches ~715 GB/s. Rows beyond
    ``n`` (the last partial tile) are zeroed by an index-validity guard, so
    any row count works. f32 end to end.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = Xl.shape
    if tile is None:
        tile = _pallas_gram_tile(d)
    if interpret is None:
        interpret = FORCE_INTERPRET

    def kern(x_ref, m_ref, mu_ref, G_ref, s_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            G_ref[:] = jnp.zeros_like(G_ref)
            s_ref[:] = jnp.zeros_like(s_ref)

        # rows past n: the block is fetched beyond the array — zero them
        # explicitly (jnp.where, not multiply: OOB fill could be non-finite)
        row = i * tile + jax.lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
        valid = row < n
        x = jnp.where(valid, x_ref[:], 0.0)
        m = jnp.where(valid[:, 0], m_ref[:], 0.0)
        xs = (x - mu_ref[:]) * m[:, None]
        G_ref[:] += jax.lax.dot_general(
            xs, xs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        s_ref[:] += jnp.sum(xs, axis=0, keepdims=True)

    G, s = pl.pallas_call(
        kern,
        grid=(pl.cdiv(n, tile),),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((d, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
            # 16 MB double-buffered row tiles + centering temporaries + the
            # d×d accumulator (16 MB at d=2048) need headroom past the
            # 64 MB default (v5e has 128 MB VMEM)
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )(Xl, ml, mean_hat.reshape(1, d))
    return G, s[0]


def mp_gram_blocks(mesh, d: int) -> int:
    """Resolved model-axis degree for the blocked (feature-sharded) Gram
    accumulators: the mesh's mp extent when ``TPUML_MP_GRAM`` is on and the
    (padded) feature width splits evenly across it, else 1. Reads the env
    OUTSIDE jit — callers pass the result in as a static arg so retraces
    track the knob."""
    from ..runtime import envspec

    n_mp = int(mesh.shape.get(MP_AXIS, 1))
    if n_mp <= 1 or d % n_mp != 0:
        return 1
    if str(envspec.get("TPUML_MP_GRAM")) == "off":
        return 1
    return n_mp


def _pallas_gram_ok(d: int, dtype) -> bool:
    """Trace-time gate for the Pallas gram path: TPU backend, lane-aligned
    feature width, f32 (the kernel accumulates in f32; f64 fits keep the
    scan path). d is capped so the d×d VMEM accumulator plus double-buffered
    16 MB row blocks stay under the kernel's 100 MB VMEM budget — wider
    fits route to the scan path, which handles any d."""
    return (
        (jax.default_backend() == "tpu" or FORCE_INTERPRET)
        and d % 128 == 0
        and d <= 2048
        and dtype == jnp.float32
    )


def mean_and_cov_chunked(
    X: jax.Array, mask: jax.Array, mesh, csize: int, *, mp_blocks: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`mean_and_cov` with O(csize·d) temporaries and ~1 pass over X.

    The fused form relies on XLA folding the ``(X - μ)·mask`` centering into
    the Gram matmul's operand read; at double-digit-GB row counts the
    compiler can instead materialize the centered copy and OOM a chip whose
    HBM the resident matrix already half-fills. Here each device scans its
    rows in fixed ``csize`` chunks (same pattern as the KMeans Lloyd kernel)
    so peak extra memory is one chunk.

    Numerics: the naive one-pass ``(XᵀX - n·μμᵀ)/(n-1)`` catastrophically
    cancels in f32 when |μ| >> σ, and a full two-pass centering reads X
    twice from HBM. Instead the mean is *estimated* from each device's
    first chunk (one cheap psum), the main pass accumulates shifted sums
    ``Σ m·(x-μ̂)`` and Gram ``Σ m·(x-μ̂)(x-μ̂)ᵀ``, and a final rank-1
    correction re-centers exactly: with ``δ = mean - μ̂`` small, the
    cancellation term is harmless — two-pass stability at one-pass
    bandwidth. The estimate samples ``csize`` rows *strided across the
    whole device shard* (not the leading chunk), so data sorted or
    drifting in magnitude still yields δ = O(σ/√csize); only then does
    the f32 rank-1 correction stay clear of the cancellation the shift
    avoids. Partials combine with one ``psum`` over dp — the same
    communication volume as the fused form.

    Requires per-device rows divisible by ``csize`` (``shard_rows`` pads to
    this); rows must be sharded over dp only.

    With ``mp_blocks`` (resolve via :func:`mp_gram_blocks` — env is read
    outside jit) each device accumulates only its OWN column block of the
    shifted Gram, ``Σ m·(x-μ̂)(x-μ̂[blk])ᵀ`` of shape (d, d/mp): the d²
    accumulator — the structure that bounds feature width on a chip —
    shrinks by 1/mp, the SUMMA-style row-panel × column-panel product. The
    psum stays over dp only (mp peers hold *different* blocks, dp peers the
    same block) and the returned covariance is column-sharded over mp
    (``LAYOUT.cols()``). Per-element reduction order matches the full-width
    scan, so parity with the 1-D path is tight (see docs/mesh.md tolerance
    contract).
    """

    n_mp = int(mesh.shape.get(MP_AXIS, 1)) if mp_blocks else 1
    if n_mp > 1 and X.shape[1] % n_mp != 0:
        raise ValueError(
            f"blocked Gram requires feature width ({X.shape[1]}) divisible "
            f"by the mp extent ({n_mp}); gate with mp_gram_blocks"
        )
    bw = X.shape[1] // n_mp
    use_pallas = n_mp == 1 and _pallas_gram_ok(X.shape[1], X.dtype)

    def per_device(Xl, ml):
        d = Xl.shape[1]

        # mean estimate from rows strided across the whole shard — a
        # leading-chunk sample misestimates μ̂ on sorted/drifting data
        # and the rank-1 correction then reintroduces cancellation; the
        # mask weights out any padding rows the stride lands on
        e = min(csize, Xl.shape[0])
        stride = max(1, Xl.shape[0] // e)
        x0, m0 = Xl[::stride][:e], ml[::stride][:e]
        s0 = lax.psum((x0 * m0[:, None]).sum(axis=0), DP_AXIS)
        c0 = lax.psum(m0.sum(), DP_AXIS)
        mean_hat = s0 / jnp.maximum(c0, 1.0)

        if use_pallas:
            G, s = _shifted_gram_pallas(Xl, ml, mean_hat)
            cnt = ml.sum()
        else:
            nc = check_row_chunking(Xl.shape[0], csize)
            # column-block start of THIS device's Gram panel (0 at mp=1)
            c0 = lax.axis_index(MP_AXIS) * bw if n_mp > 1 else 0

            def body(i, carry):
                s, cnt, G = carry
                x, m = row_chunk(i, csize, Xl, ml)
                xs = (x - mean_hat[None, :]) * m[:, None]
                xb = (
                    lax.dynamic_slice_in_dim(xs, c0, bw, 1)
                    if n_mp > 1
                    else xs
                )
                return (s + xs.sum(axis=0), cnt + m.sum(), G + xs.T @ xb)

            s, cnt, G = lax.fori_loop(
                0,
                nc,
                body,
                (
                    jnp.zeros((d,), Xl.dtype),
                    jnp.zeros((), Xl.dtype),
                    jnp.zeros((d, bw), Xl.dtype),
                ),
            )
        n = lax.psum(cnt, DP_AXIS)
        s = lax.psum(s, DP_AXIS)
        G = lax.psum(G, DP_AXIS)
        delta = s / n                      # exact mean minus μ̂
        mean = mean_hat + delta
        if n_mp > 1:
            delta_b = lax.dynamic_slice_in_dim(
                delta, lax.axis_index(MP_AXIS) * bw, bw, 0
            )
            cov = (G - n * jnp.outer(delta, delta_b)) / (n - 1.0)
        else:
            cov = (G - n * jnp.outer(delta, delta)) / (n - 1.0)
        return mean, cov, n

    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(LAYOUT.rows(), LAYOUT.rows()),
        out_specs=(LAYOUT.replicated(), LAYOUT.cols() if n_mp > 1 else LAYOUT.replicated(), LAYOUT.replicated()),
        check_vma=False,
    )(X, mask)


def sign_flip(vectors: jax.Array) -> jax.Array:
    """Deterministic eigenvector sign convention: make the max-|.| entry of
    each column positive (reference thrust kernel ``signFlip``,
    ``rapidsml_jni.cu:35-60``; same convention as cuML / sklearn's svd_flip).

    ``vectors``: (d, k) — columns are eigenvectors.
    """
    idx = jnp.argmax(jnp.abs(vectors), axis=0)
    picked = vectors[idx, jnp.arange(vectors.shape[1])]
    signs = jnp.where(picked < 0, -1.0, 1.0).astype(vectors.dtype)
    return vectors * signs[None, :]


def topk_eigh(cov: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k eigenpairs of a symmetric matrix, descending, sign-fixed.

    Returns (eigenvalues (k,), eigenvectors (d, k)). The reference does this
    on one GPU via ``raft::linalg::eigDC`` + column/row reversal
    (``rapidsml_jni.cu:215-268``); here it runs replicated on every chip
    (d is small relative to HBM; replication avoids a gather).
    """
    evals, evecs = jnp.linalg.eigh(cov)        # ascending
    evals = evals[::-1][:k]
    evecs = evecs[:, ::-1][:, :k]
    return evals, sign_flip(evecs)


def standardize_moments(
    X: jax.Array, mask: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(mean, std (population), n) for feature standardization.

    Reference reimplements Spark's standardization with cupy partials +
    allGather (``classification.py:989-1038``); here one masked pass with
    XLA-inserted psum.
    """
    mean, n = masked_mean(X, mask)
    # centered second pass — same f32-cancellation rationale as mean_and_cov
    d = (X - mean[None, :]) * mask[:, None]
    var = (d * d).sum(axis=0) / n
    return mean, jnp.sqrt(var), n

def probe_pallas_lowering(cache: dict, key, compile_fn, name: str) -> bool:
    """Shared hardware-lowering probe for Pallas kernels.

    Interpret-mode tests exercise kernel bodies but not Mosaic lowering
    (round 3: a scalar VMEM store traced and interpreted fine yet failed
    only on the real chip, dropping KMeans from the bench capture). Before
    first real use of a config, ``compile_fn`` AOT-compiles a tiny
    instance; a rejection routes every caller to its XLA fallback instead
    of crashing the fit. Only genuine Mosaic rejections are negative-cached
    — a transient backend failure (RPC hiccup, HBM pressure) must not pin
    the process to the slower path forever.
    """
    if key not in cache:
        try:
            compile_fn()
            cache[key] = True
        except Exception as e:
            import logging

            logging.getLogger(name).warning(
                "%s Pallas kernel failed to lower for config %s; "
                "falling back to the XLA path: %s", name, key, e
            )
            msg = str(e)
            if "Mosaic" in msg or "Not implemented" in msg:
                cache[key] = False
            return False
    return cache[key]
