"""Pod-scale serving: a front-door router over N serving replicas.

One ``ServingRuntime`` is a single dispatcher thread over a single
process — a per-host ceiling no amount of micro-batching lifts. The
:class:`Router` lifts it sideways: N replicas (in-process *loopback*
``ServingRuntime`` instances first, subprocess workers behind the same
duck-typed handle interface for real multi-host runs) serve one model
fleet, and the router spreads the request stream over them with
per-replica queue-depth / EWMA-latency awareness.

Routing policy (``TPUML_ROUTER_POLICY``):

- ``p2c`` (default) — power-of-two-choices: two rotating candidates are
  scored by ``(EWMA-estimated wait, queue depth)`` and the better one
  takes the request. The classic result applies: sampling *two* queues
  drops the max load factor exponentially vs random/round-robin while
  costing O(2) probes per request instead of least-loaded's O(N) — the
  right trade once replica state lives behind an RPC.
- ``round_robin`` — rotation only, no load awareness (the baseline the
  bench compares against).
- ``least_loaded`` — score every replica on every request; optimal
  picks at O(N) probe cost per request.

The scoring, breakers, and typed sheds reuse the extracted
``runtime/admission.py`` primitives (:class:`ServiceEwma`,
:class:`CircuitBreaker`, ``Overloaded``/``ShuttingDown``) at the
routing layer, so a slow or breaker-open replica is **routed around,
not queued behind**: admission sheds at the picked replica spend the
reroute budget (``TPUML_ROUTER_REROUTES``) on the next candidates in
score order, dispatch *faults* trip the per-replica breaker
(``TPUML_ROUTER_BREAKER_FAILS``), and a request that no candidate
admits sheds with a typed ``Overloaded`` counted on
``router_shed_total{model,reason}``.

Fleet-wide SLOs: every replica's metric snapshot merges through
``telemetry.merge_metric_snapshots`` (reservoirs pooled, so the fleet
``serve_p99_ms`` p99 is *measured* over pooled samples, not
approximated from per-rank count/sum) — :meth:`Router.fleet_metrics`
is what ``/statusz``'s fleet section and ``runtime/slo.py`` read.

Explicit-construction only — building a :class:`Router` is the opt-in,
exactly like ``ServingRuntime``. No router object means no replica
threads, no ``router_*``/``fleet_*`` metric series, and bit-identical
single-runtime serving (test-asserted in ``tests/test_router.py``).
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..parallel.replica import ReplicaGroup, replica_groups
from ..runtime import envspec, lockwitness, opsplane, telemetry
from ..runtime.admission import (
    AdmissionError,
    CircuitBreaker,
    DeadlineExceeded,
    Overloaded,
    ServiceEwma,
    ShuttingDown,
)
from .registry import ModelReloadError, ResidentModel, SwapError
from .runtime import ServingRuntime

__all__ = [
    "Router",
    "LoopbackReplica",
    "SubprocessReplica",
    "POLICIES",
]

logger = logging.getLogger("spark_rapids_ml_tpu.serving.router")

POLICIES = ("p2c", "round_robin", "least_loaded")

# shed reasons the router can emit (closed label set, TPU008): the
# replica-level reasons propagate through; the last two are router-only
_ROUTER_SHED_REASONS = (
    "queue_full", "deadline_unmeetable", "breaker_open", "draining",
    "no_replicas",
)


# ---------------------------------------------------------------------------
# replica handles
# ---------------------------------------------------------------------------


class LoopbackReplica:
    """An in-process ``ServingRuntime`` behind the replica-handle
    interface — the transport for single-host pod-scale serving and for
    every test that needs determinism. Shares this process's telemetry
    registry, so :meth:`metrics_snapshot` returns None (the router's
    local snapshot already covers it)."""

    transport = "loopback"

    def __init__(
        self,
        rank: int,
        runtime: Optional[ServingRuntime] = None,
        **runtime_kwargs: Any,
    ) -> None:
        self.rank = int(rank)
        self.runtime = runtime or ServingRuntime(
            rank=self.rank, **runtime_kwargs
        )

    def register(self, name: str, model: Any) -> ResidentModel:
        return self.runtime.register(name, model)

    def load(self, name: str, path: str) -> ResidentModel:
        return self.runtime.load(name, path)

    def swap(self, name: str, path: str) -> ResidentModel:
        return self.runtime.swap(name, path=path)

    def predict_async(
        self, name: str, X: np.ndarray, deadline_ms: Optional[float] = None
    ) -> "Future[Dict[str, np.ndarray]]":
        return self.runtime.predict_async(name, X, deadline_ms=deadline_ms)

    def queue_depth(self) -> int:
        return self.runtime.queue_depth()

    def healthy(self) -> bool:
        rt = self.runtime
        if rt.is_closed() or rt.is_draining():
            return False
        return (not rt.dispatcher_started()) or rt.dispatcher_alive()

    def warmup_state(self) -> Dict[str, Any]:
        return self.runtime.registry.warmup_state()

    def metrics_snapshot(self) -> Optional[Dict[str, Any]]:
        return None  # shares the process-global telemetry registry

    def drain(self, timeout: float = 30.0) -> Dict[str, Any]:
        return self.runtime.drain(timeout)

    def close(self) -> None:
        self.runtime.close()


def _encode_error(e: BaseException) -> Dict[str, Any]:
    return {
        "type": type(e).__name__,
        "message": str(e),
        "reason": getattr(e, "reason", None),
    }


_ERROR_TYPES = {
    "DeadlineExceeded": DeadlineExceeded,
    "ShuttingDown": ShuttingDown,
    "AdmissionError": AdmissionError,
    "SwapError": SwapError,
    "ModelReloadError": ModelReloadError,
    "KeyError": KeyError,
    "ValueError": ValueError,
}


def _revive_error(d: Dict[str, Any]) -> BaseException:
    """Rebuild a worker-side exception as its typed parent-side twin so
    router reroute/breaker logic treats subprocess sheds exactly like
    loopback sheds."""
    t, msg = d.get("type", "RuntimeError"), d.get("message", "")
    if t == "Overloaded":
        return Overloaded(msg, reason=d.get("reason") or "queue_full")
    return _ERROR_TYPES.get(t, RuntimeError)(msg)


def _read_exact(f: Any, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = f.read(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class SubprocessReplica:
    """A serving replica in its own OS process (its own GIL, its own
    dispatcher, its own device client), spoken to over a length-prefixed
    pickle protocol on stdin/stdout (``serving/_replica_worker.py`` is
    the worker side). Same handle interface as :class:`LoopbackReplica`
    with two deltas the router already tolerates: admission sheds
    surface on the returned future (not synchronously), and
    ``queue_depth`` is the in-flight RPC count (a probe-free proxy)."""

    transport = "subprocess"

    def __init__(
        self,
        rank: int,
        env: Optional[Dict[str, str]] = None,
        start_timeout_s: float = 120.0,
        rpc_timeout_s: float = 120.0,
    ) -> None:
        self.rank = int(rank)
        self._rpc_timeout_s = float(rpc_timeout_s)
        penv = dict(os.environ)
        penv["TPUML_REPLICA_RANK"] = str(self.rank)
        penv.update(env or {})
        self._proc = subprocess.Popen(
            [
                sys.executable, "-u", "-m",
                "spark_rapids_ml_tpu.serving._replica_worker",
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            env=penv,
        )
        self._pending: Dict[int, "Future[Any]"] = {}
        self._plock = lockwitness.make_lock("router.replica_proc")
        self._wlock = lockwitness.make_lock("router.replica_wire")
        self._next_id = 0
        self._closed = False
        self._hello: "Future[Dict[str, Any]]" = Future()
        self._reader = threading.Thread(
            target=self._read_loop,
            name=f"tpuml-replica-r{self.rank}-reader",
            daemon=True,
        )
        self._reader.start()
        # the worker's hello frame doubles as the readiness barrier:
        # once it arrives the runtime on the far side is constructed
        self._hello.result(start_timeout_s)

    # -- protocol ----------------------------------------------------------
    def _submit(self, op: str, **kw: Any) -> "Future[Any]":
        if self._closed:
            raise ShuttingDown(
                f"subprocess replica r{self.rank} is closed"
            )
        if self._proc.poll() is not None:
            raise RuntimeError(
                f"subprocess replica r{self.rank} exited "
                f"(rc={self._proc.returncode})"
            )
        with self._plock:
            rid = self._next_id
            self._next_id += 1
            fut: "Future[Any]" = Future()
            self._pending[rid] = fut
        payload = pickle.dumps(
            {"id": rid, "op": op, **kw}, protocol=pickle.HIGHEST_PROTOCOL
        )
        try:
            with self._wlock:
                self._proc.stdin.write(struct.pack("!I", len(payload)))
                self._proc.stdin.write(payload)
                self._proc.stdin.flush()
        except Exception as e:
            with self._plock:
                self._pending.pop(rid, None)
            raise RuntimeError(
                f"subprocess replica r{self.rank}: pipe write failed"
            ) from e
        return fut

    def _call(
        self, op: str, rpc_timeout: Optional[float] = None, **kw: Any
    ) -> Any:
        return self._submit(op, **kw).result(
            self._rpc_timeout_s if rpc_timeout is None else rpc_timeout
        )

    def _read_loop(self) -> None:
        out = self._proc.stdout
        while True:
            header = _read_exact(out, 4)
            if header is None:
                break
            (ln,) = struct.unpack("!I", header)
            body = _read_exact(out, ln)
            if body is None:
                break
            try:
                msg = pickle.loads(body)
            except Exception:
                break
            rid = msg.get("id")
            if rid == -1:
                if not self._hello.done():
                    self._hello.set_result(msg.get("value"))
                continue
            with self._plock:
                fut = self._pending.pop(rid, None)
            if fut is None or fut.done():
                continue
            if msg.get("ok"):
                fut.set_result(msg.get("value"))
            else:
                fut.set_exception(_revive_error(msg.get("error") or {}))
        # EOF: the worker died (or closed) — every outstanding future
        # resolves now; a router upstream counts these as dispatch
        # faults and trips the replica's breaker
        exc = RuntimeError(
            f"subprocess replica r{self.rank} exited "
            f"(rc={self._proc.poll()})"
        )
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        if not self._hello.done():
            self._hello.set_exception(exc)
        for fut in pending:
            if not fut.done():
                fut.set_exception(exc)

    # -- replica-handle interface ------------------------------------------
    def register(self, name: str, model: Any) -> None:
        raise NotImplementedError(
            "subprocess replicas replicate from persisted models on a "
            "shared path; persist the model and use load(name, path)"
        )

    def load(self, name: str, path: str) -> Dict[str, Any]:
        return self._call("load", name=name, path=path)

    def swap(self, name: str, path: str) -> Dict[str, Any]:
        return self._call("swap", name=name, path=path)

    def predict_async(
        self, name: str, X: np.ndarray, deadline_ms: Optional[float] = None
    ) -> "Future[Dict[str, np.ndarray]]":
        return self._submit(
            "predict",
            name=name,
            X=np.ascontiguousarray(X),
            deadline_ms=deadline_ms,
        )

    def queue_depth(self) -> int:
        with self._plock:
            return len(self._pending)

    def healthy(self) -> bool:
        return not self._closed and self._proc.poll() is None

    def warmup_state(self) -> Dict[str, Any]:
        return self._call("warmup_state")

    def metrics_snapshot(self) -> Optional[Dict[str, Any]]:
        return self._call("metrics")

    def drain(self, timeout: float = 30.0) -> Dict[str, Any]:
        return self._call(
            "drain", rpc_timeout=timeout + 10.0, timeout_s=timeout
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self._proc.poll() is None:
                self._submit_close_best_effort()
                self._proc.wait(timeout=10.0)
        except Exception:
            pass
        if self._proc.poll() is None:
            self._proc.kill()

    def _submit_close_best_effort(self) -> None:
        payload = pickle.dumps(
            {"id": -2, "op": "close"}, protocol=pickle.HIGHEST_PROTOCOL
        )
        try:
            with self._wlock:
                self._proc.stdin.write(struct.pack("!I", len(payload)))
                self._proc.stdin.write(payload)
                self._proc.stdin.flush()
                self._proc.stdin.close()
        except Exception:
            pass

    def kill(self) -> None:
        """Hard-kill the worker (the CI chaos smoke: one replica dies
        mid-stream; the fleet's goodput must continue)."""
        self._closed = True
        try:
            self._proc.kill()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


class Router:
    """Front door of a serving replica fleet. See the module docstring
    for policy and shed semantics.

    ``replicas`` is either an integer (build that many loopback
    replicas, ranks 0..N-1; default ``TPUML_ROUTER_REPLICAS``) or an
    explicit sequence of replica handles (anything duck-typing
    :class:`LoopbackReplica`). ``runtime_kwargs`` forward to each
    built loopback replica's ``ServingRuntime``.
    """

    def __init__(
        self,
        replicas: Union[int, Sequence[Any], None] = None,
        policy: Optional[str] = None,
        breaker_fails: Optional[int] = None,
        breaker_cooldown_ms: Optional[float] = None,
        reroutes: Optional[int] = None,
        runtime_kwargs: Optional[Dict[str, Any]] = None,
    ) -> None:
        if replicas is None:
            replicas = int(envspec.get("TPUML_ROUTER_REPLICAS"))
        if isinstance(replicas, int):
            if replicas < 1:
                raise ValueError(f"need >= 1 replica, got {replicas}")
            kw = dict(runtime_kwargs or {})
            self.replicas: List[Any] = [
                LoopbackReplica(rank=i, **kw) for i in range(replicas)
            ]
        else:
            self.replicas = list(replicas)
            if not self.replicas:
                raise ValueError("need >= 1 replica handle")
        self.policy = str(
            policy if policy is not None else envspec.get("TPUML_ROUTER_POLICY")
        )
        if self.policy not in POLICIES:
            raise ValueError(
                f"unknown router policy {self.policy!r}; expected one of "
                f"{POLICIES}"
            )
        fails = int(
            envspec.get("TPUML_ROUTER_BREAKER_FAILS")
            if breaker_fails is None else breaker_fails
        )
        cooldown_ms = float(
            envspec.get("TPUML_ROUTER_BREAKER_COOLDOWN_MS")
            if breaker_cooldown_ms is None else breaker_cooldown_ms
        )
        self.reroutes = int(
            envspec.get("TPUML_ROUTER_REROUTES")
            if reroutes is None else reroutes
        )
        self._ewma = ServiceEwma()
        self._breakers: Dict[int, CircuitBreaker] = {}
        for i in range(len(self.replicas)):
            self._breakers[i] = CircuitBreaker(
                str(i), fails, cooldown_ms / 1e3,
                on_state=(
                    lambda state, i=i: telemetry.gauge(
                        "router_breaker_state"
                    ).set(state, replica=str(i))
                ),
            )
        # rotation counter behind round_robin and the p2c candidate
        # pair — deterministic (TPU004: no sampling randomness; a
        # rotating pair covers all replicas like a random pair does in
        # expectation, without making tests flaky)
        self._seq = 0
        self._lock = lockwitness.make_lock("router.fleet")
        self._closed = False
        telemetry.gauge("fleet_replicas").set(len(self.replicas))
        opsplane.track_router(self)
        logger.info(
            "router: %d replica(s), policy=%s, breaker_fails=%d, "
            "reroutes=%d",
            len(self.replicas), self.policy, fails, self.reroutes,
        )

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for rep in self.replicas:
            try:
                rep.close()
            except Exception:
                logger.exception("router: replica close failed")

    def drain(self, timeout: float = 30.0) -> Dict[str, Any]:
        """Drain every replica (stop admission, flush in-flight, then
        close); resolves every outstanding future fleet-wide. The
        timeout bounds the whole fleet, not each replica."""
        with self._lock:
            already = self._closed
            self._closed = True
        if already:
            return {"drained": True, "aborted": 0, "replicas": []}
        deadline = time.monotonic() + max(0.0, float(timeout))
        per: List[Dict[str, Any]] = []
        for rep in self.replicas:
            try:
                per.append(
                    rep.drain(max(0.1, deadline - time.monotonic()))
                )
            except Exception as e:
                per.append({"drained": False, "aborted": 0, "error": str(e)})
        return {
            "drained": all(bool(p.get("drained")) for p in per),
            "aborted": sum(int(p.get("aborted", 0)) for p in per),
            "replicas": per,
        }

    def is_closed(self) -> bool:
        return self._closed

    # -- registry replication ----------------------------------------------
    def register(self, name: str, model: Any) -> List[Any]:
        """Fan an in-memory model out to every replica (loopback only;
        subprocess replicas replicate from a shared persisted path via
        :meth:`load`)."""
        return [rep.register(name, model) for rep in self.replicas]

    def load(self, name: str, path: str) -> List[Any]:
        """Replicate one persisted model onto every replica from the
        shared ``path`` — each replica pins + warms its own copy and
        reports residency per rank (:meth:`fleet_warmup_state`)."""
        return [rep.load(name, path) for rep in self.replicas]

    def swap(self, name: str, path: str) -> List[Any]:
        """Fleet-wide ROLLING hot-swap from a shared persisted path:
        replicas flip sequentially, each staging + warming vN+1 beside
        its live vN before its own atomic flip, so at every instant
        each replica serves exactly one consistent version and the
        fleet as a whole keeps full capacity (one replica warms while
        the others serve). A replica failure halts the roll with a
        typed :class:`SwapError` naming the rank — flipped replicas
        keep vN+1, the failed and remaining ranks keep vN serving
        (the registry-level invariant: a failed swap never unseats the
        prior version). Mixed-version fleets are legal mid-roll; both
        versions answer identically-routed traffic until the roll
        completes or the operator re-rolls."""
        results: List[Any] = []
        for i, rep in enumerate(self.replicas):
            try:
                results.append(rep.swap(name, path))
            except Exception as e:
                raise SwapError(
                    f"fleet swap of {name!r} halted at replica {i}: "
                    f"{len(results)}/{len(self.replicas)} replicas "
                    f"flipped, ranks {i}..{len(self.replicas) - 1} keep "
                    f"the prior version serving: {e}",
                    stage=getattr(e, "stage", "swap"),
                ) from e
        return results

    def fleet_versions(self, name: str) -> List[Optional[int]]:
        """The resident version of ``name`` per replica (None where not
        resident) — mid-roll this shows the vN/vN+1 frontier."""
        out: List[Optional[int]] = []
        for rep in self.replicas:
            try:
                models = rep.warmup_state().get("models", {})
                entry = models.get(name) or {}
                v = entry.get("version")
                out.append(None if v is None else int(v))
            except Exception:
                out.append(None)
        return out

    # -- picking -----------------------------------------------------------
    def _healthy_index(self, i: int) -> bool:
        try:
            return bool(self.replicas[i].healthy())
        except Exception:
            return False

    def _score(self, i: int) -> Tuple[float, int, int]:
        """Replica load score, lower is better: (EWMA-estimated wait
        behind the current depth, raw depth, index). A replica with no
        latency history scores wait 0 — new capacity gets probed."""
        try:
            depth = int(self.replicas[i].queue_depth())
        except Exception:
            return (float("inf"), 1 << 30, i)
        wait = self._ewma.estimated_wait_s(str(i), depth)
        return (0.0 if wait is None else wait, depth, i)

    def _order(self, healthy: List[int]) -> List[int]:
        """Candidate replicas in try-order for one request (first is
        the pick; the rest absorb the reroute budget)."""
        with self._lock:
            c = self._seq
            self._seq += 1
        n = len(healthy)
        if n == 1 or self.policy == "round_robin":
            k = c % n
            return healthy[k:] + healthy[:k]
        if self.policy == "least_loaded":
            return sorted(healthy, key=self._score)
        # p2c: two rotating candidates, better-scored first; remaining
        # replicas trail in index order as the reroute fallback chain
        a, b = healthy[c % n], healthy[(c + 1) % n]
        if self._score(b) < self._score(a):
            a, b = b, a
        return [a, b] + [i for i in healthy if i not in (a, b)]

    # -- request surface ---------------------------------------------------
    def predict_async(
        self,
        name: str,
        X: np.ndarray,
        deadline_ms: Optional[float] = None,
    ) -> "Future[Dict[str, np.ndarray]]":
        """Route one request to a replica; same future contract as
        ``ServingRuntime.predict_async``. Typed sheds only: admission
        rejections at the picked replica spend the reroute budget on
        the next candidates; a request no candidate admits raises
        ``Overloaded`` (counted on ``router_shed_total``)."""
        if self._closed:
            raise ShuttingDown("Router is closed")
        telemetry.counter("router_requests_total").inc(1, model=name)
        healthy = [
            i for i in range(len(self.replicas)) if self._healthy_index(i)
        ]
        if not healthy:
            self._shed(name, "no_replicas", "no healthy replica in the fleet")
        order = self._order(healthy)
        budget = 1 + max(0, self.reroutes)
        tried = 0
        last: Optional[AdmissionError] = None
        for i in order:
            if tried >= budget:
                break
            if not self._breakers[i].allow():
                continue  # breaker-open: routed around, no budget spent
            tried += 1
            rep = self.replicas[i]
            try:
                telemetry.gauge("router_replica_depth").set(
                    rep.queue_depth(), replica=str(i)
                )
            except Exception:
                pass
            t0 = time.perf_counter()
            try:
                fut = rep.predict_async(name, X, deadline_ms=deadline_ms)
            except AdmissionError as e:
                last = e  # replica shed at admission: spend the budget
                continue
            except (KeyError, ValueError):
                raise  # caller bug (unknown model, bad shape) — every
                # replica would answer the same; don't burn breakers
            except Exception:
                self._breakers[i].record_failure()
                logger.exception(
                    "router: dispatch to replica %d faulted", i
                )
                continue
            telemetry.counter("router_picks_total").inc(1, replica=str(i))
            self._observe(fut, i, t0)
            return fut
        if last is None:
            reason = "breaker_open"
            msg = (
                f"all {len(order)} healthy replica(s) have open router "
                f"breakers or faulted at dispatch"
            )
        elif isinstance(last, ShuttingDown):
            reason, msg = "draining", str(last)
        elif isinstance(last, DeadlineExceeded):
            reason, msg = "deadline_unmeetable", str(last)
        else:
            reason = getattr(last, "reason", "queue_full")
            msg = str(last)
        self._shed(name, reason, msg)

    def predict(
        self,
        name: str,
        X: np.ndarray,
        timeout: Optional[float] = None,
        deadline_ms: Optional[float] = None,
    ) -> Dict[str, np.ndarray]:
        return self.predict_async(name, X, deadline_ms=deadline_ms).result(
            timeout
        )

    def _shed(self, name: str, reason: str, msg: str) -> None:
        telemetry.counter("router_shed_total").inc(
            1, model=name, reason=reason
        )
        raise Overloaded(f"router: {msg}", reason=reason)

    def _observe(self, fut: "Future[Any]", i: int, t0: float) -> None:
        """Fold the request's outcome into the replica's routing state:
        success and replica-side sheds update the EWMA (a shed arrives
        late — exactly the signal to steer away from); only dispatch
        *faults* count against the breaker."""
        breaker = self._breakers[i]
        key = str(i)

        def _done(f: "Future[Any]") -> None:
            dt = time.perf_counter() - t0
            if f.cancelled():
                return
            exc = f.exception()
            if exc is None:
                self._ewma.note(key, dt, 1)
                breaker.record_success()
            elif isinstance(exc, AdmissionError):
                self._ewma.note(key, dt, 1)
            else:
                breaker.record_failure()

        fut.add_done_callback(_done)

    # -- fleet views (ops plane / SLOs) ------------------------------------
    def healthy_count(self) -> int:
        return sum(
            1 for i in range(len(self.replicas)) if self._healthy_index(i)
        )

    def replica_states(self) -> List[Dict[str, Any]]:
        out = []
        for i, rep in enumerate(self.replicas):
            try:
                depth: Optional[int] = int(rep.queue_depth())
            except Exception:
                depth = None
            out.append(
                {
                    "replica": i,
                    "rank": getattr(rep, "rank", i),
                    "transport": getattr(rep, "transport", "unknown"),
                    "healthy": self._healthy_index(i),
                    "breaker": self._breakers[i].state_name(),
                    "queue_depth": depth,
                }
            )
        return out

    def groups(self, mp: int = 1) -> List[ReplicaGroup]:
        """The fleet's rank layout as replica groups (``mp`` ranks per
        replica under model-axis sharding)."""
        return replica_groups(len(self.replicas) * max(1, int(mp)), mp)

    def replica_snapshots(self) -> List[Dict[str, Any]]:
        """Metric snapshots of replicas that do NOT share this
        process's telemetry registry (loopback handles return None and
        are covered by the local snapshot)."""
        snaps = []
        for rep in self.replicas:
            try:
                s = rep.metrics_snapshot()
            except Exception:
                s = None
            if s:
                snaps.append(s)
        return snaps

    def fleet_metrics(self) -> Dict[str, Any]:
        """The fleet-wide merged metric snapshot: local process +
        every out-of-process replica, reservoirs pooled so merged
        histogram quantiles are measured (`serve_p99_ms` p99 over the
        pooled samples), counters summed, gauges maxed."""
        snaps = [telemetry.metrics_snapshot()] + self.replica_snapshots()
        return telemetry.merge_metric_snapshots(snaps)

    def fleet_p99_ms(self) -> Dict[str, float]:
        """Measured fleet-wide serve p99 per model, from the merged
        reservoirs (empty until something has served)."""
        out: Dict[str, float] = {}
        entry = self.fleet_metrics().get("serve_p99_ms") or {}
        for s in entry.get("series", []):
            if "p99" in s:
                out[s.get("labels", {}).get("model", "")] = float(s["p99"])
        return out

    def fleet_warmup_state(self) -> Dict[str, Any]:
        """Residency/readiness per rank, rolled up: ``ready`` iff every
        replica's registry reports ready."""
        reps: List[Dict[str, Any]] = []
        for rep in self.replicas:
            try:
                reps.append(rep.warmup_state())
            except Exception as e:
                reps.append({"ready": False, "error": str(e)})
        return {
            "ready": bool(reps) and all(r.get("ready") for r in reps),
            "replicas": reps,
        }
