"""Shared admission-control primitives: typed errors, EWMA service-time
model, and the consecutive-failure circuit breaker.

Both load-facing planes gate work the same way — the serving dispatcher
(``serving/admission.py``, PR 14) at request enqueue and the fit
scheduler (``runtime/scheduler.py``) at job submit. The state machines
are identical, so they live here once:

- the typed error surface (:class:`AdmissionError` and subclasses) —
  every way work can be rejected without a result is a distinct type,
  all subclassing ``RuntimeError`` so pre-typed callers keep working;
- :class:`ServiceEwma` — the per-key EWMA of (service seconds per
  dispatch, items per dispatch) behind the "is this deadline meetable"
  estimate;
- :class:`CircuitBreaker` — closed → open after N *consecutive*
  failures, open → half-open after a cooldown (one probe), half-open →
  closed on probe success / back to open on probe failure.

This module is metric-agnostic: the breaker reports state transitions
through an ``on_state`` callback so each plane exports its own gauge
(``serve_breaker_state{model}`` vs ``sched_breaker_state{tenant}``)
without this file hard-coding either metric name.
"""

from __future__ import annotations

import threading
import time

from . import lockwitness
from typing import Callable, Dict, Optional, Tuple

# breaker states (the gauge values both planes export)
CLOSED = 0
HALF_OPEN = 1
OPEN = 2

STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half_open", OPEN: "open"}

# EWMA smoothing for service time / items per dispatch: ~5-dispatch
# memory, fast enough to track a load shift within one burst
EWMA_ALPHA = 0.2


class AdmissionError(RuntimeError):
    """Base of the typed admission error surface. Subclasses
    ``RuntimeError`` so pre-existing callers catching RuntimeError keep
    working. (``serving.ServingError`` is an alias of this class.)"""


class DeadlineExceeded(AdmissionError):
    """The work's deadline expired before dispatch (never after a
    result was computed — expiry is checked *before* dispatch)."""


class Overloaded(AdmissionError):
    """Rejected at admission; ``reason`` is the shed-metric label
    (``queue_full`` | ``deadline_unmeetable`` | ``breaker_open``)."""

    def __init__(self, message: str, reason: str) -> None:
        super().__init__(message)
        self.reason = reason


class ShuttingDown(AdmissionError):
    """The runtime is closed or draining. The message always contains
    "closed" — callers matching the pre-typed RuntimeError still match."""

    def __init__(self, message: str = "ServingRuntime is closed") -> None:
        super().__init__(message)


class ServiceEwma:
    """Per-key EWMA of ``(service seconds per dispatch, items per
    dispatch)``. Thread-safe; the first observation seeds the average
    directly so early estimates are not dragged toward zero."""

    def __init__(self, alpha: float = EWMA_ALPHA) -> None:
        self.alpha = float(alpha)
        self._lock = lockwitness.make_lock("admission.ewma")
        self._ewma: Dict[str, Tuple[float, float]] = {}

    def note(self, key: str, service_s: float, n_items: int = 1) -> None:
        """Record one completed dispatch of ``n_items`` taking
        ``service_s`` seconds."""
        a = self.alpha
        with self._lock:
            prev = self._ewma.get(key)
            if prev is None:
                self._ewma[key] = (float(service_s), float(n_items))
            else:
                s, r = prev
                self._ewma[key] = (
                    a * float(service_s) + (1 - a) * s,
                    a * float(n_items) + (1 - a) * r,
                )

    def estimate_s(self, key: str) -> Optional[float]:
        """EWMA seconds one dispatch of ``key`` takes, or None before
        any dispatch has been observed."""
        with self._lock:
            ew = self._ewma.get(key)
        return None if ew is None else ew[0]

    def estimated_wait_s(self, key: str, depth: int) -> Optional[float]:
        """Expected queueing delay for work arriving now, behind
        ``depth`` already-admitted items. None = no data yet (first
        dispatches are never shed on the deadline estimate)."""
        with self._lock:
            ew = self._ewma.get(key)
        if ew is None:
            return None
        service_s, items_per_dispatch = ew
        dispatches = depth / max(items_per_dispatch, 1.0)
        return dispatches * service_s


class CircuitBreaker:
    """Per-key consecutive-failure breaker. Thread-safe; owned by the
    admission side and poked by the dispatch side
    (record_success/record_failure), so every transition is locked.
    ``on_state`` (optional) is invoked with the new state int on every
    transition — the hook each plane uses to export its gauge."""

    def __init__(
        self,
        key: str,
        fails: int,
        cooldown_s: float,
        on_state: Optional[Callable[[int], None]] = None,
    ) -> None:
        self.key = key
        self.fails = int(fails)  # 0 = disabled
        self.cooldown_s = float(cooldown_s)
        self._on_state = on_state
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._lock = lockwitness.make_lock("admission.breaker")

    @property
    def enabled(self) -> bool:
        return self.fails > 0

    def _set_state(self, state: int) -> None:
        self._state = state
        if self._on_state is not None:
            self._on_state(state)

    def state(self) -> int:
        with self._lock:
            return self._state

    def state_name(self) -> str:
        return STATE_NAMES[self.state()]

    def allow(self) -> bool:
        """Admission-side check. Open blocks; after the cooldown the
        breaker moves to half-open and admits exactly one probe."""
        if not self.enabled:
            return True
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if time.monotonic() - self._opened_at < self.cooldown_s:
                    return False
                self._set_state(HALF_OPEN)
                return True
            # HALF_OPEN: one probe is already in flight; block the rest
            # until the dispatch side reports its outcome
            return False

    def record_success(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._consecutive = 0
            if self._state != CLOSED:
                self._set_state(CLOSED)

    def record_failure(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            if self._state == HALF_OPEN:
                # the probe failed: straight back to open, fresh cooldown
                self._opened_at = time.monotonic()
                self._set_state(OPEN)
                return
            self._consecutive += 1
            if self._state == CLOSED and self._consecutive >= self.fails:
                self._opened_at = time.monotonic()
                self._set_state(OPEN)
