"""UMAP benchmark (reference ``bench_umap.py``; quality = trustworthiness
of the embedding, the reference's score)."""

from __future__ import annotations

import numpy as np

from .base import BenchmarkBase
from .utils import with_benchmark


class BenchmarkUMAP(BenchmarkBase):
    name = "umap"
    default_dataset = "blobs"

    def add_arguments(self, parser) -> None:
        parser.add_argument("--n_neighbors", type=float, default=15)
        parser.add_argument("--n_components", type=int, default=2)
        parser.add_argument("--sample_fraction", type=float, default=1.0)

    def run_once(self, train_df, transform_df):
        a = self.args
        if a.mode == "cpu":
            raise NotImplementedError(
                "umap-learn is not available in this environment; the CPU "
                "baseline for UMAP is not supported"
            )
        from spark_rapids_ml_tpu.umap import UMAP

        est = UMAP(
            n_neighbors=a.n_neighbors, n_components=a.n_components,
            sample_fraction=a.sample_fraction, random_state=a.random_seed,
            init="random", num_workers=a.num_chips,
        )
        model, fit_t = with_benchmark("fit", lambda: est.fit(train_df))
        out, tr_t = with_benchmark("transform", lambda: model.transform(transform_df))
        # trustworthiness on a bounded subsample (exact score is O(n^2))
        ns = min(2000, model.embedding_.shape[0])
        from sklearn.manifold import trustworthiness

        Xs = np.asarray(model.raw_data_)[:ns]
        trust = float(
            trustworthiness(Xs, model.embedding_[:ns], n_neighbors=int(a.n_neighbors))
        )
        return {
            "fit_time": fit_t,
            "transform_time": tr_t,
            "total_time": fit_t + tr_t,
            "trustworthiness": trust,
        }
