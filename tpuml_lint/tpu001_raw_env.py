"""TPU001 — raw ``os.environ`` read of a ``TPUML_*`` name.

Every ``TPUML_*`` knob is registered in
``spark_rapids_ml_tpu/runtime/envspec.py``; reads must go through
``envspec.get`` so parse failures name the variable and its accepted
domain instead of dying in a bare ``int()``. Writes
(``os.environ[k] = v``, ``pop``, ``del``, ``monkeypatch.setenv``) are
allowed — tests must be able to set knobs; only *reads* bypass the
registry's typing.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .core import (
    Finding,
    SourceFile,
    dotted_name,
    os_environ_aliases,
    str_const,
)

CODE = "TPU001"
NAME = "raw-env-read"

_READ_METHODS = ("get", "setdefault")


def _is_environ(node: ast.AST, os_names: set, environ_names: set) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        base = dotted_name(node.value)
        return base in os_names
    if isinstance(node, ast.Name):
        return node.id in environ_names
    return False


def _tpuml_arg(call: ast.Call) -> str:
    for arg in call.args[:1]:
        s = str_const(arg)
        if s and s.startswith("TPUML_"):
            return s
    return ""


def check_file(sf: SourceFile) -> Iterator[Finding]:
    if sf.path.endswith("runtime/envspec.py"):
        return
    os_names, environ_names, getenv_names = os_environ_aliases(sf.tree)

    def fixit(name: str) -> str:
        return (
            f"read it via the typed registry: "
            f"envspec.get({name!r}) "
            f"(from spark_rapids_ml_tpu.runtime import envspec)"
        )

    for node in ast.walk(sf.tree):
        # os.environ.get("TPUML_X", ...) / os.environ.setdefault(...)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _READ_METHODS and _is_environ(
                node.func.value, os_names, environ_names
            ):
                name = _tpuml_arg(node)
                if name:
                    yield sf.finding(
                        CODE, node,
                        f"raw os.environ.{node.func.attr} of {name!r} "
                        f"bypasses the typed registry",
                        fixit(name),
                    )
        # os.getenv("TPUML_X") / bare getenv(...)
        if isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            if fn is not None and (
                any(fn == f"{o}.getenv" for o in os_names)
                or fn in getenv_names
            ):
                name = _tpuml_arg(node)
                if name:
                    yield sf.finding(
                        CODE, node,
                        f"raw os.getenv of {name!r} bypasses the typed "
                        f"registry",
                        fixit(name),
                    )
        # os.environ["TPUML_X"] in Load context
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.ctx, ast.Load)
            and _is_environ(node.value, os_names, environ_names)
        ):
            sl = node.slice
            s = str_const(sl)
            if s and s.startswith("TPUML_"):
                yield sf.finding(
                    CODE, node,
                    f"raw os.environ[{s!r}] read bypasses the typed "
                    f"registry",
                    fixit(s),
                )
        # "TPUML_X" in os.environ
        if isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
        ):
            operands = [node.left] + list(node.comparators)
            for left, right in zip(operands, operands[1:]):
                s = str_const(left)
                if (
                    s
                    and s.startswith("TPUML_")
                    and _is_environ(right, os_names, environ_names)
                ):
                    yield sf.finding(
                        CODE, node,
                        f"membership test of {s!r} against os.environ "
                        f"bypasses the typed registry",
                        f"use envspec.is_set({s!r})",
                    )
