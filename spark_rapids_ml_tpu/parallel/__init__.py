from .mesh import (
    DP_AXIS,
    MP_AXIS,
    default_device_count,
    global_row_count,
    make_mesh,
    pad_rows,
    replicated,
    row_sharding,
    shard_aligned,
    shard_rows,
)
from .context import TpuDistContext, distributed_env_configured, ensure_distributed

__all__ = [
    "DP_AXIS",
    "MP_AXIS",
    "default_device_count",
    "distributed_env_configured",
    "ensure_distributed",
    "global_row_count",
    "make_mesh",
    "pad_rows",
    "replicated",
    "row_sharding",
    "shard_aligned",
    "shard_rows",
    "TpuDistContext",
]
