"""TPU012: thread lifecycle — daemon, named, and reachable teardown.

Every ``threading.Thread`` created inside ``spark_rapids_ml_tpu/``
(product code; tests spawn ad-hoc threads freely) must be:

- **daemon** (``daemon=True`` literally at the constructor / in the
  subclass ``super().__init__``): a non-daemon worker turns every
  forgotten ``close()`` into a hung interpreter at exit;
- **name-stamped** (``name=...``): the witness, the thread-leak
  sanitizer fixture, and crash dumps all identify threads by name —
  ``Thread-23`` is unactionable in a flight-recorder dump;
- **reachable from a teardown path**: the owning class defines one of
  ``stop/drain/close/halt/shutdown/__exit__``, the module defines a
  top-level ``stop``/``shutdown``/``close``, or the spawning function
  itself shuts the worker down in a ``finally`` (the streaming
  prefetcher's ``cancel.set()`` pattern). Daemon-ness keeps exit from
  hanging; teardown keeps *tests* from leaking live threads into each
  other (``tests/conftest.py`` snapshots them).

The same three requirements apply to ``threading.Thread`` subclasses:
their ``__init__`` must forward ``daemon=True`` and a ``name`` through
``super().__init__``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from .core import Finding, SourceFile, dotted_name, parents_map

CODE = "TPU012"
NAME = "thread-lifecycle"

SCOPE_PREFIX = "spark_rapids_ml_tpu/"
TEARDOWN_METHODS = {"stop", "drain", "close", "halt", "shutdown", "__exit__"}
TEARDOWN_MODULE_FNS = {"stop", "shutdown", "close"}


def _is_thread_ctor(node: ast.Call) -> bool:
    return dotted_name(node.func) in ("threading.Thread", "Thread")


def _kw(node: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _daemon_true(node: ast.Call) -> bool:
    v = _kw(node, "daemon")
    return isinstance(v, ast.Constant) and v.value is True


def _module_teardown_fns(tree: ast.AST) -> Set[str]:
    return {
        n.name
        for n in getattr(tree, "body", ())
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name in TEARDOWN_MODULE_FNS
    }


def _class_methods(cls: ast.ClassDef) -> Set[str]:
    return {
        n.name
        for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _finally_teardown(fn: ast.AST) -> bool:
    """True when ``fn`` contains a ``try/finally`` whose finalbody calls
    ``.set()`` or ``.join()`` — the local-worker shutdown idiom
    (``cancel.set()`` / ``worker.join()``)."""
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Try) and node.finalbody):
            continue
        for fin in node.finalbody:
            for call in ast.walk(fin):
                if (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in ("set", "join")
                ):
                    return True
    return False


def _teardown_evidence(
    node: ast.AST, parents, module_fns: Set[str]
) -> bool:
    cur = parents.get(node)
    fn_seen = False
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            if _class_methods(cur) & TEARDOWN_METHODS:
                return True
        if not fn_seen and isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            fn_seen = True
            if _finally_teardown(cur):
                return True
        cur = parents.get(cur)
    return bool(module_fns)


def _super_init(cls: ast.ClassDef) -> Optional[ast.Call]:
    """The ``super().__init__(...)`` call inside ``cls.__init__``."""
    for n in cls.body:
        if isinstance(n, ast.FunctionDef) and n.name == "__init__":
            for node in ast.walk(n):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "__init__"
                    and isinstance(node.func.value, ast.Call)
                    and dotted_name(node.func.value.func) == "super"
                ):
                    return node
    return None


def check_file(sf: SourceFile) -> Iterator[Finding]:
    if not sf.path.startswith(SCOPE_PREFIX):
        return
    parents = parents_map(sf.tree)
    module_fns = _module_teardown_fns(sf.tree)

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and _is_thread_ctor(node):
            if not _daemon_true(node):
                yield sf.finding(
                    CODE, node,
                    "thread is not daemon=True (literal): a non-daemon "
                    "worker hangs interpreter exit on any missed "
                    "teardown path",
                    fixit="pass daemon=True at the constructor",
                )
            if _kw(node, "name") is None:
                yield sf.finding(
                    CODE, node,
                    'thread has no name= stamp: the leak sanitizer, '
                    "the lock witness, and flight-recorder dumps "
                    "identify threads by name",
                    fixit='pass name="tpuml-<role>"',
                )
            if not _teardown_evidence(node, parents, module_fns):
                yield sf.finding(
                    CODE, node,
                    "thread has no reachable teardown: no "
                    "stop/drain/close/halt/shutdown/__exit__ on the "
                    "owning class, no module-level stop/shutdown, and "
                    "no finally-block .set()/.join() in the spawning "
                    "function",
                    fixit="wire the thread into an owner teardown "
                    "method (and join or signal it there)",
                )
        elif isinstance(node, ast.ClassDef) and any(
            dotted_name(b) in ("threading.Thread", "Thread")
            for b in node.bases
        ):
            si = _super_init(node)
            if si is None or not _daemon_true(si):
                yield sf.finding(
                    CODE, si or node,
                    f"Thread subclass {node.name!r} does not pass "
                    "daemon=True (literal) through super().__init__",
                    fixit="forward daemon=True in __init__",
                )
            if si is None or _kw(si, "name") is None:
                yield sf.finding(
                    CODE, si or node,
                    f"Thread subclass {node.name!r} does not stamp a "
                    "name= through super().__init__",
                    fixit='forward name="tpuml-<role>" in __init__',
                )
            if not (
                _class_methods(node) & TEARDOWN_METHODS or module_fns
            ):
                yield sf.finding(
                    CODE, node,
                    f"Thread subclass {node.name!r} has no teardown "
                    "method (stop/drain/close/halt/shutdown/__exit__) "
                    "and the module has no stop/shutdown",
                    fixit="add a teardown method that signals and "
                    "joins the thread",
                )
