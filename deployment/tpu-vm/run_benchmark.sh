#!/usr/bin/env bash
# Run the reference workload sweep on the provisioned TPU VM — the analog
# of databricks/run_benchmark.sh (which spark-submits the benchmark
# runner per algorithm). The sweep itself is the repo's root
# ./run_benchmark.sh (the same hyperparameters CI smokes and the
# reference methodology prescribes — numTrees/maxDepth/maxBins, kmeans
# k/max_iter/tol, ...); this wrapper only adds provisioning + the
# multi-host rendezvous env.
#
# Multi-host slices: every worker gets TPUML_COORDINATOR (worker 0's
# internal IP), TPUML_NUM_PROCS, and its TPUML_PROC_ID (from the TPU VM
# metadata's agent-worker-number) — the same rendezvous contract
# run_benchmark_multihost.sh exercises locally with a 2-process world.
#
# Required env: PROJECT, ZONE, TPU_NAME
# Optional:    ROWS (default 1000000), COLS (default 3000)
set -euo pipefail

: "${PROJECT:?set PROJECT}"
: "${ZONE:?set ZONE}"
: "${TPU_NAME:?set TPU_NAME}"
ROWS="${ROWS:-1000000}"
COLS="${COLS:-3000}"

mapfile -t IPS < <(gcloud compute tpus tpu-vm describe "${TPU_NAME}" \
  --project="${PROJECT}" --zone="${ZONE}" \
  --format='value(networkEndpoints[].ipAddress)' | tr ';' '\n')
N_PROCS="${#IPS[@]}"
COORD="${IPS[0]}:12355"

gcloud compute tpus tpu-vm ssh "${TPU_NAME}" \
  --project="${PROJECT}" --zone="${ZONE}" --worker=all --command="
set -e; cd ~/spark-rapids-ml-tpu
if [ ${N_PROCS} -gt 1 ]; then
  export TPUML_COORDINATOR='${COORD}'
  export TPUML_NUM_PROCS=${N_PROCS}
  export TPUML_PROC_ID=\$(curl -s -H 'Metadata-Flavor: Google' \
    http://metadata.google.internal/computeMetadata/v1/instance/attributes/agent-worker-number)
fi
./run_benchmark.sh tpu ${ROWS} ${COLS} benchmark_report.csv
"
echo "Sweep done; benchmark_report.csv is on each worker."
