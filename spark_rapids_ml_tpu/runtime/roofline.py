"""Roofline attribution: XLA cost-model numbers per span site.

PR 9's telemetry records *where* wall time goes; this layer records *how
far from the hardware ceiling* each stage runs. At compile time the
installed hook captures ``cost_analysis()`` (FLOPs, bytes accessed —
the XLA cost model, not hand formulas) of every executable the backend
produces and hands it to the same ``jax.monitoring`` compile-event
listener the retrace watchdog uses, which attributes it to the
innermost active span site via the span ``contextvars``. When a span at
an attributed site closes, :func:`annotate` combines the site's
per-call cost with the span's fenced device time (wall time when no
fence ran) and the per-platform peak-spec table to produce
``flops_total`` / ``bytes_total`` / ``mfu`` / ``achieved_gbps`` /
``bound`` span attributes and the ``span_mfu`` / ``span_achieved_gbps``
/ ``span_flops_total`` / ``span_bytes_total`` metrics.

Peak specs come from ``TPUML_PEAK_FLOPS`` / ``TPUML_PEAK_HBM_GBPS``
when set, else from a per-device-kind table (bf16 peak FLOP/s and HBM
GB/s per chip, scaled by the device count — the same denominator
``bench.py`` uses).

Semantics worth knowing before reading numbers:

- A site's per-call cost is the SUM over the distinct programs compiled
  while that site was innermost (a fit that compiles a preamble and a
  while-loop body executes both per call). Shape-driven recompiles add
  their variants' cost too — a site in a retrace storm (TPU003) reads
  high, which is a feature.
- Programs compiled at one site but re-executed under another (compile
  under ``fit.dispatch``, reuse in ``transform``) stay attributed to
  the compiling site. Cost capture happens at compile time only; there
  is no per-execution hook.
- Everything here is best-effort and opt-in: installation happens only
  while ``TPUML_TRACE`` is set, every capture path swallows failures
  (``cost_analysis`` unavailable, negative/missing FLOPs, jax internals
  moved), and with nothing captured spans carry NO roofline attributes
  — absent, never zero or NaN (``tests/test_roofline.py``).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from . import envspec, lockwitness

_LOGGER = logging.getLogger("spark_rapids_ml_tpu")

__all__ = [
    "install",
    "installed",
    "annotate",
    "aggregate",
    "site_costs",
    "peak_specs",
    "reset_roofline",
]

# --------------------------------------------------------------------------
# per-platform peak specs
# --------------------------------------------------------------------------

# bf16 peak FLOP/s per chip by device kind (mirrors bench.py's MFU
# denominator so measured and derived MFU share a scale).
_PEAK_FLOPS_BY_KIND: Tuple[Tuple[str, float], ...] = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
# HBM bandwidth GB/s per chip by device kind (datasheet figures).
_PEAK_HBM_GBPS_BY_KIND: Tuple[Tuple[str, float], ...] = (
    ("v6", 1640.0),
    ("v5p", 2765.0),
    ("v5 lite", 819.0),
    ("v5e", 819.0),
    ("v5", 2765.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)
# nominal CPU-fallback figures: keep ratios finite without pretending a
# host is an accelerator (same convention as bench.py's _CPU_PEAK)
_CPU_PEAK_FLOPS = 1e12
_CPU_PEAK_HBM_GBPS = 100.0

_PEAK_LOCK = lockwitness.make_lock("roofline.peaks")
_PEAK_CACHE: Optional[Tuple[float, float, int]] = None


def _kind_lookup(kind: str, table: Tuple[Tuple[str, float], ...],
                 fallback: float) -> float:
    kind = kind.lower()
    for key, peak in table:
        if key in kind:
            return peak
    return fallback


def peak_specs() -> Tuple[float, float, int]:
    """``(peak_flops_per_chip, peak_hbm_gbps_per_chip, device_count)``.

    Env overrides win; otherwise the device-kind tables (CPU nominal
    fallback). Cached after first resolution — by the time a compile has
    been attributed the backend is necessarily up, so the device probe
    cannot initialize anything the program was not already using.
    """
    global _PEAK_CACHE
    with _PEAK_LOCK:
        if _PEAK_CACHE is not None:
            return _PEAK_CACHE
        kind, n_dev = "cpu", 1
        try:
            import jax

            devices = jax.devices()
            n_dev = len(devices)
            kind = getattr(devices[0], "device_kind", "cpu")
        except Exception:  # no backend: nominal single-host figures
            pass
        flops = envspec.get("TPUML_PEAK_FLOPS")
        if flops is None:
            flops = _kind_lookup(kind, _PEAK_FLOPS_BY_KIND, _CPU_PEAK_FLOPS)
        gbps = envspec.get("TPUML_PEAK_HBM_GBPS")
        if gbps is None:
            gbps = _kind_lookup(
                kind, _PEAK_HBM_GBPS_BY_KIND, _CPU_PEAK_HBM_GBPS
            )
        _PEAK_CACHE = (float(flops), float(gbps), n_dev)
        return _PEAK_CACHE


# --------------------------------------------------------------------------
# compile-time capture
# --------------------------------------------------------------------------

_LOCK = lockwitness.make_lock("roofline.state")
_INSTALLED = False
_ORIG_BACKEND_COMPILE: Any = None
# site -> [flops_per_call, bytes_per_call, n_programs]
_SITE_COST: Dict[str, List[float]] = {}
_TLS = threading.local()  # .pending: cost dicts awaiting the compile event


def _extract_cost(executable: Any) -> Optional[Tuple[float, float]]:
    """``(flops, bytes_accessed)`` from an executable's cost analysis,
    or None when the backend reports nothing usable (missing key,
    zero/negative FLOPs — XLA's "unknown" convention)."""
    try:
        ca = executable.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):  # jax.stages.Compiled convention
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed")
    if not isinstance(flops, (int, float)) or flops <= 0:
        return None
    if not isinstance(nbytes, (int, float)) or nbytes < 0:
        nbytes = 0.0
    return float(flops), float(nbytes)


def _wrapped_backend_compile(*args: Any, **kwargs: Any) -> Any:
    executable = _ORIG_BACKEND_COMPILE(*args, **kwargs)
    try:
        cost = _extract_cost(executable)
        if cost is not None:
            pending = getattr(_TLS, "pending", None)
            if pending is None:
                pending = _TLS.pending = []
            pending.append(cost)
    except Exception:  # capture must never fail a compile
        pass
    return executable


def _consume_pending(site: str) -> None:
    """Called by telemetry's ``jax.monitoring`` compile-event listener
    (synchronously on the compiling thread, right after the wrapped
    compile returned): attribute every pending cost capture to the
    innermost active span site."""
    pending = getattr(_TLS, "pending", None)
    if not pending:
        return
    _TLS.pending = []
    with _LOCK:
        rec = _SITE_COST.get(site)
        if rec is None:
            rec = _SITE_COST[site] = [0.0, 0.0, 0]
        for flops, nbytes in pending:
            rec[0] += flops
            rec[1] += nbytes
            rec[2] += 1


def install() -> bool:
    """Wrap the backend compile entry point so executables surface their
    cost analysis, and make sure the shared ``jax.monitoring`` listener
    is registered (idempotent). Returns True when the hook is active.

    The wrap targets a jax-internal symbol; when the internals have
    moved this degrades to "roofline attributes absent" rather than an
    import error — the cost-analysis-fallback contract.
    """
    global _INSTALLED, _ORIG_BACKEND_COMPILE
    with _LOCK:
        if _INSTALLED:
            return True
        try:
            from jax._src import compiler as _jax_compiler

            _ORIG_BACKEND_COMPILE = _jax_compiler.backend_compile
            _jax_compiler.backend_compile = _wrapped_backend_compile
        except Exception:
            _LOGGER.debug(
                "roofline: jax compile hook unavailable; "
                "cost-model attribution disabled"
            )
            return False
        _INSTALLED = True
    # the compile-event listener is the attribution path (telemetry owns
    # it; it calls back into _consume_pending) — register outside _LOCK,
    # telemetry takes its own locks
    from . import telemetry

    telemetry.install_retrace_watchdog()
    return True


def installed() -> bool:
    with _LOCK:
        return _INSTALLED


# --------------------------------------------------------------------------
# span-close annotation
# --------------------------------------------------------------------------


def annotate(site: str, device_s: float, wall_s: float) -> Dict[str, Any]:
    """Roofline attributes for one closing span at ``site``: empty when
    no cost was ever attributed there (metrics cleanly absent), else
    ``flops_total`` / ``bytes_total`` plus — when the span has positive
    time — ``mfu``, ``achieved_gbps``, and the ``bound`` verdict.

    ``device_s`` (the fenced time) is the preferred denominator; wall
    time stands in when no fence ran. Also files the ``span_mfu`` /
    ``span_achieved_gbps`` histograms and the ``span_flops_total`` /
    ``span_bytes_total`` counters, labeled by site.
    """
    with _LOCK:
        rec = _SITE_COST.get(site)
        if rec is None:
            return {}
        flops, nbytes, n_programs = rec
    attrs: Dict[str, Any] = {
        "flops_total": flops,
        "bytes_total": nbytes,
        "cost_programs": n_programs,
    }
    from . import telemetry

    telemetry.counter("span_flops_total").inc(int(flops), name=site)
    telemetry.counter("span_bytes_total").inc(int(nbytes), name=site)
    seconds = device_s if device_s > 0 else wall_s
    if seconds > 0:
        peak_flops, peak_gbps, n_dev = peak_specs()
        mfu = flops / (seconds * peak_flops * n_dev)
        gbps = nbytes / seconds / 1e9
        frac_hbm = gbps / (peak_gbps * n_dev)
        attrs["mfu"] = round(mfu, 6)
        attrs["achieved_gbps"] = round(gbps, 3)
        attrs["bound"] = "compute" if mfu >= frac_hbm else "memory"
        telemetry.histogram("span_mfu").observe(mfu, name=site)
        telemetry.histogram("span_achieved_gbps").observe(gbps, name=site)
    return attrs


def site_costs() -> Dict[str, Dict[str, float]]:
    """Per-site compile-time cost attribution:
    ``{site: {flops_per_call, bytes_per_call, programs}}``."""
    with _LOCK:
        return {
            site: {
                "flops_per_call": rec[0],
                "bytes_per_call": rec[1],
                "programs": int(rec[2]),
            }
            for site, rec in _SITE_COST.items()
        }


def aggregate(stats: Dict[str, Dict[str, float]]) -> Dict[str, Dict[str, Any]]:
    """Fold roofline aggregates into a :func:`telemetry.span_stats`-shaped
    dict: for every site with attributed cost, add ``flops_total`` (per
    call x span count), ``bytes_total``, and — on positive time — the
    aggregate ``mfu`` / ``achieved_gbps`` / ``bound``. Sites without
    cost pass through untouched, so the CPU/interpret fallback keeps the
    PR-9 shape exactly."""
    costs = site_costs()
    if not costs:
        return stats
    peak_flops, peak_gbps, n_dev = peak_specs()
    out: Dict[str, Dict[str, Any]] = {}
    for site, st in stats.items():
        st = dict(st)
        rec = costs.get(site)
        if rec is not None:
            flops = rec["flops_per_call"] * st["count"]
            nbytes = rec["bytes_per_call"] * st["count"]
            st["flops_total"] = flops
            st["bytes_total"] = nbytes
            seconds = st["device_seconds"] or st["wall_seconds"]
            if seconds > 0:
                mfu = flops / (seconds * peak_flops * n_dev)
                gbps = nbytes / seconds / 1e9
                st["mfu"] = round(mfu, 6)
                st["achieved_gbps"] = round(gbps, 3)
                st["bound"] = (
                    "compute" if mfu >= gbps / (peak_gbps * n_dev)
                    else "memory"
                )
        out[site] = st
    return out


def reset_roofline() -> None:
    """Clear attribution state and the peak cache (test isolation); the
    compile hook itself stays installed — like monitoring listeners it
    cannot be meaningfully unregistered mid-process."""
    global _PEAK_CACHE
    with _LOCK:
        _SITE_COST.clear()
    _TLS.pending = []
    with _PEAK_LOCK:
        _PEAK_CACHE = None
