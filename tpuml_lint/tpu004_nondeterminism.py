"""TPU004 — nondeterminism hazards in fit / kernel code.

The repo's checkpoint/resume contract (PR 3) requires bit-identical
replays: a fit interrupted at epoch k and resumed must produce the same
model as an uninterrupted run. That only holds when every random stream
is derived from an explicit seed and every epoch's key comes from
``jax.random.fold_in(base, absolute_epoch)``.

Flagged:

* module-global numpy RNG: ``np.random.seed/rand/randn/randint/
  uniform/normal/shuffle/permutation/choice`` (shared mutable state;
  use ``np.random.default_rng(seed)``);
* stdlib ``random.<fn>()`` module-level calls — ``random.Random(seed)``
  / ``random.SystemRandom()`` instances are fine (retry jitter uses a
  seeded instance deliberately);
* wall-clock reads (``time.time``/``time.time_ns``/
  ``datetime.datetime.now``/``utcnow``) inside a jit-decorated function
  or a pallas kernel body — under tracing these bake in a constant from
  compile time, which is both nondeterministic across runs and silently
  stale across cache hits;
* ``jax.random.PRNGKey``/``jax.random.key`` constructed inside a loop —
  per-epoch keys must come from ``fold_in`` on an absolute step index,
  not repeated key construction.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from .core import (
    COMPREHENSION_NODES,
    Finding,
    LOOP_NODES,
    SourceFile,
    dotted_name,
    enclosing_within_function,
    parents_map,
)

CODE = "TPU004"
NAME = "nondeterminism"

_NP_GLOBAL_RNG = frozenset({
    "seed", "rand", "randn", "randint", "random", "uniform", "normal",
    "shuffle", "permutation", "choice", "standard_normal",
})
_NP_ALIASES = ("np.random.", "numpy.random.")
_RANDOM_OK = frozenset({"Random", "SystemRandom", "getstate", "setstate"})
_CLOCK_NAMES = frozenset({
    "time.time", "time.time_ns", "time.perf_counter",
    "time.monotonic", "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.now", "datetime.utcnow",
})
_KEY_NAMES = ("jax.random.PRNGKey", "jax.random.key", "jrandom.PRNGKey", "jr.PRNGKey")
_JIT_DECOR = ("jax.jit", "jit", "pl.pallas_call", "pallas_call")
_PARTIALS = ("functools.partial", "partial")


def _stdlib_random_aliases(tree: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "random":
                    names.add(a.asname or "random")
    return names


def _decorator_is_traced(dec: ast.AST) -> bool:
    """True for @jax.jit, @partial(jax.jit, ...), @pl.pallas_call-ish."""
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func)
        if fn in _JIT_DECOR:
            return True
        if fn in _PARTIALS and dec.args:
            return dotted_name(dec.args[0]) in _JIT_DECOR
        return False
    return dotted_name(dec) in _JIT_DECOR


def _kernel_like(fn: ast.AST) -> bool:
    """Heuristic for pallas kernel bodies: `*_kernel(... ref ...)` defs."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    if fn.name.endswith("_kernel") or fn.name == "kernel":
        return True
    args = [a.arg for a in fn.args.args]
    return sum(1 for a in args if a.endswith("_ref") or a == "ref") >= 2


def _traced_context(node: ast.AST, parents) -> Optional[str]:
    """Name of the enclosing jit-decorated or kernel-like def, if any."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_is_traced(d) for d in cur.decorator_list):
                return cur.name
            if _kernel_like(cur):
                return cur.name
        cur = parents.get(cur)
    return None


def check_file(sf: SourceFile) -> Iterator[Finding]:
    parents = parents_map(sf.tree)
    random_aliases = _stdlib_random_aliases(sf.tree)

    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if fn is None:
            continue

        # numpy module-global RNG
        for prefix in _NP_ALIASES:
            if fn.startswith(prefix) and fn[len(prefix):] in _NP_GLOBAL_RNG:
                yield sf.finding(
                    CODE, node,
                    f"{fn}() draws from numpy's shared module-global RNG "
                    f"(order-dependent, not seedable per-fit)",
                    "use a local generator: rng = np.random.default_rng("
                    "seed); rng.<method>(...)",
                )
                break

        # stdlib random module-level calls
        for alias in random_aliases:
            if fn.startswith(alias + "."):
                leaf = fn[len(alias) + 1:]
                if "." not in leaf and leaf not in _RANDOM_OK:
                    yield sf.finding(
                        CODE, node,
                        f"{fn}() uses the process-global stdlib RNG",
                        "use a seeded instance: rng = random.Random(seed)",
                    )
                break

        # wall clock inside traced/kernel code
        if fn in _CLOCK_NAMES:
            ctx = _traced_context(node, parents)
            if ctx is not None:
                yield sf.finding(
                    CODE, node,
                    f"{fn}() inside traced/kernel function {ctx!r} is "
                    f"evaluated once at trace time and baked into the "
                    f"compiled program",
                    "time outside the jitted call, or pass the value in "
                    "as an argument",
                )

        # PRNGKey construction inside a loop
        if fn in _KEY_NAMES:
            loop = enclosing_within_function(
                node, parents, LOOP_NODES + COMPREHENSION_NODES
            )
            if loop is not None:
                yield sf.finding(
                    CODE, node,
                    f"{fn} constructed inside a loop — per-epoch keys "
                    f"built this way break the segmented==fused resume "
                    f"contract",
                    "derive per-step keys from one base key: "
                    "jax.random.fold_in(base_key, absolute_step)",
                )
