#!/usr/bin/env python
"""CI gate: fail when the newest bench run regresses against the prior one.

Reads the ``BENCH_r*.json`` trajectory (driver wrapper files holding the
bench stdout/stderr tail) plus optionally a current raw ``bench.py``
output line, extracts the per-entry metric dicts, and compares the
newest run against the most recent prior run that produced entries:

- ``fit_seconds``   — regression when it grows past ``+threshold``
- ``vs_baseline``   — regression when it shrinks past ``-threshold``
- ``mfu``           — regression when it shrinks past ``-threshold``
- ``p99_ms``        — regression when it grows past ``+threshold``
  (serving tail latency; only entries that report it gate on it)
- ``serve_batch_fill`` — regression when it shrinks past ``-threshold``
  (micro-batch fill collapse wastes the padded dispatch)
- ``qps_sweep[<q>].p99_ms`` — every swept QPS level's tail gates like
  ``p99_ms``, so a regression visible only at high offered load cannot
  hide behind the top-level number
- ``aggregate_goodput_qps`` / ``replica_scaling_efficiency`` —
  regressions when they shrink past ``-threshold`` (the router bench's
  fleet goodput and its fraction of perfect N-replica scaling)
- ``fleet_p99_ms`` — regression when it grows past ``+threshold``
  (fleet tail measured from the MERGED per-rank reservoirs)
- ``tuned_vs_default`` — regression when it shrinks past ``-threshold``
  AND, unconditionally, when it falls below the absolute floor
  ``1.0 - threshold``: the autotuner measures the default config first
  and falls back to it on a loss, so a tuned run that loses to the
  default means the search or the cache is broken, not that the
  hardware got slower. The floor gates even ``tunnel_bound`` and
  first-appearance entries — tuned and default are measured
  back-to-back in the SAME run over the same link, so link weather
  cancels out of the ratio.

Rules that keep the gate honest on real trajectories:

- ``tunnel_bound`` entries (host->device ingest over the remote tunnel)
  measure the link, not the chip — their run-to-run swings are network
  weather, so they are reported but never gate.
- Zero/missing baselines (mfu 0.0 where no cost model applies,
  vs_baseline 0.0 from an unreachable-baseline run) cannot express a
  ratio — skipped, not failed.
- Entries present only in the current run are new coverage, not a
  regression.

Exit status: 0 when nothing regressed, 1 with a readable table naming
every offending entry/field otherwise. Deliberately stdlib-only (runs
in CI before any jax import).

Usage:
    python scripts/bench_regress.py                       # newest vs prior
    python scripts/bench_regress.py --current out.json    # gate a fresh run
    python scripts/bench_regress.py --threshold 0.20
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# per-entry dicts inside a (possibly truncated) bench stdout tail:
# '"pca": {...}' — entries never nest, so a flat brace group is enough
_ENTRY_RE = re.compile(r'"(\w+)":\s*(\{[^{}]*\})')

Entries = Dict[str, Dict[str, Any]]


def _entries_from_text(text: str) -> Entries:
    """Per-entry metric dicts from raw bench output (or a tail of it).

    Complete metric lines parse as whole-line JSON first — entries with
    nested sub-dicts (the serving entry's qps/window sweeps) are invisible
    to the flat-brace scan. The full metric line may also be truncated at
    the front by the driver's tail capture, so the fallback scans for
    every ``"name": {...}`` group and keeps the ones that look like bench
    entries (fit_seconds + samples_per_sec_per_chip). Later occurrences
    win, matching "last line is the real emit" semantics.
    """
    out: Entries = {}
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            out.update(
                {
                    k: v
                    for k, v in doc.items()
                    if isinstance(v, dict)
                    and "fit_seconds" in v
                    and "samples_per_sec_per_chip" in v
                }
            )
    if out:
        return out
    for m in _ENTRY_RE.finditer(text):
        try:
            v = json.loads(m.group(2))
        except ValueError:
            continue
        if (
            isinstance(v, dict)
            and "fit_seconds" in v
            and "samples_per_sec_per_chip" in v
        ):
            out[m.group(1)] = v
    return out


def parse_bench_file(path: str) -> Entries:
    """Entries from either a driver wrapper (``{"n", "cmd", "rc",
    "tail", ...}``) or a raw ``bench.py`` output file; empty dict when
    the run produced none (crashed before the emit)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        return _entries_from_text(text)
    if isinstance(doc, dict) and "tail" in doc:
        return _entries_from_text(doc.get("tail") or "")
    if isinstance(doc, dict):
        return {
            k: v
            for k, v in doc.items()
            if isinstance(v, dict) and "fit_seconds" in v
        }
    return {}


def _run_key(path: str) -> Tuple[int, str]:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return (int(m.group(1)) if m else -1, path)


def trajectory_files(pattern: str) -> List[str]:
    return sorted(glob.glob(pattern), key=_run_key)


_STATIC_FIELDS = (
    ("fit_seconds", +1),      # +1: larger is worse
    ("vs_baseline", -1),      # -1: smaller is worse
    ("mfu", -1),
    ("p99_ms", +1),           # serving tail latency: growth is a failure
    ("serve_batch_fill", -1),  # fill collapse = micro-batching regression
    ("goodput_qps", -1),      # overload goodput collapse = shedding broke
    ("shed_frac", +1),        # shedding more at the same offered load
    ("fits_per_sec", -1),     # fit-scheduler capacity regression
    ("fit_p99_ms", +1),       # scheduled-fit tail latency growth
    ("aggregate_goodput_qps", -1),        # fleet goodput collapse
    ("replica_scaling_efficiency", -1),   # router stopped spreading load
    ("fleet_p99_ms", +1),     # merged-reservoir fleet tail growth
    ("swap_p99_delta_ms", +1),  # hot-swap tail disturbance growth
    ("rollback_ms", +1),      # canary re-flip latency growth
    ("tuned_vs_default", -1),  # autotuner stopped beating/matching default
)

# tuned_vs_default also has an ABSOLUTE floor (see compare): the probe
# engine measures the default first, so a ratio below 1.0 - threshold is
# a broken search/cache regardless of what any prior run posted.
_ABS_FLOOR_FIELD = "tuned_vs_default"

_QPS_FIELD_RE = re.compile(r"^qps_sweep\[(.+)\]\.p99_ms$")


def _gate_fields(
    b: Dict[str, Any], c: Dict[str, Any]
) -> List[Tuple[str, int]]:
    """The (field, worse_sign) list for one entry pair: the static
    fields plus a flattened ``qps_sweep[<q>].p99_ms`` (+1) for every
    swept QPS level either run reports — a regression that only shows
    at high offered load must not slip a gate that reads the top-level
    p99 alone."""
    fields = list(_STATIC_FIELDS)
    levels: set = set()
    for src in (b, c):
        sweep = src.get("qps_sweep")
        if isinstance(sweep, dict):
            for q, sub in sweep.items():
                if isinstance(sub, dict) and "p99_ms" in sub:
                    levels.add(str(q))
    def _qkey(q: str) -> Tuple[int, Any]:
        try:
            return (0, int(q))
        except ValueError:
            return (1, q)
    for q in sorted(levels, key=_qkey):
        fields.append((f"qps_sweep[{q}].p99_ms", +1))
    return fields


def _field_value(entry: Dict[str, Any], field: str) -> Any:
    m = _QPS_FIELD_RE.match(field)
    if m is None:
        return entry.get(field)
    sweep = entry.get("qps_sweep")
    if isinstance(sweep, dict):
        sub = sweep.get(m.group(1))
        if isinstance(sub, dict):
            return sub.get("p99_ms")
    return None


def compare(
    base: Entries,
    cur: Entries,
    threshold: float,
) -> Tuple[List[Tuple[str, str, float, float, float, str]], bool]:
    """Per-entry/per-field comparison rows and the overall verdict.

    Rows are ``(entry, field, base, cur, delta_fraction, status)`` with
    status one of ``ok`` / ``REGRESS`` / ``skip:<reason>``; the bool is
    True when any row regressed.
    """
    rows: List[Tuple[str, str, float, float, float, str]] = []
    failed = False
    for name in sorted(set(base) | set(cur)):
        b, c = base.get(name), cur.get(name)
        if c is None:
            rows.append((name, "-", 0.0, 0.0, 0.0, "skip:entry-dropped"))
            continue
        # absolute floor: gates every current entry reporting the field,
        # including new and tunnel_bound ones (same-run back-to-back
        # ratio — the link cancels out; "no prior run" is no excuse)
        fv = c.get(_ABS_FLOOR_FIELD)
        if fv is not None:
            fv = float(fv)
            floor = 1.0 - threshold
            bad = fv < floor
            rows.append(
                (
                    name, f"{_ABS_FLOOR_FIELD}>=floor", floor, fv,
                    fv - 1.0, "REGRESS" if bad else "ok",
                )
            )
            failed = failed or bad
        if b is None:
            rows.append((name, "-", 0.0, 0.0, 0.0, "skip:new-entry"))
            continue
        tunnel = b.get("tunnel_bound") or c.get("tunnel_bound")
        for field, worse_sign in _gate_fields(b, c):
            bv, cv = _field_value(b, field), _field_value(c, field)
            if bv is None or cv is None:
                continue
            bv, cv = float(bv), float(cv)
            if bv <= 0:
                rows.append((name, field, bv, cv, 0.0, "skip:zero-baseline"))
                continue
            delta = (cv - bv) / bv
            if tunnel:
                rows.append((name, field, bv, cv, delta, "skip:tunnel-bound"))
                continue
            regress = worse_sign * delta > threshold
            rows.append(
                (name, field, bv, cv, delta, "REGRESS" if regress else "ok")
            )
            failed = failed or regress
    return rows, failed


def format_table(
    rows: List[Tuple[str, str, float, float, float, str]],
) -> str:
    header = ("entry", "field", "base", "current", "delta", "status")
    table = [header] + [
        (name, field, f"{bv:.4g}", f"{cv:.4g}", f"{delta:+.1%}", status)
        for name, field, bv, cv, delta, status in rows
    ]
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--trajectory",
        default=os.path.join(REPO_ROOT, "BENCH_r*.json"),
        help="glob of prior-run files, ordered by _r<N> (default: repo"
             " BENCH_r*.json)",
    )
    ap.add_argument(
        "--current",
        default=None,
        help="current-run file (wrapper or raw bench output); default:"
             " the newest trajectory file gates against the one before it",
    )
    ap.add_argument(
        "--threshold", type=float, default=0.15,
        help="noise threshold as a fraction (default 0.15 = ±15%%)",
    )
    args = ap.parse_args(argv)

    runs: List[Tuple[str, Entries]] = []
    for path in trajectory_files(args.trajectory):
        entries = parse_bench_file(path)
        if entries:
            runs.append((path, entries))
        else:
            print(f"bench_regress: {path}: no entries (skipped)")
    if args.current is not None:
        cur_path, cur = args.current, parse_bench_file(args.current)
        if not cur:
            print(f"bench_regress: {cur_path}: no entries in current run")
            return 1
    else:
        if len(runs) < 2:
            print(
                "bench_regress: need >= 2 parseable runs in the trajectory "
                f"(have {len(runs)}) — nothing to gate"
            )
            return 0
        cur_path, cur = runs.pop()
    if runs:
        base_path, base = runs[-1]
    else:
        # no trajectory yet: nothing to compare, but the absolute-floor
        # fields still gate the current run on its own
        print("bench_regress: no prior run — absolute floors only")
        base_path, base = "(none)", {}

    rows, failed = compare(base, cur, args.threshold)
    print(
        f"bench_regress: {os.path.basename(cur_path)} vs "
        f"{os.path.basename(base_path)} (threshold ±{args.threshold:.0%})"
    )
    print(format_table(rows))
    if failed:
        bad = sorted(
            {f"{name}.{field}" for name, field, *_rest, st in rows
             if st == "REGRESS"}
        )
        print(f"bench_regress: REGRESSION in {', '.join(bad)}")
        return 1
    print("bench_regress: no regression")
    return 0


if __name__ == "__main__":
    sys.exit(main())
