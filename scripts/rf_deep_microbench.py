"""Primitive measurements for the deep-level RF histogram redesign (round 4).

Round 3 established the XLA envelope: every histogram formulation XLA can
see bottoms out at ~1.2e8 scatter updates/s (docs/rf_performance.md).
The round-4 candidate bypasses XLA's one-hot-dot->scatter rewrite with a
Pallas kernel over node-contiguous rows. Its viability hinges on numbers
this script measures on the real chip:

  1. the status-quo per-level scatter cost (re-confirm the wall)
  2. row-permute gather X[perm] throughput (the compaction's per-level
     data movement)
  3. multi-operand lax.sort cost (fallback permutation application)
  4. wide-row scatter at histogram width (candidate final reduce)
  5. big-2D cumsum cost (candidate final reduce, cumsum-diff form)
  6. the Pallas sub-block histogram kernel itself

Timing methodology: the tunnel adds ~64 ms of round-trip latency per
dispatch+fetch, swamping single-op timings. Every measurement therefore
runs the op ITERS times inside one jitted fori_loop with a data
dependence through the carry (so XLA cannot hoist or CSE the body), and
divides out the loop count. A scalar fetch proves completion.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

ITERS = 32


def timeit_looped(jitted, *args, reps=3, warmup=1):
    """Time `jitted` (which runs its op ITERS times internally); returns
    seconds per op iteration."""
    for _ in range(warmup):
        np.asarray(jnp.ravel(jitted(*args))[:1])
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jnp.ravel(jitted(*args))[:1])
        ts.append(time.perf_counter() - t0)
    return min(ts) / ITERS


# bench shape
N = 131072
K = 16          # k_pad (feature subset)
NB = 128
S = 2
N_NODES = 4096  # level 12


def main():
    print("devices:", jax.devices())
    rng = np.random.default_rng(0)
    binc = jnp.asarray(rng.integers(0, NB, size=(N, K)), jnp.int32)
    sw = jnp.asarray(rng.random((N, S)), jnp.float32)
    local = jnp.asarray(rng.integers(0, N_NODES, size=(N,)), jnp.int32)

    # 0. RTT floor
    @jax.jit
    def nop(x):
        return x.sum()

    for _ in range(2):
        np.asarray(nop(sw))
    t0 = time.perf_counter()
    np.asarray(nop(sw))
    print(f"0. dispatch+fetch floor: {(time.perf_counter()-t0)*1e3:.1f} ms")

    # 1. status-quo scatter level
    @jax.jit
    def hist_scatter_loop(binc, local, sw):
        def body(_, c):
            ids = local[:, None] * NB + binc + (c.astype(jnp.int32) % 1)
            hist = jnp.stack(
                [
                    jax.vmap(
                        lambda col, cc=sw[:, s]: jax.ops.segment_sum(
                            cc, col, num_segments=N_NODES * NB + 1
                        ),
                        in_axes=1,
                    )(ids)
                    for s in range(S)
                ],
                axis=-1,
            )
            return hist[:, : N_NODES * NB, :].sum()

        return lax.fori_loop(0, ITERS, body, jnp.float32(0.0))

    t = timeit_looped(hist_scatter_loop, binc, local, sw)
    print(f"1. scatter level (n={N}, k={K}, S={S}): {t*1e3:.2f} ms "
          f"({N*K*S/t/1e8:.2f}e8 upd/s)")

    # 2. row-permute gather: carry the gathered matrix (serializes reps)
    perm = jnp.asarray(rng.permutation(N), jnp.int32)
    for W in (1, 8, 16):
        X = jnp.asarray(rng.integers(0, 1 << 30, size=(N, W)), jnp.int32)

        @jax.jit
        def rowperm_loop(X, perm):
            def body(_, Xc):
                return Xc[perm]

            return lax.fori_loop(0, ITERS, body, X).sum()

        t = timeit_looped(rowperm_loop, X, perm)
        print(f"2. row-permute gather (n={N}, w={W}): {t*1e3:.2f} ms "
              f"({N*W/t/1e9:.2f}e9 elem/s)")

    # 3. lax.sort key + payloads (key re-derived from carry each iter)
    key0 = jnp.asarray(rng.integers(0, N_NODES * 2, size=(N,)), jnp.int32)
    for n_payload in (1, 4):
        pls = [
            jnp.asarray(rng.integers(0, 1 << 30, size=(N,)), jnp.int32)
            for _ in range(n_payload)
        ]

        @jax.jit
        def sort_loop(key0, *pls):
            def body(_, k):
                out = lax.sort((k,) + pls, num_keys=1)
                return out[0] ^ 1  # depend on result, change key bits

            return lax.fori_loop(0, ITERS, body, key0).sum()

        t = timeit_looped(sort_loop, key0, *pls)
        print(f"3. lax.sort key+{n_payload} payloads: {t*1e3:.2f} ms")

    # 4. wide-row scatter: n_sb rows of width K*NB*S into N_NODES slots
    for n_sb in (8192, 20480):
        Wd = K * NB * S
        rows = jnp.asarray(rng.random((n_sb, Wd)), jnp.float32)
        seg = jnp.asarray(np.sort(rng.integers(0, N_NODES, size=(n_sb,))), jnp.int32)

        @jax.jit
        def wscatter_loop(rows, seg):
            def body(_, c):
                h = jax.ops.segment_sum(rows + c, seg, num_segments=N_NODES)
                return h.sum()

            return lax.fori_loop(0, ITERS, body, jnp.float32(0.0))

        t = timeit_looped(wscatter_loop, rows, seg)
        print(f"4. wide-row scatter ({n_sb} x {Wd}): {t*1e3:.2f} ms "
              f"({n_sb/t/1e6:.2f}e6 rows/s)")

    # 5. cumsum-diff segment reduce on (n_sb, W)
    for n_sb in (8192, 20480):
        Wd = K * NB * S
        rows = jnp.asarray(rng.random((n_sb, Wd)), jnp.float32)
        ends = jnp.asarray(
            np.sort(rng.choice(n_sb, N_NODES, replace=False)), jnp.int32
        )

        @jax.jit
        def cumdiff_loop(rows, ends):
            def body(_, c):
                cm = jnp.cumsum(rows + c, axis=0)
                seg_end = cm[ends]
                return (seg_end[1:] - seg_end[:-1]).sum()

            return lax.fori_loop(0, ITERS, body, jnp.float32(0.0))

        t = timeit_looped(cumdiff_loop, rows, ends)
        print(f"5. cumsum-diff reduce ({n_sb} x {Wd}): {t*1e3:.2f} ms")

    # 6. Pallas sub-block histogram kernel
    from spark_rapids_ml_tpu.ops.rf_pallas import subblock_hist, rf_hist_pallas_ok

    for r_sub in (8, 16, 32):
        n_pad = N
        if not rf_hist_pallas_ok(n_pad, K, NB, S, r_sub):
            print(f"6. pallas subblock hist r_sub={r_sub}: not eligible")
            continue
        binq = jnp.asarray(rng.integers(0, NB, size=(n_pad, K)), jnp.int32)
        swq = jnp.asarray(rng.random((n_pad, S)), jnp.float32)

        @jax.jit
        def phist_loop(binq, swq):
            def body(_, c):
                h = subblock_hist(
                    binq, swq + c, n_bins=NB, r_sub=r_sub
                )
                return h.sum()

            return lax.fori_loop(0, ITERS, body, jnp.float32(0.0))

        t = timeit_looped(phist_loop, binq, swq)
        print(f"6. pallas subblock hist (n={n_pad}, r_sub={r_sub}): {t*1e3:.2f} ms "
              f"({n_pad*K*S/t/1e8:.2f}e8 upd/s-equiv)")

    # 7. cumsum-diff at Pallas output granularity (n_sb, S, W)
    for r_sub in (8, 16):
        n_sb = N // r_sub + N_NODES
        Wd = K * NB
        rows = jnp.asarray(rng.random((n_sb, S * Wd)), jnp.float32)
        ends = jnp.asarray(
            np.sort(rng.choice(n_sb, N_NODES, replace=False)), jnp.int32
        )

        @jax.jit
        def cumdiff2_loop(rows, ends):
            def body(_, c):
                cm = jnp.cumsum(rows + c, axis=0)
                seg_end = cm[ends]
                return (seg_end[1:] - seg_end[:-1]).sum()

            return lax.fori_loop(0, ITERS, body, jnp.float32(0.0))

        t = timeit_looped(cumdiff2_loop, rows, ends)
        print(f"7. cumsum-diff ({n_sb} x {S*Wd}) [r_sub={r_sub}]: {t*1e3:.2f} ms")


if __name__ == "__main__":
    main()
