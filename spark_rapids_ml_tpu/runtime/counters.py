"""Process-wide resilience counters (back-compat shim).

The original int-dict registry this module held now lives in the typed
metrics layer (:mod:`runtime.telemetry`), with the metric catalog in
:mod:`runtime.metricspec` — gauge-vs-counter semantics are a property
of the registered metric, not a name check here. This shim keeps the
API every call site and test already uses (``bump`` / ``note`` /
``get`` / ``snapshot`` / ``delta_since`` / ``reset``), so bench.py can
still attach ``retries`` / ``resumed_from`` columns to every entry and
tests can still assert the clean path is fully inert (all deltas zero).

Names bumped through this shim must be declared in
``runtime/metricspec.py`` — lint rule TPU007 rejects uncataloged metric
names in repo code (the counter analog of TPU002's env/doc drift rule).
"""

from __future__ import annotations

from typing import Dict

from . import telemetry


def bump(name: str, by: int = 1) -> None:
    """Increment counter ``name`` by ``by`` (creates it at 0)."""
    telemetry._legacy_metric(name, "counter").inc(int(by))


def note(name: str, value: int) -> None:
    """Set gauge ``name`` to ``value`` (last-write-wins semantics)."""
    telemetry._legacy_metric(name, "gauge").set(int(value))


def get(name: str) -> int:
    return int(telemetry._legacy_snapshot().get(name, 0))


def snapshot() -> Dict[str, int]:
    """A point-in-time copy of every legacy-visible counter/gauge."""
    return telemetry._legacy_snapshot()


def delta_since(base: Dict[str, int]) -> Dict[str, int]:
    """Counter changes since ``base`` (a prior :func:`snapshot`).

    Gauges are reported as their current value when it changed; plain
    counters as the difference — decided by each metric's registered
    kind (``metricspec`` / the live registry), not its name. Keys with
    zero delta are omitted so the clean path reports ``{}``.
    """
    cur = snapshot()
    out: Dict[str, int] = {}
    for name, value in cur.items():
        if telemetry.metric_kind(name) == "gauge":
            if value != base.get(name, 0):
                out[name] = value
        else:
            d = value - base.get(name, 0)
            if d:
                out[name] = d
    return out


def reset() -> None:
    """Zero every counter (test isolation)."""
    telemetry._reset_metrics()
