"""Bounded exponential-backoff retries for transient infrastructure faults.

``with_retries`` wraps the two launch-time races the reference absorbed via
Spark's barrier-stage rescheduling: the multi-host rendezvous
(``jax.distributed.initialize`` when the coordinator is not up yet) and
device staging of a streamed chunk. The budget comes from env so the
launcher — not the algorithm code — decides how patient a fit is:

- ``TPUML_RETRIES``    — extra attempts after the first (default 0: a
                         single attempt, no sleeps, fully inert).
- ``TPUML_BACKOFF_MS`` — base delay of the exponential schedule
                         (default 100; delay for attempt *a* is
                         ``min(base * 2**a, 30s)`` with 50-100% jitter).

:class:`~spark_rapids_ml_tpu.runtime.faults.SimulatedPreemption` is
terminal by contract and is never retried — preemption is survived by
refit-from-checkpoint, not by waiting.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, List, Optional, Tuple, Type, TypeVar

from . import envspec
from .counters import bump
from .faults import SimulatedPreemption

logger = logging.getLogger("spark_rapids_ml_tpu.runtime.retry")

_T = TypeVar("_T")

_BACKOFF_CAP_MS = 30_000.0


def resolve_retries() -> int:
    """``TPUML_RETRIES`` as a non-negative int (default 0 = inert)."""
    return envspec.get("TPUML_RETRIES")


def resolve_backoff_ms() -> float:
    """``TPUML_BACKOFF_MS`` as a positive float (default 100)."""
    return float(envspec.get("TPUML_BACKOFF_MS"))


def backoff_schedule(
    retries: int,
    backoff_ms: float,
    *,
    cap_ms: float = _BACKOFF_CAP_MS,
    seed: int = 0,
) -> List[float]:
    """Delays (ms) before each retry: capped exponential with jitter.

    Attempt *a* (0-based) sleeps ``min(backoff_ms * 2**a, cap_ms)`` scaled
    by a uniform factor in [0.5, 1.0) — "equal jitter", so delays never
    collapse to zero but concurrent workers still decorrelate. Seeded so
    the schedule (and therefore every resilience test) is deterministic.
    """
    rng = random.Random(seed)
    out: List[float] = []
    for a in range(retries):
        base = min(backoff_ms * (2.0**a), cap_ms)
        out.append(base * (0.5 + 0.5 * rng.random()))
    return out


def is_resource_exhausted(exc: BaseException) -> bool:
    """True for allocator-pressure failures (XLA spells it in the message)."""
    return "RESOURCE_EXHAUSTED" in str(exc)


def with_retries(
    fn: Callable[[], _T],
    *,
    what: str,
    retries: Optional[int] = None,
    backoff_ms: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    giveup: Optional[Callable[[BaseException], bool]] = None,
    seed: int = 0,
    sleep: Callable[[float], None] = time.sleep,
) -> _T:
    """Call ``fn`` with up to ``retries`` re-attempts on transient failure.

    ``giveup`` classifies errors that retrying at the same shape cannot
    fix (e.g. ``is_resource_exhausted``) — they re-raise immediately so
    the caller's degradation path (chunk/group halving) runs instead of
    burning the backoff budget on a deterministic failure.

    With the default env (``TPUML_RETRIES`` unset/0) this is exactly one
    ``fn()`` call — no sleeps, no counter traffic, no behavior change.
    """
    budget = resolve_retries() if retries is None else retries
    if budget <= 0:
        return fn()
    delays = backoff_schedule(
        budget, resolve_backoff_ms() if backoff_ms is None else backoff_ms, seed=seed
    )
    last: Optional[BaseException] = None
    for attempt in range(budget + 1):
        try:
            return fn()
        except SimulatedPreemption:
            raise  # terminal by contract: survived via checkpoint, not retry
        except retry_on as exc:
            if giveup is not None and giveup(exc):
                raise
            last = exc
            if attempt >= budget:
                break
            bump("retries")
            try:
                from . import telemetry

                telemetry.add_span_event(
                    "retry",
                    what=what,
                    attempt=attempt + 1,
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
            except Exception:  # pragma: no cover - tracing must not break retry
                pass
            delay = delays[attempt]
            logger.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.0f ms",
                what,
                attempt + 1,
                budget + 1,
                exc,
                delay,
            )
            sleep(delay / 1000.0)
    assert last is not None
    raise last
