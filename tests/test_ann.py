"""IVF-Flat approximate kNN (``ops/ivf_kernels.py``): recall vs the exact
oracle, same-seed determinism, parameter validation through the
``ApproximateNearestNeighbors`` estimator surface, below-gate exact
fallback, and the ``TPUML_UMAP_GRAPH`` graph-engine dispatch contract —
mirroring the ``TPUML_UMAP_OPT`` tests in ``tests/test_umap_pallas.py``."""

import logging

import numpy as np
import pytest

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.knn import (
    ApproximateNearestNeighbors,
    NearestNeighbors,
)
from spark_rapids_ml_tpu.ops import ivf_kernels as ik
from spark_rapids_ml_tpu.umap import UMAP


def _blobs(n=2000, d=16, centers=12, seed=7):
    from sklearn.datasets import make_blobs

    X, _ = make_blobs(
        n_samples=n, n_features=d, centers=centers, random_state=seed
    )
    return X.astype(np.float32)


def _exact_ids(Xi, Xq, k):
    from sklearn.neighbors import NearestNeighbors as SkNN

    nn = SkNN(n_neighbors=k, algorithm="brute").fit(Xi)
    _, idx = nn.kneighbors(Xq)
    return idx


def _recall(approx_ids, exact_ids):
    k = exact_ids.shape[1]
    hits = [
        len(set(a) & set(e)) / k for a, e in zip(approx_ids, exact_ids)
    ]
    return float(np.mean(hits))


# --------------------------------------------------------------------------
# kernel-level: recall, determinism, exhaustive-probe exactness
# --------------------------------------------------------------------------


def test_ivf_recall_meets_target():
    X = _blobs()
    nlist, nprobe = ik.resolve_ann_params(len(X))
    index = ik.build_ivf_index(X, nlist=nlist, seed=0)
    d2, ids = ik.ivf_search(X[:256], index, k=15, nprobe=nprobe)
    exact = _exact_ids(X, X[:256], 15)
    assert _recall(np.asarray(ids), exact) >= 0.95
    # squared distances come back ascending
    d2 = np.asarray(d2)
    assert np.all(np.diff(d2, axis=1) >= -1e-5)


def test_ivf_exhaustive_probe_is_exact():
    """nprobe == nlist scans every list: the probe machinery must then
    reproduce the exact neighbor id set (validates gather/scan/merge)."""
    X = _blobs(n=600, d=8, centers=6)
    index = ik.build_ivf_index(X, nlist=8, seed=0)
    _, ids = ik.ivf_search(X[:128], index, k=10, nprobe=8)
    exact = _exact_ids(X, X[:128], 10)
    assert _recall(np.asarray(ids), exact) == 1.0


def test_ivf_same_seed_deterministic():
    X = _blobs(n=1200, d=8, centers=8)
    outs = []
    for _ in range(2):
        index = ik.build_ivf_index(X, nlist=16, seed=3)
        d2, ids = ik.ivf_search(X[:100], index, k=8, nprobe=4)
        outs.append((np.asarray(d2), np.asarray(ids)))
    np.testing.assert_array_equal(outs[0][1], outs[1][1])
    np.testing.assert_array_equal(outs[0][0], outs[1][0])


def test_index_layout_invariants():
    X = _blobs(n=900, d=6, centers=5)
    index = ik.build_ivf_index(X, nlist=12, seed=0)
    assert index.nlist == 12 and index.n_rows == 900
    assert index.cap % 8 == 0
    # CSR metadata covers every row exactly once
    assert index.offsets[0] == 0 and index.offsets[-1] == 900
    assert int(np.sum(index.lens)) == 900
    assert int(np.max(index.lens)) <= index.cap
    # padding slots carry id -1; real slots hold a permutation of all ids
    gids = np.asarray(index.grouped_ids)
    real = gids[gids >= 0]
    np.testing.assert_array_equal(np.sort(real), np.arange(900))


# --------------------------------------------------------------------------
# parameter resolution + validation
# --------------------------------------------------------------------------


def test_resolve_ann_params_validation():
    with pytest.raises(ValueError, match="nlist"):
        ik.resolve_ann_params(1000, nlist=1)
    with pytest.raises(ValueError, match="nlist"):
        ik.resolve_ann_params(100, nlist=200)
    with pytest.raises(ValueError, match="nprobe"):
        ik.resolve_ann_params(1000, nlist=16, nprobe=0)
    with pytest.raises(ValueError, match="nprobe"):
        ik.resolve_ann_params(1000, nlist=16, nprobe=32)


def test_resolve_ann_params_env_priority(monkeypatch):
    monkeypatch.setenv("TPUML_ANN_NLIST", "32")
    monkeypatch.setenv("TPUML_ANN_NPROBE", "5")
    assert ik.resolve_ann_params(10000) == (32, 5)
    # explicit args win over the env
    assert ik.resolve_ann_params(10000, nlist=64, nprobe=7) == (64, 7)


def test_resolve_umap_graph_validates(monkeypatch):
    monkeypatch.setenv("TPUML_UMAP_GRAPH", "bogus")
    with pytest.raises(ValueError, match="TPUML_UMAP_GRAPH"):
        ik.resolve_umap_graph()


def test_estimator_algo_params_validation():
    X = _blobs(n=300, d=4, centers=3)
    df = DataFrame({"features": X})
    with pytest.raises(ValueError, match="algorithm"):
        ApproximateNearestNeighbors(k=3, algorithm="cagra").fit(df)
    with pytest.raises(ValueError, match="algoParams"):
        ApproximateNearestNeighbors(
            k=3, algoParams={"n_lists": 8}
        ).fit(df)
    with pytest.raises(TypeError, match="algoParams"):
        ApproximateNearestNeighbors(k=3, algoParams=[8, 2]).fit(df)
    # out-of-domain values raise at query time even when the row gate
    # would route the call to the exact engine anyway
    model = ApproximateNearestNeighbors(
        k=3, num_workers=1, algoParams={"nlist": 1}
    ).fit(df)
    with pytest.raises(ValueError, match="nlist"):
        model.kneighbors(DataFrame({"features": X[:8]}))


# --------------------------------------------------------------------------
# estimator surface: gate fallback, ivf path, determinism
# --------------------------------------------------------------------------


def test_ann_below_gate_answers_exact():
    """Default gate (131072 rows) routes small fixtures to the exact ring:
    the ANN result must be BIT-identical to NearestNeighbors'."""
    X = _blobs(n=400, d=8, centers=4)
    df = DataFrame({"features": X})
    qdf = DataFrame({"features": X[:32]})
    ann = ApproximateNearestNeighbors(k=6, num_workers=1).fit(df)
    _, _, ann_df = ann.kneighbors(qdf)
    assert ann._ann_report["engine"] == "exact"
    exact = NearestNeighbors(k=6, num_workers=1).fit(df)
    _, _, exact_df = exact.kneighbors(qdf)
    np.testing.assert_array_equal(ann_df["indices"], exact_df["indices"])
    np.testing.assert_array_equal(ann_df["distances"], exact_df["distances"])


def test_ann_infeasible_warns_and_answers_exact(monkeypatch, caplog):
    """Above the gate but below the feasibility floor (n < 256): warn,
    then answer with the exact ring instead of crashing."""
    monkeypatch.setenv("TPUML_ANN_GATE_ROWS", "1")
    X = _blobs(n=200, d=4, centers=3)
    ann = ApproximateNearestNeighbors(k=4, num_workers=1).fit(
        DataFrame({"features": X})
    )
    ann.logger.addHandler(caplog.handler)
    try:
        _, _, knn_df = ann.kneighbors(DataFrame({"features": X[:16]}))
    finally:
        ann.logger.removeHandler(caplog.handler)
    assert ann._ann_report["engine"] == "exact"
    assert any("exact" in r.getMessage() for r in caplog.records)
    exact = _exact_ids(X, X[:16], 4)
    np.testing.assert_array_equal(knn_df["indices"], exact)


def test_ann_kneighbors_ivf_recall(monkeypatch):
    monkeypatch.setenv("TPUML_ANN_GATE_ROWS", "1")
    X = _blobs()
    ann = ApproximateNearestNeighbors(k=15, num_workers=1).fit(
        DataFrame({"features": X})
    )
    _, _, knn_df = ann.kneighbors(DataFrame({"features": X[:256]}))
    rep = ann._ann_report
    assert rep["engine"] == "ivf"
    assert rep["build_seconds"] >= 0 and rep["search_seconds"] >= 0
    exact = _exact_ids(X, X[:256], 15)
    assert _recall(np.asarray(knn_df["indices"]), exact) >= 0.95
    # distances are euclidean (not squared) and ascending, like the parent
    dist = np.asarray(knn_df["distances"])
    assert np.all(np.diff(dist, axis=1) >= -1e-4)
    # self distance: ~0 up to the f32 ||x||^2 - 2<x,y> + ||y||^2
    # cancellation error of the probe-scan formulation
    np.testing.assert_allclose(dist[:, 0], 0.0, atol=0.05)


def test_ann_kneighbors_same_seed_deterministic(monkeypatch):
    monkeypatch.setenv("TPUML_ANN_GATE_ROWS", "1")
    X = _blobs(n=800, d=8, centers=6)
    qdf = DataFrame({"features": X[:64]})
    outs = []
    for _ in range(2):
        ann = ApproximateNearestNeighbors(
            k=5, num_workers=1, algoParams={"nlist": 16, "seed": 3}
        ).fit(DataFrame({"features": X}))
        _, _, knn_df = ann.kneighbors(qdf)
        outs.append((np.asarray(knn_df["indices"]), np.asarray(knn_df["distances"])))
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    np.testing.assert_array_equal(outs[0][1], outs[1][1])


def test_ann_similarity_join(monkeypatch):
    monkeypatch.setenv("TPUML_ANN_GATE_ROWS", "1")
    X = _blobs(n=600, d=6, centers=5)
    ann = ApproximateNearestNeighbors(k=3, num_workers=1).fit(
        DataFrame({"features": X})
    )
    joined = ann.approxSimilarityJoin(DataFrame({"features": X[:20]}))
    assert "distCol" in joined
    assert len(joined["distCol"]) == 20 * 3


def test_ann_write_read_raise():
    est = ApproximateNearestNeighbors(k=3)
    with pytest.raises(NotImplementedError):
        est.write()
    with pytest.raises(NotImplementedError):
        ApproximateNearestNeighbors.read()


# --------------------------------------------------------------------------
# UMAP graph-engine dispatch (mirrors the TPUML_UMAP_OPT contract)
# --------------------------------------------------------------------------


def test_select_graph_engine_dispatch(monkeypatch):
    monkeypatch.delenv("TPUML_UMAP_GRAPH", raising=False)
    monkeypatch.delenv("TPUML_ANN_GATE_ROWS", raising=False)
    # auto below the default gate: exact (defaults-inert contract)
    assert ik.select_graph_engine(4096, 16) == "exact"
    # auto above the gate on a feasible shape: ivf
    monkeypatch.setenv("TPUML_ANN_GATE_ROWS", "1024")
    assert ik.select_graph_engine(4096, 16) == "ivf"
    # exact pins regardless of gate
    monkeypatch.setenv("TPUML_UMAP_GRAPH", "exact")
    assert ik.select_graph_engine(4096, 16) == "exact"
    # explicit ivf ignores the row gate on a feasible shape
    monkeypatch.setenv("TPUML_UMAP_GRAPH", "ivf")
    monkeypatch.setenv("TPUML_ANN_GATE_ROWS", "1000000")
    assert ik.select_graph_engine(4096, 16) == "ivf"


def test_select_graph_engine_ivf_falls_back_with_warning(monkeypatch, caplog):
    monkeypatch.setenv("TPUML_UMAP_GRAPH", "ivf")
    # the package logger does not propagate to root, so hook caplog's
    # handler onto it directly (same idiom as test_umap_pallas.py)
    lg = logging.getLogger("spark_rapids_ml_tpu.umap")
    lg.addHandler(caplog.handler)
    try:
        # n < 256: infeasible however you slice it
        assert ik.select_graph_engine(100, 8) == "exact"
    finally:
        lg.removeHandler(caplog.handler)
    assert any("falling back" in r.getMessage() for r in caplog.records)


def test_ivf_feasible_bounds():
    assert not ik.ivf_feasible(100, 8, 10, 2)       # n < 256
    assert not ik.ivf_feasible(1000, 1000, 30, 4)   # k >= n
    assert not ik.ivf_feasible(1000, 8, 500, 4)     # cells fragment
    assert not ik.ivf_feasible(100000, 64, 1000, 1) # probed pool < k
    assert ik.ivf_feasible(100000, 16, 316, 40)


# --------------------------------------------------------------------------
# UMAP end-to-end: ivf graph vs exact graph
# --------------------------------------------------------------------------


def _cluster_data(n=400, d=8, seed=5):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(3, d)) * 5
    lab = rng.integers(0, 3, size=n)
    return (centers[lab] + 0.3 * rng.normal(size=(n, d))).astype(np.float32)


def test_umap_graph_engines_agree_on_quality(monkeypatch):
    """Full UMAP fit through each graph engine: trustworthiness within
    ±0.01 and the fit report names the engine + index params that ran."""
    from sklearn.manifold import trustworthiness

    X = _cluster_data()
    df = DataFrame({"features": X})
    models = {}
    # 100 epochs: the embedding must be CONVERGED before trustworthiness
    # comparisons are meaningful — at 30 epochs the transient amplifies a
    # 0.3%-different graph to a ~0.06 gap that converges away
    for mode in ("exact", "ivf"):
        monkeypatch.setenv("TPUML_UMAP_GRAPH", mode)
        models[mode] = UMAP(
            n_neighbors=10, random_state=0, init="random", n_epochs=100,
            num_workers=1,
        ).fit(df)
        assert models[mode]._fit_report["graph_engine"] == mode
    rep = models["ivf"]._fit_report
    assert rep["ann_nlist"] >= 2 and rep["ann_nprobe"] >= 1
    t = {
        m: trustworthiness(X, np.asarray(mod.embedding_), n_neighbors=10)
        for m, mod in models.items()
    }
    assert t["exact"] > 0.85
    assert abs(t["ivf"] - t["exact"]) <= 0.01, t

    # transform through the ivf graph: report names the engine
    monkeypatch.setenv("TPUML_UMAP_GRAPH", "ivf")
    out = models["ivf"].transform(DataFrame({"features": X[:64]}))
    assert out["embedding"].shape == (64, 2)
    assert models["ivf"]._transform_report["graph_engine"] == "ivf"
    # pinning exact flips the transform path for the same model
    monkeypatch.setenv("TPUML_UMAP_GRAPH", "exact")
    models["ivf"].transform(DataFrame({"features": X[:32]}))
    assert models["ivf"]._transform_report["graph_engine"] == "exact"


def test_umap_defaults_keep_exact_graph(monkeypatch):
    """No TPUML_* ANN env set: the graph stage must run the exact engine
    (defaults-inert acceptance gate)."""
    for var in ("TPUML_UMAP_GRAPH", "TPUML_ANN_GATE_ROWS",
                "TPUML_ANN_NLIST", "TPUML_ANN_NPROBE"):
        monkeypatch.delenv(var, raising=False)
    X = _cluster_data(n=300)
    model = UMAP(
        n_neighbors=8, random_state=0, init="random", n_epochs=5,
        num_workers=1,
    ).fit(DataFrame({"features": X}))
    assert model._fit_report["graph_engine"] == "exact"
