"""Live operations plane: in-process scrape/health endpoints, an
always-on flight recorder, and SLO burn-rate evaluation.

PRs 9-10 made runs self-describing *after the fact* — files written at
exit under ``TPUML_TRACE``. This module answers the questions an
operator has while the process is still running:

- **HTTP endpoints** (``TPUML_OPS_PORT``; stdlib ``http.server`` on a
  daemon thread, bound to ``TPUML_OPS_HOST``):

  - ``/metrics``  — live Prometheus text from the typed registry
    (:func:`telemetry.prometheus_dump`, the same formatter
    ``write_metrics`` uses for the exit-time ``.prom`` shard).
  - ``/healthz``  — plain liveness (the process can answer).
  - ``/readyz``   — 200 only when every tracked
    :class:`serving.ModelRegistry` has its coalescable residents fully
    ladder-warmed AND ``retrace_storms == 0``; 503 with JSON reasons
    otherwise — the admission signal ROADMAP's elastic-scheduler item
    needs.
  - ``/statusz``  — JSON: active span tree with wall-clock ages,
    registry residency vs the ``hbm_*`` gauges, serve queue depth and
    batch fill, gang/ingest-ring occupancy, heartbeat ages for the
    long-running loops, and the SLO burn table.
  - ``/flight``   — the flight recorder's current ring as a
    Perfetto-loadable JSON document, served from memory.

- **Flight recorder** — a deterministic last-``TPUML_FLIGHT_EVENTS``
  ring of completed spans and instant events, fed by a
  :func:`telemetry.add_span_sink` hook, kept in memory even when
  ``TPUML_TRACE`` is unset. Dumped as a rank-tagged shard
  (``flight-r00-<pid>.json``, merged by ``scripts/merge_traces.py``)
  into ``TPUML_FLIGHT_DIR`` (falling back to the ``TPUML_TRACE`` dir)
  on SIGTERM, at interpreter exit, and once — ever — on the first SLO
  burn alert, so postmortems no longer require pre-enabled tracing.

- **SLO evaluation** — the declared catalog in :mod:`runtime.slo`,
  measured from periodic :func:`telemetry.metrics_snapshot` ticks every
  ``TPUML_SLO_EVAL_MS``; an alert fires when both burn windows cross
  ``TPUML_SLO_BURN_THRESHOLD``, incrementing ``slo_burn_alerts`` and
  triggering the one-shot flight dump.

Defaults are inert: with neither ``TPUML_OPS_PORT`` nor
``TPUML_FLIGHT_DIR`` set, :func:`ensure_started` returns False without
binding a socket, spawning a thread, attaching a sink, or touching
signal handlers (``tests/test_opsplane.py`` asserts all four).
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import threading
import time
import weakref
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from . import envspec, lockwitness, slo, telemetry

_LOGGER = logging.getLogger("spark_rapids_ml_tpu")

__all__ = [
    "ensure_started",
    "started",
    "stop",
    "address",
    "track_registry",
    "track_runtime",
    "track_router",
    "track_lifecycle",
    "flight_recorder",
    "slo_status",
    "FlightRecorder",
]


_LOCK = lockwitness.make_rlock("opsplane.plane")
_STARTED = False
_RECORDER: Optional["FlightRecorder"] = None
_SERVER: Optional[ThreadingHTTPServer] = None
_SERVER_THREAD: Optional[threading.Thread] = None
_EVALUATOR: Optional["_SloEvaluator"] = None
_ADDR: Optional[Tuple[str, int]] = None
_PREV_SIGTERM: Any = None
_SIGTERM_INSTALLED = False
# weakrefs so tracking never extends a registry/runtime lifetime
_REGISTRIES: List["weakref.ref[Any]"] = []
_RUNTIMES: List["weakref.ref[Any]"] = []
_SCHEDULERS: List["weakref.ref[Any]"] = []
_ROUTERS: List["weakref.ref[Any]"] = []
_LIFECYCLES: List["weakref.ref[Any]"] = []


def _active() -> bool:
    """The opt-in gate: any ops/flight env present."""
    return (
        envspec.get("TPUML_OPS_PORT") is not None
        or envspec.get("TPUML_FLIGHT_DIR") is not None
    )


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------


class FlightRecorder:
    """Bounded last-N ring of completed span/instant events.

    Attached as a telemetry span sink, so it sees every event a trace
    file would — but holds only the newest ``max_events`` in memory
    (deterministic FIFO, no sampling) and writes nothing until asked.
    """

    def __init__(self, max_events: int) -> None:
        self._lock = lockwitness.make_lock("opsplane.flight")
        self._events: Deque[Dict[str, Any]] = deque(maxlen=int(max_events))
        self._threads: Dict[int, str] = {}
        self.dumps: Dict[str, int] = {}

    def sink(self, ev: Dict[str, Any], thread_name: str) -> None:
        with self._lock:
            self._events.append(ev)
            tid = ev.get("tid")
            if tid is not None:
                self._threads.setdefault(tid, thread_name)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    @property
    def capacity(self) -> int:
        return int(self._events.maxlen or 0)

    def document(self, reason: str) -> Dict[str, Any]:
        """The ring as a Perfetto/Chrome-trace JSON document, tagged
        like a trace shard (``process_index`` metadata plus
        ``flight: true`` and the dump trigger)."""
        with self._lock:
            events = list(self._events)
            threads = dict(self._threads)
        pid = os.getpid()
        meta: List[Dict[str, Any]] = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": "spark_rapids_ml_tpu"},
            }
        ]
        for tid, tname in sorted(threads.items()):
            meta.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "metadata": {
                "process_index": telemetry._process_index(),
                "flight": True,
                "reason": reason,
            },
        }

    def dump(self, reason: str) -> Optional[str]:
        """Write the ring as ``flight-r<rank>-<pid>.json`` into
        ``TPUML_FLIGHT_DIR`` (or the ``TPUML_TRACE`` dir). Atomic
        (tmp + replace) because the crash paths call this mid-flight.
        Returns the path, or None when no directory is configured."""
        out_dir = envspec.get("TPUML_FLIGHT_DIR") or envspec.get(
            "TPUML_TRACE"
        )
        if not out_dir:
            return None
        doc = self.document(reason)
        os.makedirs(out_dir, exist_ok=True)
        tag = f"r{telemetry._process_index():02d}-{os.getpid()}"
        path = os.path.join(out_dir, f"flight-{tag}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        with self._lock:
            self.dumps[reason] = self.dumps.get(reason, 0) + 1
        telemetry.counter("flight_dumps_total").inc(reason=reason)
        return path


# --------------------------------------------------------------------------
# SLO evaluator
# --------------------------------------------------------------------------


class _SloEvaluator(threading.Thread):
    """Ticks :func:`telemetry.metrics_snapshot` every
    ``TPUML_SLO_EVAL_MS``, scores each cataloged SLO's burn rate, and
    fires the one-shot flight dump on the first alert."""

    # bound the per-SLO tick history: at the 10 ms floor this still
    # covers the default 300 s long window
    MAX_TICKS = 65536

    def __init__(
        self,
        recorder: FlightRecorder,
        period_s: float,
        threshold: float,
    ) -> None:
        super().__init__(name="tpuml-slo-eval", daemon=True)
        self._recorder = recorder
        self._period = float(period_s)
        self._threshold = float(threshold)
        self._halt = threading.Event()
        self._state_lock = lockwitness.make_lock("opsplane.slo")
        self._prev: Optional[Dict[str, Any]] = None
        self._ticks: Dict[str, Deque[Tuple[float, bool]]] = {
            s.name: deque(maxlen=self.MAX_TICKS) for s in slo.CATALOG
        }
        self._alerted: set = set()
        self._burn_dumped = False
        self._state: Dict[str, Any] = {}

    def run(self) -> None:
        while not self._halt.wait(self._period):
            try:
                self.tick()
            except Exception:  # evaluation must never kill the thread
                _LOGGER.exception("ops: SLO evaluation tick failed")

    def halt(self) -> None:
        self._halt.set()

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation pass (public so tests can drive it without
        the thread's cadence)."""
        if now is None:
            now = time.monotonic()
        # fleet-merged when a tracked router has out-of-process
        # replicas; exactly the local snapshot otherwise — so the SLO
        # table answers for the fleet, not the process
        snap = _fleet_snapshot()
        state: Dict[str, Any] = {}
        for spec in slo.CATALOG:
            value = slo.measured_value(spec, snap, self._prev)
            ticks = self._ticks[spec.name]
            if value is not None:
                ticks.append((now, slo.violates(spec, value)))
            st = slo.evaluate(spec, list(ticks), now, self._threshold)
            st["last_value"] = value
            if st["alerting"]:
                if spec.name not in self._alerted:
                    self._alerted.add(spec.name)
                    telemetry.counter("slo_burn_alerts").inc(slo=spec.name)
                    _LOGGER.warning(
                        "ops: SLO %s burning (short=%.2f long=%.2f, "
                        "objective %s %s)",
                        spec.name, st["burn_short"], st["burn_long"],
                        spec.sense, spec.objective,
                    )
                    if not self._burn_dumped:
                        # the one-shot contract: exactly one slo_burn
                        # flight dump per process, whichever SLO burns
                        # first
                        self._burn_dumped = True
                        try:
                            self._recorder.dump("slo_burn")
                        except Exception:
                            _LOGGER.exception("ops: burn dump failed")
            else:
                self._alerted.discard(spec.name)
            state[spec.name] = st
        self._prev = snap
        with self._state_lock:
            self._state = state
        return state

    def status(self) -> Dict[str, Any]:
        with self._state_lock:
            return dict(self._state)


def slo_status() -> Dict[str, Any]:
    """The latest per-SLO burn table (empty before the first tick or
    while the plane is down)."""
    ev = _EVALUATOR
    return ev.status() if ev is not None else {}


# --------------------------------------------------------------------------
# tracked subsystems
# --------------------------------------------------------------------------


def track_registry(registry: Any) -> None:
    """Weakly track a ModelRegistry for readiness/status introspection.
    Pure bookkeeping: never starts the plane, never keeps the registry
    alive."""
    with _LOCK:
        _prune(_REGISTRIES)
        _REGISTRIES.append(weakref.ref(registry))


def track_runtime(runtime: Any) -> None:
    """Weakly track a ServingRuntime for live queue-depth reporting."""
    with _LOCK:
        _prune(_RUNTIMES)
        _RUNTIMES.append(weakref.ref(runtime))


def track_scheduler(scheduler: Any) -> None:
    """Weakly track a FitScheduler (same contract as track_runtime):
    /statusz reads its stats(), /readyz gates on its loop heartbeat,
    and the SIGTERM handler drains it before the flight dump."""
    with _LOCK:
        _prune(_SCHEDULERS)
        _SCHEDULERS.append(weakref.ref(scheduler))


def track_router(router: Any) -> None:
    """Weakly track a serving Router: /statusz gains the fleet roll-up
    section, /readyz gates on the fleet having a routable replica, the
    SLO evaluator scores fleet-merged snapshots, and the SIGTERM
    handler drains the whole fleet before the flight dump."""
    with _LOCK:
        _prune(_ROUTERS)
        _ROUTERS.append(weakref.ref(router))


def track_lifecycle(lifecycle: Any) -> None:
    """Weakly track a ModelLifecycle: /statusz gains the lifecycle
    section (canaries, drift, version breakers, refreshers), /readyz
    reports 503 with a ``swap_in_progress`` reason while a hot-swap's
    warmup is incomplete, and the SIGTERM handler drains lifecycles
    FIRST — refresh drivers halt and canaries roll back before the
    router/runtime/scheduler drains, so no half-evaluated candidate
    can promote into a dying process."""
    with _LOCK:
        _prune(_LIFECYCLES)
        _LIFECYCLES.append(weakref.ref(lifecycle))


def _fleet_snapshot() -> Dict[str, Any]:
    """The snapshot SLO evaluation and /statusz quantile tables read:
    the local process's metrics, merged (reservoirs pooled) with every
    out-of-process replica snapshot a tracked router can fetch. With no
    router — or an all-loopback fleet — this is exactly the local
    snapshot, byte-identical to pre-fleet behavior."""
    local = telemetry.metrics_snapshot()
    extra: List[Dict[str, Any]] = []
    for router in _live(_ROUTERS):
        try:
            if not router.is_closed():
                extra.extend(router.replica_snapshots())
        except Exception:
            continue
    if not extra:
        return local
    return telemetry.merge_metric_snapshots([local] + extra)


def _prune(refs: List["weakref.ref[Any]"]) -> None:
    refs[:] = [r for r in refs if r() is not None]


def _live(refs: List["weakref.ref[Any]"]) -> List[Any]:
    with _LOCK:
        out = [r() for r in refs]
    return [o for o in out if o is not None]


# --------------------------------------------------------------------------
# readiness + status
# --------------------------------------------------------------------------


# a dispatcher with queued work that has not beaten for this long is
# reported stalled (the idle beat is ~1 Hz, so this is ~30 missed
# beats — far past any sane batch window, short of a long cold compile)
DISPATCHER_STALL_S = 30.0

# how long the SIGTERM handler lets each serving runtime drain before
# dumping the flight recorder and chaining to the previous disposition
SIGTERM_DRAIN_TIMEOUT_S = 5.0


def _readiness() -> Tuple[bool, List[str]]:
    reasons: List[str] = []
    storms = telemetry.counter("retrace_storms").value()
    if storms:
        reasons.append(f"retrace_storms={int(storms)}")
    for reg in _live(_REGISTRIES):
        try:
            swapping = reg.swaps_in_progress()
        except Exception:
            swapping = {}
        if swapping:
            # a flip whose warmup is incomplete: the prior version is
            # still serving, but rolling-update orchestration must not
            # advance to the next pod until the flip lands
            reasons.append(f"swap_in_progress={json.dumps(swapping)}")
        try:
            ws = reg.warmup_state()
        except Exception:
            continue
        if not ws.get("ready", True):
            pending = {
                name: m["pending_buckets"]
                for name, m in ws.get("models", {}).items()
                if m.get("pending_buckets")
            }
            reasons.append(f"warmup_pending={json.dumps(pending)}")
    for rt in _live(_RUNTIMES):
        try:
            if rt.is_closed():
                continue  # a cleanly closed runtime is not a fault
            if rt.is_draining():
                reasons.append("serving_draining")
            elif rt.dispatcher_started() and not rt.dispatcher_alive():
                reasons.append("serve_dispatcher_dead")
            else:
                age = rt.heartbeat_age_s()
                if (
                    age is not None
                    and age > DISPATCHER_STALL_S
                    and rt.queue_depth() > 0
                ):
                    reasons.append(
                        f"serve_dispatcher_stalled_age_s={age:.1f}"
                    )
            open_breakers = sorted(
                m for m, state in rt.breaker_states().items()
                if state == "open"
            )
            if open_breakers:
                reasons.append(
                    f"breaker_open={json.dumps(open_breakers)}"
                )
        except Exception:
            continue
    for router in _live(_ROUTERS):
        try:
            if router.is_closed():
                continue  # a cleanly closed router is not a fault
            if router.healthy_count() == 0:
                reasons.append("router_no_healthy_replicas")
            open_replicas = sorted(
                str(st["replica"]) for st in router.replica_states()
                if st.get("breaker") == "open"
            )
            if open_replicas:
                reasons.append(
                    f"router_breaker_open={json.dumps(open_replicas)}"
                )
        except Exception:
            continue
    for sched in _live(_SCHEDULERS):
        try:
            if sched.is_closed():
                continue  # a cleanly closed scheduler is not a fault
            if sched.is_draining():
                reasons.append("sched_draining")
            elif sched.dispatcher_started() and not sched.dispatcher_alive():
                reasons.append("sched_loop_dead")
            else:
                age = sched.heartbeat_age_s()
                if (
                    age is not None
                    and age > DISPATCHER_STALL_S
                    and sched.queue_depth() > 0
                ):
                    reasons.append(f"sched_loop_stalled_age_s={age:.1f}")
            open_breakers = sorted(
                t for t, state in sched.breaker_states().items()
                if state == "open"
            )
            if open_breakers:
                reasons.append(
                    f"sched_breaker_open={json.dumps(open_breakers)}"
                )
        except Exception:
            continue
    return (not reasons, reasons)


def _statusz() -> Dict[str, Any]:
    now = time.monotonic()
    snap = telemetry.metrics_snapshot()

    def _series(name: str) -> List[Dict[str, Any]]:
        return list((snap.get(name) or {}).get("series") or [])

    def _scalar(name: str) -> Optional[float]:
        for s in _series(name):
            if not s["labels"]:
                return s.get("value")
        return None

    heartbeats = {
        s["labels"].get("loop", "?"): round(now - float(s["value"]), 3)
        for s in _series("loop_heartbeat_ts")
    }
    hbm = {
        "budget_bytes": {
            s["labels"].get("site", "?"): s["value"]
            for s in _series("hbm_budget_bytes")
        },
        "live_bytes": {
            s["labels"].get("site", "?"): s["value"]
            for s in _series("hbm_live_bytes")
        },
    }
    serving: Dict[str, Any] = {
        "queue_depth_live": [
            rt.queue_depth() for rt in _live(_RUNTIMES)
        ],
        "queue_depth_gauge": _scalar("serve_queue_depth"),
        "batch_fill": [
            {
                "model": s["labels"].get("model", "?"),
                "count": s.get("count"),
                "p50": s.get("p50"),
                "p99": s.get("p99"),
            }
            for s in _series("serve_batch_fill")
        ],
        "p99_ms": [
            {
                "model": s["labels"].get("model", "?"),
                "count": s.get("count"),
                "p50": s.get("p50"),
                "p99": s.get("p99"),
            }
            for s in _series("serve_p99_ms")
        ],
        "draining": [rt.is_draining() for rt in _live(_RUNTIMES)],
        "dispatcher_alive": [
            rt.dispatcher_alive() for rt in _live(_RUNTIMES)
        ],
        "breakers": {
            model: state
            for rt in _live(_RUNTIMES)
            for model, state in rt.breaker_states().items()
        },
        "shed_total": {
            "{}/{}".format(
                s["labels"].get("model", "?"),
                s["labels"].get("reason", "?"),
            ): s.get("value")
            for s in _series("serve_shed_total")
        },
        "deadline_miss_total": {
            s["labels"].get("model", "?"): s.get("value")
            for s in _series("serve_deadline_miss_total")
        },
        "dispatch_errors": (
            telemetry.counter("serve_dispatch_errors_total").value() or 0
        ),
    }
    gang = {
        "dispatches": telemetry.counter("gang_dispatches").value() or 0,
        "lanes_total": telemetry.counter("gang_lanes_total").value() or 0,
    }
    scheduler: Dict[str, Any] = {
        "instances": [s.stats() for s in _live(_SCHEDULERS)],
        "draining": [s.is_draining() for s in _live(_SCHEDULERS)],
        "loop_alive": [s.dispatcher_alive() for s in _live(_SCHEDULERS)],
        "breakers": {
            tenant: state
            for s in _live(_SCHEDULERS)
            for tenant, state in s.breaker_states().items()
        },
        "fit_ms": [
            {
                "tenant": s["labels"].get("tenant", "?"),
                "count": s.get("count"),
                "p50": s.get("p50"),
                "p99": s.get("p99"),
            }
            for s in _series("sched_fit_ms")
        ],
        "shed_total": {
            "{}/{}".format(
                s["labels"].get("tenant", "?"),
                s["labels"].get("reason", "?"),
            ): s.get("value")
            for s in _series("sched_shed_total")
        },
        "preemptions": (
            telemetry.counter("sched_preemptions_total").value() or 0
        ),
        "resumes": telemetry.counter("sched_resumes_total").value() or 0,
        "dispatch_errors": (
            telemetry.counter("sched_dispatch_errors_total").value() or 0
        ),
    }
    fleet: List[Dict[str, Any]] = []
    for router in _live(_ROUTERS):
        entry: Dict[str, Any] = {
            "policy": getattr(router, "policy", "?"),
            "closed": router.is_closed(),
        }
        try:
            entry["replicas"] = router.replica_states()
            entry["healthy"] = router.healthy_count()
            entry["warmup"] = router.fleet_warmup_state()
            # measured fleet p99 from merged (pooled-reservoir)
            # snapshots — the pod-scale answer to "how slow are we"
            entry["p99_ms"] = router.fleet_p99_ms()
        except Exception as exc:
            entry["error"] = str(exc)
        fleet.append(entry)
    router_sheds = {
        "{}/{}".format(
            s["labels"].get("model", "?"), s["labels"].get("reason", "?")
        ): s.get("value")
        for s in _series("router_shed_total")
    }
    lifecycle: List[Dict[str, Any]] = []
    for lc in _live(_LIFECYCLES):
        try:
            lifecycle.append(lc.status())
        except Exception as exc:
            lifecycle.append({"error": str(exc)})
    ready, reasons = _readiness()
    rec = _RECORDER
    return {
        "pid": os.getpid(),
        "process_index": telemetry._process_index(),
        "ready": ready,
        "ready_reasons": reasons,
        "active_spans": telemetry.active_spans(),
        "registries": [
            reg.warmup_state() for reg in _live(_REGISTRIES)
        ],
        "serving": serving,
        "fleet": {"routers": fleet, "router_shed_total": router_sheds},
        "scheduler": scheduler,
        "lifecycle": lifecycle,
        "heartbeat_ages_s": heartbeats,
        "ingest_ring_occupancy": _scalar("ingest_ring_occupancy"),
        "gang": gang,
        "slo": slo_status(),
        "flight": {
            "events": len(rec) if rec is not None else 0,
            "capacity": rec.capacity if rec is not None else 0,
            "dumps": dict(rec.dumps) if rec is not None else {},
        },
    }


# --------------------------------------------------------------------------
# HTTP server
# --------------------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    server_version = "tpuml-ops"
    protocol_version = "HTTP/1.1"

    # the ops server must never spam stderr with access logs
    def log_message(self, fmt: str, *args: Any) -> None:
        return None

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        t0 = time.perf_counter()
        route = self.path.split("?", 1)[0]
        endpoint = "other"
        code = 200
        ctype = "application/json"
        try:
            if route == "/metrics":
                endpoint = "metrics"
                body = telemetry.prometheus_dump().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif route == "/healthz":
                endpoint = "healthz"
                body = json.dumps({"status": "ok"}).encode()
            elif route == "/readyz":
                endpoint = "readyz"
                ready, reasons = _readiness()
                code = 200 if ready else 503
                body = json.dumps(
                    {"ready": ready, "reasons": reasons}
                ).encode()
            elif route == "/statusz":
                endpoint = "statusz"
                body = json.dumps(
                    _statusz(), sort_keys=True, default=str
                ).encode()
            elif route == "/flight":
                endpoint = "flight"
                rec = _RECORDER
                if rec is None:
                    code = 503
                    body = json.dumps(
                        {"error": "flight recorder not running"}
                    ).encode()
                else:
                    body = json.dumps(rec.document("http")).encode()
            else:
                code = 404
                body = json.dumps(
                    {
                        "error": f"no route {route}",
                        "routes": [
                            "/metrics", "/healthz", "/readyz",
                            "/statusz", "/flight",
                        ],
                    }
                ).encode()
        except Exception as exc:  # a handler bug must not kill the fit
            code = 500
            body = json.dumps({"error": str(exc)}).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except Exception:  # client went away mid-write
            pass
        telemetry.counter("ops_requests_total").inc(endpoint=endpoint)
        telemetry.histogram("ops_request_seconds").observe(
            time.perf_counter() - t0, endpoint=endpoint
        )


# --------------------------------------------------------------------------
# crash-path dumps
# --------------------------------------------------------------------------


def _atexit_dump() -> None:
    rec = _RECORDER
    if rec is not None and len(rec):
        try:
            rec.dump("atexit")
        except Exception:
            pass


def _on_sigterm(signum: int, frame: Any) -> None:
    # lifecycle drivers drain FIRST: refresh threads halt (no new fits
    # land in a scheduler about to drain) and in-flight canaries roll
    # back typed (reason="shutdown") before serving admission stops —
    # a half-evaluated candidate must never promote into a dying
    # process; then the graceful serving drain (admission stops,
    # /readyz flips 503, in-flight work flushes, every future resolves
    # typed) so the flight dump below captures the post-drain state;
    # bounded — a wedged dispatcher cannot stall death past the timeout
    for lc in _live(_LIFECYCLES):
        try:
            lc.drain(timeout=SIGTERM_DRAIN_TIMEOUT_S)
        except Exception:
            pass
    for router in _live(_ROUTERS):
        try:
            router.drain(timeout=SIGTERM_DRAIN_TIMEOUT_S)
        except Exception:
            pass
    for rt in _live(_RUNTIMES):
        try:
            rt.drain(timeout=SIGTERM_DRAIN_TIMEOUT_S)
        except Exception:
            pass
    for sched in _live(_SCHEDULERS):
        try:
            sched.drain(timeout=SIGTERM_DRAIN_TIMEOUT_S)
        except Exception:
            pass
    rec = _RECORDER
    if rec is not None:
        try:
            rec.dump("signal")
        except Exception:
            pass
    prev = _PREV_SIGTERM
    if callable(prev):
        prev(signum, frame)
    else:
        # chain to the default disposition: restore and re-raise so
        # the process still dies with the conventional SIGTERM status
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _install_crash_paths() -> None:
    global _PREV_SIGTERM, _SIGTERM_INSTALLED
    atexit.register(_atexit_dump)
    try:
        _PREV_SIGTERM = signal.signal(signal.SIGTERM, _on_sigterm)
        _SIGTERM_INSTALLED = True
    except ValueError:  # not the main thread; atexit still covers exit
        _SIGTERM_INSTALLED = False


# --------------------------------------------------------------------------
# lifecycle
# --------------------------------------------------------------------------


def started() -> bool:
    return _STARTED


def address() -> Optional[Tuple[str, int]]:
    """(host, port) the ops server is listening on — with
    ``TPUML_OPS_PORT=0`` this is where the ephemeral port shows up —
    or None while no server runs."""
    return _ADDR


def flight_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def ensure_started() -> bool:
    """Start the ops plane once, iff opted in.

    With neither ``TPUML_OPS_PORT`` nor ``TPUML_FLIGHT_DIR`` set this
    is a cheap False: no socket, no thread, no sink, no signal handler
    — the defaults-inert contract. Otherwise: attach the flight
    recorder sink and crash-path dumps, start the SLO evaluator, and —
    when a port is configured — bind the HTTP server. Idempotent;
    called from the serving runtime and the streaming ingest loop, and
    safe to call directly."""
    global _STARTED, _RECORDER, _SERVER, _SERVER_THREAD, _EVALUATOR, _ADDR
    if not _active():
        return False
    with _LOCK:
        if _STARTED:
            return True
        _RECORDER = FlightRecorder(int(envspec.get("TPUML_FLIGHT_EVENTS")))
        telemetry.add_span_sink(_RECORDER.sink)
        _install_crash_paths()
        _EVALUATOR = _SloEvaluator(
            _RECORDER,
            period_s=float(envspec.get("TPUML_SLO_EVAL_MS")) / 1000.0,
            threshold=float(envspec.get("TPUML_SLO_BURN_THRESHOLD")),
        )
        _EVALUATOR.start()
        port = envspec.get("TPUML_OPS_PORT")
        if port is not None:
            host = str(envspec.get("TPUML_OPS_HOST"))
            server = ThreadingHTTPServer((host, int(port)), _Handler)
            server.daemon_threads = True
            _SERVER = server
            _ADDR = (server.server_address[0], server.server_address[1])
            _SERVER_THREAD = threading.Thread(
                target=server.serve_forever,
                name="tpuml-ops-http",
                daemon=True,
                kwargs={"poll_interval": 0.1},
            )
            _SERVER_THREAD.start()
            _LOGGER.info(
                "ops: serving /metrics /healthz /readyz /statusz "
                "/flight on http://%s:%d", _ADDR[0], _ADDR[1],
            )
        _STARTED = True
        return True


def stop() -> None:
    """Tear the plane down (test isolation): close the socket, halt the
    threads, detach the sink, restore the SIGTERM disposition, and
    unregister the atexit dump. Safe when never started."""
    global _STARTED, _RECORDER, _SERVER, _SERVER_THREAD, _EVALUATOR
    global _ADDR, _PREV_SIGTERM, _SIGTERM_INSTALLED
    with _LOCK:
        server, thread = _SERVER, _SERVER_THREAD
        evaluator, recorder = _EVALUATOR, _RECORDER
        _SERVER = _SERVER_THREAD = None
        _EVALUATOR = None
        _RECORDER = None
        _ADDR = None
        _STARTED = False
        _REGISTRIES.clear()
        _RUNTIMES.clear()
        _SCHEDULERS.clear()
        _ROUTERS.clear()
        _LIFECYCLES.clear()
    if server is not None:
        try:
            server.shutdown()
            server.server_close()
        except Exception:
            pass
    if thread is not None:
        thread.join(timeout=5.0)
    if evaluator is not None:
        evaluator.halt()
        evaluator.join(timeout=5.0)
    if recorder is not None:
        telemetry.remove_span_sink(recorder.sink)
    atexit.unregister(_atexit_dump)
    if _SIGTERM_INSTALLED:
        try:
            signal.signal(
                signal.SIGTERM,
                _PREV_SIGTERM if _PREV_SIGTERM is not None
                else signal.SIG_DFL,
            )
        except ValueError:
            pass
        _SIGTERM_INSTALLED = False
        _PREV_SIGTERM = None
