"""100M x 256 north-star, grouped-subprocess edition.

Two in-process 100M attempts were OOM-killed on the HOST (~130 GB RSS,
growing at exactly the ingest rate): the tunnel client retains a
host-side copy of each TRANSFERRED buffer until that exact buffer is
deleted, and the early mitigations (reference drops; deleting only the
derived f32 upcast of the f16 wire chunk) released nothing.
``ops.streaming.StreamGuard`` now deletes the raw wire buffers at proven
sync points, which bounds in-process retention — but a multi-hour
flagship run should not bet on the client's retention semantics staying
fixed across backend versions. The streaming two-pass algebra is additive
over file groups, so this driver additionally bounds retention by process
lifetime:

* pass 1 (weighted first moments) runs as one SUBPROCESS per file group,
  each writing its partials (n, Σx, Σy) to an npz and exiting — freeing
  everything the client retained for that group;
* the driver combines partials, fixes the global means, and fans out
  pass 2 (centered Gram/Xy/yy) the same way;
* ONE set of passes feeds BOTH models: PCA finalizes from G via
  ``_pca_from_cov``, LinearRegression solves from (G, Xy, yy) via
  ``_solve_from_stats`` — the exact code paths the in-process streaming
  fit uses, so results are identical by construction. Two dataset passes
  total instead of the naive four.

Per-group retention = group bytes shipped (~2 GB/pass at 5 files/group),
device memory = one chunk slab + O(d^2) accumulators throughout.

Usage:
    python scripts/run_100m_northstar_grouped.py [--data DIR]
        [--group-files 5] [--chunk-rows 524288] [--max-files N]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _worker(args) -> None:
    """Run one pass over one file group; write partials npz; exit."""
    from spark_rapids_ml_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.data.chunks import ParquetChunkSource
    from spark_rapids_ml_tpu.ops.streaming import (
        StreamGuard, gram2_init, gram2_step, moments1_init, moments1_step,
        put_chunk,
    )
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    files = args.files.split(",")
    src = ParquetChunkSource(args.data, label_col="label", _files=files)
    mesh = make_mesh()
    dtype = jnp.float32
    np_dtype = np.float32

    if args.phase == "pass1":
        acc = moments1_init(src.n_features, dtype, with_y=True)
        guard = StreamGuard()
        for chunk in src.iter_chunks(args.chunk_rows, np_dtype):
            dev = put_chunk(chunk, mesh, dtype)
            acc = moments1_step(acc, dev["X"], dev["mask"], dev["y"])
            guard.tick(dev, acc)
        guard.flush(acc)
        np.savez(
            args.out,
            n=np.asarray(acc["n"], np.float64),
            sum_x=np.asarray(acc["sum_x"], np.float64),
            sum_y=np.asarray(acc["sum_y"], np.float64),
        )
    else:
        means = np.load(args.means)
        mean_x = jnp.asarray(means["mean_x"], dtype)
        mean_y = jnp.asarray(means["mean_y"], dtype)
        acc = gram2_init(src.n_features, dtype, with_y=True)
        guard = StreamGuard()
        for chunk in src.iter_chunks(args.chunk_rows, np_dtype):
            dev = put_chunk(chunk, mesh, dtype)
            acc = gram2_step(acc, dev["X"], dev["mask"], mean_x, dev["y"], mean_y)
            guard.tick(dev, acc)
        guard.flush(acc)
        np.savez(
            args.out,
            G=np.asarray(acc["G"], np.float64),
            Xy=np.asarray(acc["Xy"], np.float64),
            yy=np.asarray(acc["yy"], np.float64),
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=os.path.join(_REPO, ".data", "blobs100m"))
    ap.add_argument("--platform", default=None)
    ap.add_argument("--group-files", type=int, default=5)
    ap.add_argument("--chunk-rows", type=int, default=1 << 19)
    ap.add_argument("--max-files", type=int, default=None)
    ap.add_argument("--sub-rows", type=int, default=500_000)
    # worker-mode internals
    ap.add_argument("--phase", choices=["pass1", "pass2"], default=None)
    ap.add_argument("--files", default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--means", default=None)
    args = ap.parse_args()

    if args.phase:
        _worker(args)
        return

    files = sorted(glob.glob(os.path.join(args.data, "part-*.parquet")))
    if args.max_files:
        files = files[: args.max_files]
    groups = [
        files[i : i + args.group_files]
        for i in range(0, len(files), args.group_files)
    ]
    tmp = tempfile.mkdtemp(prefix="northstar_grouped_")

    def run_phase(phase: str, means_path: str | None):
        outs = []
        for gi, g in enumerate(groups):
            out = os.path.join(tmp, f"{phase}-{gi:03d}.npz")
            cmd = [
                sys.executable, os.path.abspath(__file__),
                "--phase", phase, "--data", args.data,
                "--files", ",".join(g), "--out", out,
                "--chunk-rows", str(args.chunk_rows),
            ]
            if means_path:
                cmd += ["--means", means_path]
            if args.platform:
                cmd += ["--platform", args.platform]
            t0 = time.perf_counter()
            subprocess.run(cmd, check=True)
            print(
                f"[northstar-grouped] {phase} group {gi + 1}/{len(groups)} "
                f"({len(g)} files) in {time.perf_counter() - t0:.0f}s",
                file=sys.stderr, flush=True,
            )
            outs.append(out)
        return outs

    t_start = time.perf_counter()
    p1 = run_phase("pass1", None)
    n = sum(float(np.load(o)["n"]) for o in p1)
    sum_x = np.sum([np.load(o)["sum_x"] for o in p1], axis=0)
    sum_y = sum(float(np.load(o)["sum_y"]) for o in p1)
    mean_x = sum_x / n
    mean_y = sum_y / n
    means_path = os.path.join(tmp, "means.npz")
    np.savez(means_path, mean_x=mean_x, mean_y=np.float64(mean_y))
    t_pass1 = time.perf_counter() - t_start

    t0 = time.perf_counter()
    p2 = run_phase("pass2", means_path)
    G = np.sum([np.load(o)["G"] for o in p2], axis=0)
    Xy = np.sum([np.load(o)["Xy"] for o in p2], axis=0)
    yy = sum(float(np.load(o)["yy"]) for o in p2)
    t_pass2 = time.perf_counter() - t0

    # finalize BOTH models through the library's own solver paths
    from spark_rapids_ml_tpu.utils.platform import pin_platform

    pin_platform("cpu")  # d x d finalization; no need to re-grab the chip
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.models.feature import PCA, _pca_from_cov
    from spark_rapids_ml_tpu.models.regression import LinearRegression

    d = mean_x.shape[0]
    dtype = jnp.float64
    cov = jnp.asarray(G, dtype) / (n - 1.0)
    pca_out = {
        k: np.asarray(v)
        for k, v in _pca_from_cov(
            jnp.asarray(mean_x, dtype), cov, jnp.asarray(n, dtype), 3
        ).items()
    }
    stats = {
        "n": jnp.asarray(n, dtype),
        "mean_x": jnp.asarray(mean_x, dtype),
        "mean_all": jnp.asarray(mean_x, dtype),
        "mean_y": jnp.asarray(mean_y, dtype),
        "G": jnp.asarray(G, dtype),
        "Xy": jnp.asarray(Xy, dtype),
        "yy": jnp.asarray(yy, dtype),
        "var": jnp.asarray(np.diagonal(G) / n, dtype),
    }
    lin_out = LinearRegression._solve_from_stats(
        stats,
        {
            "alpha": 1e-5, "l1_ratio": 0.0, "standardization": True,
            "fit_intercept": True, "max_iter": 100, "tol": 1e-6,
        },
        dtype,
    )

    # parity: resident PCA on a strided subsample of the first file
    import pyarrow.parquet as pq

    from spark_rapids_ml_tpu.data import DataFrame

    t = pq.read_table(files[0], columns=["features"])
    sub_rows = min(len(t), args.sub_rows)
    stride = max(1, len(t) // sub_rows)
    t = t.take(np.arange(0, len(t), stride)[:sub_rows])
    fc = t.column("features").combine_chunks()
    Xs = (
        fc.flatten().to_numpy(zero_copy_only=False)
        .reshape(-1, fc.type.list_size).astype(np.float32)
    )
    resident = PCA(k=3).fit(DataFrame({"features": Xs}))
    cos = np.abs(
        np.sum(pca_out["components"] * np.asarray(resident.components_), axis=1)
    )

    wall = time.perf_counter() - t_start
    dataset_f32_gb = n * d * 4 / 1e9
    ingest_gbps = (dataset_f32_gb / 2) * 2 / max(wall, 1e-9)  # f16 wire, 2 passes
    line = {
        "metric": "northstar_100m_pca_fit",
        "rows": int(n),
        "cols": int(d),
        "pass1_seconds": round(t_pass1, 1),
        "pass2_seconds": round(t_pass2, 1),
        "wall_seconds": round(wall, 1),
        "groups": len(groups),
        "group_files": args.group_files,
        "tunnel_bound": ingest_gbps < 1.0,
        "dataset_f32_gb": round(dataset_f32_gb, 1),
        "wire_f16_gb_total": round(dataset_f32_gb, 1),  # 2 passes x f16
        "chunk_device_mb": round(args.chunk_rows * d * 4 / 1e6, 1),
        "subsample_component_cosines": [round(float(c), 5) for c in cos],
        "explained_variance_ratio": [
            round(float(v), 5) for v in pca_out["explained_variance_ratio"]
        ],
        "linreg_n_iter": int(lin_out.get("n_iter", 1)),
    }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
