"""Hyper-parameter tuning — single-pass CrossValidator.

Reference: ``/root/reference/python/src/spark_rapids_ml/tuning.py`` (177 LoC).
Its key optimization (``tuning.py:91-148``): when the estimator supports it,
fit **all** param maps in one data pass (``est.fitMultiple``), ``_combine``
the models into one multi-model, and evaluate every model in **one**
transform pass (``model._transformEvaluate``) per fold — instead of Spark's
per-param-map jobs. The same structure is kept here: the design matrix is
sharded onto the device mesh once per fold and every candidate reuses it;
folds run on a thread pool (reference ``tuning.py:106-129``).

``ParamGridBuilder`` is provided locally (the reference imports Spark's).
"""

from __future__ import annotations

import itertools
import os
import threading
from multiprocessing.pool import ThreadPool
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .core import _TpuEstimator, _TpuModel
from .data.dataframe import DataFrame, kfold
from .evaluation import Evaluator
from .params import Param, Params, TypeConverters, _mk
from .runtime import counters as _res_counters
from .runtime import envspec, telemetry
from .utils.logging import get_logger


def _cv_failfast() -> bool:
    """``TPUML_CV_FAILFAST`` (default 1 = reference semantics: any failed
    fold/param fit aborts the grid search). ``0`` records the failed combo
    as worst-metric and keeps searching — graceful degradation for long
    grids where one pathological combo (divergent solver, OOM) should not
    discard every other result."""
    return bool(envspec.get("TPUML_CV_FAILFAST"))

# Serializes per-fold device work under parallel CV (see run_fold in
# CrossValidator.fit): concurrent first-compiles of one jitted fit from
# multiple threads deadlock on jax 0.4.x.
_FOLD_DEVICE_LOCK = threading.Lock()


class ParamGridBuilder:
    """Drop-in for ``pyspark.ml.tuning.ParamGridBuilder``."""

    def __init__(self) -> None:
        self._param_grid: Dict[Param, List[Any]] = {}

    def addGrid(self, param: Param, values: Sequence[Any]) -> "ParamGridBuilder":
        if not isinstance(param, Param):
            raise TypeError("param must be an instance of Param")
        self._param_grid[param] = list(values)
        return self

    def baseOn(self, *args: Any) -> "ParamGridBuilder":
        if isinstance(args[0], dict):
            self.baseOn(*args[0].items())
            return self
        for param, value in args:
            self.addGrid(param, [value])
        return self

    def build(self) -> List[Dict[Param, Any]]:
        keys = list(self._param_grid.keys())
        grid_values = [self._param_grid[k] for k in keys]
        return [
            dict(zip(keys, combo)) for combo in itertools.product(*grid_values)
        ]


class _CrossValidatorParams(Params):
    numFolds = _mk("numFolds", "number of folds (>= 2)", TypeConverters.toInt)
    seed = _mk("seed", "random seed for fold assignment", TypeConverters.toInt)
    parallelism = _mk("parallelism", "thread-pool width over folds", TypeConverters.toInt)
    collectSubModels = _mk(
        "collectSubModels", "keep all sub-models on the CV model", TypeConverters.toBoolean
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(numFolds=3, seed=0, parallelism=1, collectSubModels=False)

    def getNumFolds(self) -> int:
        return self.getOrDefault("numFolds")

    def getSeed(self) -> int:
        return self.getOrDefault("seed")

    def getParallelism(self) -> int:
        return self.getOrDefault("parallelism")


class CrossValidator(_CrossValidatorParams):
    """Drop-in for ``pyspark.ml.tuning.CrossValidator`` with the reference's
    single-pass fast path (reference ``tuning.py:45-148``)."""

    def __init__(
        self,
        estimator: Optional[_TpuEstimator] = None,
        estimatorParamMaps: Optional[List[Dict[Param, Any]]] = None,
        evaluator: Optional[Evaluator] = None,
        numFolds: int = 3,
        seed: int = 0,
        parallelism: int = 1,
        **kwargs: Any,
    ) -> None:
        super().__init__()
        self._est = estimator
        self._epm = estimatorParamMaps
        self._eva = evaluator
        self._set(numFolds=numFolds, seed=seed, parallelism=parallelism)
        for name, value in kwargs.items():
            if not self.hasParam(name):
                raise ValueError(f"Unknown param {name!r} for CrossValidator")
            self._set(**{name: value})
        self.logger = get_logger(type(self))

    # -- component accessors (pyspark API) ---------------------------------
    def setEstimator(self, value: _TpuEstimator) -> "CrossValidator":
        self._est = value
        return self

    def getEstimator(self) -> _TpuEstimator:
        return self._est

    def setEstimatorParamMaps(self, value: List[Dict[Param, Any]]) -> "CrossValidator":
        self._epm = value
        return self

    def getEstimatorParamMaps(self) -> List[Dict[Param, Any]]:
        return self._epm

    def setEvaluator(self, value: Evaluator) -> "CrossValidator":
        self._eva = value
        return self

    def getEvaluator(self) -> Evaluator:
        return self._eva

    def setNumFolds(self, value: int) -> "CrossValidator":
        self._set(numFolds=value)
        return self

    def setParallelism(self, value: int) -> "CrossValidator":
        self._set(parallelism=value)
        return self

    def setSeed(self, value: int) -> "CrossValidator":
        self._set(seed=value)
        return self

    # -- fit ---------------------------------------------------------------
    def fit(self, dataset: DataFrame) -> "CrossValidatorModel":
        est, epm, eva = self._est, self._epm, self._eva
        if est is None or epm is None or eva is None:
            raise ValueError("estimator, estimatorParamMaps and evaluator must be set")
        num_models = len(epm)
        n_folds = self.getNumFolds()
        if n_folds < 2:
            raise ValueError("numFolds must be >= 2")

        # fast path requires the estimator's model to implement _combine +
        # _transformEvaluate (reference gate: ``tuning.py:96-99``)
        single_pass = est._supportsTransformEvaluate(eva)

        folds = kfold(dataset, n_folds, self.getSeed())
        collect_sub = bool(self.getOrDefault("collectSubModels"))

        failfast = _cv_failfast()
        # tolerant mode sentinel: a failed combo can never win the argmax/
        # argmin (and is visibly ±inf in avgMetrics)
        worst = -np.inf if eva.isLargerBetter() else np.inf

        # gang path: fit the whole folds × maps grid as fold-masked lanes
        # over ONE resident X (TPUML_GANG_FIT; estimator declines with None
        # and the per-fold path below runs unchanged). Runs before the
        # thread pool spins up, so no device lock is needed here.
        gang_grid: Optional[List[List[_TpuModel]]] = None
        if single_pass:
            try:
                gang_grid = est._gang_cv_fit_multiple(
                    dataset, epm, n_folds, self.getSeed()
                )
            except envspec.EnvSpecError:
                raise  # config errors surface regardless of failfast mode
            except Exception:
                if failfast:
                    raise
                self.logger.exception(
                    "gang CV fit failed; falling back to the per-fold path "
                    "(TPUML_CV_FAILFAST=0)"
                )
                gang_grid = None

        def run_fold(i: int) -> Tuple[np.ndarray, Optional[List[_TpuModel]]]:
            with telemetry.span("cv.fold", fold=i):
                return _run_fold(i)

        def _run_fold(
            i: int,
        ) -> Tuple[np.ndarray, Optional[List[_TpuModel]]]:
            # Device passes are serialized across fold threads: jax 0.4.x
            # can deadlock (futex wedge inside the dispatch lock) when
            # several threads race the *first* compile of the same jitted
            # fit. The lock covers ONLY device work — fold selection,
            # host-side _combine stacking, and metric aggregation run
            # outside the critical section so fold threads overlap there.
            train, validation = folds[i]
            if single_pass:
                try:
                    if gang_grid is not None:
                        models: List[_TpuModel] = gang_grid[i]
                    else:
                        with _FOLD_DEVICE_LOCK:
                            # ONE barrier-pass fit of all maps
                            models = [m for _, m in est.fitMultiple(train, epm)]
                    # host numpy stacking — no device work
                    combined = type(models[0])._combine(models)
                    with _FOLD_DEVICE_LOCK:
                        # ONE evaluate pass for every candidate
                        vals = combined._transformEvaluate(validation, eva)
                    return (
                        np.asarray(vals, dtype=np.float64),
                        models if collect_sub else None,
                    )
                except Exception:
                    if failfast:
                        raise
                    # the single-pass fit is all-or-nothing; fall through
                    # to the per-param-map loop so only the offending
                    # combos are recorded as failed
                    self.logger.exception(
                        "fold %d: single-pass fit failed; retrying "
                        "per-param-map (TPUML_CV_FAILFAST=0)", i
                    )
            vals, models = [], []
            for j, pm in enumerate(epm):
                try:
                    with _FOLD_DEVICE_LOCK:
                        model = est.fit(train, pm)
                        transformed = model.transform(validation)
                    # metric aggregation is host-side — outside the lock
                    vals.append(eva.evaluate(transformed))
                except Exception:
                    if failfast:
                        raise
                    self.logger.exception(
                        "fold %d param map %d: fit/evaluate failed; "
                        "recording worst metric (TPUML_CV_FAILFAST=0)",
                        i, j,
                    )
                    _res_counters.bump("cv_failed_fits")
                    vals.append(worst)
                    model = None
                if collect_sub:
                    models.append(model)
            return (
                np.asarray(vals, dtype=np.float64),
                models if collect_sub else None,
            )

        par = max(1, self.getParallelism())
        if par > 1:
            with ThreadPool(processes=min(par, n_folds)) as pool:
                # pool threads inherit the caller's span stack so fold
                # spans nest under the surrounding fit/tuning span
                fold_results = pool.map(
                    telemetry.bind_context(run_fold), range(n_folds)
                )
        else:
            fold_results = [run_fold(i) for i in range(n_folds)]
        metrics_per_fold = [m for m, _ in fold_results]
        sub_models = [s for _, s in fold_results] if collect_sub else None

        avg = np.mean(np.stack(metrics_per_fold), axis=0)
        best_idx = int(np.argmax(avg) if eva.isLargerBetter() else np.argmin(avg))
        if not np.isfinite(avg[best_idx]):
            raise RuntimeError(
                "CrossValidator: every param map failed in tolerant mode "
                "(TPUML_CV_FAILFAST=0) — no finite metric to select a best "
                "model from"
            )
        self.logger.info(
            "CrossValidator: best param map %d with avg metric %.6f",
            best_idx,
            avg[best_idx],
        )
        best_model = est.fit(dataset, epm[best_idx])
        cv_model = CrossValidatorModel(
            bestModel=best_model,
            avgMetrics=list(avg),
            stdMetrics=list(np.std(np.stack(metrics_per_fold), axis=0)),
        )
        cv_model.subModels = sub_models
        cv_model._est, cv_model._epm, cv_model._eva = est, epm, eva
        return cv_model


class CrossValidatorModel(_CrossValidatorParams):
    """Fitted CV model wrapping the best model (pyspark API surface)."""

    def __init__(
        self,
        bestModel: Optional[_TpuModel] = None,
        avgMetrics: Optional[List[float]] = None,
        stdMetrics: Optional[List[float]] = None,
    ) -> None:
        super().__init__()
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics or []
        self.stdMetrics = stdMetrics or []
        self.subModels: Optional[List[_TpuModel]] = None

    def transform(self, dataset: DataFrame) -> DataFrame:
        return self.bestModel.transform(dataset)

    # -- persistence: delegate to the best model + metrics sidecar ---------
    def save(self, path: str) -> None:
        import json
        import os

        self.bestModel.save(path)
        with open(os.path.join(path, "cv_metadata.json"), "w") as f:
            json.dump(
                {"avgMetrics": self.avgMetrics, "stdMetrics": self.stdMetrics}, f
            )

    @classmethod
    def load(cls, path: str) -> "CrossValidatorModel":
        import json
        import os

        from .core import _Reader

        best = _Reader(_TpuModel).load(path)
        meta_path = os.path.join(path, "cv_metadata.json")
        avg, std = [], []
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                m = json.load(f)
            avg, std = m.get("avgMetrics", []), m.get("stdMetrics", [])
        return cls(bestModel=best, avgMetrics=avg, stdMetrics=std)
