"""Linear-regression device kernels: sufficient statistics + solvers.

TPU-native replacement for the reference's three cuML solver classes
(``/root/reference/python/src/spark_rapids_ml/regression.py:502-559``:
``LinearRegressionMG`` eig for OLS, ``RidgeMG`` with the alpha×M Spark
scaling, ``CDMG`` coordinate descent for elasticnet).

Design: ONE distributed pass over the dp-sharded design matrix computes the
weighted centered sufficient statistics (Gram d×d, X'y, y'y, moments) —
XLA inserts the psum. Every solver then works on the replicated d×d
system: OLS/ridge are a Cholesky solve, elasticnet is FISTA on the
quadratic form — O(d²) per iteration with NO further data passes or
collectives (cuML's CD re-reads the data every iteration; for the
reference's d≈3000 benchmark shape this is strictly less communication).

Spark objective parity: 1/(2n)·Σ wᵢ(yᵢ - x·β - b)² + λ[(1-α)/2‖β‖₂² + α‖β‖₁]
with the penalty applied to standardized coefficients when
``standardization=True`` (Spark MLlib semantics the reference matches via
the alpha×M rescale, ``regression.py:530-537``).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("fit_intercept",))
def linreg_suffstats(
    X: jax.Array,
    mask: jax.Array,
    y: jax.Array,
    row_w: Optional[jax.Array] = None,
    *,
    fit_intercept: bool = True,
) -> Dict[str, jax.Array]:
    """Weighted centered sufficient statistics in one pass.

    Returns dict with n (Σw), mean_x, mean_y, G=(Xc√w)'(Xc√w), Xy, yy, var.
    Centering before the Gram keeps f32 stable (see ops/linalg.py).
    """
    w = mask if row_w is None else mask * row_w
    n = w.sum()
    mean_all = (X * w[:, None]).sum(axis=0) / n  # true feature means
    if fit_intercept:
        mean_x = mean_all
        mean_y = (y * w).sum() / n
    else:
        mean_x = jnp.zeros((X.shape[1],), X.dtype)
        mean_y = jnp.asarray(0.0, X.dtype)
    sw = jnp.sqrt(w)
    Xc = (X - mean_x[None, :]) * sw[:, None]
    yc = (y - mean_y) * sw
    G = Xc.T @ Xc
    Xy = Xc.T @ yc
    yy = (yc * yc).sum()
    # penalty scaling always uses the true (centered) variance, even when
    # fit_intercept=False leaves G uncentered: diag(G)/n is then E[x²], so
    # subtract mean² (matches Spark's std-based penalty semantics)
    var = jnp.diagonal(G) / n
    if not fit_intercept:
        var = var - mean_all * mean_all
    return {
        "n": n, "mean_x": mean_x, "mean_y": mean_y,
        "G": G, "Xy": Xy, "yy": yy, "var": var,
    }


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "csize", "fit_intercept", "weighted", "mp_blocks"),
)
def linreg_suffstats_chunked(
    X: jax.Array,
    mask: jax.Array,
    y: jax.Array,
    row_w: Optional[jax.Array] = None,
    *,
    mesh,
    csize: int,
    fit_intercept: bool = True,
    weighted: bool = False,
    mp_blocks: bool = False,
) -> Dict[str, jax.Array]:
    """:func:`linreg_suffstats` with O(csize·d) temporaries and one pass.

    Same memory/stability design as ``ops.linalg.mean_and_cov_chunked``: the
    fused form can materialize the centered √w-scaled copy of X at
    double-digit-GB row counts and OOM; here each device scans fixed
    ``csize`` row chunks, accumulating statistics shifted by a mean
    *estimate* (from the device's leading rows, one cheap psum), and exact
    rank-1 corrections re-center at the true weighted means. With
    ``fit_intercept=False`` the solver statistics (G, Xy, yy) accumulate
    uncentered for parity with the resident path, while the penalty
    variance still uses the shifted accumulator — stable where the
    resident ``E[x²] - mean²`` form cancels catastrophically for |μ| ≫ σ.

    Requires per-device rows divisible by ``csize``; rows sharded over dp.

    Note on Pallas: a hand-written tiled kernel for this accumulation
    (HBM→VMEM row tiles, all seven accumulators VMEM-resident, both MXU
    and VPU Xy variants, 8–16 MB tiles) measured AT PARITY with this scan
    on v5e at 12M×256 (~97 ms vs ~99 ms, ~385 GB/s both) — unlike the PCA
    covariance, where the Pallas gram kernel beats XLA ~1.9×. The scan is
    kept as the single implementation; don't re-add a Pallas path here
    without profiling past that result.

    With ``mp_blocks`` (gate via ``ops.linalg.mp_gram_blocks`` — env read
    outside jit) the d×d Gram accumulates as each device's own (d, d/mp)
    column block, psum over dp only, returned column-sharded over mp
    (``LAYOUT.cols()``) — same SUMMA panel product as the blocked
    covariance. The d-vector statistics (Xy, sums, variance) stay
    replicated: they are O(d), not O(d²).
    """
    from ._compat import shard_map
    from ..parallel.layout import LAYOUT
    from ..parallel.mesh import DP_AXIS, MP_AXIS
    from .linalg import check_row_chunking, row_chunk

    if not weighted:
        row_w = None

    n_mp = int(mesh.shape.get(MP_AXIS, 1)) if mp_blocks else 1
    if n_mp > 1 and X.shape[1] % n_mp != 0:
        raise ValueError(
            f"blocked Gram requires feature width ({X.shape[1]}) divisible "
            f"by the mp extent ({n_mp}); gate with mp_gram_blocks"
        )
    bw = X.shape[1] // n_mp

    def per_device(Xl, ml, yl, *rw):
        d = Xl.shape[1]
        wl = ml if not rw else ml * rw[0]
        # column-block start of THIS device's Gram panel (0 at mp=1)
        blk0 = lax.axis_index(MP_AXIS) * bw if n_mp > 1 else 0

        # mean estimate from each device's leading rows — shifts the
        # sum/variance accumulators ALWAYS (stable var even in the
        # uncentered fit), and the G/Xy/yy accumulators only when the fit
        # centers (fit_intercept); uncentered solver statistics must stay
        # uncentered for parity
        e = min(csize, Xl.shape[0])
        w0 = wl[:e]
        sx0 = lax.psum((Xl[:e] * w0[:, None]).sum(axis=0), DP_AXIS)
        sy0 = lax.psum((yl[:e] * w0).sum(), DP_AXIS)
        c0 = jnp.maximum(lax.psum(w0.sum(), DP_AXIS), 1.0)
        mu_x, mu_y = sx0 / c0, sy0 / c0

        nc = check_row_chunking(Xl.shape[0], csize)

        def body(i, carry):
            sx, sy, vs, W, G, Xy, yy = carry
            x, w, yv = row_chunk(i, csize, Xl, wl, yl)
            sqw = jnp.sqrt(w)
            xd = x - mu_x[None, :]
            xs = (xd if fit_intercept else x) * sqw[:, None]
            ys = ((yv - mu_y) if fit_intercept else yv) * sqw
            xdw = xd * sqw[:, None]
            xb = (
                lax.dynamic_slice_in_dim(xs, blk0, bw, 1)
                if n_mp > 1
                else xs
            )
            return (
                sx + (xdw * sqw[:, None]).sum(axis=0),  # Σ w (x-μ̂x)
                sy + ((yv - mu_y) * w).sum(),           # Σ w (y-μ̂y)
                vs + (xdw * xdw).sum(axis=0),           # Σ w (x-μ̂x)²
                W + w.sum(),
                G + xs.T @ xb,
                Xy + xs.T @ ys,
                yy + (ys * ys).sum(),
            )

        zero = functools.partial(jnp.zeros, dtype=Xl.dtype)
        sx, sy, vs, W, G, Xy, yy = lax.fori_loop(
            0,
            nc,
            body,
            (
                zero((d,)), zero(()), zero((d,)), zero(()),
                zero((d, bw)), zero((d,)), zero(()),
            ),
        )
        sx = lax.psum(sx, DP_AXIS)
        sy = lax.psum(sy, DP_AXIS)
        vs = lax.psum(vs, DP_AXIS)
        n = lax.psum(W, DP_AXIS)
        G = lax.psum(G, DP_AXIS)
        Xy = lax.psum(Xy, DP_AXIS)
        yy = lax.psum(yy, DP_AXIS)

        dx, dy = sx / n, sy / n
        var = vs / n - dx * dx             # shifted: stable for any |μ|
        dx_b = (
            lax.dynamic_slice_in_dim(dx, blk0, bw, 0) if n_mp > 1 else dx
        )
        if fit_intercept:
            # re-center the shifted statistics at the true weighted means
            G = G - n * jnp.outer(dx, dx_b)
            Xy = Xy - n * dx * dy
            yy = yy - n * dy * dy
            mean_x, mean_y = mu_x + dx, mu_y + dy
        else:
            mean_x = jnp.zeros((d,), Xl.dtype)
            mean_y = jnp.asarray(0.0, Xl.dtype)
        return n, mean_x, mean_y, G, Xy, yy, var

    args = (X, mask, y) + ((row_w,) if row_w is not None else ())
    in_specs = (LAYOUT.rows(),) * len(args)
    g_spec = LAYOUT.cols() if n_mp > 1 else LAYOUT.replicated()
    n, mean_x, mean_y, G, Xy, yy, var = shard_map(
        per_device,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(LAYOUT.replicated(), LAYOUT.replicated(), LAYOUT.replicated(), g_spec, LAYOUT.replicated(), LAYOUT.replicated(), LAYOUT.replicated()),
        check_vma=False,
    )(*args)
    return {
        "n": n, "mean_x": mean_x, "mean_y": mean_y,
        "G": G, "Xy": Xy, "yy": yy, "var": var,
    }


def _to_standardized(stats: Dict[str, jax.Array], standardization: bool):
    """Scale the quadratic system into standardized-coefficient space."""
    std = jnp.sqrt(jnp.maximum(stats["var"], 0.0))
    safe = jnp.where(std > 0, std, 1.0)
    if standardization:
        G = stats["G"] / jnp.outer(safe, safe)
        Xy = stats["Xy"] / safe
    else:
        G = stats["G"]
        Xy = stats["Xy"]
    return G, Xy, std, safe


@functools.partial(jax.jit, static_argnames=("standardization",))
def solve_normal(
    stats: Dict[str, jax.Array], l2: jax.Array, *, standardization: bool
) -> Tuple[jax.Array, jax.Array]:
    """Closed-form OLS/ridge: (G/n + λ₂I) β = Xy/n, Cholesky on device.

    Replaces the reference's eig solver path (``regression.py:502-559``).
    Returns (coefficients in original scale, intercept).
    """
    n = stats["n"]
    G, Xy, std, safe = _to_standardized(stats, standardization)
    d = G.shape[0]
    A = G / n + l2 * jnp.eye(d, dtype=G.dtype)
    # dtype-scaled jitter keeps Cholesky PD for exactly-collinear features
    # (a fixed 1e-10 underflows in f32 against a unit-scale diagonal)
    jitter = jnp.finfo(G.dtype).eps * jnp.trace(A)
    A = A + jitter * jnp.eye(d, dtype=G.dtype)
    beta = jax.scipy.linalg.cho_solve(jax.scipy.linalg.cho_factor(A), Xy / n)
    if standardization:
        beta = jnp.where(std > 0, beta / safe, 0.0)
    intercept = stats["mean_y"] - stats["mean_x"] @ beta
    return beta, intercept


@functools.partial(jax.jit, static_argnames=("standardization", "max_iter"))
def solve_elasticnet(
    stats: Dict[str, jax.Array],
    l1: jax.Array,
    l2: jax.Array,
    *,
    standardization: bool,
    max_iter: int,
    tol: float,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """FISTA on the precomputed quadratic form — replaces cuML ``CDMG``.

    grad f(β) = (Gβ - Xy)/n + λ₂β ; prox = soft-threshold at λ₁/L.
    L is bounded by power iteration on G/n. Entirely replicated d×d math:
    zero data passes, zero collectives per iteration.
    Returns (coefficients, intercept, n_iter).
    """
    n = stats["n"]
    G, Xy, std, safe = _to_standardized(stats, standardization)
    d = G.shape[0]
    Gn = G / n
    b = Xy / n

    # Lipschitz constant: power iteration for λmax(G/n). The start vector is
    # pseudo-random (an all-ones start can be exactly orthogonal to the top
    # eigenvector, e.g. for a feature and its negation, collapsing L to ~0
    # and blowing up the first FISTA step); if the iterate still collapses,
    # fall back to the Frobenius norm, a guaranteed λmax upper bound.
    def power_body(_, v):
        v = Gn @ v
        return v / jnp.maximum(jnp.linalg.norm(v), 1e-30)

    v0 = jnp.cos(jnp.arange(d, dtype=G.dtype) * 1.61803398875 + 0.5)
    v0 = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-30)
    v = lax.fori_loop(0, 16, power_body, v0)
    fro = jnp.sqrt((Gn * Gn).sum())
    L_pow = (v @ (Gn @ v)) / jnp.maximum(v @ v, 1e-30)
    L_smooth = jnp.where(L_pow > 1e-6 * fro, L_pow * 1.01, fro)
    L = L_smooth + l2 + 1e-12

    def soft(x, t):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)

    def cond(state):
        _, _, _, it, delta = state
        return jnp.logical_and(it < max_iter, delta > tol)

    def body(state):
        beta, z, t, it, _ = state
        grad = Gn @ z - b + l2 * z
        beta_new = soft(z - grad / L, l1 / L)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_new = beta_new + ((t - 1.0) / t_new) * (beta_new - beta)
        delta = jnp.abs(beta_new - beta).max()
        return (beta_new, z_new, t_new, it + 1, delta)

    beta0 = jnp.zeros((d,), G.dtype)
    state = (beta0, beta0, jnp.asarray(1.0, G.dtype), jnp.asarray(0), jnp.asarray(jnp.inf, G.dtype))
    beta, _, _, it, _ = lax.while_loop(cond, body, state)
    if standardization:
        beta = jnp.where(std > 0, beta / safe, 0.0)
    intercept = stats["mean_y"] - stats["mean_x"] @ beta
    return beta, intercept, it


@functools.partial(jax.jit, static_argnames=("standardization", "max_iter"))
def solve_elasticnet_batched(
    stats: Dict[str, jax.Array],
    l1: jax.Array,
    l2: jax.Array,
    *,
    standardization: bool,
    max_iter: int,
    tol: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Gang-lane FISTA: B elastic-net solves over ONE shared quadratic form.

    ``l1``/``l2``/``tol`` are traced ``(B,)`` lane arrays; the power
    iteration for the smooth Lipschitz bound runs once (it only depends on
    G/n) and each lane gets ``L = L_smooth + l2[b]``. One ``lax.while_loop``
    runs until every lane meets its own tol, with converged lanes frozen by
    ``jnp.where(active, new, old)`` — the same freeze contract as
    ``minimize_lbfgs_batched``. Returns (coefficients ``(B, d)``,
    intercepts ``(B,)``, n_iter ``(B,)``).
    """
    n = stats["n"]
    G, Xy, std, safe = _to_standardized(stats, standardization)
    d = G.shape[0]
    B = l1.shape[0]
    Gn = G / n
    b = Xy / n

    def power_body(_, v):
        v = Gn @ v
        return v / jnp.maximum(jnp.linalg.norm(v), 1e-30)

    v0 = jnp.cos(jnp.arange(d, dtype=G.dtype) * 1.61803398875 + 0.5)
    v0 = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-30)
    v = lax.fori_loop(0, 16, power_body, v0)
    fro = jnp.sqrt((Gn * Gn).sum())
    L_pow = (v @ (Gn @ v)) / jnp.maximum(v @ v, 1e-30)
    L_smooth = jnp.where(L_pow > 1e-6 * fro, L_pow * 1.01, fro)
    L = L_smooth + l2 + 1e-12  # (B,)

    def soft(x, t):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)

    def cond(state):
        _, _, _, it, delta = state
        return jnp.any(jnp.logical_and(it < max_iter, delta > tol))

    def body(state):
        beta, z, t, it, delta = state
        active = jnp.logical_and(it < max_iter, delta > tol)  # (B,)
        grad = jnp.einsum("de,be->bd", Gn, z) + l2[:, None] * z - b[None, :]
        beta_new = soft(z - grad / L[:, None], (l1 / L)[:, None])
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_new = beta_new + ((t - 1.0) / t_new)[:, None] * (beta_new - beta)
        delta_new = jnp.abs(beta_new - beta).max(axis=1)
        beta = jnp.where(active[:, None], beta_new, beta)
        z = jnp.where(active[:, None], z_new, z)
        t = jnp.where(active, t_new, t)
        delta = jnp.where(active, delta_new, delta)
        it = it + active.astype(jnp.int32)
        return (beta, z, t, it, delta)

    beta0 = jnp.zeros((B, d), G.dtype)
    state = (
        beta0,
        beta0,
        jnp.ones((B,), G.dtype),
        jnp.zeros((B,), jnp.int32),
        jnp.full((B,), jnp.inf, G.dtype),
    )
    beta, _, _, it, _ = lax.while_loop(cond, body, state)
    if standardization:
        beta = jnp.where((std > 0)[None, :], beta / safe[None, :], 0.0)
    intercept = stats["mean_y"] - beta @ stats["mean_x"]
    return beta, intercept, it
