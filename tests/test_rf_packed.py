"""Packed-forest inference engine tests: the FIL-style lockstep layout
(``ops/tree_kernels.pack_forest`` + the ``rf_pallas.packed_traverse``
kernel + the model dispatch layer) must be BIT-IDENTICAL to the per-tree
two-hop bins descent — leaf routing is integer comparisons and the
payload reduction replicates the bins path's association exactly, so
equality is exact, not approximate."""

import os
import subprocess
import sys

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import (
    RandomForestClassificationModel,
    RandomForestClassifier,
)
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.regression import (
    RandomForestRegressionModel,
    RandomForestRegressor,
)


def _blobs(n=400, d=10, k=3, seed=0, spread=0.4):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * 4
    labels = rng.integers(0, k, size=n)
    X = centers[labels] + spread * rng.normal(size=(n, d))
    return X.astype(np.float32), labels.astype(np.float64)


def _reg_data(n=400, d=6, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, size=(n, d))
    y = np.sin(X[:, 0]) * 3 + X[:, 1] ** 2 + 0.5 * X[:, 2]
    return X.astype(np.float32), y.astype(np.float64)


def _random_forest(rng, T, depth, d, nb):
    """Heap-ordered (feat, thrb) with consistent leaf structure: children
    of leaves are leaves (the builder's invariant pack_forest relies on)."""
    from spark_rapids_ml_tpu.ops.tree_kernels import max_nodes

    M = max_nodes(depth)
    feat = rng.integers(0, d, size=(T, M)).astype(np.int32)
    thrb = rng.integers(0, nb - 1, size=(T, M)).astype(np.int32)
    for t in range(T):
        for i in range(M):
            p = (i - 1) // 2
            if i >= (1 << depth) - 1 or (i > 0 and feat[t, p] < 0):
                feat[t, i] = -1
            elif rng.random() < 0.2:
                feat[t, i] = -1
    return feat, thrb


# ---------------------------------------------------------------------------
# kernel-level parity
# ---------------------------------------------------------------------------


def test_packed_descent_matches_python_oracle(monkeypatch):
    """pack_forest + forest_apply_packed (interpret-forced kernel) vs a
    per-row python heap walk: identical leaf heap indices across depths
    spanning k2=0 (hop-1-only) and the kernel path, tree counts off the
    pad-of-8 boundary, and a feature width beyond one 64-lane word."""
    import jax

    import spark_rapids_ml_tpu.ops.rf_pallas as rfp
    from spark_rapids_ml_tpu.ops.tree_kernels import (
        forest_apply_packed,
        pack_forest,
    )

    monkeypatch.setattr(rfp, "FORCE_INTERPRET", True)
    rng = np.random.default_rng(17)
    try:
        for depth, T, n, d, nb in [
            (5, 5, 100, 12, 32),    # k2 = 0: no kernel, hop-1 only
            (7, 7, 257, 130, 64),   # k2 = 0 at the k1 cap; d > 128 lanes
            (9, 9, 400, 16, 64),    # k2 = 2: kernel path
            (13, 4, 300, 8, 64),    # k2 = 6: deepest supported subtree
        ]:
            feat, thrb = _random_forest(rng, T, depth, d, nb)
            xb = rng.integers(0, nb, size=(n, d), dtype=np.uint8)

            def descend(t, row):
                i = 0
                while feat[t, i] >= 0:
                    i = 2 * i + 1 + int(xb[row, feat[t, i]] > thrb[t, i])
                return i

            oracle = np.array(
                [[descend(t, r) for r in range(n)] for t in range(T)]
            ).T  # (n, T)
            pf = pack_forest(feat, thrb, max_depth=depth)
            got = np.asarray(
                forest_apply_packed(
                    np.asarray(xb),
                    pf.feat1, pf.thr1, pf.feat2, pf.thr2,
                    k1=pf.k1, k2=pf.k2, max_depth=depth,
                )
            )
            np.testing.assert_array_equal(got[:, :T], oracle)
    finally:
        jax.clear_caches()


def test_packed_eval_bit_identical_to_bins(monkeypatch):
    """rf_eval_packed vs rf_eval_bins on the same forest: the payload
    accumulation replicates the bins path's group-of-8 association, so
    the float sums are bit-identical, not merely close."""
    import jax

    import spark_rapids_ml_tpu.ops.rf_pallas as rfp
    from spark_rapids_ml_tpu.ops.tree_kernels import (
        pack_forest,
        rf_eval_bins,
        rf_eval_packed,
    )

    monkeypatch.setattr(rfp, "FORCE_INTERPRET", True)
    rng = np.random.default_rng(23)
    try:
        for depth, T, n, d, nb in [(9, 9, 400, 16, 64), (5, 5, 100, 12, 32)]:
            feat, thrb = _random_forest(rng, T, depth, d, nb)
            vals = rng.normal(size=feat.shape + (3,)).astype(np.float32)
            xb = rng.integers(0, nb, size=(n, d), dtype=np.uint8)
            ref = np.asarray(
                rf_eval_bins(
                    np.asarray(xb), np.asarray(feat), np.asarray(thrb),
                    np.asarray(vals), max_depth=depth,
                )
            )
            pf = pack_forest(feat, thrb, max_depth=depth)
            got = np.asarray(
                rf_eval_packed(
                    np.asarray(xb),
                    pf.feat1, pf.thr1, pf.feat2, pf.thr2, np.asarray(vals),
                    k1=pf.k1, k2=pf.k2, max_depth=depth,
                )
            )
            np.testing.assert_array_equal(got, ref)
    finally:
        jax.clear_caches()


# ---------------------------------------------------------------------------
# model-level parity
# ---------------------------------------------------------------------------


# The deep/wide shapes cost ~30s each in interpret mode for the same packed
# traversal path as (5, 7); they stay on --runslow to keep tier-1 in budget.
@pytest.mark.parametrize(
    "depth,trees",
    [
        (5, 7),
        pytest.param(9, 9, marks=pytest.mark.slow),
        pytest.param(11, 5, marks=pytest.mark.slow),
    ],
)
def test_rf_transform_packed_matches_bins(monkeypatch, depth, trees):
    """TPUML_RF_APPLY=packed (interpret-forced kernel) must reproduce the
    bins descent bit-for-bit at the model level — every output column,
    classification AND regression. A spy proves the traversal kernel
    actually ran when the depth requires it (else the packed gate could
    silently fall back and this would compare bins against bins)."""
    import jax

    import spark_rapids_ml_tpu.ops.rf_pallas as rfp

    monkeypatch.setattr(rfp, "FORCE_INTERPRET", True)
    calls = []
    real = rfp.packed_traverse

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    # forest_apply_packed resolves packed_traverse from rf_pallas at call
    # time (function-local import), so this patch is seen by the engine
    monkeypatch.setattr(rfp, "packed_traverse", spy)

    X, y = _blobs(seed=depth)
    df = DataFrame({"features": X, "label": y})
    dfq = DataFrame({"features": X})
    try:
        m = RandomForestClassifier(
            numTrees=trees, maxDepth=depth, seed=3, num_workers=1
        ).fit(df)
        monkeypatch.setenv("TPUML_RF_APPLY", "bins")
        out_b = m.transform(dfq)
        monkeypatch.setenv("TPUML_RF_APPLY", "packed")
        assert m._packed_apply_ready()
        out_p = m.transform(dfq)
        needs_kernel = m._ensure_packed().k2 > 0
        assert bool(calls) == needs_kernel, (calls, needs_kernel)
        for c in ("prediction", "probability", "rawPrediction"):
            a, b = np.asarray(out_b[c]), np.asarray(out_p[c])
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b, err_msg=c)

        Xr, yr = _reg_data(seed=depth)
        dfr = DataFrame({"features": Xr, "label": yr})
        mr = RandomForestRegressor(
            numTrees=trees, maxDepth=depth, seed=5, num_workers=1
        ).fit(dfr)
        monkeypatch.setenv("TPUML_RF_APPLY", "bins")
        pb = np.asarray(mr.transform(dfr)["prediction"])
        monkeypatch.setenv("TPUML_RF_APPLY", "packed")
        pp = np.asarray(mr.transform(dfr)["prediction"])
        np.testing.assert_array_equal(pb, pp)
    finally:
        jax.clear_caches()


def test_rf_packed_save_load_roundtrip(monkeypatch, tmp_path):
    """Persistence: saving a model after packing stores the packed SoA
    tensors; a reload is PRE-PACKED (pack_forest never reruns) and its
    packed predictions are bit-identical to the original's."""
    import jax

    import spark_rapids_ml_tpu.models.tree as mt
    import spark_rapids_ml_tpu.ops.rf_pallas as rfp

    monkeypatch.setattr(rfp, "FORCE_INTERPRET", True)
    X, y = _blobs(seed=31)
    df = DataFrame({"features": X, "label": y})
    dfq = DataFrame({"features": X})
    try:
        m = RandomForestClassifier(
            numTrees=6, maxDepth=9, seed=3, num_workers=1
        ).fit(df)
        monkeypatch.setenv("TPUML_RF_APPLY", "packed")
        out1 = m.transform(dfq)
        assert m._model_attributes.get("packed_feat1") is not None

        path = str(tmp_path / "rf_model")
        m.write().overwrite().save(path)

        import spark_rapids_ml_tpu.ops.tree_kernels as tk

        def boom(*a, **k):
            raise AssertionError("pack_forest reran on a pre-packed reload")

        monkeypatch.setattr(tk, "pack_forest", boom)
        m2 = RandomForestClassificationModel.load(path)
        pf1, pf2 = m._ensure_packed(), m2._ensure_packed()
        assert (pf1.n_trees, pf1.k1, pf1.k2, pf1.max_depth) == (
            pf2.n_trees, pf2.k1, pf2.k2, pf2.max_depth
        )
        np.testing.assert_array_equal(pf1.feat1, pf2.feat1)
        np.testing.assert_array_equal(pf1.thr2, pf2.thr2)
        out2 = m2.transform(dfq)
        for c in ("prediction", "probability", "rawPrediction"):
            np.testing.assert_array_equal(
                np.asarray(out1[c]), np.asarray(out2[c]), err_msg=c
            )
    finally:
        jax.clear_caches()


def test_rf_apply_mode_validation(monkeypatch):
    """Typos in TPUML_RF_APPLY must error, not silently select a path."""
    X, y = _blobs(n=60, seed=2)
    df = DataFrame({"features": X, "label": y})
    m = RandomForestClassifier(numTrees=2, maxDepth=3, seed=1).fit(df)
    monkeypatch.setenv("TPUML_RF_APPLY", "packd")
    with pytest.raises(ValueError, match="TPUML_RF_APPLY"):
        m.transform(df)


def test_rf_finite_input_contract(monkeypatch):
    """Fit rejects non-finite features outright; transform does when the
    opt-in TPUML_RF_CHECK_FINITE=1 boundary check is on (binize would
    otherwise silently route NaN to bin 0)."""
    X, y = _blobs(n=80, seed=4)
    Xbad = X.copy()
    Xbad[3, 2] = np.nan
    with pytest.raises(ValueError, match="NaN/Inf"):
        RandomForestClassifier(numTrees=2, maxDepth=3, seed=1).fit(
            DataFrame({"features": Xbad, "label": y})
        )

    m = RandomForestClassifier(numTrees=2, maxDepth=3, seed=1).fit(
        DataFrame({"features": X, "label": y})
    )
    monkeypatch.setenv("TPUML_RF_APPLY", "bins")
    monkeypatch.setenv("TPUML_RF_CHECK_FINITE", "1")
    with pytest.raises(ValueError, match="NaN/Inf"):
        m.transform(DataFrame({"features": Xbad}))
    # and the guard stays out of the way for clean inputs
    m.transform(DataFrame({"features": X}))


def test_export_random_forest_packed():
    """export.random_forest_packed surfaces the cached SoA layout with
    real-tree metadata (serving integrations read this, not the model's
    private attributes)."""
    from spark_rapids_ml_tpu.export import random_forest_packed

    X, y = _blobs(n=100, seed=8)
    m = RandomForestClassifier(numTrees=5, maxDepth=6, seed=2).fit(
        DataFrame({"features": X, "label": y})
    )
    pk = random_forest_packed(m)
    assert pk["meta"]["n_trees"] == 5
    assert pk["feat1"].shape[0] % 8 == 0
    k1, k2 = pk["meta"]["k1"], pk["meta"]["k2"]
    assert k1 + k2 == m._max_depth_built
    assert pk["feat1"].shape[1] == (1 << k1) - 1
    if k2 == 0:
        assert pk["feat2"].shape == (0, 64)
    else:
        assert pk["feat2"].shape == (pk["feat1"].shape[0] * (1 << k1), 64)
    with pytest.raises(TypeError):
        random_forest_packed(object())


# ---------------------------------------------------------------------------
# bench smoke
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_bench_rf_transform_smoke(tmp_path):
    """bench.py at smoke scale must emit rf.transform_vs_baseline (the
    packed-engine serving metric) and umap.transform_vs_baseline —
    BENCH_REQUIRE_TRANSFORM=rf makes a silently dropped rf transform
    figure a nonzero exit."""
    import json

    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        BENCH_ONLY="rf,umap",
        BENCH_REQUIRE_TRANSFORM="rf",
        BENCH_ROWS="4096",
        BENCH_RF_ROWS="4096",
        BENCH_RF_TREES="4",
        BENCH_RF_DEPTH="8",
        BENCH_UMAP_ROWS="1024",
    )
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True, text=True, timeout=900, env=env, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    rf = line["rf"]
    assert "transform_vs_baseline" in rf
    assert rf["transform_engine"] in ("packed", "bins")
    assert "transform_vs_baseline" in line["umap"]
