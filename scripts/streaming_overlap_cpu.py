"""Demonstrate decode/compute overlap of ops.streaming.prefetch_chunks on
the local CPU backend (the tunnel serializes transfers behind a
~0.06 GB/s link, so the bench's overlap_efficiency cannot show there —
BENCH_NOTES round-5 note).

Producer: a generator that sleeps per chunk (GIL-releasing, modeling
I/O-bound parquet decode — a busy-wait would contend with the CPU
backend's compute for the same cores and make the measurement noise on
small hosts). Consumer: the library's streamed accumulation. With the
prefetch thread, producer time hides under device compute; without it,
the two serialize.

Run:  JAX_PLATFORMS=cpu python scripts/streaming_overlap_cpu.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp

from spark_rapids_ml_tpu.data.chunks import Chunk
from spark_rapids_ml_tpu.ops.streaming import (
    StreamGuard, gram2_init, gram2_step, prefetch_chunks, put_chunk,
)
from spark_rapids_ml_tpu.parallel.mesh import make_mesh

N_CHUNKS = 24
CHUNK_ROWS = 8192
D = 512
DECODE_S = 0.02  # simulated per-chunk decode cost


def chunks():
    rng = np.random.default_rng(0)
    base = rng.standard_normal((CHUNK_ROWS, D)).astype(np.float32)
    for i in range(N_CHUNKS):
        time.sleep(DECODE_S)  # I/O-bound "decode" (releases the GIL)
        yield Chunk(X=base * np.float32(1 + i * 1e-6), n_valid=CHUNK_ROWS)


def run(prefetch: bool) -> float:
    mesh = make_mesh()
    mean0 = jnp.zeros((D,), jnp.float32)
    acc = gram2_init(D, jnp.float32, False)
    guard = StreamGuard()
    it = prefetch_chunks(chunks()) if prefetch else chunks()
    t0 = time.perf_counter()
    for chunk in it:
        dev = put_chunk(chunk, mesh, np.float32, need_y=False, need_w=False)
        acc = gram2_step(acc, dev["X"], dev["mask"], mean0)
        guard.tick(dev, acc["G"])
    guard.flush(acc["G"])
    np.asarray(acc["G"])
    return time.perf_counter() - t0


def main():
    run(True)  # warm compiles
    t_serial = run(False)
    t_prefetch = run(True)
    decode_total = N_CHUNKS * DECODE_S
    hidden = t_serial - t_prefetch
    print(f"serial   : {t_serial:.3f}s  (decode {decode_total:.2f}s + compute)")
    print(f"prefetch : {t_prefetch:.3f}s")
    print(f"overlap  : {hidden:.3f}s of producer time hidden "
          f"({100 * hidden / decode_total:.0f}% of decode)")
    if hidden < 0.25 * decode_total:
        # demo, not a CI gate (tests/test_streaming.py holds that line):
        # on a 1-core host the measurement jitters run-to-run
        print("WARNING: prefetch hid <25% of decode on this run — "
              "re-run; persistent low overlap means a regression")
    else:
        print("OK")


if __name__ == "__main__":
    main()
