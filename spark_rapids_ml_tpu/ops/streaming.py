"""Streaming (out-of-core) accumulation kernels.

The reference holds the whole per-worker partition on device and lets cuML
reduce over it (UVM for beyond-HBM datasets,
``/root/reference/python/src/spark_rapids_ml/core.py:699-741``).  The
TPU-native scheme: fixed-shape host chunks stream through a small device
buffer; these jitted steps fold each chunk into replicated accumulator
state.  Chunks are row-sharded over the ``dp`` mesh axis and accumulators
are replicated, so XLA's SPMD partitioner inserts exactly one psum of each
partial per chunk — the same communication the reference's NCCL allreduce
performed, amortized over chunks.

Accumulators are donated (``donate_argnums=0``) so device memory stays
constant across chunks: one chunk slab + O(d²) state, independent of n.

Numerics: means first, centered Gram second (two passes) — the same
center-before-Gram discipline as the in-memory kernels (``ops/linalg.py``),
avoiding the f32 catastrophic cancellation of one-pass covariance.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..data.chunks import Chunk, ChunkSource
from ..parallel.mesh import row_sharding
from ..runtime import autotune, counters, envspec, opsplane, telemetry
from ..runtime.faults import SimulatedPreemption, fault_site
from ..runtime.scheduler import preempt_point
from ..runtime.retry import (
    backoff_schedule,
    is_resource_exhausted,
    resolve_backoff_ms,
    resolve_retries,
)
from ..utils.logging import get_logger

_res_logger = get_logger("streaming.resilience")
_wire_logger = get_logger("streaming.wire")


# ---------------------------------------------------------------------------
# Chunk transfer
# ---------------------------------------------------------------------------

# host-side backpressure period for streaming loops (chunks between syncs);
# 0 disables
_SYNC_EVERY = int(envspec.get("TPUML_STREAM_SYNC_EVERY"))

_release_err_logged = False


def _release_buffers(arrays) -> None:
    """``delete()`` retired chunk buffers (device slabs + the client's
    retained host copies).

    A failed delete is never fatal — results don't depend on it — but a
    swallowed one hides a leak that grows with total bytes shipped: each
    failure bumps the ``wire_release_errors`` counter and the first in the
    process is debug-logged with the exception, so a nonzero bench/test
    delta points straight at the cause.
    """
    global _release_err_logged
    for a in arrays:
        if a is None:
            continue
        try:
            a.delete()
        except Exception as exc:
            counters.bump("wire_release_errors")
            if not _release_err_logged:
                _release_err_logged = True
                _wire_logger.debug(
                    "chunk buffer release failed (first occurrence; further "
                    "ones only bump wire_release_errors): %r", exc,
                )


class StreamGuard:
    """Bounds host (and device) memory of a streaming loop.

    ``device_put`` transfers are async and a host decodes parquet chunks
    far faster than a tunnel-attached device drains them; with nothing in
    the loop ever synchronizing, pending transfers pin every chunk's host
    buffer (observed: a 100M-row north-star run was OOM-killed on the HOST
    at 130 GB RSS mid-pass). On the tunnel backend, dropping the Python
    references is not enough: the client retains a host-side copy of a
    transferred buffer until that EXACT buffer is deleted — deleting only
    an array derived from it (e.g. the on-device f32 upcast of an f16 wire
    chunk) releases nothing (observed: RSS kept growing at the ingest rate
    when only derived arrays were deleted). ``put_chunk`` therefore hands
    the guard the raw transferred arrays under ``"_wire"``.

    Every ``_SYNC_EVERY`` chunks — and at :meth:`flush`, which every loop
    MUST call at the end (short passes would otherwise never sync at all)
    — the guard (1) host-fetches one accumulator scalar: the accumulator
    depends on every chunk folded so far, so the fetch PROVES all enqueued
    transfers and steps completed (``jax.block_until_ready`` is NOT
    sufficient on remote backends — it can return at dispatch
    acknowledgment, see docs/tpu_kernel_notes.md); then (2) ``delete()``s
    the retired chunk arrays, releasing device buffers and the client's
    host copies.

    The guard holds strong references to up to ``_SYNC_EVERY`` chunks of
    device buffers between syncs (they are freed only once proven
    retired), so the streaming device footprint is ``_SYNC_EVERY`` chunk
    slabs, not one — sized into the default period below.
    """

    def __init__(self) -> None:
        self._pending: list = []
        self._i = 0

    def _sync_and_release(self, acc) -> None:
        with telemetry.span("stream.sync", pending=len(self._pending)):
            leaf = jax.tree_util.tree_leaves(acc)[0]
            np.asarray(jnp.ravel(leaf)[:1])
            _release_buffers(self._pending)
            self._pending.clear()

    def tick(self, dev, acc) -> None:
        for v in dev.values():
            if v is None:
                continue
            if isinstance(v, (list, tuple)):
                self._pending.extend(v)
            else:
                self._pending.append(v)
        self._i += 1
        if _SYNC_EVERY > 0 and self._i % _SYNC_EVERY == 0:
            self._sync_and_release(acc)

    def flush(self, acc) -> None:
        """Sync + release the tail; call after every streaming loop."""
        if self._pending:
            self._sync_and_release(acc)


def prefetch_chunks(it, depth: Optional[int] = None):
    """Background-thread chunk prefetch (double buffering).

    The streaming loops alternate host work (parquet decode / synthetic
    gen in ``iter_chunks``) with device work (transfer + step) and
    periodic StreamGuard syncs that BLOCK the host. Without prefetch the
    host sits idle during those waits and the device sits idle during
    decode — serial. A bounded producer thread decodes chunk i+1 (and
    i+2, ...) while the main thread transfers/folds chunk i, so wall
    time approaches max(decode, device) instead of their sum
    (asserted by ``tests/test_streaming.py`` on a synthetic slow source).

    ``depth`` bounds look-ahead (host memory: depth chunk buffers).
    TPUML_STREAM_PREFETCH=0 disables (returns ``it`` unchanged); the
    env value otherwise sets the default depth (2).

    Early consumer exit (exception mid-loop) sets a cancel flag the
    producer polls between puts, so the daemon thread cannot wedge on a
    full queue holding the source open.
    """
    if depth is None:
        depth = int(envspec.get("TPUML_STREAM_PREFETCH"))
    if depth <= 0:
        yield from it
        return

    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    end = object()
    cancel = threading.Event()
    err: list = []

    def worker():
        try:
            src = iter(it)
            while True:
                # span covers the source's decode of ONE chunk (parquet
                # read / synthetic gen), not the backpressured put
                with telemetry.span("stream.decode"):
                    c = next(src, end)
                if c is end:
                    break
                while not cancel.is_set():
                    try:
                        q.put(c, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if cancel.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised on consumer
            err.append(e)
        finally:
            while not cancel.is_set():
                try:
                    q.put(end, timeout=0.1)
                    break
                except queue.Full:
                    continue

    th = threading.Thread(
        # the bound context parents this thread's decode spans under the
        # caller's ingest span
        target=telemetry.bind_context(worker),
        name="tpuml-chunk-prefetch",
        daemon=True,
    )
    th.start()
    try:
        while True:
            # fail fast, but deliver what was produced: chunks already in
            # the queue predate the failure and are valid; once the queue
            # is empty and the producer has recorded an error, raise
            # immediately instead of waiting for the end sentinel behind
            # `depth` buffered puts
            if err:
                try:
                    c = q.get_nowait()
                except queue.Empty:
                    # the internal Empty is not part of the user's error;
                    # re-raise the worker's exception object WITH the
                    # traceback it captured in the producer thread, so the
                    # failing frame (parquet decode, injected ingest fault,
                    # ...) is visible from the consumer
                    raise err[0].with_traceback(err[0].__traceback__) from None
            else:
                c = q.get()
            if c is end:
                break
            yield c
        if err:
            raise err[0].with_traceback(err[0].__traceback__)
    finally:
        # Callers that abandon the generator early should close() it (the
        # `finally` then runs promptly); an unclosed-but-unreferenced
        # generator only cancels the producer when GC collects it, until
        # which the daemon thread spins on 0.1 s put timeouts.
        cancel.set()


# ---------------------------------------------------------------------------
# Wire formats (TPUML_WIRE_DTYPE) — fewer bytes over the host->device link
# ---------------------------------------------------------------------------

# float8 e4m3 finite max (S.1111.110 -> 448); quantization maps each
# column's observed absmax onto it
_F8_MAX = 448.0

# auto-probe acceptance thresholds: relative RMS reconstruction error of
# the FIRST chunk under each encoding (cost model + derivation:
# docs/streaming_performance.md; dispatch behavior pinned by
# tests/test_streaming_wire.py)
_AUTO_INT8_TOL = 2e-2
_AUTO_F16_TOL = 2e-3


@jax.tree_util.register_pytree_node_class
class QuantizedWire:
    """A streamed chunk living on device in its quantized wire encoding.

    Fold steps accept this in place of the dense ``X`` and call
    :func:`wire_dense` first thing INSIDE their jit: the dequantize (one
    fused multiply-add per element) happens where the step reads the data,
    so the wide matrix never materializes between transfer and fold — the
    only host->device traffic was the narrow buffer plus two O(d) scale
    vectors. Being a pytree, it crosses the jit boundary as its leaves;
    the target dtype rides in the (static) treedef, so each encoding gets
    exactly one fold-step trace.

    ``offset`` is None for the scale-only encoding (f8).
    """

    def __init__(self, q, scale, offset, dtype):
        self.q = q
        self.scale = scale
        self.offset = offset
        self.dtype = jnp.dtype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale, self.offset), self.dtype

    @classmethod
    def tree_unflatten(cls, dtype, children):
        return cls(*children, dtype)

    def dense(self) -> jax.Array:
        x = self.q.astype(self.dtype) * self.scale.astype(self.dtype)
        if self.offset is not None:
            x = x + self.offset.astype(self.dtype)
        return x

    def delete(self) -> None:
        """StreamGuard-compatible release of the underlying buffers."""
        for a in (self.q, self.scale, self.offset):
            if a is not None:
                a.delete()


def wire_dense(X):
    """Resolve a fold-step ``X`` argument to a dense matrix.

    Every jitted fold step calls this on entry: a :class:`QuantizedWire`
    dequantizes HERE — inside the caller's jit — and a plain array passes
    through untouched (zero cost on the default path).
    """
    return X.dense() if isinstance(X, QuantizedWire) else X


def _quantize_int8(
    x: np.ndarray, n_valid: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-chunk-column affine int8: ``x ~ q * scale + offset``.

    Ranges come from the VALID rows only (padding rows quantize to
    whatever clips — every fold step multiplies them away by the mask).
    A constant column gets scale 1 so the reconstruction is exact.
    """
    v = x[:n_valid] if 0 < n_valid < x.shape[0] else x
    lo = v.min(axis=0).astype(np.float32)
    hi = v.max(axis=0).astype(np.float32)
    scale = ((hi - lo) / np.float32(254.0)).astype(np.float32)
    scale = np.where(scale > 0, scale, np.float32(1.0))
    offset = ((hi + lo) * np.float32(0.5)).astype(np.float32)
    # in-place pipeline: this runs per chunk on the ingest-critical path,
    # so avoid stacking several chunk-sized float temporaries
    q = x - offset
    q /= scale
    np.rint(q, out=q)
    np.clip(q, -127, 127, out=q)
    return q.astype(np.int8), scale, offset


@functools.lru_cache(maxsize=1)
def _f8_dtype() -> Optional[np.dtype]:
    """numpy dtype of the e4m3 wire encoding, or None when the toolchain
    lacks it (``ml_dtypes`` ships with jax, but gate rather than assume)."""
    try:
        import ml_dtypes

        return np.dtype(ml_dtypes.float8_e4m3fn)
    except Exception:
        try:
            return np.dtype(jnp.float8_e4m3fn)
        except Exception:
            return None


@functools.lru_cache(maxsize=1)
def _f8_supported() -> bool:
    """True when f8 buffers round-trip through the live backend (the
    dtype exists AND device_put + upcast lower on this platform)."""
    f8 = _f8_dtype()
    if f8 is None:
        return False
    try:
        np.asarray(
            jnp.asarray(np.ones((2,), f8)).astype(jnp.float32)
        )
        return True
    except Exception:
        return False


def _quantize_f8(
    x: np.ndarray, n_valid: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-chunk-column scaled e4m3: ``x ~ q * scale`` with each column's
    absmax mapped to the f8 finite max (no offset: e4m3's ~2 decimal
    digits are spent on relative precision instead)."""
    v = x[:n_valid] if 0 < n_valid < x.shape[0] else x
    amax = np.abs(v).max(axis=0).astype(np.float32)
    scale = np.where(amax > 0, amax / np.float32(_F8_MAX), np.float32(1.0))
    q = (x / scale).astype(_f8_dtype())
    return q, scale


def resolve_wire_dtype() -> str:
    """Parsed+validated ``TPUML_WIRE_DTYPE`` (EnvSpecError on bad values)."""
    return str(envspec.get("TPUML_WIRE_DTYPE"))


def _probe_quant_error(x: np.ndarray, kind: str) -> float:
    """Relative RMS reconstruction error of encoding ``x`` as ``kind``."""
    v = np.asarray(x, np.float32)
    if kind == "int8":
        q, scale, offset = _quantize_int8(v, v.shape[0])
        rec = q.astype(np.float32) * scale + offset
    else:  # f16
        rec = v.astype(np.float16).astype(np.float32)
    rms = float(np.sqrt(np.mean(v * v)))
    return float(np.sqrt(np.mean((rec - v) ** 2))) / max(rms, 1e-12)


def _tune_wire_format(x: np.ndarray, heuristic: str, mesh) -> str:
    """Measured refinement of the ``auto`` wire pick (TPUML_AUTOTUNE).

    Candidates are the encodings AT LEAST as accurate as the heuristic's
    error-probed choice (the accuracy gate stays with the error probe —
    the tuner only ever trades bytes against encode cost among formats
    the tolerance contract already admits), heuristic first. Fitness is
    the measured encode + device_put + on-device upcast-reduce of the
    first chunk — the per-chunk ingest-path cost the knob controls."""
    ladder = ["int8", "f16", "f32"]  # narrowest (most lossy) first
    feasible = ladder[ladder.index(heuristic):]
    if len(feasible) < 2:
        return heuristic
    candidates = [heuristic] + [w for w in feasible if w != heuristic]
    key = autotune.shape_key(
        n=x.shape[0],
        d=x.shape[1] if x.ndim > 1 else 0,
        dtype=x.dtype,
        mesh=mesh,
        storage=str(x.dtype),
    )

    def measure(w: str) -> float:
        t0 = time.perf_counter()
        if w == "int8":
            q, scale, offset = _quantize_int8(x, x.shape[0])
            buf: np.ndarray = q
        elif w == "f16":
            buf = x.astype(np.float16)
        else:
            buf = np.ascontiguousarray(x, np.float32)
        dev = jax.device_put(buf, row_sharding(mesh))
        jnp.sum(jnp.asarray(dev, jnp.float32)).block_until_ready()
        return time.perf_counter() - t0

    tuned = autotune.tune("wire_dtype", key, candidates, measure, reps=2)
    return tuned if tuned in feasible else heuristic


def select_wire_format(
    sample_X: np.ndarray, requested: Optional[str] = None, mesh=None
) -> str:
    """Resolve the wire encoding for one streaming pass (never ``auto``).

    ``requested`` overrides the env (None = read ``TPUML_WIRE_DTYPE``).
    Same dispatch contract as ``TPUML_UMAP_OPT``: ``auto`` gates on a
    probe — the first chunk's quantization error under int8 (then f16)
    against the documented tolerances — and an explicit request that is
    infeasible on this host/backend WARNS and falls back instead of
    failing the fit. Non-float storage always ships as-is (``f32``).

    With ``TPUML_AUTOTUNE`` on and a ``mesh``, the ``auto`` pick is
    further refined by measurement (:func:`_tune_wire_format`) among
    the formats the error tolerances admit; explicit requests
    (including the ``f32`` default) are never second-guessed.
    """
    kind = resolve_wire_dtype() if requested is None else str(requested)
    x = np.asarray(sample_X)
    if x.dtype.kind != "f":
        return "f32"
    if kind == "auto":
        err8 = _probe_quant_error(x, "int8")
        if err8 <= _AUTO_INT8_TOL:
            kind = "int8"
        elif _probe_quant_error(x, "f16") <= _AUTO_F16_TOL:
            kind = "f16"
        else:
            kind = "f32"
        _wire_logger.info(
            "TPUML_WIRE_DTYPE=auto: int8 probe error %.2e -> wire %s",
            err8, kind,
        )
        if autotune.active() and mesh is not None:
            kind = _tune_wire_format(x, kind, mesh)
    if kind == "f8" and not _f8_supported():
        _wire_logger.warning(
            "TPUML_WIRE_DTYPE=f8 requested but float8_e4m3 is unavailable "
            "on this toolchain/backend; falling back to f16"
        )
        kind = "f16"
    return kind


def put_chunk(
    chunk: Chunk, mesh, dtype, *, need_y: bool = True, need_w: bool = True,
    wire: str = "f32",
) -> Dict[str, Optional[jax.Array]]:
    """device_put one host chunk row-sharded over dp.  Transfers are async:
    the next chunk's H2D overlaps the current chunk's accumulation step.

    Wire dtype (``wire``, a RESOLVED ``select_wire_format`` value — never
    ``auto``): ``int8`` / ``f8`` quantize per chunk column on host and ship
    the 1-byte buffer plus O(d) scales, returning ``X`` as a
    :class:`QuantizedWire` the fold step dequantizes inside its jit;
    ``f16`` downcasts wide float storage on host and upcasts on device.
    Independent of the knob, a chunk stored in a float NARROWER than the
    compute dtype (e.g. float16 parquet) ships as-is and upcasts ON DEVICE.
    Fewer wire bytes attack the streaming bottleneck on any interconnect
    (PCIe, or the remote tunnel's ~30 MB/s); the default ``f32`` keeps the
    historical byte-identical behavior.

    ``need_y`` / ``need_w``: callers whose accumulation step does not
    consume the label / weight column MUST pass False — the column is then
    never transferred. This both saves wire bytes and preserves the
    StreamGuard invariant that the accumulator fetch proves every enqueued
    transfer completed: an array the step never reads would otherwise sit
    in the guard's pending list with nothing proving its transfer retired
    before ``delete()``."""
    fault_site("ingest:chunk")
    sh = row_sharding(mesh)
    x_host = np.asarray(chunk.X)
    wire_bufs = None
    if wire in ("int8", "f8") and x_host.dtype.kind == "f":
        # every array below is a buffer the client ACTUALLY transferred
        # (and retains a host copy of); they ride along under "_wire" so
        # StreamGuard deletes THEM, not just arrays derived on device
        from ..parallel.mesh import replicated

        rep = replicated(mesh)
        if wire == "int8":
            q, scale, offset = _quantize_int8(x_host, chunk.n_valid)
        else:
            q, scale = _quantize_f8(x_host, chunk.n_valid)
            offset = None
        qd = jax.device_put(q, sh)
        sd = jax.device_put(scale, rep)
        od = None if offset is None else jax.device_put(offset, rep)
        X: Any = QuantizedWire(qd, sd, od, jnp.dtype(dtype))
        wire_bufs = [a for a in (qd, sd, od) if a is not None]
    elif x_host.dtype.kind == "f" and x_host.dtype.itemsize < np.dtype(dtype).itemsize:
        # narrow float STORAGE pass-through (also where wire="f16" lands
        # once the host buffer is already f16)
        narrow = jax.device_put(x_host, sh)
        X = jnp.asarray(narrow, dtype=dtype)
        wire_bufs = narrow
    elif wire == "f16" and x_host.dtype.kind == "f" and x_host.dtype.itemsize > 2:
        narrow = jax.device_put(x_host.astype(np.float16), sh)
        X = jnp.asarray(narrow, dtype=dtype)
        wire_bufs = narrow
    else:
        X = jax.device_put(np.asarray(x_host, dtype=dtype), sh)
    out: Dict[str, Optional[jax.Array]] = {
        "X": X,
        "mask": jax.device_put(chunk.mask(dtype), sh),
        "y": None,
        "w": None,
        "_wire": wire_bufs,
    }
    if need_y and chunk.y is not None:
        out["y"] = jax.device_put(np.asarray(chunk.y, dtype=dtype), sh)
    if need_w and chunk.w is not None:
        out["w"] = jax.device_put(np.asarray(chunk.w, dtype=dtype), sh)
    return out


def _split_chunk(chunk: Chunk, row_mult: int) -> Optional[Tuple[Chunk, Chunk]]:
    """Split a chunk into two row-slabs, each a multiple of ``row_mult``.

    ``row_mult`` is the dp mesh size — the sharding divisibility every
    ``put_chunk`` row dimension must satisfy. Returns None when the chunk
    is already at the minimum splittable size.
    """
    rows = chunk.X.shape[0]
    if rows < 2 * row_mult or rows % row_mult != 0:
        return None
    half = (rows // 2 // row_mult) * row_mult
    half = max(half, row_mult)

    def slab(lo: int, hi: int) -> Chunk:
        return Chunk(
            X=chunk.X[lo:hi],
            n_valid=int(np.clip(chunk.n_valid - lo, 0, hi - lo)),
            y=None if chunk.y is None else chunk.y[lo:hi],
            w=None if chunk.w is None else chunk.w[lo:hi],
        )

    return slab(0, half), slab(half, rows)


def stage_chunks(
    chunk: Chunk, mesh, dtype, *, need_y: bool = True, need_w: bool = True,
    wire: str = "f32",
):
    """Stage ``chunk`` on device, degrading gracefully under failure.

    Yields ``(piece, dev)`` pairs — normally exactly one, the whole chunk.
    With a retry budget (``TPUML_RETRIES`` > 0):

    - a RESOURCE_EXHAUSTED staging failure halves the chunk (at a dp-size
      row multiple, preserving sharding divisibility) and stages the
      halves independently, recursively down to one row-slab per dp rank —
      an allocator-pressure spike degrades throughput instead of killing
      the fit;
    - any other staging failure is retried on the env backoff schedule;
    - :class:`SimulatedPreemption` is terminal, never absorbed.

    With the default env (no budget) this is one ``put_chunk`` call — the
    clean path stays byte-identical. The accumulation steps downstream are
    per-chunk sum-folds, so a split chunk folds to the same result as the
    whole one (halves carry correctly sliced ``n_valid``/labels/weights).
    """
    budget = resolve_retries()
    if budget <= 0:
        with telemetry.span("stream.stage", rows=chunk.X.shape[0]):
            dev = put_chunk(
                chunk, mesh, dtype, need_y=need_y, need_w=need_w, wire=wire
            )
        yield chunk, dev
        return
    import time as _time

    delays = backoff_schedule(budget, resolve_backoff_ms())
    row_mult = max(1, int(mesh.shape.get("dp", 1)))
    attempts = 0
    pending = [chunk]
    while pending:
        piece = pending[0]
        try:
            with telemetry.span("stream.stage", rows=piece.X.shape[0]):
                dev = put_chunk(
                    piece, mesh, dtype, need_y=need_y, need_w=need_w,
                    wire=wire,
                )
        except SimulatedPreemption:
            raise
        except Exception as exc:
            if is_resource_exhausted(exc):
                halves = _split_chunk(piece, row_mult)
                if halves is not None:
                    counters.bump("chunk_halvings")
                    _res_logger.warning(
                        "chunk staging hit RESOURCE_EXHAUSTED (%s); halving "
                        "%d rows -> 2 x %d-row slabs",
                        exc,
                        piece.X.shape[0],
                        halves[0].X.shape[0],
                    )
                    pending[0:1] = list(halves)
                    continue
            if attempts >= budget:
                raise
            counters.bump("retries")
            _res_logger.warning(
                "chunk staging failed (attempt %d/%d): %s — retrying in %.0f ms",
                attempts + 1,
                budget + 1,
                exc,
                delays[attempts],
            )
            _time.sleep(delays[attempts] / 1000.0)
            attempts += 1
            continue
        pending.pop(0)
        yield piece, dev


# provenance of the most recent ingest pipeline in this process (resolved
# wire dtype + ring depths); the estimator layer copies it onto fitted
# models as ``model._ingest_report``
_LAST_INGEST: Dict[str, Any] = {}


def last_ingest_report() -> Dict[str, Any]:
    """Copy of the most recent :func:`iter_device_chunks` configuration."""
    return dict(_LAST_INGEST)


def _staged_chunks(chunks, mesh, dtype, *, need_y, need_w, wire, depth):
    """Device-staging ring stage of the ingest pipeline.

    A background thread pulls decoded chunks, wire-encodes them
    (quantization for int8/f8 is real host CPU work) and issues the async
    ``device_put``, keeping up to ``depth`` staged chunks buffered ahead
    of the consumer. The consumer's fold dispatch — and crucially the
    StreamGuard's periodic BLOCKING syncs — no longer serialize against
    encode+transfer of the next chunks.

    Single producer + FIFO queue: yields ``(chunk, dev)`` strictly in
    source order at any depth. Cancel/error discipline is identical to
    :func:`prefetch_chunks` (same close-promptly caveat).
    """
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    end = object()
    cancel = threading.Event()
    err: list = []

    def worker():
        try:
            for chunk in chunks:
                # span covers wire-encode + async device_put of ONE
                # chunk, not the backpressured put
                with telemetry.span("stream.stage", rows=chunk.X.shape[0]):
                    dev = put_chunk(
                        chunk, mesh, dtype,
                        need_y=need_y, need_w=need_w, wire=wire,
                    )
                while not cancel.is_set():
                    try:
                        q.put((chunk, dev), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if cancel.is_set():
                    return
                # ops-plane liveness: occupancy right after this put
                # plus a heartbeat, so /statusz distinguishes a wedged
                # stage thread from a fold-bound one
                telemetry.gauge("ingest_ring_occupancy").set(q.qsize())
                telemetry.gauge("loop_heartbeat_ts").set(
                    time.monotonic(), loop="stream_stage"
                )
        except BaseException as e:  # noqa: BLE001 — re-raised on consumer
            err.append(e)
        finally:
            while not cancel.is_set():
                try:
                    q.put(end, timeout=0.1)
                    break
                except queue.Full:
                    continue

    th = threading.Thread(
        # bound context: the ring thread's stage spans nest under the
        # consumer's ingest span
        target=telemetry.bind_context(worker),
        name="tpuml-chunk-stage",
        daemon=True,
    )
    th.start()
    try:
        while True:
            if err:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    raise err[0].with_traceback(err[0].__traceback__) from None
            else:
                item = q.get()
            if item is end:
                break
            yield item
        if err:
            raise err[0].with_traceback(err[0].__traceback__)
    finally:
        cancel.set()


def iter_device_chunks(
    source: ChunkSource,
    mesh,
    chunk_rows: int,
    dtype,
    *,
    need_y: bool = True,
    need_w: bool = True,
    wire: Optional[str] = None,
):
    """The shared multi-stage ingest pipeline of every streaming loop.

    Yields ``(piece, dev)`` pairs in source order. Stages, each a bounded
    ring so host memory stays O(depth) chunk buffers:

    1. **decode** — :func:`prefetch_chunks` runs ``source.iter_chunks``
       (parquet decode / synthetic gen) on a background thread,
       ``TPUML_STREAM_PREFETCH`` deep;
    2. **stage** — :func:`_staged_chunks` wire-encodes and issues the
       async ``device_put`` up to ``TPUML_STREAM_STAGE_DEPTH`` chunks
       ahead, so decode, host->device transfer, and the fold step
       genuinely overlap instead of serializing;
    3. **fold** — the caller accumulates and ``guard.tick``s as before.

    The wire encoding is resolved ONCE from the first chunk
    (:func:`select_wire_format`: env request, ``auto`` probe, fallback)
    and pinned for the whole pass, so every chunk shares one encoding and
    one fold-step trace. Ordering — and therefore every accumulator
    result — is independent of both depths (single producer per stage,
    FIFO rings); ``tests/test_streaming_wire.py`` pins that.

    With a retry budget (``TPUML_RETRIES`` > 0) staging happens on the
    consumer thread where :func:`stage_chunks` can halve/retry
    synchronously — the ring is bypassed (resilience wins over overlap).
    """
    import contextlib
    import itertools

    np_dtype = np.dtype(jnp.dtype(dtype).name)
    # a streamed fit is the long-lived loop the ops plane wants to
    # watch; no-op unless TPUML_OPS_PORT/TPUML_FLIGHT_DIR opted in
    opsplane.ensure_started()
    it = prefetch_chunks(source.iter_chunks(chunk_rows, np_dtype))
    # manual enter/exit: a `with` around a generator body would not
    # survive the consumer abandoning the iterator mid-pass
    ingest_span = telemetry.span("stream.ingest")
    ingest_span.__enter__()
    try:
        first = next(it, None)
        if first is None:
            return
        kind = select_wire_format(first.X, requested=wire, mesh=mesh)
        depth = int(envspec.get("TPUML_STREAM_STAGE_DEPTH"))
        if not envspec.is_set("TPUML_STREAM_STAGE_DEPTH") and autotune.active():
            # consult-only: a ring depth cannot be measured from inside
            # one pipeline pass, so entries come from the bench probe
            # (bench.py autotune) rather than an in-situ search
            depth_key = autotune.shape_key(
                n=first.X.shape[0],
                d=first.X.shape[1] if first.X.ndim > 1 else 0,
                dtype=np_dtype,
                mesh=mesh,
            )
            tuned_depth = autotune.consult("stream_stage_depth", depth_key)
            if isinstance(tuned_depth, int) and 0 <= tuned_depth <= 64:
                depth = tuned_depth
            else:
                autotune.record_heuristic("stream_stage_depth", depth_key, depth)
        _LAST_INGEST.clear()
        _LAST_INGEST.update(
            wire_dtype=kind,
            stage_depth=depth,
            prefetch_depth=int(envspec.get("TPUML_STREAM_PREFETCH")),
        )
        ingest_span.set_attr(wire=kind, stage_depth=depth)
        # staged slabs resident ahead of the fold: the streaming analog
        # of the gang/tree-batch budget gauges
        telemetry.record_hbm_estimate(
            "stream_stage", float(first.X.nbytes) * float(max(1, depth))
        )
        chunks = itertools.chain([first], it)
        if depth > 0 and resolve_retries() <= 0:
            staged = _staged_chunks(
                chunks, mesh, dtype,
                need_y=need_y, need_w=need_w, wire=kind, depth=depth,
            )
        else:
            staged = (
                pair
                for chunk in chunks
                for pair in stage_chunks(
                    chunk, mesh, dtype,
                    need_y=need_y, need_w=need_w, wire=kind,
                )
            )
        with contextlib.closing(staged) as staged_it:
            for i, (piece, dev) in enumerate(staged_it):
                telemetry.gauge("loop_heartbeat_ts").set(
                    time.monotonic(), loop="stream_ingest"
                )
                # the fold span brackets the yield: it measures the
                # CONSUMER's accumulate/dispatch work on this chunk
                fold_span = telemetry.span("stream.fold", chunk=i)
                fold_span.__enter__()
                try:
                    yield piece, dev
                finally:
                    fold_span.__exit__(None, None, None)
    finally:
        it.close()
        ingest_span.__exit__(None, None, None)


# ---------------------------------------------------------------------------
# Pass 1: weighted first moments
# ---------------------------------------------------------------------------


def moments1_init(d: int, dtype, with_y: bool) -> Dict[str, jax.Array]:
    acc = {
        "n": jnp.zeros((), dtype),
        "sum_x": jnp.zeros((d,), dtype),
    }
    if with_y:
        acc["sum_y"] = jnp.zeros((), dtype)
    return acc


@functools.partial(jax.jit, donate_argnums=(0,))
def moments1_step(
    acc: Dict[str, jax.Array],
    X: jax.Array,
    rw: jax.Array,
    y: Optional[jax.Array] = None,
) -> Dict[str, jax.Array]:
    """Fold one chunk into (Σw, Σw·x [, Σw·y]).  ``rw`` = mask·weight."""
    X = wire_dense(X)
    out = dict(acc)
    out["n"] = acc["n"] + rw.sum()
    out["sum_x"] = acc["sum_x"] + (X * rw[:, None]).sum(axis=0)
    if y is not None:
        out["sum_y"] = acc["sum_y"] + (y * rw).sum()
    return out


# ---------------------------------------------------------------------------
# Pass 2: centered second moments (Gram / cross / residual)
# ---------------------------------------------------------------------------


def gram2_init(d: int, dtype, with_y: bool, mesh=None) -> Dict[str, jax.Array]:
    """Zero second-moment accumulators. With ``mesh`` (a 2-D mesh whose mp
    extent divides ``d`` — gate via ``ops.linalg.mp_gram_blocks``) the d×d
    Gram is created column-sharded over mp (``LAYOUT.cols()``) from host
    zeros, so each device ever allocates only its (d, d/mp) block; the
    blocked step keeps it there across donated folds."""
    if mesh is not None:
        from jax.sharding import NamedSharding

        from ..parallel.layout import LAYOUT

        cols = NamedSharding(mesh, LAYOUT.cols())
        acc = {"G": jax.device_put(np.zeros((d, d), dtype), cols)}
    else:
        acc = {"G": jnp.zeros((d, d), dtype)}
    if with_y:
        acc["Xy"] = jnp.zeros((d,), dtype)
        acc["yy"] = jnp.zeros((), dtype)
    return acc


@functools.partial(jax.jit, donate_argnums=(0,))
def gram2_step(
    acc: Dict[str, jax.Array],
    X: jax.Array,
    rw: jax.Array,
    mean_x: jax.Array,
    y: Optional[jax.Array] = None,
    mean_y: Optional[jax.Array] = None,
) -> Dict[str, jax.Array]:
    """Fold one chunk into G=(Xc√w)'(Xc√w) [, Xy, yy] centered at mean."""
    X = wire_dense(X)
    sw = jnp.sqrt(rw)
    Xc = (X - mean_x[None, :]) * sw[:, None]
    out = dict(acc)
    out["G"] = acc["G"] + Xc.T @ Xc
    if y is not None:
        yc = (y - mean_y) * sw
        out["Xy"] = acc["Xy"] + Xc.T @ yc
        out["yy"] = acc["yy"] + (yc * yc).sum()
    return out


@functools.partial(
    jax.jit, donate_argnums=(0,), static_argnames=("mesh",)
)
def gram2_step_blocked(
    acc: Dict[str, jax.Array],
    X: jax.Array,
    rw: jax.Array,
    mean_x: jax.Array,
    y: Optional[jax.Array] = None,
    mean_y: Optional[jax.Array] = None,
    *,
    mesh,
) -> Dict[str, jax.Array]:
    """:func:`gram2_step` with the Gram accumulator pinned column-sharded
    over the mesh's mp axis: the sharding constraint makes GSPMD compute
    each device's ``XcᵀXc`` column panel in place (the SUMMA product of the
    blocked resident scan), so the fold never materializes a full d×d per
    device. Init with ``gram2_init(..., mesh=mesh)``."""
    from jax.sharding import NamedSharding

    from ..parallel.layout import LAYOUT

    X = wire_dense(X)
    sw = jnp.sqrt(rw)
    Xc = (X - mean_x[None, :]) * sw[:, None]
    cols = NamedSharding(mesh, LAYOUT.cols())
    out = dict(acc)
    out["G"] = jax.lax.with_sharding_constraint(acc["G"] + Xc.T @ Xc, cols)
    if y is not None:
        yc = (y - mean_y) * sw
        out["Xy"] = acc["Xy"] + Xc.T @ yc
        out["yy"] = acc["yy"] + (yc * yc).sum()
    return out


# ---------------------------------------------------------------------------
# KMeans chunk steps (streamed Lloyd / seeding)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("matmul_dtype",))
def kmeans_chunk_step(
    acc: Dict[str, jax.Array],
    X: jax.Array,
    mask: jax.Array,
    centers: jax.Array,
    matmul_dtype=None,
) -> Dict[str, jax.Array]:
    """Fold one chunk's assignment statistics into (sums, counts, cost).

    ``matmul_dtype``: see ``kmeans_kernels.pairwise_sq_dists`` — the
    resident kernel's bf16-operand option, same semantics here."""
    from .kmeans_kernels import pairwise_sq_dists, stats_dot

    X = wire_dense(X)
    k = centers.shape[0]
    d2 = pairwise_sq_dists(X, centers, matmul_dtype=matmul_dtype)
    assign = jnp.argmin(d2, axis=1)
    onehot = jax.nn.one_hot(assign, k, dtype=X.dtype) * mask[:, None]
    return {
        "sums": acc["sums"] + stats_dot(onehot, X, matmul_dtype),
        "counts": acc["counts"] + onehot.sum(axis=0).astype(jnp.int32),
        "cost": acc["cost"] + (jnp.min(d2, axis=1) * mask).sum(),
    }


@jax.jit
def chunk_min_sq_dists(
    X: jax.Array, mask: jax.Array, centers: jax.Array
) -> jax.Array:
    """Per-row min squared distance to any center (padding rows -> 0)."""
    from .kmeans_kernels import pairwise_sq_dists

    return jnp.min(pairwise_sq_dists(wire_dense(X), centers), axis=1) * mask


@functools.partial(jax.jit, donate_argnums=(0,))
def count_closest_chunk_step(
    counts: jax.Array, X: jax.Array, mask: jax.Array, cands: jax.Array
) -> jax.Array:
    """Fold one chunk into per-candidate closest-row counts (k-means||
    candidate weighting).  ``counts`` is int32: a float32 accumulator would
    silently drop small per-chunk increments past ~2²⁴ rows — the exact
    regime the out-of-core path exists for."""
    from .kmeans_kernels import pairwise_sq_dists

    X = wire_dense(X)
    d2 = pairwise_sq_dists(X, cands)
    assign = jnp.argmin(d2, axis=1)
    onehot = jax.nn.one_hot(assign, cands.shape[0], dtype=X.dtype) * mask[:, None]
    return counts + onehot.sum(axis=0).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Logistic-regression chunk steps (streamed L-BFGS objective)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0,))
def var_chunk_step(
    acc: jax.Array, X: jax.Array, rw: jax.Array, mean: jax.Array
) -> jax.Array:
    """Fold one chunk into Σ w·(x-mean)² (diagonal-only second moment —
    cheaper than the full Gram when only feature variances are needed)."""
    X = wire_dense(X)
    d = (X - mean[None, :]) * jnp.sqrt(rw)[:, None]
    return acc + (d * d).sum(axis=0)


@functools.partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("n_classes", "multinomial", "fit_intercept", "use_center"),
)
def logreg_chunk_vg_step(
    acc: Dict[str, jax.Array],
    X: jax.Array,
    mask: jax.Array,
    y: jax.Array,
    wflat: jax.Array,
    mean: jax.Array,
    inv_std: jax.Array,
    *,
    n_classes: int,
    multinomial: bool,
    fit_intercept: bool,
    use_center: bool,
) -> Dict[str, jax.Array]:
    """Fold one chunk's data log-loss and its gradient w.r.t. the flat
    parameter vector into the accumulator.

    Same objective as the resident kernel (``ops/logreg_kernels.py``):
    standardization is a reparametrization folded into the logits, not a
    data copy. The regularization terms are added once on the host, not
    per chunk.
    """
    X = wire_dense(X)
    dtype = X.dtype
    d = X.shape[1]
    K = n_classes if multinomial else 1
    n_coef = K * d
    yi = y.astype(jnp.int32)
    yf = y.astype(dtype)

    def chunk_loss(wf: jax.Array) -> jax.Array:
        A = wf[:n_coef].reshape(K, d)
        b = wf[n_coef:] if fit_intercept else jnp.zeros((K,), dtype)
        Aeff = A * inv_std[None, :]
        beff = b - (Aeff @ mean if use_center else jnp.zeros((), dtype))
        logits = X @ Aeff.T + beff[None, :]
        if multinomial:
            ll = jax.nn.logsumexp(logits, axis=1) - jnp.take_along_axis(
                logits, yi[:, None], axis=1
            )[:, 0]
        else:
            z = logits[:, 0]
            ll = jax.nn.softplus(z) - yf * z
        return (ll * mask).sum()

    f, g = jax.value_and_grad(chunk_loss)(wflat)
    return {"f": acc["f"] + f, "g": acc["g"] + g}


def streamed_suffstats(
    source: ChunkSource,
    mesh,
    chunk_rows: int,
    dtype,
    *,
    with_y: bool = False,
    fit_intercept: bool = True,
) -> Dict[str, jax.Array]:
    """Two streaming passes -> the same stats dict as
    ``ops.linreg_kernels.linreg_suffstats`` (n, mean_x, mean_y, G, Xy, yy,
    var) / the inputs of ``mean_and_cov`` — so every downstream solver
    (Cholesky OLS/ridge, FISTA elasticnet, eigh PCA) is reused unchanged.
    """
    from ..parallel.mesh import allreduce_sum_host

    d = source.n_features

    acc1 = moments1_init(d, dtype, with_y)
    guard = StreamGuard()
    # closing() so an exception in the loop body tears down the pipeline
    # threads promptly instead of at GC time (caveat on prefetch_chunks).
    with telemetry.span("suffstats.pass", which="moments"):
        with contextlib.closing(
            iter_device_chunks(source, mesh, chunk_rows, dtype, need_y=with_y)
        ) as chunks:
            for _, dev in chunks:
                rw = dev["mask"] if dev["w"] is None else dev["mask"] * dev["w"]
                acc1 = moments1_step(
                    acc1, dev["X"], rw, dev["y"] if with_y else None
                )
                guard.tick(dev, acc1)
        guard.flush(acc1)
    # cross-process allreduce of the first-moment partials (the NCCL
    # allreduce analog; identity single-process)
    if with_y:
        n_h, sx_h, sy_h = allreduce_sum_host(acc1["n"], acc1["sum_x"], acc1["sum_y"])
    else:
        n_h, sx_h = allreduce_sum_host(acc1["n"], acc1["sum_x"])
        sy_h = None
    n = jnp.asarray(n_h, dtype)
    mean_all = jnp.asarray(sx_h, dtype) / n
    if fit_intercept:
        mean_x = mean_all
        mean_y = (jnp.asarray(sy_h, dtype) / n) if with_y else None
    else:
        mean_x = jnp.zeros((d,), dtype)
        mean_y = jnp.zeros((), dtype) if with_y else None

    # blocked (mp-column-sharded) Gram accumulation when the mesh has a
    # model axis and the gate allows it — env resolved here, outside jit
    from .linalg import mp_gram_blocks

    mp = mp_gram_blocks(mesh, d)
    acc2 = gram2_init(d, dtype, with_y, mesh=mesh if mp > 1 else None)
    step = (
        functools.partial(gram2_step_blocked, mesh=mesh)
        if mp > 1
        else gram2_step
    )
    guard = StreamGuard()
    with telemetry.span("suffstats.pass", which="gram"):
        with contextlib.closing(
            iter_device_chunks(source, mesh, chunk_rows, dtype, need_y=with_y)
        ) as chunks:
            for _, dev in chunks:
                rw = dev["mask"] if dev["w"] is None else dev["mask"] * dev["w"]
                acc2 = step(
                    acc2, dev["X"], rw, mean_x,
                    dev["y"] if with_y else None, mean_y,
                )
                guard.tick(dev, acc2)
        guard.flush(acc2)
    mp_report = None
    if mp > 1:
        mp_report = {
            "mp_degree": mp,
            "gram_shard_bytes": int(
                acc2["G"].addressable_shards[0].data.nbytes
            ),
        }
    if with_y:
        G_h, Xy_h, yy_h = allreduce_sum_host(acc2["G"], acc2["Xy"], acc2["yy"])
    else:
        (G_h,) = allreduce_sum_host(acc2["G"])
        Xy_h = yy_h = None
    G = jnp.asarray(G_h, dtype)

    var = jnp.diagonal(G) / n
    if not fit_intercept:
        var = var - mean_all * mean_all
    stats: Dict[str, jax.Array] = {
        "n": n,
        "mean_x": mean_x,
        "mean_all": mean_all,
        "G": G,
        "var": var,
    }
    if with_y:
        stats["mean_y"] = mean_y
        stats["Xy"] = jnp.asarray(Xy_h, dtype)
        stats["yy"] = jnp.asarray(yy_h, dtype)
    if mp_report:
        stats["_mp_report"] = mp_report
    return stats


def streamed_logreg_fit(
    source: ChunkSource,
    mesh,
    chunk_rows: int,
    dtype,
    *,
    n_classes: int,
    multinomial: bool,
    fit_intercept: bool,
    standardization: bool,
    l1: float,
    l2: float,
    max_iter: int,
    tol: float,
    history: int = 10,
    checkpointer=None,
) -> Dict[str, np.ndarray]:
    """Out-of-core logistic regression: host-driven L-BFGS/OWL-QN where each
    objective evaluation streams the dataset through the device in chunks.

    Numerically mirrors the resident kernel (``ops/logreg_kernels.py``):
    same standardization-as-reparametrization, Spark objective
    (1/n)·Σ logloss + λ[(1−α)/2‖β‖₂² + α‖β‖₁] with the penalty on
    standardized coefficients and never on intercepts, same multinomial
    intercept centering. The O(m·p) quasi-Newton math runs on host in f64;
    every line-search trial is one chunked data pass (exactly the
    re-read-per-iteration cost cuML's out-of-core QN pays, reference
    ``classification.py:955-1140``).
    """
    from ..parallel.mesh import allreduce_sum_host

    from .lbfgs import minimize_lbfgs_host

    d = source.n_features
    np_dtype = np.dtype(jnp.dtype(dtype).name)

    # pass 1: n + feature means (partials allreduced across processes)
    acc1 = moments1_init(d, dtype, with_y=False)
    guard = StreamGuard()
    with contextlib.closing(
        iter_device_chunks(
            source, mesh, chunk_rows, dtype, need_y=False, need_w=False
        )
    ) as chunks:
        for _, dev in chunks:
            acc1 = moments1_step(acc1, dev["X"], dev["mask"])
            guard.tick(dev, acc1)
    guard.flush(acc1)
    n_h, sx_h = allreduce_sum_host(acc1["n"], acc1["sum_x"])
    n = float(n_h)
    mean = jnp.asarray(sx_h, dtype) / jnp.asarray(n, dtype)

    if standardization:
        # pass 2: diagonal second moment -> unbiased variance (n-1), the
        # reference's denominator (``classification.py:1024-1026``)
        vacc = jnp.zeros((d,), dtype)
        guard = StreamGuard()
        with contextlib.closing(
            iter_device_chunks(
                source, mesh, chunk_rows, dtype, need_y=False, need_w=False
            )
        ) as chunks:
            for _, dev in chunks:
                vacc = var_chunk_step(vacc, dev["X"], dev["mask"], mean)
                guard.tick(dev, vacc)
        guard.flush(vacc)
        (vacc_h,) = allreduce_sum_host(vacc)
        var = jnp.asarray(vacc_h, dtype) / max(n - 1.0, 1.0)
        std = jnp.sqrt(jnp.maximum(var, 0.0))
        inv_std = jnp.where(std > 0, 1.0 / std, 1.0)
    else:
        inv_std = jnp.ones((d,), dtype)
    use_center = standardization and fit_intercept
    mean_dev = mean if use_center else jnp.zeros((d,), dtype)

    K = n_classes if multinomial else 1
    n_coef = K * d
    p = n_coef + (K if fit_intercept else 0)
    coef_mask = np.concatenate([np.ones(n_coef), np.zeros(p - n_coef)])

    def value_grad(w_np):
        wd = jnp.asarray(w_np, dtype)
        acc = {"f": jnp.zeros((), dtype), "g": jnp.zeros((p,), dtype)}
        guard = StreamGuard()
        with telemetry.span("logreg.objective_pass"):
            with contextlib.closing(
                iter_device_chunks(
                    source, mesh, chunk_rows, dtype, need_w=False
                )
            ) as chunks:
                for _, dev in chunks:
                    acc = logreg_chunk_vg_step(
                        acc, dev["X"], dev["mask"], dev["y"], wd, mean_dev,
                        inv_std,
                        n_classes=n_classes, multinomial=multinomial,
                        fit_intercept=fit_intercept, use_center=use_center,
                    )
                    guard.tick(dev, acc)
            guard.flush(acc)
        # per-evaluation allreduce of (loss, grad) partials — the QN-loop
        # NCCL allreduce of the reference's distributed L-BFGS; every rank
        # then takes identical optimizer steps
        f_h, g_h = allreduce_sum_host(acc["f"], acc["g"])
        coefs = w_np * coef_mask
        f = float(f_h) / n + 0.5 * l2 * float(coefs @ coefs)
        g = np.asarray(g_h, np.float64) / n + l2 * coefs
        return f, g

    res = minimize_lbfgs_host(
        value_grad,
        np.zeros((p,)),
        max_iter=max_iter,
        tol=tol,
        l1_weights=(l1 * coef_mask) if l1 > 0.0 else None,
        history=history,
        checkpointer=checkpointer,
    )

    w = np.asarray(res.w)
    A = w[:n_coef].reshape(K, d)
    b = w[n_coef:] if fit_intercept else np.zeros((K,))
    inv_std_h = np.asarray(inv_std, np.float64)
    mean_h = np.asarray(mean, np.float64)
    coef = A * inv_std_h[None, :]
    intercept = b - (coef @ mean_h if use_center else 0.0)
    if fit_intercept and K > 1:
        intercept = intercept - intercept.mean()
    return {
        "coef_": coef.astype(np_dtype),
        "intercept_": np.asarray(intercept, np_dtype),
        "n_iter": int(res.n_iter),
        "objective": float(res.f),
    }


def streamed_kmeans_lloyd(
    source: ChunkSource,
    mesh,
    chunk_rows: int,
    dtype,
    centers0: np.ndarray,
    *,
    max_iter: int,
    tol: float,
    matmul_dtype=None,
    checkpointer=None,
):
    """Out-of-core Lloyd: one chunked pass per iteration accumulates
    (sums, counts, cost); centroid state stays tiny (k×d). Matches the
    resident ``kmeans_kernels.kmeans_lloyd`` semantics: empty clusters keep
    their previous center (Spark behavior), convergence on max center
    shift² <= tol², plus a final cost pass at the converged centers.
    Returns (centers, cost, n_iter) as host values.

    ``checkpointer`` (a ``runtime.FitCheckpointer``, or None) snapshots
    centers + the last center shift after each Lloyd iteration; resume
    walks the identical centroid sequence (Lloyd is deterministic given
    the centers), including the same termination iteration.
    """
    from ..parallel.mesh import allreduce_sum_host

    k, d = centers0.shape
    centers = jnp.asarray(centers0, dtype)

    def one_pass(cts, mm=matmul_dtype, _it=None):
        acc = {
            "sums": jnp.zeros((k, d), dtype),
            "counts": jnp.zeros((k,), jnp.int32),
            "cost": jnp.zeros((), dtype),
        }
        guard = StreamGuard()
        with telemetry.span("kmeans.lloyd_pass", iteration=_it) as p_span:
            with contextlib.closing(
                iter_device_chunks(
                    source, mesh, chunk_rows, dtype, need_y=False, need_w=False
                )
            ) as chunks:
                for _, dev in chunks:
                    acc = kmeans_chunk_step(
                        acc, dev["X"], dev["mask"], cts, matmul_dtype=mm
                    )
                    guard.tick(dev, acc)
            guard.flush(acc)
            p_span.fence(acc)
        # per-iteration allreduce of (sums, counts, cost) partials — the
        # Lloyd-loop NCCL allreduce; every rank then updates identically
        s_h, c_h, cost_h = allreduce_sum_host(
            acc["sums"], acc["counts"], acc["cost"]
        )
        return {"sums": s_h, "counts": c_h, "cost": cost_h}

    it = 0
    prev_shift = np.inf
    resumed = checkpointer.load() if checkpointer is not None else None
    if resumed is not None:
        it, arrays, extra = resumed
        centers = jnp.asarray(arrays["centers"], dtype)
        prev_shift = float(extra["prev_shift"])
        counters.bump("resumed_fits")
        counters.note("resumed_from", it)
    while it < max_iter and prev_shift > tol * tol:
        fault_site("sgd:epoch")
        acc = one_pass(centers, _it=it)
        sums = np.asarray(acc["sums"], np.float64)
        counts = np.asarray(acc["counts"])
        safe = np.maximum(counts.astype(np.float64), 1.0)
        new_centers = np.where(
            counts[:, None] > 0, sums / safe[:, None], np.asarray(centers, np.float64)
        )
        prev_shift = float(
            ((new_centers - np.asarray(centers, np.float64)) ** 2).sum(axis=1).max()
        )
        centers = jnp.asarray(new_centers, dtype)
        it += 1
        if checkpointer is not None:
            checkpointer.maybe_save(
                it, {"centers": np.asarray(centers)}, {"prev_shift": prev_shift}
            )
            preempt_point(
                checkpointer, it,
                lambda: {"centers": np.asarray(centers)},
                {"prev_shift": prev_shift},
            )

    # final cost pass always f32 (bf16 distance expansion cancels near
    # centroids — see kmeans_kernels.kmeans_lloyd)
    final = one_pass(centers, mm=None, _it="final")
    if checkpointer is not None:
        checkpointer.clear()
    return np.asarray(centers), float(final["cost"]), it


def streamed_label_stats(
    source: ChunkSource, chunk_rows: int
) -> Dict[str, float]:
    """One host pass over the label stream: max/min, integer check, and
    whether all labels are identical — everything the fit needs to pick
    ``n_classes`` (Spark: max(label)+1) without materializing the dataset.
    Combined across the process world so every rank agrees."""
    from ..parallel.mesh import combine_label_summaries

    y_max = -np.inf
    y_min = np.inf
    all_int = True
    first = None
    all_same = True
    n_seen = 0
    for yv in source.iter_labels(chunk_rows):
        if yv.size == 0:
            continue
        n_seen += yv.size
        y_max = max(y_max, float(yv.max()))
        y_min = min(y_min, float(yv.min()))
        if not np.all(yv == np.floor(yv)):
            all_int = False
        if first is None:
            first = float(yv[0])
        if not np.all(yv == first):
            all_same = False

    local = np.asarray(
        [
            0.0 if n_seen else 1.0,
            y_max,
            y_min,
            1.0 if all_int else 0.0,
            first if first is not None else 0.0,
            1.0 if all_same else 0.0,
            float(n_seen),
        ]
    )
    out = combine_label_summaries(local)
    if out["total"] == 0:
        raise ValueError("Labels column is empty")
    return out


# ---------------------------------------------------------------------------
# Streamed k-means|| seeding passes
# ---------------------------------------------------------------------------


def streamed_rows_at(
    source: ChunkSource, chunk_rows: int, idx: np.ndarray, dtype
) -> np.ndarray:
    """Gather rows by global index in ONE sequential pass (host-side).

    The out-of-core replacement for fancy-indexing the resident matrix:
    chunks arrive in order, so each requested (sorted) index is sliced out
    of the chunk that covers it.
    """
    idx = np.sort(np.asarray(idx, np.int64))
    out = np.empty((len(idx), source.n_features), dtype=dtype)
    pos = 0  # next unsatisfied request
    offset = 0
    for chunk in source.iter_chunks(chunk_rows, dtype):
        hi = offset + chunk.n_valid
        while pos < len(idx) and idx[pos] < hi:
            out[pos] = chunk.X[idx[pos] - offset]
            pos += 1
        offset = hi
        if pos == len(idx):
            break
    if pos != len(idx):
        raise IndexError(f"row index {idx[pos]} out of range ({offset} rows)")
    return out


def streamed_min_sq_dists_update(
    source: ChunkSource,
    mesh,
    chunk_rows: int,
    dtype,
    cands: np.ndarray,
    min_d2: Optional[np.ndarray] = None,
) -> np.ndarray:
    """One chunked pass: per-row min squared distance to ``cands``, folded
    into a host ``min_d2`` array (O(n) host floats — 4 bytes/row, the only
    per-row state k-means|| needs; the dataset itself never materializes).
    """
    cands_dev = jnp.asarray(cands, dtype)
    out = (
        np.full((source.n_rows,), np.inf, np.float64)
        if min_d2 is None
        else min_d2
    )
    offset = 0
    with contextlib.closing(
        iter_device_chunks(
            source, mesh, chunk_rows, dtype, need_y=False, need_w=False
        )
    ) as chunks:
        for piece, dev in chunks:
            d2 = np.asarray(
                chunk_min_sq_dists(dev["X"], dev["mask"], cands_dev),
                np.float64,
            )
            # the d2 fetch above proves the step completed; release the
            # chunk's buffers including the raw wire transfer (StreamGuard
            # rationale — retention otherwise grows with total bytes
            # shipped)
            _release_buffers(dev.values())
            nv = piece.n_valid
            np.minimum(
                out[offset : offset + nv],
                d2[:nv],
                out=out[offset : offset + nv],
            )
            offset += nv
    return out


def streamed_count_closest(
    source: ChunkSource, mesh, chunk_rows: int, dtype, cands: np.ndarray
) -> np.ndarray:
    """One chunked pass: for each candidate, how many rows are closest to it
    (the k-means|| candidate weights)."""
    cands_dev = jnp.asarray(cands, dtype)
    counts = jnp.zeros((cands.shape[0],), jnp.int32)
    guard = StreamGuard()
    with contextlib.closing(
        iter_device_chunks(
            source, mesh, chunk_rows, dtype, need_y=False, need_w=False
        )
    ) as chunks:
        for _, dev in chunks:
            counts = count_closest_chunk_step(
                counts, dev["X"], dev["mask"], cands_dev
            )
            guard.tick(dev, counts)
    guard.flush(counts)
    return np.asarray(counts, np.float64)
