"""Live operations plane: the defaults-inert contract (no env => no
socket, no thread, no sink, bit-identical fits), live /metrics and
/statusz scrapes mid-streamed-fit, the /readyz warmup flip, flight
recorder ring bounds and the SIGTERM crash dump (``TPUML_TRACE``
unset), the one-shot SLO burn alert on a synthetic p99 spike, and
rank-tagged flight shard merging via ``scripts/merge_traces.py``.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.models.clustering import KMeans
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.runtime import opsplane, telemetry
from spark_rapids_ml_tpu.serving import ModelRegistry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_OPS_ENVS = (
    "TPUML_OPS_PORT",
    "TPUML_OPS_HOST",
    "TPUML_FLIGHT_DIR",
    "TPUML_FLIGHT_EVENTS",
    "TPUML_SLO_EVAL_MS",
    "TPUML_SLO_BURN_THRESHOLD",
    "TPUML_TRACE",
)


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    for var in _OPS_ENVS:
        monkeypatch.delenv(var, raising=False)
    opsplane.stop()
    telemetry.reset_telemetry()
    yield
    opsplane.stop()
    telemetry.reset_telemetry()


@pytest.fixture(scope="module")
def pca_model():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(96, 6)).astype(np.float32)
    return PCA(k=3).fit(DataFrame({"features": X}))


def _get(path):
    """(status, content-type, body) from the running ops server —
    HTTPError carries the 4xx/5xx bodies the endpoints serve."""
    host, port = opsplane.address()
    url = f"http://{host}:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, r.headers.get("Content-Type", ""), r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type", ""), e.read()


def _ops_threads():
    return [
        t for t in threading.enumerate()
        if t.name.startswith(("tpuml-ops", "tpuml-slo"))
    ]


def _flight_shards(d):
    return sorted(f for f in os.listdir(d) if f.startswith("flight-"))


def _load_by_path(name):
    spec = importlib.util.spec_from_file_location(
        f"_test_ops_{name}", os.path.join(REPO_ROOT, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- defaults inert --------------------------------------------------------


def test_defaults_inert_no_socket_no_thread_no_sink():
    """With neither TPUML_OPS_PORT nor TPUML_FLIGHT_DIR set the plane
    refuses to start: no listening socket, no background thread, no
    span sink (spans stay the shared disabled singleton)."""
    assert opsplane.ensure_started() is False
    assert not opsplane.started()
    assert opsplane.address() is None
    assert opsplane.flight_recorder() is None
    assert _ops_threads() == []
    # no sink attached: the disabled span singleton still short-circuits
    assert telemetry.span("a") is telemetry.span("b", k=1)
    assert telemetry.active_spans() == []


def test_ops_enabled_fit_bit_identical(monkeypatch):
    """A fit under a live ops plane (server + flight sink running) is
    bit-identical to the plain fit — observation must not perturb."""
    rng = np.random.default_rng(1)
    X = rng.normal(size=(512, 4)).astype(np.float32)
    df = DataFrame({"features": X})

    def centers():
        m = KMeans(k=3, maxIter=4, seed=0).setFeaturesCol("features").fit(df)
        return m.cluster_centers_

    plain = centers()
    monkeypatch.setenv("TPUML_OPS_PORT", "0")
    monkeypatch.setenv("TPUML_SLO_EVAL_MS", "60000")
    assert opsplane.ensure_started()
    observed = centers()
    assert plain.tobytes() == observed.tobytes()
    # the sink really saw the fit: the flight ring is non-empty
    assert len(opsplane.flight_recorder()) > 0


# --- endpoints -------------------------------------------------------------


def test_endpoint_shapes_and_routes(monkeypatch):
    monkeypatch.setenv("TPUML_OPS_PORT", "0")  # ephemeral port
    monkeypatch.setenv("TPUML_SLO_EVAL_MS", "60000")
    assert opsplane.ensure_started()
    assert opsplane.ensure_started()  # idempotent
    host, port = opsplane.address()
    assert host == "127.0.0.1" and port > 0

    with telemetry.span("probe"):
        pass

    code, ctype, body = _get("/healthz")
    assert code == 200 and json.loads(body) == {"status": "ok"}

    code, ctype, body = _get("/metrics")
    assert code == 200
    assert ctype.startswith("text/plain")
    lines = body.decode().splitlines()
    assert any(line.startswith("# TYPE tpuml_") for line in lines)
    for line in lines:
        if line and not line.startswith("#"):
            assert line.startswith("tpuml_"), line

    code, _, body = _get("/flight")
    assert code == 200
    doc = json.loads(body)
    assert doc["metadata"]["flight"] is True
    assert "probe" in {
        e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"
    }

    code, _, body = _get("/nope")
    assert code == 404
    assert "/statusz" in json.loads(body)["routes"]

    # the scrapes themselves were metered
    reqs = telemetry.counter("ops_requests_total")
    assert reqs.value(endpoint="metrics") == 1
    assert reqs.value(endpoint="other") == 1


def test_statusz_reports_active_span_tree(monkeypatch):
    monkeypatch.setenv("TPUML_OPS_PORT", "0")
    monkeypatch.setenv("TPUML_SLO_EVAL_MS", "60000")
    assert opsplane.ensure_started()
    with telemetry.span("outer", phase="x"):
        with telemetry.span("inner"):
            code, _, body = _get("/statusz")
    assert code == 200
    st = json.loads(body)
    assert st["pid"] == os.getpid()
    spans = {s["name"]: s for s in st["active_spans"]}
    assert {"outer", "inner"} <= set(spans)
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["outer"]["age_seconds"] >= spans["inner"]["age_seconds"]
    assert st["flight"]["capacity"] > 0


# --- live scrape during a streamed fit -------------------------------------


def test_live_scrape_during_streamed_kmeans_fit(monkeypatch):
    """The satellite contract: a streamed fit auto-starts the plane and
    answers /metrics + /statusz scrapes while chunks are still folding.
    The scrape fires from a span sink on the first completed
    `stream.fold`, so it provably lands mid-fit."""
    monkeypatch.setenv("TPUML_OPS_PORT", "0")
    monkeypatch.setenv("TPUML_SLO_EVAL_MS", "60000")
    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 4)).astype(np.float32)
    df = DataFrame({"features": X})

    scrapes = []

    def scrape_on_fold(ev, thread_name):
        if ev.get("name") == "stream.fold" and not scrapes:
            scrapes.append((_get("/metrics"), _get("/statusz")))

    telemetry.add_span_sink(scrape_on_fold)
    try:
        KMeans(
            k=3, maxIter=2, seed=0, num_workers=2,
            streaming=True, stream_chunk_rows=64,
        ).setFeaturesCol("features").fit(df)
    finally:
        telemetry.remove_span_sink(scrape_on_fold)

    assert opsplane.started()  # iter_device_chunks brought the plane up
    assert scrapes, "no stream.fold span completed during the fit"
    (mcode, mctype, mbody), (scode, _sctype, sbody) = scrapes[0]
    assert mcode == 200 and mctype.startswith("text/plain")
    assert any(
        line.startswith("# TYPE tpuml_")
        for line in mbody.decode().splitlines()
    )
    assert scode == 200
    st = json.loads(sbody)
    # the ingest loop had already filed its heartbeat when we scraped
    assert "stream_ingest" in st["heartbeat_ages_s"]
    assert st["heartbeat_ages_s"]["stream_ingest"] >= 0.0
    # the fit was mid-flight: its ingest span was live in the tree
    assert "stream.ingest" in {s["name"] for s in st["active_spans"]}
    # observation did not destabilize the fit
    storms = telemetry.counter("retrace_storms").value()
    assert not storms


# --- readiness -------------------------------------------------------------


def test_readyz_flips_on_registry_warmup(monkeypatch, pca_model):
    monkeypatch.setenv("TPUML_OPS_PORT", "0")
    monkeypatch.setenv("TPUML_SLO_EVAL_MS", "60000")
    assert opsplane.ensure_started()

    # nothing tracked: liveness + storm check only
    code, _, body = _get("/readyz")
    assert code == 200 and json.loads(body)["ready"]

    reg = ModelRegistry(warmup=False)
    entry = reg.register("pca", pca_model)
    assert entry.coalesce  # premise: pca coalesces on this backend

    code, _, body = _get("/readyz")
    assert code == 503
    payload = json.loads(body)
    assert not payload["ready"]
    assert any("warmup_pending" in r for r in payload["reasons"])
    code, _, body = _get("/statusz")
    st = json.loads(body)
    assert st["ready"] is False
    assert st["registries"][0]["models"]["pca"]["pending_buckets"]

    reg.warm(entry)
    code, _, body = _get("/readyz")
    assert code == 200 and json.loads(body)["ready"]
    code, _, body = _get("/statusz")
    assert json.loads(body)["ready"] is True


# --- flight recorder -------------------------------------------------------


def test_flight_ring_bounded_and_deterministic():
    rec = opsplane.FlightRecorder(4)
    for i in range(100):
        rec.sink(
            {"name": f"e{i}", "ph": "X", "pid": 1, "tid": 7,
             "ts": i, "dur": 1, "args": {}},
            "worker",
        )
    assert len(rec) == 4 and rec.capacity == 4
    doc = rec.document("test")
    xs = [e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert xs == ["e96", "e97", "e98", "e99"]  # deterministic last-N
    threads = {
        e["tid"]: e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert threads == {7: "worker"}
    assert doc["metadata"]["reason"] == "test"
    # no directory configured: dump declines rather than guessing
    assert rec.dump("test") is None


def test_flight_ring_capacity_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUML_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("TPUML_FLIGHT_EVENTS", "8")
    monkeypatch.setenv("TPUML_SLO_EVAL_MS", "60000")
    assert opsplane.ensure_started()
    assert opsplane.address() is None  # flight-only: no HTTP server
    for i in range(50):
        with telemetry.span(f"s{i}"):
            pass
    rec = opsplane.flight_recorder()
    assert rec.capacity == 8 and len(rec) == 8
    path = rec.dump("manual")
    assert os.path.basename(path) == f"flight-r00-{os.getpid()}.json"
    with open(path) as f:
        doc = json.load(f)
    names = [e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert names == [f"s{i}" for i in range(42, 50)]
    assert telemetry.counter("flight_dumps_total").value(reason="manual") == 1


def test_sigterm_crash_dump_without_tracing(tmp_path):
    """A killed run with TPUML_TRACE unset still yields a loadable
    flight shard: the SIGTERM handler dumps the ring, then chains to
    the default disposition so the exit status stays conventional."""
    child = (
        "import os, time\n"
        "from spark_rapids_ml_tpu.runtime import opsplane, telemetry\n"
        "assert os.environ.get('TPUML_TRACE') is None\n"
        "assert opsplane.ensure_started()\n"
        "with telemetry.span('prelude'):\n"
        "    with telemetry.span('work'):\n"
        "        pass\n"
        "print('READY', flush=True)\n"
        "time.sleep(120)\n"
    )
    env = dict(os.environ)
    for var in _OPS_ENVS:
        env.pop(var, None)
    env.update(
        TPUML_FLIGHT_DIR=str(tmp_path),
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO_ROOT,
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", child],
        cwd=REPO_ROOT, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    try:
        line = proc.stdout.readline()
        assert "READY" in line, line
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        proc.kill()
        proc.stdout.close()
    assert rc == -signal.SIGTERM  # chained default disposition

    shards = _flight_shards(tmp_path)
    assert len(shards) == 1, shards
    with open(os.path.join(tmp_path, shards[0])) as f:
        doc = json.load(f)
    assert doc["metadata"]["flight"] is True
    assert doc["metadata"]["reason"] == "signal"
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"prelude", "work"} <= names


# --- SLO burn --------------------------------------------------------------


def test_slo_burn_alert_on_p99_spike(tmp_path, monkeypatch):
    """A synthetic serving p99 spike: both burn windows cross the
    threshold after two violating ticks, the alert counter increments
    once per episode, and the flight dump is one-shot per process."""
    monkeypatch.setenv("TPUML_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("TPUML_SLO_EVAL_MS", "60000")  # keep cadence quiet
    assert opsplane.ensure_started()
    ev = opsplane._EVALUATOR

    for _ in range(8):
        telemetry.histogram("serve_p99_ms").observe(900.0, model="m")

    st = ev.tick(now=1000.0)
    assert not st["serving_p99_ms"]["alerting"]  # one tick never alerts
    st = ev.tick(now=1001.0)
    assert st["serving_p99_ms"]["alerting"]
    assert st["serving_p99_ms"]["burn_short"] >= 1.0
    alerts = telemetry.counter("slo_burn_alerts")
    assert alerts.value(slo="serving_p99_ms") == 1
    assert _flight_shards(tmp_path) == [
        f"flight-r00-{os.getpid()}.json"
    ]
    rec = opsplane.flight_recorder()
    assert rec.dumps == {"slo_burn": 1}

    # still burning: no re-alert, no second dump
    ev.tick(now=1002.0)
    assert alerts.value(slo="serving_p99_ms") == 1
    assert rec.dumps == {"slo_burn": 1}
    assert opsplane.slo_status()["serving_p99_ms"]["alerting"]

    # recovery: flood the ring with in-objective samples, age the
    # violating ticks out of both windows
    for _ in range(4096):
        telemetry.histogram("serve_p99_ms").observe(1.0, model="m")
    st = ev.tick(now=10_000.0)
    assert not st["serving_p99_ms"]["alerting"]

    # a second burn episode re-alerts — but the dump stays one-shot
    for _ in range(4096):
        telemetry.histogram("serve_p99_ms").observe(900.0, model="m")
    ev.tick(now=10_001.0)
    st = ev.tick(now=10_002.0)
    assert st["serving_p99_ms"]["alerting"]
    assert alerts.value(slo="serving_p99_ms") == 2
    assert rec.dumps == {"slo_burn": 1}
    assert _flight_shards(tmp_path) == [
        f"flight-r00-{os.getpid()}.json"
    ]


def test_slo_window_measures_need_two_snapshots(tmp_path, monkeypatch):
    """window_delta SLOs measure increments between ticks: a
    retrace-storm counter bump alerts on the next two ticks, and an
    idle counter never measures at all."""
    monkeypatch.setenv("TPUML_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("TPUML_SLO_EVAL_MS", "60000")
    assert opsplane.ensure_started()
    ev = opsplane._EVALUATOR

    st = ev.tick(now=1.0)  # baseline snapshot: nothing measured yet
    assert st["fit_retrace_storms"]["last_value"] is None
    telemetry.counter("retrace_storms").inc()
    st = ev.tick(now=2.0)
    assert st["fit_retrace_storms"]["last_value"] == 1.0
    assert not st["fit_retrace_storms"]["alerting"]  # single tick
    telemetry.counter("retrace_storms").inc()
    st = ev.tick(now=3.0)
    assert st["fit_retrace_storms"]["alerting"]
    # fault_injections never moved: no ticks, no alert
    assert st["fit_fault_injections"]["last_value"] is None
    assert not st["fit_fault_injections"]["alerting"]


# --- shard merging ---------------------------------------------------------


def test_flight_shards_merge_rank_tagged(tmp_path, monkeypatch):
    """Two ranks' flight dumps merge like trace shards: per-host track
    groups keyed by process_index, flight metadata preserved."""
    monkeypatch.setenv("TPUML_FLIGHT_DIR", str(tmp_path))
    pid = os.getpid()
    for rank in (0, 1):
        monkeypatch.setenv("TPUML_PROC_ID", str(rank))
        rec = opsplane.FlightRecorder(16)
        rec.sink(
            {"name": f"work.r{rank}", "ph": "X", "pid": pid, "tid": 1,
             "ts": 0, "dur": 5, "args": {}},
            "MainThread",
        )
        path = rec.dump("test")
        assert os.path.basename(path) == f"flight-r{rank:02d}-{pid}.json"
    monkeypatch.delenv("TPUML_PROC_ID")

    mt = _load_by_path("merge_traces")
    assert mt.main([str(tmp_path)]) == 0
    with open(os.path.join(tmp_path, "merged-flight.json")) as f:
        merged = json.load(f)
    assert merged["metadata"]["flight"] is True
    assert merged["metadata"]["hosts"] == [0, 1]
    pnames = {
        e["args"]["name"]
        for e in merged["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert pnames == {f"host0 (pid {pid})", f"host1 (pid {pid})"}
    xs = {
        e["name"]: e["pid"]
        for e in merged["traceEvents"]
        if e.get("ph") == "X"
    }
    assert xs == {"work.r0": 0, "work.r1": 1}
