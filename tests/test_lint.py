"""tpuml_lint: per-rule positive/suppressed/negative fixtures, baseline
mechanics, envspec parse semantics, and the whole-repo integration run
(the tree must lint clean with the committed — empty — baseline)."""

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

import tpuml_lint
from tpuml_lint import (
    tpu001_raw_env,
    tpu003_jit_in_loop,
    tpu004_nondeterminism,
    tpu005_static_args,
    tpu006_lane_align,
    tpu007_metric_catalog,
    tpu008_label_cardinality,
    tpu009_inline_pspec,
)
from tpuml_lint.core import (
    Finding,
    SourceFile,
    apply_baseline,
    load_baseline,
    write_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(rule, code, path="pkg/mod.py"):
    """Run one per-file rule over a source snippet; suppressions applied."""
    text = textwrap.dedent(code)
    sf = SourceFile(
        path=path, abspath="/" + path, text=text,
        tree=ast.parse(text),
    )
    return [f for f in rule.check_file(sf) if not sf.suppressed(f)]


# --- TPU001: raw env reads -------------------------------------------------


def test_tpu001_flags_all_read_forms():
    findings = lint_snippet(tpu001_raw_env, """
        import os
        from os import environ, getenv

        a = os.environ.get("TPUML_RETRIES")
        b = os.getenv("TPUML_CKPT_DIR", "x")
        c = os.environ["TPUML_NUM_PROCS"]
        d = "TPUML_COORDINATOR" in os.environ
        e = environ.get("TPUML_LIB")
        f = getenv("TPUML_BLAS_LIB")
    """)
    assert len(findings) == 6
    assert all(f.rule == "TPU001" for f in findings)
    assert "envspec" in findings[0].fixit


def test_tpu001_aliased_import():
    findings = lint_snippet(tpu001_raw_env, """
        import os as _os
        v = _os.environ.get("TPUML_UMAP_OPT", "auto")
    """)
    assert len(findings) == 1


def test_tpu001_allows_writes_and_non_tpuml():
    findings = lint_snippet(tpu001_raw_env, """
        import os
        os.environ["TPUML_RETRIES"] = "3"     # write: allowed
        os.environ.pop("TPUML_RETRIES", None) # write: allowed
        del os.environ["TPUML_CKPT_DIR"]      # write: allowed
        path = os.environ.get("HOME")         # not TPUML_*
    """)
    assert findings == []


def test_tpu001_exempts_envspec_itself():
    findings = lint_snippet(
        tpu001_raw_env,
        'import os\nx = os.environ.get("TPUML_RETRIES")\n',
        path="spark_rapids_ml_tpu/runtime/envspec.py",
    )
    assert findings == []


def test_tpu001_suppression_comment():
    findings = lint_snippet(tpu001_raw_env, """
        import os
        x = os.environ.get("TPUML_NB_CPU")  # tpuml: ignore[TPU001]
        # tpuml: ignore[TPU001]
        y = os.environ.get("TPUML_NB_CPU")
        z = os.environ.get("TPUML_NB_CPU")  # tpuml: ignore[TPU003]
    """)
    assert len(findings) == 1  # wrong code doesn't suppress


# --- TPU003: jit construction hazards --------------------------------------


def test_tpu003_jit_in_loop():
    findings = lint_snippet(tpu003_jit_in_loop, """
        import jax
        def fit(chunks):
            for c in chunks:
                f = jax.jit(lambda x: x + 1)
                f(c)
    """)
    assert len(findings) == 1
    assert "loop" in findings[0].message


def test_tpu003_partial_jit_and_comprehension():
    findings = lint_snippet(tpu003_jit_in_loop, """
        import functools
        import jax
        def fit(fns):
            return [functools.partial(jax.jit, static_argnames=("n",))(f)
                    for f in fns]
    """)
    assert len(findings) == 1


def test_tpu003_construct_and_invoke_per_call():
    findings = lint_snippet(tpu003_jit_in_loop, """
        import jax
        def fetch(arr):
            return jax.jit(lambda a: a * 2)(arr)
    """)
    assert len(findings) == 1
    assert "per call" in findings[0].message


def test_tpu003_clean_patterns():
    findings = lint_snippet(tpu003_jit_in_loop, """
        import functools
        import jax

        @jax.jit
        def f(x):
            return x

        @functools.partial(jax.jit, static_argnames=("n",))
        def g(x, n):
            return x * n

        h = jax.jit(lambda x: x)  # module-level: constructed once

        def fit(chunks):
            for c in chunks:
                f(c)  # calling a cached jit in a loop is the whole point
    """)
    assert findings == []


# --- TPU004: nondeterminism ------------------------------------------------


def test_tpu004_numpy_global_rng():
    findings = lint_snippet(tpu004_nondeterminism, """
        import numpy as np
        def init(shape):
            np.random.seed(0)
            return np.random.randn(*shape)
    """)
    assert len(findings) == 2
    assert "default_rng" in findings[0].fixit


def test_tpu004_stdlib_random_module_calls():
    findings = lint_snippet(tpu004_nondeterminism, """
        import random
        def jitter():
            return random.uniform(0, 1)
    """)
    assert len(findings) == 1


def test_tpu004_allows_seeded_instances():
    findings = lint_snippet(tpu004_nondeterminism, """
        import random
        import numpy as np
        rng = random.Random(1234)
        gen = np.random.default_rng(0)
        v = rng.uniform(0, 1)
    """)
    assert findings == []


def test_tpu004_clock_in_traced_code():
    findings = lint_snippet(tpu004_nondeterminism, """
        import time
        import jax

        @jax.jit
        def step(x):
            t0 = time.time()
            return x + t0

        def host_timer():
            return time.time()  # outside trace: fine

        def add_kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] + time.time()
    """)
    assert len(findings) == 2
    assert {"step", "add_kernel"} == {
        f.message.split("'")[1] for f in findings
    }


def test_tpu004_prngkey_in_loop():
    findings = lint_snippet(tpu004_nondeterminism, """
        import jax
        def fit(n, base):
            for epoch in range(n):
                k = jax.random.PRNGKey(epoch)
            for epoch in range(n):
                k = jax.random.fold_in(base, epoch)  # the sanctioned form
    """)
    assert len(findings) == 1
    assert "fold_in" in findings[0].fixit


# --- TPU005: static arg hazards --------------------------------------------


def test_tpu005_unknown_static_argname():
    findings = lint_snippet(tpu005_static_args, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("n_bins",))
        def hist(x, nbins):
            return x * nbins
    """)
    assert len(findings) == 1
    assert "n_bins" in findings[0].message


def test_tpu005_unhashable_default():
    findings = lint_snippet(tpu005_static_args, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("shape",))
        def zeros(x, shape=[8, 128]):
            return x
    """)
    assert len(findings) == 1
    assert "unhashable" in findings[0].message


def test_tpu005_argnums_out_of_range():
    findings = lint_snippet(tpu005_static_args, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(2,))
        def f(x, y):
            return x + y
    """)
    assert len(findings) == 1


def test_tpu005_assigned_jit_of_local_def():
    findings = lint_snippet(tpu005_static_args, """
        import jax

        def _impl(x, cfg):
            return x

        f = jax.jit(_impl, static_argnames=("config",))
    """)
    assert len(findings) == 1


def test_tpu005_clean():
    findings = lint_snippet(tpu005_static_args, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("n", "shape"))
        def f(x, n, shape=(8, 128)):
            return x

        @functools.partial(jax.jit, static_argnames=("opt",))
        def g(x, **opts):
            return x  # **kwargs can absorb any static name
    """)
    assert findings == []


# --- TPU006: lane alignment ------------------------------------------------


def test_tpu006_unaligned_minor_dim():
    findings = lint_snippet(tpu006_lane_align, """
        import jax.experimental.pallas as pl
        spec = pl.BlockSpec((8, 100), lambda i: (i, 0))
    """)
    assert len(findings) == 1
    assert "128" in findings[0].message


def test_tpu006_clean_specs():
    findings = lint_snippet(tpu006_lane_align, """
        import jax.experimental.pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        a = pl.BlockSpec((8, 256), lambda i: (i, 0))     # aligned
        b = pl.BlockSpec((bn, feat_pad), lambda i: (i, 0))  # symbolic
        c = pl.BlockSpec((1, 1), memory_space=pltpu.SMEM)   # scalar
        d = pl.BlockSpec((1, 1), lambda i: (0, 0))          # (1,1) scalar
    """)
    assert findings == []


# --- TPU007: metric catalog ------------------------------------------------


def lint_project_snippet(rule, code, path="pkg/mod.py"):
    """Run one project rule over a single-file snippet; suppressions
    applied (mirrors how the runner filters project findings)."""
    text = textwrap.dedent(code)
    sf = SourceFile(
        path=path, abspath="/" + path, text=text,
        tree=ast.parse(text),
    )
    return [
        f for f in rule.check_project([sf], REPO_ROOT)
        if f.path != sf.path or not sf.suppressed(f)
    ]


def test_tpu007_flags_undeclared_names():
    findings = lint_project_snippet(tpu007_metric_catalog, """
        from spark_rapids_ml_tpu.runtime import counters, telemetry
        counters.bump("bogus_counter")
        counters.note("bogus_gauge", 3)
        telemetry.counter("bogus_tele").inc()
        telemetry.histogram("bogus_hist").observe(0.5)
    """)
    assert len(findings) == 4
    assert all(f.rule == "TPU007" for f in findings)
    assert all("not declared" in f.message for f in findings)


def test_tpu007_flags_kind_mismatch():
    # resumed_from is declared as a gauge; bump() implies a counter
    findings = lint_project_snippet(tpu007_metric_catalog, """
        from spark_rapids_ml_tpu.runtime import counters
        counters.bump("resumed_from")
    """)
    assert len(findings) == 1
    assert "declared as a gauge" in findings[0].message


def test_tpu007_allows_declared_and_dynamic_names():
    findings = lint_project_snippet(tpu007_metric_catalog, """
        from spark_rapids_ml_tpu.runtime import counters, telemetry
        counters.bump("retries")
        counters.note("resumed_from", 7)
        counters.get("retries")
        telemetry.counter("gang_dispatches").inc(2)
        telemetry.gauge("hbm_budget_bytes").set(1.0)
        name = "retr" + "ies"
        counters.bump(name)  # dynamic: out of scope
        unrelated.bump("whatever")  # not a counters/telemetry call
    """)
    assert findings == []


def test_tpu007_suppression_comment():
    findings = lint_project_snippet(tpu007_metric_catalog, """
        from spark_rapids_ml_tpu.runtime import counters
        counters.bump("bogus_one")  # tpuml: ignore[TPU007]
        counters.bump("bogus_two")
    """)
    assert len(findings) == 1
    assert "bogus_two" in findings[0].message


def test_tpu007_slo_catalog_must_reference_declared_metrics(tmp_path):
    """An SLO over a nonexistent metric would silently never measure —
    the project pass rejects it (checked against a scratch repo whose
    slo.py references a bogus metric; the real catalog is covered by
    the clean whole-repo run)."""
    rt = tmp_path / "spark_rapids_ml_tpu" / "runtime"
    rt.mkdir(parents=True)
    real = os.path.join(REPO_ROOT, "spark_rapids_ml_tpu", "runtime")
    for name in ("envspec.py", "metricspec.py"):
        with open(os.path.join(real, name)) as fh:
            (rt / name).write_text(fh.read())
    (rt / "slo.py").write_text(textwrap.dedent("""
        from dataclasses import dataclass

        @dataclass(frozen=True)
        class SLOSpec:
            name: str
            metric: str

        CATALOG = (SLOSpec("phantom", "metric_nobody_declared"),)
    """))
    findings = list(tpu007_metric_catalog.check_project([], str(tmp_path)))
    assert len(findings) == 1
    assert findings[0].rule == "TPU007"
    assert "metric_nobody_declared" in findings[0].message
    assert findings[0].context == "slo:phantom"

    # a bare scratch repo with no slo.py at all lints clean
    (rt / "slo.py").unlink()
    assert list(tpu007_metric_catalog.check_project([], str(tmp_path))) == []


# --- TPU008: metric label cardinality ---------------------------------------


def test_tpu008_flags_splat_and_undeclared_labels():
    findings = lint_project_snippet(tpu008_label_cardinality, """
        from spark_rapids_ml_tpu.runtime import telemetry
        labels = {"request_id": rid}
        telemetry.counter("retries").inc(**labels)
        telemetry.counter("retries").inc(model="x")
        telemetry.gauge("hbm_live_bytes").set(1.0, shard=3)
        telemetry.histogram("serve_p99_ms").observe(2.0, user=u)
    """)
    assert len(findings) == 4
    assert all(f.rule == "TPU008" for f in findings)
    assert "splat" in findings[0].message
    assert "undeclared label 'model'" in findings[1].message
    assert "'site'" in findings[2].message  # names the declared set
    assert "undeclared label 'user'" in findings[3].message


def test_tpu008_allows_declared_labels_and_value_params():
    findings = lint_project_snippet(tpu008_label_cardinality, """
        from spark_rapids_ml_tpu.runtime import telemetry
        telemetry.counter("retries").inc()
        telemetry.counter("retries").inc(by=3)
        telemetry.counter("xla_compiles").inc(site="serve.batch")
        telemetry.gauge("hbm_live_bytes").set(1.0, site="gang_fit")
        telemetry.gauge("resumed_from").set(value=7)
        telemetry.histogram("serve_p99_ms").observe(2.0, model="pca")
        telemetry.histogram("span_seconds").observe(value=0.1, name="x")
        telemetry.counter("undeclared_name").inc(model="x")  # TPU007's job
        name = "ret" + "ries"
        telemetry.counter(name).inc(model="x")  # dynamic: out of scope
        m = telemetry.counter("retries")
        m.inc(model="x")  # not the chained form: out of scope
    """)
    assert findings == []


def test_tpu008_suppression_comment():
    findings = lint_project_snippet(tpu008_label_cardinality, """
        from spark_rapids_ml_tpu.runtime import telemetry
        telemetry.counter("retries").inc(model="a")  # tpuml: ignore[TPU008]
        telemetry.counter("retries").inc(model="b")
    """)
    assert len(findings) == 1
    assert "model" in findings[0].message


# --- TPU009: inline PartitionSpec outside parallel/ -------------------------


def test_tpu009_flags_inline_pspec_in_kernels():
    findings = lint_snippet(tpu009_inline_pspec, """
        import jax
        from jax.sharding import PartitionSpec as P

        a = P("dp")
        b = P(None, "mp")
        c = jax.sharding.PartitionSpec("dp", "mp")
    """, path="spark_rapids_ml_tpu/ops/some_kernels.py")
    assert len(findings) == 3
    assert all(f.rule == "TPU009" for f in findings)
    assert "LAYOUT" in findings[0].fixit


def test_tpu009_allows_parallel_package_and_out_of_scope_paths():
    code = """
        from jax.sharding import PartitionSpec

        s = PartitionSpec("dp")
    """
    for path in (
        "spark_rapids_ml_tpu/parallel/layout.py",
        "spark_rapids_ml_tpu/parallel/mesh.py",
        "tests/test_mesh2d.py",
        "bench.py",
    ):
        assert lint_snippet(tpu009_inline_pspec, code, path=path) == []


def test_tpu009_ignores_layout_calls_and_unrelated_names():
    findings = lint_snippet(tpu009_inline_pspec, """
        from spark_rapids_ml_tpu.parallel.layout import LAYOUT

        a = LAYOUT.rows()
        b = LAYOUT.cols()

        def P(x):
            return x

        c = P("not a partition spec")
    """, path="spark_rapids_ml_tpu/ops/clean.py")
    assert findings == []


def test_tpu009_suppression_comment():
    findings = lint_snippet(tpu009_inline_pspec, """
        from jax.sharding import PartitionSpec as P

        a = P("dp")  # tpuml: ignore[TPU009]
        b = P("dp")
    """, path="spark_rapids_ml_tpu/ops/some_kernels.py")
    assert len(findings) == 1


# --- baseline + suppression mechanics --------------------------------------


def _finding(path="a.py", rule="TPU001", context="x = 1"):
    return Finding(rule=rule, path=path, line=3, col=1,
                   message="m", context=context)


def test_baseline_roundtrip_and_churn_tolerance(tmp_path):
    f = _finding()
    p = str(tmp_path / "baseline.json")
    write_baseline(p, [f])
    baseline = load_baseline(p)
    # same finding on a DIFFERENT line (code above it churned): absorbed
    moved = Finding(rule=f.rule, path=f.path, line=99, col=5,
                    message=f.message, context=f.context)
    new, stale = apply_baseline([moved], baseline)
    assert new == [] and stale == []
    # different context line: new finding + stale entry
    other = _finding(context="y = 2")
    new, stale = apply_baseline([other], baseline)
    assert len(new) == 1 and len(stale) == 1


def test_committed_baseline_is_empty():
    p = os.path.join(REPO_ROOT, "tpuml_lint", "baseline.json")
    with open(p) as fh:
        assert json.load(fh)["findings"] == []


# --- envspec parse semantics ------------------------------------------------


def test_envspec_parse_errors_name_variable_and_domain():
    from spark_rapids_ml_tpu.runtime import envspec

    with pytest.raises(envspec.EnvSpecError, match="TPUML_NUM_PROCS"):
        envspec.parse("TPUML_NUM_PROCS", "zero")
    with pytest.raises(envspec.EnvSpecError, match="must be >= 1"):
        envspec.parse("TPUML_NUM_PROCS", "0")
    with pytest.raises(envspec.EnvSpecError, match="auto|sort|partial"):
        envspec.parse("TPUML_KNN_TOPK", "bogus")
    with pytest.raises(envspec.EnvSpecError, match="boolean"):
        envspec.parse("TPUML_RF_CHECK_FINITE", "maybe")
    # EnvSpecError is a ValueError for pre-registry except clauses
    assert issubclass(envspec.EnvSpecError, ValueError)


def test_envspec_defaults_and_empty_means_unset():
    from spark_rapids_ml_tpu.runtime import envspec

    assert envspec.parse("TPUML_RETRIES", None) == 0
    assert envspec.parse("TPUML_RETRIES", "") == 0
    assert envspec.parse("TPUML_CV_FAILFAST", "off") is False
    assert envspec.parse("TPUML_UMAP_OPT", " Pallas ") == "pallas"
    assert envspec.get("TPUML_CKPT_EVERY", env={}) == 1
    assert envspec.get("TPUML_CKPT_EVERY", env={"TPUML_CKPT_EVERY": "7"}) == 7


def test_envspec_is_stdlib_only():
    """The by-file-path loaders (tpuml_lint, gen_config_docs) depend on
    envspec importing nothing beyond the stdlib."""
    path = os.path.join(
        REPO_ROOT, "spark_rapids_ml_tpu", "runtime", "envspec.py"
    )
    with open(path) as fh:
        tree = ast.parse(fh.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            assert node.level == 0, "no relative imports in envspec.py"
            assert node.module.split(".")[0] in ("os", "dataclasses", "typing", "__future__")
        elif isinstance(node, ast.Import):
            for a in node.names:
                assert a.name.split(".")[0] in ("os", "dataclasses", "typing")


def test_every_registered_var_is_in_docs_table():
    from spark_rapids_ml_tpu.runtime import envspec

    with open(os.path.join(REPO_ROOT, "docs", "configuration.md")) as fh:
        doc = fh.read()
    for name in envspec.registered_names():
        assert name in doc, f"{name} missing from docs/configuration.md"


# --- integration ------------------------------------------------------------


def _run_lint(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tpuml_lint", *args],
        cwd=cwd, capture_output=True, text=True,
    )


def test_repo_lints_clean():
    """The acceptance gate: the tree has zero non-baselined findings."""
    r = _run_lint("spark_rapids_ml_tpu", "tests", "bench.py")
    assert r.returncode == 0, r.stdout + r.stderr


def test_lint_fails_on_each_rule(tmp_path):
    bad = {
        "TPU001": 'import os\nx = os.environ.get("TPUML_RETRIES")\n',
        "TPU003": (
            "import jax\n"
            "def f(cs):\n"
            "    for c in cs:\n"
            "        jax.jit(lambda x: x)(c)\n"
        ),
        "TPU004": "import numpy as np\nnp.random.seed(0)\n",
        "TPU005": (
            "import functools, jax\n"
            "@functools.partial(jax.jit, static_argnames=('typo',))\n"
            "def f(x):\n"
            "    return x\n"
        ),
        "TPU006": (
            "import jax.experimental.pallas as pl\n"
            "s = pl.BlockSpec((8, 100), lambda i: (i, 0))\n"
        ),
        "TPU007": (
            "from spark_rapids_ml_tpu.runtime import counters\n"
            'counters.bump("not_in_the_catalog")\n'
        ),
        "TPU008": (
            "from spark_rapids_ml_tpu.runtime import telemetry\n"
            'telemetry.counter("retries").inc(request_id="r1")\n'
        ),
        "TPU010": (
            "from spark_rapids_ml_tpu.runtime import lockwitness\n"
            'l = lockwitness.make_lock("not.in.the.catalog")\n'
        ),
        "TPU011": (
            "import time\n"
            "from spark_rapids_ml_tpu.runtime import lockwitness\n"
            '_L = lockwitness.make_lock("faults.cache")\n'
            "def f():\n"
            "    with _L:\n"
            "        time.sleep(1)\n"
        ),
        # TPU012 is scoped to spark_rapids_ml_tpu/ paths, so a tmp-file
        # fixture cannot trip it; tests/test_concurrency.py covers it
        # through the in-process harness with a scoped path.
    }
    for code, src in bad.items():
        p = tmp_path / f"{code.lower()}_fixture.py"
        p.write_text(src)
        r = _run_lint(str(p), "--no-baseline", "--rule", code)
        assert r.returncode == 1, f"{code} not detected:\n{r.stdout}"
        assert code in r.stdout


def test_gen_config_docs_check_mode():
    r = subprocess.run(
        [sys.executable, "scripts/gen_config_docs.py", "--check"],
        cwd=REPO_ROOT, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
