"""Test harness: simulate an 8-chip mesh on CPU.

The reference tests against local-mode Spark with real GPUs
(``/root/reference/python/tests/conftest.py:34-51``), emulating a
multi-node-multi-GPU cluster on one box. The TPU-native equivalent is
``--xla_force_host_platform_device_count``: 8 virtual CPU devices form a
mesh with the same SPMD program (and collectives) a v5e-8 slice would run.
"""

import os

# Must run before jax initializes its backends. Force CPU even when the
# session environment points at a real TPU (tests simulate the mesh).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The session's TPU plugin (if any) may force its own platform list from
# sitecustomize AFTER env vars are read; explicitly pin CPU here.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the tree-builder programs dominate suite
# wall-clock; caching compiled executables on disk makes repeat runs (CI
# rounds on the same machine) start warm.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # older jax without the knobs
    pass

import threading  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.default_backend() == "cpu"
assert len(jax.devices()) == 8, f"expected 8 virtual devices, got {len(jax.devices())}"


@pytest.fixture(params=[1, 2, 4])
def n_workers(request):
    """Parametrized worker counts, like the reference's ``gpu_number``."""
    return request.param


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False, help="run slow tests"
    )


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: mark test as slow")
    config.addinivalue_line("markers", "compat: CPU-oracle equivalence test")
    config.addinivalue_line(
        "markers",
        "allow_threads: test intentionally leaves named threads running",
    )


@pytest.fixture(autouse=True)
def _thread_leak_sanitizer(request):
    """Fail any test that leaks a live non-daemon thread.

    Snapshot-diff by thread name around each test: a non-daemon thread
    still alive afterwards means a missed ``close()``/``drain()`` —
    exactly the leak that hangs interpreter exit in production and
    bleeds scheduler/serving state into the next test. Daemon threads
    get a short grace join (dispatcher loops observe their shutdown
    flag within a tick) and are tolerated if still winding down —
    TPU012 already guarantees they cannot block exit. Opt out with
    ``@pytest.mark.allow_threads`` and a reason in the test body.
    """
    before = {t.name for t in threading.enumerate()}
    yield
    if request.node.get_closest_marker("allow_threads"):
        return
    leaked = [
        t
        for t in threading.enumerate()
        if t.is_alive() and not t.daemon and t.name not in before
    ]
    for t in leaked:
        t.join(timeout=2.0)
    leaked = [t for t in leaked if t.is_alive()]
    assert not leaked, (
        "test leaked live non-daemon thread(s): "
        f"{sorted(t.name for t in leaked)} — close/drain the owner, or "
        "mark the test @pytest.mark.allow_threads with a reason"
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="need --runslow option to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)
