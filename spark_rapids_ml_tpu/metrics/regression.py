"""Regression metrics from mergeable moment buffers.

Port of the reference's ``RegressionMetrics`` + ``_SummarizerBuffer``
(``/root/reference/python/src/spark_rapids_ml/metrics/RegressionMetrics.py``),
itself a port of Spark's Scala ``SummarizerBuffer``. The buffer tracks
mean / m2n (centered second moment) / m2 (raw second moment) / l1 for the
three series [label, label−prediction, prediction]; two buffers merge with
the Chan et al. parallel-variance update, so per-shard statistics combine
exactly.
"""

from __future__ import annotations

import math
from collections import namedtuple
from typing import Any, List

import numpy as np

RegMetrics = namedtuple("RegMetrics", ("m2n", "m2", "l1", "mean", "total_count"))
reg_metrics = RegMetrics("m2n", "m2", "l1", "mean", "total_count")


class _SummarizerBuffer:
    """Mergeable moment buffer (reference ``RegressionMetrics.py:30-149``).

    All of mean/m2n/m2/l1 have the same length (3 here), ordered
    [label, label-prediction, prediction]::

        mean = 1/N · Σ x_i
        m2n  = Σ (x_i − mean)²   (variance · N)
        m2   = Σ x_i²
        l1   = Σ |x_i|
    """

    def __init__(
        self,
        mean: List[float],
        m2n: List[float],
        m2: List[float],
        l1: List[float],
        total_cnt: int,
    ):
        self._curr_mean = list(mean)
        self._curr_m2n = list(m2n)
        self._curr_m2 = list(m2)
        self._curr_l1 = list(l1)
        self._num_cols = len(mean)
        self._total_cnt = total_cnt
        # weight col unsupported (parity with the reference): weight = 1/row
        self._total_weight_sum = total_cnt
        self._weight_square_sum = total_cnt
        self._curr_weight_sum = [total_cnt] * self._num_cols

    def merge(self, other: "_SummarizerBuffer") -> "_SummarizerBuffer":
        """Merge the other into self and return a new buffer (Chan et al.)."""
        self._total_cnt += other._total_cnt
        self._total_weight_sum += other._total_weight_sum
        self._weight_square_sum += other._weight_square_sum

        for i in range(self._num_cols):
            this_weight_sum = self._curr_weight_sum[i]
            other_weight_sum = other._curr_weight_sum[i]
            total_weight_sum = this_weight_sum + other_weight_sum
            if total_weight_sum != 0.0:
                delta_mean = other._curr_mean[i] - self._curr_mean[i]
                self._curr_mean[i] += delta_mean * other_weight_sum / total_weight_sum
                self._curr_m2n[i] += (
                    other._curr_m2n[i]
                    + delta_mean
                    * delta_mean
                    * this_weight_sum
                    * other_weight_sum
                    / total_weight_sum
                )
                self._curr_m2[i] += other._curr_m2[i]
                self._curr_l1[i] += other._curr_l1[i]
            self._curr_weight_sum[i] = total_weight_sum

        return _SummarizerBuffer(
            self._curr_mean,
            self._curr_m2n,
            self._curr_m2,
            self._curr_l1,
            self._total_cnt,
        )

    @property
    def total_count(self) -> int:
        return self._total_cnt

    @property
    def weight_sum(self) -> int:
        return self._total_weight_sum

    @property
    def m2(self) -> List[float]:
        return self._curr_m2

    @property
    def norm_l1(self) -> List[float]:
        return self._curr_l1

    @property
    def mean(self) -> List[float]:
        return self._curr_mean

    @property
    def variance(self) -> List[float]:
        """Unbiased sample variance per series (Spark semantics)."""
        denom = self._total_weight_sum - (
            self._weight_square_sum / self._total_weight_sum
        )
        if denom > 0:
            return [
                max(m2n / denom, 0.0) for m2n in self._curr_m2n
            ]
        return [0.0] * self._num_cols


class RegressionMetrics:
    """Metrics for regression (reference ``RegressionMetrics.py:153-267``)."""

    def __init__(self, summary: _SummarizerBuffer):
        self._summary = summary

    @staticmethod
    def create(
        mean: List[float],
        m2n: List[float],
        m2: List[float],
        l1: List[float],
        total_cnt: int,
    ) -> "RegressionMetrics":
        return RegressionMetrics(_SummarizerBuffer(mean, m2n, m2, l1, total_cnt))

    @classmethod
    def from_predictions(
        cls, labels: np.ndarray, predictions: np.ndarray
    ) -> "RegressionMetrics":
        """Build the moment buffer from a (shard of) predictions."""
        y = np.asarray(labels, dtype=np.float64)
        p = np.asarray(predictions, dtype=np.float64)
        series = [y, y - p, p]
        mean = [float(s.mean()) for s in series]
        m2n = [float(((s - s.mean()) ** 2).sum()) for s in series]
        m2 = [float((s * s).sum()) for s in series]
        l1 = [float(np.abs(s).sum()) for s in series]
        return cls.create(mean, m2n, m2, l1, int(y.shape[0]))

    def merge(self, other: "RegressionMetrics") -> "RegressionMetrics":
        return RegressionMetrics(self._summary.merge(other._summary))

    @property
    def _ss_y(self) -> float:
        """Sum of squares for label."""
        return self._summary.m2[0]

    @property
    def _ss_err(self) -> float:
        """Sum of squares for label−prediction."""
        return self._summary.m2[1]

    @property
    def _ss_tot(self) -> float:
        return self._summary.variance[0] * (self._summary.weight_sum - 1)

    @property
    def _ss_reg(self) -> float:
        return (
            self._summary.m2[2]
            + math.pow(self._summary.mean[0], 2) * self._summary.weight_sum
            - 2
            * self._summary.mean[0]
            * self._summary.mean[2]
            * self._summary.weight_sum
        )

    @property
    def mean_squared_error(self) -> float:
        return self._ss_err / self._summary.weight_sum

    @property
    def root_mean_squared_error(self) -> float:
        return math.sqrt(self.mean_squared_error)

    def r2(self, through_origin: bool) -> float:
        return (
            (1 - self._ss_err / self._ss_y)
            if through_origin
            else (1 - self._ss_err / self._ss_tot)
        )

    @property
    def mean_absolute_error(self) -> float:
        return self._summary.norm_l1[1] / self._summary.weight_sum

    @property
    def explained_variance(self) -> float:
        return self._ss_reg / self._summary.weight_sum

    def evaluate(self, evaluator: Any) -> float:
        metric_name = evaluator.getMetricName()
        if metric_name == "rmse":
            return self.root_mean_squared_error
        elif metric_name == "mse":
            return self.mean_squared_error
        elif metric_name == "r2":
            return self.r2(evaluator.getThroughOrigin())
        elif metric_name == "mae":
            return self.mean_absolute_error
        elif metric_name == "var":
            return self.explained_variance
        else:
            raise ValueError(f"Unsupported metric name, found {metric_name}")
