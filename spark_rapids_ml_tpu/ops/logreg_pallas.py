"""Fused logistic loss+gradient Pallas kernel — one X pass per L-BFGS eval.

``jax.value_and_grad`` of the logistic data term reads the design matrix
twice per objective evaluation: once forward (``X @ Aᵀ``) and once backward
(``Rᵀ @ X``). For the bandwidth-bound L-BFGS fit that is the entire cost.
This kernel computes the masked loss **and** the gradient in a single
HBM pass: per row tile, logits → per-row loss → residuals → the tile's
``Rᵀ x`` contribution, with the (K, d) gradient accumulator resident in
VMEM. A ``jax.custom_vjp`` wrapper computes both in the forward pass and
makes the backward pass free, so the solver's value-and-grad costs one
data read instead of two.

Used by ``logreg_fit`` (``ops/logreg_kernels.py``) when a dp-only mesh is
supplied and the shapes qualify (TPU backend, f32, lane-aligned d); the
portable XLA path is unchanged otherwise. cuML reference this replaces:
the QN solver's fused objective inside ``LogisticRegressionMG``
(``/root/reference/python/src/spark_rapids_ml/classification.py:1062-1064``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import pallas_tpu_compiler_params, shard_map
from ..parallel.layout import LAYOUT
from ..parallel.mesh import DP_AXIS

_LANES = 128

# Test hook: when True, logreg_pallas_ok ignores the backend check and the
# kernel runs through the Pallas interpreter — lets CPU CI exercise the
# REAL fused branch inside logreg_fit (gate → custom_vjp → L-BFGS), not
# just the standalone kernel.
FORCE_INTERPRET = False


from .linalg import _pallas_gram_tile


def _row_tile(d: int, Kp: int) -> int:
    """Row-tile size: the gram kernel's sizing, shrunk when the padded
    class count is large — multinomial materializes several (tile, Kp)
    intermediates (logits, softmax, residuals, one-hot, the packed
    loss/residual block), which at small d and many classes would
    otherwise dominate scoped VMEM.

    Dtype does NOT change the tile: measured on v5e, the kernel runs at
    the same ~2.2 ns/row for f32 and bf16 X alike (pipeline-bound, not
    HBM-bound), so bf16's value is halved residency — a full-speed fit
    from an X that occupies half the HBM — not throughput. Doubling the
    bf16 tile was measured a wash, and the validity-guard where-copy it
    would evict is load-bearing: without it the input window feeds the
    MXU directly and the kernel drops to ~1.7x slower (the guard's
    select decouples the window from the dots, letting the DMA
    double-buffer run ahead)."""
    return _pallas_gram_tile(max(d, 6 * Kp))


def logreg_pallas_ok(d: int, n_classes: int, dtype) -> bool:
    """Trace-time gate: TPU, f32/bf16 X, lane-aligned d, and few enough
    classes that the sublane-padded class block plus the loss lane pack
    into one 128-lane row (ceil(K/8)*8 + 1 <= 128, i.e. K <= 120). bf16 X
    feeds both dots directly (f32 accumulation) — no VMEM upcast."""
    return (
        (jax.default_backend() == "tpu" or FORCE_INTERPRET)
        and d % _LANES == 0
        and d <= 2048
        and -(-n_classes // 8) * 8 + 1 <= _LANES
        and dtype in (jnp.float32, jnp.bfloat16)
    )


def _loss_grad_pallas(Xl, yl, ml, A, b_row, *, multinomial: bool,
                      n_valid_classes: int, tile: int, interpret: bool):
    """Per-device fused pass.

    ``A`` is (Kp, d) with Kp a sublane multiple (rows >= n_valid_classes are
    zero); ``b_row`` is (1, 128) with the first K lanes holding intercepts.
    Returns (gA (Kp, d), acc (1, 128) = [loss_sum, grad_b_0..K-1, ...]).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = Xl.shape
    Kp = A.shape[0]
    K = n_valid_classes

    def kern(x_ref, y_ref, m_ref, a_ref, b_ref, gA_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            gA_ref[:] = jnp.zeros_like(gA_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        # x stays in its storage dtype: a materialized f32 upcast of a bf16
        # tile doubles VMEM pressure and caps the tile size — instead both
        # dots below take the narrow operands directly with f32
        # accumulation (the MXU-native mixed-precision path; the TF32
        # analog cuML gets implicitly on Ampere). Parameters/residuals are
        # rounded to the operand dtype per dot; with objective_dtype=bf16
        # the data itself already carries that rounding.
        row = i * tile + lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
        valid = row < n
        x = jnp.where(valid, x_ref[:], jnp.zeros((), x_ref.dtype))
        m = jnp.where(valid[:, 0], m_ref[:], 0.0)
        yv = jnp.where(valid[:, 0], y_ref[:], 0.0)

        A_t = a_ref[:].astype(x.dtype)       # (Kp, d)
        b = b_ref[0, :Kp]                    # (Kp,) f32
        z = lax.dot_general(                 # (tile, Kp) logits, f32
            x, A_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) + b[None, :]

        if multinomial:
            lane_k = lax.broadcasted_iota(jnp.int32, (tile, Kp), 1)
            # padded classes must not contribute to softmax/logsumexp
            z = jnp.where(lane_k < K, z, -1e30)
            zmax = jnp.max(z, axis=1, keepdims=True)
            ez = jnp.exp(z - zmax)
            sez = jnp.sum(ez, axis=1, keepdims=True)
            lse = jnp.log(sez[:, 0]) + zmax[:, 0]
            oh = (lane_k == yv.astype(jnp.int32)[:, None]).astype(jnp.float32)
            ll = lse - jnp.sum(z * oh, axis=1)
            R = (ez / sez - oh) * m[:, None]          # (tile, Kp)
        else:
            z1 = z[:, 0]
            ll = jax.nn.softplus(z1) - yv * z1
            r = (jax.nn.sigmoid(z1) - yv) * m          # (tile,)
            lane_k = lax.broadcasted_iota(jnp.int32, (tile, Kp), 1)
            R = jnp.where(lane_k == 0, r[:, None], 0.0)

        gA_ref[:] += lax.dot_general(                  # (Kp, d), f32 acc
            R.astype(x.dtype), x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        S = jnp.concatenate(
            [
                (ll * m)[:, None],
                R,
                jnp.zeros((tile, _LANES - 1 - Kp), jnp.float32),
            ],
            axis=1,
        )
        acc_ref[:] += jnp.sum(S, axis=0, keepdims=True)

    gA, acc = pl.pallas_call(
        kern,
        grid=(pl.cdiv(n, tile),),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((Kp, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((Kp, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Kp, d), jnp.float32),
            jax.ShapeDtypeStruct((1, _LANES), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )(Xl, yl, ml, A, b_row)
    return gA, acc


def make_fused_data_loss(X, y, mask, mesh, K: int, multinomial: bool,
                         interpret: bool | None = None):
    """Build ``f(Aeff, beff) -> Σ m·logloss`` whose value-and-grad is ONE
    data pass (custom_vjp: the forward pallas pass also yields the
    gradients; backward is a couple of multiplies).

    ``X``/``y``/``mask`` must be dp-sharded over ``mesh``; the (K, d)
    parameters are replicated. Gradients flow only to ``Aeff``/``beff``.
    """
    if interpret is None:
        interpret = FORCE_INTERPRET
    d = X.shape[1]
    Kp = max(8, -(-K // 8) * 8)
    tile = _row_tile(d, Kp)

    def run(Aeff, beff):
        A = jnp.zeros((Kp, d), jnp.float32).at[:K].set(Aeff)
        b_row = jnp.zeros((1, _LANES), jnp.float32).at[0, :K].set(beff)

        def per_device(Xl, yl, ml, A, b_row):
            gA, acc = _loss_grad_pallas(
                Xl, yl, ml, A, b_row,
                multinomial=multinomial, n_valid_classes=K,
                tile=tile, interpret=interpret,
            )
            gA = lax.psum(gA, DP_AXIS)
            acc = lax.psum(acc, DP_AXIS)
            return gA, acc

        gA, acc = shard_map(
            per_device,
            mesh=mesh,
            in_specs=(LAYOUT.rows(), LAYOUT.rows(), LAYOUT.rows(), LAYOUT.replicated(), LAYOUT.replicated()),
            out_specs=(LAYOUT.replicated(), LAYOUT.replicated()),
            check_vma=False,
        )(X, y, mask, A, b_row)
        return acc[0, 0], gA[:K], acc[0, 1:1 + K]

    @jax.custom_vjp
    def f(Aeff, beff):
        loss, _, _ = run(Aeff, beff)
        return loss

    def f_fwd(Aeff, beff):
        loss, gA, gb = run(Aeff, beff)
        return loss, (gA, gb)

    def f_bwd(res, g):
        gA, gb = res
        return (g * gA, g * gb)

    f.defvjp(f_fwd, f_bwd)
    return f
