"""Online inference serving: a device-resident model registry plus a
micro-batched request queue over the existing transform engines.

Everything here is explicitly constructed — importing the package (or
the library) starts no thread, opens no file, and reads no
``TPUML_SERVE_*`` variable; the batch fit/transform paths are untouched
(see ``docs/serving.md``).

The typed error surface (``docs/serving.md#resilience``): every way a
request can fail without a model result is a distinct
:class:`ServingError` subclass — :class:`DeadlineExceeded` (deadline
passed while queued), :class:`Overloaded` (shed at admission, with a
``reason``), :class:`ShuttingDown` (runtime draining or closed).
"""

from .admission import (
    AdmissionController,
    CircuitBreaker,
    DeadlineExceeded,
    Overloaded,
    ServingError,
    ShuttingDown,
)
from .lifecycle import LifecycleError, ModelLifecycle, RefreshDriver
from .registry import (
    ModelRegistry,
    ModelReloadError,
    ResidentModel,
    SwapError,
    feature_width,
    resident_nbytes,
    serving_family,
)
from .runtime import ServingRuntime
from .router import POLICIES, LoopbackReplica, Router, SubprocessReplica

__all__ = [
    "AdmissionController",
    "CircuitBreaker",
    "DeadlineExceeded",
    "LifecycleError",
    "LoopbackReplica",
    "ModelLifecycle",
    "ModelRegistry",
    "ModelReloadError",
    "Overloaded",
    "POLICIES",
    "RefreshDriver",
    "ResidentModel",
    "Router",
    "ServingError",
    "ServingRuntime",
    "ShuttingDown",
    "SubprocessReplica",
    "SwapError",
    "feature_width",
    "resident_nbytes",
    "serving_family",
]
