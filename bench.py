"""Benchmark entry point — prints ONE JSON line with the headline metric.

Headline: PCA fit throughput in samples/sec/chip at the reference benchmark
feature width (BASELINE.md: PCA/KMeans/LogReg fit at 100M x 256 scale; we
measure per-chip throughput on a slice of that workload so the number scales
linearly to pod size).

``vs_baseline`` compares against an A10G cuML estimate derived from the
reference's benchmark setup (BASELINE.md: 2x g5.2xlarge, 1M x 3000): PCA fit
is Gram-bound at 2*n*d^2 FLOPs; an A10G sustains ~15 TFLOP/s fp32 effective
on cuBLAS SYRK-shaped work, giving ~15e12 / (2*256^2) ≈ 1.1e8 samples/sec
per GPU at d=256. vs_baseline = ours / that.
"""

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from spark_rapids_ml_tpu.models.feature import _pca_fit_kernel
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh, shard_rows

    n_chips = len(jax.devices())
    n, d, k = 4_000_000, 256, 3
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)

    mesh = make_mesh(n_chips)
    Xd, mask = shard_rows(X, mesh)
    jax.block_until_ready(Xd)

    # warmup / compile
    out = _pca_fit_kernel(Xd, mask, k)
    jax.block_until_ready(out)

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = _pca_fit_kernel(Xd, mask, k)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    best = min(times)
    samples_per_sec_per_chip = n / best / n_chips

    baseline = 1.1e8  # A10G cuML PCA estimate at d=256, see module docstring
    print(
        json.dumps(
            {
                "metric": "pca_fit_throughput",
                "value": round(samples_per_sec_per_chip, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(samples_per_sec_per_chip / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
