from .mesh import (
    DP_AXIS,
    MP_AXIS,
    default_device_count,
    make_mesh,
    pad_rows,
    replicated,
    row_sharding,
    shard_rows,
)
from .context import TpuDistContext

__all__ = [
    "DP_AXIS",
    "MP_AXIS",
    "default_device_count",
    "make_mesh",
    "pad_rows",
    "replicated",
    "row_sharding",
    "shard_rows",
    "TpuDistContext",
]
