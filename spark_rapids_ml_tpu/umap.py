"""Drop-in module alias: ``spark_rapids_ml_tpu.umap`` ≙ reference
``spark_rapids_ml.umap`` (``/root/reference/python/src/spark_rapids_ml/umap.py``)."""

from .models.umap import UMAP, UMAPModel

__all__ = ["UMAP", "UMAPModel"]
