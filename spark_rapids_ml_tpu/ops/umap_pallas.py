"""VMEM-resident Pallas SGD engine for the UMAP embedding optimization.

The XLA epoch loop (``umap_kernels.optimize_embedding_rows``) is bound by
random gathers against an HBM-resident embedding whose minor dim is 2:
per epoch it fetches K tail rows plus K*neg negative rows per CSR-padded
row — ~1.8M 8-byte random reads at the 65k bench shape — while the whole
(65536, 2) f32 table is only 512 KB. This engine is the counter-move:
the gather TABLE stays VMEM-resident across the entire epoch while the
CSR-padded row streams (heads, tails, probabilities, negative ids) flow
HBM→VMEM block by block, and every tail/negative fetch becomes an
on-chip ``dynamic_gather`` instead of an HBM transaction. The embedding
is written back once per epoch (512 KB — noise), not once per gather.

Division of labor per epoch (and why):

* in-kernel — the K + K*neg random row gathers per CSR row (144 of the
  145 gathered rows per row at the bench config) and the full gradient
  arithmetic (attractive + negative-sampling terms, clip discipline);
* XLA side — the sorted head gather (1/145 of the gather traffic,
  near-sequential), the sorted ``segment_sum`` (<1 ms measured) and the
  ``emb + alpha*upd`` apply, plus the per-epoch randomness (see below).

Randomness has two modes:

* ``rng="xla"`` — the Bernoulli slot uniforms are drawn with the *exact*
  ``jax.random`` stream of the XLA path (same ``fold_in``/``split``
  order, shared via ``umap_kernels.epoch_rng_keys``) and streamed into
  the kernel. Same-seed outputs match ``optimize_embedding_rows`` to
  float associativity — this is the parity-testable mode, and the only
  mode under interpret (jax 0.4.x has no interpreter for the TPU PRNG).
* ``rng="onchip"`` — the kernel draws the slot mask from the TPU
  hardware PRNG (``pltpu.prng_seed``/``prng_random_bits``), removing the
  (R, K) uniform stream from HBM entirely. Statistically equivalent
  (uniform marginal per slot), not bit-equal to the XLA stream.

Negative-sample indices reproduce the XLA path's tiled-permutation
semantics exactly: tn[r, k, s] = src[perm[(((r - offs[s]) mod R)·K + k)
mod n_tab]], materialized per epoch as cheap contiguous tiles/rolls of
the (n_tab,) permutation — integer copies, never an embedding gather.

Hardware gating follows the rf_pallas convention: a trace-time shape
gate plus ``ops.linalg.probe_pallas_lowering`` on a two-block instance
of the real config; any Mosaic rejection (e.g. of the sublane
``dynamic_gather`` or non-integer ``pow``) routes the caller to the XLA
loop. Engine selection is ``TPUML_UMAP_OPT`` = auto | pallas | xla,
mirroring ``TPUML_RF_APPLY``.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

from ._compat import pallas_tpu_compiler_params, pallas_tpu_prng
from ..runtime import envspec
from .umap_kernels import epoch_alpha, epoch_rng_keys

# Test hook (mirrors ops.rf_pallas.FORCE_INTERPRET): run the kernel
# through the Pallas interpreter on CPU so tests cover the real body.
FORCE_INTERPRET = False

# Hardware-lowering probe results keyed by (n_tab, K, C, neg, rng);
# policy in ops.linalg.probe_pallas_lowering. n_tab is in the key because
# the table's whole-array VMEM residency is the config being probed.
_LOWERING_OK: dict = {}

# CSR rows per grid block. 256 divides both row buckets the fit uses
# (4096 and 256); transform batches are padded up to it with inert rows.
BLOCK_ROWS = 256

def resolve_umap_opt() -> str:
    """Validated ``TPUML_UMAP_OPT`` (auto | pallas | xla)."""
    return str(envspec.get("TPUML_UMAP_OPT"))


def default_rng_mode() -> str:
    """On-chip PRNG on real TPU hardware; the XLA stream everywhere else
    (the interpreter has no PRNG lowering on jax 0.4.x)."""
    if FORCE_INTERPRET or jax.default_backend() != "tpu":
        return "xla"
    from jax.experimental.pallas import tpu as pltpu

    return "onchip" if pallas_tpu_prng(pltpu) is not None else "xla"


def umap_sgd_pallas_ok(
    n_tab: int, K: int, C: int, neg: int, rng: str = "xla"
) -> bool:
    """Trace-time gate: TPU (or interpret), slot widths in range, and the
    lane-padded table inside the VMEM budget — then a probed lowering."""
    ok = (
        (jax.default_backend() == "tpu" or FORCE_INTERPRET)
        and 1 <= C <= 8
        and 1 <= K <= 128
        and 1 <= neg <= 16
        and K * (1 + neg) <= 1024
        # Mosaic lane-pads the (n_tab, C<=8) f32 table to (8, 128) tiles:
        # n_tab * 512 B resident. Cap at 64 MB so streams + double
        # buffers fit the 100 MB vmem budget (65536 rows -> 33.5 MB).
        and n_tab * 512 <= 64 * 1024 * 1024
    )
    if ok and rng == "onchip":
        if FORCE_INTERPRET:
            return False
        from jax.experimental.pallas import tpu as pltpu

        ok = pallas_tpu_prng(pltpu) is not None
    if ok and not FORCE_INTERPRET:
        ok = _probe_lowering(n_tab, K, C, neg, rng)
    return ok


def _probe_lowering(n_tab: int, K: int, C: int, neg: int, rng: str) -> bool:
    from .linalg import probe_pallas_lowering

    key = (n_tab, K, C, neg, rng)
    B = BLOCK_ROWS

    def compile_fn():
        # two grid blocks (rf_pallas rationale: single-block probes mask
        # multi-block rejections) at the REAL table shape — residency is
        # part of the config
        src = jax.ShapeDtypeStruct((n_tab, C), jnp.float32)
        h = jax.ShapeDtypeStruct((2 * B, C), jnp.float32)
        tails = jax.ShapeDtypeStruct((2 * B, K), jnp.int32)
        p = jax.ShapeDtypeStruct((2 * B, K), jnp.float32)
        nids = jax.ShapeDtypeStruct((2 * B, neg * K), jnp.int32)
        u = (
            jax.ShapeDtypeStruct((2 * B, K), jnp.float32)
            if rng == "xla"
            else None
        )
        seed = jax.ShapeDtypeStruct((1, 1), jnp.int32)
        sgd_epoch_rows.lower(
            src, h, tails, p, nids, u, seed,
            a=1.577, b=0.895, gamma=1.0, attract_scale=2.0, rng=rng,
        ).compile()

    return probe_pallas_lowering(
        _LOWERING_OK, key, compile_fn, "UMAP VMEM-resident SGD"
    )


def select_sgd_engine(
    n_tab: int, K: int, C: int, neg: int, *, rng: str | None = None
) -> str:
    """Resolve ``TPUML_UMAP_OPT`` against the gate/probe: returns
    ``"pallas"`` or ``"xla"``. An explicit ``pallas`` that the gate
    rejects warns and falls back — the fit must not crash on a config
    Mosaic refuses (same clean-fallback contract as the probe itself)."""
    mode = resolve_umap_opt()
    if mode == "xla":
        return "xla"
    if rng is None:
        rng = default_rng_mode()
    if umap_sgd_pallas_ok(n_tab, K, C, neg, rng):
        return "pallas"
    if mode == "pallas":
        logging.getLogger("spark_rapids_ml_tpu.umap").warning(
            "TPUML_UMAP_OPT=pallas but the VMEM-resident SGD kernel is "
            "unavailable for config (n_tab=%d, K=%d, C=%d, neg=%d, rng=%s);"
            " falling back to the XLA epoch loop",
            n_tab, K, C, neg, rng,
        )
    return "xla"


@functools.partial(
    jax.jit,
    static_argnames=("a", "b", "gamma", "attract_scale", "rng", "interpret"),
)
def sgd_epoch_rows(
    src: jax.Array,        # (n_tab, C) f32 gather table — VMEM-resident
    h: jax.Array,          # (R, C) f32 head rows (pre-gathered, sorted)
    tails_pad: jax.Array,  # (R, K) int32 tail ids
    p_pad: jax.Array,      # (R, K) f32 slot activation probabilities
    neg_ids: jax.Array,    # (R, neg*K) int32 negative ids, slot-major per s
    u,                     # (R, K) f32 slot uniforms (rng="xla") or None
    seed: jax.Array,       # (1, 1) int32 per-epoch seed (rng="onchip")
    *,
    a: float,
    b: float,
    gamma: float,
    attract_scale: float,
    rng: str = "xla",
    interpret: bool | None = None,
) -> jax.Array:
    """One SGD epoch over CSR-padded rows: per-row gradient sums (R, C).

    The caller applies the sorted ``segment_sum`` and the ``alpha`` step —
    exactly the XLA path's epoch tail — so the two engines share every
    instruction outside the gather/gradient hot loop. R must be a
    BLOCK_ROWS multiple (the wrapper pads with inert p=0 rows)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = FORCE_INTERPRET
    R, K = tails_pad.shape
    n_tab, C = src.shape
    neg = neg_ids.shape[1] // K
    B = BLOCK_ROWS
    n_blocks = R // B

    def kern(seed_ref, src_ref, h_ref, t_ref, p_ref, n_ref, *rest):
        if rng == "xla":
            u_ref, o_ref = rest
        else:
            (o_ref,) = rest
        srcv = src_ref[...]                       # (n_tab, C) resident
        hv = h_ref[...]                           # (B, C)
        p = p_ref[...]                            # (B, K)
        if rng == "xla":
            unif = u_ref[...]
        else:
            prng_seed, prng_bits = pallas_tpu_prng(pltpu)
            # decorrelate grid blocks off the per-epoch seed
            prng_seed(seed_ref[0, 0] + pl.program_id(0))
            bits = prng_bits((B, K))
            unif = (bits >> jnp.uint32(8)).astype(jnp.float32) * (
                1.0 / (1 << 24)
            )
        active = (unif < p).astype(jnp.float32)   # (B, K)

        def gather_rows(ids2d):
            # (B, K) ids -> (B, K, C) table rows via the sublane
            # dynamic_gather form (take_along_axis with matching rank)
            m = ids2d.shape[0] * ids2d.shape[1]
            flat = ids2d.reshape(m, 1)
            g = jnp.take_along_axis(
                srcv, jnp.broadcast_to(flat, (m, C)), axis=0
            )
            return g.reshape(ids2d.shape[0], ids2d.shape[1], C)

        def clip4(x):
            return jnp.clip(x, -4.0, 4.0)

        t = gather_rows(t_ref[...])               # (B, K, C)
        diff = hv[:, None, :] - t
        d2 = (diff * diff).sum(axis=2)            # (B, K)
        ac = (-2.0 * a * b * d2 ** (b - 1.0)) / (a * d2**b + 1.0)
        ac = jnp.where(d2 > 0.0, ac, 0.0) * active
        grad = clip4(ac[..., None] * diff) * attract_scale

        nids = n_ref[...]                         # (B, neg*K)
        for s in range(neg):
            tn = gather_rows(nids[:, s * K : (s + 1) * K])
            diff_n = hv[:, None, :] - tn
            d2n = (diff_n * diff_n).sum(axis=2)
            rc = (2.0 * gamma * b) / ((0.001 + d2n) * (a * d2n**b + 1.0))
            rc = jnp.where(d2n > 0.0, rc, 0.0) * active
            grad = grad + clip4(rc[..., None] * diff_n)

        o_ref[...] = grad.sum(axis=1)             # (B, C)

    in_specs = [
        pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec(
            (n_tab, C), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec((B, C), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((B, K), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((B, K), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec(
            (B, neg * K), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
    ]
    args = [seed, src, h, tails_pad, p_pad, neg_ids]
    if rng == "xla":
        in_specs.append(
            pl.BlockSpec((B, K), lambda i: (i, 0), memory_space=pltpu.VMEM)
        )
        args.append(u)
    return pl.pallas_call(
        kern,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((B, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, C), jnp.float32),
        compiler_params=pallas_tpu_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )(*args)


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_epochs", "a", "b", "gamma", "initial_alpha",
        "negative_sample_rate", "self_table", "rng", "interpret",
        "epoch_span",
    ),
)
def umap_sgd_pallas(
    emb_head: jax.Array,    # (n_head, C) embedding being optimized
    table: jax.Array,       # (n_tab, C) frozen tail table (transform); the
                            # SAME array for fit (self_table=True)
    row_heads: jax.Array,   # (R,) int32, sorted ascending
    tails_pad: jax.Array,   # (R, K) int32
    p_pad: jax.Array,       # (R, K) f32 sampling probabilities
    key: jax.Array,
    *,
    n_epochs: int,
    a: float,
    b: float,
    gamma: float = 1.0,
    initial_alpha: float = 1.0,
    negative_sample_rate: int = 5,
    self_table: bool = True,
    rng: str = "xla",
    interpret: bool | None = None,
    epoch_offset=0,
    epoch_span: int | None = None,
) -> jax.Array:
    """Drop-in engine for ``umap_kernels.optimize_embedding_rows`` with the
    gather/gradient hot loop in the VMEM-resident Pallas kernel.

    Epoch structure mirrors the XLA path exactly: randomness is drawn via
    the shared ``epoch_rng_keys`` stream (uniforms only materialize for
    ``rng="xla"``), negatives reproduce the tiled-permutation + per-sample
    row-roll semantics as precomputed index tiles, and the epoch tail
    (sorted segment_sum, ``emb + alpha*upd``) is byte-for-byte the same
    code path — so ``rng="xla"`` outputs are same-seed equivalent.

    ``epoch_offset``/``epoch_span`` (the checkpoint/resume segmenting
    contract of ``optimize_embedding_rows``): run absolute epochs
    ``[offset, offset + span)``. All per-epoch state — epoch keys, alpha,
    the on-chip PRNG's ``seed_base + e`` — is a function of the absolute
    index, so segmented runs match single-shot ones."""
    from jax import lax

    R, K = tails_pad.shape
    n_head, C = emb_head.shape
    n_tab = table.shape[0]
    neg = int(negative_sample_rate)
    reps = -(-(R * K) // n_tab)
    pad_rows = (-R) % BLOCK_ROWS

    # Kernel block padding: randomness and roll moduli are computed at the
    # ORIGINAL R (parity with the XLA path); padded rows carry p = 0
    # (never activate), tail/negative id 0 (valid, gradient masked) and
    # head n_head-1, keeping row_heads ascending for the sorted
    # segment_sum — the build_row_adjacency padding discipline.
    tails_b = jnp.pad(tails_pad, ((0, pad_rows), (0, 0)))
    p_b = jnp.pad(p_pad, ((0, pad_rows), (0, 0)))
    heads_b = jnp.pad(
        row_heads, (0, pad_rows), constant_values=n_head - 1
    )
    # per-epoch seed base for the on-chip PRNG (ignored under rng="xla");
    # drawn off a side-channel fold so epoch keys stay untouched
    seed_base = jax.random.randint(
        jax.random.fold_in(key, 0x5EED), (), 0, jnp.iinfo(jnp.int32).max,
        dtype=jnp.int32,
    )

    span = n_epochs if epoch_span is None else int(epoch_span)
    e0 = jnp.asarray(epoch_offset, jnp.int32)

    def epoch(i, emb):
        e = e0 + i  # absolute epoch: RNG + alpha match single-shot runs
        src = emb if self_table else table
        k1, k2, k3 = epoch_rng_keys(key, e)
        alpha = epoch_alpha(initial_alpha, e, n_epochs)
        u = None
        if rng == "xla":
            u = jnp.pad(
                jax.random.uniform(k1, (R, K)), ((0, pad_rows), (0, 0))
            )
        # negatives: tn[r,k,s] = src[perm[(((r-offs[s]) mod R)*K + k) mod
        # n_tab]] — the XLA path's fused tile/roll views, materialized as
        # integer index tiles (contiguous copies, no embedding gather)
        perm = jax.random.permutation(k2, n_tab)
        pidx = (
            jnp.tile(perm, (reps,))[: R * K].reshape(R, K).astype(jnp.int32)
        )
        offs = jax.random.randint(k3, (neg,), 0, R)
        neg_ids = jnp.concatenate(
            [jnp.roll(pidx, offs[s], axis=0) for s in range(neg)], axis=1
        )
        neg_b = jnp.pad(neg_ids, ((0, pad_rows), (0, 0)))
        # sorted head gather stays in XLA: 1/(1+K+K*neg) of the gather
        # traffic, near-sequential by construction
        h_b = jnp.pad(emb[row_heads], ((0, pad_rows), (0, 0)))
        seed_e = (seed_base + e).astype(jnp.int32).reshape(1, 1)
        row_upd = sgd_epoch_rows(
            src, h_b, tails_b, p_b, neg_b, u, seed_e,
            a=a, b=b, gamma=gamma,
            attract_scale=2.0 if self_table else 1.0,
            rng=rng, interpret=interpret,
        )
        upd = jax.ops.segment_sum(
            row_upd, heads_b, num_segments=n_head, indices_are_sorted=True
        )
        return emb + alpha * upd

    return lax.fori_loop(0, span, epoch, emb_head)
