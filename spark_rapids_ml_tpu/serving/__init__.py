"""Online inference serving: a device-resident model registry plus a
micro-batched request queue over the existing transform engines.

Everything here is explicitly constructed — importing the package (or
the library) starts no thread, opens no file, and reads no
``TPUML_SERVE_*`` variable; the batch fit/transform paths are untouched
(see ``docs/serving.md``).
"""

from .registry import (
    ModelRegistry,
    ResidentModel,
    feature_width,
    resident_nbytes,
    serving_family,
)
from .runtime import ServingRuntime

__all__ = [
    "ModelRegistry",
    "ResidentModel",
    "ServingRuntime",
    "feature_width",
    "resident_nbytes",
    "serving_family",
]
