"""Jitted L-BFGS / OWL-QN minimizer — the framework's quasi-Newton engine.

TPU-native replacement for the solver inside cuML's ``LogisticRegressionMG``
(the reference dispatches to cuML's C++ QN solver with ``lbfgs_memory=10``,
``/root/reference/python/src/spark_rapids_ml/classification.py:1062-1064``).
Here the whole optimization is ONE jitted ``lax.while_loop``: each iteration
evaluates the caller's loss/gradient (a masked data pass over the dp-sharded
design matrix — XLA inserts the psum collectives), then does replicated
O(m·p) two-loop-recursion math on fixed-size history buffers. No Python in
the loop, no host round-trips, no dynamic shapes.

L1 regularization uses OWL-QN (the same algorithm Spark/cuML use for
elasticnet): pseudo-gradient in place of the gradient, search-direction
sign alignment, and orthant projection inside the line search.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class LbfgsResult(NamedTuple):
    w: jax.Array          # (p,) solution
    f: jax.Array          # final objective (incl. L1 term)
    n_iter: jax.Array     # iterations taken
    converged: jax.Array  # bool


def _pseudo_gradient(w: jax.Array, g: jax.Array, l1w: jax.Array) -> jax.Array:
    """OWL-QN pseudo-gradient of f(w) + ||l1w * w||_1.

    For w_i != 0 the subgradient is g_i + l1w_i*sign(w_i); at w_i == 0 pick
    the one-sided derivative if it is negative in either direction, else 0.
    """
    nonzero = g + l1w * jnp.sign(w)
    lo = g - l1w  # right derivative
    hi = g + l1w  # left derivative
    at_zero = jnp.where(lo > 0.0, lo, jnp.where(hi < 0.0, hi, 0.0))
    return jnp.where(w != 0.0, nonzero, at_zero)


def _two_loop(
    g: jax.Array, S: jax.Array, Y: jax.Array, k: jax.Array
) -> jax.Array:
    """Standard L-BFGS two-loop recursion H·g on circular buffers.

    ``S``/``Y`` are (m, p); entry i is valid iff i < min(k, m). ``k`` is the
    number of (s, y) pairs ever stored; the newest lives at (k-1) % m.
    """
    m = S.shape[0]
    dtype = g.dtype
    tiny = jnp.asarray(1e-30, dtype)
    n_valid = jnp.minimum(k, m)

    def bwd(i, carry):
        q, alphas = carry
        idx = (k - 1 - i) % m
        valid = (i < n_valid).astype(dtype)
        s, y = S[idx], Y[idx]
        rho = 1.0 / jnp.maximum(jnp.vdot(y, s), tiny)
        alpha = rho * jnp.vdot(s, q) * valid
        q = q - alpha * y
        return q, alphas.at[idx].set(alpha)

    q, alphas = lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), dtype)))

    recent = (k - 1) % m
    s_r, y_r = S[recent], Y[recent]
    gamma = jnp.where(
        k > 0,
        jnp.vdot(s_r, y_r) / jnp.maximum(jnp.vdot(y_r, y_r), tiny),
        jnp.asarray(1.0, dtype),
    )
    r = gamma * q

    def fwd(i, r):
        idx = (k - n_valid + i) % m  # oldest -> newest
        valid = (i < n_valid).astype(dtype)
        s, y = S[idx], Y[idx]
        rho = 1.0 / jnp.maximum(jnp.vdot(y, s), tiny)
        beta = rho * jnp.vdot(y, r)
        r = r + s * (alphas[idx] - beta) * valid
        return r

    return lax.fori_loop(0, m, fwd, r)


def minimize_lbfgs(
    fun: Callable[[jax.Array], jax.Array],
    w0: jax.Array,
    *,
    max_iter: int,
    tol: float,
    l1_weights: Optional[jax.Array] = None,
    history: int = 10,
    max_ls: int = 30,
) -> LbfgsResult:
    """Minimize ``fun(w) + ||l1_weights * w||_1`` from ``w0``.

    ``fun`` must be a smooth, jit-traceable scalar loss (it may close over
    dp-sharded arrays; every call is a distributed data pass). When
    ``l1_weights`` is None or all-zero the algorithm is plain L-BFGS with
    Armijo backtracking; otherwise OWL-QN. Call under ``jit``.
    """
    dtype = w0.dtype
    p = w0.shape[0]
    vg = jax.value_and_grad(fun)
    use_l1 = l1_weights is not None
    l1w = l1_weights if use_l1 else jnp.zeros((p,), dtype)

    def full_obj_parts(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """(L1-inclusive objective, smooth gradient) in one fwd+bwd pass."""
        f, g = vg(w)
        return f + jnp.abs(l1w * w).sum(), g

    f0, g0 = full_obj_parts(w0)

    # state: (w, f, g, S, Y, k, it, converged)
    S0 = jnp.zeros((history, p), dtype)
    Y0 = jnp.zeros((history, p), dtype)
    state0 = (w0, f0, g0, S0, Y0, jnp.asarray(0), jnp.asarray(0), jnp.asarray(False))

    c1 = jnp.asarray(1e-4, dtype)

    def cond(state):
        _, _, _, _, _, _, it, converged = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(converged))

    def body(state):
        w, f, g, S, Y, k, it, _ = state
        pg = _pseudo_gradient(w, g, l1w) if use_l1 else g
        d = -_two_loop(pg, S, Y, k)
        if use_l1:
            # align direction with -pg (OWL-QN sign fix)
            d = jnp.where(d * pg < 0.0, d, 0.0)
            # orthant for the projected line search
            xi = jnp.where(w != 0.0, jnp.sign(w), -jnp.sign(pg))
        dir_deriv = jnp.vdot(pg, d)

        d_norm = jnp.sqrt(jnp.vdot(d, d))
        t0 = jnp.where(
            k == 0, 1.0 / jnp.maximum(d_norm, 1.0), jnp.asarray(1.0, dtype)
        )

        def trial_point(t):
            w_t = w + t * d
            if use_l1:
                w_t = jnp.where(w_t * xi < 0.0, 0.0, w_t)  # orthant projection
            return w_t

        # Armijo backtracking on the full (L1-inclusive) objective. Each
        # trial evaluates value AND gradient in one fused fwd+bwd data pass:
        # the accepted trial's gradient feeds the curvature update directly,
        # so no extra pass is spent re-evaluating the accepted point.
        def ls_cond(carry):
            t, f_t, _, n_try = carry
            ok = f_t <= f + c1 * t * dir_deriv
            return jnp.logical_and(jnp.logical_not(ok), n_try < max_ls)

        def ls_body(carry):
            t, _, _, n_try = carry
            t = t * 0.5
            f_t, g_t = full_obj_parts(trial_point(t))
            return t, f_t, g_t, n_try + 1

        f_t0, g_t0 = full_obj_parts(trial_point(t0))
        t, f_new, g_new, _ = lax.while_loop(
            ls_cond, ls_body, (t0, f_t0, g_t0, jnp.asarray(0))
        )
        w_new = trial_point(t)

        s = w_new - w
        yv = g_new - g
        curv = jnp.vdot(s, yv)
        store = curv > jnp.asarray(1e-10, dtype)
        idx = k % history
        S = jnp.where(store, S.at[idx].set(s), S)
        Y = jnp.where(store, Y.at[idx].set(yv), Y)
        k = jnp.where(store, k + 1, k)

        denom = jnp.maximum(jnp.maximum(jnp.abs(f), jnp.abs(f_new)), 1.0)
        rel_impr = (f - f_new) / denom
        # stop on stall (no descent direction / line-search failure) or tol
        converged = jnp.logical_or(rel_impr <= tol, dir_deriv >= 0.0)

        return (w_new, f_new, g_new, S, Y, k, it + 1, converged)

    w, f, g, S, Y, k, it, converged = lax.while_loop(cond, body, state0)
    return LbfgsResult(w=w, f=f, n_iter=it, converged=converged)


class LbfgsBatchedResult(NamedTuple):
    w: jax.Array          # (B, p) per-lane solutions
    f: jax.Array          # (B,) final objectives (incl. L1 term)
    n_iter: jax.Array     # (B,) iterations each lane took
    converged: jax.Array  # (B,) bool


# vmapping the SAME two-loop the solo solver runs (rather than rewriting
# the reductions with a batch axis) keeps the per-lane op sequence —
# dot_general contractions, scatter updates, index arithmetic — identical
# to a solo solve, which is what the lane/solo bit-parity contract rests on
_two_loop_batched = jax.vmap(_two_loop)
_vdot_batched = jax.vmap(jnp.vdot)


def minimize_lbfgs_batched(
    fun: Callable[[jax.Array], jax.Array],
    w0: jax.Array,
    *,
    max_iter: int,
    tol: jax.Array,
    l1_weights: Optional[jax.Array] = None,
    history: int = 10,
    max_ls: int = 30,
) -> LbfgsBatchedResult:
    """Gang-scheduled :func:`minimize_lbfgs`: B independent lanes, one loop.

    ``fun`` is the *batched* smooth loss ``(B, p) -> (B,)`` — lane b's value
    may only depend on row b of the argument (per-lane gradients come from
    one vjp with a ones cotangent, i.e. one fused fwd+bwd data pass for all
    lanes). ``w0`` is ``(B, p)``; ``tol`` is per-lane ``(B,)``;
    ``l1_weights`` (optional) is per-lane ``(B, p)`` and switches the whole
    group to OWL-QN (lanes wanting plain L-BFGS must go in a separate call —
    OWL-QN's direction sign-fix is not the identity even at l1=0).

    The ``lax.while_loop`` runs until every lane is done. Correctness core:
    a lane that converges (or exhausts ``max_iter``) is FROZEN — every state
    update is guarded by ``jnp.where(active, new, old)`` — so its final
    state is bit-identical to a solo :func:`minimize_lbfgs` run of the same
    problem, no matter how long the slowest lane keeps the gang looping.
    (A plain vmap-of-while has no such guarantee: it keeps executing the
    body for finished lanes, and OWL-QN's orthant projection can move a
    converged iterate again.) The line search is per-lane: each lane halves
    its own step until its own Armijo test passes, riding the shared data
    pass of the lanes still searching.
    """
    dtype = w0.dtype
    B, p = w0.shape
    use_l1 = l1_weights is not None
    l1w = l1_weights if use_l1 else jnp.zeros((B, p), dtype)

    def full_obj_parts(W: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Per-lane (L1-inclusive objective, smooth gradient), ONE shared
        fwd+bwd data pass. The ones-cotangent vjp is exact per-lane: lane
        b's loss depends only on lane b's params, so rows of the vjp output
        are the per-lane gradients."""
        f, vjp = jax.vjp(fun, W)
        (g,) = vjp(jnp.ones_like(f))
        return f + jnp.abs(l1w * W).sum(axis=-1), g

    f0, g0 = full_obj_parts(w0)

    S0 = jnp.zeros((B, history, p), dtype)
    Y0 = jnp.zeros((B, history, p), dtype)
    zi = jnp.zeros((B,), jnp.int32)
    state0 = (w0, f0, g0, S0, Y0, zi, zi, jnp.zeros((B,), bool))

    c1 = jnp.asarray(1e-4, dtype)

    def cond(state):
        _, _, _, _, _, _, it, converged = state
        return jnp.any(jnp.logical_and(jnp.logical_not(converged), it < max_iter))

    def body(state):
        w, f, g, S, Y, k, it, converged = state
        # lanes still running this iteration; everything a frozen lane
        # "computes" below is discarded by the where-guards at the bottom
        active = jnp.logical_and(jnp.logical_not(converged), it < max_iter)

        pg = _pseudo_gradient(w, g, l1w) if use_l1 else g
        d = -_two_loop_batched(pg, S, Y, k)
        if use_l1:
            d = jnp.where(d * pg < 0.0, d, 0.0)
            xi = jnp.where(w != 0.0, jnp.sign(w), -jnp.sign(pg))
        dir_deriv = _vdot_batched(pg, d)

        d_norm = jnp.sqrt(_vdot_batched(d, d))
        t0 = jnp.where(
            k == 0, 1.0 / jnp.maximum(d_norm, 1.0), jnp.asarray(1.0, dtype)
        )

        def trial_point(t):
            w_t = w + t[:, None] * d
            if use_l1:
                w_t = jnp.where(w_t * xi < 0.0, 0.0, w_t)
            return w_t

        # Per-lane Armijo backtracking. One batched data pass per halving
        # round serves every lane still searching; lanes already accepted
        # (and frozen lanes) keep their (t, f, g) via the need-guard, so
        # each lane sees exactly the solo solver's trial sequence.
        def ls_cond(carry):
            _, _, _, n_try, ok = carry
            return jnp.any(active & ~ok & (n_try < max_ls))

        def ls_body(carry):
            t, f_t, g_t, n_try, ok = carry
            need = active & ~ok & (n_try < max_ls)
            t_new = jnp.where(need, t * 0.5, t)
            f_n, g_n = full_obj_parts(trial_point(t_new))
            f_t = jnp.where(need, f_n, f_t)
            g_t = jnp.where(need[:, None], g_n, g_t)
            ok = jnp.where(need, f_t <= f + c1 * t_new * dir_deriv, ok)
            return t_new, f_t, g_t, n_try + need.astype(jnp.int32), ok

        f_t0, g_t0 = full_obj_parts(trial_point(t0))
        ok0 = f_t0 <= f + c1 * t0 * dir_deriv
        t, f_new, g_new, _, _ = lax.while_loop(
            ls_cond, ls_body, (t0, f_t0, g_t0, jnp.zeros((B,), jnp.int32), ok0)
        )
        w_new = trial_point(t)

        s = w_new - w
        yv = g_new - g
        curv = _vdot_batched(s, yv)
        store = active & (curv > jnp.asarray(1e-10, dtype))
        idx = k % history
        S_set = jax.vmap(lambda Sb, i, sb: Sb.at[i].set(sb))(S, idx, s)
        Y_set = jax.vmap(lambda Yb, i, yb: Yb.at[i].set(yb))(Y, idx, yv)
        S = jnp.where(store[:, None, None], S_set, S)
        Y = jnp.where(store[:, None, None], Y_set, Y)
        k = jnp.where(store, k + 1, k)

        denom = jnp.maximum(jnp.maximum(jnp.abs(f), jnp.abs(f_new)), 1.0)
        rel_impr = (f - f_new) / denom
        conv_now = jnp.logical_or(rel_impr <= tol, dir_deriv >= 0.0)

        # the freeze: frozen lanes keep w/f/g (and S/Y/k via the store
        # guard above, which requires `active`) bit-exactly
        w = jnp.where(active[:, None], w_new, w)
        f = jnp.where(active, f_new, f)
        g = jnp.where(active[:, None], g_new, g)
        converged = jnp.where(active, conv_now, converged)
        it = it + active.astype(jnp.int32)
        return (w, f, g, S, Y, k, it, converged)

    w, f, g, S, Y, k, it, converged = lax.while_loop(cond, body, state0)
    return LbfgsBatchedResult(w=w, f=f, n_iter=it, converged=converged)


def minimize_lbfgs_host(
    value_grad: Callable,
    w0,
    *,
    max_iter: int,
    tol: float,
    l1_weights=None,
    history: int = 10,
    max_ls: int = 30,
    checkpointer=None,
) -> LbfgsResult:
    """Host-driven L-BFGS/OWL-QN for out-of-core objectives.

    Same algorithm as :func:`minimize_lbfgs` (Armijo backtracking on the
    L1-inclusive objective, pseudo-gradient + orthant projection for L1,
    curvature-guarded history) but the loop runs in Python: each
    ``value_grad(w)`` call is free to stream the dataset through the device
    in chunks (a full distributed data pass), which a ``lax.while_loop``
    cannot express. The O(m·p) two-loop math runs in float64 on host —
    negligible next to the data passes.

    ``value_grad`` must return the SMOOTH (f, g) pair; the L1 term is added
    here, mirroring ``full_obj_parts`` in the jitted solver.

    ``checkpointer`` (a ``runtime.FitCheckpointer``, or None) snapshots the
    full carry — ``w/f/g`` and the ``S/Y`` history — after each iteration
    and resumes from the last committed one on refit. The algorithm is
    deterministic given the carry, so an interrupted-then-resumed run walks
    the identical iterate sequence as an uninterrupted one.
    """
    import numpy as np

    from ..runtime import counters
    from ..runtime.faults import fault_site
    from ..runtime.scheduler import preempt_point

    w = np.asarray(w0, dtype=np.float64)
    p = w.shape[0]
    use_l1 = l1_weights is not None
    l1w = np.asarray(l1_weights, np.float64) if use_l1 else np.zeros((p,))

    def full_obj(wv):
        f, g = value_grad(wv)
        return float(f) + float(np.abs(l1w * wv).sum()), np.asarray(g, np.float64)

    def pseudo_grad(wv, g):
        nonzero = g + l1w * np.sign(wv)
        lo = g - l1w
        hi = g + l1w
        at_zero = np.where(lo > 0.0, lo, np.where(hi < 0.0, hi, 0.0))
        return np.where(wv != 0.0, nonzero, at_zero)

    S: list = []
    Y: list = []
    c1 = 1e-4
    it = 0
    converged = False
    resumed = checkpointer.load() if checkpointer is not None else None
    if resumed is not None:
        it, arrays, extra = resumed
        w = np.asarray(arrays["w"], np.float64)
        g = np.asarray(arrays["g"], np.float64)
        S = [np.asarray(row, np.float64) for row in arrays["S"]]
        Y = [np.asarray(row, np.float64) for row in arrays["Y"]]
        f = float(extra["f"])
        converged = bool(extra.get("converged", False))
        counters.bump("resumed_fits")
        counters.note("resumed_from", it)
    else:
        f, g = full_obj(w)
    while it < max_iter and not converged:
        fault_site("sgd:epoch")
        pg = pseudo_grad(w, g) if use_l1 else g
        # two-loop recursion over the (oldest -> newest) history
        q = pg.copy()
        alphas = []
        for s, yv in reversed(list(zip(S, Y))):
            rho = 1.0 / max(float(yv @ s), 1e-30)
            a = rho * float(s @ q)
            q -= a * yv
            alphas.append((a, rho))
        if S:
            s_r, y_r = S[-1], Y[-1]
            gamma = float(s_r @ y_r) / max(float(y_r @ y_r), 1e-30)
        else:
            gamma = 1.0
        r = gamma * q
        for (a, rho), (s, yv) in zip(reversed(alphas), zip(S, Y)):
            beta = rho * float(yv @ r)
            r += s * (a - beta)
        d = -r
        if use_l1:
            d = np.where(d * pg < 0.0, d, 0.0)
            xi = np.where(w != 0.0, np.sign(w), -np.sign(pg))
        dir_deriv = float(pg @ d)

        d_norm = float(np.sqrt(d @ d))
        t = 1.0 / max(d_norm, 1.0) if not S else 1.0

        def trial(tv):
            wt = w + tv * d
            if use_l1:
                wt = np.where(wt * xi < 0.0, 0.0, wt)
            return wt

        f_t, g_t = full_obj(trial(t))
        n_try = 0
        while f_t > f + c1 * t * dir_deriv and n_try < max_ls:
            t *= 0.5
            f_t, g_t = full_obj(trial(t))
            n_try += 1
        w_new = trial(t)

        s = w_new - w
        yv = g_t - g
        if float(s @ yv) > 1e-10:
            S.append(s)
            Y.append(yv)
            if len(S) > history:
                S.pop(0)
                Y.pop(0)

        denom = max(abs(f), abs(f_t), 1.0)
        rel_impr = (f - f_t) / denom
        converged = rel_impr <= tol or dir_deriv >= 0.0
        w, f, g = w_new, f_t, g_t
        it += 1
        if checkpointer is not None:
            state = lambda: {
                "w": w,
                "g": g,
                "S": np.stack(S) if S else np.zeros((0, p)),
                "Y": np.stack(Y) if Y else np.zeros((0, p)),
            }
            checkpointer.maybe_save(
                it, state(), {"f": f, "converged": bool(converged)}
            )
            preempt_point(
                checkpointer, it, state, {"f": f, "converged": bool(converged)}
            )

    if checkpointer is not None:
        checkpointer.clear()

    import jax.numpy as _jnp

    return LbfgsResult(
        w=w, f=_jnp.asarray(f), n_iter=_jnp.asarray(it), converged=_jnp.asarray(converged)
    )
