"""PCA — Spark ML drop-in, TPU-native fit/transform.

Reference: ``/root/reference/python/src/spark_rapids_ml/feature.py`` (447 LoC).
API parity targets:
  * params: ``k`` (mapped to backend ``n_components``, reference
    ``feature.py:61-75``), ``inputCol``/``featuresCol``/``featuresCols``,
    ``outputCol``.
  * model attributes: ``mean_``, ``components_``, ``explained_variance_``,
    ``explained_variance_ratio_``, ``singular_values_``, plus Spark-style
    ``pc`` / ``explainedVariance``.
  * transform semantics: Spark's PCA does NOT mean-center at transform time;
    the reference compensates cuML's centering by adding the projected mean
    back (``feature.py:426-439``). We compute ``X @ pc`` directly.

TPU-native fit (vs reference's cuML ``PCAMG.fit``, ``feature.py:216-259``):
one jitted global-math function over the row-sharded design matrix — masked
mean + Gram (psum'd by XLA over the dp mesh axis), replicated ``eigh`` of
the d×d covariance, deterministic sign flip.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core import FitFunc, FitInputs, _TpuEstimator, _TpuModel
from ..data.dataframe import DataFrame
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasInputCol,
    HasOutputCol,
    Param,
    TypeConverters,
    _mk,
)
from ..ops.linalg import (
    mean_and_cov,
    mean_and_cov_chunked,
    mp_gram_blocks,
    topk_eigh,
)


class PCAClass:
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # reference ``feature.py:61-75``
        return {"k": "n_components"}

    @classmethod
    def _param_value_mapping(cls) -> Dict[str, Callable[[Any], Any]]:
        return {}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {"n_components": None, "whiten": False}


class _PCAParams(HasInputCol, HasOutputCol, HasFeaturesCol, HasFeaturesCols):
    k = _mk("k", "number of principal components", TypeConverters.toInt)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(outputCol="pca_features")

    def getK(self) -> int:
        return self.getOrDefault("k")


@functools.partial(jax.jit, static_argnames=("k",))
def _pca_from_cov(mean: jax.Array, cov: jax.Array, n: jax.Array, k: int):
    """Finalize PCA from (mean, covariance, count) — shared by the resident
    and streaming fits so both produce bit-identical model attributes."""
    evals, evecs = topk_eigh(cov, k)
    evals = jnp.maximum(evals, 0.0)
    total_var = jnp.trace(cov)
    # singular values of the centered matrix: sqrt(λ·(n-1))
    singular_values = jnp.sqrt(evals * (n - 1.0))
    return {
        "mean": mean,
        "components": evecs.T,            # (k, d)
        "explained_variance": evals,
        "explained_variance_ratio": evals / total_var,
        "singular_values": singular_values,
    }


@functools.partial(
    jax.jit, static_argnames=("k", "mesh", "csize", "mp_blocks")
)
def _pca_fit_kernel(
    X: jax.Array, mask: jax.Array, k: int, mesh=None, csize=None,
    mp_blocks: bool = False,
):
    """Resident-fit kernel. With ``mesh``/``csize`` (rows dp-sharded, padded
    to a per-device ``csize`` multiple) the covariance is accumulated in
    row-chunk scans with O(csize·d) temporaries — at double-digit-GB row
    counts the fused form can materialize the centered copy of X and OOM;
    without them (e.g. 2-D (dp, mp)-sharded dry runs) the fused global-math
    path is used. ``mp_blocks`` (static; resolve with ``mp_gram_blocks``
    outside jit) column-shards the Gram accumulator over the mesh's mp
    axis; the blocked covariance also rides out in the result so the
    caller can measure its per-shard bytes."""
    if mesh is not None and _TpuEstimator.rows_chunkable(
        X.shape[0], mesh, csize
    ):
        mean, cov, n = mean_and_cov_chunked(
            X, mask, mesh, csize, mp_blocks=mp_blocks
        )
    else:
        mean, cov, n = mean_and_cov(X, mask)
    out = _pca_from_cov(mean, cov, n, k)
    if mp_blocks:
        out["cov"] = cov
    return out


class PCA(PCAClass, _TpuEstimator, _PCAParams):
    """``PCA(k=3).fit(df)`` — drop-in for ``pyspark.ml.feature.PCA``."""

    def __init__(self, **kwargs: Any) -> None:
        _TpuEstimator.__init__(self)
        _PCAParams.__init__(self)
        self._set_params(**kwargs)

    def setK(self, value: int) -> "PCA":
        self._set_params(k=value)
        return self

    def setInputCol(self, value: str) -> "PCA":
        self._set_params(inputCol=value)
        return self

    def setOutputCol(self, value: str) -> "PCA":
        self._set_params(outputCol=value)
        return self

    def _chunk_rows(self, n_rows: int, n_dp: int) -> int:
        # route resident fits through the chunked covariance scan: 64k-row
        # chunks keep temporaries O(chunk·d) so a near-HBM-sized X cannot
        # OOM on the centered copy (see mean_and_cov_chunked)
        return self._equal_chunk_rows(n_rows, n_dp, 65_536)

    def _get_tpu_fit_func(self, dataset: DataFrame) -> FitFunc:
        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            k = int(params.get("n_components") or self.getK())
            if k > inputs.n_features:
                raise ValueError(
                    f"k={k} must be <= number of features {inputs.n_features}"
                )
            mp = mp_gram_blocks(inputs.mesh, inputs.X.shape[1])
            use_mp = mp > 1 and _TpuEstimator.rows_chunkable(
                inputs.X.shape[0], inputs.mesh, inputs.csize
            )
            out = _pca_fit_kernel(
                inputs.X, inputs.mask, k, mesh=inputs.mesh,
                csize=inputs.csize, mp_blocks=use_mp,
            )
            report = None
            if use_mp:
                cov = out.pop("cov")
                report = {
                    "mp_degree": mp,
                    "gram_shard_bytes": int(
                        cov.addressable_shards[0].data.nbytes
                    ),
                }
            result = {key: np.asarray(v) for key, v in out.items()}
            if report:
                result["_fit_report"] = report
            return result

        return _fit

    def _get_tpu_streaming_fit_func(self, dataset: DataFrame):
        """Out-of-core fit: two chunked passes (mean, then centered Gram)
        accumulate the d×d covariance with O(chunk + d²) device memory; the
        eigh finalize is shared with the resident kernel."""
        from ..core import StreamInputs
        from ..ops.streaming import streamed_suffstats

        def _fit(inputs: StreamInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            k = int(params.get("n_components") or self.getK())
            if k > inputs.n_features:
                raise ValueError(
                    f"k={k} must be <= number of features {inputs.n_features}"
                )
            stats = streamed_suffstats(
                inputs.source, inputs.mesh, inputs.chunk_rows, inputs.dtype,
                with_y=False, fit_intercept=True,
            )
            report = stats.pop("_mp_report", None)
            cov = stats["G"] / (stats["n"] - 1.0)
            out = _pca_from_cov(stats["mean_x"], cov, stats["n"], k)
            result = {key: np.asarray(v) for key, v in out.items()}
            if report:
                result["_fit_report"] = report
            return result

        return _fit

    def _create_model(self, result: Dict[str, Any]) -> "PCAModel":
        return PCAModel(**result)


class PCAModel(PCAClass, _TpuModel, _PCAParams):
    def __init__(self, **attrs: Any) -> None:
        _TpuModel.__init__(self, **attrs)
        _PCAParams.__init__(self)

    # -- attribute surface (reference model attrs + Spark names) -----------
    @property
    def mean_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["mean"])

    @property
    def components_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["components"])

    @property
    def explained_variance_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["explained_variance"])

    @property
    def explained_variance_ratio_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["explained_variance_ratio"])

    @property
    def singular_values_(self) -> np.ndarray:
        return np.asarray(self._model_attributes["singular_values"])

    @property
    def pc(self) -> np.ndarray:
        """Spark-style principal-components matrix, shape (d, k)."""
        return self.components_.T

    @property
    def explainedVariance(self) -> np.ndarray:
        return self.explained_variance_ratio_

    def setInputCol(self, value: str) -> "PCAModel":
        self._set_params(inputCol=value)
        return self

    def setOutputCol(self, value: str) -> "PCAModel":
        self._set_params(outputCol=value)
        return self

    # -- transform ---------------------------------------------------------
    def _get_tpu_transform_func(
        self, dataset: Optional[DataFrame] = None
    ) -> Callable[[np.ndarray], Dict[str, np.ndarray]]:
        out_col = self.getOrDefault("outputCol")

        def _build() -> Callable[[np.ndarray], Dict[str, np.ndarray]]:
            components = jnp.asarray(self.components_)  # (k, d)

            @jax.jit
            def _project(Xb: jax.Array) -> jax.Array:
                # Spark semantics: no mean removal (reference
                # ``feature.py:426-439``)
                return Xb @ components.T

            def _fn(Xb: np.ndarray) -> Dict[str, np.ndarray]:
                return {out_col: np.asarray(_project(jnp.asarray(Xb)))}

            return _fn

        return self._memoized_transform_fn(("pca", out_col), _build)

    def _out_cols(self):
        return [self.getOrDefault("outputCol")]
