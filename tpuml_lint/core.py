"""tpuml-lint core: findings, suppressions, file walking, baseline.

Stdlib-only by design (``ast``, ``json``, ``tokenize`` — no third-party
deps), so the CI stage that runs it can never get the "not installed;
skipping" treatment black/mypy get in hermetic images. Rules live in
sibling ``tpu00N_*.py`` modules; each exposes ``CODE``, ``NAME``, and
either ``check_file(sf)`` (per-file AST pass) or
``check_project(files, repo_root)`` (whole-tree invariants like the
env-var doc-drift check).

Suppression syntax (`docs/static_analysis.md`): a ``# tpuml:
ignore[TPU003]`` trailing comment on the flagged line, or on a
comment-only line directly above it (for findings on long wrapped
calls). Multiple codes: ``# tpuml: ignore[TPU001,TPU004]``.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_IGNORE_RE = re.compile(r"#\s*tpuml:\s*ignore\[([A-Z0-9,\s]+)\]")


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``context`` (the stripped source line) is the
    churn-tolerant third of the baseline fingerprint — line numbers move
    on every edit, the offending line text rarely does."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    fixit: str = ""
    context: str = ""

    def fingerprint(self) -> Tuple[str, str, str]:
        return (self.path, self.rule, self.context)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        out = f"{loc}: {self.rule} {self.message}"
        if self.fixit:
            out += f"\n    fix: {self.fixit}"
        return out


@dataclass
class SourceFile:
    """A parsed python file handed to per-file rules."""

    path: str  # repo-relative, forward slashes
    abspath: str
    text: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.text.splitlines()

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(
        self,
        rule: str,
        node: ast.AST,
        message: str,
        fixit: str = "",
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        return Finding(
            rule=rule,
            path=self.path,
            line=line,
            col=col,
            message=message,
            fixit=fixit,
            context=self.line_at(line),
        )

    def suppressed(self, f: Finding) -> bool:
        """True when the finding's line (or a comment-only line directly
        above) carries a matching ``# tpuml: ignore[...]`` marker."""
        for lineno in (f.line, f.line - 1):
            if not (1 <= lineno <= len(self.lines)):
                continue
            raw = self.lines[lineno - 1]
            if lineno != f.line and not raw.strip().startswith("#"):
                continue
            m = _IGNORE_RE.search(raw)
            if m and f.rule in {c.strip() for c in m.group(1).split(",")}:
                return True
        return False


def iter_py_files(paths: Sequence[str], repo_root: str) -> List[str]:
    """Expand CLI path operands into a sorted list of .py files."""
    out: List[str] = []
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap):
            if ap.endswith(".py"):
                out.append(ap)
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".pytest_cache")
            ]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def load_source(abspath: str, repo_root: str) -> Tuple[Optional[SourceFile], Optional[Finding]]:
    rel = os.path.relpath(abspath, repo_root).replace(os.sep, "/")
    try:
        with open(abspath, "r", encoding="utf-8") as f:
            text = f.read()
        tree = ast.parse(text, filename=abspath)
    except (OSError, SyntaxError, ValueError) as e:
        return None, Finding(
            rule="TPU000",
            path=rel,
            line=getattr(e, "lineno", 1) or 1,
            col=1,
            message=f"file could not be parsed: {e}",
        )
    return SourceFile(path=rel, abspath=abspath, text=text, tree=tree), None


# --- baseline --------------------------------------------------------------


def load_baseline(path: str) -> List[Tuple[str, str, str]]:
    """Grandfathered fingerprints; missing file = empty baseline."""
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return [
        (e["path"], e["rule"], e.get("context", ""))
        for e in data.get("findings", [])
    ]


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    data = {
        "comment": (
            "Grandfathered tpuml-lint findings. Target: empty. New code "
            "must fix or inline-suppress, never extend this file."
        ),
        "findings": [
            {"path": f.path, "rule": f.rule, "context": f.context}
            for f in sorted(findings, key=lambda f: (f.path, f.line))
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Sequence[Tuple[str, str, str]]
) -> Tuple[List[Finding], List[Tuple[str, str, str]]]:
    """(new findings, stale baseline entries). Each baseline fingerprint
    absorbs any number of identical findings (a context line duplicated
    within one file counts once — good enough for a target-empty file)."""
    allowed = set(baseline)
    new = [f for f in findings if f.fingerprint() not in allowed]
    seen = {f.fingerprint() for f in findings}
    stale = [b for b in baseline if b not in seen]
    return new, stale


# --- shared AST helpers ----------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def os_environ_aliases(tree: ast.AST) -> Tuple[set, set, set]:
    """(os module aliases, bare 'environ' aliases, bare 'getenv' aliases)
    bound by imports in this module."""
    os_names, environ_names, getenv_names = set(), set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "os":
                    os_names.add(a.asname or "os")
        elif isinstance(node, ast.ImportFrom) and node.module == "os":
            for a in node.names:
                if a.name == "environ":
                    environ_names.add(a.asname or "environ")
                elif a.name == "getenv":
                    getenv_names.add(a.asname or "getenv")
    return os_names, environ_names, getenv_names


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def parents_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """child -> parent for every node (one pass; rules share it)."""
    out: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            out[child] = parent
    return out


LOOP_NODES = (ast.For, ast.While, ast.AsyncFor)
COMPREHENSION_NODES = (
    ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp
)


def enclosing(
    node: ast.AST, parents: Dict[ast.AST, ast.AST], kinds: tuple
) -> Optional[ast.AST]:
    """Nearest ancestor of one of ``kinds`` (not crossing function defs
    unless the def itself matches)."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = parents.get(cur)
    return None


def enclosing_within_function(
    node: ast.AST, parents: Dict[ast.AST, ast.AST], kinds: tuple
) -> Optional[ast.AST]:
    """Like :func:`enclosing` but stops at the nearest enclosing function
    boundary — a loop OUTSIDE the def that merely calls a helper is not a
    per-iteration construction of anything inside the helper."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return None
        cur = parents.get(cur)
    return None
