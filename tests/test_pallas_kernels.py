"""Pallas kernel correctness (interpret mode on the CPU mesh).

The real kernels run only on TPU (`_pallas_gram_ok` gates on backend); these
tests run the same kernel bodies through the Pallas interpreter against
numpy oracles, including the last-partial-tile index-validity guard.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.linalg import _shifted_gram_pallas


@pytest.mark.parametrize("n,tile", [(512, 128), (700, 128), (100, 256)])
def test_shifted_gram_pallas_matches_numpy(n, tile):
    d = 256
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32) + 2.0
    mask = (rng.random(n) > 0.1).astype(np.float32)
    mu = X[:64].mean(axis=0)

    G, s = _shifted_gram_pallas(
        jnp.asarray(X), jnp.asarray(mask), jnp.asarray(mu),
        tile=tile, interpret=True,
    )

    xs = (X.astype(np.float64) - mu.astype(np.float64)) * mask[:, None]
    G_ref = xs.T @ xs
    s_ref = xs.sum(axis=0)
    scale = np.abs(G_ref).max()
    assert np.abs(np.asarray(G, np.float64) - G_ref).max() / scale < 1e-5
    assert np.abs(np.asarray(s, np.float64) - s_ref).max() < 1e-2


def test_shifted_gram_pallas_all_masked_tail():
    # padding suffix fully masked: the guard and the mask must compose
    d, n, tile = 256, 384, 128
    rng = np.random.default_rng(1)
    X = rng.normal(size=(n, d)).astype(np.float32)
    X[300:] = 1e30  # padded rows may hold (finite) garbage — must not leak
    mask = (np.arange(n) < 300).astype(np.float32)
    mu = X[:64].mean(axis=0)

    G, s = _shifted_gram_pallas(
        jnp.asarray(X), jnp.asarray(mask), jnp.asarray(mu),
        tile=tile, interpret=True,
    )
    assert np.isfinite(np.asarray(G)).all()
    xs = (X[:300].astype(np.float64) - mu.astype(np.float64))
    G_ref = xs.T @ xs
    assert np.abs(np.asarray(G, np.float64) - G_ref).max() / np.abs(G_ref).max() < 1e-5


@pytest.mark.parametrize("multinomial,K", [(False, 1), (True, 3)])
def test_fused_logreg_loss_grad_matches_autodiff(multinomial, K):
    """The fused Pallas loss+grad (one data pass) must match
    jax.value_and_grad of the reference formulation, including masking and
    the padded-classes guard."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.ops.logreg_pallas import make_fused_data_loss
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    rng = np.random.default_rng(0)
    n, d = 8 * 40, 256
    X = rng.normal(size=(n, d)).astype(np.float32)
    ncls = K if multinomial else 2
    y = rng.integers(0, ncls, size=n).astype(np.float32)
    mask = (np.arange(n) < n - 13).astype(np.float32)
    put = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    Xd, yd, md = put(X), put(y), put(mask)
    Aeff = jnp.asarray(rng.normal(size=(K, d)).astype(np.float32) * 0.1)
    beff = jnp.asarray(rng.normal(size=(K,)).astype(np.float32) * 0.1)

    f = make_fused_data_loss(Xd, yd, md, mesh, K, multinomial, interpret=True)
    loss, (gA, gb) = jax.value_and_grad(
        lambda a, b: f(a, b), argnums=(0, 1)
    )(Aeff, beff)

    def ref(a, b):
        logits = Xd @ a.T + b[None, :]
        if multinomial:
            yi = yd.astype(jnp.int32)
            ll = jax.nn.logsumexp(logits, axis=1) - jnp.take_along_axis(
                logits, yi[:, None], axis=1
            )[:, 0]
        else:
            z = logits[:, 0]
            ll = jax.nn.softplus(z) - yd * z
        return (ll * md).sum()

    rl, (rgA, rgb) = jax.value_and_grad(ref, argnums=(0, 1))(Aeff, beff)
    assert abs(float(loss) - float(rl)) < 1e-2
    assert float(jnp.abs(gA - rgA).max() / jnp.abs(rgA).max()) < 1e-4
    assert float(jnp.abs(gb - rgb).max()) < 1e-2


def test_logreg_fit_fused_branch_matches_xla(monkeypatch):
    """Run the REAL fused branch inside logreg_fit (gate -> custom_vjp ->
    L-BFGS) via the interpret override and require coefficient parity with
    the XLA branch — guards the integration wiring (the /n scaling, the
    standardization reparametrization feeding Aeff/beff)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.ops import logreg_pallas
    from spark_rapids_ml_tpu.ops.logreg_kernels import logreg_fit
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    rng = np.random.default_rng(2)
    n, d = 8 * 48, 256
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32) * 0.2
    y = (X @ w > 0).astype(np.float32)
    mask = (np.arange(n) < n - 17).astype(np.float32)
    put = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    Xd, yd, md = put(X), put(y), put(mask)

    kw = dict(
        n_classes=2, multinomial=False, fit_intercept=True,
        standardization=True, l1=jnp.float32(0.0), l2=jnp.float32(1e-3),
        use_l1=False, max_iter=25, tol=jnp.float32(0.0),
    )
    ref = logreg_fit(Xd, md, yd, mesh=None, **kw)

    monkeypatch.setattr(logreg_pallas, "FORCE_INTERPRET", True)
    assert logreg_pallas.logreg_pallas_ok(d, 1, jnp.float32)
    # FORCE_INTERPRET is read at trace time but is not part of the jit
    # cache key: drop cached executables so this call really traces (and
    # runs) the fused branch, and again afterwards so no interpreted
    # executable leaks into later same-signature calls
    jax.clear_caches()
    try:
        fused = logreg_fit(Xd, md, yd, mesh=mesh, **kw)
    finally:
        jax.clear_caches()

    cr = np.asarray(ref["coef_"])
    cf = np.asarray(fused["coef_"])
    assert np.abs(cr - cf).max() / max(np.abs(cr).max(), 1e-9) < 1e-3
    assert abs(float(ref["intercept_"][0]) - float(fused["intercept_"][0])) < 1e-3


def test_logreg_pallas_gate_rejects_overwide_class_packing():
    # K in 121..127 would make the packed row exceed 128 lanes (Kp=128 + loss)
    from spark_rapids_ml_tpu.ops.logreg_pallas import logreg_pallas_ok

    assert not logreg_pallas_ok(256, 121, jnp.float32)
    assert not logreg_pallas_ok(256, 127, jnp.float32)


def test_mean_and_cov_chunked_pallas_branch_matches_scan(monkeypatch):
    """Run the REAL Pallas branch inside mean_and_cov_chunked (gate ->
    shard_map -> kernel -> rank-1 correction) via the interpret override
    and require parity with the scan branch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.ops import linalg
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    rng = np.random.default_rng(4)
    n, d, csize = 8 * 3 * 16, 128, 16
    X = (rng.normal(size=(n, d)) + 100.0).astype(np.float32)
    mask = (np.arange(n) < n - 19).astype(np.float32)
    put = lambda a: jax.device_put(a, NamedSharding(mesh, P("dp")))
    Xd, md = put(X), put(mask)

    m1, c1, n1 = linalg.mean_and_cov_chunked(Xd, md, mesh, csize)

    monkeypatch.setattr(linalg, "FORCE_INTERPRET", True)
    assert linalg._pallas_gram_ok(d, jnp.float32)
    jax.clear_caches()  # FORCE_INTERPRET is read at trace time, not cached
    try:
        m2, c2, n2 = linalg.mean_and_cov_chunked(Xd, md, mesh, csize)
    finally:
        jax.clear_caches()

    assert float(n1) == float(n2)
    assert np.abs(np.asarray(m1) - np.asarray(m2)).max() < 1e-3
    scale = np.abs(np.asarray(c1)).max()
    assert np.abs(np.asarray(c1) - np.asarray(c2)).max() / scale < 1e-4


def test_logreg_fused_bf16_objective_close_to_f32():
    """bf16 X reads (f32 accumulation) must land within solver noise of
    the f32 fit — the bandwidth-halving bench configuration."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops import logreg_pallas
    from spark_rapids_ml_tpu.ops.logreg_kernels import logreg_fit
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh, shard_rows

    rng = np.random.default_rng(0)
    n, d = 512, 128
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d,))
    y = (X @ w > 0).astype(np.float32)
    mesh = make_mesh(2)
    Xd, mask = shard_rows(X, mesh)
    yd, _ = shard_rows(y, mesh)

    logreg_pallas.FORCE_INTERPRET = True
    jax.clear_caches()
    try:
        kw = dict(
            n_classes=2, multinomial=False, fit_intercept=True,
            standardization=True, l1=jnp.float32(0.0), l2=jnp.float32(1e-3),
            use_l1=False, max_iter=30, tol=jnp.float32(0.0), mesh=mesh,
        )
        f32 = logreg_fit(Xd, mask, yd, objective_dtype="float32", **kw)
        b16 = logreg_fit(Xd, mask, yd, objective_dtype="bfloat16", **kw)
    finally:
        logreg_pallas.FORCE_INTERPRET = False
        jax.clear_caches()
    np.testing.assert_allclose(
        np.asarray(b16["coef_"]), np.asarray(f32["coef_"]), rtol=0.05, atol=0.02
    )
    # predictions must agree except at the decision boundary
    agree = np.mean(
        (X @ np.asarray(f32["coef_"]).T[:, 0] > 0)
        == (X @ np.asarray(b16["coef_"]).T[:, 0] > 0)
    )
    assert agree > 0.99, agree


@pytest.mark.parametrize("matmul_dtype", [None, "bfloat16"])
def test_lloyd_step_pallas_matches_xla_chunk_stats(matmul_dtype):
    """The fused Pallas Lloyd pass must reproduce the XLA chunked step's
    (sums, counts, cost) triple — including masked rows, a non-128 k
    (center padding must never win the argmin), and both contraction
    dtypes."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops import kmeans_pallas
    from spark_rapids_ml_tpu.ops.kmeans_kernels import _chunk_stats

    md = None if matmul_dtype is None else jnp.bfloat16
    rng = np.random.default_rng(9)
    n, d, k = 4096, 128, 37
    X = rng.normal(size=(n, d)).astype(np.float32)
    mask = np.ones((n,), np.float32)
    mask[-300:] = 0.0  # padding rows must not contribute
    centers = rng.normal(size=(k, d)).astype(np.float32)

    # force the XLA branch for the reference values: on a TPU host the
    # gate would engage Pallas inside _chunk_stats too, and the test
    # would compare the kernel against itself
    orig_ok = kmeans_pallas.kmeans_pallas_ok
    kmeans_pallas.kmeans_pallas_ok = lambda *a: False
    try:
        # single-call reference computation  # tpuml: ignore[TPU003]
        sums_x, counts_x, cost_x = jax.jit(
            lambda X, m, c: _chunk_stats(X, m, c, csize=1024, matmul_dtype=md)
        )(X, mask, centers)
    finally:
        kmeans_pallas.kmeans_pallas_ok = orig_ok

    # TILE must divide n for the gate; shrink it for test scale. _TILE is
    # baked into lloyd_step_pallas's jit trace — drop caches on restore
    # so later same-shape calls don't silently reuse the test tile.
    old_tile = kmeans_pallas._TILE
    kmeans_pallas._TILE = 512
    try:
        sums_p, counts_p, cost_p = kmeans_pallas.lloyd_step_pallas(
            X, mask, centers, matmul_dtype=md, interpret=True
        )
    finally:
        kmeans_pallas._TILE = old_tile
        jax.clear_caches()

    np.testing.assert_array_equal(np.asarray(counts_p), np.asarray(counts_x))
    rtol = 1e-6 if md is None else 1e-2
    np.testing.assert_allclose(
        np.asarray(sums_p), np.asarray(sums_x), rtol=rtol, atol=1e-3
    )
    np.testing.assert_allclose(
        float(cost_p), float(cost_x), rtol=1e-5 if md is None else 1e-2
    )


def test_kmeans_fit_pallas_branch_matches_xla(monkeypatch):
    """Full KMeans fit with the fused Pallas step ENGAGED (interpret +
    TPUML_LANE_PAD, mirroring the on-TPU ingestion) must match the
    XLA-step fit. The spy asserts the branch actually ran — the gate
    silently falling back would make this test vacuous."""
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.ops import kmeans_pallas

    rng = np.random.default_rng(4)
    # 1024 rows / 2 workers -> 512-row shards: divisible by the test TILE
    X = np.concatenate(
        [
            rng.normal(loc=c, scale=0.3, size=(256, 5))
            for c in (-3.0, 0.0, 3.0, 6.0)
        ]
    ).astype(np.float32)
    df = DataFrame({"features": X})
    kw = dict(k=4, maxIter=12, seed=1, initMode="random", num_workers=2)

    m_xla = KMeans(**kw).fit(df)

    calls = []
    orig = kmeans_pallas.lloyd_step_pallas

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setenv("TPUML_LANE_PAD", "128")  # on-TPU ingestion shape
    monkeypatch.setattr(kmeans_pallas, "FORCE_INTERPRET", True)
    monkeypatch.setattr(kmeans_pallas, "_TILE", 128)
    monkeypatch.setattr(kmeans_pallas, "lloyd_step_pallas", spy)
    jax.clear_caches()  # FORCE_INTERPRET/_TILE are not jit cache keys
    try:
        m_pl = KMeans(**kw).fit(df)
    finally:
        jax.clear_caches()

    assert calls, "fused Pallas Lloyd step never engaged"
    np.testing.assert_allclose(
        np.sort(np.asarray(m_pl.clusterCenters()), axis=0),
        np.sort(np.asarray(m_xla.clusterCenters()), axis=0),
        rtol=1e-5, atol=1e-5,
    )


class TestKnnPallas:
    def test_fused_pass_matches_xla_ring(self):
        """The fused Pallas distance+top-k pass (interpret mode) must agree
        with the XLA tile path on distances and ids, including item padding
        (ni not a block multiple) and query padding."""
        import spark_rapids_ml_tpu.ops.knn_pallas as kp
        import spark_rapids_ml_tpu.ops.knn_kernels as kk
        from spark_rapids_ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
        rng = np.random.default_rng(5)
        nq, ni, d, k = 96, 600, 128, 8
        Xq = jnp.asarray(rng.standard_normal((nq, d)), jnp.float32)
        Xi = jnp.asarray(rng.standard_normal((ni, d)), jnp.float32)
        mi = jnp.ones((ni,), jnp.float32).at[-7:].set(0.0)  # masked tail
        ids = jnp.arange(ni, dtype=jnp.int32) * 3 + 1

        d_ref, i_ref = jax.tree.map(
            np.asarray, kk.ring_knn(Xq, Xi, mi, ids, mesh=mesh, k=k)
        )
        kp.FORCE_INTERPRET = True
        calls = []
        real_pass = kp.knn_pallas_pass
        try:
            # fresh jit so the pallas gate re-evaluates; spy proves the
            # fused path was actually traced (not a cache/gate miss)
            import functools

            def spy(*a, **kw):
                calls.append(1)
                return real_pass(*a, **kw)

            kp.knn_pallas_pass = spy
            fresh = jax.jit(
                functools.partial(kk.ring_knn.__wrapped__, mesh=mesh, k=k)
            )
            d_pal, i_pal = jax.tree.map(np.asarray, fresh(Xq, Xi, mi, ids))
        finally:
            kp.FORCE_INTERPRET = False
            kp.knn_pallas_pass = real_pass
        assert calls, "fused Pallas kNN pass was not traced"

        np.testing.assert_allclose(d_pal, d_ref, rtol=1e-5, atol=1e-5)
        # ids may differ only where distances tie; none expected here
        np.testing.assert_array_equal(i_pal, i_ref)
        # masked items never appear
        masked_ids = set(np.asarray(ids[-7:]).tolist())
        assert not (set(i_pal.ravel().tolist()) & masked_ids)

    def test_sort_impl_routes_around_fused_kernel(self):
        """TPUML_KNN_TOPK=sort is the validated escape hatch: it must
        bypass the fused Pallas pass entirely, not just the tile top-k."""
        import functools

        import spark_rapids_ml_tpu.ops.knn_kernels as kk
        import spark_rapids_ml_tpu.ops.knn_pallas as kp
        from spark_rapids_ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
        rng = np.random.default_rng(9)
        nq, ni, d, k = 64, 256, 128, 4
        Xq = jnp.asarray(rng.standard_normal((nq, d)), jnp.float32)
        Xi = jnp.asarray(rng.standard_normal((ni, d)), jnp.float32)
        mi = jnp.ones((ni,), jnp.float32)
        ids = jnp.arange(ni, dtype=jnp.int32)

        kp.FORCE_INTERPRET = True  # pallas gate would otherwise pass
        calls = []
        real_pass = kp.knn_pallas_pass
        try:
            kp.knn_pallas_pass = lambda *a, **kw: calls.append(1) or real_pass(
                *a, **kw
            )
            fresh = jax.jit(
                functools.partial(
                    kk.ring_knn.__wrapped__, mesh=mesh, k=k, topk_impl="sort"
                )
            )
            d_s, i_s = jax.tree.map(np.asarray, fresh(Xq, Xi, mi, ids))
        finally:
            kp.FORCE_INTERPRET = False
            kp.knn_pallas_pass = real_pass
        assert not calls, "sort impl must not trace the fused Pallas pass"
        # and it still returns correct neighbors
        d2 = ((np.asarray(Xq)[:, None, :] - np.asarray(Xi)[None, :, :]) ** 2).sum(-1)
        oracle = np.sort(d2, axis=1)[:, :k]
        np.testing.assert_allclose(np.asarray(d_s), oracle, rtol=1e-4, atol=1e-4)
