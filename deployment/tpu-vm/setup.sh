#!/usr/bin/env bash
# Install the framework on every worker of the TPU VM — the analog of the
# reference's per-CSP init scripts (dataproc/init_benchmark.sh,
# databricks/init-pip-cuda-11.8.sh), which pip-install spark-rapids-ml
# and its RAPIDS stack on each executor node.
#
# Required env: PROJECT, ZONE, TPU_NAME (as in start_cluster.sh)
# Optional:    REPO_URL (git remote to clone; defaults to rsyncing the
#              local checkout), JAX_VERSION pin.
set -euo pipefail

: "${PROJECT:?set PROJECT}"
: "${ZONE:?set ZONE}"
: "${TPU_NAME:?set TPU_NAME}"
REPO_DIR="$(cd "$(dirname "$0")/../.." && pwd)"

run_all() {
  gcloud compute tpus tpu-vm ssh "${TPU_NAME}" \
    --project="${PROJECT}" --zone="${ZONE}" --worker=all --command="$1"
}

if [ -n "${REPO_URL:-}" ]; then
  run_all "rm -rf ~/spark-rapids-ml-tpu && git clone ${REPO_URL} ~/spark-rapids-ml-tpu"
else
  # ship the local checkout (scp to every worker). Remove any previous
  # copy first: scp into an EXISTING directory nests the new tree inside
  # it and pip would silently reinstall the stale code.
  run_all "rm -rf ~/spark-rapids-ml-tpu"
  gcloud compute tpus tpu-vm scp --recurse "${REPO_DIR}" \
    "${TPU_NAME}":~/spark-rapids-ml-tpu \
    --project="${PROJECT}" --zone="${ZONE}" --worker=all
fi

JAX_SPEC="jax[tpu]${JAX_VERSION:+==${JAX_VERSION}}"
run_all "pip install -q '${JAX_SPEC}' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html"
run_all "cd ~/spark-rapids-ml-tpu && pip install -q -e . && python -c 'import jax; print(jax.devices())'"
echo "Setup complete on all workers."
