"""Benchmark entry point (reference
``/root/reference/python/benchmark/benchmark_runner.py``), same CLI shape:

    python benchmark_runner.py <algorithm> [--platform cpu|tpu]
        [--mode tpu|cpu] [--num_chips N]
        [--num_rows N --num_cols D | --train_path dir] [algo flags...]

Supported algorithms: kmeans, knn, linear_regression, pca,
random_forest_classifier, random_forest_regressor, logistic_regression, umap.

``--platform`` (or a ``JAX_PLATFORMS`` env var, honored in-process) pins the
jax backend BEFORE any backend touch — required because a TPU-plugin
sitecustomize hook ignores the env var and the first backend touch would
otherwise block on TPU client setup (see
``spark_rapids_ml_tpu/utils/platform.py``).
"""

import sys


def _pop_platform_flag(argv):
    """Extract --platform[=| ]VALUE from argv; returns (value_or_None, rest)."""
    rest = []
    value = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--platform":
            if i + 1 >= len(argv):
                sys.exit("--platform requires a value (cpu|tpu)")
            value = argv[i + 1]
            i += 2
            continue
        if a.startswith("--platform="):
            value = a.split("=", 1)[1]
            i += 1
            continue
        rest.append(a)
        i += 1
    return value, rest


def main() -> None:
    argv = sys.argv[1:]
    platform, argv = _pop_platform_flag(argv)

    # Pin before importing the bench modules (they import jax-using code).
    from spark_rapids_ml_tpu.utils.platform import pin_platform

    pin_platform(platform)

    from benchmark.bench_kmeans import BenchmarkKMeans
    from benchmark.bench_linear_regression import BenchmarkLinearRegression
    from benchmark.bench_logistic_regression import BenchmarkLogisticRegression
    from benchmark.bench_nearest_neighbors import BenchmarkNearestNeighbors
    from benchmark.bench_pca import BenchmarkPCA
    from benchmark.bench_random_forest import (
        BenchmarkRandomForestClassifier,
        BenchmarkRandomForestRegressor,
    )
    from benchmark.bench_umap import BenchmarkUMAP

    registered = {
        "kmeans": BenchmarkKMeans,
        "knn": BenchmarkNearestNeighbors,
        "linear_regression": BenchmarkLinearRegression,
        "pca": BenchmarkPCA,
        "random_forest_classifier": BenchmarkRandomForestClassifier,
        "random_forest_regressor": BenchmarkRandomForestRegressor,
        "logistic_regression": BenchmarkLogisticRegression,
        "umap": BenchmarkUMAP,
    }

    if not argv or argv[0] in ("-h", "--help") or argv[0] not in registered:
        names = "\n    ".join(sorted(registered))
        print(f"usage: benchmark_runner.py <algorithm> [<args>]\n\nalgorithms:\n    {names}")
        sys.exit(0 if argv and argv[0] in ("-h", "--help") else 1)
    registered[argv[0]](argv[1:]).run()


if __name__ == "__main__":
    main()
