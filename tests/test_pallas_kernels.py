"""Pallas kernel correctness (interpret mode on the CPU mesh).

The real kernels run only on TPU (`_pallas_gram_ok` gates on backend); these
tests run the same kernel bodies through the Pallas interpreter against
numpy oracles, including the last-partial-tile index-validity guard.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.linalg import _shifted_gram_pallas


@pytest.mark.parametrize("n,tile", [(512, 128), (700, 128), (100, 256)])
def test_shifted_gram_pallas_matches_numpy(n, tile):
    d = 256
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32) + 2.0
    mask = (rng.random(n) > 0.1).astype(np.float32)
    mu = X[:64].mean(axis=0)

    G, s = _shifted_gram_pallas(
        jnp.asarray(X), jnp.asarray(mask), jnp.asarray(mu),
        tile=tile, interpret=True,
    )

    xs = (X.astype(np.float64) - mu.astype(np.float64)) * mask[:, None]
    G_ref = xs.T @ xs
    s_ref = xs.sum(axis=0)
    scale = np.abs(G_ref).max()
    assert np.abs(np.asarray(G, np.float64) - G_ref).max() / scale < 1e-5
    assert np.abs(np.asarray(s, np.float64) - s_ref).max() < 1e-2


def test_shifted_gram_pallas_all_masked_tail():
    # padding suffix fully masked: the guard and the mask must compose
    d, n, tile = 256, 384, 128
    rng = np.random.default_rng(1)
    X = rng.normal(size=(n, d)).astype(np.float32)
    X[300:] = 1e30  # padded rows may hold (finite) garbage — must not leak
    mask = (np.arange(n) < 300).astype(np.float32)
    mu = X[:64].mean(axis=0)

    G, s = _shifted_gram_pallas(
        jnp.asarray(X), jnp.asarray(mask), jnp.asarray(mu),
        tile=tile, interpret=True,
    )
    assert np.isfinite(np.asarray(G)).all()
    xs = (X[:300].astype(np.float64) - mu.astype(np.float64))
    G_ref = xs.T @ xs
    assert np.abs(np.asarray(G, np.float64) - G_ref).max() / np.abs(G_ref).max() < 1e-5
