"""spark_rapids_ml_tpu — a TPU-native distributed classic-ML framework.

Same capability surface as spark-rapids-ml (PCA, KMeans, Linear/Logistic
Regression, RandomForest, exact kNN, UMAP, single-pass CrossValidator),
re-designed for TPU: JAX/XLA global-math kernels over ``jax.sharding.Mesh``
device meshes replace cuML/NCCL/UCX; a lightweight partitioned
``DataFrame`` replaces the Spark data plane.

Drop-in import layout mirrors the reference package::

    from spark_rapids_ml_tpu.feature import PCA
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.regression import LinearRegression
    from spark_rapids_ml_tpu.classification import LogisticRegression
"""

__version__ = "0.1.0"

# Multi-process bootstrap must precede ANY backend touch
# (jax.distributed.initialize refuses after the first jax.devices()/array
# op). Env-gated no-op outside a launcher-provided multi-process world.
from .parallel.context import ensure_distributed as _ensure_distributed

_ensure_distributed()

from .data.dataframe import DataFrame, Row

__all__ = ["DataFrame", "Row", "__version__"]
