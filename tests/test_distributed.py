"""Multi-process distributed path tests.

The reference treats communicator bootstrap as a first-class tested layer
(``/root/reference/python/src/spark_rapids_ml/common/cuml_context.py:35-147``,
tested by ``python/tests/test_ucx.py:35-99``). The TPU-native analog —
``TpuDistContext`` / ``jax.distributed`` + a global device mesh — gets the
same treatment: a REAL 2-process world (subprocesses with gloo CPU
collectives), each process holding its own data partition, asserting the
distributed fit matches the single-process fit bit-for-bit at f32 tolerance.

The multi-process tests require a jaxlib whose CPU backend implements
multiprocess computations (some builds raise ``INVALID_ARGUMENT:
Multiprocess computations aren't implemented on the CPU backend`` from
the very first ``process_allgather``). That is an environment property,
not a code property, so the tests gate on an explicit capability probe
— a 2-process ``jax.distributed.initialize`` + ``process_allgather``
round-trip in subprocesses — and skip with the probe's failure as the
reason when the build can't do it.
"""

import os
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_DIST_PROBE = textwrap.dedent(
    """
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    jax.distributed.initialize(
        coordinator_address=os.environ["PROBE_COORD"],
        num_processes=2,
        process_id=int(os.environ["PROBE_ID"]),
    )
    from jax.experimental import multihost_utils
    out = multihost_utils.process_allgather(
        np.array([1 + int(os.environ["PROBE_ID"])], np.int32)
    )
    assert int(out.sum()) == 3, out
    print("DIST_PROBE_OK", flush=True)
    """
)

# None = not probed yet; "" = capable; anything else = the skip reason
_DIST_PROBE_RESULT = None


def _probe_two_process_cpu_world() -> str:
    """Run the minimal primitive every test here depends on: a real
    2-process gloo world doing one allgather on the CPU backend."""
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "dist_probe.py")
        with open(script, "w") as fh:
            fh.write(_DIST_PROBE)
        coord = f"127.0.0.1:{_free_port()}"
        procs = []
        for pid in range(2):
            env = dict(os.environ)
            env.update(
                PROBE_COORD=coord, PROBE_ID=str(pid), JAX_PLATFORMS="cpu"
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, script], env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        outs = []
        for p in procs:
            try:
                stdout, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                return "2-process jax.distributed CPU probe timed out"
            outs.append(stdout)
    if all(p.returncode == 0 for p in procs):
        return ""
    bad = next(o for p, o in zip(procs, outs) if p.returncode != 0)
    lines = [ln for ln in bad.strip().splitlines() if ln]
    return (
        "this jaxlib cannot run a 2-process CPU world: "
        + (lines[-1] if lines else "probe produced no output")
    )


def _require_two_process_cpu_world() -> None:
    """Skip (with the probe's diagnosis) unless a real multi-process
    CPU world works here. Probed once per session, cached."""
    global _DIST_PROBE_RESULT
    if _DIST_PROBE_RESULT is None:
        _DIST_PROBE_RESULT = _probe_two_process_cpu_world()
    if _DIST_PROBE_RESULT:
        pytest.skip(_DIST_PROBE_RESULT)

_WORKER = textwrap.dedent(
    """
    import os, sys
    import numpy as np

    # pin CPU before any backend touch (axon sitecustomize ignores env vars)
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, {repo!r})
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.feature import PCA
    from spark_rapids_ml_tpu.classification import LogisticRegression
    from spark_rapids_ml_tpu.clustering import KMeans

    from spark_rapids_ml_tpu.runtime import envspec
    pid = int(envspec.get("TPUML_PROC_ID"))

    # deterministic dataset; each process holds ITS partition only
    # (uneven split: exercises the cross-process shard agreement)
    rng = np.random.default_rng(42)
    X = rng.normal(size=(237, 9)).astype(np.float32) + 3.0
    y = (X @ rng.normal(size=(9,)) > 27.0).astype(np.float32)
    half = 150  # process 0: 150 rows, process 1: 87 rows
    sl = slice(0, half) if pid == 0 else slice(half, None)
    df = DataFrame({{"features": X[sl], "label": y[sl]}})

    # fit spans both processes (4 global devices); mesh bootstrap happens
    # inside make_mesh via ensure_distributed()
    m = PCA(k=3, num_workers=4).fit(df)
    lr = LogisticRegression(num_workers=4, regParam=0.01).fit(df)
    km = KMeans(k=4, seed=3, num_workers=4, maxIter=30).fit(df)

    # class 2 exists ONLY in process 1's partition: n_classes must still
    # resolve globally to 3 on every rank (local label stats would compile
    # mismatched collectives and deadlock)
    y3 = np.zeros(len(X), np.float32)
    y3[100:150] = 1.0
    y3[180:] = 2.0
    lr3 = LogisticRegression(num_workers=4, regParam=0.01).fit(
        DataFrame({{"features": X[sl], "label": y3[sl]}})
    )
    assert lr3.numClasses == 3, lr3.numClasses

    # RF: trees are sharded across the global device mesh; the model must
    # be identical to the single-process fit (same global layout + seeds)
    from spark_rapids_ml_tpu.classification import RandomForestClassifier
    rf = RandomForestClassifier(numTrees=8, maxDepth=4, seed=5, num_workers=4).fit(df)

    # UMAP: single-node fit gathers every process's partition, so all
    # ranks embed the FULL dataset identically
    from spark_rapids_ml_tpu.umap import UMAP
    um = UMAP(n_neighbors=8, random_state=1, init="random").fit(df)
    assert um.raw_data_.shape[0] == len(X), um.raw_data_.shape

    if pid == 0:
        np.savez(
            os.environ["SRMT_TEST_OUT"],
            components=m.components_,
            mean=m.mean_,
            ev=m.explained_variance_,
            coef=lr.coefficientMatrix,
            intercept=lr.interceptVector,
            centers=np.asarray(sorted(km.clusterCenters(), key=lambda c: tuple(c))),
            km_cost=km.trainingCost,
            coef3=lr3.coefficientMatrix,
            rf_features=rf._features_arr,
            rf_thresholds=rf._thresholds_arr,
            umap_emb=um.embedding_,
        )
    """
)


@pytest.mark.slow
def test_two_process_fit_matches_single_process(tmp_path):
    _require_two_process_cpu_world()
    out = str(tmp_path / "result.npz")
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=REPO))

    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            TPUML_COORDINATOR=coord,
            TPUML_NUM_PROCS="2",
            TPUML_PROC_ID=str(pid),
            SRMT_TEST_OUT=out,
            JAX_PLATFORMS="cpu",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(stdout)
    for p, stdout in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{stdout[-3000:]}"

    res = np.load(out)

    # single-process oracle on the full dataset
    rng = np.random.default_rng(42)
    X = rng.normal(size=(237, 9)).astype(np.float32) + 3.0
    y = (X @ rng.normal(size=(9,)) > 27.0).astype(np.float32)
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.classification import LogisticRegression
    from spark_rapids_ml_tpu.feature import PCA

    from spark_rapids_ml_tpu.clustering import KMeans

    df = DataFrame({"features": X, "label": y})
    m = PCA(k=3, num_workers=4).fit(df)
    lr = LogisticRegression(num_workers=4, regParam=0.01).fit(df)
    km = KMeans(k=4, seed=3, num_workers=4, maxIter=30).fit(df)

    np.testing.assert_allclose(res["mean"], m.mean_, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        res["components"], m.components_, rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        res["ev"], m.explained_variance_, rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        res["coef"], lr.coefficientMatrix, rtol=5e-3, atol=5e-4
    )
    np.testing.assert_allclose(
        res["intercept"], lr.interceptVector, rtol=5e-3, atol=5e-4
    )
    # same-seed k-means||: sampling depends only on global logical rows, so
    # the 2-process and 1-process fits converge to the same optimum
    np.testing.assert_allclose(float(res["km_cost"]), km.trainingCost, rtol=1e-2)

    y3 = np.zeros(len(X), np.float32)
    y3[100:150] = 1.0
    y3[180:] = 2.0
    lr3 = LogisticRegression(num_workers=4, regParam=0.01).fit(
        DataFrame({"features": X, "label": y3})
    )
    np.testing.assert_allclose(
        res["coef3"], lr3.coefficientMatrix, rtol=5e-3, atol=5e-4
    )

    from spark_rapids_ml_tpu.classification import RandomForestClassifier
    from spark_rapids_ml_tpu.umap import UMAP

    rf = RandomForestClassifier(numTrees=8, maxDepth=4, seed=5, num_workers=4).fit(df)
    np.testing.assert_array_equal(res["rf_features"], rf._features_arr)
    np.testing.assert_allclose(res["rf_thresholds"], rf._thresholds_arr, rtol=1e-5)

    um = UMAP(n_neighbors=8, random_state=1, init="random").fit(df)
    np.testing.assert_allclose(res["umap_emb"], um.embedding_, rtol=1e-4, atol=1e-4)


def test_dist_context_noop_single_process():
    """Without launcher env, the context is a no-op and exceptions pass
    through (no distributed runtime to abort)."""
    from spark_rapids_ml_tpu.parallel import TpuDistContext

    with TpuDistContext() as ctx:
        assert ctx.rank == 0 and ctx.nranks == 1
    with pytest.raises(ValueError, match="boom"):
        with TpuDistContext():
            raise ValueError("boom")


def test_distributed_env_detection(monkeypatch):
    from spark_rapids_ml_tpu.parallel import distributed_env_configured

    assert distributed_env_configured() is False
    monkeypatch.setenv("TPUML_COORDINATOR", "127.0.0.1:9")
    monkeypatch.setenv("TPUML_NUM_PROCS", "2")
    assert distributed_env_configured() is True
    monkeypatch.setenv("TPUML_NUM_PROCS", "1")
    assert distributed_env_configured() is False


_KNN_WORKER = textwrap.dedent(
    """
    import os, sys
    import numpy as np

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.knn import NearestNeighbors

    from spark_rapids_ml_tpu.runtime import envspec
    pid = int(envspec.get("TPUML_PROC_ID"))
    rng = np.random.default_rng(11)
    Xi = rng.normal(size=(157, 6)).astype(np.float32)
    Xq = rng.normal(size=(63, 6)).astype(np.float32)
    isl = slice(0, 90) if pid == 0 else slice(90, None)
    qsl = slice(0, 40) if pid == 0 else slice(40, None)
    m = NearestNeighbors(k=4, num_workers=4).fit(DataFrame({{"features": Xi[isl]}}))
    _, _, knn_df = m.kneighbors(DataFrame({{"features": Xq[qsl]}}))
    idxs = np.asarray(knn_df.column("indices"))
    dists = np.asarray(knn_df.column("distances"))

    # oracle: brute force over the FULL item set for this rank's queries;
    # auto-generated ids are globally offset, so they equal positions in Xi
    qs = Xq[qsl]
    d2 = ((qs[:, None, :] - Xi[None, :, :]) ** 2).sum(-1)
    exp_idx = np.argsort(d2, axis=1)[:, :4]
    exp_d = np.sqrt(np.take_along_axis(d2, exp_idx, 1))
    assert np.allclose(np.sort(dists, 1), np.sort(exp_d, 1), atol=1e-4)
    assert (np.sort(idxs, 1) == np.sort(exp_idx, 1)).all()

    # exactNearestNeighborsJoin: every joined pair's distance must equal
    # the true pair distance even when the item row lives on the other rank
    out = m.exactNearestNeighborsJoin(DataFrame({{"features": Xq[qsl]}}), distCol="d")
    dj = np.asarray(out.column("d"))
    qf = np.asarray(out.column("query_features"))
    itf = np.asarray(out.column("item_features"))
    assert np.allclose(dj, np.sqrt(((qf - itf) ** 2).sum(1)), atol=1e-4)

    # string ids: the cross-process id exchange and the (index-selective)
    # join must carry str ids byte-exactly; ids of differing widths across
    # ranks exercise the global width agreement
    # rank 1's ids are wider: exercises the global width agreement
    all_sids = np.array(
        ["it_%03d" % i if i < 90 else "it_%03d_r1" % i for i in range(len(Xi))],
        dtype=object,
    )
    qids = np.array(["q_%02d" % i for i in range(len(Xq))], dtype=object)
    m2 = NearestNeighbors(k=3, num_workers=4, idCol="sid").fit(
        DataFrame({{"features": Xi[isl], "sid": all_sids[isl]}})
    )
    _, _, knn2 = m2.kneighbors(
        DataFrame({{"features": Xq[qsl], "sid": qids[qsl]}})
    )
    idx2 = np.asarray(knn2.column("indices"))
    assert idx2.dtype.kind == "U", idx2.dtype
    exp3 = np.argsort(d2, axis=1)[:, :3]
    assert (np.sort(idx2, 1) == np.sort(all_sids[exp3].astype(idx2.dtype), 1)).all()

    out2 = m2.exactNearestNeighborsJoin(
        DataFrame({{"features": Xq[qsl], "sid": qids[qsl]}}), distCol="d"
    )
    dj2 = np.asarray(out2.column("d"))
    qf2 = np.asarray(out2.column("query_features"))
    itf2 = np.asarray(out2.column("item_features"))
    assert np.allclose(dj2, np.sqrt(((qf2 - itf2) ** 2).sum(1)), atol=1e-4)
    assert np.asarray(out2.column("item_sid")).dtype.kind == "U"
    print(f"rank {{pid}} ok", flush=True)
    """
)


@pytest.mark.slow
def test_two_process_knn_exact(tmp_path):
    """Cross-process kNN: each rank owns item and query partitions; results
    must match a full-dataset brute-force oracle (the reference's UCX
    partition exchange contract, ``knn.py:377-379``)."""
    _require_two_process_cpu_world()
    script = tmp_path / "knn_worker.py"
    script.write_text(_KNN_WORKER.format(repo=REPO))
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            TPUML_COORDINATOR=coord,
            TPUML_NUM_PROCS="2",
            TPUML_PROC_ID=str(pid),
            JAX_PLATFORMS="cpu",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"knn worker failed:\n{stdout[-3000:]}"


_STREAM_WORKER = textwrap.dedent(
    """
    import os, sys
    import numpy as np

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.feature import PCA
    from spark_rapids_ml_tpu.regression import LinearRegression
    from spark_rapids_ml_tpu.classification import LogisticRegression
    from spark_rapids_ml_tpu.clustering import KMeans

    from spark_rapids_ml_tpu.runtime import envspec
    pid = int(envspec.get("TPUML_PROC_ID"))
    rng = np.random.default_rng(42)
    X = (rng.normal(size=(357, 7)) + 2.0).astype(np.float32)
    w = rng.normal(size=(7,))
    yr = (X @ w + 0.5).astype(np.float32)
    yc = (X @ w > 14.0).astype(np.float32)
    sl = slice(0, 200) if pid == 0 else slice(200, None)

    kw = dict(streaming=True, stream_chunk_rows=64)
    pca = PCA(k=3, **kw).fit(DataFrame({{"features": X[sl]}}))
    lin = LinearRegression(regParam=0.01, **kw).fit(
        DataFrame({{"features": X[sl], "label": yr[sl]}}))
    log = LogisticRegression(regParam=0.01, **kw).fit(
        DataFrame({{"features": X[sl], "label": yc[sl]}}))
    km = KMeans(k=3, seed=5, maxIter=25, **kw).fit(DataFrame({{"features": X[sl]}}))
    if pid == 0:
        np.savez(
            os.environ["SRMT_TEST_OUT"],
            pca=np.asarray(pca.components_),
            lin=np.asarray(lin.coefficients),
            log=np.asarray(log.coefficientMatrix),
            km_cost=km.trainingCost,
        )
    """
)


@pytest.mark.slow
def test_two_process_streaming_matches_single_process(tmp_path):
    """Out-of-core fits across processes: each rank streams ITS partition
    through its own chips; sufficient-statistic partials allreduce — the
    reference's per-worker Arrow stream + NCCL allreduce architecture."""
    _require_two_process_cpu_world()
    out = str(tmp_path / "stream.npz")
    script = tmp_path / "stream_worker.py"
    script.write_text(_STREAM_WORKER.format(repo=REPO))
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            TPUML_COORDINATOR=coord,
            TPUML_NUM_PROCS="2",
            TPUML_PROC_ID=str(pid),
            SRMT_TEST_OUT=out,
            JAX_PLATFORMS="cpu",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"stream worker failed:\n{stdout[-3000:]}"

    res = np.load(out)
    rng = np.random.default_rng(42)
    X = (rng.normal(size=(357, 7)) + 2.0).astype(np.float32)
    w = rng.normal(size=(7,))
    yr = (X @ w + 0.5).astype(np.float32)
    yc = (X @ w > 14.0).astype(np.float32)
    from spark_rapids_ml_tpu.classification import LogisticRegression
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.data import DataFrame
    from spark_rapids_ml_tpu.feature import PCA
    from spark_rapids_ml_tpu.regression import LinearRegression

    kw = dict(streaming=True, stream_chunk_rows=64)
    pca = PCA(k=3, **kw).fit(DataFrame({"features": X}))
    lin = LinearRegression(regParam=0.01, **kw).fit(
        DataFrame({"features": X, "label": yr}))
    log = LogisticRegression(regParam=0.01, **kw).fit(
        DataFrame({"features": X, "label": yc}))
    km = KMeans(k=3, seed=5, maxIter=25, **kw).fit(DataFrame({"features": X}))

    np.testing.assert_allclose(res["pca"], np.asarray(pca.components_), atol=2e-4)
    np.testing.assert_allclose(
        res["lin"], np.asarray(lin.coefficients), rtol=5e-3, atol=5e-4
    )
    np.testing.assert_allclose(
        res["log"], np.asarray(log.coefficientMatrix), rtol=2e-2, atol=2e-3
    )
    np.testing.assert_allclose(float(res["km_cost"]), km.trainingCost, rtol=2e-2)


@pytest.mark.slow
def test_multihost_benchmark_launcher():
    """The cluster-submission analog (reference databricks/run_benchmark.sh):
    N processes, same command line, joined via the TPUML_* bootstrap."""
    _require_two_process_cpu_world()
    r = subprocess.run(
        [os.path.join(REPO, "run_benchmark_multihost.sh"), "2", "cpu", "3000", "16"],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "EXTRA_ALGOS": "pca"},
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "multihost benchmark OK" in r.stdout
