"""Fused Pallas distance + exact top-k pass for the kNN ring.

The XLA ring step materializes every (qc, ic) distance tile in HBM and
runs ``lax.top_k`` over it; measured on v5e at the bench shape the tile
matmul+epilogue costs ~9 ms and the top_k read adds ~21 ms at an effective
51 GB/s — the selection, not the math, dominates (12 s of a 13.3 s
kneighbors call). This kernel keeps the whole tile VMEM-resident and
replaces the sort with a tau-gated extraction loop:

* score = ||xi||^2 - 2 xq.xi (the row-constant ||xq||^2 cannot change a
  row's ordering; it is added back once, outside, like the Lloyd kernel);
  masked/padded items ride in with score +inf via their ||xi||^2;
* a ``lax.while_loop`` extracts the block's best candidate and inserts it
  into the running (k)-slot state, repeating only while some row still has
  a candidate better than its current k-th best (tau). Once tau tightens
  (a few ring blocks in), most blocks run ZERO iterations — the loop
  condition is the only full-tile read, and it fuses with the matmul.
* Exactness: each iteration inserts the globally best remaining candidate
  of the block; k iterations bound the loop because a block's (k+1)-th
  best can never enter the top-k alongside its k better neighbours.
  Verified on-chip bit-for-bit against ``lax.top_k`` (ids and distances).

Reference role: replaces the fused distance+select kernels cuML's
``NearestNeighborsMG.kneighbors`` runs per partition pair
(``/root/reference/python/src/spark_rapids_ml/knn.py:553-564``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ._compat import pallas_tpu_compiler_params
from jax import lax

# Test hook (mirrors ops.kmeans_pallas.FORCE_INTERPRET).
FORCE_INTERPRET = False

# Block sizes trade grid overhead + item-matrix re-reads against VMEM:
# the item shard is swept once per QUERY block, so HBM traffic scales as
# (nq/_QB) * ni * d * 4 — at the bench shape (131k x 1M x 256) the
# original 256-row query blocks cost 512 GB of Xi re-reads (and a ~1M
# step grid); 2048-row blocks cut that to 64 GB / 62k steps. The
# (QB, IB) f32 score tile and its while-carry copies stay ~8 MB each,
# well inside the 100 MB budget.
_QB = 2048  # query rows per block
_IB = 1024  # item cols per block


# Hardware-lowering probe results per (d, k); the probe policy lives in
# ops.linalg.probe_pallas_lowering.
_LOWERING_OK: dict = {}


def _probe_lowering(d: int, k: int) -> bool:
    from .linalg import probe_pallas_lowering

    def compile_fn():
        args = (
            jax.ShapeDtypeStruct((_QB, d), jnp.float32),
            jax.ShapeDtypeStruct((_IB, d), jnp.float32),
            jax.ShapeDtypeStruct((1, _IB), jnp.float32),
            jax.ShapeDtypeStruct((1, _IB), jnp.int32),
            jax.ShapeDtypeStruct((_QB, k), jnp.float32),
            jax.ShapeDtypeStruct((_QB, k), jnp.int32),
        )
        knn_pallas_pass.lower(*args).compile()

    return probe_pallas_lowering(_LOWERING_OK, (d, k), compile_fn, "fused kNN")


def knn_pallas_ok(nq: int, ni: int, d: int, k: int, dtype) -> bool:
    """Trace-time gate: TPU, f32, lane-aligned d, block-aligned shapes,
    and k small enough that the (QB, k) state stays trivial."""
    ok = (
        (jax.default_backend() == "tpu" or FORCE_INTERPRET)
        and dtype == jnp.float32
        and d % 128 == 0
        and nq % _QB == 0
        and ni % _IB == 0
        and 1 <= k <= 128
    )
    if ok and not FORCE_INTERPRET:
        ok = _probe_lowering(d, k)
    return ok


@functools.partial(jax.jit, static_argnames=("interpret",))
def knn_pallas_pass(
    Xq: jax.Array,       # (nq, d) f32
    Xi: jax.Array,       # (ni, d) f32 — current ring shard
    csq_eff: jax.Array,  # (1, ni) f32: ||xi||^2, +inf for masked items
    ids: jax.Array,      # (1, ni) int32 global item ids
    topd: jax.Array,     # (nq, k) f32 running scores (NO ||xq||^2 term)
    topi: jax.Array,     # (nq, k) int32 running global ids
    *,
    interpret: bool | None = None,
):
    """One full (nq x ni) pass folding every item of the shard into the
    running top-k state. Returns (topd, topi) updated."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = FORCE_INTERPRET
    nq, d = Xq.shape
    ni = Xi.shape[0]
    k = topd.shape[1]

    def kern(xq_ref, xi_ref, csq_ref, ids_ref, td_in, ti_in, td_ref, ti_ref):
        ii = pl.program_id(1)

        @pl.when(ii == 0)
        def _():
            td_ref[:] = td_in[:]
            ti_ref[:] = ti_in[:]

        xq = xq_ref[:]                    # (QB, d)
        xi = xi_ref[:]                    # (IB, d)
        xc = jax.lax.dot_general(
            xq, xi, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                 # (QB, IB)
        score0 = csq_ref[:] - 2.0 * xc    # (1, IB) broadcasts; +inf = masked
        lane_k = jax.lax.broadcasted_iota(jnp.int32, (_QB, k), 1)
        lane_ib = jax.lax.broadcasted_iota(jnp.int32, (_QB, _IB), 1)
        ids_b = ids_ref[:]                # (1, IB)

        def cond(carry):
            j, score, td, ti = carry
            tau = jnp.max(td, axis=1, keepdims=True)
            m = jnp.min(score, axis=1, keepdims=True)
            return jnp.logical_and(j < k, jnp.any(m < tau))

        def body(carry):
            j, score, td, ti = carry
            tau = jnp.max(td, axis=1, keepdims=True)
            m = jnp.min(score, axis=1, keepdims=True)        # (QB, 1)
            am = jnp.argmin(score, axis=1, keepdims=True)    # first-min lane
            firstm = (lane_ib == am) & (m < tau)             # (QB, IB)
            sel = jnp.sum(
                jnp.where(firstm, jnp.broadcast_to(ids_b, firstm.shape), 0),
                axis=1, keepdims=True,
            )                                                # (QB, 1)
            worst = jnp.argmax(td, axis=1, keepdims=True)
            repl = (lane_k == worst) & (m < tau)
            td = jnp.where(repl, jnp.broadcast_to(m, td.shape), td)
            ti = jnp.where(repl, jnp.broadcast_to(sel, ti.shape), ti)
            score = jnp.where(firstm, jnp.inf, score)
            return (j + 1, score, td, ti)

        _, _, td, ti = lax.while_loop(
            cond, body, (jnp.int32(0), score0, td_ref[:], ti_ref[:])
        )
        td_ref[:] = td
        ti_ref[:] = ti

    return pl.pallas_call(
        kern,
        grid=(nq // _QB, ni // _IB),
        in_specs=[
            pl.BlockSpec((_QB, d), lambda qi, ii: (qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_IB, d), lambda qi, ii: (ii, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _IB), lambda qi, ii: (0, ii),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _IB), lambda qi, ii: (0, ii),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_QB, k), lambda qi, ii: (qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_QB, k), lambda qi, ii: (qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_QB, k), lambda qi, ii: (qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_QB, k), lambda qi, ii: (qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nq, k), jnp.float32),
            jax.ShapeDtypeStruct((nq, k), jnp.int32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )(Xq, Xi, csq_eff, ids, topd, topi)
