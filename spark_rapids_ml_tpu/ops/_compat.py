"""Version shims for the narrow band of jax APIs whose spelling moved
between the 0.4.x series and current jax.

Kernels are written against the modern surface (``jax.shard_map`` with
``check_vma``, ``pltpu.CompilerParams``); this module backfills those
names on 0.4.x so the library imports and runs on either series without
scattering try/except through every ops module.
"""

from __future__ import annotations

import functools

try:  # jax >= 0.6: public top-level export, `check_vma` kwarg
    from jax import shard_map as shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x: experimental module, `check_rep` kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_04

    @functools.wraps(_shard_map_04)
    def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        if f is None:  # partial-application form: shard_map(mesh=..., ...)(f)
            return lambda g: shard_map(
                g, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma, **kw,
            )
        return _shard_map_04(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kw,
        )


def pallas_tpu_compiler_params(pltpu_module, **kwargs):
    """Build the TPU compiler-params struct under either spelling
    (``CompilerParams`` today, ``TPUCompilerParams`` on 0.4.x)."""
    cls = getattr(pltpu_module, "CompilerParams", None)
    if cls is None:
        cls = pltpu_module.TPUCompilerParams
    return cls(**kwargs)


def pallas_tpu_prng(pltpu_module):
    """``(prng_seed, prng_random_bits)`` for the on-chip TPU PRNG, or
    ``None`` when this jax build does not expose it — callers (the UMAP
    SGD engine) then stay on their XLA-stream randomness instead of
    scattering hasattr checks through kernel code."""
    seed = getattr(pltpu_module, "prng_seed", None)
    bits = getattr(pltpu_module, "prng_random_bits", None)
    if seed is None or bits is None:
        return None
    return seed, bits
