"""Benchmark entry point — prints ONE JSON line with the headline metric.

Covers the three BASELINE.md fit workloads (PCA, KMeans, LogisticRegression;
reference methodology ``/root/reference/python/benchmark/databricks/run_benchmark.sh:44-135``)
at the 256-feature width of the 100M x 256 north-star, measuring per-chip fit
throughput so the number scales linearly to pod size.  Also reports an MFU
estimate per algorithm (FLOP model / chip peak).

``vs_baseline`` compares against an A10G cuML roofline estimate derived from
the reference's benchmark hardware (BASELINE.md: 2x g5.2xlarge, A10G 24 GB):

* PCA — Gram-bound, 2*n*d^2 FLOPs; A10G sustains ~15 TFLOP/s effective fp32
  on SYRK-shaped work -> 15e12 / (2*256^2) ~= 1.1e8 samples/sec/GPU.
* KMeans — distance-bound, 2*n*k*d FLOPs/iter (k=1024) ->
  15e12 / (2*1024*256) ~= 2.9e7 sample-iters/sec/GPU.
* LogReg — bandwidth-bound (matvec-shaped): ~2 passes over X per L-BFGS
  iter at 600 GB/s A10G HBM -> 600e9 / (2*256*4) ~= 2.9e8
  sample-iters/sec/GPU.

Headline metric stays ``pca_fit_throughput`` (round-1 continuity); the same
JSON line carries ``kmeans``/``logreg`` sub-objects and per-algo MFU.

Robustness (round-1 postmortem): any algo failing with a transient
``UNAVAILABLE`` TPU backend error is retried once after a cooldown; partial
results still produce a JSON line; diagnostics go to stderr.
"""

import json
import math
import os
import sys
import time
import traceback

import numpy as np

# Honor an env/CLI platform pin in-process (sitecustomize TPU hooks ignore
# plain env vars) BEFORE the first backend touch.
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from spark_rapids_ml_tpu.utils.platform import pin_platform  # noqa: E402

_platform = None
for _i, _a in enumerate(sys.argv[1:], start=1):
    if _a == "--platform":
        if _i + 1 >= len(sys.argv):
            sys.exit("--platform requires a value (cpu|tpu)")
        _platform = sys.argv[_i + 1]
    elif _a.startswith("--platform="):
        _platform = _a.split("=", 1)[1]
pin_platform(_platform)

N_ROWS = int(os.environ.get("BENCH_ROWS", 4_000_000))
N_COLS = int(os.environ.get("BENCH_COLS", 256))
KMEANS_K = int(os.environ.get("BENCH_KMEANS_K", 1024))
KMEANS_ITERS = 10
LOGREG_ITERS = 20
def _csize(n_rows: int) -> int:
    return min(16384, max(256, n_rows // 8))


CSIZE = _csize(N_ROWS)

# bf16 peak FLOP/s per chip by device kind (MFU denominator).
_PEAK_BY_KIND = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]
_CPU_PEAK = 1e12  # nominal, keeps MFU finite on the CPU fallback


def _chip_peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, peak in _PEAK_BY_KIND:
        if key in kind:
            return peak
    return _CPU_PEAK


def _fetch(out) -> float:
    """Force full materialization on the host.

    ``block_until_ready`` alone is not trustworthy through a remote-tunnel
    backend (observed: identical executions "complete" in 0.1 ms, implying
    server-side memoization or lazy futures). Summing one leaf to a Python
    float forces the computation and a device->host round trip.
    """
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(out)
    acc = 0.0
    for leaf in leaves:
        acc += float(jnp.sum(jnp.asarray(leaf).astype(jnp.float32)))
    return acc


def _best_time(fn, reps: int = 3) -> float:
    """min-of-reps wall time of ``fn(rep_index)``.

    ``fn`` takes the rep index so callers can perturb inputs per rep —
    identical (executable, buffers) pairs may be memoized by a remote
    backend, which would report physically impossible times.
    """
    times = []
    for rep in range(reps):
        t0 = time.perf_counter()
        _fetch(fn(rep))
        times.append(time.perf_counter() - t0)
    return min(times)


def bench_pca(X, mask, mesh, n_chips):
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.models.feature import _pca_fit_kernel

    # per-rep mask perturbation -> distinct input buffers (see _best_time)
    t = _best_time(lambda rep: _pca_fit_kernel(X, mask * jnp.float32(1.0 + rep * 1e-6), 3))
    n = N_ROWS
    flops = 2.0 * n * N_COLS * N_COLS  # Gram dominates
    return {
        "samples_per_sec_per_chip": n / t / n_chips,
        "fit_seconds": t,
        "flops_model": flops,
        "baseline_samples_per_sec": 1.1e8,
    }


def bench_kmeans(X, mask, mesh, n_chips):
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.kmeans_kernels import kmeans_lloyd

    rng = np.random.default_rng(1)
    centers0 = jax.device_put(
        rng.standard_normal((KMEANS_K, N_COLS), dtype=np.float32)
    )
    csize = CSIZE

    def run(rep):
        return kmeans_lloyd(
            X, mask, centers0 + jnp.float32(rep * 1e-6), mesh=mesh, csize=csize,
            max_iter=KMEANS_ITERS, tol=0.0,
        )

    out = run(0)  # compile + read the actual iteration count
    iters = int(np.asarray(out[2])) + 1  # +1 final cost pass
    # rep+1: never reuse the warmup's inputs (memoizable on remote backends)
    t = _best_time(lambda rep: run(rep + 1))
    # FLOPs are spent on padded rows; throughput counts real samples only
    flops = 2.0 * X.shape[0] * KMEANS_K * N_COLS * iters
    n = N_ROWS
    return {
        "samples_per_sec_per_chip": n * iters / t / n_chips,
        "fit_seconds": t,
        "iters": iters,
        "flops_model": flops,
        "baseline_samples_per_sec": 2.9e7,
    }


def bench_logreg(X, mask, y, mesh, n_chips):
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.logreg_kernels import logreg_fit

    def run(rep):
        # rep-dependent l2 -> distinct scalar input buffer (see _best_time)
        return logreg_fit(
            X, mask, y,
            n_classes=2, multinomial=False, fit_intercept=True,
            standardization=False,
            l1=jnp.float32(0.0), l2=jnp.float32(1e-5 * (1.0 + rep * 1e-3)),
            use_l1=False, max_iter=LOGREG_ITERS, tol=jnp.float32(0.0),
        )

    out = run(0)  # compile + get n_iter
    iters = max(int(out["n_iter"]), 1)
    # rep+1: never reuse the warmup's inputs (memoizable on remote backends)
    t = _best_time(lambda rep: run(rep + 1))
    n = N_ROWS
    # ~2 objective evals/iter (step + line search), fwd+grad = 4*n*d each
    flops = 8.0 * n * N_COLS * iters
    return {
        "samples_per_sec_per_chip": n * iters / t / n_chips,
        "fit_seconds": t,
        "iters": iters,
        "flops_model": flops,
        "baseline_samples_per_sec": 2.9e8,
    }


def bench_pca_stream(mesh, n_chips):
    """Out-of-core PCA: chunks stream through a bounded device buffer
    (``ops/streaming.py``), the path that handles beyond-HBM datasets
    (BASELINE.md 100M x 256 north-star). Self-calibrates the row count so a
    slow host->device link cannot blow the wall-clock budget; the reported
    rate is per-pass ingest+accumulate throughput (2 passes per fit)."""
    import jax

    from spark_rapids_ml_tpu.data.chunks import GeneratorChunkSource
    from spark_rapids_ml_tpu.models.feature import _pca_from_cov
    from spark_rapids_ml_tpu.ops.streaming import streamed_suffstats

    d = N_COLS
    n_dp = mesh.shape["dp"]
    chunk_rows = int(os.environ.get("BENCH_STREAM_CHUNK", 1 << 18))
    chunk_rows = max(n_dp, (chunk_rows // n_dp) * n_dp)
    rng = np.random.default_rng(2)
    block = rng.standard_normal((chunk_rows, d), dtype=np.float32)

    def gen(start, count, seed):
        return block[:count], None

    def run(rows):
        src = GeneratorChunkSource(gen, rows, d)
        stats = streamed_suffstats(src, mesh, chunk_rows, np.float32, with_y=False)
        cov = stats["G"] / (stats["n"] - 1.0)
        out = _pca_from_cov(stats["mean_x"], cov, stats["n"], 3)
        _fetch(out)
        return out

    # calibrate: compile + measure a 4-chunk fit, then size the real run
    calib_rows = 4 * chunk_rows
    run(calib_rows)  # compile
    t0 = time.perf_counter()
    run(calib_rows)
    t_calib = time.perf_counter() - t0
    budget_s = float(os.environ.get("BENCH_STREAM_SECONDS", 60))
    max_rows = int(os.environ.get("BENCH_STREAM_ROWS", 16_000_000))
    rows = int(min(max_rows, calib_rows * max(1.0, budget_s / max(t_calib, 1e-9))))
    rows = max(chunk_rows, (rows // chunk_rows) * chunk_rows)

    t0 = time.perf_counter()
    run(rows)
    t = time.perf_counter() - t0
    flops = 2.0 * rows * d * d  # pass-2 Gram dominates
    return {
        "samples_per_sec_per_chip": rows / t / n_chips,
        "fit_seconds": t,
        "rows": rows,
        "stream_gb": round(rows * d * 4 * 2 / 1e9, 2),  # 2 passes
        "flops_model": flops,
        "baseline_samples_per_sec": 1.1e8,
    }


def _probe_backend(attempts: int = 2, probe_timeout: int = 75, cooldown: int = 30) -> bool:
    """Fail fast if the backend hangs at init (round-1 failure mode).

    A wedged TPU tunnel blocks *inside* ``make_c_api_client`` — uninterruptible
    from Python — so probe in a subprocess with a hard timeout before touching
    the backend in-process.  Skipped when pinned to CPU.

    Returns True if the accelerator is reachable; False means the caller
    should fall back to CPU (a flagged CPU number beats no number at all).
    """
    import subprocess

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return True
    last = ""
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.devices())"],
                capture_output=True, text=True, timeout=probe_timeout,
            )
            if proc.returncode == 0:
                return True
            last = proc.stderr[-2000:]
        except subprocess.TimeoutExpired:
            last = f"backend init did not respond within {probe_timeout}s (hang in make_c_api_client)"
        print(f"[bench] backend probe attempt {attempt} failed: {last}", file=sys.stderr)
        if attempt + 1 < attempts:
            time.sleep(cooldown)
    print(
        "[bench] accelerator backend unreachable after "
        f"{attempts} probes; falling back to CPU (flagged in output). "
        f"Last error: {last}",
        file=sys.stderr,
    )
    return False


def main() -> None:
    global N_ROWS, CSIZE
    tpu_ok = _probe_backend()
    if not tpu_ok:
        pin_platform("cpu")
    import jax

    devices = jax.devices()
    n_chips = len(devices)
    peak = _chip_peak_flops(devices[0])
    if devices[0].platform == "cpu" and "BENCH_ROWS" not in os.environ:
        # CPU fallback at the accelerator row count would blow any time
        # budget (kmeans k=1024 over millions of rows); scale down unless
        # the caller pinned a size explicitly
        N_ROWS = min(N_ROWS, 50_000)
        CSIZE = _csize(N_ROWS)
        print(
            f"[bench] cpu device: reducing N_ROWS to {N_ROWS} "
            "(set BENCH_ROWS to override)",
            file=sys.stderr,
        )

    from spark_rapids_ml_tpu.parallel.mesh import make_mesh, shard_rows

    mesh = make_mesh(n_chips)
    rng = np.random.default_rng(0)
    Xh = rng.standard_normal((N_ROWS, N_COLS), dtype=np.float32)
    w_true = rng.standard_normal((N_COLS,), dtype=np.float32)
    yh = (Xh @ w_true > 0).astype(np.float32)

    csize = CSIZE
    X, mask = shard_rows(Xh, mesh, row_multiple=csize)
    y, _ = shard_rows(yh, mesh, row_multiple=csize)
    jax.block_until_ready(X)
    del Xh, yh

    runs = {
        "pca": lambda: bench_pca(X, mask, mesh, n_chips),
        "kmeans": lambda: bench_kmeans(X, mask, mesh, n_chips),
        "logreg": lambda: bench_logreg(X, mask, y, mesh, n_chips),
        "pca_stream": lambda: bench_pca_stream(mesh, n_chips),
    }
    from spark_rapids_ml_tpu.utils.profiling import trace

    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    results = {}
    for name, fn in runs.items():
        for attempt in (0, 1):
            try:
                # per-algo TensorBoard profile capture when requested
                with trace(
                    os.path.join(profile_dir, name) if profile_dir else None
                ):
                    res = fn()
                res["mfu"] = res["flops_model"] / (
                    res["fit_seconds"] * peak * n_chips
                )
                res["vs_baseline"] = (
                    res["samples_per_sec_per_chip"] / res["baseline_samples_per_sec"]
                )
                results[name] = res
                print(
                    f"[bench] {name}: {res['samples_per_sec_per_chip']:.3e} "
                    f"samples/sec/chip, mfu={res['mfu']:.3f}, "
                    f"vs_baseline={res['vs_baseline']:.2f}",
                    file=sys.stderr,
                )
                break
            except Exception as e:  # noqa: BLE001
                transient = "UNAVAILABLE" in str(e)
                print(
                    f"[bench] {name} attempt {attempt} failed"
                    f"{' (transient, will retry)' if transient and attempt == 0 else ''}:\n"
                    f"{traceback.format_exc()}",
                    file=sys.stderr,
                )
                if not (transient and attempt == 0):
                    break
                time.sleep(15)

    if not results:
        print("[bench] all algorithms failed; no metric to report", file=sys.stderr)
        sys.exit(1)

    vs = [r["vs_baseline"] for r in results.values()]
    geomean_vs = math.exp(sum(math.log(v) for v in vs) / len(vs))
    headline = results.get("pca") or next(iter(results.values()))
    line = {
        "metric": "pca_fit_throughput",
        "value": round(headline["samples_per_sec_per_chip"], 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(headline["vs_baseline"], 3),
        "vs_baseline_geomean": round(geomean_vs, 3),
        "device": getattr(devices[0], "device_kind", "cpu"),
        "tpu_unreachable": not tpu_ok,
        # timings taken inside an active trace carry profiler overhead —
        # not comparable with unprofiled runs
        "profiled": bool(profile_dir),
        "n_chips": n_chips,
        "n_rows": N_ROWS,
        "n_cols": N_COLS,
    }
    for name, r in results.items():
        line[name] = {
            "samples_per_sec_per_chip": round(r["samples_per_sec_per_chip"], 1),
            "fit_seconds": round(r["fit_seconds"], 4),
            "mfu": round(r["mfu"], 4),
            "vs_baseline": round(r["vs_baseline"], 3),
        }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
