"""Drop-in module alias: ``spark_rapids_ml_tpu.clustering`` ≙ reference
``spark_rapids_ml.clustering`` (``/root/reference/python/src/spark_rapids_ml/clustering.py``)."""

from .models.clustering import KMeans, KMeansModel

__all__ = ["KMeans", "KMeansModel"]
