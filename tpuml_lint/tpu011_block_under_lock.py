"""TPU011: blocking call while a cataloged lock is held.

Project rule sharing TPU010's lexical lock resolution. Inside a
``with`` over a resolvable cataloged lock it flags the classic
dispatcher-deadlock shapes — calls that can block indefinitely (or for
a full device step) while every other thread needing the lock stalls
behind them:

- ``<future>.result(...)`` — a Future resolved by a thread that may
  itself need the held lock;
- ``time.sleep(...)`` — a critical section priced in wall-clock;
- ``<queue>.get(...)`` on queue-named receivers — waiting for a
  producer who may be waiting for the lock;
- ``jax.block_until_ready`` / ``.block_until_ready()`` — a device
  fence (milliseconds to seconds) under a host lock;
- subprocess RPC (``subprocess.run/...``, ``.communicate()``);
- ``<thread>.join(...)`` — joining a thread that may need the lock
  (string/path joins are filtered out);
- ``.predict(...)`` / ``.fit(...)`` — whole model executions.

``Condition.wait`` is deliberately NOT flagged: waiting releases the
lock, which is the sanctioned way to block inside a critical section.
The fix is almost always the repo's established idiom — snapshot under
the lock, do the slow work outside (see ``ModelRegistry.warm`` or the
dispatcher's collect-then-execute split).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Sequence, Tuple

from . import envinfo, locks
from .core import Finding, SourceFile, dotted_name

CODE = "TPU011"
NAME = "block-under-lock"

_QUEUEISH = re.compile(r"(^|_)(q|queue|inq|outq|jobs|work)s?$", re.I)

#: attribute names that block on another actor finishing
_BLOCKING_ATTRS = {
    "result": "Future.result() blocks on another worker",
    "communicate": "subprocess RPC round-trip",
    "predict": "a whole model execution",
    "fit": "a whole model fit",
    "block_until_ready": "a device fence",
}
_SUBPROCESS_FNS = {
    "subprocess.run", "subprocess.check_output",
    "subprocess.check_call", "subprocess.call",
}


def _blocking_reason(node: ast.Call) -> Optional[str]:
    dn = dotted_name(node.func)
    if dn is not None:
        if dn == "time.sleep":
            return "time.sleep() prices the critical section in wall-clock"
        if dn in _SUBPROCESS_FNS or dn.startswith("jax.block_until_ready"):
            return "a blocking subprocess/device call"
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    recv = node.func.value
    if attr in _BLOCKING_ATTRS:
        return _BLOCKING_ATTRS[attr]
    if attr == "get":
        rname = recv.id if isinstance(recv, ast.Name) else (
            recv.attr if isinstance(recv, ast.Attribute) else ""
        )
        if _QUEUEISH.search(rname or ""):
            return "queue.get() waits on a producer"
        return None
    if attr == "join":
        # thread joins only: filter string-literal receivers and
        # path-flavored dotted names (os.path.join, PurePath.join...)
        if isinstance(recv, ast.Constant):
            return None
        rdn = dotted_name(recv) or ""
        if "path" in rdn.lower() or "sep" in rdn.lower():
            return None
        return "joining a thread that may itself need the held lock"
    return None


def _scan(
    sf: SourceFile,
    lm: locks.LockMap,
    spec_by_name,
    body: Sequence[ast.stmt],
    cls: Optional[str],
    held: List[str],
) -> Iterator[Finding]:
    for stmt in body:
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered = 0
            for item in stmt.items:
                name = lm.resolve(item.context_expr, cls)
                if name is not None and name in spec_by_name:
                    held.append(name)
                    entered += 1
            yield from _scan(sf, lm, spec_by_name, stmt.body, cls, held)
            for _ in range(entered):
                held.pop()
            continue
        if held:
            for node in _calls_outside_defs(stmt):
                reason = _blocking_reason(node)
                if reason is not None:
                    yield sf.finding(
                        CODE, node,
                        f"blocking call under lock {held[-1]!r}: "
                        f"{reason}; every thread needing the lock "
                        "stalls behind it",
                        fixit="snapshot state under the lock and do "
                        "the blocking work outside the critical "
                        "section",
                    )
        for child_body in _bodies(stmt):
            yield from _scan(
                sf, lm, spec_by_name, child_body, cls, held
            )


def _bodies(stmt: ast.stmt) -> Iterator[Sequence[ast.stmt]]:
    for attr in ("body", "orelse", "finalbody"):
        b = getattr(stmt, attr, None)
        if b:
            yield b
    for h in getattr(stmt, "handlers", ()):
        yield h.body


def _calls_outside_defs(stmt: ast.stmt) -> Iterator[ast.Call]:
    """Calls in this statement's own expressions — not in nested
    compound bodies (scanned with their own held-stack state) and not
    inside nested function defs (run elsewhere)."""
    stack: List[ast.AST] = [stmt]
    first = True
    while stack:
        node = stack.pop()
        if not first and isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
             ast.Lambda),
        ):
            continue
        if not first and isinstance(node, ast.stmt) and any(
            True for _ in _bodies(node)
        ):
            # a nested compound statement: its header expressions still
            # run under the lock, its bodies are scanned separately
            for field in ("test", "iter", "items"):
                v = getattr(node, field, None)
                if v is not None:
                    stack.extend(v if isinstance(v, list) else [v])
            continue
        first = False
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))
    return


def check_project(
    files: Sequence[SourceFile], repo_root: str
) -> Iterator[Finding]:
    lockspec = envinfo.load_lockspec(repo_root)
    if lockspec is None:
        return
    spec_by_name = dict(lockspec.SPEC)
    from .tpu010_lock_order import _functions

    for sf in files:
        lm = locks.build(sf)
        if not lm.named:
            continue
        for cls, body in _functions(sf.tree):
            yield from _scan(sf, lm, spec_by_name, body, cls, [])
