"""Continuous-training lifecycle: the layer closing train→deploy→monitor.

Joins the fit plane (``runtime/scheduler.py``) to the serving plane
(``serving/runtime.py``) so a served model can be refreshed without a
process restart, and watches what serves so staleness is measured, not
assumed:

- **Versioned hot-swap** — :meth:`ModelLifecycle.swap` stages vN+1
  beside the live vN (spare HBM), warms its full bucket ladder under
  warmup-flagged spans, then flips routing atomically and releases vN.
  Zero typed sheds, ``retrace_storms == 0``, and a fault at any stage
  (the ``swap:warm``/``swap:flip`` injection sites) leaves exactly one
  consistent version serving: the old one.
- **Shadow canary with auto-rollback** — :meth:`start_canary` registers
  the candidate fully warmed under an alias and mirrors a deterministic
  traffic fraction to it; callers keep receiving the live version's
  (bit-identical) outputs while mirrored pairs score through
  :func:`evaluation.prediction_agreement`. At
  ``TPUML_CANARY_MIN_REQUESTS`` pairs the verdict is automatic:
  promote (an atomic flip of the already-warmed entry) at or above
  ``TPUML_CANARY_MIN_SCORE``, roll back under it — and a NEW SLO-burn
  alert (the PR-12 multi-window burn machinery) rolls back immediately
  without waiting for the count. Every rollback opens the model's
  *version breaker*: further swap/canary attempts raise a typed
  :class:`LifecycleError` until ``TPUML_CANARY_COOLDOWN_MS`` passes.
- **Refresh driver** — :class:`RefreshDriver` periodically re-fits
  through the scheduler as a low-priority, preemptible, slow-aging
  tenant and hands each completed fit to the swap (or canary) path.
- **Drift gauges** — :meth:`watch_drift` observes served outputs
  through the runtime's result-observer hook and scores each window's
  population stability index (PSI) against a frozen first-window
  reference into ``serve_drift_score`` (the ``serving_drift`` SLO
  budgets its p99); surfaced on ``/statusz``.

Defaults stay inert (the house contract): constructing nothing here
means no thread, no shadow route, no observer, and no new metric
series — the serving fast path is untouched.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

import numpy as np

from ..runtime import envspec, lockwitness, opsplane, telemetry
from ..runtime.admission import CLOSED, CircuitBreaker
from .registry import ResidentModel
from .runtime import ServingRuntime

__all__ = ["LifecycleError", "ModelLifecycle", "RefreshDriver"]

_LOGGER = logging.getLogger("spark_rapids_ml_tpu.serving.lifecycle")

_ROLLBACK_REASONS = ("score", "slo_burn", "manual", "shutdown")

# PSI smoothing floor: keeps empty bins from blowing the log while
# staying far below the 0.1 "drifting" rule-of-thumb threshold
_PSI_EPS = 1e-6


class LifecycleError(RuntimeError):
    """Typed lifecycle rejection: a canary already in progress, a
    version breaker still open after a rollback, or an operation the
    configured target cannot support. Never raised for load — the
    admission planes own those types."""


def _primary_column(host: Dict[str, Any]) -> Optional[str]:
    """The output column lifecycle scoring keys on: ``prediction``
    when present (every supervised family emits it), else the first
    column in sorted order (deterministic for pca/umap embeddings)."""
    if "prediction" in host:
        return "prediction"
    cols = sorted(host)
    return cols[0] if cols else None


@dataclass
class _Canary:
    """One in-flight shadow evaluation of a candidate version."""

    name: str
    alias: str
    version: int
    min_requests: int
    min_score: float
    burn_baseline: frozenset
    t_start: float = field(default_factory=time.perf_counter)
    live_vals: List[np.ndarray] = field(default_factory=list)
    shadow_vals: List[np.ndarray] = field(default_factory=list)
    pairs: int = 0
    score: Optional[float] = None
    scored: bool = False
    done: bool = False
    lock: Any = field(
        default_factory=lambda: lockwitness.make_lock("lifecycle.canary")
    )


@dataclass
class _DriftState:
    """Windowed PSI accumulator for one watched model."""

    window: int
    bins: int
    column: Optional[str] = None
    buf: List[np.ndarray] = field(default_factory=list)
    buffered: int = 0
    edges: Optional[np.ndarray] = None
    reference: Optional[np.ndarray] = None
    windows_scored: int = 0
    last_psi: Optional[float] = None
    lock: Any = field(
        default_factory=lambda: lockwitness.make_lock("lifecycle.drift")
    )


def _hist_probs(vals: np.ndarray, edges: np.ndarray) -> np.ndarray:
    counts, _ = np.histogram(vals, bins=edges)
    return counts.astype(np.float64) / max(1, vals.size)


def _psi(reference: np.ndarray, observed: np.ndarray) -> float:
    """Population stability index of ``observed`` bin probabilities
    against ``reference`` ones: ``sum((q - p) * ln(q / p))`` with an
    epsilon floor so empty bins stay finite. Always >= 0; ~0.1 is the
    classic 'drifting' threshold, ~0.25 'retrain'."""
    p = reference + _PSI_EPS
    q = observed + _PSI_EPS
    p = p / p.sum()
    q = q / q.sum()
    return float(np.sum((q - p) * np.log(q / p)))


class ModelLifecycle:
    """Lifecycle driver over a :class:`ServingRuntime` (full surface)
    or a :class:`serving.Router` (fleet-wide :meth:`swap` fan-out;
    canary/drift need a single runtime's mirror and observer hooks).

    Explicit-construction only — building this object is the opt-in;
    it starts no thread by itself (only :meth:`add_refresh` does) and
    records no metric until a lifecycle action runs.
    """

    def __init__(
        self,
        target: Any,
        scheduler: Any = None,
        *,
        canary_fraction: Optional[float] = None,
        canary_min_requests: Optional[int] = None,
        canary_min_score: Optional[float] = None,
        canary_cooldown_ms: Optional[float] = None,
        drift_window: Optional[int] = None,
        drift_bins: Optional[int] = None,
        burn_probe: Optional[Callable[[], Any]] = None,
    ) -> None:
        self._target = target
        self._runtime: Optional[ServingRuntime] = (
            target if isinstance(target, ServingRuntime) else None
        )
        self.scheduler = scheduler
        self._fraction = float(
            envspec.get("TPUML_CANARY_FRACTION")
            if canary_fraction is None else canary_fraction
        )
        self._min_requests = int(
            envspec.get("TPUML_CANARY_MIN_REQUESTS")
            if canary_min_requests is None else canary_min_requests
        )
        self._min_score = float(
            envspec.get("TPUML_CANARY_MIN_SCORE")
            if canary_min_score is None else canary_min_score
        )
        self._cooldown_s = float(
            envspec.get("TPUML_CANARY_COOLDOWN_MS")
            if canary_cooldown_ms is None else canary_cooldown_ms
        ) / 1e3
        self._drift_window = int(
            envspec.get("TPUML_LIFECYCLE_DRIFT_WINDOW")
            if drift_window is None else drift_window
        )
        self._drift_bins = int(
            envspec.get("TPUML_LIFECYCLE_DRIFT_BINS")
            if drift_bins is None else drift_bins
        )
        # SLO-burn tripwire: names of currently-alerting SLOs. The
        # default reads the live ops plane; tests inject their own.
        self._burn_probe = burn_probe
        self._lock = lockwitness.make_rlock("lifecycle.manager")
        self._canaries: Dict[str, _Canary] = {}
        self._drift: Dict[str, _DriftState] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._refreshers: List["RefreshDriver"] = []
        self._observer_installed = False
        self._closed = False
        # weakref-tracked: /statusz gets a lifecycle section and the
        # SIGTERM chain drains lifecycles before routers/runtimes
        opsplane.track_lifecycle(self)

    # -- introspection -----------------------------------------------------
    def is_closed(self) -> bool:
        return self._closed

    def swap_in_progress(self) -> bool:
        """True while a hot-swap is staging (load/warm/flip window) —
        the `/readyz` 503 ``swap_in_progress`` signal."""
        if self._runtime is None:
            return False
        return bool(self._runtime.registry.swaps_in_progress())

    def canary_in_progress(self, name: str) -> bool:
        with self._lock:
            return name in self._canaries

    def refreshers_alive(self) -> int:
        with self._lock:
            return sum(1 for r in self._refreshers if r.is_alive())

    def status(self) -> Dict[str, Any]:
        """The `/statusz` lifecycle section."""
        with self._lock:
            canaries = {
                name: {
                    "alias": st.alias,
                    "version": st.version,
                    "pairs": st.pairs,
                    "min_requests": st.min_requests,
                    "score": st.score,
                    "age_s": round(time.perf_counter() - st.t_start, 3),
                }
                for name, st in self._canaries.items()
            }
            drift = {
                name: {
                    "windows_scored": st.windows_scored,
                    "last_psi": (
                        None if st.last_psi is None
                        else round(st.last_psi, 6)
                    ),
                    "reference_ready": st.reference is not None,
                    "window": st.window,
                }
                for name, st in self._drift.items()
            }
            breakers = {
                name: br.state_name()
                for name, br in self._breakers.items()
                if br.state() != CLOSED
            }
            refreshers = [r.status() for r in self._refreshers]
        out: Dict[str, Any] = {
            "closed": self._closed,
            "canaries": canaries,
            "drift": drift,
            "version_breakers": breakers,
            "refreshers": refreshers,
        }
        if self._runtime is not None:
            out["swaps_in_progress"] = (
                self._runtime.registry.swaps_in_progress()
            )
        return out

    # -- hot-swap ----------------------------------------------------------
    def swap(
        self, name: str, model: Any = None, path: Optional[str] = None,
    ) -> Any:
        """Zero-downtime version flip of ``name`` (see
        :meth:`ModelRegistry.swap`). Against a router target the swap
        fans out fleet-wide (``path`` required — every replica loads
        the same persisted version). Refused with a typed
        :class:`LifecycleError` while the model's version breaker is
        open after a canary rollback."""
        self._check_open("swap")
        self._check_breaker(name, "swap")
        if self._runtime is not None:
            return self._runtime.swap(name, model=model, path=path)
        if path is None:
            raise LifecycleError(
                "fleet-wide swap through a Router needs a persisted "
                "path — every replica loads the same version"
            )
        return self._target.swap(name, path)

    # -- shadow canary -----------------------------------------------------
    def start_canary(
        self,
        name: str,
        model: Any = None,
        path: Optional[str] = None,
        *,
        fraction: Optional[float] = None,
        min_requests: Optional[int] = None,
        min_score: Optional[float] = None,
    ) -> str:
        """Stage a candidate version of ``name`` as a fully-warmed
        shadow entry and start mirroring a deterministic traffic
        fraction to it. Returns the shadow alias (``<name>@v<N+1>``).
        Callers keep receiving the live version's outputs until
        :meth:`promote` flips routing; the verdict is automatic once
        enough mirrored pairs score (or an SLO burn fires first)."""
        if self._runtime is None:
            raise LifecycleError(
                "canary needs a ServingRuntime target: the shadow "
                "mirror and pair scoring live in one runtime's "
                "dispatcher (fleet-wide canary is not supported)"
            )
        self._check_open("start_canary")
        self._check_breaker(name, "canary")
        with self._lock:
            if name in self._canaries:
                raise LifecycleError(
                    f"a canary for {name!r} is already in progress "
                    f"({self._canaries[name].alias})"
                )
        live = self._runtime.registry.get(name)
        version = live.version + 1
        alias = f"{name}@v{version}"
        # stage the candidate under the alias: full probe + ladder
        # warmup now, so promotion later is a pure atomic flip
        if model is not None:
            self._runtime.registry.register(alias, model)
        elif path is not None:
            self._runtime.registry.load(alias, path)
        else:
            raise ValueError("start_canary needs a model or a path")
        state = _Canary(
            name=name,
            alias=alias,
            version=version,
            min_requests=(
                self._min_requests if min_requests is None
                else int(min_requests)
            ),
            min_score=(
                self._min_score if min_score is None else float(min_score)
            ),
            burn_baseline=frozenset(self._alerting_slos()),
        )
        with self._lock:
            self._canaries[name] = state
        self._runtime.set_shadow(
            name,
            alias,
            self._fraction if fraction is None else float(fraction),
            on_pair=lambda live_out, shadow_out, st=state: self._on_pair(
                st, live_out, shadow_out
            ),
        )
        _LOGGER.info(
            "lifecycle: canary %s -> %s started (verdict at %d pairs, "
            "min score %.4f)",
            name, alias, state.min_requests, state.min_score,
        )
        return alias

    def promote(self, name: str) -> ResidentModel:
        """Flip ``name`` to its canary candidate: the alias entry is
        already probed and warmed, so this is one atomic registry move
        — no cold dispatch, no shed, no new compile."""
        state = self._take_canary(name)
        if state is None:
            raise LifecycleError(f"no canary in progress for {name!r}")
        self._runtime.clear_shadow(name)
        entry = self._runtime.registry.promote_alias(state.alias, name)
        telemetry.counter("canary_promotions_total").inc(1, model=name)
        self._breaker(name).record_success()
        _LOGGER.info(
            "lifecycle: promoted %s -> %s v%d (score=%s over %d pairs)",
            state.alias, name, entry.version, state.score, state.pairs,
        )
        return entry

    def rollback(self, name: str, reason: str = "manual") -> None:
        """Discard ``name``'s canary candidate with the live version
        untouched (it never stopped serving — the candidate only saw
        mirrored traffic) and open the version breaker so an immediate
        retry of the same refresh is refused typed."""
        if reason not in _ROLLBACK_REASONS:
            raise ValueError(
                f"rollback reason must be one of {_ROLLBACK_REASONS}, "
                f"got {reason!r}"
            )
        state = self._take_canary(name)
        if state is None:
            raise LifecycleError(f"no canary in progress for {name!r}")
        self._runtime.clear_shadow(name)
        try:
            self._runtime.registry.evict(state.alias)
        except Exception:  # already evicted (LRU raced us): fine
            _LOGGER.debug("lifecycle: %s already gone", state.alias)
        self._breaker(name).record_failure()
        telemetry.counter("canary_rollbacks_total").inc(
            1, model=name, reason=reason
        )
        _LOGGER.warning(
            "lifecycle: rolled back canary %s of %s (reason=%s score=%s "
            "pairs=%d); version breaker open for %.0f ms",
            state.alias, name, reason, state.score, state.pairs,
            self._cooldown_s * 1e3,
        )

    def _take_canary(self, name: str) -> Optional[_Canary]:
        with self._lock:
            state = self._canaries.pop(name, None)
        if state is not None:
            state.done = True
        return state

    def _on_pair(
        self,
        state: _Canary,
        live_out: Optional[Dict[str, np.ndarray]],
        shadow_out: Optional[Dict[str, np.ndarray]],
    ) -> None:
        """One mirrored request resolved on both sides (dispatcher
        thread). Accumulate the pair, check the SLO-burn tripwire, and
        render the verdict at the configured pair count."""
        if state.done or self._closed:
            return
        burning = self._alerting_slos() - set(state.burn_baseline)
        if burning:
            try:
                self.rollback(state.name, reason="slo_burn")
            except LifecycleError:  # verdict raced us
                pass
            else:
                _LOGGER.warning(
                    "lifecycle: SLO burn tripwire fired for %s: %s",
                    state.name, sorted(burning),
                )
            return
        if live_out is None or shadow_out is None:
            return  # a failed half never scores; live errors are the
            # serving plane's problem, not agreement evidence
        col = _primary_column(live_out)
        if col is None or col not in shadow_out:
            return
        with state.lock:
            if state.scored:
                return
            state.live_vals.append(
                np.asarray(live_out[col], dtype=np.float64).ravel()
            )
            state.shadow_vals.append(
                np.asarray(shadow_out[col], dtype=np.float64).ravel()
            )
            state.pairs += 1
            if state.pairs < state.min_requests:
                return
            state.scored = True
            live_cat = np.concatenate(state.live_vals)
            shadow_cat = np.concatenate(state.shadow_vals)
        from ..evaluation import prediction_agreement

        try:
            score = prediction_agreement(live_cat, shadow_cat)
        except Exception:
            _LOGGER.exception(
                "lifecycle: canary scoring failed for %s — rolling back",
                state.name,
            )
            score = float("-inf")
        state.score = None if score == float("-inf") else score
        try:
            if score >= state.min_score:
                self.promote(state.name)
            else:
                self.rollback(state.name, reason="score")
        except LifecycleError:  # burn tripwire or manual call raced us
            pass

    def _breaker(self, name: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                # one rollback opens it (fails=1): the point is a typed
                # refusal of the SAME bad refresh, not failure counting
                br = CircuitBreaker(
                    f"version:{name}", fails=1, cooldown_s=self._cooldown_s
                )
                self._breakers[name] = br
            return br

    def _check_breaker(self, name: str, what: str) -> None:
        if not self._breaker(name).allow():
            raise LifecycleError(
                f"version breaker for {name!r} is open after a canary "
                f"rollback; {what} refused until the "
                f"{self._cooldown_s * 1e3:.0f} ms cooldown passes"
            )

    def _check_open(self, what: str) -> None:
        if self._closed:
            raise LifecycleError(f"lifecycle is closed; {what} refused")

    def _alerting_slos(self) -> Set[str]:
        if self._burn_probe is not None:
            try:
                return set(self._burn_probe())
            except Exception:
                return set()
        try:
            status = opsplane.slo_status()
        except Exception:
            return set()
        return {
            name
            for name, st in (status or {}).items()
            if isinstance(st, dict) and st.get("alerting")
        }

    # -- drift gauges ------------------------------------------------------
    def watch_drift(
        self,
        name: str,
        column: Optional[str] = None,
        window: Optional[int] = None,
        bins: Optional[int] = None,
    ) -> None:
        """Score ``name``'s served output distribution per window into
        ``serve_drift_score{model}``: the first full window freezes a
        quantile-binned reference histogram, every later window scores
        its PSI against it. Installs the (single, shared) runtime
        result observer on first watch."""
        if self._runtime is None:
            raise LifecycleError(
                "drift gauges need a ServingRuntime target (the result "
                "observer hook lives in the dispatcher)"
            )
        st = _DriftState(
            window=self._drift_window if window is None else int(window),
            bins=self._drift_bins if bins is None else int(bins),
            column=column,
        )
        with self._lock:
            self._drift[name] = st
            if not self._observer_installed:
                self._runtime.add_result_observer(self._observe_result)
                self._observer_installed = True

    def unwatch_drift(self, name: str) -> None:
        with self._lock:
            self._drift.pop(name, None)

    def drift_state(self, name: str) -> Optional[Dict[str, Any]]:
        st = self._drift.get(name)
        if st is None:
            return None
        with st.lock:
            return {
                "windows_scored": st.windows_scored,
                "last_psi": st.last_psi,
                "reference_ready": st.reference is not None,
            }

    def _observe_result(
        self, entry: ResidentModel, host: Dict[str, np.ndarray]
    ) -> None:
        # dispatcher thread, after every successful group dispatch;
        # canary aliases are invisible here (keyed by exact live name)
        st = self._drift.get(entry.name)
        if st is None:
            return
        col = st.column or _primary_column(host)
        if col is None or col not in host:
            return
        vals = np.asarray(host[col], dtype=np.float64).ravel()
        psi: Optional[float] = None
        with st.lock:
            st.buf.append(vals)
            st.buffered += int(vals.size)
            if st.buffered < st.window:
                return
            data = np.concatenate(st.buf)
            window_vals, rest = data[: st.window], data[st.window:]
            st.buf = [rest] if rest.size else []
            st.buffered = int(rest.size)
            if st.reference is None:
                # freeze the reference at the first full window:
                # equal-mass quantile bins, open-ended edges so later
                # windows can land outside the observed range
                interior = np.unique(
                    np.quantile(
                        window_vals, np.linspace(0.0, 1.0, st.bins + 1)
                    )[1:-1]
                )
                st.edges = np.concatenate(
                    [[-np.inf], interior, [np.inf]]
                )
                st.reference = _hist_probs(window_vals, st.edges)
                return
            psi = _psi(
                st.reference, _hist_probs(window_vals, st.edges)
            )
            st.windows_scored += 1
            st.last_psi = psi
        telemetry.histogram("serve_drift_score").observe(
            psi, model=entry.name
        )

    # -- refresh driver ----------------------------------------------------
    def add_refresh(
        self,
        name: str,
        estimator_factory: Callable[[], Any],
        dataset: Any,
        **kwargs: Any,
    ) -> "RefreshDriver":
        """Attach and start a :class:`RefreshDriver` re-fitting
        ``name`` periodically (``TPUML_LIFECYCLE_REFRESH_MS``) through
        this lifecycle's scheduler. Keyword arguments pass through to
        the driver constructor."""
        self._check_open("add_refresh")
        driver = RefreshDriver(
            self, name, estimator_factory, dataset,
            scheduler=kwargs.pop("scheduler", self.scheduler), **kwargs,
        )
        with self._lock:
            self._refreshers.append(driver)
        driver.start()
        return driver

    # -- shutdown ----------------------------------------------------------
    def drain(self, timeout: float = 30.0) -> Dict[str, Any]:
        """Graceful stop, FIRST in the SIGTERM chain (before router /
        runtime / scheduler drains): halt refresh drivers so no new
        fits land in a draining scheduler, roll back in-flight canaries
        (``reason="shutdown"``) so no half-evaluated candidate can
        promote, and detach the drift observer."""
        with self._lock:
            if self._closed:
                return {
                    "drained": True, "rolled_back": 0,
                    "refreshers": len(self._refreshers),
                }
            self._closed = True
            refreshers = list(self._refreshers)
            names = list(self._canaries)
        for r in refreshers:
            r.halt()
        rolled = 0
        for name in names:
            try:
                self.rollback(name, reason="shutdown")
                rolled += 1
            except LifecycleError:  # verdict landed while we drained
                pass
        deadline = time.monotonic() + max(0.0, float(timeout))
        for r in refreshers:
            r.join(max(0.1, deadline - time.monotonic()))
        if self._observer_installed and self._runtime is not None:
            self._runtime.remove_result_observer(self._observe_result)
            self._observer_installed = False
        return {
            "drained": all(not r.is_alive() for r in refreshers),
            "rolled_back": rolled,
            "refreshers": len(refreshers),
        }

    def close(self) -> None:
        self.drain(timeout=5.0)

    def __enter__(self) -> "ModelLifecycle":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class RefreshDriver:
    """Periodic re-fit loop for one served model.

    Each cycle builds a fresh estimator (``estimator_factory()``), fits
    it — through the :class:`FitScheduler` as a low-priority,
    preemptible, slow-aging tenant when one is attached, else inline —
    and hands the model to the lifecycle swap path (or
    :meth:`ModelLifecycle.start_canary` with ``canary=True``). Cycle
    outcomes are counted under ``lifecycle_refresh_total{model,
    outcome}``; a cycle refused by a version breaker or an in-flight
    canary counts ``skipped`` and retries next period.

    The thread only exists once :meth:`start` runs (``ModelLifecycle.
    add_refresh`` calls it); ``daemon=True`` so a forgotten driver
    never blocks interpreter exit — :meth:`halt` + :meth:`join` is the
    clean path.
    """

    def __init__(
        self,
        lifecycle: ModelLifecycle,
        name: str,
        estimator_factory: Callable[[], Any],
        dataset: Any,
        *,
        period_ms: Optional[float] = None,
        scheduler: Any = None,
        tenant: str = "lifecycle-refresh",
        priority: int = -1,
        aging_ms: Optional[float] = None,
        fit_timeout_s: float = 600.0,
        canary: bool = False,
        max_refreshes: Optional[int] = None,
    ) -> None:
        self.lifecycle = lifecycle
        self.name = name
        self._factory = estimator_factory
        self._dataset = dataset
        self._period_s = float(
            envspec.get("TPUML_LIFECYCLE_REFRESH_MS")
            if period_ms is None else period_ms
        ) / 1e3
        self._scheduler = scheduler
        self._tenant = tenant
        self._priority = int(priority)
        # refits are background work: age toward the EDF front 10x
        # slower than interactive fits unless told otherwise
        self._aging_ms = aging_ms
        self._fit_timeout_s = float(fit_timeout_s)
        self._canary = bool(canary)
        self._max_refreshes = max_refreshes
        self.refreshes = 0
        self.outcomes: Dict[str, int] = {}
        self._halt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- thread lifecycle --------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=telemetry.bind_context(self._run),
            name=f"tpuml-lifecycle-refresh-{self.name}",
            daemon=True,
        )
        self._thread.start()

    def halt(self) -> None:
        self._halt.set()

    def join(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    def is_alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def status(self) -> Dict[str, Any]:
        return {
            "model": self.name,
            "alive": self.is_alive(),
            "period_ms": round(self._period_s * 1e3, 1),
            "refreshes": self.refreshes,
            "outcomes": dict(self.outcomes),
            "mode": "canary" if self._canary else "swap",
        }

    def _run(self) -> None:
        while not self._halt.wait(self._period_s):
            if self.lifecycle.is_closed():
                return
            self.refresh_now()
            if (
                self._max_refreshes is not None
                and self.refreshes >= self._max_refreshes
            ):
                return

    # -- one cycle ---------------------------------------------------------
    def refresh_now(self) -> str:
        """Run one re-fit cycle synchronously and return its outcome
        (``swapped`` | ``canary`` | ``skipped`` | ``failed``) — also
        the test/bench entry point, no thread required."""
        outcome = "failed"
        try:
            outcome = self._refresh_once()
        except LifecycleError as e:
            outcome = "skipped"  # breaker open / canary in flight
            _LOGGER.info(
                "lifecycle: refresh of %s skipped: %s", self.name, e
            )
        except Exception:
            _LOGGER.exception(
                "lifecycle: refresh of %s failed", self.name
            )
        self.refreshes += 1
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        telemetry.counter("lifecycle_refresh_total").inc(
            1, model=self.name, outcome=outcome
        )
        return outcome

    def _refresh_once(self) -> str:
        estimator = self._factory()
        dataset = self._dataset() if callable(self._dataset) else self._dataset
        if self._scheduler is not None:
            fut = self._scheduler.submit(
                estimator, dataset,
                tenant=self._tenant,
                priority=self._priority,
                aging_ms=self._aging_ms,
            )
            model = fut.result(self._fit_timeout_s)
        else:
            model = estimator.fit(dataset)
        if self.lifecycle.is_closed():
            return "skipped"
        if self._canary:
            if self.lifecycle.canary_in_progress(self.name):
                return "skipped"
            self.lifecycle.start_canary(self.name, model=model)
            return "canary"
        self.lifecycle.swap(self.name, model=model)
        return "swapped"
