"""TPU002 — env-var registry / docs drift.

Project-level rule, four checks:

1. every ``TPUML_*`` name the code touches (``envspec.get/...`` calls,
   raw ``os.environ`` access, test ``setenv``) is registered in
   ``runtime/envspec.py``;
2. every registered variable appears in ``docs/configuration.md``, plus
   any extra files its registration names (``also_documented_in`` —
   e.g. the resilience knobs must appear in ``docs/fault_tolerance.md``);
3. every ``TPUML_*`` token mentioned in those docs is registered (a doc
   describing a deleted knob is drift too);
4. the generated env table in ``docs/configuration.md`` (between the
   ``tpuml-envspec`` markers) byte-matches what
   ``scripts/gen_config_docs.py`` would emit from the registry today.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Tuple

from .core import Finding, SourceFile, dotted_name, str_const
from .envinfo import ENVSPEC_RELPATH, load_envspec

CODE = "TPU002"
NAME = "env-doc-drift"

_DOC_FILES = ("docs/configuration.md", "docs/fault_tolerance.md")
_TOKEN_RE = re.compile(r"\bTPUML_[A-Z0-9_]+\b")
# registry functions whose first string arg is an env-var use
_ENVSPEC_FNS = ("get", "get_raw", "is_set", "parse")
# env writers whose first string arg asserts the var exists
_WRITER_FNS = ("setenv", "delenv")


def _used_names(sf: SourceFile) -> Iterator[Tuple[str, ast.AST]]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if fn is None:
            continue
        leaf = fn.rsplit(".", 1)[-1]
        if leaf in _ENVSPEC_FNS and "envspec" in fn:
            s = str_const(node.args[0]) if node.args else None
            if s and s.startswith("TPUML_"):
                yield s, node
        elif leaf in _WRITER_FNS or (leaf in ("get", "pop") and "environ" in fn):
            s = str_const(node.args[0]) if node.args else None
            if s and s.startswith("TPUML_"):
                yield s, node


def check_project(files: List[SourceFile], repo_root: str) -> Iterator[Finding]:
    try:
        envspec = load_envspec(repo_root)
    except Exception as e:  # registry must at least load
        yield Finding(
            rule=CODE,
            path=ENVSPEC_RELPATH.replace(os.sep, "/"),
            line=1,
            col=1,
            message=f"could not load the env registry: {e}",
        )
        return
    registered = set(envspec.SPEC)
    spec_relpath = ENVSPEC_RELPATH.replace(os.sep, "/")

    # registration line of each var (for fix-it anchors)
    reg_lines: Dict[str, int] = {}
    spec_path = os.path.join(repo_root, ENVSPEC_RELPATH)
    with open(spec_path, "r", encoding="utf-8") as f:
        spec_lines = f.read().splitlines()
    for i, line in enumerate(spec_lines, 1):
        for tok in _TOKEN_RE.findall(line):
            reg_lines.setdefault(tok, i)

    # 1. used-but-unregistered
    for sf in files:
        if sf.path == spec_relpath:
            continue
        for name, node in _used_names(sf):
            if name not in registered:
                yield sf.finding(
                    CODE, node,
                    f"{name} is used in code but not registered in "
                    f"{spec_relpath}",
                    f"add an EnvVar({name!r}, ...) entry to the registry "
                    f"and run scripts/gen_config_docs.py",
                )

    # 2. registered-but-undocumented + 3. documented-but-unregistered
    # every also_documented_in target participates alongside the static
    # list, so new per-subsystem docs are covered without editing the rule
    doc_files = set(_DOC_FILES)
    for var in envspec.SPEC.values():
        doc_files.update(getattr(var, "also_documented_in", ()) or ())
    doc_text: Dict[str, str] = {}
    for rel in sorted(doc_files):
        p = os.path.join(repo_root, rel)
        if os.path.exists(p):
            with open(p, "r", encoding="utf-8") as f:
                doc_text[rel] = f.read()

    for name, var in envspec.SPEC.items():
        required = ("docs/configuration.md",) + tuple(
            getattr(var, "also_documented_in", ())
        )
        for rel in required:
            text = doc_text.get(rel)
            if text is None:
                yield Finding(
                    rule=CODE, path=rel, line=1, col=1,
                    message=f"documentation file missing (required for "
                            f"{name})",
                )
            elif name not in text:
                yield Finding(
                    rule=CODE,
                    path=spec_relpath,
                    line=reg_lines.get(name, 1),
                    col=1,
                    message=f"{name} is registered but absent from {rel}",
                    fixit="run scripts/gen_config_docs.py (configuration.md "
                          "table) or mention the variable in the doc's prose",
                    context=name,
                )

    for rel, text in doc_text.items():
        for i, line in enumerate(text.splitlines(), 1):
            for tok in sorted(set(_TOKEN_RE.findall(line))):
                if tok not in registered:
                    yield Finding(
                        rule=CODE, path=rel, line=i, col=1,
                        message=f"{tok} is documented here but not "
                                f"registered in {spec_relpath}",
                        fixit="register the variable or delete the stale "
                              "doc reference",
                        context=tok,
                    )

    # 4. generated-table drift
    conf = doc_text.get("docs/configuration.md")
    if conf is not None:
        expected = list(envspec.doc_table_lines())
        begin, end = envspec.TABLE_BEGIN, envspec.TABLE_END
        lines = conf.splitlines()
        try:
            b = lines.index(begin)
            e = lines.index(end)
            actual = lines[b : e + 1]
        except ValueError:
            yield Finding(
                rule=CODE, path="docs/configuration.md", line=1, col=1,
                message="generated env-var table markers not found "
                        "(tpuml-envspec:begin/end)",
                fixit="run scripts/gen_config_docs.py",
            )
            return
        if actual != expected:
            yield Finding(
                rule=CODE, path="docs/configuration.md", line=b + 1, col=1,
                message="generated env-var table is stale (does not match "
                        "the registry)",
                fixit="run scripts/gen_config_docs.py",
                context="<envspec table>",
            )
