"""TPU007 — metric names must be declared in the metric catalog.

Counter analog of TPU002: every metric name the code passes to the
telemetry registry (``telemetry.counter/gauge/histogram``) or to the
legacy counters shim (``counters.bump/note/get``) must be declared in
``runtime/metricspec.py``.  An undeclared name is either a typo (the
increments land in a metric nobody exports a description for) or a new
metric missing its catalog entry — both silently corrupt dashboards
built on the Prometheus dump.

Two checks per call site with a literal first argument:

1. the name is declared in the catalog;
2. the call's implied kind matches the declared kind (``bump`` and
   ``counter`` imply a counter, ``note`` and ``gauge`` a gauge,
   ``histogram`` a histogram) — the runtime raises on mismatch, this
   catches it before anything runs.

The project pass also validates the SLO catalog (``runtime/slo.py``):
every declared ``SLOSpec.metric`` must reference a cataloged metric —
an SLO over a nonexistent metric would silently never measure, which
is the worst possible failure mode for an alerting rule.

Dynamic (non-literal) names are out of scope, as with TPU002.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Optional, Tuple

from .core import Finding, SourceFile, dotted_name, str_const
from .envinfo import (
    METRICSPEC_RELPATH,
    SLOSPEC_RELPATH,
    load_metricspec,
    load_slospec,
)

CODE = "TPU007"
NAME = "metric-catalog"

# leaf function -> metric kind it implies (None: any kind, read-only)
_COUNTERS_FNS = {"bump": "counter", "note": "gauge", "get": None}
_TELEMETRY_FNS = {
    "counter": "counter",
    "gauge": "gauge",
    "histogram": "histogram",
}
_TELEMETRY_RELPATH = "spark_rapids_ml_tpu/runtime/telemetry.py"


def _used_names(
    sf: SourceFile,
) -> Iterator[Tuple[str, Optional[str], ast.AST]]:
    """(metric name, implied kind, node) for every literal-name call."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if fn is None:
            continue
        leaf = fn.rsplit(".", 1)[-1]
        if leaf in _COUNTERS_FNS and "counters" in fn:
            kind = _COUNTERS_FNS[leaf]
        elif leaf in _TELEMETRY_FNS and (
            "telemetry" in fn
            # inside telemetry.py itself the registry functions are
            # bare names — still catalog-bound
            or (fn == leaf and sf.path == _TELEMETRY_RELPATH)
        ):
            kind = _TELEMETRY_FNS[leaf]
        else:
            continue
        name = str_const(node.args[0]) if node.args else None
        if name:
            yield name, kind, node


def check_project(files: List[SourceFile], repo_root: str) -> Iterator[Finding]:
    spec_relpath = METRICSPEC_RELPATH.replace(os.sep, "/")
    try:
        metricspec = load_metricspec(repo_root)
    except Exception as e:  # catalog must at least load
        yield Finding(
            rule=CODE,
            path=spec_relpath,
            line=1,
            col=1,
            message=f"could not load the metric catalog: {e}",
        )
        return
    catalog = metricspec.SPEC

    for sf in files:
        if sf.path == spec_relpath:
            continue
        for name, kind, node in _used_names(sf):
            declared = catalog.get(name)
            if declared is None:
                yield sf.finding(
                    CODE, node,
                    f"metric {name!r} is used in code but not declared in "
                    f"{spec_relpath}",
                    f"add a MetricSpec({name!r}, ...) entry to the catalog",
                )
            elif kind is not None and declared.kind != kind:
                yield sf.finding(
                    CODE, node,
                    f"metric {name!r} is declared as a {declared.kind} in "
                    f"{spec_relpath} but used here as a {kind}",
                    "use the matching registry accessor or fix the "
                    "catalog kind",
                )

    # the SLO catalog must only reference cataloged metrics
    slo_relpath = SLOSPEC_RELPATH.replace(os.sep, "/")
    try:
        slospec = load_slospec(repo_root)
    except Exception as e:
        yield Finding(
            rule=CODE,
            path=slo_relpath,
            line=1,
            col=1,
            message=f"could not load the SLO catalog: {e}",
        )
        return
    if slospec is None:
        return
    for s in getattr(slospec, "CATALOG", ()):
        if s.metric not in catalog:
            yield Finding(
                rule=CODE,
                path=slo_relpath,
                line=1,
                col=1,
                message=(
                    f"SLO {s.name!r} references metric {s.metric!r} which "
                    f"is not declared in {spec_relpath} — it would never "
                    f"measure anything"
                ),
                context=f"slo:{s.name}",
            )
