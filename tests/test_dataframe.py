"""DataFrame (data plane) tests."""

import numpy as np
import pytest
import scipy.sparse as sp

from spark_rapids_ml_tpu.data import DataFrame, kfold


def _df(n=10):
    return DataFrame(
        {
            "features": np.arange(n * 3, dtype=np.float32).reshape(n, 3),
            "label": np.arange(n, dtype=np.float32),
        },
        num_partitions=2,
    )


def test_basic_shape():
    df = _df()
    assert df.count() == 10
    assert set(df.columns) == {"features", "label"}
    assert df.column("features").shape == (10, 3)


def test_mismatched_rows_raises():
    with pytest.raises(ValueError, match="rows"):
        DataFrame({"a": np.zeros(3), "b": np.zeros(4)})


def test_select_withcolumn_drop():
    df = _df()
    assert df.select("label").columns == ["label"]
    df2 = df.withColumn("pred", np.zeros(10))
    assert "pred" in df2.columns and "pred" not in df.columns
    assert df2.drop("pred").columns == df.columns


def test_filter_and_order():
    df = _df()
    sub = df.filter(df["label"] > 5)
    assert sub.count() == 4
    rev = df.orderBy("label", ascending=False)
    assert rev["label"][0] == 9


def test_union_and_split():
    df = _df()
    both = df.union(df)
    assert both.count() == 20
    a, b = df.randomSplit([0.5, 0.5], seed=1)
    assert a.count() + b.count() == 10


def test_partitions():
    df = _df().repartition(3)
    parts = list(df.iter_partitions())
    assert len(parts) == 3
    assert sum(p.count() for p in parts) == 10


def test_collect_rows():
    rows = _df(3).collect()
    assert rows[1].label == 1.0
    assert rows[1]["features"].shape == (3,)


def test_pandas_roundtrip():
    df = _df(5)
    pdf = df.toPandas()
    back = DataFrame.from_pandas(pdf)
    np.testing.assert_array_equal(back["features"], df["features"])


def test_parquet_roundtrip(tmp_path):
    df = _df(7)
    df.write_parquet(str(tmp_path / "d"), rows_per_file=3)
    back = DataFrame.read_parquet(str(tmp_path / "d"))
    np.testing.assert_allclose(back["features"], df["features"])
    np.testing.assert_allclose(back["label"], df["label"])


def test_sparse_column():
    m = sp.random(10, 5, density=0.3, format="csr", random_state=0)
    df = DataFrame({"features": m, "label": np.zeros(10)})
    assert df.count() == 10
    sub = df.take_rows(np.arange(4))
    assert sub["features"].shape == (4, 5)


def test_kfold():
    folds = kfold(_df(20), 4, seed=0)
    assert len(folds) == 4
    for train, val in folds:
        assert train.count() + val.count() == 20
