"""Benchmark harness for spark_rapids_ml_tpu.

Mirrors the reference's ``python/benchmark/benchmark`` package
(``/root/reference/python/benchmark/``): a per-algorithm ``BenchmarkBase``
subclass parses CLI flags, runs fit/transform ``num_runs`` times on either
the TPU framework or a CPU (sklearn) baseline, and appends timing + quality
rows to a CSV report.
"""
