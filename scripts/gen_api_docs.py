"""Generate markdown API reference pages under docs/api/ from docstrings
(the committed-output analog of the reference's Sphinx site,
``/root/reference/docs/site/api/``; this image has no sphinx/pdoc, so the
generator is dependency-free inspect walking).

Run from the repo root on CPU:
    JAX_PLATFORMS=cpu python scripts/gen_api_docs.py
"""
import importlib
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "docs", "api"
)

# public surface: module -> classes/functions to document (None = every
# public name defined in the module)
MODULES = [
    ("spark_rapids_ml_tpu.classification", None),
    ("spark_rapids_ml_tpu.regression", None),
    ("spark_rapids_ml_tpu.clustering", None),
    ("spark_rapids_ml_tpu.feature", None),
    ("spark_rapids_ml_tpu.knn", None),
    ("spark_rapids_ml_tpu.umap", None),
    ("spark_rapids_ml_tpu.tuning", None),
    ("spark_rapids_ml_tpu.evaluation", None),
    ("spark_rapids_ml_tpu.metrics", None),
    ("spark_rapids_ml_tpu.pipeline", None),
    ("spark_rapids_ml_tpu.params", ["Param", "Params", "TypeConverters"]),
    ("spark_rapids_ml_tpu.data", ["DataFrame"]),
    ("spark_rapids_ml_tpu.data.dataframe", ["ParquetScanFrame"]),
    ("spark_rapids_ml_tpu.core", ["_TpuEstimator", "_TpuModel"]),
    ("spark_rapids_ml_tpu.native", None),
    ("spark_rapids_ml_tpu.parallel.context", ["TpuDistContext"]),
    ("spark_rapids_ml_tpu.parallel.mesh", None),
    ("spark_rapids_ml_tpu.ops.streaming", None),
    ("spark_rapids_ml_tpu.utils.platform", None),
    ("spark_rapids_ml_tpu.utils.profiling", None),
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def _doc(obj):
    d = inspect.getdoc(obj)
    return d or ""


def render_class(name, cls):
    lines = [f"### class `{name}{_sig(cls.__init__) if '__init__' in cls.__dict__ else ''}`", ""]
    d = _doc(cls)
    if d:
        lines += [d, ""]
    members = []
    seen = set()
    own = set(vars(cls))
    for klass in cls.__mro__:
        if not klass.__module__.startswith("spark_rapids_ml_tpu"):
            continue
        for mname, m in sorted(vars(klass).items()):
            if mname.startswith("_") or mname in seen:
                continue
            seen.add(mname)
            inh = "" if mname in own else ", inherited"
            if isinstance(m, property):
                members.append((mname, f"property{inh}",
                                _doc(m.fget) if m.fget else ""))
            elif isinstance(m, (classmethod, staticmethod)):
                fn = m.__func__
                kind = ("classmethod" if isinstance(m, classmethod)
                        else "staticmethod") + inh
                members.append((f"{mname}{_sig(fn)}", kind, _doc(fn)))
            elif inspect.isfunction(m):
                members.append((f"{mname}{_sig(m)}", f"method{inh}", _doc(m)))
    members.sort(key=lambda t: t[0])
    for label, kind, doc in members:
        lines.append(f"- **`{label}`** *({kind})*")
        if doc:
            first = doc.splitlines()
            head = first[0]
            lines.append(f"  — {head}")
    lines.append("")
    return "\n".join(lines)


def render_function(name, fn):
    lines = [f"### `{name}{_sig(fn)}`", ""]
    d = _doc(fn)
    if d:
        lines += [d, ""]
    return "\n".join(lines)


def main():
    os.makedirs(OUT, exist_ok=True)
    # stale pages from renamed/delisted modules must not linger in the
    # committed output
    for f in os.listdir(OUT):
        if f.endswith(".md"):
            os.remove(os.path.join(OUT, f))
    index = [
        "# spark_rapids_ml_tpu API reference",
        "",
        "Generated from docstrings by `scripts/gen_api_docs.py` "
        "(committed output — regenerate after changing public surfaces).",
        "",
    ]
    for modname, names in MODULES:
        mod = importlib.import_module(modname)
        if names is None:
            names = [
                n for n in (getattr(mod, "__all__", None) or sorted(vars(mod)))
                if not n.startswith("_")
                and getattr(getattr(mod, n, None), "__module__", "").startswith(
                    "spark_rapids_ml_tpu"
                )
            ]
        page = [f"# `{modname}`", ""]
        d = _doc(mod)
        if d:
            page += [d, ""]
        count = 0
        for n in names:
            obj = getattr(mod, n, None)
            if obj is None:
                continue
            if inspect.isclass(obj):
                page.append(render_class(n, obj))
                count += 1
            elif inspect.isfunction(obj):
                page.append(render_function(n, obj))
                count += 1
        if count == 0:
            continue
        fname = modname.replace("spark_rapids_ml_tpu", "srmt").replace(".", "_") + ".md"
        with open(os.path.join(OUT, fname), "w") as f:
            f.write("\n".join(page))
        index.append(f"- [`{modname}`]({fname}) — {count} documented entries")
        print(f"wrote docs/api/{fname} ({count} entries)")
    with open(os.path.join(OUT, "index.md"), "w") as f:
        f.write("\n".join(index) + "\n")
    print("wrote docs/api/index.md")


if __name__ == "__main__":
    main()
