"""Gang-scheduled batched fitting (TPUML_GANG_FIT).

Contract layering (see docs/gang_fit.md):

- The FREEZE is bitwise: once a lane converges its state never changes,
  even while other lanes keep iterating — asserted by varying OTHER lanes'
  traced tol inside the SAME compiled program and checking the converged
  lane's output is bit-identical. Identical-param lanes inside one gang are
  likewise bitwise equal.
- Gang vs SOLO is tight-tolerance + iteration lockstep, NOT bitwise: the
  batched and solo programs are different XLA computations and fusion
  choices legitimately differ by ulps.
- Defaults are inert: with the env unset, fitMultiple/CV run the sequential
  path and no gang counters move.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu.core import resolve_gang_fit
from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.data.dataframe import kfold, kfold_ids
from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.regression import LinearRegression
from spark_rapids_ml_tpu.ops.lbfgs import minimize_lbfgs, minimize_lbfgs_batched
from spark_rapids_ml_tpu.ops.linreg_kernels import (
    linreg_suffstats,
    solve_elasticnet,
    solve_elasticnet_batched,
)
from spark_rapids_ml_tpu.runtime import counters
from spark_rapids_ml_tpu.runtime.envspec import EnvSpecError
from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder


def _quad_problem(seed=0, n=256, p=8):
    """A strongly-convex least-squares objective with a batch axis: lane b's
    loss depends only on row b of W, so per-lane gradients are exact."""
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(n, p))
    x_true = rng.normal(size=p)
    y = A @ x_true + 0.1 * rng.normal(size=n)
    Aj, yj = jnp.asarray(A, jnp.float32), jnp.asarray(y, jnp.float32)

    def fun_batched(W):  # (B, p) -> (B,)
        r = W @ Aj.T - yj[None, :]
        return 0.5 * (r * r).mean(axis=1)

    def fun_solo(w):
        r = Aj @ w - yj
        return 0.5 * (r * r).mean()

    return fun_batched, fun_solo, p


def _clf_data(seed=0, n=3000, d=10, classes=2):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    if classes == 2:
        w = rng.normal(size=d)
        y = (X @ w + 0.5 * rng.normal(size=n) > 0).astype(float)
    else:
        W = rng.normal(size=(d, classes))
        y = np.argmax(X @ W + 0.5 * rng.normal(size=(n, classes)), axis=1).astype(
            float
        )
    return DataFrame({"features": X, "label": y})


def _grid(est, reg_values, enet_values):
    return (
        ParamGridBuilder()
        .addGrid(est.getParam("regParam"), list(reg_values))
        .addGrid(est.getParam("elasticNetParam"), list(enet_values))
        .build()
    )


# ---------------------------------------------------------------------------
# solver-level contracts
# ---------------------------------------------------------------------------


def test_freeze_bitwise_under_other_lane_tol_change():
    """The correctness core: a converged lane's output must be bit-identical
    whether the while_loop stops right after it converges or keeps running
    for OTHER lanes. tol is traced, so both runs are the SAME compiled
    program — any difference is a freeze bug, not fusion noise."""
    fun_b, _, p = _quad_problem()
    B = 3
    w0 = jnp.zeros((B, p), jnp.float32)
    # lane 0 is the probe; lanes 1-2 get loose then brutal tolerances
    tol_short = jnp.asarray([1e-4, 1e-3, 1e-3], jnp.float32)
    tol_long = jnp.asarray([1e-4, 1e-12, 1e-12], jnp.float32)
    short = minimize_lbfgs_batched(fun_b, w0, max_iter=100, tol=tol_short)
    long = minimize_lbfgs_batched(fun_b, w0, max_iter=100, tol=tol_long)
    assert int(long.n_iter[1]) > int(short.n_iter[1])  # loop really ran longer
    np.testing.assert_array_equal(np.asarray(short.w[0]), np.asarray(long.w[0]))
    np.testing.assert_array_equal(np.asarray(short.f[0]), np.asarray(long.f[0]))
    assert int(short.n_iter[0]) == int(long.n_iter[0])


def test_identical_lanes_bitwise_equal():
    """Lanes with identical params inside ONE gang see the same op sequence
    and must agree bitwise."""
    fun_b, _, p = _quad_problem(seed=3)
    B = 4
    w0 = jnp.zeros((B, p), jnp.float32)
    tol = jnp.full((B,), 1e-8, jnp.float32)
    out = minimize_lbfgs_batched(fun_b, w0, max_iter=100, tol=tol)
    for b in range(1, B):
        np.testing.assert_array_equal(np.asarray(out.w[0]), np.asarray(out.w[b]))
        assert int(out.n_iter[0]) == int(out.n_iter[b])


def test_gang_vs_solo_lockstep_and_tolerance():
    fun_b, fun_s, p = _quad_problem(seed=1)
    B = 3
    tols = [1e-5, 1e-7, 1e-9]
    out = minimize_lbfgs_batched(
        fun_b,
        jnp.zeros((B, p), jnp.float32),
        max_iter=200,
        tol=jnp.asarray(tols, jnp.float32),
    )
    for b, t in enumerate(tols):
        solo = minimize_lbfgs(
            fun_s, jnp.zeros((p,), jnp.float32), max_iter=200, tol=t
        )
        assert abs(int(out.n_iter[b]) - int(solo.n_iter)) <= 1
        np.testing.assert_allclose(
            np.asarray(out.w[b]), np.asarray(solo.w), rtol=1e-3, atol=1e-5
        )


def test_owlqn_lane_mixing_l1_magnitudes():
    """OWL-QN lanes with DIFFERENT l1 strengths in one gang each match
    their solo OWL-QN solve (the per-lane orthant projection and sign-fix
    must not leak across lanes)."""
    fun_b, fun_s, p = _quad_problem(seed=2)
    l1s = [0.001, 0.05, 0.5]
    B = len(l1s)
    l1w = jnp.asarray(l1s, jnp.float32)[:, None] * jnp.ones((B, p), jnp.float32)
    out = minimize_lbfgs_batched(
        fun_b,
        jnp.zeros((B, p), jnp.float32),
        max_iter=200,
        tol=jnp.full((B,), 1e-9, jnp.float32),
        l1_weights=l1w,
    )
    for b, l1 in enumerate(l1s):
        solo = minimize_lbfgs(
            fun_s,
            jnp.zeros((p,), jnp.float32),
            max_iter=200,
            tol=1e-9,
            l1_weights=jnp.full((p,), l1, jnp.float32),
        )
        np.testing.assert_allclose(
            np.asarray(out.w[b]), np.asarray(solo.w), rtol=1e-3, atol=1e-5
        )
        # the strong-l1 lane must actually be sparse — proves the orthant
        # machinery ran per-lane rather than being averaged away
        if l1 == 0.5:
            assert np.sum(np.asarray(out.w[b]) == 0.0) > 0


def test_elasticnet_batched_matches_solo():
    rng = np.random.default_rng(4)
    n, d = 2000, 8
    X = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    y = jnp.asarray(
        rng.normal(size=n) + np.asarray(X[:, 0]) * 2.0, jnp.float32
    )
    mask = jnp.ones((n,), jnp.float32)
    stats = linreg_suffstats(X, mask, y, None, fit_intercept=True)
    lanes = [(0.1, 0.05), (0.01, 0.2), (0.3, 0.0)]
    bl1 = jnp.asarray([a for a, _ in lanes], jnp.float32)
    bl2 = jnp.asarray([b for _, b in lanes], jnp.float32)
    btol = jnp.full((len(lanes),), 1e-7, jnp.float32)
    beta_b, int_b, it_b = solve_elasticnet_batched(
        stats, bl1, bl2, standardization=True, max_iter=500, tol=btol
    )
    for i, (l1, l2) in enumerate(lanes):
        beta, inter, it = solve_elasticnet(
            stats,
            jnp.asarray(l1, jnp.float32),
            jnp.asarray(l2, jnp.float32),
            standardization=True,
            max_iter=500,
            tol=1e-7,
        )
        assert abs(int(it_b[i]) - int(it)) <= 2
        np.testing.assert_allclose(
            np.asarray(beta_b[i]), np.asarray(beta), rtol=1e-4, atol=1e-6
        )
        np.testing.assert_allclose(
            float(int_b[i]), float(inter), rtol=1e-4, atol=1e-6
        )


# ---------------------------------------------------------------------------
# resolver / env validation
# ---------------------------------------------------------------------------


def test_resolver_off_auto_int(monkeypatch):
    monkeypatch.delenv("TPUML_GANG_FIT", raising=False)
    assert resolve_gang_fit(8, 1.0) == 1
    monkeypatch.setenv("TPUML_GANG_FIT", "off")
    assert resolve_gang_fit(8, 1.0) == 1
    monkeypatch.setenv("TPUML_GANG_FIT", "auto")
    assert resolve_gang_fit(8, 1.0) == 8
    monkeypatch.setenv("TPUML_GANG_FIT", "3")
    assert resolve_gang_fit(8, 1.0) == 3


def test_resolver_budget_clamp(monkeypatch):
    monkeypatch.setenv("TPUML_GANG_FIT", "auto")
    monkeypatch.setenv("TPUML_GANG_FIT_BUDGET", "1000")
    assert resolve_gang_fit(8, 250.0) == 4  # 1000 // 250
    assert resolve_gang_fit(8, 5000.0) == 1  # budget < one lane: degrade to 1
    monkeypatch.setenv("TPUML_GANG_FIT_BUDGET", "1e12")
    assert resolve_gang_fit(8, 250.0) == 8


def test_resolver_env_validation(monkeypatch):
    monkeypatch.setenv("TPUML_GANG_FIT", "bogus")
    with pytest.raises(EnvSpecError, match="TPUML_GANG_FIT"):
        resolve_gang_fit(4, 1.0)
    monkeypatch.setenv("TPUML_GANG_FIT", "0")
    with pytest.raises(EnvSpecError, match=">= 1"):
        resolve_gang_fit(4, 1.0)
    monkeypatch.setenv("TPUML_GANG_FIT", "auto")
    monkeypatch.setenv("TPUML_GANG_FIT_BUDGET", "-5")
    with pytest.raises(EnvSpecError):
        resolve_gang_fit(4, 1.0)


def test_static_bucket_grouping():
    lr = LogisticRegression(maxIter=25)
    param_sets = []
    for reg, enet in [(0.1, 0.0), (0.01, 0.0), (0.1, 0.5), (0.01, 1.0)]:
        est = lr.copy()
        lr._copy_tpu_params(est)
        est._set_params(regParam=reg, elasticNetParam=enet)
        param_sets.append(dict(est._tpu_params))
    groups = dict(lr._gang_fit_groups(param_sets))
    # plain-L2 lanes and OWL-QN lanes compile different programs: 2 buckets
    assert len(groups) == 2
    by_use_l1 = {key[2]: idxs for key, idxs in groups.items()}
    assert by_use_l1[False] == [0, 1]
    assert by_use_l1[True] == [2, 3]


def test_linreg_groups_exclude_cholesky_lanes():
    ln = LinearRegression(maxIter=100)
    param_sets = []
    for reg, enet in [(0.1, 0.0), (0.1, 0.5), (0.2, 1.0)]:
        est = ln.copy()
        ln._copy_tpu_params(est)
        est._set_params(regParam=reg, elasticNetParam=enet)
        param_sets.append(dict(est._tpu_params))
    groups = dict(ln._gang_fit_groups(param_sets))
    (idxs,) = groups.values()
    assert idxs == [1, 2]  # the l1 == 0 Cholesky lane stays sequential


# ---------------------------------------------------------------------------
# end-to-end fitMultiple / CV
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("classes", [2, 3])
def test_gang_fitmultiple_matches_sequential(monkeypatch, classes):
    df = _clf_data(seed=5, classes=classes)
    lr = LogisticRegression(maxIter=40, tol=1e-8)
    grid = _grid(lr, [0.01, 0.1, 1.0], [0.0, 0.5])
    monkeypatch.delenv("TPUML_GANG_FIT", raising=False)
    seq = [m for _, m in lr.fitMultiple(df, grid)]
    monkeypatch.setenv("TPUML_GANG_FIT", "auto")
    gang = [m for _, m in lr.fitMultiple(df, grid)]
    for a, b in zip(seq, gang):
        ca, cb = np.asarray(a.coef_), np.asarray(b.coef_)
        assert abs(a.n_iter_ - b.n_iter_) <= 1
        np.testing.assert_allclose(
            cb, ca, rtol=5e-3, atol=1e-5 * max(1.0, np.abs(ca).max())
        )
        assert b._fit_report["gang_lanes"] >= 2
        assert b._fit_report["gang_groups"] == 2
        assert a._fit_report == {}  # sequential models carry no gang report


def test_gang_fitmultiple_linreg(monkeypatch):
    rng = np.random.default_rng(6)
    X = rng.normal(size=(2000, 10))
    y = X @ rng.normal(size=10) + 0.3 * rng.normal(size=2000)
    df = DataFrame({"features": X, "label": y})
    ln = LinearRegression(maxIter=300, tol=1e-10)
    grid = _grid(ln, [0.01, 0.1], [0.5, 1.0])
    monkeypatch.delenv("TPUML_GANG_FIT", raising=False)
    seq = [m for _, m in ln.fitMultiple(df, grid)]
    monkeypatch.setenv("TPUML_GANG_FIT", "auto")
    gang = [m for _, m in ln.fitMultiple(df, grid)]
    for a, b in zip(seq, gang):
        np.testing.assert_allclose(
            np.asarray(b.coefficients),
            np.asarray(a.coefficients),
            rtol=1e-5,
            atol=1e-8,
        )
        assert b._fit_report["gang_lanes"] == 4


def test_gang_budget_clamp_splits_dispatches(monkeypatch):
    df = _clf_data(seed=7)
    lr = LogisticRegression(maxIter=20, tol=1e-6)
    grid = _grid(lr, [0.01, 0.1, 1.0, 10.0], [0.0])
    monkeypatch.setenv("TPUML_GANG_FIT", "auto")
    # budget fits exactly two lanes of this dataset's (n, B, 1) residents
    monkeypatch.setenv(
        "TPUML_GANG_FIT_BUDGET", str(2 * 16.0 * 3008)
    )  # n=3000 padded to 8-device multiple
    counters.reset()
    gang = [m for _, m in lr.fitMultiple(df, grid)]
    assert all(m._fit_report["gang_lanes"] == 2 for m in gang)
    snap = counters.snapshot()
    assert snap["gang_dispatches"] == 2
    assert snap["gang_lanes_total"] == 4


def test_defaults_inert(monkeypatch):
    """Env unset: sequential path, bit-identical across runs, no gang
    counters, no gang report."""
    monkeypatch.delenv("TPUML_GANG_FIT", raising=False)
    df = _clf_data(seed=8)
    lr = LogisticRegression(maxIter=25, tol=1e-7)
    grid = _grid(lr, [0.01, 0.1], [0.0, 0.5])
    counters.reset()
    a = [m for _, m in lr.fitMultiple(df, grid)]
    b = [m for _, m in lr.fitMultiple(df, grid)]
    for x, z in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x.coef_), np.asarray(z.coef_))
        np.testing.assert_array_equal(
            np.asarray(x.intercept_), np.asarray(z.intercept_)
        )
        assert x._fit_report == {}
    snap = counters.snapshot()
    assert snap.get("gang_dispatches", 0) == 0
    assert snap.get("gang_lanes_total", 0) == 0


def test_kfold_ids_matches_kfold():
    df = _clf_data(seed=9, n=500)
    ids = kfold_ids(df.count(), 3, seed=11)
    folds = kfold(df, 3, seed=11)
    for f, (_, val) in enumerate(folds):
        assert val.count() == int(np.sum(ids == f))


def test_gang_cv_matches_sequential(monkeypatch):
    """Fold-masked gang CV vs the materialized per-fold sequential path.
    Tolerance-only: the sequential path reduces over contiguous fold
    subsets while the masked lanes reduce over the full row order (see
    docs/gang_fit.md), so coefficients agree tightly but not bitwise."""
    df = _clf_data(seed=10, n=2400)
    lr = LogisticRegression(maxIter=40, tol=1e-8)
    grid = _grid(lr, [0.01, 0.1], [0.0, 0.5])
    eva = MulticlassClassificationEvaluator(metricName="logLoss")
    monkeypatch.delenv("TPUML_GANG_FIT", raising=False)
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid, evaluator=eva, numFolds=3,
        seed=13, collectSubModels=True,
    )
    m_seq = cv.fit(df)
    monkeypatch.setenv("TPUML_GANG_FIT", "auto")
    m_gang = cv.fit(df)
    np.testing.assert_allclose(
        np.asarray(m_gang.avgMetrics), np.asarray(m_seq.avgMetrics),
        rtol=5e-3, atol=5e-4,
    )
    assert np.argmin(m_seq.avgMetrics) == np.argmin(m_gang.avgMetrics)
    # per-lane models: tight coefficient agreement + gang provenance
    for f in range(3):
        for a, b in zip(m_seq.subModels[f], m_gang.subModels[f]):
            ca, cb = np.asarray(a.coef_), np.asarray(b.coef_)
            np.testing.assert_allclose(
                cb, ca, rtol=2e-2, atol=1e-4 * max(1.0, np.abs(ca).max())
            )
            assert b._fit_report["gang_lanes"] >= 2
            assert b._fit_report["gang_fold"] == f


def test_gang_cv_counters(monkeypatch):
    df = _clf_data(seed=12, n=1200)
    lr = LogisticRegression(maxIter=15, tol=1e-6)
    grid = _grid(lr, [0.01, 0.1], [0.0])
    eva = MulticlassClassificationEvaluator(metricName="accuracy")
    monkeypatch.setenv("TPUML_GANG_FIT", "auto")
    counters.reset()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid, evaluator=eva, numFolds=3,
        seed=1,
    )
    cv.fit(df)
    snap = counters.snapshot()
    # 3 folds × 2 maps = 6 lanes in one static bucket = one dispatch
    assert snap["gang_lanes_total"] >= 6
    assert snap["gang_dispatches"] >= 1
