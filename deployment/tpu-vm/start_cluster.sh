#!/usr/bin/env bash
# Provision a Cloud TPU VM (single host or pod slice) for benchmarking —
# the analog of the reference's dataproc/start_cluster.sh (which creates
# a Dataproc cluster with GPU workers + the spark-rapids plugin).
#
# Required env:
#   PROJECT, ZONE           gcloud project/zone
#   TPU_NAME                name for the TPU VM
# Optional:
#   ACCELERATOR_TYPE        default v5litepod-8 (one host, 8 chips);
#                           v5litepod-16+ provisions a multi-host slice
#   RUNTIME_VERSION         default v2-alpha-tpuv5-lite
set -euo pipefail

: "${PROJECT:?set PROJECT}"
: "${ZONE:?set ZONE}"
: "${TPU_NAME:?set TPU_NAME}"
ACCELERATOR_TYPE="${ACCELERATOR_TYPE:-v5litepod-8}"
RUNTIME_VERSION="${RUNTIME_VERSION:-v2-alpha-tpuv5-lite}"

gcloud compute tpus tpu-vm create "${TPU_NAME}" \
  --project="${PROJECT}" \
  --zone="${ZONE}" \
  --accelerator-type="${ACCELERATOR_TYPE}" \
  --version="${RUNTIME_VERSION}"

echo "TPU VM ${TPU_NAME} (${ACCELERATOR_TYPE}) ready."
echo "Next: ./setup.sh to install the framework on every worker."
