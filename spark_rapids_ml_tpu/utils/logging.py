"""Logger factory (reference: ``/root/reference/python/src/spark_rapids_ml/utils.py:271-288``)."""

from __future__ import annotations

import logging
import sys
from typing import Any, Union

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"
_configured = False


def _ensure_configured() -> None:
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        root = logging.getLogger("spark_rapids_ml_tpu")
        if not root.handlers:
            root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True


def get_logger(cls: Union[type, str, Any], level: int = logging.INFO) -> logging.Logger:
    _ensure_configured()
    if isinstance(cls, str):
        name = cls
    elif isinstance(cls, type):
        name = cls.__name__
    else:
        name = type(cls).__name__
    logger = logging.getLogger(f"spark_rapids_ml_tpu.{name}")
    logger.setLevel(level)
    return logger
