"""Drop-in module alias: ``spark_rapids_ml_tpu.classification`` ≙ reference
``spark_rapids_ml.classification`` (``/root/reference/python/src/spark_rapids_ml/classification.py``)."""

from .models.classification import LogisticRegression, LogisticRegressionModel
from .models.tree import (
    GBTClassificationModel,
    GBTClassifier,
    RandomForestClassificationModel,
    RandomForestClassifier,
)
from .pipeline import OneVsRest, OneVsRestModel  # pyspark.ml.classification layout

__all__ = [
    "GBTClassifier",
    "GBTClassificationModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "OneVsRest",
    "OneVsRestModel",
    "RandomForestClassifier",
    "RandomForestClassificationModel",
]
