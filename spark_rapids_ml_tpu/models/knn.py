"""Exact NearestNeighbors — no Spark ML equivalent; API-parity with the
reference's ``spark_rapids_ml.knn`` (``/root/reference/python/src/spark_rapids_ml/knn.py``).

Contract parity:
* ``fit(item_df)`` only captures the item DataFrame (reference
  ``knn.py:297-317`` — no compute at fit time);
* ``kneighbors(query_df)`` -> ``(item_df_withid, query_df_withid, knn_df)``
  with knn_df columns ``(query_<id>, indices, distances)`` sorted by query
  id (reference ``knn.py:412-467``); euclidean distances, float32;
* ``exactNearestNeighborsJoin(query_df, distCol)`` explodes the knn result
  into one row per (item, query) pair (reference ``knn.py:612-680``; struct
  columns are flattened to ``item_<col>`` / ``query_<col>`` prefixes since
  this DataFrame has no struct type);
* no persistence — ``write``/``read`` raise (reference ``knn.py:334-343``).

The compute path replaces the reference's UCX endpoint exchange with the
``ops/knn_kernels.ring_knn`` ppermute ring.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core import _TpuEstimator, _TpuModel
from ..data.dataframe import DataFrame
from ..params import Params, TypeConverters, _TpuParams, _mk
from ..parallel.mesh import make_mesh, shard_rows
from ..ops.knn_kernels import resolve_knn_topk, ring_knn
from ..runtime import autotune, envspec, telemetry
from ..utils.logging import get_logger

_DEFAULT_ID_COL = "unique_id"


class NearestNeighborsClass:
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_neighbors"}

    @classmethod
    def _param_value_mapping(cls) -> Dict[str, Callable[[Any], Any]]:
        return {}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {"n_neighbors": 5}


class _NearestNeighborsParams(Params):
    k = _mk("k", "number of nearest neighbors", TypeConverters.toInt)
    inputCol = _mk("inputCol", "features column (vector/array)", TypeConverters.toString)
    inputCols = _mk("inputCols", "scalar feature columns", TypeConverters.toListString)
    idCol = _mk("idCol", "row id column", TypeConverters.toString)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(k=5, inputCol="features")

    def getK(self) -> int:
        # _tpu_params is authoritative: users may set either the Spark name
        # ``k`` (synced there by _set_params) or the backend name
        # ``n_neighbors`` (stored only there)
        if getattr(self, "_tpu_params", None) and "n_neighbors" in self._tpu_params:
            return int(self._tpu_params["n_neighbors"])
        return self.getOrDefault("k")

    def setK(self, value: int) -> "_NearestNeighborsParams":
        self._set_params(k=value)  # type: ignore[attr-defined]
        return self

    def setInputCol(self, value: Union[str, List[str]]) -> "_NearestNeighborsParams":
        if isinstance(value, (list, tuple)):
            self._set(inputCols=list(value))
        else:
            self._set(inputCol=value)
        return self

    def setInputCols(self, value: List[str]) -> "_NearestNeighborsParams":
        self._set(inputCols=value)
        return self

    def setIdCol(self, value: str) -> "_NearestNeighborsParams":
        self._set(idCol=value)
        return self

    def getIdCol(self) -> str:
        return (
            self.getOrDefault("idCol") if self.isDefined("idCol") else _DEFAULT_ID_COL
        )

    def _ensureIdCol(self, df: DataFrame) -> DataFrame:
        """Add a monotonically-increasing id column when the user didn't set
        one (reference ``knn.py:135-152``). Multi-process: ids are offset by
        the lower ranks' row counts so they are globally unique."""
        if self.isDefined("idCol"):
            id_col = self.getOrDefault("idCol")
            if id_col not in df:
                raise ValueError(f"idCol {id_col!r} not in DataFrame columns {df.columns}")
            return df
        if _DEFAULT_ID_COL in df:
            return df
        offset = 0
        if jax.process_count() > 1:
            from ..parallel.mesh import allgather_host

            counts = allgather_host(np.asarray([df.count()])).ravel().astype(np.int64)
            offset = int(counts[: jax.process_index()].sum())
        return df.withColumn(
            _DEFAULT_ID_COL, np.arange(offset, offset + df.count(), dtype=np.int64)
        )

    def _resolve_features(self, df: DataFrame) -> np.ndarray:
        from ..core import _resolve_features_f32

        return _resolve_features_f32(self, df)


class NearestNeighbors(NearestNeighborsClass, _TpuEstimator, _NearestNeighborsParams):
    """``NearestNeighbors(k=3).fit(item_df).kneighbors(query_df)`` — exact
    brute-force kNN (reference ``knn.py:154-343``)."""

    def __init__(self, **kwargs: Any) -> None:
        _TpuEstimator.__init__(self)
        _NearestNeighborsParams.__init__(self)
        if kwargs.pop("float32_inputs", True) is False:
            self.logger.warning(
                "This estimator does not support double precision inputs; ignoring"
            )
        self._set_params(**kwargs)

    def fit(self, dataset: DataFrame, params: Optional[Dict[Any, Any]] = None) -> "NearestNeighborsModel":
        if params:
            est = self.copy()
            self._copy_tpu_params(est)
            kw = {p.name if hasattr(p, "name") else p: v for p, v in params.items()}
            est._set_params(**kw)
            return est.fit(dataset)
        # no compute at fit time (reference ``knn.py:297-317``)
        item_df_withid = self._ensureIdCol(dataset)
        model = NearestNeighborsModel(item_df=item_df_withid)
        self._copyValues(model)
        self._copy_tpu_params(model)
        return model

    def _fit(self, dataset: DataFrame) -> "NearestNeighborsModel":
        return self.fit(dataset)

    def _get_tpu_fit_func(self, dataset: DataFrame):  # pragma: no cover
        raise NotImplementedError("NearestNeighbors overrides fit directly")

    def _create_model(self, result: Dict[str, Any]):  # pragma: no cover
        raise NotImplementedError("NearestNeighbors overrides fit directly")

    def write(self) -> Any:
        raise NotImplementedError(
            "NearestNeighbors does not support saving/loading, just re-create the estimator."
        )

    @classmethod
    def read(cls) -> Any:
        raise NotImplementedError(
            "NearestNeighbors does not support saving/loading, just re-create the estimator."
        )


class NearestNeighborsModel(NearestNeighborsClass, _TpuModel, _NearestNeighborsParams):
    """Reference ``knn.py:346-690``. Holds the item DataFrame; ``kneighbors``
    runs the distributed ring search."""

    def __init__(self, item_df: DataFrame, **attrs: Any) -> None:
        _TpuModel.__init__(self, **attrs)
        _NearestNeighborsParams.__init__(self)
        self._item_df_withid = item_df

    # -- core search -------------------------------------------------------
    def kneighbors(
        self, query_df: DataFrame
    ) -> Tuple[DataFrame, DataFrame, DataFrame]:
        from ..parallel.context import ensure_distributed
        from ..parallel.mesh import (
            allgather_host,
            global_row_count,
            local_row_block,
            row_sharding,
        )

        ensure_distributed()  # idempotent (package import already ran it)
        nproc = jax.process_count()
        k = self.getK()
        item_df = self._item_df_withid
        n_items = global_row_count(item_df.count())
        if k > n_items:
            raise ValueError(f"k={k} must be <= number of item rows {n_items}")
        query_df_withid = self._ensureIdCol(query_df)
        id_col = self.getIdCol()

        Xi = self._resolve_features(item_df)
        Xq = self._resolve_features(query_df_withid)
        if Xi.shape[1] != Xq.shape[1]:
            raise ValueError(
                f"item/query dims differ: {Xi.shape[1]} vs {Xq.shape[1]}"
            )

        ids_arr = np.asarray(item_df.column(id_col))
        if nproc > 1 and not np.issubdtype(ids_arr.dtype, np.number):
            # the byte-view id exchange needs a fixed-width viewable dtype:
            # object/str ids are normalized to a unicode width agreed
            # across the process world (empty-string padding slots are
            # never selected — masked rows carry +inf distance in the ring)
            from ..parallel.mesh import unify_string_width

            ids_arr = unify_string_width(ids_arr)

        mesh = make_mesh(self.num_workers)
        Xi_d, mi_d = shard_rows(Xi, mesh)
        Xq_d, _ = shard_rows(Xq, mesh)
        n_item_rows = Xi_d.shape[0]  # global padded
        if nproc > 1:
            # each process provides its block of global padded positions —
            # this is the UCX-partition-ownership analog (``knn.py:573-586``
            # remaps cuML row numbers to user ids the same way)
            local_rows = n_item_rows // nproc
            p = jax.process_index()
            ids_local = np.arange(
                p * local_rows, (p + 1) * local_rows, dtype=np.int32
            )
            ids_d = jax.make_array_from_process_local_data(
                row_sharding(mesh), ids_local, (n_item_rows,)
            )
        else:
            ids_d, _ = shard_rows(np.arange(n_item_rows, dtype=np.int32), mesh)

        d2, idx = ring_knn(
            Xq_d, Xi_d, mi_d, ids_d, mesh=mesh, k=k,
            topk_impl=resolve_knn_topk(),
        )
        nq = Xq.shape[0]
        if nproc > 1:
            # this rank's query rows live in its own addressable shards —
            # no collective needed; map global padded item positions ->
            # user ids via a host allgather of each rank's (padded) ids
            d2 = local_row_block(d2)[:nq]
            idx = local_row_block(idx)[:nq]
            # padded layout preserves the user's id dtype EXACTLY: the
            # allgather moves raw bytes (jax would canonicalize int64 ->
            # int32 without x64); padding slots are never selected (masked
            # rows carry +inf distance in the ring)
            padded_ids = np.zeros((local_rows,), ids_arr.dtype)
            padded_ids[: Xi.shape[0]] = ids_arr
            gathered = allgather_host(np.ascontiguousarray(padded_ids).view(np.uint8))
            item_ids = gathered.reshape(-1).view(ids_arr.dtype)
        else:
            d2 = np.asarray(d2)[:nq]
            idx = np.asarray(idx)[:nq]
            item_ids = ids_arr

        knn_df = self._knn_result_df(query_df_withid, d2, idx, item_ids)
        return item_df, query_df_withid, knn_df

    def exactNearestNeighborsJoin(
        self, query_df: DataFrame, distCol: str = "distCol"
    ) -> DataFrame:
        id_col = self.getIdCol()
        if jax.process_count() > 1:
            # fail fast, before the (expensive) distributed search: every
            # item column must be exchangeable (numeric, str, or bytes).
            # The verdict must be AGREED across ranks — partitions can
            # differ in typing, and one rank raising while another enters
            # the kneighbors collective would hang, not error.
            from ..parallel.mesh import allgather_host, object_string_kind

            probe = self._ensureIdCol(self._item_df_withid)
            local_err = ""
            for c in probe.columns:
                col = np.asarray(probe.column(c))
                if col.dtype.kind == "O":
                    try:
                        object_string_kind(col)
                    except TypeError as e:
                        local_err = f"column {c!r}: {e}"
                        break
            any_err = allgather_host(
                np.asarray([1 if local_err else 0], np.int64)
            ).sum()
            if any_err:
                raise TypeError(
                    "exactNearestNeighborsJoin: non-exchangeable item column "
                    f"on at least one rank ({local_err or 'other rank'})"
                )
        item_df_withid, query_df_withid, knn_df = self.kneighbors(query_df)
        if jax.process_count() > 1:
            # a query's neighbors may be items owned by other ranks. The
            # reference pays a Spark shuffle join here (``knn.py:655-668``);
            # the collective analog is an index-selective exchange: ranks
            # agree on the union of item ids any rank's knn result touches,
            # then gather ONLY those items' rows — host memory
            # O(global unique matches) <= O(nq_global * k), independent of
            # the item-table size (previously O(global items)). Byte-exact
            # + string-safe gathers: a jax-array gather would canonicalize
            # int64/float64 to 32-bit, and str columns ride a width-unified
            # byte view.
            from ..parallel.mesh import allgather_ragged_any

            needed_local = np.unique(np.asarray(knn_df.column("indices")).ravel())
            needed = np.unique(allgather_ragged_any(needed_local))
            local_ids = np.asarray(item_df_withid.column(id_col))
            sel = np.isin(local_ids, needed)
            gathered: Dict[str, Any] = {
                c: allgather_ragged_any(
                    np.asarray(item_df_withid.column(c))[sel]
                )
                for c in item_df_withid.columns
            }
            item_df_withid = DataFrame(gathered)
        k = self.getK()

        query_ids = np.asarray(knn_df.column(f"query_{id_col}"))
        indices = np.asarray(knn_df.column("indices"))      # (nq, k)
        distances = np.asarray(knn_df.column("distances"))  # (nq, k)

        flat_query = np.repeat(query_ids, k)
        flat_item = indices.reshape(-1)
        flat_dist = distances.reshape(-1)

        # join back full item/query rows by id (reference joins struct
        # columns, ``knn.py:655-668``; flattened to prefixed columns here)
        def _positions(ids: np.ndarray, values: np.ndarray) -> np.ndarray:
            order = np.argsort(ids, kind="stable")
            return order[np.searchsorted(ids[order], values)]

        item_rows = _positions(np.asarray(item_df_withid.column(id_col)), flat_item)
        query_rows = _positions(np.asarray(query_df_withid.column(id_col)), flat_query)

        drop_generated = not self.isDefined("idCol")
        data: Dict[str, Any] = {}
        for c in item_df_withid.columns:
            if drop_generated and c == _DEFAULT_ID_COL:
                continue
            data[f"item_{c}"] = np.asarray(item_df_withid.column(c))[item_rows]
        for c in query_df_withid.columns:
            if drop_generated and c == _DEFAULT_ID_COL:
                continue
            data[f"query_{c}"] = np.asarray(query_df_withid.column(c))[query_rows]
        data[distCol] = flat_dist
        return DataFrame(data)

    # -- id mapping + result assembly (shared with the ANN subclass) -------
    def _knn_result_df(
        self,
        query_df_withid: DataFrame,
        d2: np.ndarray,
        idx: np.ndarray,
        item_ids: np.ndarray,
    ) -> DataFrame:
        """Assemble the ``(query_<id>, indices, distances)`` result frame
        from squared distances + global item positions, sorted by query id
        — one definition for the exact ring and the IVF probe search so
        the output contract cannot diverge."""
        id_col = self.getIdCol()
        distances = np.sqrt(np.maximum(d2, 0.0)).astype(np.float32)
        indices = item_ids[np.clip(idx, 0, len(item_ids) - 1)]
        query_ids = np.asarray(query_df_withid.column(id_col))
        order = np.argsort(query_ids, kind="stable")
        return DataFrame(
            {
                f"query_{id_col}": query_ids[order],
                "indices": indices[order],
                "distances": distances[order],
            }
        )

    # -- unsupported surfaces (parity with reference) ----------------------
    def transform(self, dataset: DataFrame) -> DataFrame:
        raise NotImplementedError(
            "NearestNeighborsModel does not provide transform; use kneighbors instead."
        )

    def _get_tpu_transform_func(self, dataset: Optional[DataFrame] = None):  # pragma: no cover
        raise NotImplementedError("use kneighbors")

    def write(self) -> Any:
        raise NotImplementedError(
            "NearestNeighborsModel does not support saving/loading, just re-fit the estimator to re-create a model."
        )

    @classmethod
    def read(cls) -> Any:
        raise NotImplementedError(
            "NearestNeighborsModel does not support saving/loading, just re-fit the estimator to re-create a model."
        )


# ==========================================================================
# Approximate nearest neighbors (IVF-Flat) — reference ``knn.py:693-1170``
# ==========================================================================

_ANN_ALGO_KEYS = frozenset(("nlist", "nprobe", "seed"))


def _algo_params_conv(value: Any) -> Optional[Dict[str, int]]:
    """``algoParams`` converter: None or a {nlist, nprobe, seed} -> int
    mapping (the reference's cuvs ``algo_params`` dict, restricted to the
    keys the TPU IVF-Flat engine understands). Unknown keys raise rather
    than silently doing nothing."""
    if value is None:
        return None
    if not isinstance(value, dict):
        raise TypeError(
            f"algoParams must be a dict or None, got {type(value).__name__}"
        )
    unknown = set(value) - _ANN_ALGO_KEYS
    if unknown:
        raise ValueError(
            f"algoParams keys {sorted(unknown)} not supported; "
            f"accepted: {sorted(_ANN_ALGO_KEYS)}"
        )
    return {k: int(v) for k, v in value.items()}


class ApproximateNearestNeighborsClass(NearestNeighborsClass):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "k": "n_neighbors",
            "algorithm": "algorithm",
            "algoParams": "algoParams",
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {"n_neighbors": 5, "algorithm": "ivfflat", "algoParams": None}


class _ApproximateNearestNeighborsParams(_NearestNeighborsParams):
    algorithm = _mk(
        "algorithm",
        "ANN algorithm (only ivfflat is supported)",
        TypeConverters.toString,
    )
    algoParams = _mk(
        "algoParams",
        "algorithm tuning dict: nlist, nprobe, seed (unset keys fall back "
        "to TPUML_ANN_NLIST/TPUML_ANN_NPROBE, then heuristics)",
        _algo_params_conv,
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(algorithm="ivfflat")

    def getAlgorithm(self) -> str:
        return self.getOrDefault("algorithm")

    def setAlgorithm(self, value: str) -> "_ApproximateNearestNeighborsParams":
        self._set_params(algorithm=value)  # type: ignore[attr-defined]
        return self

    def getAlgoParams(self) -> Optional[Dict[str, int]]:
        return (
            self.getOrDefault("algoParams")
            if self.isDefined("algoParams") and self.isSet("algoParams")
            else None
        )

    def setAlgoParams(
        self, value: Optional[Dict[str, int]]
    ) -> "_ApproximateNearestNeighborsParams":
        self._set_params(algoParams=value)  # type: ignore[attr-defined]
        return self

    def _check_algorithm(self) -> None:
        algo = self.getAlgorithm()
        if algo != "ivfflat":
            raise ValueError(
                f"algorithm={algo!r} is not supported; only 'ivfflat' is "
                "(the reference's cagra/ivfpq backends have no TPU engine)"
            )

    def _resolved_algo_params(self, n_items: int) -> Tuple[int, int, int]:
        """Validated (nlist, nprobe, seed) for an ``n_items`` index:
        ``algoParams`` wins over the ``TPUML_ANN_*`` env overrides, which
        win over the sqrt(n) heuristics. Raises ``ValueError`` on
        out-of-domain values."""
        from ..ops.ivf_kernels import resolve_ann_params

        ap = self.getAlgoParams() or {}
        nlist, nprobe = resolve_ann_params(
            n_items, nlist=ap.get("nlist"), nprobe=ap.get("nprobe")
        )
        return nlist, nprobe, int(ap.get("seed", 0))


class ApproximateNearestNeighbors(
    ApproximateNearestNeighborsClass,
    _TpuEstimator,
    _ApproximateNearestNeighborsParams,
):
    """``ApproximateNearestNeighbors(k=3, algorithm="ivfflat",
    algoParams={"nlist": 64, "nprobe": 8}).fit(item_df)`` — IVF-Flat
    approximate kNN (reference ``knn.py:693-905``). ``kneighbors`` output
    is identical in shape and semantics to the exact estimator's; below
    ``TPUML_ANN_GATE_ROWS`` items the model answers with the exact ring
    (the probe overhead beats nothing at small n — and the result is then
    exact, not approximate)."""

    def __init__(self, **kwargs: Any) -> None:
        _TpuEstimator.__init__(self)
        _ApproximateNearestNeighborsParams.__init__(self)
        if kwargs.pop("float32_inputs", True) is False:
            self.logger.warning(
                "This estimator does not support double precision inputs; ignoring"
            )
        self._set_params(**kwargs)

    def fit(
        self, dataset: DataFrame, params: Optional[Dict[Any, Any]] = None
    ) -> "ApproximateNearestNeighborsModel":
        if params:
            est = self.copy()
            self._copy_tpu_params(est)
            kw = {p.name if hasattr(p, "name") else p: v for p, v in params.items()}
            est._set_params(**kw)
            return est.fit(dataset)
        # fail fast on a bad algorithm/algoParams surface — before any
        # query-time compute (reference validates in the constructor)
        self._check_algorithm()
        _algo_params_conv(self.getAlgoParams())
        item_df_withid = self._ensureIdCol(dataset)
        model = ApproximateNearestNeighborsModel(item_df=item_df_withid)
        self._copyValues(model)
        self._copy_tpu_params(model)
        return model

    def _fit(self, dataset: DataFrame) -> "ApproximateNearestNeighborsModel":
        return self.fit(dataset)

    def _get_tpu_fit_func(self, dataset: DataFrame):  # pragma: no cover
        raise NotImplementedError("ApproximateNearestNeighbors overrides fit directly")

    def _create_model(self, result: Dict[str, Any]):  # pragma: no cover
        raise NotImplementedError("ApproximateNearestNeighbors overrides fit directly")

    def write(self) -> Any:
        raise NotImplementedError(
            "ApproximateNearestNeighbors does not support saving/loading, just re-create the estimator."
        )

    @classmethod
    def read(cls) -> Any:
        raise NotImplementedError(
            "ApproximateNearestNeighbors does not support saving/loading, just re-create the estimator."
        )


class ApproximateNearestNeighborsModel(
    ApproximateNearestNeighborsClass,
    NearestNeighborsModel,
    _ApproximateNearestNeighborsParams,
):
    """Reference ``knn.py:908-1170``. ``kneighbors`` runs the IVF-Flat
    probe search (``ops/ivf_kernels.py``) against an index built lazily on
    first use and cached on the model; below the row gate (or on an
    infeasible shape) it falls back to the exact ring via the parent."""

    def __init__(self, item_df: DataFrame, **attrs: Any) -> None:
        _TpuModel.__init__(self, **attrs)
        _ApproximateNearestNeighborsParams.__init__(self)
        self._item_df_withid = item_df

    def _ivf_index(self, Xi: np.ndarray, nlist: int, seed: int):
        """Build-once index cache: keyed by the parameters that change the
        layout (the item set is frozen at fit)."""
        from ..ops.ivf_kernels import build_ivf_index

        cache = getattr(self, "_ivf_index_cache", None)
        if cache is None:
            cache = self._ivf_index_cache = {}
        key = (nlist, seed, Xi.shape[0])
        if key not in cache:
            cache[key] = build_ivf_index(Xi, nlist=nlist, seed=seed)
        return cache[key]

    def _tuned_nprobe(
        self,
        Xi: np.ndarray,
        Xq: np.ndarray,
        index: Any,
        nlist: int,
        nprobe: int,
        k: int,
        mesh: Any,
    ) -> int:
        """Recall-gated measured nprobe search (``TPUML_AUTOTUNE``).

        Candidates are an octave ladder around the heuristic (measured
        first); fitness is the measured probe-search time on a small
        query sample, and a candidate is INFEASIBLE unless its recall
        against the exact top-k on that sample stays >= 0.95 — the
        documented ANN operating point, so the tuner can never trade
        recall for speed. nlist is pinned: rebuilding the index per
        candidate would blow the probe budget, so the cached value is a
        ``[nlist, nprobe]`` pair only valid at this nlist (the
        ``resolve_ann_params`` consult checks that)."""
        import time as _time

        from ..ops.ivf_kernels import ivf_feasible, ivf_search

        key = autotune.shape_key(n=Xi.shape[0])
        ladder = [nprobe]
        for cand in (
            max(1, nprobe // 2),
            min(nlist, nprobe * 2),
            min(nlist, nprobe * 4),
        ):
            if cand not in ladder:
                ladder.append(cand)
        xs = np.asarray(Xq[: min(128, Xq.shape[0])], np.float32)
        xi = np.asarray(Xi, np.float32)
        d2x = (
            (xs * xs).sum(axis=1)[:, None]
            - 2.0 * (xs @ xi.T)
            + (xi * xi).sum(axis=1)[None, :]
        )
        true_idx = np.argpartition(d2x, kth=k - 1, axis=1)[:, :k]
        true_sets = [set(row.tolist()) for row in true_idx]

        def measure(value: Any) -> Optional[float]:
            cand = int(value[1])
            if not ivf_feasible(xi.shape[0], k, nlist, cand):
                return None
            xq_d, _ = shard_rows(xs, mesh)
            t0 = _time.perf_counter()
            _, idx = ivf_search(
                xq_d, index, k=k, nprobe=cand,
                topk_impl=resolve_knn_topk(), mesh=mesh,
            )
            idx = np.asarray(idx)[: xs.shape[0]]
            dt = _time.perf_counter() - t0
            hits = sum(
                len(true_sets[i] & set(idx[i].tolist()))
                for i in range(xs.shape[0])
            )
            if hits / float(xs.shape[0] * k) < 0.95:
                return None
            return dt

        tuned = autotune.tune(
            "ann_params", key, [[nlist, c] for c in ladder], measure
        )
        if (
            isinstance(tuned, (list, tuple))
            and len(tuned) == 2
            and tuned[0] == nlist
            and 1 <= int(tuned[1]) <= nlist
        ):
            return int(tuned[1])
        return nprobe

    def kneighbors(
        self, query_df: DataFrame
    ) -> Tuple[DataFrame, DataFrame, DataFrame]:
        from ..ops.ivf_kernels import (
            ivf_feasible,
            ivf_search,
            last_search_report,
            resolve_ann_gate_rows,
        )
        from ..parallel.context import ensure_distributed
        from ..parallel.mesh import (
            allgather_ragged_any,
            allgather_ragged_rows,
            global_row_count,
            local_row_block,
        )
        from ..utils.profiling import StageTimer

        ensure_distributed()  # idempotent (package import already ran it)
        self._check_algorithm()
        nproc = jax.process_count()
        k = self.getK()
        item_df = self._item_df_withid
        n_items = global_row_count(item_df.count())
        if k > n_items:
            raise ValueError(f"k={k} must be <= number of item rows {n_items}")
        # resolve + validate FIRST: bad nlist/nprobe must raise even when
        # the gate would route this call to the exact engine anyway
        nlist, nprobe, seed = self._resolved_algo_params(n_items)
        gated = n_items >= resolve_ann_gate_rows()
        feasible = ivf_feasible(n_items, k, nlist, nprobe)
        if not (gated and feasible):
            if gated:
                self.logger.warning(
                    "ivfflat infeasible for shape (n_items=%d, k=%d, "
                    "nlist=%d, nprobe=%d); answering with the exact ring",
                    n_items, k, nlist, nprobe,
                )
            out = super().kneighbors(query_df)
            self._ann_report = {
                "engine": "exact", "nlist": nlist, "nprobe": nprobe,
            }
            return out

        query_df_withid = self._ensureIdCol(query_df)
        id_col = self.getIdCol()
        Xi = self._resolve_features(item_df)
        Xq = self._resolve_features(query_df_withid)
        if Xi.shape[1] != Xq.shape[1]:
            raise ValueError(
                f"item/query dims differ: {Xi.shape[1]} vs {Xq.shape[1]}"
            )
        ids_arr = np.asarray(item_df.column(id_col))
        if nproc > 1:
            # the IVF index is REPLICATED over the global item set (like a
            # broadcast FAISS shard): gather features + ids in rank order
            # so index positions map 1:1 onto the gathered id vector. The
            # ragged byte gather keeps the user's id dtype exact.
            Xi = allgather_ragged_rows(Xi)
            ids_arr = allgather_ragged_any(ids_arr)

        timer = StageTimer("ann.kneighbors")
        with telemetry.span("ann.kneighbors", nlist=nlist, nprobe=nprobe):
            with timer.stage("build"):
                index = self._ivf_index(Xi, nlist, seed)
            mesh = make_mesh(self.num_workers)
            # measured nprobe refinement (TPUML_AUTOTUNE): only when the
            # value came from the heuristic (algoParams/env pins win) and
            # single-process — ranks timing probes independently could
            # disagree on the winner and deadlock the sharded search
            if (
                nproc == 1
                and autotune.active()
                and (self.getAlgoParams() or {}).get("nprobe") is None
                and not envspec.is_set("TPUML_ANN_NPROBE")
            ):
                nprobe = self._tuned_nprobe(
                    Xi, Xq, index, nlist, nprobe, k, mesh
                )
            with timer.stage("search"):
                Xq_d, _ = shard_rows(Xq, mesh)
                d2, idx = ivf_search(
                    Xq_d, index, k=k, nprobe=nprobe,
                    topk_impl=resolve_knn_topk(), mesh=mesh,
                )
                nq = Xq.shape[0]
                if nproc > 1:
                    d2 = local_row_block(d2)[:nq]
                    idx = local_row_block(idx)[:nq]
                else:
                    d2 = np.asarray(d2)[:nq]
                    idx = np.asarray(idx)[:nq]
            knn_df = self._knn_result_df(query_df_withid, d2, idx, ids_arr)
        stages = dict(timer.totals)
        self._ann_report = {
            "engine": "ivf",
            "nlist": nlist,
            "nprobe": nprobe,
            "build_seconds": round(stages.get("build", 0.0), 4),
            "search_seconds": round(stages.get("search", 0.0), 4),
        }
        # list-sharded search provenance (empty on the replicated layout)
        self._ann_report.update(last_search_report())
        return item_df, query_df_withid, knn_df

    def approxSimilarityJoin(
        self, query_df: DataFrame, distCol: str = "distCol"
    ) -> DataFrame:
        """Reference ``knn.py:1098-1170``: explode the ANN result into one
        row per (item, query) pair — identical join semantics to the exact
        estimator's join, riding this model's (approximate) kneighbors."""
        return self.exactNearestNeighborsJoin(query_df, distCol)
