"""TPU009 — inline ``PartitionSpec(...)`` outside ``parallel/``.

Every array layout in the framework is named: ``parallel/layout.py``
owns the canonical specs over the ``(dp, mp)`` mesh (``LAYOUT.rows()``,
``LAYOUT.cols()``, ``LAYOUT.list_blocks()``, ...). An inline
``PartitionSpec("dp")`` in a kernel hard-codes axis names at the call
site, so a mesh-axis rename (or a third axis) becomes a grep-and-pray
sweep instead of a one-file change. Kernels under
``spark_rapids_ml_tpu/`` must take their specs from
``parallel.layout.LAYOUT`` (or ``parallel.mesh`` helpers); only the
``parallel/`` package itself may construct ``PartitionSpec`` directly.
Tests, scripts, and benchmark code are out of scope — they legitimately
build ad-hoc specs to probe layouts.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from .core import Finding, SourceFile, dotted_name

CODE = "TPU009"
NAME = "inline-partition-spec"

_FIXIT = (
    "use a named layout: LAYOUT.rows()/replicated()/cols()/"
    "feature_blocks()/centroid_blocks()/list_blocks() "
    "(from spark_rapids_ml_tpu.parallel.layout import LAYOUT), "
    "or add the spec to parallel/layout.py"
)


def _in_scope(path: str) -> bool:
    return path.startswith("spark_rapids_ml_tpu/") and not path.startswith(
        "spark_rapids_ml_tpu/parallel/"
    )


def _pspec_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to ``jax.sharding.PartitionSpec`` by imports."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
            "jax.sharding",
            "jax.experimental.pjit",
            "jax.interpreters.pxla",
        ):
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    names.add(alias.asname or alias.name)
    return names


def check_file(sf: SourceFile) -> Iterator[Finding]:
    if not _in_scope(sf.path):
        return
    aliases = _pspec_aliases(sf.tree)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if fn is None:
            continue
        hit = (
            fn in aliases
            or fn.endswith(".PartitionSpec")
            or fn == "PartitionSpec"
        )
        if hit:
            yield sf.finding(
                CODE,
                node,
                f"inline PartitionSpec construction ({fn}) outside "
                f"parallel/ hard-codes mesh axis names at the call site",
                _FIXIT,
            )
