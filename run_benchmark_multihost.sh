#!/usr/bin/env bash
# Multi-host benchmark launcher — the honest analog of the reference's
# cluster submission scripts (databricks/run_benchmark.sh:44-135,
# dataproc/, aws-emr/: they create a Spark cluster and spark-submit the
# same benchmark_runner with N workers). Spark-free, a "cluster" is N
# processes joined through the jax.distributed bootstrap this framework
# already uses (parallel/context.py): each process gets the SAME command
# line plus TPUML_COORDINATOR / TPUML_NUM_PROCS / TPUML_PROC_ID.
#
#   ./run_benchmark_multihost.sh <nprocs> [cpu|tpu] [num_rows] [num_cols] [report.csv]
#
# Single-machine form (this script): N local processes, each simulating a
# host with its virtual CPU devices — the topology the 2-process
# distributed tests validate. On a real multi-host TPU pod, run the inner
# command on every host with TPUML_PROC_ID set to the host index and
# TPUML_COORDINATOR pointing at host 0 (exactly how the reference's
# cluster scripts fan out spark-submit).
set -euo pipefail
cd "$(dirname "$0")"

NPROCS="${1:-2}"
PLATFORM="${2:-cpu}"
NUM_ROWS="${3:-5000}"
NUM_COLS="${4:-64}"
REPORT="${5:-}"

PORT=$(python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
EOF
)
COORD="127.0.0.1:${PORT}"

REPORT_ARGS=()
if [ -n "$REPORT" ]; then
    REPORT_ARGS=(--report_path "$REPORT")
fi

# one representative workload per family keeps the multi-host smoke fast;
# pass EXTRA_ALGOS to widen
ALGOS="${EXTRA_ALGOS:-pca kmeans logistic_regression}"

for ALGO in $ALGOS; do
    echo "== multihost($NPROCS) $ALGO =="
    PIDS=()
    for PID_IDX in $(seq 0 $((NPROCS - 1))); do
        TPUML_COORDINATOR="$COORD" TPUML_NUM_PROCS="$NPROCS" \
        TPUML_PROC_ID="$PID_IDX" \
        python benchmark_runner.py "$ALGO" \
            --platform "$PLATFORM" --num_rows "$NUM_ROWS" \
            --num_cols "$NUM_COLS" --num_chips "$NPROCS" --num_runs 1 \
            ${REPORT_ARGS[@]+"${REPORT_ARGS[@]}"} \
            > "/tmp/bench_mh_${ALGO}_${PID_IDX}.log" 2>&1 &
        PIDS+=($!)
    done
    FAIL=0
    for P in "${PIDS[@]}"; do
        wait "$P" || FAIL=1
    done
    if [ "$FAIL" -ne 0 ]; then
        echo "-- $ALGO FAILED; rank logs:"
        tail -20 "/tmp/bench_mh_${ALGO}_"*.log
        exit 1
    fi
    grep -h "fit_s\|total_s\|seconds\|RESULT" "/tmp/bench_mh_${ALGO}_0.log" | tail -3 || true
done
echo "multihost benchmark OK ($NPROCS procs)"
