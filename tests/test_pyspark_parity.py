"""pyspark.ml API-shape parity — runs wherever pyspark is installed.

The reference's load-bearing contract is drop-in ``pyspark.ml``
compatibility, verified against Spark CPU in its test suite
(``/root/reference/python/tests/test_pca.py:353-355`` etc.). This image
ships no pyspark, so these tests *skip* here — but they are real
assertions, not documentation: on any machine with pyspark they compare
our Param surfaces, defaults, and user-facing accessors against the
genuine ``pyspark.ml`` classes, so API drift fails CI there instead of
being self-asserted.
"""

import numpy as np
import pytest

pyspark = pytest.importorskip("pyspark")

from pyspark.ml.classification import (  # noqa: E402
    LogisticRegression as SparkLogReg,
    RandomForestClassifier as SparkRFC,
)
from pyspark.ml.clustering import KMeans as SparkKMeans  # noqa: E402
from pyspark.ml.feature import PCA as SparkPCA  # noqa: E402
from pyspark.ml.regression import (  # noqa: E402
    LinearRegression as SparkLinReg,
    RandomForestRegressor as SparkRFR,
)

from spark_rapids_ml_tpu.classification import (  # noqa: E402
    LogisticRegression,
    RandomForestClassifier,
)
from spark_rapids_ml_tpu.clustering import KMeans  # noqa: E402
from spark_rapids_ml_tpu.feature import PCA  # noqa: E402
from spark_rapids_ml_tpu.regression import (  # noqa: E402
    LinearRegression,
    RandomForestRegressor,
)

PAIRS = [
    (PCA, SparkPCA),
    (KMeans, SparkKMeans),
    (LinearRegression, SparkLinReg),
    (LogisticRegression, SparkLogReg),
    (RandomForestClassifier, SparkRFC),
    (RandomForestRegressor, SparkRFR),
]


@pytest.fixture(scope="module")
def spark():
    """pyspark.ml estimators are JavaEstimator wrappers whose __init__
    requires an active SparkContext — without this fixture the parity
    tests would error at construction on exactly the machines they
    exist for."""
    from pyspark.sql import SparkSession

    session = SparkSession.builder.master("local[1]").getOrCreate()
    yield session
    session.stop()


@pytest.mark.parametrize("ours,theirs", PAIRS, ids=[p[0].__name__ for p in PAIRS])
def test_spark_params_are_accepted(ours, theirs, spark):
    """Every Param pyspark.ml exposes must be accepted by our estimator —
    either mapped to a backend param, accepted-and-ignored, or raising
    the reference's documented unsupported-param ValueError (never an
    unknown-attribute surprise)."""
    spark_est = theirs()
    our_est = ours()
    for p in spark_est.params:
        assert our_est.hasParam(p.name) or p.name in getattr(
            ours, "_param_mapping", lambda: {}
        )(), f"{ours.__name__} silently lacks Spark param {p.name!r}"


@pytest.mark.parametrize("ours,theirs", PAIRS, ids=[p[0].__name__ for p in PAIRS])
def test_spark_defaults_match(ours, theirs, spark):
    """Shared Params must carry Spark's default values (the drop-in
    contract: constructing with no arguments behaves identically)."""
    spark_est = theirs()
    our_est = ours()
    for p in spark_est.params:
        if not (spark_est.hasDefault(p) and our_est.hasParam(p.name)):
            continue
        ours_p = our_est.getParam(p.name)
        if not our_est.hasDefault(ours_p):
            continue
        sv = spark_est.getOrDefault(p)
        ov = our_est.getOrDefault(ours_p)
        if isinstance(sv, float):
            assert ov == pytest.approx(sv), p.name
        else:
            assert ov == sv, p.name


def test_vectorudt_parquet_roundtrip(tmp_path, spark):
    """A Spark-written VectorUDT parquet must load through our DataFrame
    with identical, row-aligned values — the on-disk interop contract
    data/dataframe.py implements."""
    from pyspark.ml.linalg import Vectors

    from spark_rapids_ml_tpu.data import DataFrame

    rows = [(Vectors.dense([float(i), float(i) / 2]), float(i % 2)) for i in range(64)]
    sdf = spark.createDataFrame(rows, ["features", "label"])
    path = str(tmp_path / "vec.parquet")
    sdf.write.parquet(path)
    df = DataFrame.scan_parquet(path)
    X = np.asarray(df.column("features"))  # VectorUDT decodes to (n, 2)
    y = np.asarray(df.column("label"))
    assert X.shape == (64, 2)
    order = np.argsort(X[:, 0])
    np.testing.assert_allclose(X[order, 0], np.arange(64.0))
    # second component and label must ride row-aligned with the first
    np.testing.assert_allclose(X[order, 1], np.arange(64.0) / 2)
    np.testing.assert_allclose(y[order], np.arange(64) % 2)
