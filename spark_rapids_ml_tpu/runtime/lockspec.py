"""Typed catalog of every named lock in ``runtime/`` and ``serving/``.

Single source of truth for the repo's lock hierarchy — the concurrency
analog of :mod:`metricspec` for metric names. Each entry gives a lock a
stable dotted name, a **rank**, and its declared home (module / class /
attribute). The rank is the lock-order discipline: a thread may only
acquire a lock whose rank is *strictly greater* than every lock it
already holds. Two enforcement layers read this catalog:

- ``tpuml_lint`` rule TPU010 (static): nested ``with`` acquisitions in
  one function body must ascend in rank, every lock constructed in
  ``runtime/``/``serving/`` must go through :mod:`runtime.lockwitness`
  with a name declared here, and a cataloged name must be constructed
  in its declared module.
- :mod:`runtime.lockwitness` (runtime, opt-in via
  ``TPUML_LOCK_WITNESS``): checks the same rank discipline on the real
  per-thread acquisition order, across call boundaries the AST pass
  cannot see.

Deliberately stdlib-only (no jax/numpy, no relative imports): the
linter loads this file directly via ``importlib`` without importing the
package, so the hierarchy check runs even where jax does not.

Rank bands (outermost first — the order a request naturally descends):

====  ====================================================derived
10    ops-plane coordinator (owns subsystem refs + thread startup)
20s   lifecycle (swap/canary/drift orchestration)
30s   fit scheduler (queue state, breaker map)
36-47 serving data plane (router fleet, runtime, replicas)
50s   model registry + admission primitives
70s   SLO evaluator state
80s   fault injection, roofline attribution
88+   flight recorder + telemetry registries (innermost leaves —
      every layer above records metrics/spans while holding its own
      lock, so these must never wrap a call back out)
====  ====================================================
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

KINDS = ("lock", "rlock", "condition")


@dataclass(frozen=True)
class LockSpec:
    """One cataloged lock. ``kind`` is lock|rlock|condition."""

    name: str
    rank: int
    kind: str
    # declared home: repo-relative module path, owning class ("" for
    # module level), and attribute name. TPU010 rejects a cataloged
    # name constructed outside its declared module.
    module: str
    cls: str
    attr: str
    doc: str


def _registry(*specs: LockSpec) -> Dict[str, LockSpec]:
    out: Dict[str, LockSpec] = {}
    ranks: Dict[int, str] = {}
    for s in specs:
        assert s.kind in KINDS, f"{s.name}: bad kind {s.kind}"
        assert s.name not in out, f"duplicate registration {s.name}"
        assert s.rank not in ranks, (
            f"{s.name}: rank {s.rank} already held by {ranks[s.rank]} — "
            "ranks are unique so every ordering question has one answer"
        )
        out[s.name] = s
        ranks[s.rank] = s.name
    return out


_RT = "spark_rapids_ml_tpu/runtime"
_SV = "spark_rapids_ml_tpu/serving"

SPEC: Dict[str, LockSpec] = _registry(
    # --- ops-plane coordinator (outermost) --------------------------------
    LockSpec(
        "opsplane.plane", 10, "rlock", f"{_RT}/opsplane.py", "", "_LOCK",
        "Ops-plane module state: server/evaluator startup, tracked "
        "subsystem refs. Outermost — holders start threads and walk "
        "every subsystem's status hooks.",
    ),
    # --- continuous-training lifecycle ------------------------------------
    LockSpec(
        "lifecycle.manager", 20, "rlock",
        f"{_SV}/lifecycle.py", "ModelLifecycle", "_lock",
        "Lifecycle orchestration state (versions, canaries, breakers); "
        "holders call into the scheduler and registry below.",
    ),
    LockSpec(
        "lifecycle.canary", 22, "lock",
        f"{_SV}/lifecycle.py", "_Canary", "lock",
        "One canary's mirrored-pair tally.",
    ),
    LockSpec(
        "lifecycle.drift", 24, "lock",
        f"{_SV}/lifecycle.py", "_DriftState", "lock",
        "One model's drift baseline/window accumulators.",
    ),
    # --- fit scheduler -----------------------------------------------------
    LockSpec(
        "scheduler.state", 30, "lock",
        f"{_RT}/scheduler.py", "FitScheduler", "_lock",
        "Scheduler queue/dispatcher state; also the lock under the "
        "scheduler's Condition (`_cv` shares it).",
    ),
    LockSpec(
        "scheduler.breakers", 32, "lock",
        f"{_RT}/scheduler.py", "FitScheduler", "_block",
        "Per-tenant breaker map; `submit` takes it while holding "
        "`scheduler.state` (the one sanctioned scheduler nesting).",
    ),
    # --- serving data plane ------------------------------------------------
    LockSpec(
        "router.fleet", 36, "lock",
        f"{_SV}/router.py", "Router", "_lock",
        "Router replica table + health/ordering state; replica calls "
        "(which take the locks below) happen outside it.",
    ),
    LockSpec(
        "serving.state", 40, "lock",
        f"{_SV}/runtime.py", "ServingRuntime", "_lock",
        "ServingRuntime buckets/admission/shutdown state.",
    ),
    LockSpec(
        "serving.shadow", 42, "lock",
        f"{_SV}/runtime.py", "_ShadowRoute", "lock",
        "One shadow route's mirrored-tally state.",
    ),
    LockSpec(
        "serving.idle", 44, "condition",
        f"{_SV}/runtime.py", "ServingRuntime", "_idle",
        "Idle/backpressure waiters; briefly taken with `serving.state` "
        "held on the enqueue path.",
    ),
    LockSpec(
        "router.replica_proc", 46, "lock",
        f"{_SV}/router.py", "SubprocessReplica", "_plock",
        "One subprocess replica's lifecycle (spawn/kill/restart).",
    ),
    LockSpec(
        "router.replica_wire", 47, "lock",
        f"{_SV}/router.py", "SubprocessReplica", "_wlock",
        "One subprocess replica's wire protocol (framed writes).",
    ),
    # --- registry + admission ----------------------------------------------
    LockSpec(
        "registry.models", 50, "rlock",
        f"{_SV}/registry.py", "ModelRegistry", "_lock",
        "Model registry entries/budget; warmup and swap stage work run "
        "outside it, metric filing happens under it.",
    ),
    LockSpec(
        "admission.controller", 54, "lock",
        f"{_SV}/admission.py", "AdmissionController", "_lock",
        "Admission controller's per-model breaker map.",
    ),
    LockSpec(
        "admission.ewma", 56, "lock",
        f"{_RT}/admission.py", "ServiceEwma", "_lock",
        "One service-time EWMA accumulator.",
    ),
    LockSpec(
        "admission.breaker", 58, "lock",
        f"{_RT}/admission.py", "CircuitBreaker", "_lock",
        "One circuit breaker's state machine; the state-change callback "
        "(telemetry gauge) fires under it.",
    ),
    # --- SLO evaluator ------------------------------------------------------
    LockSpec(
        "opsplane.slo", 72, "lock",
        f"{_RT}/opsplane.py", "_SloEvaluator", "_state_lock",
        "SLO burn-rate evaluator tick state; holders snapshot the "
        "telemetry registry and may trigger a flight dump.",
    ),
    # --- fault injection + roofline ----------------------------------------
    LockSpec(
        "faults.plan", 80, "lock",
        f"{_RT}/faults.py", "FaultInjector", "_lock",
        "One fault injector's hit counters and pending actions.",
    ),
    LockSpec(
        "faults.cache", 81, "lock",
        f"{_RT}/faults.py", "", "_cache_lock",
        "The process-wide parsed-plan cache.",
    ),
    LockSpec(
        "autotune.file", 82, "lock",
        f"{_RT}/autotune.py", "", "_FILE_LOCK",
        "Serializes the tuning-cache file's read-merge-replace cycle so "
        "concurrent in-process stores cannot drop each other's entries; "
        "held only around local file I/O, never around probes. Below "
        "autotune.cache: the merge's corrupt-file path takes the "
        "warn-once lock while holding this one.",
    ),
    LockSpec(
        "autotune.cache", 83, "lock",
        f"{_RT}/autotune.py", "", "_LOCK",
        "Autotuner in-memory cache + probe bookkeeping; holders may "
        "file autotune metrics (telemetry band below) but never call "
        "back out into dispatch layers.",
    ),
    LockSpec(
        "roofline.peaks", 84, "lock",
        f"{_RT}/roofline.py", "", "_PEAK_LOCK",
        "Resolved per-device peak FLOPs/bandwidth cache.",
    ),
    LockSpec(
        "roofline.state", 85, "lock",
        f"{_RT}/roofline.py", "", "_LOCK",
        "Per-site cost-attribution accumulators.",
    ),
    # --- flight recorder + telemetry (innermost leaves) --------------------
    LockSpec(
        "opsplane.flight", 88, "lock",
        f"{_RT}/opsplane.py", "FlightRecorder", "_lock",
        "Flight-recorder ring. Near-innermost: the recorder is a span "
        "sink, so any thread may reach it while holding its own "
        "subsystem lock mid-span-close.",
    ),
    LockSpec(
        "telemetry.metrics", 90, "rlock",
        f"{_RT}/telemetry.py", "", "_MLOCK",
        "The typed metric registry. Innermost band: every layer above "
        "records metrics while holding its own lock.",
    ),
    LockSpec(
        "telemetry.trace", 91, "lock",
        f"{_RT}/telemetry.py", "", "_RLOCK",
        "Span/trace buffers and sink list.",
    ),
    LockSpec(
        "telemetry.watchdog", 92, "lock",
        f"{_RT}/telemetry.py", "", "_WD_LOCK",
        "Retrace-watchdog per-site compile counts.",
    ),
)


def registered_names() -> Tuple[str, ...]:
    return tuple(SPEC)
