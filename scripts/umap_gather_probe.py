"""Probe: does Mosaic tpu.dynamic_gather (take_along_axis axis=0) compile for
a VMEM-resident embedding table, and at what rate?

Shapes tried: (65536, 8) f32, (8192, 128) f32. Grid >= 2 blocks per the
probe discipline (memory: block-shape violations slip through on 1 block).
"""
import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def probe(n, c, steps=64):
    """Kernel: per grid step, gather the whole (n, c) table by a step-varying
    index array and accumulate. Measures gather of n rows x c lanes."""

    def kernel(idx_ref, emb_ref, out_ref):
        s = pl.program_id(0)

        @pl.when(s == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        # step-dependent index perturbation (cheap, keeps steps distinct)
        idx = (idx_ref[...] + s) % n
        g = jnp.take_along_axis(emb_ref[...], idx, axis=0)
        out_ref[...] += g

    rng = np.random.default_rng(0)
    emb = jnp.asarray(rng.normal(size=(n, c)).astype(np.float32))
    idx = jnp.asarray(
        np.broadcast_to(
            rng.integers(0, n, size=(n, 1)).astype(np.int32), (n, c)
        ).copy()
    )

    fn = pl.pallas_call(
        kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((n, c), lambda s: (0, 0)),
            pl.BlockSpec((n, c), lambda s: (0, 0)),
        ],
        out_specs=pl.BlockSpec((n, c), lambda s: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), jnp.float32),
    )
    jitted = jax.jit(fn)
    out = np.asarray(jitted(idx, emb))  # compile + run
    t0 = time.perf_counter()
    out = np.asarray(jitted((idx + 1) % n, emb))
    t = time.perf_counter() - t0
    per = t / steps
    print(
        f"dynamic_gather ({n},{c}): {per*1e6:.0f} us/gather of {n} rows "
        f"-> {n/per/1e6:.0f}M rows/s, {n*c*4/per/1e9:.1f} GB/s"
    )
    return out


def main():
    for n, c in [(65536, 8), (8192, 128), (65536, 128)]:
        try:
            probe(n, c)
        except Exception as e:
            msg = str(e).split("\n")[0][:160]
            print(f"({n},{c}) FAILED: {type(e).__name__}: {msg}")


if __name__ == "__main__":
    main()
