"""Overload-safe serving: deadlines fail typed before dispatch,
admission sheds (queue bound / unmeetable deadline / open breaker) with
`Overloaded`, the per-model circuit breaker cycles
closed -> open -> half-open -> closed under injected `serve:dispatch`
faults, RESOURCE_EXHAUSTED group dispatch halves at exact shapes
bit-identically, drain/close resolve every outstanding future
(`ShuttingDown`, zero hangs — including a close/predict race storm),
the dispatcher survives unexpected dispatch exceptions, and the whole
admission plane is defaults-inert.
"""

import concurrent.futures
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from spark_rapids_ml_tpu.data import DataFrame
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.runtime import envspec, faults, opsplane, retry, telemetry
from spark_rapids_ml_tpu.serving import (
    AdmissionController,
    CircuitBreaker,
    DeadlineExceeded,
    Overloaded,
    ServingError,
    ServingRuntime,
    ShuttingDown,
)
from spark_rapids_ml_tpu.serving.runtime import _Request

N, D = 400, 10
SEED = 7


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset_telemetry()
    faults.reset_faults()
    yield
    telemetry.reset_telemetry()
    faults.reset_faults()


@pytest.fixture(scope="module")
def pca_model():
    rng = np.random.default_rng(SEED)
    X = rng.normal(size=(N, D)).astype(np.float32)
    return PCA(k=4).fit(DataFrame({"features": X}))


def _q(rng, rows):
    return rng.normal(size=(rows, D)).astype(np.float32)


def _slow_entry(rt, name, delay_s):
    """Wrap a registered entry's transform with a sleep so the
    dispatcher stays busy long enough to build a queue behind it."""
    entry = rt.registry.get(name)
    inner = entry.fn

    def slow(X):
        time.sleep(delay_s)
        return inner(X)

    entry.fn = slow
    return entry


def _wait_until(cond, timeout=30.0, interval=0.002):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# --- unit: breaker + admission ---------------------------------------------


def test_circuit_breaker_cycle():
    b = CircuitBreaker("m", fails=2, cooldown_s=0.05)
    assert b.state_name() == "closed"
    b.record_failure()
    assert b.allow() and b.state_name() == "closed"
    b.record_failure()  # second consecutive: trips
    assert b.state_name() == "open"
    assert not b.allow()
    time.sleep(0.06)
    assert b.allow()  # cooldown elapsed: half-open probe admitted
    assert b.state_name() == "half_open"
    assert not b.allow()  # only ONE probe at a time
    b.record_failure()  # probe failed: straight back to open
    assert b.state_name() == "open"
    time.sleep(0.06)
    assert b.allow()
    b.record_success()  # probe succeeded: closed, counter reset
    assert b.state_name() == "closed"
    b.record_failure()
    b.record_success()  # success resets the consecutive count
    b.record_failure()
    assert b.state_name() == "closed"


def test_circuit_breaker_disabled_is_inert():
    b = CircuitBreaker("m", fails=0, cooldown_s=0.01)
    for _ in range(10):
        b.record_failure()
        assert b.allow()
    assert b.state_name() == "closed"
    assert telemetry.metrics_snapshot().get("serve_breaker_state") is None


def test_admission_queue_full_and_deadline_unmeetable():
    adm = AdmissionController(queue_limit=2, breaker_fails=0)
    adm.admit("m", 1, None)
    with pytest.raises(Overloaded) as ei:
        adm.admit("m", 2, None)
    assert ei.value.reason == "queue_full"
    # prime the service-time model: ~100 ms per single-request batch
    for _ in range(5):
        adm.note_batch("m", 0.1, 1)
    assert 0.05 < adm.service_estimate_s("m") < 0.2
    with pytest.raises(Overloaded) as ei:
        adm.admit("m", 1, 0.01)  # ~100 ms wait vs 10 ms budget
    assert ei.value.reason == "deadline_unmeetable"
    adm.admit("m", 1, 10.0)  # generous deadline passes
    adm.admit("m", 1, None)  # no deadline: never shed on the estimate
    shed = telemetry.metrics_snapshot()["serve_shed_total"]["series"]
    reasons = {s["labels"]["reason"] for s in shed}
    assert reasons == {"queue_full", "deadline_unmeetable"}


def test_admission_defaults_admit_everything():
    adm = AdmissionController()
    assert adm.queue_limit is None and adm.breaker_fails == 0
    for depth in (0, 10, 100_000):
        adm.admit("m", depth, None)
    assert telemetry.metrics_snapshot().get("serve_shed_total") is None


def test_serve_fault_sites_registered():
    entries = faults.parse_fault_spec(
        "serve:admit:0:raise,serve:dispatch:1:oom,serve:transfer:2:preempt"
    )
    assert entries == [
        ("serve:admit", 0, "raise"),
        ("serve:dispatch", 1, "oom"),
        ("serve:transfer", 2, "preempt"),
    ]


def test_retry_giveup_skips_backoff():
    calls = {"n": 0}

    def oom():
        calls["n"] += 1
        raise RuntimeError("RESOURCE_EXHAUSTED: injected")

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        retry.with_retries(
            oom, what="t", retries=5, backoff_ms=1,
            giveup=retry.is_resource_exhausted,
        )
    assert calls["n"] == 1  # no re-attempt at a shape that cannot fit


def test_new_env_vars_validate():
    with pytest.raises(envspec.EnvSpecError, match="must be >= 1"):
        envspec.parse("TPUML_SERVE_QUEUE_LIMIT", "0")
    with pytest.raises(envspec.EnvSpecError, match="must be >"):
        envspec.parse("TPUML_SERVE_DEFAULT_DEADLINE_MS", "0")
    with pytest.raises(envspec.EnvSpecError, match="must be >="):
        envspec.parse("TPUML_SERVE_BREAKER_FAILS", "-1")
    assert envspec.parse("TPUML_SERVE_QUEUE_LIMIT", None) is None
    assert envspec.parse("TPUML_SERVE_DEFAULT_DEADLINE_MS", None) is None
    assert envspec.parse("TPUML_SERVE_BREAKER_FAILS", None) == 0
    assert envspec.parse("TPUML_SERVE_BREAKER_COOLDOWN_MS", None) == 1000.0


def test_packer_is_edf_within_arrival_order(pca_model):
    """Tight deadlines sort to the front of the pack; no-deadline
    requests keep arrival order behind them (stable sort)."""
    rng = np.random.default_rng(3)
    now = time.perf_counter()

    def req(rows, dl):
        return _Request(
            name="m", X=_q(rng, rows), future=concurrent.futures.Future(),
            deadline=None if dl is None else now + dl,
        )

    reqs = [req(2, None), req(2, 5.0), req(2, 1.0), req(2, None)]
    rt = ServingRuntime(batch_window_us=0, max_bucket_rows=64)
    try:
        groups = rt._group(SimpleNamespace(coalesce=True), reqs)
    finally:
        rt.close()
    packed = [r for g in groups for r in g]
    assert [r.deadline for r in packed[:2]] == [
        reqs[2].deadline, reqs[1].deadline
    ]
    assert packed[2:] == [reqs[0], reqs[3]]


# --- deadlines --------------------------------------------------------------


def test_deadline_expires_in_queue(pca_model):
    """A request whose deadline passes while the dispatcher is busy is
    failed typed BEFORE dispatch and counted as a deadline miss."""
    rng = np.random.default_rng(5)
    with ServingRuntime(batch_window_us=0, max_bucket_rows=64) as rt:
        rt.register("pca", pca_model)
        _slow_entry(rt, "pca", 0.15)
        blocker = rt.predict_async("pca", _q(rng, 4))
        assert _wait_until(lambda: rt.queue_depth() == 0)  # picked up
        doomed = rt.predict_async("pca", _q(rng, 4), deadline_ms=30)
        with pytest.raises(DeadlineExceeded, match="expired"):
            doomed.result(60)
        blocker.result(60)  # the no-deadline request is untouched
    snap = telemetry.metrics_snapshot()
    misses = snap["serve_deadline_miss_total"]["series"]
    assert [s["value"] for s in misses] == [1]
    assert snap.get("serve_shed_total") is None  # admitted, not shed


def test_deadline_unmeetable_sheds_at_admission(pca_model):
    """Once the EWMA service model knows a batch takes ~150 ms, a
    10 ms-deadline request arriving behind a queue is shed at enqueue
    (`deadline_unmeetable`), not admitted to fail later."""
    rng = np.random.default_rng(9)
    with ServingRuntime(batch_window_us=0, max_bucket_rows=64) as rt:
        rt.register("pca", pca_model)
        _slow_entry(rt, "pca", 0.15)
        rt.predict("pca", _q(rng, 4), timeout=60)  # primes the EWMA
        blocker = rt.predict_async("pca", _q(rng, 4))
        assert _wait_until(lambda: rt.queue_depth() == 0)
        queued = rt.predict_async("pca", _q(rng, 4))  # depth -> 1
        with pytest.raises(Overloaded) as ei:
            rt.predict_async("pca", _q(rng, 4), deadline_ms=10)
        assert ei.value.reason == "deadline_unmeetable"
        blocker.result(60)
        queued.result(60)
    shed = telemetry.metrics_snapshot()["serve_shed_total"]["series"]
    assert [(s["labels"]["reason"], s["value"]) for s in shed] == [
        ("deadline_unmeetable", 1)
    ]


def test_shed_on_queue_full_with_bounded_admitted_latency(pca_model):
    """With a 2-deep queue bound and a slow model, overflow sheds typed
    `Overloaded(queue_full)` while every ADMITTED request resolves with
    latency bounded by its place in line — overload degrades service
    for the shed tail, never for the admitted head."""
    rng = np.random.default_rng(11)
    delay = 0.1
    with ServingRuntime(
        batch_window_us=0, max_bucket_rows=64, queue_limit=2
    ) as rt:
        rt.register("pca", pca_model)
        _slow_entry(rt, "pca", delay)
        inflight = rt.predict_async("pca", _q(rng, 4))
        assert _wait_until(lambda: rt.queue_depth() == 0)
        admitted = [rt.predict_async("pca", _q(rng, 4)) for _ in range(2)]
        shed = 0
        for _ in range(5):
            try:
                rt.predict_async("pca", _q(rng, 4))
            except Overloaded as e:
                assert e.reason == "queue_full"
                shed += 1
        assert shed >= 4  # at most one slot could have freed mid-loop
        t0 = time.perf_counter()
        for f in [inflight] + admitted:
            f.result(60)
        # 3 outstanding requests, <= 3 slow batches: bounded wait
        assert time.perf_counter() - t0 < 10 * delay
    snap = telemetry.metrics_snapshot()
    assert snap["serve_shed_total"]["series"][0]["labels"] == {
        "model": "pca", "reason": "queue_full"
    }


# --- breaker ----------------------------------------------------------------


def test_breaker_cycle_under_injected_faults(pca_model, monkeypatch):
    """Two consecutive injected dispatch failures open the breaker
    (fast-fail at admission, gauge=2, /readyz not ready); after the
    cooldown one probe is admitted — its success closes the breaker."""
    monkeypatch.setenv(
        "TPUML_FAULT_SPEC",
        "serve:dispatch:0:raise,serve:dispatch:1:raise,"
        "serve:dispatch:2:raise",
    )
    faults.reset_faults()
    rng = np.random.default_rng(13)
    with ServingRuntime(
        batch_window_us=0, max_bucket_rows=64,
        breaker_fails=2, breaker_cooldown_ms=150,
    ) as rt:
        rt.register("pca", pca_model)
        for _ in range(2):  # two consecutive dispatch failures
            with pytest.raises(faults.InjectedFault):
                rt.predict("pca", _q(rng, 4), timeout=60)
        assert rt.breaker_states() == {"pca": "open"}
        with pytest.raises(Overloaded) as ei:
            rt.predict_async("pca", _q(rng, 4))
        assert ei.value.reason == "breaker_open"
        ready, reasons = opsplane._readiness()
        assert not ready and any("breaker_open" in r for r in reasons)
        gauge = telemetry.metrics_snapshot()["serve_breaker_state"]
        assert gauge["series"][0]["value"] == 2

        time.sleep(0.2)  # past the cooldown: next request is the probe
        with pytest.raises(faults.InjectedFault):  # probe eats fault #2
            rt.predict("pca", _q(rng, 4), timeout=60)
        assert rt.breaker_states() == {"pca": "open"}  # probe failed

        time.sleep(0.2)
        out = rt.predict("pca", _q(rng, 4), timeout=60)  # probe succeeds
        assert set(out) and rt.breaker_states() == {"pca": "closed"}
        rt.predict("pca", _q(rng, 4), timeout=60)  # closed: serves fine
    shed = telemetry.metrics_snapshot()["serve_shed_total"]["series"]
    assert [(s["labels"]["reason"], s["value"]) for s in shed] == [
        ("breaker_open", 1)
    ]


# --- RESOURCE_EXHAUSTED halving --------------------------------------------


def test_oom_group_halving_bit_identity(pca_model, monkeypatch):
    """An injected RESOURCE_EXHAUSTED on the coalesced group splits it
    and retries halves at exact shapes; every result stays bit-identical
    to a direct transform of the same rows (the PR-3 halving contract
    at serving granularity)."""
    monkeypatch.setenv("TPUML_FAULT_SPEC", "serve:dispatch:0:oom")
    faults.reset_faults()
    rng = np.random.default_rng(17)
    qs = [_q(rng, s) for s in (3, 5, 4, 6)]
    with ServingRuntime(batch_window_us=30_000, max_bucket_rows=64) as rt:
        rt.register("pca", pca_model)
        futs = [rt.predict_async("pca", q) for q in qs]
        outs = [f.result(120) for f in futs]
    for q, out in zip(qs, outs):
        direct = pca_model.transform(DataFrame({"features": q}))
        for col, served in out.items():
            assert np.array_equal(served, np.asarray(direct[col])), (
                col, q.shape,
            )
    snap = telemetry.metrics_snapshot()
    inj = snap["fault_injections"]["series"]
    assert [(s["labels"]["kind"], s["value"]) for s in inj] == [("oom", 1)]
    # the OOM was absorbed by halving, not surfaced as a dispatch error
    assert snap.get("serve_dispatch_errors_total") is None


# --- drain / close ----------------------------------------------------------


def test_drain_under_load_resolves_every_future(pca_model):
    """drain(): admission stops (typed ShuttingDown + draining shed
    metric, /readyz reports draining), admitted work flushes, and every
    future is resolved — zero hangs."""
    rng = np.random.default_rng(19)
    release = threading.Event()
    with ServingRuntime(batch_window_us=0, max_bucket_rows=64) as rt:
        rt.register("pca", pca_model)
        entry = rt.registry.get("pca")
        inner = entry.fn

        def gated(X):
            release.wait(60)  # holds the dispatcher mid-batch
            return inner(X)

        entry.fn = gated
        futs = [rt.predict_async("pca", _q(rng, 3)) for _ in range(20)]
        report = {}
        drainer = threading.Thread(
            target=lambda: report.update(rt.drain(timeout=120))
        )
        drainer.start()
        assert _wait_until(lambda: rt.is_draining())
        with pytest.raises(ShuttingDown, match="draining"):
            rt.predict_async("pca", _q(rng, 3))
        ready, reasons = opsplane._readiness()
        assert not ready and "serving_draining" in reasons
        release.set()  # un-wedge: drain flushes everything admitted
        drainer.join(120)
        assert not drainer.is_alive()
        assert report == {"drained": True, "aborted": 0}
        done, not_done = concurrent.futures.wait(futs, timeout=60)
        assert not_done == set()
        for f in done:
            assert set(f.result(0))  # all admitted work completed
        with pytest.raises(ShuttingDown):
            rt.predict_async("pca", _q(rng, 3))
    shed = telemetry.metrics_snapshot()["serve_shed_total"]["series"]
    assert {s["labels"]["reason"] for s in shed} == {"draining"}


def test_drain_timeout_aborts_wedged_batch(pca_model):
    """A dispatcher wedged inside a device call cannot make drain hang:
    at the timeout the in-flight futures fail typed ShuttingDown."""
    rng = np.random.default_rng(23)
    release = threading.Event()
    rt = ServingRuntime(batch_window_us=0, max_bucket_rows=64)
    try:
        rt.register("pca", pca_model)
        entry = rt.registry.get("pca")
        inner = entry.fn

        def wedge(X):
            release.wait(30)
            return inner(X)

        entry.fn = wedge
        fut = rt.predict_async("pca", _q(rng, 3))
        report = rt.drain(timeout=0.5)
        assert report == {"drained": False, "aborted": 1}
        with pytest.raises(ShuttingDown):
            fut.result(0)
    finally:
        release.set()
        rt.close()


def test_close_predict_storm_zero_hung_futures(pca_model):
    """The PR-11 race: a request enqueued after the shutdown sentinel
    hung forever. Now a concurrent close()+predict storm leaves zero
    unresolved futures — each is a result or a typed ServingError."""
    rng = np.random.default_rng(29)
    rt = ServingRuntime(batch_window_us=0, max_bucket_rows=64)
    rt.register("pca", pca_model)
    rt.predict("pca", _q(rng, 3), timeout=60)  # warm before the storm
    futs = []
    futs_lock = threading.Lock()
    stop = threading.Event()

    def storm():
        while not stop.is_set():
            try:
                f = rt.predict_async("pca", _q(rng, 3))
            except ServingError:
                continue
            with futs_lock:
                futs.append(f)

    threads = [threading.Thread(target=storm) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    rt.close()
    stop.set()
    for t in threads:
        t.join(60)
        assert not t.is_alive()
    assert futs  # the storm actually got requests in
    done, not_done = concurrent.futures.wait(futs, timeout=60)
    assert not_done == set(), f"{len(not_done)} futures hung"
    for f in done:
        exc = f.exception(0)
        assert exc is None or isinstance(exc, ServingError), exc


# --- crash-proof dispatcher -------------------------------------------------


def test_dispatcher_survives_unexpected_exception(pca_model, monkeypatch):
    """An exception escaping _dispatch fails that batch's futures and
    bumps serve_dispatch_errors_total — the serve thread itself lives
    on and keeps serving (the silent-death satellite)."""
    rng = np.random.default_rng(31)
    with ServingRuntime(batch_window_us=0, max_bucket_rows=64) as rt:
        rt.register("pca", pca_model)
        boom = {"armed": True}
        orig = rt._group

        def group_once(entry, reqs):
            if boom.pop("armed", False):
                raise RuntimeError("telemetry sink exploded")
            return orig(entry, reqs)

        monkeypatch.setattr(rt, "_group", group_once)
        f = rt.predict_async("pca", _q(rng, 3))
        with pytest.raises(RuntimeError, match="sink exploded"):
            f.result(60)
        assert (
            telemetry.counter("serve_dispatch_errors_total").value() == 1
        )
        assert rt.dispatcher_alive()
        out = rt.predict("pca", _q(rng, 3), timeout=60)  # loop survived
        assert set(out)
        ready, reasons = opsplane._readiness()
        assert "serve_dispatcher_dead" not in reasons


def test_readiness_reports_dead_and_stalled_dispatcher(
    pca_model, monkeypatch
):
    """/readyz surfaces a dead serve thread, and a stalled one via the
    loop_heartbeat_ts age once work is queued behind it."""
    with ServingRuntime(batch_window_us=0, max_bucket_rows=64) as rt:
        rt.register("pca", pca_model)
        rt.predict("pca", np.zeros((3, D), np.float32), timeout=60)
        ready, reasons = opsplane._readiness()
        assert ready, reasons
        monkeypatch.setattr(rt, "dispatcher_alive", lambda: False)
        ready, reasons = opsplane._readiness()
        assert not ready and "serve_dispatcher_dead" in reasons
        # stalled: alive but silent past the threshold with queued work
        monkeypatch.setattr(rt, "dispatcher_alive", lambda: True)
        monkeypatch.setattr(rt, "queue_depth", lambda: 3)
        monkeypatch.setattr(
            rt, "heartbeat_age_s",
            lambda: 2 * opsplane.DISPATCHER_STALL_S,
        )
        ready, reasons = opsplane._readiness()
        assert not ready
        assert any("serve_dispatcher_stalled" in r for r in reasons)
    # a cleanly closed runtime is not a fault
    ready, reasons = opsplane._readiness()
    assert ready, reasons


# --- SLO catalog ------------------------------------------------------------


def test_slo_catalog_has_shed_and_deadline_budgets():
    from spark_rapids_ml_tpu.runtime import slo

    shed = slo.BY_NAME["serving_shed_rate"]
    miss = slo.BY_NAME["serving_deadline_miss"]
    assert shed.metric == "serve_shed_total"
    assert shed.measure == "window_delta" and shed.sense == "max"
    assert miss.metric == "serve_deadline_miss_total"
    assert miss.error_budget < shed.error_budget  # misses are worse
    # window_delta over the counter: a shed-free tick measures 0 (no
    # violation), a tick with new sheds violates the 0.0 objective
    snap0 = {"serve_shed_total": {"series": [
        {"labels": {"model": "m", "reason": "queue_full"}, "value": 2.0}
    ]}}
    snap1 = {"serve_shed_total": {"series": [
        {"labels": {"model": "m", "reason": "queue_full"}, "value": 5.0}
    ]}}
    assert slo.measured_value(shed, snap1, snap0) == 3.0
    assert slo.violates(shed, 3.0)
    assert not slo.violates(shed, 0.0)


# --- defaults inert ---------------------------------------------------------


def test_defaults_inert_unbounded_bit_identical(pca_model):
    """No TPUML_SERVE_* env, no deadline: admission admits everything,
    no breaker/shed/deadline metric is ever recorded, the queue is
    unbounded, and served outputs stay bit-identical to a direct
    transform — the pre-admission behavior, exactly."""
    rng = np.random.default_rng(37)
    qs = [_q(rng, s) for s in (3, 17, 1, 2, 33)]
    with ServingRuntime(batch_window_us=20_000, max_bucket_rows=64) as rt:
        assert rt.admission.queue_limit is None
        assert rt.admission.breaker_fails == 0
        rt.register("pca", pca_model)
        futs = [rt.predict_async("pca", q) for q in qs]
        outs = [f.result(120) for f in futs]
    for q, out in zip(qs, outs):
        direct = pca_model.transform(DataFrame({"features": q}))
        for col, served in out.items():
            assert np.array_equal(served, np.asarray(direct[col])), (
                col, q.shape,
            )
    snap = telemetry.metrics_snapshot()
    for metric in (
        "serve_shed_total",
        "serve_deadline_miss_total",
        "serve_breaker_state",
        "serve_dispatch_errors_total",
    ):
        assert snap.get(metric) is None, metric
