"""Regression metrics from mergeable moment vectors.

Everything ``RegressionEvaluator`` supports (rmse/mse/r2/mae/var, Spark
semantics) computes from four length-3 moment vectors over the series
``[label, residual, prediction]``:

    mean = 1/N · Σ x        m2n = Σ (x − mean)²  (centered)
    m2   = Σ x²             l1  = Σ |x|

Two shards merge exactly with the Chan et al. parallel-variance update —
the same sufficient-statistics contract as the reference's
``RegressionMetrics``/``_SummarizerBuffer``
(``/root/reference/python/src/spark_rapids_ml/metrics/RegressionMetrics.py``,
itself a port of Spark's Scala ``SummarizerBuffer``), held here as
vectorized numpy state rather than per-series Python lists.
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np


class RegressionMetrics:
    """Mergeable regression metrics over [label, residual, prediction]."""

    def __init__(
        self,
        n: int,
        mean: np.ndarray,
        m2n: np.ndarray,
        m2: np.ndarray,
        l1: np.ndarray,
    ) -> None:
        self._n = int(n)
        self._mean = np.asarray(mean, np.float64)
        self._m2n = np.asarray(m2n, np.float64)
        self._m2 = np.asarray(m2, np.float64)
        self._l1 = np.asarray(l1, np.float64)

    @classmethod
    def from_predictions(
        cls, labels: np.ndarray, predictions: np.ndarray
    ) -> "RegressionMetrics":
        """Build the moment vectors from a (shard of) predictions — one
        stacked (3, n) pass."""
        y = np.asarray(labels, np.float64)
        p = np.asarray(predictions, np.float64)
        s = np.stack([y, y - p, p])  # (3, n)
        mean = s.mean(axis=1)
        return cls(
            n=y.shape[0],
            mean=mean,
            m2n=((s - mean[:, None]) ** 2).sum(axis=1),
            m2=(s * s).sum(axis=1),
            l1=np.abs(s).sum(axis=1),
        )

    def merge(self, other: "RegressionMetrics") -> "RegressionMetrics":
        """Exact shard merge (Chan et al. parallel variance, weights = 1)."""
        na, nb = self._n, other._n
        n = na + nb
        if n == 0:
            return RegressionMetrics(0, self._mean, self._m2n, self._m2, self._l1)
        delta = other._mean - self._mean
        return RegressionMetrics(
            n=n,
            mean=self._mean + delta * (nb / n),
            m2n=self._m2n + other._m2n + delta * delta * (na * nb / n),
            m2=self._m2 + other._m2,
            l1=self._l1 + other._l1,
        )

    # series indices: 0 = label, 1 = residual, 2 = prediction
    @property
    def mean_squared_error(self) -> float:
        if self._n == 0:
            raise ZeroDivisionError("metrics undefined on an empty dataset")
        return float(self._m2[1] / self._n)

    @property
    def root_mean_squared_error(self) -> float:
        return math.sqrt(self.mean_squared_error)

    @property
    def mean_absolute_error(self) -> float:
        return float(self._l1[1] / self._n)

    def _variance(self) -> np.ndarray:
        """Unbiased sample variance per series (Spark semantics; unit
        weights make the correction denominator n − 1)."""
        denom = self._n - 1
        if denom > 0:
            return np.maximum(self._m2n / denom, 0.0)
        return np.zeros_like(self._m2n)

    def r2(self, through_origin: bool) -> float:
        # fail loudly on degenerate denominators (constant labels / n<=1):
        # a silent nan would make every model-selection comparison False
        ss_err = self._m2[1]
        if through_origin:
            if self._m2[0] == 0.0:
                raise ZeroDivisionError("r2 undefined: sum of squared labels is 0")
            return float(1 - ss_err / self._m2[0])
        ss_tot = self._variance()[0] * (self._n - 1)
        if ss_tot == 0.0:
            raise ZeroDivisionError("r2 undefined: label variance is 0")
        return float(1 - ss_err / ss_tot)

    @property
    def explained_variance(self) -> float:
        # Spark's SS_reg / N with SS_reg = Σŷ² + ȳ²·N − 2·ȳ·mean(ŷ)·N
        ss_reg = (
            self._m2[2]
            + self._mean[0] ** 2 * self._n
            - 2 * self._mean[0] * self._mean[2] * self._n
        )
        return float(ss_reg / self._n)

    def evaluate(self, evaluator: Any) -> float:
        name = evaluator.getMetricName()
        if name == "rmse":
            return self.root_mean_squared_error
        if name == "mse":
            return self.mean_squared_error
        if name == "r2":
            return self.r2(evaluator.getThroughOrigin())
        if name == "mae":
            return self.mean_absolute_error
        if name == "var":
            return self.explained_variance
        raise ValueError(f"Unsupported metric name, found {name}")
