#!/usr/bin/env python
"""Merge per-host telemetry shards into one cluster-wide Perfetto trace.

Every process pointed at a shared ``TPUML_TRACE`` directory writes
rank-tagged shards (``trace-r<rank>-<pid>.json``,
``metrics-r<rank>-<pid>.json`` — see ``runtime/telemetry.py``). This
script folds them:

- **Traces** — one Chrome-trace/Perfetto JSON whose events keep their
  original timestamps but get a per-host ``pid`` remap plus a
  ``process_name`` metadata row (``host0 (pid 1234)``, ...), so the
  Perfetto UI shows one track group per host. Clock domains are
  per-host ``perf_counter`` origins; cross-host alignment is cosmetic
  (all shards start at ts 0), which is exactly what a per-host track
  layout wants.
- **Metrics** — kind-aware fold of the JSON snapshots: counters SUM,
  gauges MAX, histogram count/sum SUM with min/max merged and per-rank
  reservoirs pooled, bounded, and re-quantiled (fleet p99 is measured
  over the pooled samples, not approximated). These are the same rules
  as ``telemetry.merge_metric_snapshots``; the ``dryrun_multichip``
  harness parity-checks the two implementations.

Deliberately stdlib-only and importable without jax or the package
(``dryrun_multichip`` and the tests load it by file path).

Usage:
    python scripts/merge_traces.py <trace_dir> [-o merged.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

_SHARD_RE = re.compile(r"^trace-r(\d+)-(\d+)\.json$")
_METRICS_RE = re.compile(r"^metrics-r(\d+)-(\d+)\.json$")
_FLIGHT_RE = re.compile(r"^flight-r(\d+)-(\d+)\.json$")


def find_shards(trace_dir: str) -> List[Tuple[int, str]]:
    """``[(rank, path), ...]`` for every rank-tagged trace shard, sorted
    by rank then filename (stable when one rank wrote several pids)."""
    out: List[Tuple[int, str]] = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "trace-*.json"))):
        m = _SHARD_RE.match(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    out.sort(key=lambda rp: (rp[0], rp[1]))
    return out


def find_metric_shards(trace_dir: str) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "metrics-*.json"))):
        m = _METRICS_RE.match(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    out.sort(key=lambda rp: (rp[0], rp[1]))
    return out


def find_flight_shards(trace_dir: str) -> List[Tuple[int, str]]:
    """Flight-recorder dumps (``flight-r<rank>-<pid>.json``, written by
    ``runtime/opsplane.py`` on SIGTERM/atexit/SLO-burn) — same naming
    and document shape as trace shards, so they merge the same way."""
    out: List[Tuple[int, str]] = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "flight-*.json"))):
        m = _FLIGHT_RE.match(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    out.sort(key=lambda rp: (rp[0], rp[1]))
    return out


def merge_trace_docs(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold trace documents (each a ``{"traceEvents": [...], "metadata":
    {"process_index": r}}`` shard) into one, remapping every event's
    ``pid`` to the shard's process index so hosts render as separate
    track groups. Shard-local ``process_name`` metadata is replaced by
    a per-host row naming the rank and original pid."""
    events: List[Dict[str, Any]] = []
    hosts: List[int] = []
    for doc in docs:
        rank = int(doc.get("metadata", {}).get("process_index", len(hosts)))
        hosts.append(rank)
        orig_pid: Optional[int] = None
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                orig_pid = ev.get("pid")
                continue  # replaced by the per-host row below
            ev = dict(ev)
            if orig_pid is None:
                orig_pid = ev.get("pid")
            ev["pid"] = rank
            events.append(ev)
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": rank,
                "tid": 0,
                "args": {"name": f"host{rank} (pid {orig_pid})"},
            }
        )
    # metadata rows first, then events in timestamp order — Perfetto
    # accepts any order but deterministic output diffs cleanly
    events.sort(
        key=lambda e: (e.get("ph") != "M", e.get("pid", 0), e.get("ts", 0))
    )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"hosts": sorted(set(hosts)), "merged": True},
    }


#: Mirror of ``telemetry.RESERVOIR_MERGE_CAP`` (this script is
#: stdlib-only and cannot import the package).
RESERVOIR_MERGE_CAP = 4096


def _merged_quantile(ordered: List[float], q: float) -> float:
    """The exact ``_Hist.quantile`` rule over an already-sorted list."""
    q = min(1.0, max(0.0, q))
    return ordered[min(len(ordered) - 1, int(q * (len(ordered) - 1)))]


def _fold_reservoir(samples: List[float]) -> List[float]:
    """Sort concatenated per-rank reservoirs and evenly downsample to
    ``RESERVOIR_MERGE_CAP`` keeping both endpoints — deterministic
    (TPU004: no sampling randomness) and input-order-independent."""
    ordered = sorted(samples)
    n = len(ordered)
    cap = RESERVOIR_MERGE_CAP
    if n <= cap:
        return ordered
    return [ordered[i * (n - 1) // (cap - 1)] for i in range(cap)]


def merge_metric_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Kind-aware fold of ``telemetry.metrics_snapshot`` dicts: counters
    SUM, gauges MAX, histogram count/sum SUM + min/max merged, per-rank
    reservoirs pooled/bounded/re-quantiled (quantiles of reservoir-less
    legacy snapshots are dropped rather than faked). Must stay
    rule-for-rule identical to ``telemetry.merge_metric_snapshots``
    (parity-checked in ``dryrun_multichip``)."""
    merged: Dict[str, Any] = {}
    for snap in snaps:
        for name, entry in snap.items():
            kind = entry.get("kind", "counter")
            slot = merged.setdefault(name, {"kind": kind, "series": {}})
            for series in entry.get("series", []):
                labels = series.get("labels", {})
                key = tuple(sorted(labels.items()))
                have = slot["series"].get(key)
                if kind == "histogram":
                    if have is None:
                        slot["series"][key] = {
                            "labels": labels,
                            "count": series.get("count", 0),
                            "sum": series.get("sum", 0.0),
                            "min": series.get("min"),
                            "max": series.get("max"),
                            "reservoir": list(
                                series.get("reservoir") or []
                            ),
                        }
                    else:
                        have["count"] += series.get("count", 0)
                        have["sum"] += series.get("sum", 0.0)
                        for fld, pick in (("min", min), ("max", max)):
                            v = series.get(fld)
                            if v is not None:
                                have[fld] = (
                                    v if have[fld] is None
                                    else pick(have[fld], v)
                                )
                        have["reservoir"].extend(
                            series.get("reservoir") or []
                        )
                else:
                    value = series.get("value", 0)
                    if have is None:
                        slot["series"][key] = {
                            "labels": labels, "value": value,
                        }
                    elif kind == "gauge":
                        have["value"] = max(have["value"], value)
                    else:
                        have["value"] += value
    out: Dict[str, Any] = {}
    for name, entry in sorted(merged.items()):
        series_out = []
        for k in sorted(entry["series"]):
            s = entry["series"][k]
            if entry["kind"] == "histogram":
                res = _fold_reservoir(s.pop("reservoir"))
                if res:
                    s["reservoir"] = res
                    for q in (0.5, 0.95, 0.99):
                        s[f"p{int(q * 100)}"] = _merged_quantile(res, q)
            series_out.append(s)
        out[name] = {"kind": entry["kind"], "series": series_out}
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_dir", help="TPUML_TRACE directory holding shards")
    ap.add_argument(
        "-o", "--out", default=None,
        help="merged trace path (default: <trace_dir>/merged.json)",
    )
    ap.add_argument(
        "--metrics-out", default=None,
        help="merged metrics path (default: <trace_dir>/merged-metrics.json"
             " when metric shards exist)",
    )
    ap.add_argument(
        "--flight-out", default=None,
        help="merged flight-recorder path (default: "
             "<trace_dir>/merged-flight.json when flight shards exist)",
    )
    args = ap.parse_args(argv)

    shards = find_shards(args.trace_dir)
    flights = find_flight_shards(args.trace_dir)
    if not shards and not flights:
        print(
            f"merge_traces: no trace-r*-*.json or flight-r*-*.json shards "
            f"in {args.trace_dir}",
            file=sys.stderr,
        )
        return 1
    if shards:
        docs = []
        for _rank, path in shards:
            with open(path) as f:
                docs.append(json.load(f))
        merged = merge_trace_docs(docs)
        out = args.out or os.path.join(args.trace_dir, "merged.json")
        with open(out, "w") as f:
            json.dump(merged, f)
        n_ev = sum(1 for e in merged["traceEvents"] if e.get("ph") != "M")
        print(
            f"merge_traces: {len(shards)} shard(s), hosts "
            f"{merged['metadata']['hosts']}, {n_ev} events -> {out}"
        )

    if flights:
        docs = []
        for _rank, path in flights:
            with open(path) as f:
                docs.append(json.load(f))
        fmerged = merge_trace_docs(docs)
        fmerged["metadata"]["flight"] = True
        fout = args.flight_out or os.path.join(
            args.trace_dir, "merged-flight.json"
        )
        with open(fout, "w") as f:
            json.dump(fmerged, f)
        n_ev = sum(1 for e in fmerged["traceEvents"] if e.get("ph") != "M")
        print(
            f"merge_traces: {len(flights)} flight shard(s), hosts "
            f"{fmerged['metadata']['hosts']}, {n_ev} events -> {fout}"
        )

    msnaps = find_metric_shards(args.trace_dir)
    if msnaps:
        snaps = []
        for _rank, path in msnaps:
            with open(path) as f:
                snaps.append(json.load(f))
        mout = args.metrics_out or os.path.join(
            args.trace_dir, "merged-metrics.json"
        )
        with open(mout, "w") as f:
            json.dump(merge_metric_snapshots(snaps), f, indent=2, sort_keys=True)
        print(f"merge_traces: {len(msnaps)} metric shard(s) -> {mout}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
