"""Fitted-model export to scikit-learn for accelerator-free serving.

The reference's ``cpu()`` converts fitted models into stock Spark JVM
models so they can be served by plain Spark ML with the GPU gone — PCA at
``/root/reference/python/src/spark_rapids_ml/feature.py:365-379``, forests
via ``_convert_to_java_trees`` (``tree.py:510-555``) and the tree-JSON
translator (``utils.py:297-467``). Spark-free, the natural serving target
is scikit-learn: every exporter here builds a genuine fitted sklearn
estimator whose ``predict``/``transform`` reproduces this framework's
output on the same inputs, so a model trained on TPU outlives the
accelerator (pickle it, serve it anywhere sklearn runs).

Semantics notes
---------------
* PCA follows the Spark convention (no centering in ``transform``); the
  exported ``sklearn.decomposition.PCA`` gets ``mean_ = 0`` so its
  ``transform`` matches ours exactly. The fitted mean is preserved as
  ``tpu_mean_`` for callers who want sklearn-style centering.
* Forest split semantics differ at equality: our nodes route
  ``x >= thr`` right (``ops/tree_kernels.py:354``), sklearn routes
  ``x <= thr`` left. Exported thresholds are ``nextafter(thr, -inf)`` in
  float32 so the two predicates agree for every float32 input.
* sklearn ≥1.4 stores classifier tree values as per-node *fractions*
  (``tree_.predict`` feeds ``predict_proba`` unnormalized), so exported
  values are normalized class distributions, matching Spark's
  per-tree-normalized vote (``rf_classify``).
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

__all__ = [
    "pca_to_sklearn",
    "kmeans_to_sklearn",
    "linear_regression_to_sklearn",
    "logistic_regression_to_sklearn",
    "random_forest_to_sklearn",
    "random_forest_packed",
    "to_sklearn",
]


def pca_to_sklearn(model: Any):
    """``PCAModel`` -> fitted ``sklearn.decomposition.PCA``."""
    from sklearn.decomposition import PCA

    comps = np.asarray(model.components_, dtype=np.float64)
    k, d = comps.shape
    out = PCA(n_components=k)
    out.components_ = comps
    out.explained_variance_ = np.asarray(model.explained_variance_, np.float64)
    out.explained_variance_ratio_ = np.asarray(
        model.explained_variance_ratio_, np.float64
    )
    out.singular_values_ = np.asarray(model.singular_values_, np.float64)
    # Spark-convention transform does not center; sklearn's subtracts mean_.
    out.mean_ = np.zeros(d, dtype=np.float64)
    out.tpu_mean_ = np.asarray(model.mean_, np.float64)
    out.n_components_ = k
    out.n_features_in_ = d
    out.n_samples_ = max(int(getattr(model, "n_rows_fit_", 0) or 0), k)
    out.noise_variance_ = 0.0
    out.whiten = False
    return out


def kmeans_to_sklearn(model: Any):
    """``KMeansModel`` -> fitted ``sklearn.cluster.KMeans``."""
    from sklearn.cluster import KMeans

    centers = np.asarray(model.cluster_centers_, dtype=np.float64)
    k, d = centers.shape
    out = KMeans(n_clusters=k, n_init=1)
    out.cluster_centers_ = centers
    out.n_features_in_ = d
    out.inertia_ = float(model.trainingCost)
    out.n_iter_ = int(model.numIter)
    out.labels_ = np.zeros(0, dtype=np.int32)
    out._n_threads = 1
    return out


def linear_regression_to_sklearn(model: Any):
    """``LinearRegressionModel`` -> fitted ``sklearn.linear_model.LinearRegression``."""
    from sklearn.linear_model import LinearRegression

    coef = np.asarray(model.coefficients, dtype=np.float64).ravel()
    out = LinearRegression()
    out.coef_ = coef
    out.intercept_ = float(model.intercept)
    out.n_features_in_ = coef.shape[0]
    out.rank_ = coef.shape[0]
    return out


def logistic_regression_to_sklearn(model: Any):
    """``LogisticRegressionModel`` -> fitted ``sklearn.linear_model.LogisticRegression``.

    Binary models export the (1, d) sigmoid parameterization sklearn uses.
    A softmax-parameterized 2-class fit (``family='multinomial'``) is
    collapsed exactly: ``sigmoid(w1-w0, b1-b0)`` equals the 2-way softmax.
    """
    from sklearn.linear_model import LogisticRegression

    coef = np.atleast_2d(np.asarray(model.coef_, dtype=np.float64))
    intercept = np.atleast_1d(np.asarray(model.intercept_, dtype=np.float64))
    n_classes = int(model.numClasses)
    if n_classes == 2 and coef.shape[0] == 2:
        coef = (coef[1] - coef[0])[None, :]
        intercept = np.asarray([intercept[1] - intercept[0]])
    out = LogisticRegression()
    out.coef_ = coef
    out.intercept_ = intercept
    out.classes_ = np.arange(n_classes, dtype=np.float64)
    out.n_features_in_ = coef.shape[1]
    out.n_iter_ = np.asarray([int(getattr(model, "n_iter_", 0))])
    return out


def _compact_tree(
    feat: np.ndarray,       # (M,) int32, heap layout, -1 = leaf
    thr: np.ndarray,        # (M,) float32 raw thresholds (x >= thr -> right)
    counts: np.ndarray,     # (M,) rows behind each node
    values: np.ndarray,     # (M, V) per-node output values (already final)
    impurity: np.ndarray,   # (M,)
    max_depth: int,
    n_features: int,
):
    """Heap-layout node arrays -> a fitted ``sklearn.tree._tree.Tree``.

    Walks the reachable nodes in preorder (sklearn's native layout),
    re-indexing heap children ``2i+1 / 2i+2`` to compact ids.
    """
    from sklearn.tree._tree import NODE_DTYPE, Tree

    order: List[int] = []      # heap index per compact node
    stack = [0]
    while stack:
        i = stack.pop()
        order.append(i)
        if feat[i] >= 0:
            # preorder: left first (LIFO stack -> push right first)
            stack.append(2 * i + 2)
            stack.append(2 * i + 1)
    compact = {h: c for c, h in enumerate(order)}
    n_nodes = len(order)
    V = values.shape[1]

    nodes = np.zeros(n_nodes, dtype=NODE_DTYPE)
    vals = np.zeros((n_nodes, 1, V), dtype=np.float64)
    for c, h in enumerate(order):
        is_split = feat[h] >= 0
        nodes[c]["left_child"] = compact[2 * h + 1] if is_split else -1
        nodes[c]["right_child"] = compact[2 * h + 2] if is_split else -1
        nodes[c]["feature"] = int(feat[h]) if is_split else -2
        # ours: left iff x < thr (f32); sklearn: left iff x <= t. The
        # largest f32 strictly below thr makes the predicates identical
        # for every f32 input.
        nodes[c]["threshold"] = (
            float(np.nextafter(np.float32(thr[h]), np.float32(-np.inf)))
            if is_split
            else -2.0
        )
        nodes[c]["impurity"] = float(impurity[h])
        nodes[c]["n_node_samples"] = int(round(float(counts[h])))
        nodes[c]["weighted_n_node_samples"] = float(counts[h])
        if "missing_go_to_left" in nodes.dtype.names:  # sklearn >= 1.3
            nodes[c]["missing_go_to_left"] = 0
        vals[c, 0, :] = values[h]

    tree = Tree(n_features, np.asarray([V], dtype=np.intp), 1)
    tree.__setstate__(
        {
            "max_depth": int(max_depth),
            "node_count": n_nodes,
            "nodes": nodes,
            "values": vals,
        }
    )
    return tree


def random_forest_to_sklearn(model: Any):
    """``RandomForest{Classification,Regression}Model`` -> fitted sklearn forest."""
    from sklearn.ensemble import RandomForestClassifier, RandomForestRegressor
    from sklearn.tree import DecisionTreeClassifier, DecisionTreeRegressor

    feat = model._features_arr          # (T, M)
    thr = model._thresholds_arr         # (T, M)
    ls = model._leaf_stats_arr          # (T, M, S)
    depth = model._max_depth_built
    d = model.numFeatures
    n_classes = int(model._model_attributes["n_classes"])
    is_cls = n_classes > 0
    T = feat.shape[0]

    if is_cls:
        counts = ls.sum(axis=2)                                       # (T, M)
        tot = np.maximum(counts, 1e-12)[:, :, None]
        values = (ls / tot).astype(np.float64)                        # fractions
        p = ls / tot
        try:
            criterion = model.getOrDefault("impurity")
        except Exception:
            criterion = "gini"
        if criterion == "entropy":
            with np.errstate(divide="ignore", invalid="ignore"):
                impurity = -np.where(p > 0, p * np.log2(p), 0.0).sum(axis=2)
        else:
            impurity = 1.0 - (p * p).sum(axis=2)                      # gini
        forest = RandomForestClassifier(
            n_estimators=T, max_depth=depth, criterion=criterion
        )
        forest.classes_ = np.arange(n_classes, dtype=np.float64)
        forest.n_classes_ = n_classes
        mk = lambda: DecisionTreeClassifier(  # noqa: E731
            max_depth=depth, criterion=criterion
        )
        V = n_classes
    else:
        counts = ls[:, :, 0]
        safe = np.maximum(counts, 1e-12)
        mean = ls[:, :, 1] / safe
        values = mean[:, :, None].astype(np.float64)
        impurity = np.maximum(ls[:, :, 2] / safe - mean * mean, 0.0)  # variance
        forest = RandomForestRegressor(n_estimators=T, max_depth=depth)
        mk = lambda: DecisionTreeRegressor(max_depth=depth)  # noqa: E731
        V = 1

    estimators = []
    for t in range(T):
        est = mk()
        est.tree_ = _compact_tree(
            feat[t], thr[t], counts[t], values[t], impurity[t], depth, d
        )
        est.n_features_in_ = d
        est.n_outputs_ = 1
        if is_cls:
            est.classes_ = forest.classes_
            est.n_classes_ = n_classes
        estimators.append(est)

    forest.estimators_ = estimators
    forest.estimator_ = mk()
    forest.n_features_in_ = d
    forest.n_outputs_ = 1
    return forest


def random_forest_packed(model: Any) -> dict:
    """The FIL-style packed SoA layout of a fitted forest, as plain numpy.

    Returns the exact tensors the lockstep transform engine traverses
    (``ops/tree_kernels.pack_forest``): breadth-first interleaved,
    lane-width padded, hop-split at ``k1``. Packing runs at most once per
    model — the layout is cached on the model object and persisted through
    save/load, so calling this on a freshly loaded round-5+ model does no
    repacking work. Keys:

    * ``feat1``/``thr1`` — ``(T_pad, 2^k1 - 1)`` int32 hop-1 heap levels
      (feature id / bin threshold; ``feat < 0`` marks leaves).
    * ``feat2``/``thr2`` — ``(T_pad * 2^k1, 64)`` int32 hop-2 subtree
      tables, one 64-lane row per hop-1 exit slot (empty ``(0, 64)`` when
      the forest is shallow enough that hop 1 reaches every leaf).
    * ``meta`` — ``{"n_trees", "k1", "k2", "max_depth"}``; ``n_trees`` is
      the REAL tree count, rows beyond it in ``feat1`` are all-leaf
      padding to the sublane multiple of 8.
    """
    from .models.tree import _RandomForestModel

    if not isinstance(model, _RandomForestModel):
        raise TypeError(f"expected a RandomForest model, got {type(model).__name__}")
    if model._model_attributes.get("threshold_bins") is None:
        raise ValueError(
            "model predates bin-space tables (pre-round-5 save); "
            "re-fit to obtain the packed layout"
        )
    pf = model._ensure_packed()
    return {
        "feat1": np.asarray(pf.feat1),
        "thr1": np.asarray(pf.thr1),
        "feat2": np.asarray(pf.feat2),
        "thr2": np.asarray(pf.thr2),
        "meta": {
            "n_trees": pf.n_trees,
            "k1": pf.k1,
            "k2": pf.k2,
            "max_depth": pf.max_depth,
        },
    }


def to_sklearn(model: Any):
    """Dispatch a fitted model to its sklearn exporter by family."""
    # local imports: model modules import this one's helpers lazily
    from .models.classification import LogisticRegressionModel
    from .models.clustering import KMeansModel
    from .models.feature import PCAModel
    from .models.regression import LinearRegressionModel
    from .models.tree import _RandomForestModel

    if isinstance(model, PCAModel):
        return pca_to_sklearn(model)
    if isinstance(model, KMeansModel):
        return kmeans_to_sklearn(model)
    if isinstance(model, LinearRegressionModel):
        return linear_regression_to_sklearn(model)
    if isinstance(model, LogisticRegressionModel):
        return logistic_regression_to_sklearn(model)
    if isinstance(model, _RandomForestModel):
        return random_forest_to_sklearn(model)
    raise TypeError(f"no sklearn exporter for {type(model).__name__}")
