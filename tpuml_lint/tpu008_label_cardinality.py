"""TPU008 — metric label sets must be bounded and declared.

The live ``/metrics`` endpoint (``runtime/opsplane.py``) serializes
every labeled series on each scrape, and series live forever in the
in-process registry. A call site that labels a metric with an unbounded
value set — a per-request id, a user-supplied model name splatted from
a dict — grows the registry without limit and turns the scrape into an
O(cardinality) walk. This rule bounds cardinality *by declaration*:

1. every label key passed at a recording call site
   (``telemetry.counter("x").inc(model=...)`` and the ``gauge``/
   ``histogram`` analogs) must be in the metric's declared
   ``labels=(...)`` tuple in ``runtime/metricspec.py``;
2. ``**dict`` splats at recording call sites are rejected outright —
   a splatted label set cannot be statically bounded.

Only the direct chained form (``telemetry.<kind>("name").<record>()``)
is checked; a metric object stored in a variable first is out of scope
(the repo convention is the chained form, and TPU007 already forces
the name through the catalog). Label *values* remain free — the
declared key set is the cardinality contract, matching how the
Prometheus ecosystem bounds series growth.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Optional, Tuple

from .core import Finding, SourceFile, dotted_name, str_const
from .envinfo import METRICSPEC_RELPATH, load_metricspec

CODE = "TPU008"
NAME = "metric-label-cardinality"

_TELEMETRY_FNS = ("counter", "gauge", "histogram")
_TELEMETRY_RELPATH = "spark_rapids_ml_tpu/runtime/telemetry.py"

# recording method -> keyword params that are values, not labels
_RECORD_FNS = {
    "inc": {"by"},
    "set": {"value"},
    "observe": {"value"},
}


def _metric_call(
    node: ast.AST, sf: SourceFile
) -> Optional[Tuple[str, ast.Call]]:
    """``(metric_name, registry_call)`` when ``node`` is a
    ``telemetry.counter/gauge/histogram("literal")`` call."""
    if not isinstance(node, ast.Call):
        return None
    fn = dotted_name(node.func)
    if fn is None:
        return None
    leaf = fn.rsplit(".", 1)[-1]
    if leaf not in _TELEMETRY_FNS:
        return None
    if not (
        "telemetry" in fn
        or (fn == leaf and sf.path == _TELEMETRY_RELPATH)
    ):
        return None
    name = str_const(node.args[0]) if node.args else None
    if not name:
        return None
    return name, node


def _record_sites(
    sf: SourceFile,
) -> Iterator[Tuple[str, str, ast.Call]]:
    """(metric name, record method, call node) for each chained
    ``telemetry.<kind>("name").<inc|set|observe>(...)`` call."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if func.attr not in _RECORD_FNS:
            continue
        base = _metric_call(func.value, sf)
        if base is None:
            continue
        yield base[0], func.attr, node


def check_project(files: List[SourceFile], repo_root: str) -> Iterator[Finding]:
    spec_relpath = METRICSPEC_RELPATH.replace(os.sep, "/")
    try:
        metricspec = load_metricspec(repo_root)
    except Exception:
        return  # TPU007 reports the unloadable catalog; don't double up
    catalog = metricspec.SPEC

    for sf in files:
        if sf.path == spec_relpath:
            continue
        for name, method, call in _record_sites(sf):
            declared = catalog.get(name)
            if declared is None:
                continue  # undeclared name is TPU007's finding
            allowed = tuple(getattr(declared, "labels", ()) or ())
            value_params = _RECORD_FNS[method]
            for kw in call.keywords:
                if kw.arg is None:
                    yield sf.finding(
                        CODE, call,
                        f"metric {name!r} is recorded with a **splat "
                        f"label set — label cardinality cannot be "
                        f"statically bounded",
                        "pass each label as an explicit keyword from the "
                        f"declared set {allowed!r}",
                    )
                    continue
                if kw.arg in value_params:
                    continue
                if kw.arg not in allowed:
                    yield sf.finding(
                        CODE, call,
                        f"metric {name!r} is recorded with undeclared "
                        f"label {kw.arg!r} (declared labels: "
                        f"{allowed!r})",
                        f"add {kw.arg!r} to the metric's labels=() tuple "
                        f"in {spec_relpath} or drop the label",
                    )
