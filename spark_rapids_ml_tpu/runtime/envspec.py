"""Typed registry of every ``TPUML_*`` environment knob.

Single source of truth for name, type, default, validation domain, and
one-line doc of each variable. All library reads go through
:func:`get` — ``tpuml_lint`` rule TPU001 rejects raw ``os.environ``
access to ``TPUML_*`` names anywhere else, and TPU002 cross-checks this
registry against the committed docs tables (``scripts/gen_config_docs.py``
regenerates them from here).

Deliberately stdlib-only (no jax/numpy, no relative imports): the linter
and the doc generator load this file directly via ``importlib`` without
importing the package, so the doc-drift check runs even where jax does
not.

Parse conventions (uniform across every variable, unlike the ad-hoc
``int(os.environ[...])`` reads this replaced):

- unset or empty string -> the registered default (shell ``FOO= cmd``
  patterns mean "unset", never "parse the empty string");
- bools accept ``1/0, true/false, yes/no, on/off`` case-insensitively;
- choice values are stripped and lowercased before matching;
- any other malformed value raises :class:`EnvSpecError` naming the
  variable, the offending value, and the accepted domain.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Mapping, Optional, Tuple


class EnvSpecError(ValueError):
    """A ``TPUML_*`` variable failed to parse or validate.

    Subclasses ``ValueError`` so pre-registry callers that caught
    ``ValueError`` from bare ``int()`` parses keep working.
    """


@dataclass(frozen=True)
class EnvVar:
    """One registered knob. ``type`` is int|float|bool|str|path|choice."""

    name: str
    type: str
    default: Any
    doc: str
    choices: Optional[Tuple[str, ...]] = None
    minimum: Optional[float] = None  # inclusive lower bound (int/float)
    exclusive_minimum: Optional[float] = None  # strict lower bound
    category: str = "general"
    # docs files (repo-relative) whose prose must mention this variable;
    # TPU002 enforces membership. configuration.md is implied for all.
    also_documented_in: Tuple[str, ...] = ()

    def domain(self) -> str:
        """Human-readable accepted domain, used in error messages."""
        if self.type == "choice":
            assert self.choices is not None
            return "one of " + "|".join(self.choices)
        if self.type == "bool":
            return "a boolean (1/0, true/false, yes/no, on/off)"
        bound = ""
        if self.minimum is not None:
            bound = f" >= {self.minimum:g}"
        elif self.exclusive_minimum is not None:
            bound = f" > {self.exclusive_minimum:g}"
        return {"int": "an integer", "float": "a number"}.get(
            self.type, "a string"
        ) + bound

    def default_repr(self) -> str:
        """Default as shown in the generated docs table."""
        if self.default is None:
            return "unset"
        if self.type == "bool":
            return "1" if self.default else "0"
        if self.type == "float":
            return f"{self.default:g}"
        return str(self.default)


_TRUE = frozenset(("1", "true", "yes", "on"))
_FALSE = frozenset(("0", "false", "no", "off"))


def _registry(*specs: EnvVar) -> Dict[str, EnvVar]:
    out: Dict[str, EnvVar] = {}
    for s in specs:
        assert s.name not in out, f"duplicate registration {s.name}"
        out[s.name] = s
    return out


SPEC: Dict[str, EnvVar] = _registry(
    # --- multi-process rendezvous (parallel/context.py) -------------------
    EnvVar(
        "TPUML_COORDINATOR", "str", None,
        "Address of process 0 (e.g. `10.0.0.1:8476`) for the multi-host "
        "rendezvous; provided by the launcher (the reference's NCCL-uid "
        "allGather bootstrap). Unset = single-process.",
        category="distributed",
    ),
    EnvVar(
        "TPUML_NUM_PROCS", "int", 1,
        "Total process count of the multi-host world; provided by the "
        "launcher together with `TPUML_COORDINATOR`.",
        minimum=1, category="distributed",
    ),
    EnvVar(
        "TPUML_PROC_ID", "int", 0,
        "This process's rank in `[0, TPUML_NUM_PROCS)`; provided by the "
        "launcher together with `TPUML_COORDINATOR`.",
        minimum=0, category="distributed",
    ),
    # --- 2-D mesh / model axis (parallel/mesh.py, parallel/layout.py) -----
    EnvVar(
        "TPUML_MESH_MP", "str", "off",
        "Model-parallel (`mp`) degree of the 2-D `(dp, mp)` device mesh: "
        "`off` (default) keeps the 1-D row-sharded mesh (mp=1, "
        "bit-identical to the pre-2-D behavior), an integer pins the mp "
        "degree (clamped to the device count), `auto` picks the smallest "
        "power-of-two degree whose per-device model-axis shard (Gram "
        "block / centroid block / IVF list shard) fits the HBM budget "
        "(`TPUML_MESH_MP_BUDGET`). See `docs/mesh.md` for axis semantics "
        "and the tolerance contract.",
        category="distributed",
        also_documented_in=("docs/mesh.md",),
    ),
    EnvVar(
        "TPUML_MESH_MP_BUDGET", "float", None,
        "HBM budget in bytes for one device's model-axis shard under "
        "`TPUML_MESH_MP=auto` (default: a quarter of the device's "
        "reported memory, 4 GB fallback) — the same convention as the "
        "gang-fit and tree-batch resolvers.",
        exclusive_minimum=0, category="distributed",
        also_documented_in=("docs/mesh.md",),
    ),
    EnvVar(
        "TPUML_MP_GRAM", "choice", "auto",
        "Per-kernel gate for the feature-sharded (SUMMA-blocked) Gram/"
        "covariance accumulators (PCA, LinearRegression, streamed "
        "suffstats): `auto` shards the d-axis over mp when the mesh has "
        "mp>1 and d divides evenly, `off` pins the replicated 1-D "
        "accumulator on any mesh.",
        choices=("auto", "off"), category="distributed",
        also_documented_in=("docs/mesh.md",),
    ),
    EnvVar(
        "TPUML_MP_KMEANS", "choice", "auto",
        "Per-kernel gate for centroid-sharded KMeans (k-axis over mp, "
        "per-shard partial argmin + global min-reduce): `auto` shards "
        "when the mesh has mp>1 and k >= mp, `off` pins the replicated "
        "centroid table.",
        choices=("auto", "off"), category="distributed",
        also_documented_in=("docs/mesh.md",),
    ),
    EnvVar(
        "TPUML_MP_IVF", "choice", "auto",
        "Per-kernel gate for list-sharded IVF-Flat search (cluster lists "
        "partitioned over mp instead of whole-index replication): `auto` "
        "shards when the mesh has mp>1 and nlist >= mp, `off` pins the "
        "replicated index.",
        choices=("auto", "off"), category="distributed",
        also_documented_in=("docs/mesh.md",),
    ),
    # --- ingest / streaming ----------------------------------------------
    EnvVar(
        "TPUML_STREAM_THRESHOLD_BYTES", "int", None,
        "Dataset size above which fits stream automatically instead of "
        "materializing (default: 60% of one device's reported memory, or "
        "8 GiB when the backend reports none).",
        exclusive_minimum=0, category="streaming",
    ),
    EnvVar(
        "TPUML_STREAM_PREFETCH", "int", 2,
        "Look-ahead depth of the streaming decode thread (host memory: "
        "that many chunk buffers); `0` disables prefetch entirely.",
        minimum=0, category="streaming",
    ),
    EnvVar(
        "TPUML_STREAM_SYNC_EVERY", "int", 4,
        "Host-side backpressure period of streaming loops, in chunks "
        "between blocking device syncs (bounds pending-transfer host "
        "memory); `0` disables the periodic sync.",
        minimum=0, category="streaming",
    ),
    EnvVar(
        "TPUML_WIRE_DTYPE", "choice", "f32",
        "Host->device wire encoding of streamed feature chunks: `f32` "
        "ships the storage dtype unchanged (the default — bit-identical "
        "results); `f16` downcasts on host and upcasts on device; `int8` / "
        "`f8` quantize per chunk column on host (affine / e4m3 scaled) and "
        "dequantize inside the jitted fold step; `auto` probes the first "
        "chunk's quantization error and picks the narrowest encoding "
        "within tolerance (see `docs/streaming_performance.md`). "
        "Infeasible explicit requests warn and fall back.",
        choices=("auto", "f32", "f16", "int8", "f8"), category="streaming",
        also_documented_in=("docs/streaming_performance.md",),
    ),
    EnvVar(
        "TPUML_STREAM_STAGE_DEPTH", "int", 2,
        "Look-ahead depth of the device-staging ring: a background thread "
        "wire-encodes and `device_put`s up to that many chunks ahead of "
        "the fold loop, so decode, host->device transfer, and accumulate "
        "overlap. `0` stages serially on the consumer thread (the "
        "pre-ring behavior). Fold order and results are identical at any "
        "depth (see `docs/streaming_performance.md`).",
        minimum=0, category="streaming",
        also_documented_in=("docs/streaming_performance.md",),
    ),
    EnvVar(
        "TPUML_STREAM_SHARD_FILES", "bool", False,
        "Per-host sharded ingest: each process of a multi-host world "
        "streams only its round-robin subset of the parquet files "
        "(`files[process_index::process_count]`), so N hosts pull N files "
        "concurrently; partial statistics combine through the existing "
        "cross-process allreduce. Identity in a single-process world "
        "(see `docs/streaming_performance.md`).",
        category="streaming",
        also_documented_in=("docs/streaming_performance.md",),
    ),
    # --- native layer -----------------------------------------------------
    EnvVar(
        "TPUML_LIB", "path", None,
        "Path to a prebuilt `libtpuml.so` (skips the cmake build).",
        category="native",
    ),
    EnvVar(
        "TPUML_BLAS_LIB", "path", None,
        "Path to a cblas shared object for the native layer (default: "
        "auto-discovered from the numpy/scipy wheels).",
        category="native",
    ),
    # --- kmeans -----------------------------------------------------------
    EnvVar(
        "TPUML_LANE_PAD", "int", None,
        "KMeans feature lane-padding multiple override (default: 128 on "
        "TPU, off elsewhere). Padding to the lane multiple is HBM-free on "
        "TPU and removes XLA's defensive copy of X around the Lloyd loop "
        "at `d % 128 != 0`.",
        minimum=0, category="kmeans",
    ),
    EnvVar(
        "TPUML_KMEANS_MATMUL_DTYPE", "choice", None,
        "Operand dtype of KMeans' two MXU contractions (f32 accumulation; "
        "the final cost pass always runs f32). Also an estimator kwarg "
        "`matmul_dtype`, which wins over the env.",
        choices=("float32", "bfloat16"), category="kmeans",
    ),
    # --- logreg -----------------------------------------------------------
    EnvVar(
        "TPUML_LOGREG_OBJECTIVE_DTYPE", "choice", "float32",
        "Dtype of the X copy the L-BFGS objective reads (statistics/"
        "params/accumulation stay f32; bf16 halves HBM bytes of the "
        "bandwidth-bound eval). Also an estimator kwarg `objective_dtype`, "
        "which wins over the env.",
        choices=("float32", "bfloat16"), category="logreg",
    ),
    # --- gang fit ---------------------------------------------------------
    EnvVar(
        "TPUML_GANG_FIT", "str", "off",
        "Gang-scheduled batched fitting of a fitMultiple/CrossValidator "
        "grid: `off` (default) keeps the sequential per-param loop, `auto` "
        "fits each static bucket of the grid as one batched device "
        "dispatch over the shared resident X, an integer pins the lane "
        "width (clamped to the HBM budget). Continuous params (regParam, "
        "elasticNetParam, tol) ride traced lane arrays; static params "
        "split dispatch groups (see `docs/gang_fit.md`).",
        category="gang-fit",
        also_documented_in=("docs/gang_fit.md",),
    ),
    EnvVar(
        "TPUML_GANG_FIT_BUDGET", "float", None,
        "HBM budget in bytes for gang-fit per-lane residents (default: a "
        "quarter of the device's reported memory, 4 GB fallback). The lane "
        "width is clamped so the batched objective's `(n, B, K)` "
        "temporaries fit.",
        exclusive_minimum=0, category="gang-fit",
        also_documented_in=("docs/gang_fit.md",),
    ),
    # --- random forest ----------------------------------------------------
    EnvVar(
        "TPUML_RF_ROWS_PER_TREE", "choice", "auto",
        "`all`: every tree sees the full dataset (one `all_gather` of the "
        "uint8 binned matrix); `local`: only its worker's partition (the "
        "reference's exact semantics); `auto`: gather when the gathered "
        "operands fit `TPUML_RF_GATHER_BUDGET_BYTES`.",
        choices=("auto", "all", "local"), category="random-forest",
    ),
    EnvVar(
        "TPUML_RF_GATHER_BUDGET_BYTES", "float", 4e9,
        "Gathered-operand budget for `TPUML_RF_ROWS_PER_TREE=auto`.",
        exclusive_minimum=0, category="random-forest",
    ),
    EnvVar(
        "TPUML_RF_SCATTER_EQ_FLOPS", "float", 5e5,
        "Histogram strategy cost-model constant: per-level crossover "
        "between MXU one-hot matmuls and scatter-adds; re-tune for other "
        "chip generations (see `docs/rf_performance.md`).",
        exclusive_minimum=0, category="random-forest",
    ),
    EnvVar(
        "TPUML_RF_SEL_HBM_BUDGET", "float", None,
        "HBM budget in bytes for the fused-selection histogram path's "
        "residents (default: 3/4 of the device's reported memory, or a "
        "16 GB-class fallback).",
        exclusive_minimum=0, category="random-forest",
    ),
    EnvVar(
        "TPUML_RF_FORCE_STRATEGY", "choice", "auto",
        "Histogram build strategy: `auto` = per-level cost model, "
        "`matmul`/`scatter` pin one formulation, `compact` forces the "
        "node-contiguous Pallas path on every eligible level (falls back "
        "to scatter where its lowering is not).",
        choices=("auto", "matmul", "scatter", "compact"),
        category="random-forest",
    ),
    EnvVar(
        "TPUML_RF_CONTRACT_GATHER", "choice", "auto",
        "Subset-extraction strategy of the fused-selection path: `auto` "
        "(TPU at moderate widths), `on`, or `off`. Rides the static "
        "ForestConfig so it participates in the jit cache key.",
        choices=("auto", "on", "off"), category="random-forest",
    ),
    EnvVar(
        "TPUML_RF_APPLY", "choice", "auto",
        "Forest inference path: `auto` prefers the FIL-style packed-forest "
        "lockstep engine on TPU (bit-identical to both descents), falling "
        "back to the two-hop bin-space descent then the raw-threshold "
        "descent; `legacy`/`bins`/`packed` pin one engine (see "
        "`docs/rf_performance.md`).",
        choices=("auto", "legacy", "bins", "packed"), category="random-forest",
    ),
    EnvVar(
        "TPUML_RF_CHECK_FINITE", "bool", False,
        "Opt-in NaN/Inf screen on every transform batch at the serving "
        "boundary (a full host pass, so off by default). Fit always "
        "rejects non-finite features; without this flag, transform-time "
        "NaN silently routes to bin 0 in the bin-space descents.",
        category="random-forest",
    ),
    EnvVar(
        "TPUML_RF_BYTE_GATHER", "bool", False,
        "Opt-in Pallas lane-shuffle byte gather in the two-hop descent. "
        "Measured 3x slower in situ on the current toolchain "
        "(call-boundary de-fusion; `docs/rf_performance.md` round 5) — a "
        "documented negative result kept for future toolchains.",
        category="random-forest",
    ),
    EnvVar(
        "TPUML_RF_TREE_BATCH", "str", "auto",
        "Trees advanced per batched level dispatch inside one worker: "
        "`auto` sizes the batch to the HBM budget (histogram tile scales "
        "xT), `off` pins the sequential per-tree builder, an integer pins "
        "a batch width (clamped to a divisor of the dispatch group). "
        "Batched and sequential builders are bit-identical at the same "
        "keys (see `docs/rf_performance.md`).",
        category="random-forest",
        also_documented_in=("docs/rf_performance.md",),
    ),
    EnvVar(
        "TPUML_RF_TREE_BATCH_BUDGET", "float", None,
        "HBM budget in bytes for the tree-batched builder's per-level "
        "residents under `TPUML_RF_TREE_BATCH=auto` (default: a quarter "
        "of the fused-selection budget, see `TPUML_RF_SEL_HBM_BUDGET`).",
        exclusive_minimum=0, category="random-forest",
    ),
    # --- gradient boosted trees ------------------------------------------
    EnvVar(
        "TPUML_GBT_ROUND_LOG_EVERY", "int", 0,
        "Log training-loss progress every N boosting rounds during "
        "GBTClassifier/GBTRegressor fit (0 = off; each probe is a host "
        "fetch of the margin vector).",
        minimum=0, category="gbt",
    ),
    # --- knn / umap -------------------------------------------------------
    EnvVar(
        "TPUML_KNN_TOPK", "choice", "auto",
        "Tile top-k implementation: `auto` = fused Pallas distance+top-k "
        "kernel when eligible, else the partial-reduce tile path; "
        "`partial` forces the XLA tile path with `lax.approx_max_k` "
        "(routes around the fused kernel); `sort` forces full `lax.top_k`.",
        choices=("auto", "sort", "partial"), category="knn",
    ),
    EnvVar(
        "TPUML_UMAP_GRAPH", "choice", "auto",
        "UMAP kNN-graph engine: `exact` pins the brute-force sweep; `ivf` "
        "requests the IVF-Flat approximate engine (warns + falls back to "
        "exact when the shape is infeasible); `auto` uses IVF only at or "
        "above `TPUML_ANN_GATE_ROWS` rows, so defaults stay bit-identical "
        "to the exact graph (see `docs/ann_performance.md`).",
        choices=("auto", "exact", "ivf"), category="umap",
        also_documented_in=(
            "docs/ann_performance.md", "docs/umap_performance.md",
        ),
    ),
    EnvVar(
        "TPUML_ANN_NLIST", "int", None,
        "IVF-Flat coarse-quantizer list count override (default: a "
        "`sqrt(n_rows)`-scaled heuristic). Applies to the "
        "`ApproximateNearestNeighbors` estimator (where `algoParams` wins "
        "over the env) and the `TPUML_UMAP_GRAPH=ivf` graph stage.",
        minimum=2, category="knn",
        also_documented_in=("docs/ann_performance.md",),
    ),
    EnvVar(
        "TPUML_ANN_NPROBE", "int", None,
        "IVF-Flat probe count override — lists scanned per query (default: "
        "`max(6, nlist/8)`, a ~12%-of-lists scan fraction). Recall/throughput "
        "knob; `algoParams` wins over the env on the estimator.",
        minimum=1, category="knn",
        also_documented_in=("docs/ann_performance.md",),
    ),
    EnvVar(
        "TPUML_ANN_GATE_ROWS", "int", 131072,
        "Row count at which `auto` graph/ANN dispatch starts preferring "
        "the IVF engine over the exact sweep (below it the index build + "
        "probe overhead beats nothing). Tests lower it to force the IVF "
        "path on small fixtures.",
        minimum=1, category="knn",
        also_documented_in=("docs/ann_performance.md",),
    ),
    EnvVar(
        "TPUML_UMAP_OPT", "choice", "auto",
        "UMAP SGD engine for fit and the transform refine pass: `auto` "
        "prefers the VMEM-resident Pallas engine when the lowering probe "
        "accepts the config, falling back to the jitted XLA epoch loop; "
        "`pallas` forces the kernel where eligible (warns + falls back "
        "when not); `xla` pins the epoch loop (see "
        "`docs/umap_performance.md`).",
        choices=("auto", "pallas", "xla"), category="umap",
    ),
    # --- serving (docs/serving.md) ----------------------------------------
    EnvVar(
        "TPUML_SERVE_BATCH_WINDOW_US", "int", 2000,
        "Micro-batching coalesce window in microseconds: after the first "
        "request of a batch arrives, the dispatcher keeps draining the "
        "queue for this long before padding and launching, trading p50 "
        "latency for batch fill. `0` dispatches every drain immediately "
        "(still coalescing whatever is already queued). Only read by an "
        "explicitly constructed `serving.ServingRuntime` — no serving "
        "thread or file exists otherwise.",
        minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_SERVE_MAX_BUCKET_ROWS", "int", 2048,
        "Largest padded request-batch bucket, in rows. Coalesced rows "
        "are padded up to the next power of two and capped here, so the "
        "compiled-shape set per model is at most "
        "`log2(max_bucket_rows) - 2` programs; larger coalesced batches "
        "split across buckets. Rounded down to a power of two (>= 8).",
        minimum=8, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_SERVE_HBM_BUDGET", "float", None,
        "Device-memory budget in bytes for the serving model registry's "
        "resident buffers (packed forests, projection/coefficient "
        "matrices, UMAP tables + IVF indexes). Loading past the budget "
        "evicts least-recently-used models first; a single model larger "
        "than the budget is rejected. Unset = no eviction. The running "
        "total is filed under the `serve_registry` site of the "
        "`hbm_budget_bytes`/`hbm_live_bytes` gauges when tracing is on.",
        exclusive_minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_SERVE_WARMUP", "bool", True,
        "Eager per-bucket warmup at registry load: compile every padded "
        "bucket shape of a model's transform program before the first "
        "request, so steady-state serving never pays a compile (the "
        "`retrace_storms == 0` contract). `0` warms lazily instead — "
        "the first request at each bucket runs under a per-bucket "
        "warmup span and eats the compile.",
        category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_SERVE_DEFAULT_DEADLINE_MS", "float", None,
        "Default per-request deadline in milliseconds for "
        "`ServingRuntime.predict(..., deadline_ms=)` callers that pass "
        "none. A request whose deadline expires while queued is failed "
        "with a typed `DeadlineExceeded` *before* padding/dispatch, and "
        "admission sheds (`deadline_unmeetable`) when the estimated "
        "wait already exceeds the deadline. Unset = no deadline: "
        "requests wait indefinitely, exactly the pre-deadline behavior.",
        exclusive_minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_SERVE_QUEUE_LIMIT", "int", None,
        "Bound on queued (admitted, not yet dispatched) serving "
        "requests. Enqueues past the bound are rejected with a typed "
        "`Overloaded` (counted on `serve_shed_total{reason=queue_full}`)"
        " instead of growing the queue without limit. Unset = unbounded "
        "queue, the pre-admission behavior.",
        minimum=1, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_SERVE_BREAKER_FAILS", "int", 0,
        "Consecutive dispatch failures that trip a model's circuit "
        "breaker from closed to open; while open, requests for that "
        "model fast-fail at admission (`serve_shed_total{reason="
        "breaker_open}`) and `/readyz` reports 503. `0` (default) "
        "disables the breaker entirely.",
        minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_SERVE_BREAKER_COOLDOWN_MS", "float", 1000.0,
        "How long an open circuit breaker blocks before moving to "
        "half-open and admitting a single probe request; the probe's "
        "outcome closes (success) or re-opens (failure) the breaker. "
        "Only read when `TPUML_SERVE_BREAKER_FAILS` > 0.",
        exclusive_minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    # --- pod-scale serving router (serving/router.py, docs/serving.md) ----
    EnvVar(
        "TPUML_ROUTER_REPLICAS", "int", 2,
        "Default replica count for a `serving.Router()` constructed "
        "without an explicit replica list: the router builds this many "
        "in-process loopback `ServingRuntime` replicas (ranks 0..N-1). "
        "Only read by an explicitly constructed router — no router "
        "thread, replica, or metric series exists otherwise.",
        minimum=1, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_ROUTER_POLICY", "choice", "p2c",
        "Replica-picking policy of the serving router: `p2c` (default) "
        "scores two rotating candidates by EWMA-estimated wait and "
        "queue depth and takes the better (power-of-two-choices — "
        "near-least-loaded at O(2) probes); `round_robin` ignores load; "
        "`least_loaded` scores every replica on every request. All "
        "policies route around breaker-open and unhealthy replicas.",
        choices=("p2c", "round_robin", "least_loaded"),
        category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_ROUTER_BREAKER_FAILS", "int", 3,
        "Consecutive *dispatch-fault* failures (not typed sheds) that "
        "trip a replica's router-side circuit breaker; while open the "
        "replica is routed around, not queued behind, and re-probed "
        "after `TPUML_ROUTER_BREAKER_COOLDOWN_MS`. `0` disables the "
        "router breakers.",
        minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_ROUTER_BREAKER_COOLDOWN_MS", "float", 1000.0,
        "How long an open router-side replica breaker blocks before "
        "moving to half-open and admitting a single probe request. "
        "Only read when `TPUML_ROUTER_BREAKER_FAILS` > 0.",
        exclusive_minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_ROUTER_REROUTES", "int", 1,
        "How many *additional* replicas the router tries when the "
        "picked replica sheds at admission (queue full, deadline "
        "unmeetable, draining). `0` = no rerouting: the first pick's "
        "shed is the caller's shed.",
        minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_REPLICA_RANK", "int", None,
        "Replica rank of a subprocess serving worker "
        "(`serving/_replica_worker.py`); set by the parent "
        "`SubprocessReplica` transport, never by hand. The worker's "
        "runtime rank-stamps its warmup spans and residency reports "
        "with this value.",
        minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    # --- continuous-training lifecycle (serving/lifecycle.py) -------------
    EnvVar(
        "TPUML_LIFECYCLE_REFRESH_MS", "float", 300000.0,
        "Default period between `RefreshDriver` re-fit cycles in "
        "milliseconds (5 minutes). Only read by an explicitly "
        "constructed driver — no driver object means no refresh "
        "thread, no scheduled fits, no metric series.",
        exclusive_minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_LIFECYCLE_DRIFT_WINDOW", "int", 256,
        "Served output rows accumulated per drift-scoring window: the "
        "first full window freezes the reference histogram, every "
        "later one scores a PSI observation into `serve_drift_score`. "
        "Smaller windows detect faster but are noisier.",
        minimum=16, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_LIFECYCLE_DRIFT_BINS", "int", 16,
        "Histogram bins of the drift reference, placed at the first "
        "window's quantiles (equal-mass, so every bin starts at "
        "1/bins probability and the PSI epsilon floor is never the "
        "signal).",
        minimum=4, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_CANARY_FRACTION", "float", 0.125,
        "Fraction of a canaried model's admitted traffic mirrored to "
        "the candidate (deterministic request-counter picking, no "
        "RNG). Callers always receive the live version's output; the "
        "mirror only feeds scoring.",
        exclusive_minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_CANARY_MIN_REQUESTS", "int", 32,
        "Mirrored (live, shadow) pairs a canary must score before the "
        "promote-or-rollback verdict; an SLO-burn alert rolls back "
        "immediately without waiting for this count.",
        minimum=1, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_CANARY_MIN_SCORE", "float", 0.99,
        "Minimum shadow-vs-live agreement score (r2 for continuous "
        "outputs, accuracy for integral labels — scored through "
        "`evaluation.prediction_agreement`) for a canary to promote; "
        "anything under rolls back and opens the version breaker.",
        category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    EnvVar(
        "TPUML_CANARY_COOLDOWN_MS", "float", 60000.0,
        "How long a model's version breaker stays open after a canary "
        "rollback: further swap/canary attempts for that name raise a "
        "typed error until the cooldown passes (half-open then admits "
        "one probe attempt).",
        exclusive_minimum=0, category="serving",
        also_documented_in=("docs/serving.md",),
    ),
    # --- fit scheduler (docs/scheduler.md) --------------------------------
    EnvVar(
        "TPUML_SCHED_QUEUE_LIMIT", "int", None,
        "Bound on queued (admitted, not yet dispatched) fit jobs in a "
        "`runtime.FitScheduler`. Submits past the bound are rejected "
        "with a typed `Overloaded` (counted on "
        "`sched_shed_total{reason=queue_full}`) instead of growing the "
        "queue without limit. Unset = unbounded queue. Only read by an "
        "explicitly constructed scheduler — no thread or metric series "
        "exists otherwise.",
        minimum=1, category="scheduler",
        also_documented_in=("docs/scheduler.md",),
    ),
    EnvVar(
        "TPUML_SCHED_QUANTUM_MS", "float", None,
        "Device quantum for scheduled fits, in milliseconds. A fit "
        "whose quantum expires checkpoints at its next iteration "
        "boundary (via the `FitCheckpointer`, so `TPUML_CKPT_DIR` must "
        "be set for preemption to engage), yields the device, and is "
        "re-queued; the resumed dispatch continues from the committed "
        "iteration with the same-seed parity the segmented solvers "
        "guarantee. Unset = fits run to completion once dispatched.",
        exclusive_minimum=0, category="scheduler",
        also_documented_in=("docs/scheduler.md",),
    ),
    EnvVar(
        "TPUML_SCHED_BREAKER_FAILS", "int", 0,
        "Consecutive fit failures that trip a tenant's circuit breaker "
        "from closed to open; while open, that tenant's submits "
        "fast-fail at admission (`sched_shed_total{reason="
        "breaker_open}`). `0` (default) disables the breaker entirely.",
        minimum=0, category="scheduler",
        also_documented_in=("docs/scheduler.md",),
    ),
    EnvVar(
        "TPUML_SCHED_BREAKER_COOLDOWN_MS", "float", 1000.0,
        "How long an open per-tenant breaker blocks before moving to "
        "half-open and admitting a single probe fit; the probe's "
        "outcome closes (success) or re-opens (failure) the breaker. "
        "Only read when `TPUML_SCHED_BREAKER_FAILS` > 0.",
        exclusive_minimum=0, category="scheduler",
        also_documented_in=("docs/scheduler.md",),
    ),
    EnvVar(
        "TPUML_SCHED_AGING_MS", "float", 10000.0,
        "Aging horizon for deadline-free fit jobs: a job with no "
        "deadline is ordered as if due `aging_ms` after submit, so "
        "EDF ordering (and gang-bucket packing built on it) can never "
        "starve it behind a stream of deadline-bearing arrivals.",
        exclusive_minimum=0, category="scheduler",
        also_documented_in=("docs/scheduler.md",),
    ),
    EnvVar(
        "TPUML_SCHED_DEFAULT_DEADLINE_MS", "float", None,
        "Default per-job deadline in milliseconds for "
        "`FitScheduler.submit(..., deadline_ms=)` callers that pass "
        "none. A job whose deadline expires while queued is failed "
        "with a typed `DeadlineExceeded` before dispatch, and "
        "admission sheds (`deadline_unmeetable`) when the EWMA fit-"
        "time estimate says the deadline cannot be met. Unset = no "
        "deadline: jobs wait indefinitely.",
        exclusive_minimum=0, category="scheduler",
        also_documented_in=("docs/scheduler.md",),
    ),
    # --- CI / notebooks ---------------------------------------------------
    EnvVar(
        "TPUML_NB_CPU", "bool", False,
        "Pin the generated notebooks to the CPU backend when executing "
        "headless (exported by `ci/run_notebooks.py`); unset = default "
        "backend, i.e. the TPU.",
        category="ci",
    ),
    # --- resilience (docs/fault_tolerance.md) -----------------------------
    EnvVar(
        "TPUML_CKPT_DIR", "path", None,
        "Directory for periodic fit snapshots of the iterative solvers "
        "(streamed KMeans Lloyd, L-BFGS host loop, UMAP SGD); unset = "
        "checkpointing off. A refit with the same params/seed resumes "
        "from the last committed snapshot and matches an uninterrupted "
        "fit exactly.",
        category="resilience",
        also_documented_in=("docs/fault_tolerance.md",),
    ),
    EnvVar(
        "TPUML_CKPT_EVERY", "int", 1,
        "Snapshot cadence in solver iterations (UMAP: epochs). Only read "
        "when `TPUML_CKPT_DIR` is set.",
        minimum=1, category="resilience",
        also_documented_in=("docs/fault_tolerance.md",),
    ),
    EnvVar(
        "TPUML_RETRIES", "int", 0,
        "Retry budget for transient failures at the distributed bootstrap "
        "and host-to-device chunk staging (default 0 = single attempt). "
        "`RESOURCE_EXHAUSTED` staging errors additionally degrade by "
        "halving the chunk within the budget.",
        minimum=0, category="resilience",
        also_documented_in=("docs/fault_tolerance.md",),
    ),
    EnvVar(
        "TPUML_BACKOFF_MS", "float", 100.0,
        "Base delay for the exponential-backoff-with-jitter retry "
        "schedule (doubles per attempt, capped at 30 s, equal jitter).",
        exclusive_minimum=0, category="resilience",
        also_documented_in=("docs/fault_tolerance.md",),
    ),
    EnvVar(
        "TPUML_FAULT_SPEC", "str", "",
        "Deterministic fault injection for resilience testing: comma-"
        "separated `scope:point:index:action` entries (`ingest:chunk` / "
        "`sgd:epoch` / `gbt:round` / `init:connect` / `serve:admit` / "
        "`serve:dispatch` / `serve:transfer` / `sched:admit` / "
        "`sched:preempt` / `sched:resume` / `sched:dispatch` sites; "
        "`raise`/`preempt`/`oom` actions; 0-based per-site hit index, "
        "each entry fires once).",
        category="resilience",
        also_documented_in=("docs/fault_tolerance.md",),
    ),
    EnvVar(
        "TPUML_CV_FAILFAST", "bool", True,
        "`1` (reference semantics): any failed fold/param fit aborts "
        "`CrossValidator.fit`. `0` records the failed combo as worst-"
        "metric (±inf in `avgMetrics`) and keeps searching; raises only "
        "if every combo failed.",
        category="resilience",
        also_documented_in=("docs/fault_tolerance.md",),
    ),
    # --- observability (docs/observability.md) ----------------------------
    EnvVar(
        "TPUML_TRACE", "path", None,
        "Directory for structured telemetry output: a Chrome-trace/"
        "Perfetto JSON shard (`trace-r<rank>-<pid>.json`), a JSONL span "
        "event log (`events-r<rank>-<pid>.jsonl`), and Prometheus/JSON "
        "metric dumps on request — process-index-tagged so multi-host "
        "runs sharing one directory stay disjoint "
        "(`scripts/merge_traces.py` merges the shards). Unset (the "
        "default) keeps the whole telemetry path inert: no files, no "
        "span allocation, outputs bit-identical.",
        category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_TELEMETRY_DEVICE_TIME", "bool", False,
        "Opt-in device-time fencing: spans that wrap device work call "
        "`block_until_ready` on close so their duration includes device "
        "execution, and per-span `device_seconds` aggregates become "
        "meaningful. Off by default because the fence serializes "
        "dispatch against the host. Only read when `TPUML_TRACE` is set.",
        category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_TELEMETRY_RETRACE_LIMIT", "int", 16,
        "Retrace-watchdog threshold: warn once per span site when XLA "
        "compilations attributed to it exceed this count in steady state "
        "(the runtime enforcement of lint rule TPU003). `0` disables the "
        "watchdog. The listener installs when `TPUML_TRACE` is set or "
        "this variable is set explicitly.",
        minimum=0, category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_TELEMETRY_RESERVOIR", "int", 512,
        "Bound of each histogram metric's observation ring (a "
        "deterministic last-N window feeding the exported quantiles); "
        "running count/sum/min/max are exact regardless of the bound.",
        minimum=1, category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_PEAK_FLOPS", "float", None,
        "Per-chip peak FLOP/s used as the roofline MFU denominator "
        "(`runtime/roofline.py`). Unset = the built-in per-device-kind "
        "bf16 table (same figures as bench.py). Set it when the "
        "workload runs a different dtype mix or the device kind is "
        "missing from the table. Only read when `TPUML_TRACE` is set.",
        exclusive_minimum=0, category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_PEAK_HBM_GBPS", "float", None,
        "Per-chip peak HBM bandwidth in GB/s for the roofline "
        "memory-bound verdict (`runtime/roofline.py`). Unset = the "
        "built-in per-device-kind table. Only read when `TPUML_TRACE` "
        "is set.",
        exclusive_minimum=0, category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    # --- live operations plane (runtime/opsplane.py) ----------------------
    EnvVar(
        "TPUML_OPS_PORT", "int", None,
        "Port of the in-process ops HTTP server (`/metrics`, `/healthz`, "
        "`/readyz`, `/statusz`, `/flight`); `0` binds an ephemeral port. "
        "Setting it also activates the flight recorder and the SLO "
        "burn-rate evaluator. Unset (the default) is fully inert: no "
        "listening socket, no background thread, no files.",
        minimum=0, category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_OPS_HOST", "str", "127.0.0.1",
        "Bind address of the ops HTTP server. Loopback by default — the "
        "endpoints expose span names and model names, so widening the "
        "bind is an explicit decision. Only read when `TPUML_OPS_PORT` "
        "is set.",
        category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_FLIGHT_DIR", "path", None,
        "Directory for flight-recorder crash dumps "
        "(`flight-r<rank>-<pid>.json`, rank-tagged like trace shards): "
        "written on SIGTERM, at interpreter exit, and on the first SLO "
        "burn alert. Setting it activates the flight recorder even "
        "without `TPUML_OPS_PORT`. Unset = dumps fall back to the "
        "`TPUML_TRACE` directory, or are skipped entirely when neither "
        "is set (the `/flight` endpoint still serves the in-memory "
        "ring).",
        category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_FLIGHT_EVENTS", "int", 2048,
        "Bound of the flight recorder's in-memory ring: the last N "
        "completed spans and instant events kept for `/flight` and the "
        "crash-dump paths (a deterministic last-N window, like the "
        "histogram reservoir). Only read while the recorder is active.",
        minimum=1, category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_SLO_EVAL_MS", "int", 1000,
        "Tick period of the SLO burn-rate evaluator in milliseconds: "
        "each tick snapshots the metric registry and scores every "
        "`runtime/slo.py` catalog entry over its short/long burn "
        "windows. Only read while the ops plane is active.",
        minimum=10, category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_SLO_BURN_THRESHOLD", "float", 1.0,
        "Burn-rate multiple at which an SLO alert fires: alert when "
        "BOTH the short and long windows burn error budget at or above "
        "this rate (1.0 = exactly exhausting the budget). Raising it "
        "tolerates faster burns; only read while the ops plane is "
        "active.",
        exclusive_minimum=0, category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    EnvVar(
        "TPUML_LOCK_WITNESS", "choice", "off",
        "Runtime lock-order witness (`runtime/lockwitness.py`): `1` "
        "(alias `count`) makes every cataloged lock constructed after "
        "this point an instrumented wrapper that checks the "
        "`runtime/lockspec.py` rank hierarchy at each acquire, counts "
        "violations in `lock_order_violations_total`, and exports "
        "per-lock `lock_hold_ms`/`lock_wait_ms` histograms; `raise` "
        "escalates the first occurrence of each violation to an "
        "exception. `off` (the default) constructs raw `threading` "
        "primitives — zero overhead, no metric series.",
        choices=("off", "1", "count", "raise"), category="observability",
        also_documented_in=("docs/observability.md",),
    ),
    # --- measured autotuner (runtime/autotune.py) -------------------------
    EnvVar(
        "TPUML_AUTOTUNE", "choice", "off",
        "Measured knob autotuner (`runtime/autotune.py`): `off` (the "
        "default) disables every cache read and probe — resolvers use "
        "their static heuristics and outputs are bit-identical to an "
        "untuned run; `on` consults the shape-keyed tuning cache before "
        "each `auto` resolver's heuristic and probes candidate values "
        "with short dispatches of the real jitted work on a miss; "
        "`force` re-probes even over an existing cache entry "
        "(overwriting stale winners). See `docs/autotune.md` for the "
        "search strategy and fitness definition.",
        choices=("off", "on", "force"), category="autotune",
        also_documented_in=("docs/autotune.md",),
    ),
    EnvVar(
        "TPUML_AUTOTUNE_CACHE", "path", None,
        "Directory of the persistent tuning cache "
        "(`autotune-cache.json`, atomic tmp+rename, written by rank 0 "
        "only). Unset with `TPUML_AUTOTUNE=on` keeps tuned winners "
        "in-process (probes still run; nothing is persisted). Corrupt "
        "or truncated files are tolerated: the tuner warns once and "
        "falls back to heuristics.",
        category="autotune",
        also_documented_in=("docs/autotune.md",),
    ),
    EnvVar(
        "TPUML_AUTOTUNE_BUDGET_MS", "float", 2000,
        "Wall-clock probe budget per (knob, shape) search, in "
        "milliseconds. The successive-halving search stops starting new "
        "measurements once the budget is spent and keeps the best "
        "candidate measured so far (the heuristic default is always "
        "measured first, so a truncated search can never do worse than "
        "no tuner).",
        exclusive_minimum=0, category="autotune",
        also_documented_in=("docs/autotune.md",),
    ),
)


def registered_names() -> Tuple[str, ...]:
    return tuple(SPEC)


def parse(name: str, raw: Optional[str]) -> Any:
    """Parse+validate a raw string for ``name`` (None/"" -> default)."""
    try:
        var = SPEC[name]
    except KeyError:
        raise EnvSpecError(
            f"{name} is not a registered TPUML_* variable "
            f"(spark_rapids_ml_tpu/runtime/envspec.py is the registry)"
        ) from None
    if raw is None or raw == "":
        return var.default

    if var.type in ("str", "path"):
        return raw
    if var.type == "choice":
        v = raw.strip().lower()
        assert var.choices is not None
        if v not in var.choices:
            raise EnvSpecError(f"{name}={raw!r} must be {var.domain()}")
        return v
    if var.type == "bool":
        v = raw.strip().lower()
        if v in _TRUE:
            return True
        if v in _FALSE:
            return False
        raise EnvSpecError(f"{name}={raw!r} must be {var.domain()}")
    # numeric
    try:
        num: Any = int(raw) if var.type == "int" else float(raw)
    except ValueError:
        raise EnvSpecError(
            f"{name}={raw!r} is not {var.domain()}"
        ) from None
    if var.minimum is not None and num < var.minimum:
        raise EnvSpecError(f"{name}={raw!r} must be >= {var.minimum:g}")
    if var.exclusive_minimum is not None and num <= var.exclusive_minimum:
        raise EnvSpecError(
            f"{name}={raw!r} must be > {var.exclusive_minimum:g}"
        )
    return num


def get(name: str, *, env: Optional[Mapping[str, str]] = None) -> Any:
    """The parsed, validated value of registered variable ``name``.

    Reads the live environment on every call (tests flip these between
    fits); callers that need trace-cache safety resolve once outside jit
    or at module import and pass the value through static args — see
    `docs/static_analysis.md` (TPU003).
    """
    source = os.environ if env is None else env
    return parse(name, source.get(name))


def get_raw(name: str) -> Optional[str]:
    """Raw string value (no parsing); None when unset. ``name`` must be
    registered — unregistered names raise like :func:`get`."""
    if name not in SPEC:
        return parse(name, None)  # raises EnvSpecError naming the registry
    return os.environ.get(name)


def is_set(name: str) -> bool:
    """True when ``name`` is present AND non-empty in the environment."""
    if name not in SPEC:
        parse(name, None)  # raises EnvSpecError naming the registry
    return bool(os.environ.get(name))


# --- docs table generation (scripts/gen_config_docs.py + TPU002) ----------

TABLE_BEGIN = "<!-- tpuml-envspec:begin (generated by scripts/gen_config_docs.py — edit envspec.py, not this table) -->"
TABLE_END = "<!-- tpuml-envspec:end -->"


def doc_table_lines() -> Tuple[str, ...]:
    """The generated markdown table for ``docs/configuration.md``,
    including the begin/end markers TPU002 anchors its drift check on."""
    rows = [
        TABLE_BEGIN,
        "| variable | type | default | meaning |",
        "|---|---|---|---|",
    ]
    for var in SPEC.values():
        typ = var.type if var.type != "choice" else "|".join(var.choices or ())
        rows.append(
            f"| `{var.name}` | {typ} | {var.default_repr()} | {var.doc} |"
        )
    rows.append(TABLE_END)
    return tuple(rows)
